"""Compile-latency subsystem: kernel registry, background warmup, and the
persistent compilation cache.

The search grows ``st.num_gates`` through shape buckets
(:data:`sboxgates_tpu.search.context.BUCKETS`); every bucket crossing
changes the padded table shapes and therefore recompiles the whole jitted
sweep ladder in :mod:`sboxgates_tpu.ops.sweeps` — on real silicon each XLA
compile is seconds, paid **mid-search, on the critical path**.  Three
coordinated parts eliminate that latency:

1. **Persistent compilation cache** (:func:`configure_compile_cache`):
   ``jax_compilation_cache_dir`` behind ``Options.compile_cache`` /
   ``--compile-cache DIR`` / ``SBG_COMPILE_CACHE``, so restarts and
   ``--resume-run`` deserialize every previously built executable instead
   of recompiling it.

2. **Kernel registry** (:data:`KERNELS` / :func:`kernel` /
   :func:`warm_specs`): ONE declarative table mapping registry names to
   the jitted sweep entry points, their static-argument names, and — for
   the bucket-shaped kernels — a warm-spec enumerator that reproduces the
   exact (statics, arg avals) the live drivers dispatch at a given gate
   count.  The drivers (``search/context.py`` dispatch methods consumed by
   ``lut.py``/``kwan.py``) fetch their kernels *from this registry* via
   :meth:`SearchContext.kernel_call`, so the warmed set cannot drift from
   the live call sites: a driver cannot dispatch a kernel the registry
   does not know, and the registry validates statics against the jitted
   function's own ``static_argnames``.

3. **KernelWarmer**: a background daemon thread that, on entry to bucket
   *b*, AOT-compiles (``fn.lower(ShapeDtypeStruct...).compile()``) the
   sweep-kernel set for the NEXT bucket off the critical path.  Warmup
   only compiles, never executes — results are bit-identical with it on
   or off.  Warmed executables are handed back to the dispatchers through
   :meth:`KernelWarmer.lookup`; a hit calls the AOT ``Compiled`` object
   directly, which performs **zero** tracing and zero compilation (the
   basis of the strict ``recompile_guard`` bucket-transition test).

Pivot-structured kernels (``lut5_pivot_stream`` / ``lut5_pivot_tile``) are
registered (so their dispatches flow through the same accounting) but not
warmable: their operand shapes are keyed to the exact gate count via the
pair grids, not to the bucket, so there is no "next bucket" shape to build
ahead of time — the persistent cache still covers them across restarts.

Mesh runs keep the lazy path: warmed avals would need the run's sharding
layouts, and GSPMD compiles are exactly the executables the persistent
cache is for.

Thread-safety: the warmer's shared state (compiled map, schedule, stats)
is guarded by one lock; the worker is registered as a jaxlint thread root
(``[tool.jaxlint] thread_roots``) and the whole-program R4x pass checks
the discipline.  A failed or hung background compile (fault site
``warmup.compile``) degrades to the ordinary lazy-compile behavior — the
search never blocks on the warmer, and shutdown joins with a bounded
deadline.
"""

from __future__ import annotations

import logging
import os
import threading
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..ops import combinatorics as comb
from ..ops import sweeps
from ..resilience.faults import fault_point
from ..telemetry import attribution as _tattr
from ..telemetry import metrics as _tmetrics
from ..telemetry import trace as _ttrace

logger = logging.getLogger(__name__)

#: Truth-table row: 8 little-endian uint32 words (core.ttable.N_WORDS).
_N_WORDS = 8


# -------------------------------------------------------------------------
# Persistent compilation cache
# -------------------------------------------------------------------------


def compile_cache_dir(
    explicit: Optional[str] = None, output_dir: Optional[str] = None
) -> Optional[str]:
    """Resolves the persistent-compile-cache directory: the explicit
    setting (``--compile-cache`` / ``Options.compile_cache``) wins, then
    ``SBG_COMPILE_CACHE``, then an ``xla_cache/`` subdir of the run's
    output directory.  Returns None (cache off) when nothing is set; an
    explicit empty string or ``SBG_COMPILE_CACHE=""`` disables it."""
    if explicit is not None:
        return explicit or None
    env = os.environ.get("SBG_COMPILE_CACHE")
    if env is not None:
        return env or None
    if output_dir is not None:
        return os.path.join(output_dir, "xla_cache")
    return None


def configure_compile_cache(path: Optional[str]) -> Optional[str]:
    """Points jax's persistent compilation cache at ``path`` (created if
    missing) and removes the size/time floors so every sweep-kernel
    executable is cached — a restarted or ``--resume-run`` search then
    deserializes instead of recompiling.  No-op on None.  Safe to call
    before any kernel compiles; returns the applied path."""
    if not path:
        return None
    import jax

    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # Default floors skip tiny/fast executables; the whole point here is
    # that EVERY ladder kernel (some compile in <1s on CPU but seconds on
    # real silicon) is reusable next run.
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    return path


# -------------------------------------------------------------------------
# Kernel registry
# -------------------------------------------------------------------------


@dataclass(frozen=True)
class KernelDef:
    """One registry entry: the jitted sweep entry point (resolved from
    :mod:`sboxgates_tpu.ops.sweeps` at call time, so test seams that
    monkeypatch the module keep working) and its static-arg names.

    ``warmable`` marks bucket-shaped kernels :func:`warm_specs` can build
    ahead of time; pivot kernels are registered but not warmable (shapes
    keyed to the exact g, not the bucket)."""

    name: str
    static_names: Tuple[str, ...]
    warmable: bool = True

    @property
    def fn(self) -> Callable:
        return getattr(sweeps, self.name)


#: Name -> definition for every jitted sweep entry point the drivers
#: dispatch (registry names ARE the sweeps attribute names).
#: ``search/context.py`` builds its kernels from this table (see
#: ``SearchContext.kernel_call``), so a dispatch of an unregistered
#: kernel is impossible by construction.
KERNELS: Dict[str, KernelDef] = {
    d.name: d
    for d in (
        KernelDef("gate_step_stream", ("chunk3", "has_not", "has_triple")),
        KernelDef("lut_step_stream",
                  ("chunk3", "chunk5", "has5", "solve_rows")),
        KernelDef("lut7_step_stream", ("chunk7", "solve7")),
        KernelDef("lut3_stream", ("chunk",)),
        KernelDef("lut5_stream", ("chunk", "solve_rows")),
        KernelDef("feasible_stream", ("k", "chunk")),
        KernelDef("lut_filter", ()),
        KernelDef("lut5_solve", ()),
        KernelDef("lut7_solve", ()),
        KernelDef("tuple_match_sweep", ("num_cells",)),
        KernelDef("match_stream", ("k", "chunk", "num_cells")),
        # Pivot kernels: warmable since the bucket-keyed shape refactor
        # (search.lut.PIVOT_G_BUCKETS) — every pivot operand pads to its
        # g-bucket, so warm_specs can reproduce the exact live avals.
        KernelDef("lut5_pivot_stream",
                  ("tl", "th", "solve_rows", "tile_batch", "pipeline",
                   "backend")),
        KernelDef("lut5_pivot_tile", ("tl", "th")),
        KernelDef("pivot_pair_cells", ()),
        # Fused multi-round driver (search/rounds.py): device-resident
        # search state advanced sweep->verdict->append for up to
        # max_rounds per dispatch.  Not warmable: its shapes key on the
        # (gate bucket x ROUND_BUCKETS rung) cross product of a chain
        # the warmer cannot predict; the persistent compile cache still
        # covers restarts.
        KernelDef(
            "round_driver",
            ("chunk3", "chunk5", "has5", "max_rounds", "solve_rows"),
            warmable=False,
        ),
        # Stacked-fleet round chains (search/rounds.py
        # run_fleet_round_chains): a whole wave's chains advance in ONE
        # dispatch.  Not warmable by the bucket enumerator for the same
        # reason as round_driver; the chain-shape warm specs
        # (chain_warm_specs / KernelWarmer.note_chain) AOT-build the
        # (jobs_bucket, gate_bucket, chain-length) cross product the
        # live wave drivers dispatch.
        KernelDef(
            "fleet_round_driver",
            ("chunk3", "chunk5", "has5", "max_rounds", "solve_rows"),
            warmable=False,
        ),
        # 64-bit-rank device enumeration (search/lut.py big-space
        # streams) and the 5-LUT filter head with the pallas backend:
        # dispatched on g-exact shapes / env-levered backends, so they
        # stay registered-but-unwarmable like the old pivot kernels.
        KernelDef(
            "feasible_stream_wide", ("k", "chunk", "backend"),
            warmable=False,
        ),
        KernelDef("lut5_filter", ("backend",), warmable=False),
        # Spectral best-first prepass (ops/spectral.py + search/lut.py
        # tier segments): one dispatch scoring every rank chunk before a
        # sweep.  Not warmable: n_chunks keys on the live rank-space
        # size bucket and the backend static rides the pallas latch.
        KernelDef(
            "spectral_score_stream",
            ("k", "chunk", "n_chunks", "backend"),
            warmable=False,
        ),
        KernelDef("spectral_gate_scores", ("backend",), warmable=False),
    )
}


#: Rendezvous/fleet shared-argument indices per kernel (operands
#: identical across restarts/jobs, mapped ``in_axes=None`` instead of
#: gaining a job axis).  MUST mirror the ``shared=`` tuples at the
#: ``SearchContext._dispatch`` / ``SearchContext.stream_dispatch`` call
#: sites — the fleet warm specs are enumerated from this table, and the
#: registry parity test (tests/test_fleet.py) asserts live submissions
#: agree with it.  Since PR 8 this covers EVERY kernel head the fleet
#: merges: the fused per-node heads AND the formerly per-thread
#: streaming paths (pivot sweeps, staged 7-LUT collection, overflow
#: re-drives, decomposition solvers).
FLEET_SHARED: Dict[str, Tuple[int, ...]] = {
    "gate_step_stream": (2, 4, 8, 10, 11, 12),
    "lut_step_stream": (2, 4, 11, 12, 13),
    "lut7_step_stream": (1, 7, 8),
    "lut7_solve": (2, 3),
    # Streaming paths folded into the fleet axis: binomial table, split
    # tables, and (for the whole-space 3-LUT stream, whose exclusion
    # list is always empty) the exclusion array are job-invariant.
    "lut3_stream": (1, 5),
    "lut5_stream": (1, 8, 9),
    "feasible_stream": (1,),
    "lut5_solve": (2, 3),
    "lut5_pivot_stream": (9, 10),
    "lut5_pivot_tile": (),
    "pivot_pair_cells": (),
    # Fused round-chain windows (search/rounds.py): binomial table,
    # empty exclusion array, the pivot-size cap, and the 5-LUT split
    # tables are job-invariant; tables/g/targets/masks/seeds/dcs/n gain
    # the jobs axis.  This is how a serve wave's concurrent chains merge
    # into one vmapped round_driver dispatch.
    "round_driver": (1, 5, 9, 10, 11, 12),
}


def kernel(name: str, statics: dict) -> Callable:
    """The statically-bound jitted callable for a registry entry — the
    single source both the live dispatchers and the warmer compile from.
    Validates the static names against the registry so a drifted call
    site fails loudly instead of silently retracing."""
    import functools

    d = KERNELS[name]
    unknown = set(statics) - set(d.static_names)
    if unknown:
        raise TypeError(
            f"kernel {name!r} does not take static args {sorted(unknown)}; "
            f"registry declares {d.static_names}"
        )
    return functools.partial(d.fn, **statics) if statics else d.fn


def arg_signature(args: Sequence) -> tuple:
    """Hashable shape/dtype signature of positional kernel operands —
    the warm-cache key half that pins the compiled executable to the
    exact avals the dispatch traces.  Arrays sign as (shape, dtype);
    Python scalars by type (they become weak-typed avals, distinct from
    an equal-valued numpy scalar)."""
    out = []
    for a in args:
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is not None and dtype is not None:
            out.append((tuple(shape), str(dtype)))
        else:
            out.append((type(a).__name__,))
    return tuple(out)


def warm_key(name: str, statics: dict, args: Sequence) -> tuple:
    return (name, tuple(sorted(statics.items())), arg_signature(args))


@dataclass(frozen=True)
class WarmSpec:
    """One ahead-of-time compile target: registry name, the exact static
    args, and positional avals (ShapeDtypeStructs for arrays, concrete
    Python scalars for weak-typed operands)."""

    name: str
    statics: tuple  # sorted (name, value) items — hashable
    avals: tuple

    @property
    def key(self) -> tuple:
        return (self.name, self.statics, arg_signature(self.avals))


@dataclass
class WarmPlan:
    """Configuration snapshot the warm-spec enumerator needs — captured
    from the context on the MAIN thread so the worker never touches live
    context state.

    ``pivot`` pins the pivot-stream levers (tile_batch, pipeline,
    backend) at context creation so the warmed executables match the
    live dispatches; None disables pivot warm specs (pallas backends —
    their Mosaic compiles are single-device A/B territory).

    ``fleet_mesh`` / ``mesh`` pin the sharding configuration: a fleet
    mesh makes the fleet specs lower with the job-axis out-sharding the
    dispatcher uses; a (single-process) candidate mesh switches the warm
    sets to the sharded stream executables (mesh_warm_specs)."""

    lut_graph: bool
    has_not: bool  # gate-mode NOT-augmented pair table present
    pair_table: Tuple[tuple, str]  # (shape, dtype) of the match tables
    not_table: Optional[Tuple[tuple, str]]
    triple_table: Tuple[tuple, str]
    pivot: Optional[tuple] = None
    fleet_mesh: Optional[object] = None  # jax.sharding.Mesh
    mesh: Optional[object] = None        # jax.sharding.Mesh

    @classmethod
    def from_context(cls, ctx) -> "WarmPlan":
        def sd(a):
            return (tuple(a.shape), str(a.dtype))

        from . import lut as L  # deferred: lut imports context

        backend = L.pivot_backend()
        pivot = (
            None if backend.startswith("pallas")
            else (L.pivot_tile_batch(), L.pivot_pipeline(), backend)
        )
        return cls(
            lut_graph=ctx.opt.lut_graph,
            has_not=bool(ctx.not_entries) and not ctx.opt.lut_graph,
            pair_table=sd(ctx.pair_table_np),
            not_table=(
                sd(ctx.not_table_np) if ctx.not_table_np is not None else None
            ),
            triple_table=sd(ctx.triple_table_np),
            pivot=pivot,
            fleet_mesh=(
                ctx.fleet_plan.mesh if ctx.fleet_plan is not None else None
            ),
            mesh=(
                ctx.mesh_plan.mesh if ctx.mesh_plan is not None else None
            ),
        )


def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))


def warm_specs(plan: WarmPlan, g: int) -> List[WarmSpec]:
    """The sweep-kernel set the drivers dispatch at gate count ``g`` (a
    bucket's entry point), as AOT-compile targets.

    This mirrors — and is tested for parity against — the static-arg and
    operand-shape choices of the live dispatch sites: the fused node
    heads (``ctx.gate_step`` / ``ctx.lut_step`` / ``ctx.lut7_step``), the
    standalone LUT streams, the feasible-stream resume loops, and the
    decomposition solvers."""
    # Deferred: context imports this module at top level.
    from . import context as C

    specs: List[WarmSpec] = []
    b = C.bucket_size(g)
    npairs = b * (b - 1) // 2
    tables = _sds((b, _N_WORDS), np.uint32)
    valid_g = _sds((b,), np.bool_)
    combos = _sds((npairs, 2), np.int32)
    pair_valid = _sds((npairs,), np.bool_)
    bt = sweeps.binom_table()
    binom = _sds(bt.shape, bt.dtype)
    tgt = _sds((_N_WORDS,), np.uint32)
    excl = _sds((8,), np.int32)
    # Python scalars: weak-typed avals, exactly like the live call sites'
    # int operands (g, totals, start, seed).
    gi, start, seed = 0, 0, 0

    def add(name, statics, avals):
        specs.append(WarmSpec(name, tuple(sorted(statics.items())), avals))

    total3 = comb.n_choose_k(g, 3)
    chunk3 = C.pick_chunk(max(total3, 1), C.STREAM_CHUNK[3])

    if not plan.lut_graph:
        nt = plan.not_table if plan.has_not else plan.pair_table
        add(
            "gate_step_stream",
            dict(chunk3=chunk3, has_not=plan.has_not, has_triple=g >= 3),
            (tables, valid_g, combos, pair_valid, binom, gi, tgt, tgt,
             excl, total3, _sds(*plan.pair_table), _sds(*nt),
             _sds(*plan.triple_table), seed),
        )
        return specs

    # LUT mode: fused head + the standalone streams/solvers it hands off
    # to at this gate count.
    total5 = comb.n_choose_k(g, 5)
    has5 = C.lut_head_has5(g)
    chunk5 = C.pick_chunk(max(total5, 1), C.STREAM_CHUNK[5]) if has5 else 1024
    _, w_tab, m_tab = sweeps.lut5_split_tables()
    jw, jm = _sds(w_tab.shape, w_tab.dtype), _sds(m_tab.shape, m_tab.dtype)
    add(
        "lut_step_stream",
        dict(chunk3=chunk3, chunk5=chunk5, has5=has5,
             solve_rows=C.LUT5_HEAD_SOLVE_ROWS),
        (tables, valid_g, combos, pair_valid, binom, gi, tgt, tgt, excl,
         total3, total5, _sds(*plan.pair_table), jw, jm, seed),
    )
    if g >= 3:
        # Standalone fused 3-LUT stream (lut3_search outside the head).
        add("lut3_stream", dict(chunk=chunk3),
            (tables, binom, gi, tgt, tgt, excl, start, total3, seed))
    if g >= 5 and total5 >= C.PIVOT_MIN_TOTAL and plan.pivot is not None:
        # Pivot-structured whole-space sweep: shapes key on the pivot
        # g-bucket (search.lut.PIVOT_G_BUCKETS), so these avals are
        # exactly what _lut5_search_pivot dispatches for every g and
        # exclusion list in the bucket.
        from . import lut as L

        tile_batch, pipeline, backend = plan.pivot
        tl, th = L.pivot_tile_shape(g)
        p2pad, tpad = L.pivot_padded_shapes(g, tl, th)
        cells = _sds((4, p2pad, _N_WORDS), np.uint32)
        pvalid = _sds((p2pad,), np.bool_)
        pgrid = _sds((p2pad, 2), np.int32)
        pdescs = _sds((tpad, 5), np.int32)
        add("pivot_pair_cells", {}, (tables, pgrid, pgrid, tgt, tgt))
        add(
            "lut5_pivot_stream",
            dict(tl=tl, th=th, tile_batch=tile_batch, pipeline=pipeline,
                 backend=backend),
            (tables, cells, cells, cells, pvalid, pvalid, pdescs, start,
             start, jw, jm, seed),
        )
        # Overflow re-drive of one flagged tile.
        add("lut5_pivot_tile", dict(tl=tl, th=th),
            (tables, cells, cells, cells, pvalid, pvalid, pdescs, start))
        # The re-driven tile's feasible rows solve through lut5_solve at
        # its compiled pads.
        for rows in (C.CHUNK_SIZES[0], C.LUT5_SOLVE_CHUNK):
            req = _sds((rows,), np.uint32)
            add("lut5_solve", {}, (req, req, jw, jm, seed))
    if g >= 5 and total5 < C.PIVOT_MIN_TOTAL:
        chunk5s = C.pick_chunk(total5, C.STREAM_CHUNK[5])
        add("lut5_stream", dict(chunk=chunk5s),
            (tables, binom, gi, tgt, tgt, excl, start, total5, jw, jm,
             seed))
        # Overflow re-drive of one flagged chunk (two-phase path).
        add("feasible_stream", dict(k=5, chunk=chunk5s),
            (tables, binom, gi, tgt, tgt, excl, start, total5))
        # The packed-cell decomposition solver, at both compiled pads.
        for rows in (C.CHUNK_SIZES[0], C.LUT5_SOLVE_CHUNK):
            req = _sds((rows,), np.uint32)
            add("lut5_solve", {}, (req, req, jw, jm, seed))
    if g >= 7:
        total7 = comb.n_choose_k(g, 7)
        chunk7 = C.pick_chunk(max(total7, 1), C.STREAM_CHUNK[7])
        idx_tab, pp_tab = sweeps.lut7_pair_tables()
        jidx = _sds(idx_tab.shape, idx_tab.dtype)
        jpp = _sds(pp_tab.shape, pp_tab.dtype)
        if C.lut_head_has7(g):
            add("lut7_step_stream",
                dict(chunk7=chunk7, solve7=C.LUT7_HEAD_SOLVE_ROWS),
                (tables, binom, gi, tgt, tgt, excl, total7, jidx, jpp,
                 seed))
        elif sweeps.device_rank_limit(g, 7):
            # Staged path stage A: the chunked feasible stream.
            add("feasible_stream", dict(k=7, chunk=chunk7),
                (tables, binom, gi, tgt, tgt, excl, start, total7))
        else:
            # Rank past int32 (g >= 76): stage A runs the host-chunked
            # driver, whose device work is the lut_filter dispatches.
            csize = C.pick_chunk(total7, C.LUT7_CHUNK)
            add("lut_filter", {},
                (tables, _sds((csize, 7), np.int32),
                 _sds((csize,), np.bool_), tgt, tgt))
        # Stage B solver at its smallest pad (the native stage-A hybrid
        # and small hit lists; larger pads compile lazily on first use).
        r7 = _sds((C.LUT7_SOLVE_SIZES[0], 4), np.uint32)
        add("lut7_solve", {}, (r7, r7, jidx, jpp, seed))
    return specs


# -------------------------------------------------------------------------
# Fleet kernels: one compiled executable sweeping a whole job batch
# -------------------------------------------------------------------------

#: jit(vmap(kernel)) wrappers for the fleet dispatch path, keyed on
#: (name, statics, shared, nargs, lanes, mesh).  Process-wide for the
#: same reason as the rendezvous _VMAP_CACHE: re-tracing the fused heads
#: per context costs seconds of host time.
_FLEET_LOCK = threading.Lock()
_FLEET_JIT: Dict[tuple, Callable] = {}


def fleet_kernel(
    name: str, statics: dict, shared: Tuple[int, ...], nargs: int,
    lanes: int, mesh=None, stacked: bool = False,
) -> Callable:
    """The fleet-batched form of a registry kernel: ``lanes`` jobs'
    sweeps execute as ONE compiled dispatch (``jax.vmap`` over a leading
    job axis; with ``mesh`` the job axis is pjit-sharded over its
    ``"jobs"`` mesh axis via the output sharding, composing with the
    ``"candidates"`` axis of a 2-D fleet mesh).

    Default (``stacked=False``, the rendezvous dispatch shape): the
    wrapper takes FLAT per-job operands — one argument per ``shared``
    index, ``lanes`` arguments per batched index, in argument-major
    order — and stacks the job axis INSIDE the jit, so a warmed fleet
    dispatch runs zero eager ops: no host-side jnp.stack, no tracing,
    no compiles (the basis of the fleet bucket-crossing
    ``recompile_guard(allowed=0)`` gate).

    ``stacked=True`` (the lockstep ``fleet_gate_step`` shape): operands
    arrive pre-stacked ``[lanes, ...]`` and the vmap applies directly;
    ``lanes`` is then irrelevant to the compiled shape and ignored in
    the cache key."""
    import jax

    key = (
        name, tuple(sorted(statics.items())), tuple(shared), nargs,
        "stacked" if stacked else lanes, mesh,
    )
    with _FLEET_LOCK:
        fn = _FLEET_JIT.get(key)
    if fn is not None:
        return fn
    import jax.numpy as jnp

    base = kernel(name, statics)
    shared_set = set(shared)
    in_axes = [None if i in shared_set else 0 for i in range(nargs)]
    vm = jax.vmap(base, in_axes=in_axes)

    if stacked:
        call = vm
    else:
        def call(*flat):
            args, k = [], 0
            for i in range(nargs):
                if i in shared_set:
                    args.append(flat[k])
                    k += 1
                else:
                    args.append(jnp.stack(flat[k : k + lanes]))
                    k += lanes
            return vm(*args)

    if mesh is None:
        fn = jax.jit(call)
    else:
        from jax.sharding import NamedSharding, PartitionSpec

        from ..parallel.mesh import JOBS_AXIS

        fn = jax.jit(
            call,
            out_shardings=NamedSharding(mesh, PartitionSpec(JOBS_AXIS)),
        )
    with _FLEET_LOCK:
        fn = _FLEET_JIT.setdefault(key, fn)
    return fn


def fleet_warm_key(
    name: str, statics: dict, shared: Tuple[int, ...], lanes: int,
    flat_args: Sequence, mesh=None, stacked: bool = False,
) -> tuple:
    """Warm-cache key for one fleet dispatch — the (jobs_bucket, bucket)
    keying the ISSUE names: ``lanes`` is the jobs bucket, the arg
    signature carries the padded table bucket (and, for the pivot
    kernels, the pivot g-bucket — making these the ``(jobs_bucket,
    pivot_g_bucket)`` keys).  ``stacked`` distinguishes the pre-stacked
    ``[lanes, ...]`` operand form from the flat per-job form — the two
    lower different wrappers, so their executables must never alias."""
    return (
        "fleet-stacked" if stacked else "fleet", name,
        tuple(sorted(statics.items())), tuple(shared),
        lanes, arg_signature(flat_args), mesh,
    )


def fleet_flat_avals(spec: WarmSpec, shared: Tuple[int, ...], lanes: int):
    """Flattens one per-job WarmSpec into the fleet wrapper's flat
    operand list: shared avals once, batched avals ``lanes`` times.
    Batched Python-scalar avals become int32 scalar arrays — the fleet
    dispatcher normalizes per-job scalars to np.int32 so the in-jit
    stack sees one strong dtype per argument."""
    flat = []
    for i, a in enumerate(spec.avals):
        if i in shared:
            flat.append(a)
            continue
        if not hasattr(a, "shape"):
            a = _sds((), np.int32)
        flat.extend([a] * lanes)
    return tuple(flat)


def fleet_stacked_avals(spec: WarmSpec, shared: Tuple[int, ...], lanes: int):
    """Lifts one per-job WarmSpec to the stacked wrapper's operand list:
    shared avals unchanged, batched avals with a leading ``lanes`` jobs
    axis (Python-scalar avals become int32[lanes] vectors — the stacked
    dispatchers collect per-job scalars into one int32 array)."""
    out = []
    for i, a in enumerate(spec.avals):
        if i in shared:
            out.append(a)
            continue
        if not hasattr(a, "shape"):
            out.append(_sds((lanes,), np.int32))
        else:
            out.append(_sds((lanes,) + tuple(a.shape), a.dtype))
    return tuple(out)


def fleet_warm_specs(
    plan: WarmPlan, g: int, lanes: int, stacked: Optional[bool] = None,
) -> List[tuple]:
    """AOT-compile targets for the fleet dispatch path at gate count
    ``g`` and jobs bucket ``lanes``: every rendezvous-merged kernel of
    ``warm_specs(plan, g)``, lifted to its fleet form.  ``stacked=None``
    resolves by the jobs bucket: lanes past the flat-operand cap
    (``search.fleet.FLEET_BUCKETS[-1]``) can only dispatch stacked.
    Returns (warm_key, name, statics, shared, nargs, avals, stacked)
    tuples."""
    from .fleet import FLEET_BUCKETS

    if stacked is None:
        stacked = lanes > FLEET_BUCKETS[-1]
    out = []
    for spec in warm_specs(plan, g):
        shared = FLEET_SHARED.get(spec.name)
        if shared is None:
            continue
        statics = dict(spec.statics)
        avals = (
            fleet_stacked_avals(spec, shared, lanes) if stacked
            else fleet_flat_avals(spec, shared, lanes)
        )
        out.append((
            fleet_warm_key(
                spec.name, statics, shared, lanes, avals,
                plan.fleet_mesh, stacked=stacked,
            ),
            spec.name, statics, shared, len(spec.avals), avals, stacked,
        ))
    return out


# -------------------------------------------------------------------------
# Mesh-shaped warm specs: AOT builds of the sharded stream executables
# -------------------------------------------------------------------------


def mesh_warm_specs(plan: WarmPlan, g: int) -> List[tuple]:
    """AOT-compile targets for a PINNED single-process candidate mesh:
    the sharded feasible/pivot stream executables the drivers dispatch at
    gate count ``g`` (under a mesh the node heads route to the native
    host, so the sharded streams ARE the device surface).  Returns
    (warm_key, builder, avals) with ``builder()`` resolving the jitted
    shard_map callable to lower.

    PR 5 left mesh coverage to the persistent compile cache (restarts
    only); these specs move the FIRST run's GSPMD compiles off the
    critical path too."""
    if plan.mesh is None or not plan.lut_graph:
        return []
    from . import context as C

    mesh = plan.mesh
    specs: List[tuple] = []
    b = C.bucket_size(g)
    tables = _sds((b, _N_WORDS), np.uint32)
    bt = sweeps.binom_table()
    binom = _sds(bt.shape, bt.dtype)
    tgt = _sds((_N_WORDS,), np.uint32)
    excl = _sds((8,), np.int32)
    gi, start, seed = 0, 0, 0

    def add(kind, statics, builder, avals):
        specs.append((
            ("mesh", kind, tuple(sorted(statics.items())),
             arg_signature(avals), mesh),
            builder, avals,
        ))

    from ..parallel import mesh as M

    nshards = mesh.shape[M.CANDIDATES_AXIS]
    for k in (3, 5, 7):
        total = comb.n_choose_k(g, k)
        if total <= 0 or not sweeps.device_rank_limit(g, k):
            continue
        if k == 5 and total >= C.PIVOT_MIN_TOTAL:
            continue  # pivot-sized spaces take the sharded pivot stream
        chunk = C.pick_chunk(max(total, 1), C.STREAM_CHUNK[k])
        chunk = -(-chunk // nshards) * nshards
        add(
            "sharded_feasible_stream",
            dict(k=k, chunk=chunk, compact=False),
            lambda k=k, chunk=chunk: M._sharded_stream_fn(
                mesh, k, chunk, False
            ),
            (tables, binom, gi, tgt, tgt, excl, start, total),
        )
    total5 = comb.n_choose_k(g, 5)
    if g >= 5 and total5 >= C.PIVOT_MIN_TOTAL and plan.pivot is not None:
        from . import lut as L

        _tile_batch, pipeline, backend = plan.pivot
        if not backend.startswith("pallas"):
            accum = M.pivot_accum_name(backend)
            tl, th = L.pivot_tile_shape(g)
            p2pad, tpad = L.pivot_padded_shapes(g, tl, th)
            cells = _sds((4, p2pad, _N_WORDS), np.uint32)
            pvalid = _sds((p2pad,), np.bool_)
            pdescs = _sds((tpad, 5), np.int32)
            _, w_tab, m_tab = sweeps.lut5_split_tables()
            jw = _sds(w_tab.shape, w_tab.dtype)
            jm = _sds(m_tab.shape, m_tab.dtype)

            def pivot_builder(tl=tl, th=th, pipeline=pipeline, accum=accum):
                import jax.numpy as jnp

                return M._sharded_pivot_fn(
                    mesh, tl, th, 64, bool(pipeline),
                    getattr(jnp, accum),
                )

            add(
                "sharded_pivot_stream",
                dict(tl=tl, th=th, solve_rows=64, pipeline=bool(pipeline),
                     accum=accum),
                pivot_builder,
                (tables, cells, cells, cells, pvalid, pvalid, pdescs,
                 start, start, jw, jm, seed),
            )
    return specs


def chain_warm_specs(
    plan: WarmPlan, g: int, lanes: int, rounds: int,
) -> List[tuple]:
    """AOT-compile targets for the merged round-chain windows, keyed on
    (jobs_bucket, gate_bucket, chain length): the shapes
    ``search.rounds`` dispatches for a window of up to ``rounds`` fused
    rounds starting at gate count ``g``, across ``lanes`` wave lanes.

    Two dispatch forms exist and both are covered: ``lanes >= 2`` waves
    merge through the fleet rendezvous (flat/stacked-wrapped
    ``round_driver``, the serve merged-wave path) AND through the
    explicit pre-stacked ``fleet_round_driver`` kernel (the lockstep
    ``run_fleet_round_chains`` path); ``lanes == 1`` is the direct
    per-job ``round_driver`` window.  Returns the ``_compile_jobs``
    tuple format (cache key, lower resolver, avals, statics, label)."""
    from . import context as C
    from .rounds import ROUND_BUCKETS, _chain_bucket, round_bucket

    want = max(1, min(int(rounds), ROUND_BUCKETS[-1]))
    b, n = _chain_bucket(g, want)
    rb = round_bucket(n)
    statics = dict(
        chunk3=C.pick_chunk(comb.n_choose_k(b, 3), C.STREAM_CHUNK[3]),
        chunk5=C.pick_chunk(C.PIVOT_MIN_TOTAL, C.STREAM_CHUNK[5]),
        has5=True, max_rounds=rb, solve_rows=C.LUT5_HEAD_SOLVE_ROWS,
    )
    splits, w_tab, m_tab = sweeps.lut5_split_tables()
    bt = sweeps.binom_table()
    gi = 0  # python-int scalars, weak-typed like the live operands
    per_job = (
        _sds((b, _N_WORDS), np.uint32),          # tables
        _sds(bt.shape, bt.dtype),                # binom (shared)
        gi,                                      # g0
        _sds((rb, _N_WORDS), np.uint32),         # targets
        _sds((rb, _N_WORDS), np.uint32),         # masks
        _sds((8,), np.int32),                    # excl (shared)
        _sds((rb,), np.int32),                   # seeds
        _sds((rb,), np.int32),                   # dc draws
        gi,                                      # n_rounds
        gi,                                      # total5_cap (shared)
        _sds(splits.shape, splits.dtype),        # splits (shared)
        _sds(w_tab.shape, w_tab.dtype),          # w_tab (shared)
        _sds(m_tab.shape, m_tab.dtype),          # m_tab (shared)
    )
    spec = WarmSpec(
        "round_driver", tuple(sorted(statics.items())), per_job
    )
    jobs: List[tuple] = []
    if lanes < 2:
        jobs.append((
            spec.key,
            (lambda: KERNELS["round_driver"].fn.lower),
            per_job, statics, "round_driver",
        ))
        return jobs
    shared = FLEET_SHARED["round_driver"]
    from .fleet import FLEET_BUCKETS

    stacked = lanes > FLEET_BUCKETS[-1]
    avals = (
        fleet_stacked_avals(spec, shared, lanes) if stacked
        else fleet_flat_avals(spec, shared, lanes)
    )
    jobs.append((
        fleet_warm_key(
            "round_driver", statics, shared, lanes, avals,
            plan.fleet_mesh, stacked=stacked,
        ),
        (lambda st=stacked: fleet_kernel(
            "round_driver", statics, shared, len(per_job), lanes,
            plan.fleet_mesh, stacked=st,
        ).lower),
        avals, {}, "round_driver",
    ))
    # The lockstep driver's pre-stacked kernel: per-lane scalar operands
    # arrive as int32[lanes] vectors, exactly as run_fleet_round_chains
    # builds them.
    stacked_avals = fleet_stacked_avals(spec, shared, lanes)
    jobs.append((
        warm_key("fleet_round_driver", statics, stacked_avals),
        (lambda: KERNELS["fleet_round_driver"].fn.lower),
        stacked_avals, statics, "fleet_round_driver",
    ))
    return jobs


def mesh_warm_lookup(kind: tuple, mesh, statics: dict, args: Sequence):
    """Warmed sharded executable for one live mesh dispatch, or None."""
    key = (
        "mesh", kind, tuple(sorted(statics.items())), arg_signature(args),
        mesh,
    )
    with _WARM_LOCK:
        return _WARM_COMPILED.get(key)


# -------------------------------------------------------------------------
# Background warmer
# -------------------------------------------------------------------------

#: Process-wide warmed-executable cache, shared by every KernelWarmer:
#: AOT executables are keyed purely on (kernel, statics, avals), so a
#: second context with the same configuration reuses the first's compiles
#: instead of re-warming.  Every access holds _WARM_LOCK.
_WARM_LOCK = threading.Lock()
_WARM_COMPILED: Dict[tuple, Callable] = {}


def drop_warm_cache() -> None:
    """Clears the process-wide warmed-executable cache (tests)."""
    with _WARM_LOCK:
        _WARM_COMPILED.clear()


def next_bucket(b: int) -> Optional[int]:
    from . import context as C

    for nb in C.BUCKETS:
        if nb > b:
            return nb
    return None


#: Seconds the warm worker idles on an empty queue before retiring
#: itself (a later schedule spawns a fresh one).  Without this, every
#: warmup-enabled context in a long-lived library process would park one
#: daemon thread forever after its warm set finished.
WORKER_IDLE_EXIT_S = 60.0


class KernelWarmer:
    """Background ahead-of-time compiler for the next bucket's kernels.

    Dispatch sites report their gate count through :meth:`note_gates`
    (via ``SearchContext.kernel_call``); the first dispatch inside bucket
    *b* schedules an AOT compile of bucket ``next(b)``'s warm-spec set on
    a daemon worker.  :meth:`lookup` hands a warmed ``Compiled`` back to
    the dispatcher — calling it performs no tracing and no compilation,
    so a warmed bucket transition is compile-free under a strict
    ``recompile_guard``.

    All shared state (compiled map, schedule, stats) lives under one
    lock; the public API never blocks on a compile.  A failed compile
    (``warmup.compile`` fault site, or any real error) is counted and
    skipped — the dispatcher simply falls back to lazy compilation.
    """

    def __init__(self, plan: WarmPlan, enabled: bool = True):
        self.plan = plan
        self.enabled = enabled and os.environ.get("SBG_WARMUP", "1") != "0"
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._scheduled: set = set()   # buckets queued or done
        self._inflight = 0
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        # Worker-side telemetry; dispatch-side hit/miss tallies live in
        # ctx.stats (kernel_call) — ONE owner per counter, so the -vv
        # report and the warmup: line can never disagree.  A private
        # metrics registry (atomic inc; not the declared ctx schema).
        self.stats = _tmetrics.MetricsRegistry(
            {"warm_compiled": 0, "warm_failed": 0}, declared=None
        )

    # -- main-thread API ---------------------------------------------------

    def note_gates(self, g: Optional[int]) -> None:
        """Bucket-entry hook: called by every registry dispatch that
        knows its gate count.  Cheap when nothing new (one lock'd set
        probe); schedules the next bucket's warm set otherwise, for the
        first gate count the drivers will dispatch after crossing the
        boundary.  LUT plans additionally warm the next PIVOT g-bucket
        (search.lut.PIVOT_G_BUCKETS — finer than the table buckets), so
        a mid-bucket pivot-shape crossing is compile-free too."""
        if not self.enabled or g is None:
            return
        from . import context as C

        b = C.bucket_size(g)
        if next_bucket(b) is not None:
            self._schedule(("bucket", b), ("specs", b + 1))
        if self.plan.lut_graph and self.plan.pivot is not None:
            from . import lut as L

            pb = L.pivot_g_bucket(g)
            if pb < L.PIVOT_G_BUCKETS[-1]:
                self._schedule(("pivotb", pb), ("specs", pb + 1))

    def prewarm(self, g: Optional[int]) -> None:
        """Schedules an AOT build of gate count ``g``'s OWN kernel set
        (vs :meth:`note_gates`' next-bucket trigger): with a persistent
        compile cache, a restarted or resumed search rebuilds its current
        bucket's executables off the critical path — time-to-first-
        dispatch pays a cache deserialize in the background instead of a
        compile in the foreground."""
        if self.enabled and g is not None:
            self._schedule(("exact", g), ("specs", g))

    def note_fleet(
        self, g: Optional[int], lanes: int, stacked: bool = False,
        ladder: bool = False,
    ) -> None:
        """Fleet-dispatch hook (search.fleet): warm specs are keyed on
        (jobs_bucket, bucket), and both axes cross mid-run — the fleet
        shrinks as jobs retire, the tables grow through gate buckets —
        so entry to (lanes, bucket) schedules the set itself plus its
        two successors: the next gate bucket at these lanes and the
        next SMALLER jobs bucket at this gate count.  ``stacked`` warms
        the pre-stacked-operand wrapper (the form every stacked step
        dispatches at ANY lane count) instead of the flat one.

        ``ladder`` is the FleetRendezvous semantics: each lane count's
        form follows the jobs-bucket ladder — stacked past the flat cap
        (``FLEET_BUCKETS[-1]``), flat at or below it — so the
        retirement pre-warm of a stacked group's next SMALLER bucket
        builds the FLAT wrapper the rendezvous will actually dispatch
        when the fleet shrinks across the stacked-to-flat boundary.

        LUT plans with pivot-sized spaces additionally warm the next
        PIVOT g-bucket at each lane set — the ``(jobs_bucket,
        pivot_g_bucket)`` keys of the stacked pivot stream, so a warmed
        crossing of EITHER stacked bucket axis is compile-free."""
        if not self.enabled or g is None:
            return
        from . import context as C
        from .fleet import FLEET_BUCKETS, prev_fleet_bucket

        b = C.bucket_size(g)
        gates = [g] + ([b + 1] if next_bucket(b) is not None else [])
        pl = prev_fleet_bucket(lanes)
        # A 1-lane group bypasses the fleet wrapper entirely (the
        # rendezvous runs singletons through the registry kernel), so
        # lanes<2 sets would warm executables nothing dispatches —
        # except in stacked form, where a 1-lane step is a real
        # dispatch of the stacked wrapper.
        lane_set = [lanes] + (
            [pl]
            if pl is not None and (pl >= 2 or (stacked and not ladder))
            else []
        )
        # Full cross product: the fleet can cross both axes at once (a
        # job retires in the same round the survivors' tables grow past
        # the bucket), so the diagonal set must be warm too.
        targets = [(gg, ll) for gg in gates for ll in lane_set]
        if self.plan.lut_graph and self.plan.pivot is not None:
            from . import lut as L

            pb = L.pivot_g_bucket(g)
            if pb < L.PIVOT_G_BUCKETS[-1]:
                # First gate count of the next pivot bucket: its fleet
                # warm set carries the next bucket's pivot-stream avals
                # (the other kernels' shapes are table-bucket-keyed and
                # mostly coincide with the sets above).
                targets += [(pb + 1, ll) for ll in lane_set]
        for gg, ll in targets:
            form = (ll > FLEET_BUCKETS[-1]) if ladder else stacked
            self._schedule(
                ("fleet", self._fleet_shape_key(gg), ll, form),
                ("fleet", gg, ll, form),
            )

    def note_chain(
        self, g: Optional[int], lanes: int, rounds: int,
    ) -> None:
        """Round-chain dispatch hook (search.rounds): schedules the
        merged-window executables for a chain at gate count ``g`` across
        ``lanes`` wave lanes — the (jobs_bucket, gate_bucket,
        chain-length) wave shapes — plus the NEXT window's set (a fused
        window grows the graph by up to two gates per round, so the next
        window can start in the next gate bucket)."""
        if not self.enabled or g is None:
            return
        from .fleet import fleet_bucket
        from .rounds import ROUND_BUCKETS, _chain_bucket, round_bucket

        want = max(1, min(int(rounds), ROUND_BUCKETS[-1]))
        ll = fleet_bucket(max(1, lanes))
        for gg in (g, g + 2 * want):
            try:
                b, n = _chain_bucket(gg, want)
            except ValueError:  # no append capacity at the gate cap
                continue
            self._schedule(
                ("chain", b, round_bucket(n), ll),
                ("chain", gg, ll, want),
            )

    def _fleet_shape_key(self, g: int) -> tuple:
        """Dedup key for one fleet warm set's shapes at gate count g:
        the table bucket, plus the pivot g-bucket when the plan has
        pivot-shaped kernels (two gate counts in one table bucket can
        still differ in pivot operand pads)."""
        from . import context as C

        key = (C.bucket_size(g),)
        if self.plan.lut_graph and self.plan.pivot is not None:
            from . import lut as L

            key += (L.pivot_g_bucket(g),)
        return key

    def _schedule(self, key, item: tuple) -> None:
        with self._cv:
            if key in self._scheduled or self._stop:
                return
            self._scheduled.add(key)
            self._queue.append(item)
            self._inflight += 1
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._work, name="sbg-warmup", daemon=True
                )
                self._thread.start()
            self._cv.notify()

    def lookup(self, name: str, statics: dict, args: Sequence):
        """The warmed executable for this exact dispatch, or None (lazy
        path).  Hit/miss accounting is the caller's (kernel_call tallies
        into ctx.stats, warmable kernels only)."""
        if not self.enabled:
            return None
        key = warm_key(name, statics, args)
        with _WARM_LOCK:
            return _WARM_COMPILED.get(key)

    def lookup_key(self, key: tuple):
        """Warmed executable by prebuilt cache key (the fleet dispatcher
        builds fleet_warm_key itself), or None."""
        if not self.enabled:
            return None
        with _WARM_LOCK:
            return _WARM_COMPILED.get(key)

    def count(self, key: str) -> None:
        """Bumps one telemetry counter (used by the dispatchers for
        events the warmer itself cannot see, e.g. an aval mismatch
        surfacing at call time).  The registry increment is atomic."""
        self.stats.inc(key)

    def stats_snapshot(self) -> dict:
        with self._lock:
            out = dict(self.stats)
            out["warm_inflight"] = self._inflight
            return out

    def wait_idle(self, timeout: float) -> bool:
        """Blocks until every scheduled warm finished (tests/bench); True
        when idle, False on timeout."""
        with self._cv:
            return self._cv.wait_for(
                lambda: self._inflight == 0 and not self._queue, timeout
            )

    def shutdown(self, timeout: float = 2.0) -> None:
        """Deadline-bounded join: a worker parked in a hung compile (the
        ``warmup.compile`` hang injection, or a wedged backend) is simply
        abandoned — it is a daemon thread and never blocks process
        exit."""
        with self._cv:
            self._stop = True
            # Queued-but-unstarted buckets will never run: release their
            # in-flight claims so wait_idle/stats stay truthful.
            self._inflight -= len(self._queue)
            self._queue.clear()
            self._cv.notify_all()
            t = self._thread
        if t is not None:
            t.join(timeout)

    # -- worker ------------------------------------------------------------

    def _work(self) -> None:
        while True:
            with self._cv:
                got = self._cv.wait_for(
                    lambda: self._queue or self._stop,
                    timeout=WORKER_IDLE_EXIT_S,
                )
                if self._stop:
                    return
                if not got:
                    # Idle long enough: retire (under the lock, so a
                    # concurrent _schedule either sees this thread alive
                    # or spawns a successor — never neither).
                    self._thread = None
                    return
                item = self._queue.popleft()
            try:
                if item[0] == "fleet":
                    self._warm_fleet(item[1], item[2], item[3])
                elif item[0] == "chain":
                    self._warm_chain(item[1], item[2], item[3])
                else:
                    self._warm_bucket(item[1])
            finally:
                with self._cv:
                    self._inflight -= 1
                    self._cv.notify_all()

    def _warm_bucket(self, g: int) -> None:
        try:
            if self.plan.mesh is not None:
                jobs = [
                    (key, (lambda b=builder: b().lower), avals, {}, key[1])
                    for key, builder, avals in mesh_warm_specs(self.plan, g)
                ]
            else:
                jobs = [
                    (
                        spec.key,
                        (lambda n=spec.name: KERNELS[n].fn.lower),
                        spec.avals,
                        dict(spec.statics),
                        spec.name,
                    )
                    for spec in warm_specs(self.plan, g)
                ]
        except Exception as e:
            # Spec enumeration failing must degrade exactly like a failed
            # compile — counted and skipped — never kill the worker (a
            # dead singleton thread would silently disable warmup for the
            # rest of the run while _schedule kept queueing onto it).
            logger.warning(
                "warm-spec enumeration for g=%d failed (%s); skipping "
                "this warm set", g, e
            )
            self.count("warm_failed")
            return
        self._compile_jobs(jobs)

    def _warm_fleet(self, g: int, lanes: int, stacked: bool = False) -> None:
        try:
            jobs = [
                (
                    key,
                    (lambda n=name, s=statics, sh=shared, na=nargs, st=stk:
                        fleet_kernel(
                            n, s, sh, na, lanes, self.plan.fleet_mesh,
                            stacked=st,
                        ).lower),
                    avals, {}, name,
                )
                for key, name, statics, shared, nargs, avals, stk
                in fleet_warm_specs(self.plan, g, lanes, stacked=stacked)
            ]
        except Exception as e:
            logger.warning(
                "fleet warm-spec enumeration for g=%d lanes=%d failed "
                "(%s); skipping this warm set", g, lanes, e
            )
            self.count("warm_failed")
            return
        self._compile_jobs(jobs)

    def _warm_chain(self, g: int, lanes: int, rounds: int) -> None:
        try:
            jobs = chain_warm_specs(self.plan, g, lanes, rounds)
        except Exception as e:
            logger.warning(
                "chain warm-spec enumeration for g=%d lanes=%d rounds=%d "
                "failed (%s); skipping this warm set", g, lanes, rounds, e
            )
            self.count("warm_failed")
            return
        self._compile_jobs(jobs)

    def _compile_jobs(self, jobs) -> None:
        """Shared AOT loop: each job is (cache key, lower-fn resolver,
        positional avals, static kwargs, kernel label — the attribution
        key the cost capture records under)."""
        for key, lower_of, avals, statics, kernel_label in jobs:
            with self._lock:
                if self._stop:
                    return
            with _WARM_LOCK:
                if key in _WARM_COMPILED:
                    continue
            try:
                # Fault site: raise degrades this spec to lazy compile,
                # hang parks this daemon worker forever (the search is
                # untouched; shutdown abandons it after the bounded
                # join).
                fault_point("warmup.compile")
                # .lower on the underlying jitted callable (registry fn,
                # fleet wrapper, or sharded stream); statics ride as
                # keywords exactly as the live call passes them.  One
                # "warmup" span per AOT build: the exported trace shows
                # the warmer's background activity against the critical
                # path it keeps clear.
                with _ttrace.span("warmup.compile", "warmup",
                                  key=str(key[:2])):
                    compiled = lower_of()(*avals, **statics).compile()
            except Exception as e:
                # Any failure means "no warm entry": the dispatcher lazy-
                # compiles exactly as without a warmer.  Never propagate —
                # a background compile must not be able to fail the search.
                logger.warning(
                    "background warmup of %s failed (%s); falling back "
                    "to lazy compilation", key[:2], e
                )
                self.count("warm_failed")
                continue
            with _WARM_LOCK:
                _WARM_COMPILED[key] = compiled
            # Free cost probe: the AOT build holds the Compiled object,
            # so XLA's cost/memory analysis is one method call away —
            # this is where the attribution table's rows come from on
            # warmed paths (kernel_call covers the lazy ones).
            _tattr.capture(kernel_label, compiled, avals, source="warmup")
            self.count("warm_compiled")
