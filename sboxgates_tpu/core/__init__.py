from . import boolfunc, ttable  # noqa: F401
