"""Boolean-function algebra.

Enumerates the 2-input gate functions available to a search and the 3-input
functions expressible as ``fun2(fun1(A, B), C)`` over them, with optional NOT
gates on inputs/outputs.  Mirrors the semantics of the reference's
``boolfunc.c`` (get_val boolfunc.c:22-25, create_2_input_fun boolfunc.c:56-71,
get_not_functions boolfunc.c:36-54, get_3_input_function_list
boolfunc.c:73-134) — this layer is tiny, branchy host code, so it is plain
Python; the *evaluation* of these functions happens in batched device sweeps.

Note: the reference has an apparent indexing bug where 3-input commutativity
flags are read from ``opt->avail_3[m]`` with ``m`` a gate index rather than
the function index ``p`` (sboxgates.c:411,418,425).  We use the function's
own flags.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import List, Optional, Sequence

# Enum values of the 16 two-input gate functions plus NOT/IN/LUT, identical
# to the reference's gate_type (state.h:36-57).  The enum value of a 2-input
# gate is its 4-bit truth table: f(1,1)=bit0, f(1,0)=bit1, f(0,1)=bit2,
# f(0,0)=bit3.
FALSE_GATE = 0
AND = 1
A_AND_NOT_B = 2
A = 3
NOT_A_AND_B = 4
B = 5
XOR = 6
OR = 7
NOR = 8
XNOR = 9
NOT_B = 10
A_OR_NOT_B = 11
NOT_A = 12
NOT_A_OR_B = 13
NAND = 14
TRUE_GATE = 15
NOT = 16
IN = 17
LUT = 18

GATE_NAMES = [
    "FALSE",
    "AND",
    "A_AND_NOT_B",
    "A",
    "NOT_A_AND_B",
    "B",
    "XOR",
    "OR",
    "NOR",
    "XNOR",
    "NOT_B",
    "A_OR_NOT_B",
    "NOT_A",
    "NOT_A_OR_B",
    "NAND",
    "TRUE",
    "NOT",
    "IN",
    "LUT",
]

GATE_BY_NAME = {name: i for i, name in enumerate(GATE_NAMES)}

DEFAULT_AVAILABLE = (1 << AND) | (1 << OR) | (1 << XOR)  # = 2 + 64 + 128


def get_val(fun: int, a: int, b: int) -> int:
    """Value of 2-input function ``fun`` on inputs A=a, B=b."""
    return (fun >> (3 - ((a << 1) | b))) & 1


def fun3_val(fun: int, a: int, b: int, c: int) -> int:
    """Value of 3-input function byte ``fun``: bit k = f at k = A<<2|B<<1|C."""
    return (fun >> ((a << 2) | (b << 1) | c)) & 1


@dataclass(frozen=True)
class BoolFunc:
    """A 2- or 3-input Boolean function with its gate decomposition.

    3-input functions decompose as ``fun2(fun1(A, B), C)``; NOT gates may be
    interposed on any input or the output (reference: boolfunc.h:28-40).
    """

    num_inputs: int
    fun: int                 # 4-bit (2-input) or 8-bit (3-input) truth table
    fun1: int                # first 2-input gate
    fun2: Optional[int]      # second 2-input gate (3-input functions only)
    not_a: bool = False
    not_b: bool = False
    not_c: bool = False
    not_out: bool = False
    ab_commutative: bool = False
    ac_commutative: bool = False
    bc_commutative: bool = False

    @property
    def extra_gates(self) -> int:
        """Number of NOT gates this decomposition adds on top of fun1/fun2."""
        return sum((self.not_a, self.not_b, self.not_c, self.not_out))


def create_2_input_fun(fun: int) -> BoolFunc:
    """Wraps a function nibble; A/B commutativity iff f(0,1) == f(1,0)."""
    assert 0 <= fun < 16
    return BoolFunc(
        num_inputs=2,
        fun=fun,
        fun1=fun,
        fun2=None,
        ab_commutative=bool(~((fun >> 1) ^ (fun >> 2)) & 1),
    )


def create_avail_gates(bitfield: int) -> List[BoolFunc]:
    """Expands a 16-bit gate-availability bitfield into BoolFuncs.

    Reference: create_avail_gates (sboxgates.c:870-880); the default set is
    AND+OR+XOR (sboxgates.c:1078).
    """
    assert 0 < bitfield <= 0xFFFF
    return [create_2_input_fun(i) for i in range(16) if bitfield & (1 << i)]


def get_not_functions(input_funs: Sequence[BoolFunc]) -> List[BoolFunc]:
    """For each available gate, adds its output complement if novel.

    E.g. AND available -> NAND becomes available by appending a NOT gate.
    Reference: get_not_functions (boolfunc.c:36-54).
    """
    have = {f.fun for f in input_funs}
    out: List[BoolFunc] = []
    for f in input_funs:
        cfun = ~f.fun & 0xF
        if cfun not in have and cfun not in {g.fun for g in out}:
            out.append(replace(f, fun=cfun, not_out=not f.not_out))
    return out


def _fun3_commutativity(fun: int) -> tuple:
    """(ab, ac, bc) commutativity of a 3-input function byte.

    Swapping two inputs permutes truth-table bit positions; the function is
    commutative in that pair iff the table is invariant (boolfunc.c:106-108).
    """
    ab = bool((~((fun >> 2) ^ (fun >> 4)) & ~((fun >> 3) ^ (fun >> 5))) & 1)
    ac = bool((~((fun >> 1) ^ (fun >> 4)) & ~((fun >> 3) ^ (fun >> 6))) & 1)
    bc = bool((~((fun >> 1) ^ (fun >> 2)) & ~((fun >> 5) ^ (fun >> 6))) & 1)
    return ab, ac, bc


def get_3_input_function_list(
    input_funs: Sequence[BoolFunc], try_nots: bool
) -> List[BoolFunc]:
    """All distinct 3-input functions buildable as fun2(fun1(A,B),C).

    With ``try_nots``, NOT gates may be placed on any of the three inputs (8
    polarity combinations) and on the output.  The first decomposition found
    for each 8-bit truth table wins, matching the reference's enumeration
    order (boolfunc.c:73-134): polarities in the order
    {none, c, b, a, b+c, a+c, a+b, a+b+c}, then fun1, then fun2.
    """
    funs: dict = {}
    # Reference order nots[] = {0,1,2,4,3,5,6,7} where bit2=not_a, bit1=not_b,
    # bit0=not_c applied to the *input index* during table construction.
    nots_order = (0, 1, 2, 4, 3, 5, 6, 7)
    for notsp in nots_order if try_nots else (0,):
        for fi in input_funs:
            for fk in input_funs:
                fun = 0
                for val in range(8):
                    idx = (7 - val) ^ notsp
                    a, b, c = (idx >> 2) & 1, (idx >> 1) & 1, idx & 1
                    fun = (fun << 1) | get_val(fk.fun, get_val(fi.fun, a, b), c)
                if fun not in funs:
                    ab, ac, bc = _fun3_commutativity(fun)
                    funs[fun] = BoolFunc(
                        num_inputs=3,
                        fun=fun,
                        fun1=fi.fun,
                        fun2=fk.fun,
                        not_a=bool(notsp & 4),
                        not_b=bool(notsp & 2),
                        not_c=bool(notsp & 1),
                        ab_commutative=ab,
                        ac_commutative=ac,
                        bc_commutative=bc,
                    )
    if try_nots:
        for fun in range(256):
            nfun = ~fun & 0xFF
            if fun in funs and nfun not in funs:
                base = funs[fun]
                ab, ac, bc = _fun3_commutativity(nfun)
                funs[nfun] = replace(
                    base,
                    fun=nfun,
                    not_out=True,
                    ab_commutative=ab,
                    ac_commutative=ac,
                    bc_commutative=bc,
                )
    return [funs[f] for f in sorted(funs)]


def permute_fun3(fun: int, perm: tuple) -> int:
    """Truth table of ``fun`` with its inputs permuted.

    ``perm`` maps new operand positions to old: the returned function g
    satisfies g(x0, x1, x2) = fun(x[perm[0]], x[perm[1]], x[perm[2]]).
    Used to fold non-commutative operand orders into plain byte comparisons
    in the triple sweep (replacing the reference's repeated ttable
    evaluations at sboxgates.c:406-432).
    """
    g = 0
    for k in range(8):
        x = ((k >> 2) & 1, (k >> 1) & 1, k & 1)  # (x0, x1, x2)
        src = (x[perm[0]] << 2) | (x[perm[1]] << 1) | x[perm[2]]
        g |= ((fun >> src) & 1) << k
    return g


def swap_fun2(fun: int) -> int:
    """Truth table of a 2-input function with A and B swapped."""
    return (fun & 0b1001) | ((fun & 0b0100) >> 1) | ((fun & 0b0010) << 1)
