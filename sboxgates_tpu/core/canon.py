"""Canonical forms of ``(target, mask, metric)`` queries.

The result store (``sboxgates_tpu.store``) keys finished circuits on the
CANONICAL representative of a query's equivalence class under the group
the truth-table algebra makes cheap to act with:

* **input permutation** — relabeling the S-box input variables,
* **input negation** — complementing any subset of input variables,
* **output complement** — complementing the whole table.

Two tenants asking for ``f(x0, x1, x2)`` and ``~f(~x1, x0, x2)`` are
asking for the same circuit up to a zero-cost rewiring, so both queries
must map to ONE store key — and the store must be able to rewrite the
stored circuit back into each tenant's frame (``store.rewrite``).

A group element is a :class:`Transform` ``t = (perm, neg, comp)`` acting
on tables as ``(t . T)(y) = comp ^ T(x)`` where input variable
``perm[k]`` of the original frame carries ``y_k ^ neg[k]``.  The algebra
(:func:`apply_transform` / :func:`compose` / :func:`invert`) is closed
and property-tested; :func:`canonical_key` returns both the key and the
concrete transform from the QUERY frame to the canonical frame, so a hit
can compose "query -> canonical -> publisher" into one rewrite.

Canonicalization strategy (exact, not heuristic): the canonical table is
the lexicographic minimum of ``t . T`` over a candidate set restricted
by *covariant* invariants — conditions on the RESULT table only (its
popcount, its per-variable cofactor counts), so every member of an
equivalence class restricts to the same residual set and therefore the
same minimum.  For random-looking tables (real S-box outputs) the
invariants collapse the 2 * 2^n * n! group to a handful of candidates
and the column-elimination scan finishes in well under a millisecond;
highly symmetric tables (XOR-like) would blow the candidate set up, so
past :data:`CANON_CAP` candidates the query falls back to an
exact-digest key (``kind="x"``) — still content-addressed and correct,
it just stops merging frames for that pathological orbit.  The fallback
decision is itself orbit-invariant (the candidate count is), so
equivalent queries always agree on which keying they use.

Only the standard low-``2^n`` masks (:func:`ttable.mask_table`) get the
canonical treatment — the permutation group is then exactly the first
``n`` variables and the mask is invariant.  Any other mask shape keys
exactly (don't-care bits are zeroed first either way, so the key never
depends on values outside the mask).
"""

from __future__ import annotations

import hashlib
import itertools
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from . import ttable as tt

#: Candidate-set ceiling for the exact lex-min scan.  Above this the
#: query keys exactly (see the module docstring); the bound keeps the
#: worst-case canonicalization cost (fully symmetric 8-input tables)
#: from turning store.get into a denial of service.
CANON_CAP = 4096

#: Key-format version — bump when the canonical form changes (old store
#: entries then simply stop matching instead of mismatching silently).
KEY_VERSION = 1


@dataclass(frozen=True)
class Transform:
    """One group element; ``perm[k]`` is the ORIGINAL variable index
    feeding transformed variable ``k`` (negated when ``neg[k]``), and
    ``comp`` complements the output."""

    perm: Tuple[int, ...]
    neg: Tuple[int, ...]
    comp: int

    @property
    def n(self) -> int:
        return len(self.perm)

    def is_identity(self) -> bool:
        return (
            self.comp == 0
            and not any(self.neg)
            and all(p == k for k, p in enumerate(self.perm))
        )


def identity_transform(n: int) -> Transform:
    return Transform(tuple(range(n)), (0,) * n, 0)


def _index_map(perm, neg) -> np.ndarray:
    """``x`` such that ``(t . T)[j] = comp ^ T[x[j]]`` for j < 2^n."""
    n = len(perm)
    j = np.arange(1 << n)
    x = np.zeros(1 << n, dtype=np.int64)
    for k in range(n):
        x |= (((j >> k) & 1) ^ int(neg[k])) << int(perm[k])
    return x


def apply_transform(t: Transform, table) -> np.ndarray:
    """``t . T`` as uint32 words; positions >= 2^n are zeroed (outside
    the canonical domain)."""
    # jaxlint: ignore[R2x] host-side by contract: store keys/rewrites are computed from host word arrays, never live device values
    bits = tt.to_bits(np.asarray(table, dtype=np.uint32))
    out = np.zeros(tt.TABLE_BITS, dtype=bool)
    dom = 1 << t.n
    out[:dom] = bits[_index_map(t.perm, t.neg)] ^ bool(t.comp)
    return tt.from_bits(out)


def compose(t2: Transform, t1: Transform) -> Transform:
    """``t2 o t1`` (apply ``t1`` first): ``(t2 o t1) . T = t2 . (t1 . T)``."""
    assert t1.n == t2.n
    n = t1.n
    perm = tuple(t1.perm[t2.perm[k]] for k in range(n))
    neg = tuple(t2.neg[k] ^ t1.neg[t2.perm[k]] for k in range(n))
    return Transform(perm, neg, t1.comp ^ t2.comp)


def invert(t: Transform) -> Transform:
    """``t^-1`` such that ``compose(invert(t), t)`` is the identity."""
    n = t.n
    inv = [0] * n
    for k, p in enumerate(t.perm):
        inv[p] = k
    perm = tuple(inv)
    neg = tuple(t.neg[perm[i]] for i in range(n))
    return Transform(perm, neg, t.comp)


def standard_mask_inputs(mask) -> Optional[int]:
    """``n`` when ``mask`` is exactly :func:`ttable.mask_table`'s
    low-``2^n`` form (the only mask the search drivers produce), else
    None — non-standard care-sets key exactly."""
    mask = np.asarray(mask, dtype=np.uint32)
    for n in range(1, 9):
        if np.array_equal(mask, tt.mask_table(n)):
            return n
    return None


def _digest(*parts: bytes) -> str:
    h = hashlib.blake2b(digest_size=20)
    for p in parts:
        h.update(p)
    return h.hexdigest()


def exact_key(target, mask, metric: int) -> str:
    """Exact-digest key (identity frame only): used for non-standard
    masks and over-:data:`CANON_CAP` symmetric orbits.  Don't-care bits
    are zeroed first, so the key never depends on values the mask
    excludes."""
    target = np.asarray(target, dtype=np.uint32)
    mask = np.asarray(mask, dtype=np.uint32)
    masked = (target & mask).astype("<u4")
    return "x%d-%s" % (
        int(metric),
        _digest(bytes([KEY_VERSION]), masked.tobytes(),
                mask.astype("<u4").tobytes()),
    )


def exact_multi_key(targets, mask, metric: int) -> str:
    """Exact key for a MULTI-output query (the all-outputs beam search):
    one digest over the per-bit tables in output order.  Multi-output
    joint canonicalization (shared input transform, per-bit complements,
    output reordering) is not attempted — cross-tenant repeats of whole
    S-boxes are overwhelmingly exact repeats."""
    mask = np.asarray(mask, dtype=np.uint32)
    parts = [bytes([KEY_VERSION, len(targets)]),
             mask.astype("<u4").tobytes()]
    for targ in targets:
        masked = (np.asarray(targ, dtype=np.uint32) & mask).astype("<u4")
        parts.append(masked.tobytes())
    return "m%d-%s" % (int(metric), _digest(*parts))


def _candidate_transforms(bits: np.ndarray, n: int):
    """The covariantly-restricted candidate set for ``bits`` (bool,
    length 2^n): arrays ``(P, NU, C)`` of per-candidate permutations,
    negations, and output complements, or None past :data:`CANON_CAP`.

    Restrictions (all conditions on the RESULT table, hence shared by
    every member of the orbit):

    * complement: the result's popcount is <= 2^(n-1) (tie: both),
    * negation: each result variable's 0-cofactor count <= its
      1-cofactor count (tie: both polarities),
    * permutation: the result's per-variable (min, max) cofactor-count
      pairs are non-decreasing (ties: all orders within a tie group).
    """
    dom = 1 << n
    idx = np.arange(dom)
    w = int(bits.sum())
    if 2 * w < dom:
        comp_choices = (0,)
    elif 2 * w > dom:
        comp_choices = (1,)
    else:
        comp_choices = (0, 1)

    rows: List[Tuple[int, Tuple[int, ...], Tuple[int, ...]]] = []
    total = 0
    for c in comp_choices:
        cb = bits ^ bool(c)
        wc = int(cb.sum())
        c1 = np.array(
            [int(cb[((idx >> i) & 1) == 1].sum()) for i in range(n)]
        )
        c0 = wc - c1
        negs = [
            (0,) if c0[i] < c1[i] else (1,) if c0[i] > c1[i] else (0, 1)
            for i in range(n)
        ]
        sig = [(int(min(c0[i], c1[i])), int(max(c0[i], c1[i])))
               for i in range(n)]
        order = sorted(range(n), key=lambda i: sig[i])
        groups: List[List[int]] = []
        for i in order:
            if groups and sig[groups[-1][0]] == sig[i]:
                groups[-1].append(i)
            else:
                groups.append([i])
        count = 1
        for g in groups:
            for f in range(2, len(g) + 1):
                count *= f
        for i in range(n):
            count *= len(negs[i])
        total += count
        if total > CANON_CAP:
            return None
        for parts in itertools.product(
            *[itertools.permutations(g) for g in groups]
        ):
            perm = tuple(v for part in parts for v in part)
            for nu in itertools.product(*[negs[v] for v in perm]):
                rows.append((c, perm, nu))
    P = np.array([p for _, p, _ in rows], dtype=np.int64)
    NU = np.array([nu for _, _, nu in rows], dtype=np.int64)
    C = np.array([c for c, _, _ in rows], dtype=np.uint8)
    return P, NU, C


def canonicalize(target, mask, metric: int):
    """``(key, transform)`` for one single-output query.

    ``transform`` maps the QUERY frame to the canonical frame
    (``apply_transform(transform, target & mask)`` IS the canonical
    table) and is None exactly when the key is exact-kind (non-standard
    mask, or a past-cap symmetric orbit) — those entries only ever match
    identity-frame repeats.  Deterministic: the same query always yields
    the same transform, so a repeated query composes to an identity
    rewrite and gets the stored bytes back untouched."""
    target = np.asarray(target, dtype=np.uint32)
    mask = np.asarray(mask, dtype=np.uint32)
    n = standard_mask_inputs(mask)
    if n is None:
        return exact_key(target, mask, metric), None
    dom = 1 << n
    bits = tt.to_bits(target & mask)[:dom]
    cands = _candidate_transforms(bits, n)
    if cands is None:
        return exact_key(target, mask, metric), None
    P, NU, C = cands
    Tb = bits.astype(np.uint8)
    kbits = np.arange(n)
    canon = np.zeros(dom, dtype=np.uint8)
    for j in range(dom):
        if len(P) == 1:
            canon = C[0] ^ Tb[_index_map(P[0], NU[0])]
            break
        jb = (j >> kbits) & 1
        x = ((jb[None, :] ^ NU) << P).sum(axis=1)
        b = C ^ Tb[x]
        mn = b.min()
        canon[j] = mn
        if b.max() != mn:
            keep = b == mn
            P, NU, C = P[keep], NU[keep], C[keep]
    key = "c%d-%d-%s" % (
        n, int(metric),
        _digest(bytes([KEY_VERSION, n]), canon.tobytes()),
    )
    return key, Transform(tuple(int(v) for v in P[0]),
                          tuple(int(v) for v in NU[0]), int(C[0]))
