"""Truth-table bitvector primitives.

A truth table (*ttable*) represents a Boolean function of up to eight
variables as a 256-bit vector: bit ``i`` holds the function value for input
``i``.  The reference implements this as a 256-bit GCC vector of four
``uint64_t`` lanes (``/root/reference/state.h:64-68``) with LSB-first bit
order inside each lane (``/root/reference/state.c:232-250``).

TPU-natively, a ttable is an array of **eight little-endian uint32 words**
(last axis), because uint32 is the natural VPU lane width.  Bit ``i`` lives
in word ``i // 32`` at position ``i % 32`` — the same global bit order as the
reference, just with a narrower word.  A *batch* of N tables is a
``uint32[N, 8]`` array; all gate evaluations are elementwise logic ops that
JAX maps straight onto the VPU, and batches shard along the leading axis.

All functions here are polymorphic over numpy and jax.numpy arrays: they use
only operators and methods both support, so the same code runs as the host
oracle and inside jitted sweeps.
"""

from __future__ import annotations

import numpy as np

N_WORDS = 8
WORD_BITS = 32
TABLE_BITS = N_WORDS * WORD_BITS  # 256

_FULL_WORD = np.uint32(0xFFFFFFFF)


def zero() -> np.ndarray:
    """All-false truth table."""
    return np.zeros(N_WORDS, dtype=np.uint32)


def ones() -> np.ndarray:
    """All-true truth table."""
    return np.full(N_WORDS, _FULL_WORD, dtype=np.uint32)


def from_bits(bits) -> np.ndarray:
    """Packs a boolean array (last axis = 256) into uint32 words (last axis = 8).

    Host-side constructor (numpy only).
    """
    bits = np.asarray(bits, dtype=bool)
    assert bits.shape[-1] == TABLE_BITS
    b = bits.reshape(bits.shape[:-1] + (N_WORDS, WORD_BITS)).astype(np.uint32)
    shifts = np.arange(WORD_BITS, dtype=np.uint32)
    return (b << shifts).sum(axis=-1, dtype=np.uint32)


def to_bits(tt) -> np.ndarray:
    """Unpacks uint32 words (last axis = 8) into a boolean array (last axis = 256).

    Host-side helper (numpy only).
    """
    # jaxlint: ignore[R2x] host-side helper by contract: decode/emit callers pass host word arrays; a device value crossing here is the documented boundary
    tt = np.asarray(tt, dtype=np.uint32)
    assert tt.shape[-1] == N_WORDS
    shifts = np.arange(WORD_BITS, dtype=np.uint32)
    bits = (tt[..., :, None] >> shifts) & np.uint32(1)
    return bits.reshape(tt.shape[:-1] + (TABLE_BITS,)).astype(bool)


def target_table(sbox: np.ndarray, bit: int) -> np.ndarray:
    """Truth table of output bit ``bit`` of an S-box.

    Bit ``i`` of the result is ``(sbox[i] >> bit) & 1``.  Equivalent of the
    reference's ``generate_target(bit, true)`` (state.c:232-250).
    """
    sbox = np.asarray(sbox, dtype=np.uint32)
    assert sbox.shape == (256,)
    return from_bits((sbox >> np.uint32(bit)) & np.uint32(1))


def input_table(var: int) -> np.ndarray:
    """Truth table of input variable ``var``: bit ``i`` is ``(i >> var) & 1``.

    Equivalent of the reference's ``generate_target(bit, false)``.
    """
    assert 0 <= var < 8
    idx = np.arange(TABLE_BITS, dtype=np.uint32)
    return from_bits((idx >> np.uint32(var)) & np.uint32(1))


def mask_table(num_inputs: int) -> np.ndarray:
    """Mask with the low ``2**num_inputs`` bits set.

    For an n-input S-box only the first 2^n positions of a ttable are
    meaningful; everything else is masked off.  Equivalent of the reference's
    ``generate_mask`` (sboxgates.c:644-659).
    """
    assert 1 <= num_inputs <= 8
    valid = 1 << num_inputs
    idx = np.arange(TABLE_BITS, dtype=np.uint32)
    return from_bits(idx < valid)


def is_zero(tt):
    """True where the table (last axis) is all-zero. Works on np and jnp."""
    return ~(tt != 0).any(axis=-1)


def eq_mask(a, b, mask):
    """Masked equality: true where ``a`` and ``b`` agree on all bits set in
    ``mask`` (reference: ``ttable_equals_mask``, sboxgates.c:91-93).

    Broadcasts over leading axes; reduces the last (word) axis.
    """
    return is_zero((a ^ b) & mask)


def _fresh(x):
    """Pass-through result: numpy arrays are copied so callers can never
    alias (and later mutate) a live gate table; jax values are immutable,
    and tracers (e.g. Pallas, whose Mosaic lowering has no copy_p rule)
    must pass through untouched."""
    return np.copy(x) if isinstance(x, np.ndarray) else x


# Direct expressions per gate nibble (enum value = truth table with
# f(1,1)=bit0, f(1,0)=bit1, f(0,1)=bit2, f(0,0)=bit3): 1-2 elementwise
# ops instead of the 11-op minterm sum — the host search engine evaluates
# one gate at a time, where numpy per-op overhead dominates.  The
# pass-through functions (A, B) return via _fresh (copy for numpy only).
_GATE2_DIRECT = {
    0b0000: lambda a, b: a & ~a,
    0b0001: lambda a, b: a & b,
    0b0010: lambda a, b: a & ~b,
    0b0011: lambda a, b: _fresh(a),
    0b0100: lambda a, b: ~a & b,
    0b0101: lambda a, b: _fresh(b),
    0b0110: lambda a, b: a ^ b,
    0b0111: lambda a, b: a | b,
    0b1000: lambda a, b: ~(a | b),
    0b1001: lambda a, b: ~(a ^ b),
    0b1010: lambda a, b: ~b,
    0b1011: lambda a, b: a | ~b,
    0b1100: lambda a, b: ~a,
    0b1101: lambda a, b: ~a | b,
    0b1110: lambda a, b: ~(a & b),
    0b1111: lambda a, b: ~(a & ~a),
}


def eval_gate2(fun, a, b):
    """Evaluates a 2-input gate given its 4-bit function value.

    The gate_type enum value *is* the function's truth table read MSB-first
    from input (A=0,B=0) (reference: get_val, boolfunc.c:22-25), i.e.::

        f(1,1) = bit0,  f(1,0) = bit1,  f(0,1) = bit2,  f(0,0) = bit3

    ``fun`` may be scalar or an array broadcastable against ``a``/``b``.
    Scalar functions dispatch to direct 1-2-op expressions; array
    functions use the sum-of-minterms form (four fused elementwise ops on
    the VPU instead of the reference's 16-way switch, boolfunc.c:136-157).
    """
    f = fun
    if isinstance(f, (int, np.integer)):
        return _GATE2_DIRECT[int(f) & 0xF](a, b)
    b0 = -((f >> 0) & 1)  # all-ones where bit set (two's complement trick)
    b1 = -((f >> 1) & 1)
    b2 = -((f >> 2) & 1)
    b3 = -((f >> 3) & 1)
    b0, b1, b2, b3 = (x.astype(a.dtype) for x in (b0, b1, b2, b3))
    return (b0 & a & b) | (b1 & a & ~b) | (b2 & ~a & b) | (b3 & ~a & ~b)


def eval_lut(func, a, b, c):
    """Evaluates a 3-input LUT given its 8-bit function value.

    Bit ``k`` of ``func`` is the output for inputs ``k = A<<2 | B<<1 | C``
    (reference: generate_lut_ttable, state.c:202-230).  Sum of the up-to-8
    minterms, vectorized over broadcast shapes.
    """
    f = func
    scalar = isinstance(f, (int, np.integer))

    def bit(k):
        v = -((f >> k) & 1)
        if scalar:
            return np.uint32(v & 0xFFFFFFFF)
        return v.astype(a.dtype)

    return (
        (bit(0) & ~a & ~b & ~c)
        | (bit(1) & ~a & ~b & c)
        | (bit(2) & ~a & b & ~c)
        | (bit(3) & ~a & b & c)
        | (bit(4) & a & ~b & ~c)
        | (bit(5) & a & ~b & c)
        | (bit(6) & a & b & ~c)
        | (bit(7) & a & b & c)
    )


def table_as_hex(tt) -> str:
    """Debug representation: 64 hex chars, most significant position first."""
    words = np.asarray(tt, dtype=np.uint32)
    return "".join(f"{int(w):08x}" for w in words[::-1])


def ttable_text(tt) -> str:
    """Byte-format parity with the reference's debug ttable dump
    (print_ttable, convert_graph.c:28-45): 256 bits as 16 rows of 16
    '0'/'1' characters, position 0 first, trailing newline."""
    words = np.asarray(tt, dtype=np.uint32).reshape(8)
    bits = ((words[:, None] >> np.arange(32)[None, :]) & 1).reshape(256)
    rows = [
        "".join(str(int(b)) for b in bits[r : r + 16])
        for r in range(0, 256, 16)
    ]
    return "\n".join(rows) + "\n"
