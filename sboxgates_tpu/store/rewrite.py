"""Circuit-graph rewriting under a :class:`~sboxgates_tpu.core.canon.Transform`.

A stored circuit realizes its publisher's target in the publisher's
input frame.  A store hit in ANOTHER frame composes
``query -> canonical -> publisher`` into one transform ``r`` and rewires
the graph instead of re-searching:

* **input permutation** — publisher input ``i`` becomes query input
  ``r.perm^-1(i)`` (IN gates stay the contiguous prefix; internal gate
  ids are unchanged, so an identity transform reproduces the stored
  graph byte-for-byte),
* **input negation** — absorbed into the consuming gates' function
  values (the 16 2-input functions and the 256 3-LUT functions are both
  closed under input complement); a NOT gate fed a complemented value
  keeps its shape and hands the complement to ITS consumers instead,
* **output complement** — absorbed into the output gate's function when
  that gate has no other consumers, resolved through NOT gates by
  rebinding the output to their operand, and only as a last resort
  materialized as one appended NOT gate.

Truth tables and the SAT metric are recomputed by the ordinary
:meth:`State.replay_gate` mutators — never trusted from the store — and
the store's ``get`` re-verifies the rewritten output against the
ORIGINAL query table over all 2^8 inputs before anything is returned.
"""

from __future__ import annotations

from ..core import boolfunc as bf
from ..core.canon import Transform
from ..graph.state import NO_GATE, State


class RewriteError(Exception):
    """The stored graph cannot be rewritten into the query frame."""


def _gate2_negate_inputs(fun: int, na: int, nb: int) -> int:
    """The 2-input function value computing ``f(a ^ na, b ^ nb)``.

    The gate-type enum value IS the function's truth table
    (``f(1,1)=bit0, f(1,0)=bit1, f(0,1)=bit2, f(0,0)=bit3`` — see
    ``ttable.eval_gate2``), so negating an input permutes its bits.
    """
    if na:
        fun = ((fun >> 2) & 0b0011) | ((fun << 2) & 0b1100)
    if nb:
        fun = ((fun >> 1) & 0b0101) | ((fun << 1) & 0b1010)
    return fun


def _lut_negate_inputs(fun: int, na: int, nb: int, nc: int) -> int:
    """The 3-LUT function computing ``f(a ^ na, b ^ nb, c ^ nc)`` (bit
    ``k`` of ``fun`` is the output for ``k = A<<2 | B<<1 | C``)."""
    flip = (na << 2) | (nb << 1) | nc
    if not flip:
        return fun
    out = 0
    for k in range(8):
        if (fun >> (k ^ flip)) & 1:
            out |= 1 << k
    return out


def rewrite_state(st: State, t: Transform) -> State:
    """A new :class:`State` computing ``t . (each bound output's table)``
    over the transformed input frame; see the module docstring.  The
    identity transform reproduces the input graph exactly (same gates,
    same wiring, same outputs)."""
    n = st.num_inputs
    if t.n != n:
        raise RewriteError(
            f"transform is over {t.n} inputs, circuit has {n}"
        )
    inv = [0] * n
    for k, p in enumerate(t.perm):
        if not (0 <= p < n):
            raise RewriteError(f"bad transform permutation {t.perm}")
        inv[p] = k
    new = State.init_inputs(n)
    # old gate id -> (new gate id, pending output complement): a flag
    # means "the new gate holds the COMPLEMENT of what consumers want"
    # and is absorbed by each consumer in turn.
    gmap = {i: (inv[i], t.neg[inv[i]]) for i in range(n)}
    for gid in range(n, st.num_gates):
        g = st.gates[gid]
        if g.type == bf.IN:
            raise RewriteError("IN gate outside the input prefix")
        if g.type == bf.NOT:
            ni, f1 = gmap[g.in1]
            gmap[gid] = (new.replay_gate(bf.NOT, ni, NO_GATE), f1)
        elif g.type == bf.LUT:
            (a, fa), (b, fb), (c3, fc) = (
                gmap[g.in1], gmap[g.in2], gmap[g.in3]
            )
            fun = _lut_negate_inputs(g.function, fa, fb, fc)
            gmap[gid] = (
                new.replay_gate(bf.LUT, a, b, c3, function=fun), 0
            )
        else:
            (a, fa), (b, fb) = gmap[g.in1], gmap[g.in2]
            fun = _gate2_negate_inputs(g.type, fa, fb)
            gmap[gid] = (new.replay_gate(fun, a, b), 0)

    consumers = [0] * new.num_gates
    for g in new.gates:
        for ref in (g.in1, g.in2, g.in3):
            if ref != NO_GATE:
                consumers[ref] += 1
    # Output-binding multiplicity: a gate bound by MORE than one output
    # bit must never be complemented in place — the first bit's flip
    # would silently invert what the second bit observes.
    bound: dict = {}
    for bit in range(8):
        if st.outputs[bit] != NO_GATE:
            ni0 = gmap[st.outputs[bit]][0]
            bound[ni0] = bound.get(ni0, 0) + 1

    outputs = [NO_GATE] * 8
    for bit in range(8):
        if st.outputs[bit] == NO_GATE:
            continue
        ni, flag = gmap[st.outputs[bit]]
        own = ni  # this bit's own binding may count once in `bound`
        want = flag ^ t.comp
        while want:
            g = new.gates[ni]
            if g.type == bf.NOT:
                # ~(~x) == x: bind the output to the NOT's operand.
                ni, want = g.in1, want ^ 1
                continue
            others = bound.get(ni, 0) - (1 if ni == own else 0)
            if consumers[ni] == 0 and others == 0 and g.type != bf.IN:
                # Complement the function in place: the gate feeds only
                # this output, so nothing else observes the flip.
                if g.type == bf.LUT:
                    fun = ~g.function & 0xFF
                    if fun == 0:
                        break  # constant-true LUT: fall through to NOT
                    g.function = fun
                else:
                    g.type = ~g.type & 0xF
                new.tables[ni] = ~new.tables[ni]
                want = 0
                continue
            break
        if want:
            ni = new.replay_gate(bf.NOT, ni, NO_GATE)
            consumers.append(0)
            consumers[new.gates[ni].in1] += 1
        outputs[bit] = ni
    new.outputs = outputs
    return new
