"""The content-addressed global result store (``--result-store DIR``).

One level up from the digest-keyed device-table and compile caches: a
DURABLE store of finished, verified circuit graphs — and the per-round
frontier of interrupted searches — keyed on the CANONICAL form of
``(target, mask, metric)`` (:mod:`sboxgates_tpu.core.canon`).  At
millions-of-users scale most submitted targets are not novel; the store
turns the engine from "compute per query" into "compute per NOVEL
query": a repeat query is answered from disk in milliseconds with zero
device dispatches, a repeat of an interrupted search resumes from the
stored frontier, and ReducedLUT-style decomposition sub-tables published
by one tenant's search serve every later tenant.

Layout (all writes tmp + fsync + atomic-replace, the checkpoint
durability discipline)::

    DIR/objects/<kk>/<key>.json          # circuit entries (key = canon digest)
    DIR/objects/<kk>/<fkey>.json         # frontier entries (exact frame + config)
    DIR/index.jsonl                      # advisory append-only listing
    DIR/quarantine/                      # corrupt entries, moved aside

Every entry embeds a SHA-256 over its body; a torn, truncated, or
digest-corrupt entry is treated as a MISS and moved to ``quarantine/``
— never a crash, never a wrong answer.  Full hits are additionally
re-verified against the ORIGINAL (uncanonicalized) query table over all
2^8 inputs after the frame rewrite, so even a store bug degrades to
miss-and-search.  ``index.jsonl`` is observability only — the
content-addressed object path IS the index, so a lost or corrupt index
costs nothing.

Chaos sites (``resilience.faults``): ``store.get`` entering a lookup,
``store.put`` before an entry write, ``store.index`` before an index
append.  An injected raise at any of them degrades (miss / skipped
publish / skipped index line) — the store never takes a search down.

Writes ride one background writer thread (:meth:`ResultStore._work`,
pinned in ``[tool.jaxlint] thread_roots``) so publishing never blocks a
search's completion path on an fsync; :meth:`flush` drains it (tests,
bench arms), :meth:`close` drains and stops it.  An unwritable or
read-only directory degrades the store to read-only mode with one
logged note — lookups keep working, publishes become no-ops.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..core import canon
from ..core import ttable as tt
from ..graph.state import NO_GATE, State
from ..graph.xmlio import state_from_xml, state_to_xml
from ..resilience.checkpoint import clean_stale_tmp, durable_write_text
from ..resilience.faults import (
    InjectedFault,
    current_job,
    fault_point,
    set_job,
)
from .rewrite import RewriteError, rewrite_state

logger = logging.getLogger(__name__)

#: Entry-format version; unknown versions read as a miss, not an error.
ENTRY_VERSION = 1

#: Most sub-table entries published per circuit (largest cones first).
SUB_ENTRY_CAP = 8


@dataclass
class StoreHit:
    """One full hit: the stored circuit rewritten into the QUERY frame
    and re-verified against the original query."""

    state: State
    key: str
    meta: dict = field(default_factory=dict)
    #: True when the composed rewrite was the identity — the returned
    #: graph is byte-identical to the published one.
    exact_frame: bool = True


class ResultStore:
    """Durable content-addressed result store; see the module docstring.

    ``stats`` (a ``telemetry.metrics.MetricsRegistry``) receives the
    declared ``store_*`` counters and the ``store_get_s`` histogram;
    None keeps the store silent.  ``sync`` forces writes inline
    (subprocess tests that exit immediately after a put)."""

    def __init__(self, root: str, stats=None, readonly: bool = False,
                 sync: bool = False):
        self.root = root
        self.stats = stats
        self._lock = threading.Lock()
        self.readonly = bool(readonly)
        if not self.readonly:
            try:
                os.makedirs(os.path.join(root, "objects"), exist_ok=True)
                if not os.access(root, os.W_OK):
                    raise OSError(f"{root} is not writable")
                for sub in self._object_dirs():
                    clean_stale_tmp(sub)
            except OSError as e:
                # The satellite degradation contract: an unwritable
                # store serves lookups read-only with one logged note.
                logger.warning(
                    "result store %s is not writable (%s); degrading to "
                    "read-only mode", root, e,
                )
                self.readonly = True
        self._queue: Optional["queue.Queue"] = None
        self._thread: Optional[threading.Thread] = None
        if not self.readonly and not sync:
            self._queue = queue.Queue()
            self._thread = threading.Thread(
                target=self._work, name="sbg-store-writer", daemon=True
            )
            self._thread.start()

    # -- plumbing ----------------------------------------------------------

    def _object_dirs(self) -> List[str]:
        base = os.path.join(self.root, "objects")
        try:
            # Sorted: index rebuilds and sweeps must visit shards in the
            # same order on every platform/filesystem.
            return [
                os.path.join(base, d) for d in sorted(os.listdir(base))
                if os.path.isdir(os.path.join(base, d))
            ]
        except OSError:
            return []

    def _path(self, key: str) -> str:
        return os.path.join(
            self.root, "objects", key[-2:], f"{key}.json"
        )

    def _inc(self, name: str, by: float = 1) -> None:
        if self.stats is not None:
            self.stats.inc(name, by)

    def _observe(self, name: str, v: float) -> None:
        if self.stats is not None:
            self.stats.observe(name, v)

    def _work(self) -> None:
        """The background writer: drains queued publish closures.  A
        failed write is logged and dropped — publishing is best-effort
        by contract (the search result is already safe on the caller's
        side)."""
        q = self._queue  # close() nulls the attribute; the local keeps
        while True:      # draining until the sentinel arrives
            item = q.get()
            if item is None:
                return
            try:
                item()
            except Exception as e:
                logger.warning("result store write failed: %r", e)

    def _submit(self, fn) -> None:
        # The caller's @job:ID fault pin rides onto the writer thread,
        # so store.put stays job-targetable through the async path.
        job = current_job()
        if self._queue is not None:
            def run() -> None:
                set_job(job)
                try:
                    fn()
                finally:
                    set_job(None)

            self._queue.put(run)
            return
        try:
            fn()
        except Exception as e:
            logger.warning("result store write failed: %r", e)

    def flush(self) -> None:
        """Blocks until every queued write has landed (tests/bench)."""
        if self._queue is None:
            return
        done = threading.Event()
        self._queue.put(done.set)
        done.wait(30.0)

    def close(self) -> None:
        """Drains and stops the writer thread; idempotent."""
        with self._lock:
            q, t = self._queue, self._thread
            self._queue, self._thread = None, None
        if q is not None:
            q.put(None)
        if t is not None:
            t.join(30.0)

    # -- entry files -------------------------------------------------------

    def _quarantine(self, path: str) -> None:
        qdir = os.path.join(self.root, "quarantine")
        try:
            os.makedirs(qdir, exist_ok=True)
            # jaxlint: ignore[R12] rename of already-durable bytes — no content is written, so there is nothing to tear
            os.replace(path, os.path.join(qdir, os.path.basename(path)))
            self._inc("store_corrupt_quarantined")
            logger.warning(
                "result store: corrupt entry %s quarantined", path
            )
        except OSError:
            logger.warning(
                "result store: corrupt entry %s could not be "
                "quarantined; treating as a miss", path,
            )

    def _load_entry(self, path: str) -> Optional[dict]:
        """The entry body, or None (missing / torn / digest-corrupt —
        corrupt files are quarantined, never fatal)."""
        try:
            with open(path, "r", encoding="utf-8") as f:
                raw = f.read()
        except OSError:
            return None
        try:
            doc = json.loads(raw)
            body = doc["body"]
            recorded = doc["sha256"]
        except (ValueError, KeyError, TypeError):
            self._quarantine(path)
            return None
        if doc.get("v") != ENTRY_VERSION:
            # An unknown (e.g. newer) entry version is a plain MISS,
            # never quarantine: stores are shared across builds, and an
            # older reader must not destroy an entry a newer build can
            # read.
            return None
        digest = hashlib.sha256(
            json.dumps(body, sort_keys=True).encode()
        ).hexdigest()
        if digest != recorded:
            self._quarantine(path)
            return None
        return body

    def _write_entry(self, key: str, body: dict) -> bool:
        """Durably publishes one entry; keep-first (the first publisher
        of a key wins — repeat queries then get byte-stable answers).
        Returns False when the key already existed."""
        path = self._path(key)
        with self._lock:
            if os.path.exists(path):
                return False
            fault_point("store.put")
            os.makedirs(os.path.dirname(path), exist_ok=True)
            doc = {
                "v": ENTRY_VERSION,
                "sha256": hashlib.sha256(
                    json.dumps(body, sort_keys=True).encode()
                ).hexdigest(),
                "body": body,
            }
            durable_write_text(path, json.dumps(doc, sort_keys=True))
        self._inc("store_puts")
        self._append_index(key, body.get("kind", "?"))
        return True

    def _append_index(self, key: str, kind: str) -> None:
        """Advisory listing line (observability; the object path is the
        real index) — any failure here is logged and ignored."""
        try:
            fault_point("store.index")
            with self._lock:
                with open(
                    os.path.join(self.root, "index.jsonl"), "a",
                    encoding="utf-8",
                ) as f:
                    f.write(json.dumps(
                        {"key": key, "kind": kind, "t": time.time()}
                    ) + "\n")
        except (OSError, InjectedFault) as e:
            logger.warning("result store index append failed: %r", e)

    # -- lookups -----------------------------------------------------------

    def fetch(self, target, mask, metric: int,
               frontier_cfg: Optional[dict] = None):
        """One single-output query: ``("hit", StoreHit)`` for a full
        circuit hit, ``("partial", frontier_body)`` when only an
        interrupted-search frontier matches (``frontier_cfg`` given),
        else ``("miss", None)``.  Counts store_hits /
        store_partial_hits / store_misses disjointly and observes the
        end-to-end latency into ``store_get_s``.  Never raises: every
        failure shape (injected fault, torn entry, failed rewrite,
        failed verification) degrades to a miss."""
        t0 = time.perf_counter()
        try:
            fault_point("store.get")
            hit = self._lookup_full(target, mask, metric)
            if hit is not None:
                self._inc("store_hits")
                return "hit", hit
            if frontier_cfg is not None:
                fr = self._lookup_frontier(
                    target, mask, metric, frontier_cfg
                )
                if fr is not None:
                    self._inc("store_partial_hits")
                    return "partial", fr
        except InjectedFault as e:
            logger.warning("result store lookup fault (%s); miss", e)
        except (OSError, ValueError, KeyError, RewriteError) as e:
            logger.warning("result store lookup failed (%r); miss", e)
        finally:
            self._observe("store_get_s", time.perf_counter() - t0)
        self._inc("store_misses")
        return "miss", None

    def fetch_multi(self, targets, mask, metric: int,
                     frontier_cfg: Optional[dict] = None):
        """The all-outputs variant: exact-key only (see
        ``canon.exact_multi_key``), every bound output verified."""
        t0 = time.perf_counter()
        try:
            fault_point("store.get")
            key = canon.exact_multi_key(targets, mask, metric)
            body = self._load_entry(self._path(key))
            if body is not None and body.get("kind") == "circuit":
                st = state_from_xml(body["circuit"])
                mask_w = np.asarray(mask, dtype=np.uint32)
                ok = all(
                    st.outputs[bit] != NO_GATE
                    and bool(tt.eq_mask(
                        st.tables[st.outputs[bit]],
                        np.asarray(targets[bit], dtype=np.uint32),
                        mask_w,
                    ))
                    for bit in range(len(targets))
                )
                if ok:
                    self._inc("store_hits")
                    return "hit", StoreHit(
                        st, key, dict(body.get("meta", {}))
                    )
                logger.warning(
                    "result store: entry %s failed re-verification; "
                    "treating as a miss", key,
                )
            if frontier_cfg is not None:
                fr = self._lookup_frontier(
                    None, mask, metric, frontier_cfg, multi=targets
                )
                if fr is not None:
                    self._inc("store_partial_hits")
                    return "partial", fr
        except InjectedFault as e:
            logger.warning("result store lookup fault (%s); miss", e)
        except (OSError, ValueError, KeyError) as e:
            logger.warning("result store lookup failed (%r); miss", e)
        finally:
            self._observe("store_get_s", time.perf_counter() - t0)
        self._inc("store_misses")
        return "miss", None

    def _lookup_full(self, target, mask, metric: int) -> Optional[StoreHit]:
        target = np.asarray(target, dtype=np.uint32)
        mask = np.asarray(mask, dtype=np.uint32)
        key, t_q = canon.canonicalize(target, mask, metric)
        body = self._load_entry(self._path(key))
        if body is None or body.get("kind") != "circuit":
            return None
        st = state_from_xml(body["circuit"])
        tr = body.get("transform")
        if (tr is None) != (t_q is None):
            return None  # key kinds can never mix, but stay defensive
        exact_frame = True
        if t_q is not None:
            t_pub = canon.Transform(
                tuple(tr["perm"]), tuple(tr["neg"]), int(tr["comp"])
            )
            r = canon.compose(canon.invert(t_q), t_pub)
            if not r.is_identity():
                exact_frame = False
                st = rewrite_state(st, r)
        # The safety net: whatever the canonicalization and rewrite did,
        # the returned circuit must realize the ORIGINAL query table on
        # every input the mask cares about — all 2^8 positions checked.
        gid = st.outputs[0]
        if gid == NO_GATE or not bool(
            tt.eq_mask(st.tables[gid], target, mask)
        ):
            logger.warning(
                "result store: entry %s failed re-verification against "
                "the query; treating as a miss", key,
            )
            return None
        return StoreHit(st, key, dict(body.get("meta", {})), exact_frame)

    # -- publishing --------------------------------------------------------

    def put_state(self, st: State, target, mask, metric: int,
                  output: int = 0, sub_tables: bool = False,
                  meta: Optional[dict] = None) -> None:
        """Publishes one finished single-output circuit (the value is
        normalized to bind output bit 0; hits rebind to the querying
        bit).  ``sub_tables`` also publishes the LUT-decomposition
        sub-circuits as shared entries (:data:`SUB_ENTRY_CAP` largest
        cones).  Asynchronous and best-effort: failures are logged,
        never raised into the search."""
        if self.readonly:
            return
        gid = st.outputs[output]
        if gid == NO_GATE:
            return
        target = np.asarray(target, dtype=np.uint32).copy()
        mask = np.asarray(mask, dtype=np.uint32).copy()
        entry_st = _rebind(st, gid)
        meta = dict(meta or {})
        subs: List[State] = (
            _sub_states(st, SUB_ENTRY_CAP) if sub_tables else []
        )

        def write() -> None:
            self._put_single(entry_st, target, mask, metric, meta)
            for sub in subs:
                sub_target = sub.tables[sub.outputs[0]]
                self._put_single(
                    sub, sub_target, mask, metric,
                    dict(meta, sub_table=True),
                )

        self._submit(write)

    def put_multi(self, st: State, targets, mask, metric: int,
                  sub_tables: bool = False,
                  meta: Optional[dict] = None) -> None:
        """Publishes a finished ALL-outputs circuit under its exact
        multi key, plus one single-output entry per bound output (the
        output's cone — so a later one-output query for any bit of this
        S-box, in any equivalent frame, hits) and optionally the LUT
        sub-tables."""
        if self.readonly:
            return
        targets = [np.asarray(t, dtype=np.uint32).copy() for t in targets]
        mask = np.asarray(mask, dtype=np.uint32).copy()
        meta = dict(meta or {})
        st = st.copy()
        subs: List[State] = (
            _sub_states(st, SUB_ENTRY_CAP) if sub_tables else []
        )

        def write() -> None:
            try:
                fault_point("store.put")
            except InjectedFault as e:
                logger.warning("result store put fault (%s); skipped", e)
                return
            key = canon.exact_multi_key(targets, mask, metric)
            body = {
                "kind": "circuit",
                "key": key,
                "metric": int(metric),
                "transform": None,
                "circuit": state_to_xml(st),
                "meta": meta,
            }
            try:
                self._write_entry(key, body)
            except (OSError, InjectedFault) as e:
                logger.warning("result store put failed (%r)", e)
            for bit in range(len(targets)):
                gid = st.outputs[bit]
                if gid == NO_GATE:
                    continue
                self._put_single(
                    _cone_state(st, gid), targets[bit], mask, metric,
                    dict(meta, output_bit=bit),
                )
            for sub in subs:
                self._put_single(
                    sub, sub.tables[sub.outputs[0]], mask, metric,
                    dict(meta, sub_table=True),
                )

        self._submit(write)

    def _put_single(self, st: State, target, mask, metric: int,
                    meta: dict) -> None:
        """One normalized (output-bit-0) circuit entry; canonical key +
        recorded publisher transform.  All failure shapes degrade to a
        skipped publish."""
        try:
            key, t_pub = canon.canonicalize(target, mask, metric)
            body = {
                "kind": "circuit",
                "key": key,
                "metric": int(metric),
                "transform": (
                    None if t_pub is None else {
                        "perm": list(t_pub.perm),
                        "neg": list(t_pub.neg),
                        "comp": t_pub.comp,
                    }
                ),
                "circuit": state_to_xml(st),
                "meta": meta,
            }
            self._write_entry(key, body)
        except (OSError, InjectedFault) as e:
            logger.warning("result store put failed (%r)", e)

    # -- frontiers (interrupted searches) ----------------------------------

    def _frontier_key(self, target, mask, metric: int, cfg: dict,
                      multi=None) -> str:
        """Frontier entries are EXACT-frame by contract: the journal
        snapshot embeds PRNG state, which does not commute with frame
        rewrites — so the key binds the exact target digest AND the
        draw-shaping configuration digest."""
        if multi is not None:
            base = canon.exact_multi_key(multi, mask, metric)
        else:
            base = canon.exact_key(target, mask, metric)
        cfg_digest = hashlib.blake2b(
            json.dumps(cfg, sort_keys=True, default=str).encode(),
            digest_size=12,
        ).hexdigest()
        return f"f-{base}-{cfg_digest}"

    def put_frontier(self, target, mask, metric: int, cfg: dict,
                     records: List[dict], checkpoints: Dict[str, str],
                     multi=None, meta: Optional[dict] = None) -> None:
        """Publishes the per-round frontier of an interrupted search:
        the journal's progress records (the PR 3 snapshot format —
        beam membership, budget ratchets, exact PRNG position) plus the
        checkpoint XML bodies they reference.  A later equivalent query
        with the SAME seed/configuration seeds its search from this
        frontier and finishes bit-identically to an uninterrupted
        run."""
        if self.readonly or not records:
            return
        key = self._frontier_key(target, mask, metric, cfg, multi=multi)
        body = {
            "kind": "frontier",
            "key": key,
            "metric": int(metric),
            "cfg": dict(cfg),
            "records": list(records),
            "checkpoints": dict(checkpoints),
            "meta": dict(meta or {}),
        }

        def write() -> None:
            try:
                # Frontiers overwrite-forward: a LATER frontier of the
                # same search strictly extends the earlier one (same
                # deterministic prefix), so last-writer-wins is safe and
                # resumes from the furthest published point.
                path = self._path(key)
                fault_point("store.put")
                with self._lock:
                    os.makedirs(os.path.dirname(path), exist_ok=True)
                    doc = {
                        "v": ENTRY_VERSION,
                        "sha256": hashlib.sha256(json.dumps(
                            body, sort_keys=True
                        ).encode()).hexdigest(),
                        "body": body,
                    }
                    durable_write_text(
                        path, json.dumps(doc, sort_keys=True)
                    )
                self._inc("store_puts")
                self._append_index(key, "frontier")
            except (OSError, InjectedFault) as e:
                logger.warning("result store frontier put failed (%r)", e)

        self._submit(write)

    def _lookup_frontier(self, target, mask, metric: int, cfg: dict,
                         multi=None) -> Optional[dict]:
        key = self._frontier_key(target, mask, metric, cfg, multi=multi)
        body = self._load_entry(self._path(key))
        if body is None or body.get("kind") != "frontier":
            return None
        # The key already binds the cfg digest; the full comparison
        # closes the (vanishing) digest-collision window.
        if json.dumps(body.get("cfg"), sort_keys=True, default=str) != \
                json.dumps(cfg, sort_keys=True, default=str):
            return None
        return body

    # -- introspection -----------------------------------------------------

    def status_view(self) -> dict:
        """Host-side store counters for /status and the serve queue
        view; zero device syncs."""
        s = self.stats
        return {
            "root": self.root,
            "readonly": self.readonly,
            "hits": int(s.get("store_hits", 0)) if s is not None else 0,
            "misses": (
                int(s.get("store_misses", 0)) if s is not None else 0
            ),
            "partial_hits": (
                int(s.get("store_partial_hits", 0))
                if s is not None else 0
            ),
            "puts": int(s.get("store_puts", 0)) if s is not None else 0,
        }


def _rebind(st: State, gid: int) -> State:
    """A copy with ONLY output bit 0 bound to ``gid`` — the normalized
    entry shape (hits rebind to the querying bit)."""
    out = st.copy()
    out.outputs = [NO_GATE] * 8
    out.outputs[0] = gid
    return out


def _cone_reachable(st: State, gid: int) -> List[int]:
    """Gate ids (sorted) reachable from ``gid`` through inputs,
    EXCLUDING the IN prefix."""
    n = st.num_inputs
    seen = set()
    stack = [gid]
    while stack:
        g = stack.pop()
        if g in seen or g < n:
            continue
        seen.add(g)
        gate = st.gates[g]
        for ref in (gate.in1, gate.in2, gate.in3):
            if ref != NO_GATE:
                stack.append(ref)
    return sorted(seen)


def _cone_state(st: State, gid: int) -> State:
    """The subcircuit realizing gate ``gid``: same IN prefix, only the
    cone's gates (original order), output bit 0 bound to the root."""
    n = st.num_inputs
    cone = _cone_reachable(st, gid)
    new = State.init_inputs(n)
    remap = {i: i for i in range(n)}
    for g in cone:
        gate = st.gates[g]
        remap[g] = new.replay_gate(
            gate.type,
            remap.get(gate.in1, NO_GATE) if gate.in1 != NO_GATE else NO_GATE,
            remap.get(gate.in2, NO_GATE) if gate.in2 != NO_GATE else NO_GATE,
            remap.get(gate.in3, NO_GATE) if gate.in3 != NO_GATE else NO_GATE,
            function=gate.function,
        )
    new.outputs[0] = remap[gid]
    return new


def _sub_states(st: State, cap: int) -> List[State]:
    """The ReducedLUT-style shared sub-entries: for each LUT gate whose
    cone holds at least two gates (a real decomposition sub-table, not
    a single-gate triviality), the cone as a standalone circuit —
    largest cones first, at most ``cap``."""
    from ..core import boolfunc as bf

    n = st.num_inputs
    cones = []
    for gid in range(n, st.num_gates):
        if st.gates[gid].type != bf.LUT:
            continue
        cone = _cone_reachable(st, gid)
        if len(cone) >= 2:
            cones.append((len(cone), gid))
    cones.sort(key=lambda c: (-c[0], c[1]))
    return [_cone_state(st, gid) for _, gid in cones[:cap]]
