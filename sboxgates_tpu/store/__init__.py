"""Content-addressed global result store (see ``store.store``)."""

from .store import ResultStore, StoreHit  # noqa: F401
from .rewrite import RewriteError, rewrite_state  # noqa: F401
