"""R12: durability discipline + the chaos-coverage report.

**R12.** The torn-write contract (PR 3) says every file the recovery
path may read is produced by ONE idiom: write the full payload to a
``TMP_PREFIX`` temp file, ``fsync`` it, ``os.replace`` it over the
destination, ``fsync`` the directory — all packaged in
``resilience.checkpoint.durable_write_text``.  Until now that was
convention; this pass makes it structural: inside the persistence
modules (``[tool.jaxlint] durable_modules``), a truncating ``open``
(``"w"``/``"x"`` modes), a ``json.dump`` to a stream, or a raw
``os.replace`` outside the declared ``durable_helpers`` is a finding.
Append-mode opens stay legal — the journal's fsync'd append protocol
is a different (and valid) durability discipline.

**Chaos coverage.** ``faults.KNOWN_SITES`` declares the crash surface;
the kill matrices only mean something if every declared site is
actually exercised.  :func:`chaos_coverage` cross-references the
declared sites (extracted by the R7 machinery) against a static scan
of ``tests/`` for ``faults.arm(...)`` calls and ``SBG_FAULTS``-style
spec strings, minus reasoned waivers from ``[tool.jaxlint]
chaos_waivers`` ("site: reason").  A waiver naming a site that is no
longer declared is itself a finding — the R7 stale-pin contract.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import ProjectGraph, iter_body_nodes, spec_matches_function
from .config import JaxlintConfig
from .registries import CONFIG_PATH, extract_registries
from .rules import dotted

RawFinding = Tuple[str, int, int, str]

_TRUNCATING = frozenset("wx")


def _tail(name: Optional[str]) -> Optional[str]:
    return name.rsplit(".", 1)[-1] if name else None


def _open_mode(node: ast.Call) -> Optional[str]:
    """The literal mode of an ``open``-family call, if statically known
    (second positional arg or ``mode=`` keyword; default "r")."""
    expr: Optional[ast.AST] = None
    if len(node.args) >= 2:
        expr = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            expr = kw.value
    if expr is None:
        return "r"
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    return None


def run_r12(graph: ProjectGraph,
            config: JaxlintConfig) -> Dict[str, List[RawFinding]]:
    """R12 findings per project-relative path."""
    out: Dict[str, List[RawFinding]] = {}
    helpers = list(config.durable_helpers)
    for fkey in sorted(graph.functions):
        fi = graph.functions[fkey]
        if not config.is_durable(fi.path):
            continue
        if any(spec_matches_function(s, fkey) for s in helpers):
            continue  # the helper IS the sanctioned idiom
        for node in iter_body_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name in ("open", "io.open", "os.fdopen"):
                mode = _open_mode(node)
                if mode is not None and any(
                    ch in _TRUNCATING for ch in mode
                ):
                    out.setdefault(fi.path, []).append(
                        (
                            "R12",
                            node.lineno,
                            node.col_offset,
                            f"truncating open(mode={mode!r}) in a "
                            "persistence module bypasses the durable "
                            "helper — a kill mid-write leaves a torn "
                            "file; route through durable_write_text "
                            "(tmp + fsync + atomic replace) or "
                            "acknowledge with ignore[R12] and a reason",
                        )
                    )
            elif name == "json.dump":
                out.setdefault(fi.path, []).append(
                    (
                        "R12",
                        node.lineno,
                        node.col_offset,
                        "json.dump to a stream in a persistence module "
                        "bypasses the durable helper — serialize with "
                        "json.dumps and route through "
                        "durable_write_text, or acknowledge with "
                        "ignore[R12] and a reason",
                    )
                )
            elif name == "os.replace":
                out.setdefault(fi.path, []).append(
                    (
                        "R12",
                        node.lineno,
                        node.col_offset,
                        "raw os.replace in a persistence module — the "
                        "atomic-replace step belongs inside the durable "
                        "helper (which fsyncs payload AND directory); "
                        "route through durable_write_text or "
                        "acknowledge with ignore[R12] and a reason",
                    )
                )
    return out


# --------------------------------------------------------------------------
# chaos coverage


#: One "site[:action][@when]" element of an SBG_FAULTS spec string.
_SPEC_RE = re.compile(
    r"^([a-z_][a-z0-9_.]*)"          # site
    r"(?:@(?:rank|job):[^:]+)?"      # optional @rank:N / @job:ID target
    r":(?:raise|crash|hang)"         # action
    r"(?:@\d+\+?)?$"                 # optional trigger
)


def _sites_in_spec_string(text: str) -> List[str]:
    sites: List[str] = []
    for part in text.split(","):
        m = _SPEC_RE.match(part.strip())
        if m:
            sites.append(m.group(1))
    return sites


def _scan_test_source(src: str, declared: Set[str]) -> Set[str]:
    """Fault sites a test file arms: ``faults.arm("site", ...)`` calls,
    any ``SBG_FAULTS``-shaped spec string constant, and bare string
    constants naming a declared site (parametrized site lists build the
    spec in an f-string the scanner cannot fold).  Bare names count only
    when the file shows real fault plumbing — an ``arm()`` call, a spec
    constant, or a non-docstring ``SBG_FAULTS`` reference — so a site
    name quoted in, say, the coverage gate's own assertions arms
    nothing."""
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return set()
    docstrings: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.FunctionDef,
                             ast.AsyncFunctionDef, ast.ClassDef)):
            body = node.body
            if body and isinstance(body[0], ast.Expr) \
                    and isinstance(body[0].value, ast.Constant) \
                    and isinstance(body[0].value.value, str):
                docstrings.add(id(body[0].value))
    armed: Set[str] = set()
    bare: Set[str] = set()
    plumbed = False
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _tail(dotted(node.func)) == "arm":
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                armed.add(node.args[0].value.partition("@")[0])
                plumbed = True
        elif isinstance(node, ast.Constant) \
                and isinstance(node.value, str) \
                and id(node) not in docstrings:
            spec_sites = _sites_in_spec_string(node.value)
            if spec_sites:
                armed.update(spec_sites)
                plumbed = True
            if node.value == "SBG_FAULTS":
                plumbed = True
            if node.value in declared:
                bare.add(node.value)
    if plumbed:
        armed |= bare
    return armed


def _default_test_sources(config: JaxlintConfig) -> Dict[str, str]:
    """{relpath: source} for every test file under <root>/tests, fixture
    packs excluded (an ``arm()`` in a lint fixture is not a test)."""
    out: Dict[str, str] = {}
    tests_dir = os.path.join(config.root, "tests")
    for dirpath, dirnames, filenames in os.walk(tests_dir):
        dirnames[:] = sorted(
            d for d in dirnames if d != "analysis_fixtures"
        )
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, config.root).replace(os.sep, "/")
            try:
                with open(full, "r", encoding="utf-8") as f:
                    out[rel] = f.read()
            except OSError:
                continue
    return out


def parse_waivers(config: JaxlintConfig
                  ) -> Tuple[Dict[str, str], List[str]]:
    """(site -> reason, malformed entries).  A waiver is "site: reason";
    the reason is mandatory — a bare site name waives nothing."""
    waivers: Dict[str, str] = {}
    malformed: List[str] = []
    for entry in config.chaos_waivers:
        site, sep, reason = entry.partition(":")
        site, reason = site.strip(), reason.strip()
        if not sep or not site or not reason:
            malformed.append(entry)
            continue
        waivers[site] = reason
    return waivers, malformed


def chaos_coverage(
    graph: ProjectGraph,
    config: JaxlintConfig,
    test_sources: Optional[Dict[str, str]] = None,
) -> dict:
    """Cross-reference declared fault sites against armed tests.

    Returns a deterministic report dict; ``uncovered`` and
    ``stale_waivers`` non-empty means the gate fails."""
    declared = extract_registries(graph).fault_sites
    if test_sources is None:
        test_sources = _default_test_sources(config)
    declared_names = set(declared.entries)
    armed_by: Dict[str, List[str]] = {}
    for rel in sorted(test_sources):
        for site in sorted(
            _scan_test_source(test_sources[rel], declared_names)
        ):
            armed_by.setdefault(site, []).append(rel)
    waivers, malformed = parse_waivers(config)

    sites: Dict[str, dict] = {}
    uncovered: List[str] = []
    for name in sorted(declared.entries):
        path, line = declared.entries[name]
        armed = armed_by.get(name, [])
        waiver = waivers.get(name)
        sites[name] = {
            "declared": f"{path}:{line}",
            "armed_by": armed,
            "waiver": waiver,
        }
        if not armed and waiver is None:
            uncovered.append(name)
    stale = sorted(
        s for s in waivers if s not in declared.entries
    ) + sorted(f"(malformed) {e}" for e in malformed)
    return {
        "schema": 1,
        "config": CONFIG_PATH,
        "sites": sites,
        "uncovered": uncovered,
        "stale_waivers": stale,
        "armed_total": sum(
            1 for s in sites.values() if s["armed_by"]
        ),
        "declared_total": len(sites),
    }
