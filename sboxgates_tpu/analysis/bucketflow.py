"""R8 — bucket-discipline dataflow (shape provenance at dispatch sites).

Every operand shape entering a registered kernel must derive from a
declared bucket ladder (``bucket_size``/``PIVOT_G_BUCKETS``/
``FLEET_BUCKETS``/``STACKED_BUCKETS``, plus ``[tool.jaxlint]
bucket_sources`` extras): jit specializes on shapes, so an operand
padded to ``bucket - n`` compiles once per bucket, while one shaped by
a raw ``n``/``len(...)``/loop variable compiles once per VALUE — a
recompile storm that silently erases the compile-cache win.  The
per-file R1 sees only static-argument churn; this pass follows the
shape expressions themselves.

The analysis is intraprocedural by design: a dispatch site's operands
are either constructed in the dispatching function (checkable here) or
built by a shared operand builder whose own dispatch-facing shapes are
checked where THEY dispatch.  For each call of
``kernel_call``/``stream_dispatch``/``feasible_stream_dispatch`` in a
dispatch module, every array-constructor shape expression reachable
through local assignments is classified per axis:

* an axis mentioning a bucket source (directly, through local
  derivation, or via arithmetic like ``bucket - n`` — the padding
  idiom) is disciplined;
* an axis built ONLY from dynamic values (parameters, loop variables,
  locals of unknown provenance, data-dependent calls like ``len``) is
  a finding;
* constants and module-level names are static — one shape, no hazard.

Deliberately unbucketed shapes (a one-off probe, a host-only path) are
acknowledged with ``# jaxlint: ignore[R8] reason``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .callgraph import ProjectGraph, iter_body_nodes as _body_nodes
from .config import JaxlintConfig
from .rules import dotted

RawFinding = Tuple[str, int, int, str]

#: Dispatch entry points whose operands the pass follows.
_DISPATCH_TAILS = frozenset(
    {"kernel_call", "stream_dispatch", "feasible_stream_dispatch"}
)

#: Array constructors and the index/kwarg of their shape expression.
#: ``None`` index = every positional argument is an axis (reshape).
_SHAPE_CTORS: Dict[str, Tuple[Optional[int], Optional[str]]] = {
    "zeros": (0, "shape"),
    "ones": (0, "shape"),
    "empty": (0, "shape"),
    "full": (0, "shape"),
    "broadcast_to": (1, "shape"),
    "reshape": (None, None),
    "pad": (1, None),  # pad_width carries the bucket arithmetic
}

_ARRAY_HEADS = frozenset({"np", "numpy", "jnp", "jax"})


def _tail(name: Optional[str]) -> str:
    return (name or "").rsplit(".", 1)[-1]


def _is_const_expr(expr: ast.AST) -> bool:
    """Built only from literals and operators — no names, calls, or
    attribute loads, so the value is the same every execution."""
    return not any(
        isinstance(n, (ast.Name, ast.Call, ast.Attribute))
        for n in ast.walk(expr)
    )


def _is_source_name(name: str, sources: Sequence[str]) -> bool:
    t = _tail(name)
    return t in sources or "bucket" in t.lower()


class _FuncShapes:
    """Shape-provenance scan of ONE function."""

    def __init__(self, fi, config: JaxlintConfig) -> None:
        self.fi = fi
        self.sources = list(config.bucket_sources)
        self.assigns: Dict[str, List[ast.AST]] = {}
        self.loop_vars: Set[str] = set()
        a = fi.node.args
        self.params: Set[str] = {
            p.arg
            for p in (
                a.posonlyargs + a.args + a.kwonlyargs
                + ([a.vararg] if a.vararg else [])
                + ([a.kwarg] if a.kwarg else [])
            )
        }
        self._index(fi.node)
        self.derived = self._derived_fixpoint()
        #: locals whose every assignment is a compile-time-constant
        #: expression — one shape, no recompile hazard (n = 128)
        self.const_locals: Set[str] = {
            name
            for name, exprs in self.assigns.items()
            if name not in self.loop_vars
            and all(_is_const_expr(e) for e in exprs)
        }

    def _index(self, fn: ast.AST) -> None:
        for node in _body_nodes(fn):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                if node.value is None:
                    continue
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            self.assigns.setdefault(n.id, []).append(
                                node.value
                            )
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                for n in ast.walk(node.target):
                    if isinstance(n, ast.Name):
                        self.loop_vars.add(n.id)
            elif isinstance(node, ast.comprehension):
                for n in ast.walk(node.target):
                    if isinstance(n, ast.Name):
                        self.loop_vars.add(n.id)

    def _expr_mentions_derived(self, expr: ast.AST,
                               derived: Set[str]) -> bool:
        for n in ast.walk(expr):
            if isinstance(n, ast.Name):
                if n.id in derived or _is_source_name(n.id, self.sources):
                    return True
            elif isinstance(n, (ast.Attribute, ast.Call)):
                name = dotted(n if isinstance(n, ast.Attribute) else n.func)
                if name is not None and _is_source_name(name, self.sources):
                    return True
        return False

    def _derived_fixpoint(self) -> Set[str]:
        derived: Set[str] = set()
        for p in self.params:
            if _is_source_name(p, self.sources):
                derived.add(p)
        changed = True
        while changed:
            changed = False
            for name in sorted(self.assigns):
                if name in derived:
                    continue
                if any(
                    self._expr_mentions_derived(e, derived)
                    for e in self.assigns[name]
                ):
                    derived.add(name)
                    changed = True
        return derived

    # -- axis classification ----------------------------------------------

    def axis_offenders(self, expr: ast.AST) -> Tuple[bool, List[str]]:
        """(mentions a bucket derivation, dynamic offender names)."""
        has_derived = False
        offenders: List[str] = []

        def walk(n: ast.AST) -> None:
            nonlocal has_derived
            if isinstance(n, ast.Name):
                if n.id in self.derived or _is_source_name(
                    n.id, self.sources
                ):
                    has_derived = True
                elif n.id in self.loop_vars:
                    offenders.append(f"loop variable '{n.id}'")
                elif n.id in self.params:
                    offenders.append(f"parameter '{n.id}'")
                elif n.id in self.const_locals:
                    pass  # constant-assigned local: static, quiet
                elif n.id in self.assigns:
                    offenders.append(f"'{n.id}'")
                # else: module constant / import — static, quiet
                return
            if isinstance(n, ast.Attribute):
                name = dotted(n)
                if name is not None and _is_source_name(name, self.sources):
                    has_derived = True
                # other attributes (x.shape, CONST.width) stay quiet
                return
            if isinstance(n, ast.Call):
                fname = dotted(n.func)
                if fname is not None and _is_source_name(
                    fname, self.sources
                ):
                    has_derived = True
                    return
                for a in list(n.args) + [
                    kw.value for kw in n.keywords
                ]:
                    walk(a)
                offenders.append(f"'{_tail(fname) or '<call>'}()'")
                return
            for child in ast.iter_child_nodes(n):
                walk(child)

        walk(expr)
        return has_derived, offenders


def _shape_exprs(call: ast.Call) -> List[ast.AST]:
    """The shape expression(s) of an array-constructor call, if it is
    one."""
    fname = dotted(call.func)
    t = _tail(fname)
    if t not in _SHAPE_CTORS:
        return []
    if t == "reshape":
        if not isinstance(call.func, ast.Attribute):
            return []
        head = (fname or "").split(".", 1)[0]
        if head in _ARRAY_HEADS:
            # free function np.reshape(arr, newshape): the array operand
            # is not an axis
            return list(call.args[1:])
        return list(call.args)  # method form x.reshape(a, b, ...)
    # np/jnp free functions only (a project helper named `zeros` is not
    # an array constructor we can reason about)
    head = (fname or "").split(".", 1)[0]
    if head not in _ARRAY_HEADS:
        return []
    idx, kwname = _SHAPE_CTORS[t]
    out: List[ast.AST] = []
    if idx is not None and len(call.args) > idx:
        out.append(call.args[idx])
    if kwname is not None:
        out.extend(
            kw.value for kw in call.keywords if kw.arg == kwname
        )
    return out


def run_r8(graph: ProjectGraph,
           config: JaxlintConfig) -> Dict[str, List[RawFinding]]:
    out: Dict[str, List[RawFinding]] = {}
    for fkey in sorted(graph.functions):
        fi = graph.functions[fkey]
        if not config.is_dispatch(fi.path):
            continue
        scan: Optional[_FuncShapes] = None
        for node in _body_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            if _tail(dotted(node.func)) not in _DISPATCH_TAILS:
                continue
            if scan is None:
                scan = _FuncShapes(fi, config)
            kernel = "?"
            if node.args and isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                kernel = node.args[0].value
            seen_ctors: Set[int] = set()
            arg_exprs = list(node.args) + [
                kw.value for kw in node.keywords
            ]
            for arg in arg_exprs:
                for ctor, shape in _operand_shapes(arg, scan):
                    if id(ctor) in seen_ctors:
                        continue
                    seen_ctors.add(id(ctor))
                    _check_shape(
                        out, fi.path, kernel, ctor, shape, scan
                    )
    return out


def _operand_shapes(arg: ast.AST, scan: _FuncShapes):
    """(constructor call, shape expr) pairs reachable from one operand
    expression: constructors inline in the expression, plus those in
    the local assignments of every name it mentions (transitively)."""
    exprs: List[ast.AST] = [arg]
    visited: Set[str] = set()
    frontier = [
        n.id for n in ast.walk(arg) if isinstance(n, ast.Name)
    ]
    while frontier:
        name = frontier.pop()
        if name in visited:
            continue
        visited.add(name)
        for e in scan.assigns.get(name, ()):
            exprs.append(e)
            frontier.extend(
                n.id for n in ast.walk(e) if isinstance(n, ast.Name)
            )
    for e in exprs:
        for n in ast.walk(e):
            if isinstance(n, ast.Call):
                for shape in _shape_exprs(n):
                    yield n, shape


def _check_shape(out, path: str, kernel: str, ctor: ast.Call,
                 shape: ast.AST, scan: _FuncShapes) -> None:
    axes = (
        shape.elts
        if isinstance(shape, (ast.Tuple, ast.List))
        else [shape]
    )
    bad: List[str] = []
    for axis in axes:
        has_derived, offenders = scan.axis_offenders(axis)
        if offenders and not has_derived:
            bad.extend(offenders)
    if not bad:
        return
    uniq = sorted(set(bad))
    out.setdefault(path, []).append(
        (
            "R8",
            ctor.lineno,
            ctor.col_offset,
            f"operand shape for dispatch of '{kernel}' derives from "
            f"non-bucketed value(s) {', '.join(uniq)}: every distinct "
            "value compiles a fresh executable — pad to a declared "
            "bucket ladder (bucket_size/PIVOT_G_BUCKETS/FLEET_BUCKETS/"
            "STACKED_BUCKETS) or acknowledge with ignore[R8] and a "
            "reason",
        )
    )
