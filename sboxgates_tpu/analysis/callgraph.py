"""Project symbol table and cross-module call graph for jaxlint.

The per-file rules (R1–R5) are structurally blind to anything that
crosses a module boundary: a lock imported from a sibling module, a
thread target that reaches shared state three calls deep, a jitted
function whose static args are abused from another file.  This module
builds the whole-program substrate the ``x``-rules run on:

* a **module index** per file — import aliases, module-level locks and
  mutable state, every function/method (any nesting level), classes,
  and ``global``-rebound names;
* a **project symbol resolver** that chases import/alias/re-export
  chains to the defining module;
* a **call graph** whose edges carry the call site, the enclosing
  ``with``-lock stack, loop context, and the raw ``ast.Call`` (for
  static-arg inspection), with name-based fallback resolution for
  attribute calls (``self.stream.next_chunk(...)`` resolves to every
  project method named ``next_chunk``);
* **thread-entry roots** (``threading.Thread(target=...)`` anywhere,
  plus ``[tool.jaxlint] thread_roots`` extras) and **jit-boundary
  roots** (functions jit-decorated or jit-wrapped at module scope,
  plus ``jit_roots`` extras);
* a **lock-parameter fixpoint** so ``with lock:`` counts as held when
  the lock arrives as an argument, and **unlocked reachability** from
  the thread roots with path reconstruction for the findings.

Everything is deterministic: module/function iteration is sorted, BFS
uses sorted adjacency, and name-based candidates are sorted, so two
runs over the same tree produce byte-identical output.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .rules import (
    _LOCK_CTORS,
    _MUTABLE_CTORS,
    _MUTATORS,
    _const_ints,
    _const_strs,
    _jit_call_of,
    classify_sync,
    dotted,
)

#: Attribute-call method names never resolved by bare name: they collide
#: with builtin container / file / threading APIs, and a false edge from
#: ``d.get(...)`` into a project method named ``get`` would make half the
#: package spuriously thread-reachable.  Skipping only costs edges
#: (false negatives), never false findings.
_COMMON_METHOD_NAMES = frozenset(
    {
        "append", "extend", "insert", "add", "update", "remove", "discard",
        "pop", "popitem", "popleft", "appendleft", "clear", "setdefault",
        "get", "put", "set", "is_set", "wait", "notify", "notify_all",
        "join", "start", "acquire", "release", "items", "keys", "values",
        "close", "open", "read", "write", "flush", "seek", "copy", "sort",
        "split", "strip", "format", "encode", "decode", "count", "index",
        "result", "done", "cancel", "submit", "mkdir", "exists", "lower",
        "upper", "startswith", "endswith", "replace", "tolist", "item",
        "astype", "reshape", "sum", "any", "all", "min", "max", "mean",
    }
)

_THREAD_CTORS = {"threading.Thread", "Thread"}


def iter_body_nodes(fn_node: ast.AST):
    """Every AST node in a function's own BODY — decorators and nested
    defs/lambdas excluded (decorators are definition-time; nested defs
    are their own graph entries).  Shared by the R7/R8 contract passes."""
    stack = list(getattr(fn_node, "body", ()))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def strip_locals(qualname: str) -> str:
    """``outer.<locals>.inner`` -> ``outer.inner`` — the form config
    root specs are written in (nobody types ``<locals>`` in pyproject)."""
    return qualname.replace(".<locals>", "")


def spec_matches_function(spec: str, key: str) -> bool:
    """Does a config root spec ("Qual.Name" or "pkg.mod:Qual.Name")
    name this function key?  ``<locals>`` segments in the key are
    transparent."""
    mod, qual = key.split(":", 1)
    quals = (qual, strip_locals(qual))
    if ":" in spec:
        smod, squal = spec.split(":", 1)
        return smod == mod and squal in quals
    return spec in quals


def _locally_bound_names(fn: ast.AST) -> Set[str]:
    """Names bound in ``fn``'s own scope: parameters plus assignment /
    loop / with-as / except-as / comprehension targets.  Nested def and
    lambda subtrees are skipped — their bindings live in THEIR scopes."""
    a = fn.args
    out: Set[str] = {
        p.arg
        for p in (
            a.posonlyargs + a.args + a.kwonlyargs
            + ([a.vararg] if a.vararg else [])
            + ([a.kwarg] if a.kwarg else [])
        )
    }

    def add_target(t: ast.AST) -> None:
        for n in ast.walk(t):
            if isinstance(n, ast.Name):
                out.add(n.id)

    def walk(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    out.add(child.name)
                continue
            if isinstance(child, (ast.Assign, ast.AugAssign,
                                  ast.AnnAssign)):
                targets = (
                    child.targets
                    if isinstance(child, ast.Assign)
                    else [child.target]
                )
                for t in targets:
                    # Name and tuple/list/starred unpacking targets bind
                    # locals; Attribute/Subscript stores bind nothing
                    # (and walking them would wrongly collect the base
                    # object's name).
                    if isinstance(t, (ast.Name, ast.Tuple, ast.List,
                                      ast.Starred)):
                        add_target(t)
            elif isinstance(child, (ast.For, ast.AsyncFor)):
                add_target(child.target)
            elif isinstance(child, (ast.With, ast.AsyncWith)):
                for item in child.items:
                    if item.optional_vars is not None:
                        add_target(item.optional_vars)
            elif isinstance(child, ast.ExceptHandler):
                if child.name:
                    out.add(child.name)
            elif isinstance(child, ast.comprehension):
                add_target(child.target)
            elif isinstance(child, ast.NamedExpr):
                add_target(child.target)
            walk(child)

    walk(fn)
    return out


def bind_call_args(callee: "FunctionInfo", call: ast.Call):
    """(param name, argument expr) pairs for a call of ``callee``,
    skipping the implicit ``self`` of bound-method calls.  ONE binder
    for the lock-parameter fixpoint and R1x — drift here would check
    the wrong parameter."""
    params = callee.params
    skip_self = 1 if callee.cls is not None and params[:1] == ["self"] else 0
    bound = []
    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Starred):
            break  # positional binding unknowable past *args
        j = i + skip_self
        if j < len(params):
            bound.append((params[j], arg))
    for kw in call.keywords:
        if kw.arg is not None:
            bound.append((kw.arg, kw.value))
    return bound


def module_name_for(relpath: str) -> str:
    """Dotted module name for a project-relative posix path."""
    mod = relpath[:-3] if relpath.endswith(".py") else relpath
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    elif mod == "__init__":
        mod = ""
    return mod


def _resolve_relative(module: str, is_package: bool, level: int,
                      target: str) -> str:
    """``from ..p import x`` resolution: the absolute module the import
    names (without the imported symbol)."""
    parts = module.split(".") if module else []
    if not is_package:
        parts = parts[:-1]
    drop = level - 1
    if drop:
        parts = parts[: len(parts) - drop] if drop <= len(parts) else []
    if target:
        parts = parts + target.split(".")
    return ".".join(parts)


@dataclass
class FunctionInfo:
    """One function or method (any nesting level)."""

    qualname: str  # "Class.meth", "fn", "outer.<locals>.inner"
    module: str
    path: str
    node: ast.AST  # FunctionDef / AsyncFunctionDef
    cls: Optional[str] = None  # enclosing class name, if a method
    parent: Optional[str] = None  # enclosing function qualname, if nested
    params: List[str] = field(default_factory=list)
    #: static parameter names when jit-decorated with statics
    jit_statics: Set[str] = field(default_factory=set)
    jit_decorated: bool = False

    @property
    def key(self) -> str:
        return f"{self.module}:{self.qualname}"


@dataclass
class CallSite:
    """One resolved call edge out of a function."""

    caller: str  # FunctionInfo.key
    callee: str  # FunctionInfo.key
    path: str
    line: int
    col: int
    #: with-items lexically enclosing the call (raw exprs; lockedness is
    #: evaluated after the lock-parameter fixpoint)
    with_stack: Tuple[ast.AST, ...] = ()
    in_loop: bool = False
    loop_vars: Tuple[str, ...] = ()
    call: Optional[ast.Call] = None
    #: resolution mode: "direct" (name/import) or "attr" (name-based)
    via: str = "direct"


@dataclass
class Mutation:
    """A mutation of module-level mutable state inside a function."""

    func: str  # FunctionInfo.key
    state_module: str
    state_name: str
    path: str
    line: int
    col: int
    what: str  # rendered form for the message
    with_stack: Tuple[ast.AST, ...] = ()


@dataclass
class ThreadCreation:
    """One ``threading.Thread(target=...)`` creation site (R7 pin gate)."""

    func: str  # creating FunctionInfo.key ("" at module scope)
    path: str
    line: int
    col: int
    #: resolved target function keys (name-based attr fallback may yield
    #: several candidates; empty when unresolvable)
    targets: Tuple[str, ...] = ()
    raw: str = ""  # the target expression as written


@dataclass
class SyncSite:
    """A host-device sync expression inside a function (R2x taint seed)."""

    func: str
    path: str
    line: int
    col: int
    desc: str


@dataclass
class ModuleInfo:
    name: str
    path: str
    tree: ast.Module
    is_package: bool
    imports: Dict[str, str] = field(default_factory=dict)  # alias -> dotted
    #: module-level name -> "lock" | "mutable" | "other"
    assigns: Dict[str, str] = field(default_factory=dict)
    functions: Dict[str, FunctionInfo] = field(default_factory=dict)
    classes: Dict[str, Set[str]] = field(default_factory=dict)
    global_rebinds: Set[str] = field(default_factory=set)
    #: name -> (target function qualname, statics) for module-level
    #: ``name = jax.jit(fn, static_argnames=...)`` wrappers
    jit_aliases: Dict[str, Tuple[str, Set[str]]] = field(default_factory=dict)


class ProjectGraph:
    """The resolved whole-program view: modules, functions, edges, roots."""

    def __init__(self) -> None:
        self.modules: Dict[str, ModuleInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        #: method name -> sorted function keys (name-based resolution)
        self.methods: Dict[str, List[str]] = {}
        self.edges: List[CallSite] = []
        self.out_edges: Dict[str, List[CallSite]] = {}
        self.mutations: List[Mutation] = []
        self.sync_sites: List[SyncSite] = []
        #: function keys directly named as Thread targets (+ config extras)
        self.thread_roots: List[str] = []
        #: every Thread(target=...) creation site, resolved or not (R7)
        self.thread_creations: List[ThreadCreation] = []
        #: jit-decorated or module-scope jit-wrapped functions (+ extras)
        self.jit_roots: List[str] = []
        #: instance attribute names assigned a Lock anywhere in the project
        self.lock_attrs: Set[str] = set()
        #: per-function lock-typed parameter names (fixpoint result)
        self.lock_params: Dict[str, Set[str]] = {}
        #: set by the R9 pass (analysis.lockorder.LockOrderResult) so
        #: --graph can export the lock-order graph alongside the calls
        self.lock_order = None

    # -- symbol resolution -------------------------------------------------

    def resolve(self, module: str, name: str,
                _depth: int = 0) -> Optional[Tuple[str, str]]:
        """Resolves a dotted name used in ``module`` to a defining
        ``(module, symbol)`` pair; symbol may be "" for a bare module.
        Chases import aliases and re-exports (bounded depth)."""
        if _depth > 12:
            return None
        mi = self.modules.get(module)
        if mi is None:
            return None
        # Longest alias prefix match.
        parts = name.split(".")
        for cut in range(len(parts), 0, -1):
            prefix = ".".join(parts[:cut])
            target = mi.imports.get(prefix)
            if target is None:
                continue
            rest = parts[cut:]
            full = target + ("." + ".".join(rest) if rest else "")
            return self._resolve_absolute(full, _depth)
        # A bare name defined in this module.
        if len(parts) == 1:
            if (
                parts[0] in mi.assigns
                or parts[0] in mi.functions
                or parts[0] in mi.classes
                or parts[0] in mi.jit_aliases
            ):
                return (module, parts[0])
        # "mod.sym" where the head is this very module's name is unusual;
        # fall through to absolute resolution for fully-qualified uses.
        return self._resolve_absolute(name, _depth)

    def _resolve_absolute(self, full: str,
                          _depth: int) -> Optional[Tuple[str, str]]:
        """Splits an absolute dotted path into (project module, symbol)."""
        parts = full.split(".")
        for cut in range(len(parts), 0, -1):
            mod = ".".join(parts[:cut])
            mi = self.modules.get(mod)
            if mi is None:
                continue
            rest = parts[cut:]
            if not rest:
                return (mod, "")
            sym = rest[0]
            # Re-export: the symbol is itself an import alias there.
            if sym in mi.imports and sym not in mi.assigns \
                    and sym not in mi.functions and sym not in mi.classes:
                chased = self.resolve(mod, ".".join(rest), _depth + 1)
                if chased is not None:
                    return chased
            if len(rest) == 1:
                return (mod, sym)
            # Class attribute / nested access: keep the head symbol.
            return (mod, sym)
        return None

    def expand_alias(self, module: str, name: str) -> str:
        """The absolute dotted name after expanding ``module``'s import
        aliases (one level; no project-module requirement) — for
        recognizing stdlib references like ``th.Thread`` under
        ``import threading as th``."""
        mi = self.modules.get(module)
        if mi is None:
            return name
        parts = name.split(".")
        for cut in range(len(parts), 0, -1):
            prefix = ".".join(parts[:cut])
            target = mi.imports.get(prefix)
            if target is not None:
                rest = parts[cut:]
                return target + ("." + ".".join(rest) if rest else "")
        return name

    def resolve_function(self, module: str,
                         name: str) -> Optional[FunctionInfo]:
        """Resolves a call-expression name to a project function, through
        imports and module-scope jit aliases."""
        got = self.resolve(module, name)
        if got is None:
            return None
        mod, sym = got
        mi = self.modules.get(mod)
        if mi is None or not sym:
            return None
        if sym in mi.jit_aliases:
            target, _statics = mi.jit_aliases[sym]
            return mi.functions.get(target)
        return mi.functions.get(sym)

    def jit_statics_for(self, module: str,
                        name: str) -> Optional[Tuple[FunctionInfo, Set[str]]]:
        """(function, static param names) when ``name`` used in ``module``
        is a jitted callable with static args — via decorator or a
        module-scope ``x = jax.jit(fn, static_argnames=...)`` alias."""
        got = self.resolve(module, name)
        if got is None:
            return None
        mod, sym = got
        mi = self.modules.get(mod)
        if mi is None or not sym:
            return None
        if sym in mi.jit_aliases:
            target, statics = mi.jit_aliases[sym]
            fn = mi.functions.get(target)
            if fn is not None and statics:
                return fn, statics
            return None
        fn = mi.functions.get(sym)
        if fn is not None and fn.jit_statics:
            return fn, fn.jit_statics
        return None

    def is_lock_symbol(self, module: str, name: str) -> bool:
        got = self.resolve(module, name)
        if got is None:
            return False
        mod, sym = got
        mi = self.modules.get(mod)
        return mi is not None and mi.assigns.get(sym) == "lock"

    def mutable_symbol(self, module: str,
                       name: str) -> Optional[Tuple[str, str]]:
        got = self.resolve(module, name)
        if got is None:
            return None
        mod, sym = got
        mi = self.modules.get(mod)
        if mi is not None and mi.assigns.get(sym) == "mutable":
            return (mod, sym)
        return None

    # -- lockedness --------------------------------------------------------

    def _expr_is_lock(self, module: str, func: Optional[str],
                      expr: ast.AST) -> bool:
        """Is this with-item / argument expression a known lock?"""
        name = dotted(expr)
        if name is None:
            return False
        if isinstance(expr, ast.Attribute):
            if expr.attr in self.lock_attrs:
                return True
        if "." not in name and func is not None:
            if name in self.lock_params.get(func, ()):  # passed-in lock
                return True
        return self.is_lock_symbol(module, name)

    def stack_holds_lock(self, module: str, func: Optional[str],
                         with_stack: Sequence[ast.AST]) -> bool:
        return any(self._expr_is_lock(module, func, e) for e in with_stack)

    # -- reachability ------------------------------------------------------

    def unlocked_reachable(self) -> Dict[str, List[str]]:
        """Functions reachable from a thread root through edges whose call
        sites hold no lock; value = one witness path (root first)."""
        reach: Dict[str, List[str]] = {}
        frontier: List[str] = []
        for root in sorted(set(self.thread_roots)):
            if root in self.functions and root not in reach:
                reach[root] = [root]
                frontier.append(root)
        while frontier:
            frontier.sort()
            nxt: List[str] = []
            for fkey in frontier:
                fi = self.functions[fkey]
                for e in self.out_edges.get(fkey, ()):
                    if e.callee in reach or e.callee not in self.functions:
                        continue
                    if self.stack_holds_lock(fi.module, fkey, e.with_stack):
                        continue  # callee runs under a lock on this path
                    reach[e.callee] = reach[fkey] + [e.callee]
                    nxt.append(e.callee)
            frontier = nxt
        return reach

    def sync_taint(self, acknowledged: Set[Tuple[str, int]]
                   ) -> Dict[str, SyncSite]:
        """Fixpoint of "this function transitively performs a host sync".

        ``acknowledged``: (path, line) pairs carrying a valid R2/R2x
        suppression — a deliberate, justified sync does not taint its
        callers.  Value = the witness sync site (minimal (path, line))."""
        taint: Dict[str, SyncSite] = {}
        for s in sorted(self.sync_sites,
                        key=lambda s: (s.path, s.line, s.col)):
            if (s.path, s.line) in acknowledged:
                continue
            if s.func not in taint:
                taint[s.func] = s
        changed = True
        while changed:
            changed = False
            for fkey in sorted(self.functions):
                best = taint.get(fkey)
                for e in self.out_edges.get(fkey, ()):
                    w = taint.get(e.callee)
                    if w is None:
                        continue
                    if best is None or (w.path, w.line, w.col) < (
                        best.path, best.line, best.col
                    ):
                        best = w
                if best is not None and taint.get(fkey) is not best:
                    if fkey not in taint or (
                        (best.path, best.line, best.col)
                        < (taint[fkey].path, taint[fkey].line,
                           taint[fkey].col)
                    ):
                        taint[fkey] = best
                        changed = True
        return taint

    def call_index(self, fkey: str) -> Dict[Tuple[int, int], List[str]]:
        """(line, col) of each resolved call inside ``fkey`` -> sorted
        callee keys.  Lets a lexical pass (R10's branch-side walk) look
        up which project functions a given ``ast.Call`` resolves to."""
        idx: Dict[Tuple[int, int], List[str]] = {}
        for e in self.out_edges.get(fkey, ()):
            idx.setdefault((e.line, e.col), []).append(e.callee)
        for v in idx.values():
            v.sort()
        return idx

    def reach_witness(self, seeds: Dict[str, str]) -> Dict[str, str]:
        """Fixpoint of "this function transitively reaches a seeded site".

        ``seeds``: function key -> witness description.  Result maps every
        function that reaches a seed through call edges to the minimal
        witness string (deterministic: same shape as ``sync_taint`` but
        generic over what the seeds mean — R10 seeds agreement sites,
        R11 seeds nondeterminism sources)."""
        reach = dict(seeds)
        changed = True
        while changed:
            changed = False
            for fkey in sorted(self.functions):
                best = reach.get(fkey)
                for e in self.out_edges.get(fkey, ()):
                    w = reach.get(e.callee)
                    if w is not None and (best is None or w < best):
                        best = w
                if best is not None and reach.get(fkey) != best:
                    reach[fkey] = best
                    changed = True
        return reach

    # -- serialization -----------------------------------------------------

    def as_json(self) -> dict:
        """Deterministic JSON view for ``--graph`` debugging."""
        return {
            "modules": sorted(self.modules),
            "functions": {
                k: {
                    "path": fi.path,
                    "line": fi.node.lineno,
                    "class": fi.cls,
                    "jit_statics": sorted(fi.jit_statics),
                }
                for k, fi in sorted(self.functions.items())
            },
            "edges": [
                {
                    "caller": e.caller,
                    "callee": e.callee,
                    "path": e.path,
                    "line": e.line,
                    "locked": self.stack_holds_lock(
                        self.functions[e.caller].module, e.caller,
                        e.with_stack,
                    ),
                    "in_loop": e.in_loop,
                    "via": e.via,
                }
                for e in sorted(
                    self.edges,
                    key=lambda e: (e.path, e.line, e.col, e.caller, e.callee),
                )
            ],
            "thread_roots": sorted(set(self.thread_roots)),
            "jit_roots": sorted(set(self.jit_roots)),
            "lock_attrs": sorted(self.lock_attrs),
            "lock_params": {
                k: sorted(v)
                for k, v in sorted(self.lock_params.items())
                if v
            },
        }


# --------------------------------------------------------------------------
# module indexing


_ABS_LOCK_CTORS = frozenset(
    {"threading.Lock", "threading.RLock", "threading.Condition"}
)


def _expand_imports(imports: Dict[str, str], name: str) -> str:
    """Longest-prefix import-alias expansion of a dotted name (e.g.
    ``_threading.Lock`` -> ``threading.Lock`` under ``import threading
    as _threading``)."""
    parts = name.split(".")
    for cut in range(len(parts), 0, -1):
        target = imports.get(".".join(parts[:cut]))
        if target is not None:
            rest = parts[cut:]
            return target + ("." + ".".join(rest) if rest else "")
    return name


def is_lock_ctor(name: Optional[str],
                 imports: Optional[Dict[str, str]] = None) -> bool:
    """Is this dotted call name a Lock/RLock/Condition constructor,
    including through an import alias (``import threading as _th``)?"""
    if name is None:
        return False
    if name in _LOCK_CTORS:
        return True
    if imports is not None:
        return _expand_imports(imports, name) in _ABS_LOCK_CTORS
    return False


def _classify_module_assign(value: ast.AST,
                            imports: Optional[Dict[str, str]] = None) -> str:
    vname = dotted(value.func) if isinstance(value, ast.Call) else None
    if is_lock_ctor(vname, imports):
        return "lock"
    if isinstance(value, (ast.List, ast.Dict, ast.Set)) or (
        vname in _MUTABLE_CTORS
    ):
        return "mutable"
    return "other"


def index_module(relpath: str, tree: ast.Module) -> ModuleInfo:
    name = module_name_for(relpath)
    mi = ModuleInfo(
        name=name,
        path=relpath,
        tree=tree,
        is_package=relpath.endswith("__init__.py"),
    )

    for node in tree.body:
        if isinstance(node, ast.Import):
            for al in node.names:
                mi.imports[al.asname or al.name] = al.name
        elif isinstance(node, ast.ImportFrom):
            base = _resolve_relative(
                name, mi.is_package, node.level, node.module or ""
            ) if node.level else (node.module or "")
            for al in node.names:
                if al.name == "*":
                    continue
                mi.imports[al.asname or al.name] = (
                    f"{base}.{al.name}" if base else al.name
                )
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [
                node.target
            ]
            value = node.value
            if value is None:
                continue
            for t in targets:
                if not isinstance(t, ast.Name):
                    continue
                mi.assigns[t.id] = _classify_module_assign(value, mi.imports)
                # Module-scope jit wrapper: name = jax.jit(fn, ...)
                call = _jit_call_of(value)
                if call is not None and call.args and isinstance(
                    call.args[0], ast.Name
                ):
                    target_fn = call.args[0].id
                    mi.jit_aliases[t.id] = (target_fn, set())

    # Functions (all nesting levels) and classes, with qualnames.
    def add_function(node, qual: str, cls: Optional[str],
                     parent: Optional[str]) -> None:
        fi = FunctionInfo(
            qualname=qual,
            module=name,
            path=relpath,
            node=node,
            cls=cls,
            parent=parent,
            params=[a.arg for a in node.args.posonlyargs + node.args.args],
        )
        _apply_jit_decorators(fi, node)
        mi.functions[qual] = fi
        walk_defs(node.body, f"{qual}.<locals>.", None, qual)

    def walk_defs(body, qual_prefix: str, cls: Optional[str],
                  parent: Optional[str]) -> None:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                add_function(node, f"{qual_prefix}{node.name}", cls, parent)
            elif isinstance(node, ast.ClassDef):
                methods = {
                    sub.name
                    for sub in node.body
                    if isinstance(sub,
                                  (ast.FunctionDef, ast.AsyncFunctionDef))
                }
                mi.classes[node.name] = methods
                # methods get "Class.meth" qualnames
                for sub in node.body:
                    if isinstance(sub,
                                  (ast.FunctionDef, ast.AsyncFunctionDef)):
                        add_function(
                            sub, f"{node.name}.{sub.name}", node.name, None
                        )

    walk_defs(tree.body, "", None, None)

    # Drop jit aliases whose wrapped function isn't a module-level def,
    # then bind each surviving alias's static names to the target's
    # params (the alias assignment's jit(...) call names them).
    for alias, (target, _s) in list(mi.jit_aliases.items()):
        if target not in mi.functions:
            del mi.jit_aliases[alias]
    for node in tree.body:
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        if node.value is None:
            continue
        call = _jit_call_of(node.value)
        if call is None or not call.args:
            continue
        targets = node.targets if isinstance(node, ast.Assign) else [
            node.target
        ]
        for t in targets:
            if isinstance(t, ast.Name) and t.id in mi.jit_aliases:
                target, _ = mi.jit_aliases[t.id]
                fn = mi.functions[target]
                statics = _call_statics(fn.params, call)
                mi.jit_aliases[t.id] = (target, statics)

    # global-rebound module names count as mutable scalar state
    for node in ast.walk(tree):
        if isinstance(node, ast.Global):
            for n in node.names:
                mi.global_rebinds.add(n)
                if n in mi.assigns and mi.assigns[n] == "other":
                    mi.assigns[n] = "mutable"
    return mi


def _decorator_statics(fn: ast.AST, jit_call: ast.Call) -> Set[str]:
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    return _call_statics(params, jit_call)


_JIT_DECORATOR_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}


def _apply_jit_decorators(fi: FunctionInfo, node: ast.AST) -> None:
    """Marks ``fi`` jitted when ``node`` carries a jit decorator — the
    call form (``@jax.jit(...)`` / ``@partial(jax.jit, ...)``) or the
    bare-name form (``@jax.jit``)."""
    for dec in node.decorator_list:
        call = _jit_call_of(dec)
        if call is not None:
            fi.jit_decorated = True
            fi.jit_statics = _decorator_statics(node, call)
        elif dotted(dec) in _JIT_DECORATOR_NAMES:
            fi.jit_decorated = True


def _call_statics(params: List[str], jit_call: ast.Call) -> Set[str]:
    out: Set[str] = set()
    for kw in jit_call.keywords:
        if kw.arg == "static_argnames":
            out.update(_const_strs(kw.value))
        elif kw.arg == "static_argnums":
            for n in _const_ints(kw.value):
                if 0 <= n < len(params):
                    out.add(params[n])
    return out


# --------------------------------------------------------------------------
# per-function body scan (calls, mutations, syncs, thread targets)


class _BodyScan(ast.NodeVisitor):
    """Walks ONE function's body (skipping nested defs — they are their
    own graph nodes), collecting call sites with their with/loop
    context, mutations of module-level state, and sync expressions."""

    def __init__(self, graph: ProjectGraph, mi: ModuleInfo,
                 fi: FunctionInfo) -> None:
        self.g = graph
        self.mi = mi
        self.fi = fi
        self.with_stack: List[ast.AST] = []
        self.loop_depth = 0
        self.loop_vars: List[Set[str]] = []
        self.globals_declared: Set[str] = set()
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Global):
                self.globals_declared.update(node.names)
        # Names bound in THIS function's scope (params + assignments +
        # loop/with/except targets, nested defs excluded): a bare use of
        # one refers to the local, not to same-named module state — it
        # must not resolve through the project symbol table.
        self.local_names = _locally_bound_names(fi.node)
        self.local_names -= self.globals_declared

    def _shadowed(self, name: str) -> bool:
        return name.split(".", 1)[0] in self.local_names

    def run(self) -> None:
        for stmt in self.fi.node.body:
            self.visit(stmt)

    # ---- context tracking

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass  # nested defs scanned as their own functions

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass

    def visit_With(self, node: ast.With) -> None:
        self.with_stack.extend(item.context_expr for item in node.items)
        for child in node.body:
            self.visit(child)
        del self.with_stack[len(self.with_stack) - len(node.items):]
        for item in node.items:
            self.visit(item.context_expr)

    def visit_For(self, node: ast.For) -> None:
        names = {
            n.id for n in ast.walk(node.target) if isinstance(n, ast.Name)
        }
        self.loop_vars.append(names)
        self.loop_depth += 1
        for child in node.body:
            self.visit(child)
        self.loop_depth -= 1
        self.loop_vars.pop()
        # the else: body and the iterable run once, outside the loop
        for child in node.orelse:
            self.visit(child)
        self.visit(node.iter)

    def visit_While(self, node: ast.While) -> None:
        self.loop_vars.append(set())
        self.loop_depth += 1
        # the test re-evaluates every iteration: it IS loop context
        self.visit(node.test)
        for child in node.body:
            self.visit(child)
        self.loop_depth -= 1
        self.loop_vars.pop()
        for child in node.orelse:
            self.visit(child)

    # ---- mutations and syncs

    def _all_loop_vars(self) -> Tuple[str, ...]:
        out: Set[str] = set()
        for frame in self.loop_vars:
            out |= frame
        return tuple(sorted(out))

    def _note_mutation(self, node: ast.AST, mod: str, sym: str,
                       what: str) -> None:
        self.g.mutations.append(
            Mutation(
                func=self.fi.key,
                state_module=mod,
                state_name=sym,
                path=self.fi.path,
                line=node.lineno,
                col=node.col_offset,
                what=what,
                with_stack=tuple(self.with_stack),
            )
        )

    def visit_Assign(self, node: ast.Assign) -> None:
        self._check_store_targets(node.targets)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_store_targets([node.target])
        self.generic_visit(node)

    def _check_store_targets(self, targets) -> None:
        for t in targets:
            if isinstance(t, ast.Name) and t.id in self.globals_declared:
                if self.mi.assigns.get(t.id) in ("mutable", "other"):
                    self._note_mutation(
                        t, self.mi.name, t.id, f"'{t.id}'"
                    )
            elif isinstance(t, ast.Subscript):
                name = dotted(t.value)
                if name is None or self._shadowed(name):
                    continue
                got = self.g.mutable_symbol(self.mi.name, name)
                if got is not None:
                    self._note_mutation(
                        t, got[0], got[1], f"'{name}[...]'"
                    )

    def visit_Call(self, node: ast.Call) -> None:
        self._check_thread_ctor(node)
        self._check_sync(node)
        name = dotted(node.func)
        if name is not None:
            # container mutator on resolved module-level state
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS:
                base = dotted(node.func.value)
                if base is not None and not self._shadowed(base):
                    got = self.g.mutable_symbol(self.mi.name, base)
                    if got is not None:
                        self._note_mutation(
                            node, got[0], got[1],
                            f"'{base}.{node.func.attr}()'",
                        )
            self._add_call_edges(node, name)
        self.generic_visit(node)

    def _resolve_local(self, name: str) -> Optional[FunctionInfo]:
        """A bare name in this function's scope: a nested function of
        this (or an enclosing) function, else — unless a parameter or
        local variable shadows it — a module-level/imported function."""
        scope = self.fi.qualname
        while True:
            cand = f"{scope}.<locals>.{name}"
            if cand in self.mi.functions:
                return self.mi.functions[cand]
            owner = self.mi.functions.get(scope)
            if owner is None or owner.parent is None:
                break
            scope = owner.parent
        if self._shadowed(name):
            return None  # the call targets the local, not module scope
        return self.g.resolve_function(self.mi.name, name)

    def _add_call_edges(self, node: ast.Call, name: str) -> None:
        fi = self.fi
        callees: List[Tuple[str, str]] = []  # (key, via)
        if "." not in name:
            target = self._resolve_local(name)
            if target is not None:
                callees.append((target.key, "direct"))
        elif name.startswith("self.") and fi.cls is not None and \
                name.count(".") == 1:
            meth = name.split(".", 1)[1]
            cand = f"{fi.cls}.{meth}"
            if cand in self.mi.functions:
                callees.append(
                    (f"{self.mi.name}:{cand}", "direct")
                )
            else:
                callees.extend(
                    (k, "attr") for k in self._named_methods(meth)
                )
        else:
            # a local binding of the head name shadows any same-named
            # module/import symbol — only name-based fallback applies
            target = (
                None
                if self._shadowed(name)
                else self.g.resolve_function(self.mi.name, name)
            )
            if target is not None:
                callees.append((target.key, "direct"))
            else:
                meth = name.rsplit(".", 1)[1]
                callees.extend(
                    (k, "attr") for k in self._named_methods(meth)
                )
        for key, via in callees:
            self.g.edges.append(
                CallSite(
                    caller=fi.key,
                    callee=key,
                    path=fi.path,
                    line=node.lineno,
                    col=node.col_offset,
                    with_stack=tuple(self.with_stack),
                    in_loop=self.loop_depth > 0,
                    loop_vars=self._all_loop_vars(),
                    call=node,
                    via=via,
                )
            )

    def _named_methods(self, meth: str) -> List[str]:
        if meth in _COMMON_METHOD_NAMES:
            return []
        return self.g.methods.get(meth, [])

    def _check_thread_ctor(self, node: ast.Call) -> None:
        name = dotted(node.func)
        if name is None:
            return
        if name not in _THREAD_CTORS:
            # import threading as th; th.Thread(...) — expand the alias
            if self.g.expand_alias(self.mi.name, name) != "threading.Thread":
                return
        for kw in node.keywords:
            if kw.arg != "target":
                continue
            v = kw.value
            fi = self.fi
            targets: List[str] = []
            if isinstance(v, ast.Name):
                target = self._resolve_local(v.id)
                if target is not None:
                    targets.append(target.key)
            elif isinstance(v, ast.Attribute):
                meth = v.attr
                if (
                    isinstance(v.value, ast.Name)
                    and v.value.id == "self"
                    and fi.cls is not None
                    and f"{fi.cls}.{meth}" in self.mi.functions
                ):
                    targets.append(f"{self.mi.name}:{fi.cls}.{meth}")
                else:
                    # same common-name guard as call edges: a target
                    # named like a builtin container/queue method must
                    # not make every same-named project method a root
                    targets.extend(self._named_methods(meth))
            self.g.thread_roots.extend(targets)
            self.g.thread_creations.append(
                ThreadCreation(
                    func=fi.key,
                    path=fi.path,
                    line=node.lineno,
                    col=node.col_offset,
                    targets=tuple(targets),
                    raw=dotted(v) or type(v).__name__,
                )
            )

    # ---- sync sites (R2x taint seeds)

    def _check_sync(self, node: ast.Call) -> None:
        got = classify_sync(node)
        if got is not None:
            self.g.sync_sites.append(
                SyncSite(
                    func=self.fi.key,
                    path=self.fi.path,
                    line=node.lineno,
                    col=node.col_offset,
                    desc=got[1],
                )
            )


# --------------------------------------------------------------------------
# graph construction


def build_graph(
    trees: Dict[str, ast.Module],
    thread_root_config: Sequence[str] = (),
    jit_root_config: Sequence[str] = (),
) -> ProjectGraph:
    """Builds the whole-program graph from {relpath: parsed tree}.

    ``thread_root_config`` / ``jit_root_config``: extra roots from
    ``[tool.jaxlint]``, each "module.dotted:Qual.Name" or a bare
    "Qual.Name" (matched against every module)."""
    g = ProjectGraph()
    for relpath in sorted(trees):
        mi = index_module(relpath, trees[relpath])
        g.modules[mi.name] = mi
        for fi in mi.functions.values():
            g.functions[fi.key] = fi

    # Name-based method table and project-wide lock attrs.
    for mname in sorted(g.modules):
        mi = g.modules[mname]
        for qual in sorted(mi.functions):
            fi = mi.functions[qual]
            if fi.cls is not None:
                meth = qual.rsplit(".", 1)[1]
                g.methods.setdefault(meth, []).append(fi.key)
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                        and isinstance(node.value, ast.Call)
                        and is_lock_ctor(dotted(node.value.func), mi.imports)
                    ):
                        g.lock_attrs.add(t.attr)
    for meth in g.methods:
        g.methods[meth].sort()

    # Body scans (deterministic order).
    for mname in sorted(g.modules):
        mi = g.modules[mname]
        for qual in sorted(mi.functions):
            _BodyScan(g, mi, mi.functions[qual]).run()

    g.out_edges = {}
    for e in sorted(
        g.edges, key=lambda e: (e.caller, e.path, e.line, e.col, e.callee)
    ):
        g.out_edges.setdefault(e.caller, []).append(e)

    # Configured extra roots.  Bare specs match the qualname with or
    # without its ``<locals>`` segments ("run_with_deadline.work" pins
    # the nested ``run_with_deadline.<locals>.work``); module-qualified
    # specs get the same tolerance on their qualname half.
    def match_config_roots(specs: Sequence[str]) -> List[str]:
        out: List[str] = []
        for spec in specs:
            for key in sorted(g.functions):
                if spec_matches_function(spec, key):
                    out.append(key)
        return out

    g.thread_roots.extend(match_config_roots(thread_root_config))

    # Jit-boundary roots: decorated functions + module-scope jit aliases.
    for mname in sorted(g.modules):
        mi = g.modules[mname]
        for qual in sorted(mi.functions):
            if mi.functions[qual].jit_decorated:
                g.jit_roots.append(mi.functions[qual].key)
        for alias in sorted(mi.jit_aliases):
            target, _ = mi.jit_aliases[alias]
            g.jit_roots.append(f"{mname}:{target}")
    g.jit_roots.extend(match_config_roots(jit_root_config))

    # Lock-parameter fixpoint: a parameter is lock-typed when any call
    # site passes a known lock (module lock, lock attr, or another
    # function's lock param) in its position.
    g.lock_params = {k: set() for k in g.functions}
    for _round in range(8):
        changed = False
        for e in g.edges:
            if e.call is None or e.callee not in g.functions:
                continue
            callee = g.functions[e.callee]
            caller = g.functions.get(e.caller)
            cmod = caller.module if caller is not None else ""
            for pname, expr in bind_call_args(callee, e.call):
                if pname in g.lock_params[e.callee]:
                    continue
                if g._expr_is_lock(cmod, e.caller, expr):
                    g.lock_params[e.callee].add(pname)
                    changed = True
        if not changed:
            break

    g.thread_roots = sorted(set(g.thread_roots))
    g.jit_roots = sorted(set(g.jit_roots))
    return g
