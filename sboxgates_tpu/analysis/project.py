"""Whole-program jaxlint: the cross-module rules R1x / R2x / R4x.

The per-file pass (``rules.py``) is structurally blind to three hazard
classes the ROADMAP tracked as known false negatives, all of which need
a project view:

R4x  **lock aliasing + transitive thread reachability.**  A mutation of
     module-level mutable state is racy when any thread-entry root
     (``threading.Thread(target=...)``, the ``ChunkPrefetcher``
     producer, ``dispatch_with_retry`` workers, plus ``[tool.jaxlint]
     thread_roots`` extras) reaches the mutating function through the
     call graph with no dominating ``with <lock>`` on the path — where
     the lock may live in another module, be re-exported, or arrive as
     a parameter.  The canonical miss:
     ``ops/combinatorics._native_stream_available`` mutating
     ``_native_ok`` from the prefetch thread via
     ``_work -> _produce_one -> next_chunk``.
R1x  **cross-module static-arg tracking.**  Call sites of jitted
     functions imported from elsewhere (or wrapped by ``jax.jit`` at
     module scope) that pass an unhashable literal or a loop-varying
     expression as a *static* argument — every distinct value is a full
     recompile.
R2x  **interprocedural host-sync detection.**  A helper that calls
     ``block_until_ready`` / ``.item()`` / ``jax.device_get`` (etc.) is
     itself sync-tainted, transitively; calling a tainted helper inside
     a loop in a hot module stalls the dispatch pipeline exactly like
     the direct sync R2 already flags.  A sync carrying a valid
     ``# jaxlint: ignore[R2]``/``[R2x]`` suppression is acknowledged
     and does not taint its callers.

Every module is parsed exactly once: the per-file pass and the graph
share the :class:`~.rules.FileAnalysis` cache.  Findings are
deterministic (sorted traversal everywhere) and suppressible with the
existing ``# jaxlint: ignore[RULE] reason`` syntax; the
unused-suppression rule judges R1x/R2x/R4x markers only when this pass
actually ran.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .callgraph import ProjectGraph, bind_call_args, build_graph
from .config import JaxlintConfig
from .rules import (
    _UNHASHABLE_NODES,
    FileAnalysis,
    FileReport,
    Finding,
    analyze_file,
    dotted,
    finalize_report,
)

RawFinding = Tuple[str, int, int, str]  # (rule, line, col, message)


def _short_path(keys: List[str], limit: int = 8) -> str:
    """Human call-path 'a -> b -> c' from function keys, elided when long."""
    names = [k.split(":", 1)[1] for k in keys]
    if len(names) > limit:
        names = names[:3] + ["..."] + names[-(limit - 4):]
    return " -> ".join(names)


# --------------------------------------------------------------------------
# R4x — lock aliasing + transitive thread reachability


def run_r4x(
    graph: ProjectGraph,
    skip_sites: Set[Tuple[str, int]],
) -> Dict[str, List[RawFinding]]:
    """``skip_sites``: (path, line) pairs where the per-file R4 already
    fired (a direct thread-target mutation) — reported once, not twice."""
    out: Dict[str, List[RawFinding]] = {}
    reach = graph.unlocked_reachable()
    for m in sorted(
        graph.mutations,
        key=lambda m: (m.path, m.line, m.col, m.state_name),
    ):
        path_to = reach.get(m.func)
        if path_to is None:
            continue
        if (m.path, m.line) in skip_sites:
            continue
        fi = graph.functions[m.func]
        if graph.stack_holds_lock(fi.module, m.func, m.with_stack):
            continue
        root = path_to[0].split(":", 1)[1]
        via = _short_path(path_to)
        owner = (
            "its own module's state"
            if m.state_module == fi.module
            else f"state owned by '{m.state_module}'"
        )
        out.setdefault(m.path, []).append(
            (
                "R4x",
                m.line,
                m.col,
                f"module state {m.what} ({owner}) is mutated on an "
                f"unlocked path reachable from thread entry '{root}' "
                f"(via {via}) — guard the mutation with the owning "
                "module's Lock (imported/aliased/parameter locks count)",
            )
        )
    return out


# --------------------------------------------------------------------------
# R1x — cross-module static-arg tracking


def run_r1x(graph: ProjectGraph) -> Dict[str, List[RawFinding]]:
    out: Dict[str, List[RawFinding]] = {}
    seen: Set[Tuple[str, int, int, str]] = set()
    for e in sorted(
        graph.edges,
        key=lambda e: (e.path, e.line, e.col, e.caller, e.callee),
    ):
        if e.call is None or e.via != "direct":
            continue
        caller = graph.functions.get(e.caller)
        if caller is None:
            continue
        name = _call_name(e.call)
        if name is None:
            continue
        got = graph.jit_statics_for(caller.module, name)
        if got is None:
            continue
        callee, statics = got
        # The per-file R1 already checks bare-name calls of functions
        # jit-DECORATED in the same module; don't double-report those.
        if (
            callee.module == caller.module
            and callee.jit_decorated
            and isinstance(e.call.func, ast.Name)
        ):
            continue
        loop_vars = set(e.loop_vars)
        for pname, expr in bind_call_args(callee, e.call):
            if pname not in statics:
                continue
            where = f"jitted '{callee.qualname}' (from {callee.module})"
            if isinstance(expr, _UNHASHABLE_NODES):
                key = (e.path, expr.lineno, expr.col_offset, pname)
                if key in seen:
                    continue
                seen.add(key)
                out.setdefault(e.path, []).append(
                    (
                        "R1x",
                        expr.lineno,
                        expr.col_offset,
                        f"unhashable literal passed as static argument "
                        f"'{pname}' of {where}: jit static args must be "
                        "hashable, and every new value recompiles",
                    )
                )
            elif loop_vars and (_names_in(expr) & loop_vars):
                key = (e.path, expr.lineno, expr.col_offset, pname)
                if key in seen:
                    continue
                seen.add(key)
                out.setdefault(e.path, []).append(
                    (
                        "R1x",
                        expr.lineno,
                        expr.col_offset,
                        f"static argument '{pname}' of {where} varies "
                        "with the enclosing loop variable: every "
                        "iteration triggers a recompile — pass it traced "
                        "or hoist it",
                    )
                )
    return out


def _call_name(call: ast.Call) -> Optional[str]:
    return dotted(call.func)


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


# --------------------------------------------------------------------------
# R2x — interprocedural host-sync detection


def run_r2x(
    graph: ProjectGraph,
    hot_paths: Set[str],
    acknowledged: Set[Tuple[str, int]],
) -> Dict[str, List[RawFinding]]:
    """``hot_paths``: relpaths where the loop-call check applies (the
    same hot-module set R2 uses).  ``acknowledged``: sync sites carrying
    a valid R2/R2x suppression — they don't taint."""
    out: Dict[str, List[RawFinding]] = {}
    taint = graph.sync_taint(acknowledged)
    seen: Set[Tuple[str, int, int, str]] = set()
    for e in sorted(
        graph.edges,
        key=lambda e: (e.path, e.line, e.col, e.caller, e.callee),
    ):
        if not e.in_loop or e.path not in hot_paths:
            continue
        witness = taint.get(e.callee)
        if witness is None:
            continue
        callee = graph.functions.get(e.callee)
        if callee is None:
            continue
        if e.callee == e.caller:
            continue  # recursion: the direct sync is already R2's job
        # The direct sync inside THIS function at THIS line is R2's
        # territory; R2x is only about syncs hidden behind a call.
        if witness.func == e.caller and witness.line == e.line:
            continue
        key = (e.path, e.line, e.col, e.callee)
        if key in seen:
            continue
        seen.add(key)
        out.setdefault(e.path, []).append(
            (
                "R2x",
                e.line,
                e.col,
                f"call to '{callee.qualname}' inside a loop in a hot "
                f"module: it transitively performs a host-device sync "
                f"({witness.desc} at {witness.path}:{witness.line}) — "
                "every iteration stalls the dispatch pipeline; batch or "
                "hoist the sync, or suppress with a reason if the sync "
                "is the point",
            )
        )
    return out


# --------------------------------------------------------------------------
# whole-program driver


def _acknowledged_sync_sites(
    analyses: Sequence[FileAnalysis],
) -> Set[Tuple[str, int]]:
    """(path, line) pairs whose line carries a valid R2/R2x suppression
    (standalone markers cover the following line, as in finalize)."""
    ack: Set[Tuple[str, int]] = set()
    for fa in analyses:
        for s in fa.sups:
            if not (s.rules & {"R2", "R2x"}):
                continue
            ack.add((fa.path, s.line))
            if s.standalone:
                ack.add((fa.path, s.line + 1))
    return ack


def analyze_project(
    analyses: Sequence[FileAnalysis],
    config: JaxlintConfig,
) -> Tuple[List[FileReport], ProjectGraph]:
    """Runs the cross-module rules over pre-analyzed files and returns
    finalized per-file reports plus the resolved graph (for --graph)."""
    trees = {
        fa.path: fa.tree for fa in analyses if fa.tree is not None
    }
    graph = build_graph(
        trees,
        thread_root_config=config.thread_roots,
        jit_root_config=config.jit_roots,
    )

    extra: Dict[str, List[RawFinding]] = {}
    ran: Set[str] = set()
    if "R4x" in config.rules:
        ran.add("R4x")
        skip = {
            (fa.path, line)
            for fa in analyses
            for (rule, line, _c, _m) in fa.raw
            if rule == "R4"
        }
        for path, items in run_r4x(graph, skip).items():
            extra.setdefault(path, []).extend(items)
    if "R1x" in config.rules:
        ran.add("R1x")
        for path, items in run_r1x(graph).items():
            extra.setdefault(path, []).extend(items)
    if "R2x" in config.rules:
        ran.add("R2x")
        hot_paths = {fa.path for fa in analyses if fa.hot}
        ack = _acknowledged_sync_sites(analyses)
        for path, items in run_r2x(graph, hot_paths, ack).items():
            extra.setdefault(path, []).extend(items)
        # A deliberate sync can be acknowledged AT ITS SOURCE with an
        # R2x marker: the taint dies there for every caller.  Emit the
        # acknowledged source as a (suppressed) finding so the marker
        # counts as used instead of being reported stale, and so the
        # baseline documents the acknowledged sync inventory.
        sync_lines: Dict[Tuple[str, int], Tuple[int, str]] = {}
        for s in graph.sync_sites:
            key = (s.path, s.line)
            if key not in sync_lines or (s.col, s.desc) < sync_lines[key]:
                sync_lines[key] = (s.col, s.desc)
        for fa in analyses:
            for sup in fa.sups:
                if "R2x" not in sup.rules:
                    continue
                lines = [sup.line]
                if sup.standalone:
                    lines.append(sup.line + 1)
                for line in lines:
                    hit = sync_lines.get((fa.path, line))
                    if hit is not None:
                        extra.setdefault(fa.path, []).append(
                            (
                                "R2x",
                                line,
                                hit[0],
                                f"deliberate host sync at its source "
                                f"({hit[1]}): acknowledged — callers are "
                                "not sync-tainted by this site",
                            )
                        )
                        break

    # Contract-verification passes (R7/R8/R9): registry drift, bucket
    # discipline, lock ordering.  They share the same graph and the same
    # raw-finding/suppression plumbing as the other x-rules.
    if "R7" in config.rules:
        from .registries import run_r7

        ran.add("R7")
        for path, items in run_r7(graph, config).items():
            extra.setdefault(path, []).extend(items)
    if "R8" in config.rules:
        from .bucketflow import run_r8

        ran.add("R8")
        for path, items in run_r8(graph, config).items():
            extra.setdefault(path, []).extend(items)
    if "R9" in config.rules:
        from .lockorder import run_r9

        ran.add("R9")
        r9_findings, lock_order = run_r9(graph, config)
        for path, items in r9_findings.items():
            extra.setdefault(path, []).extend(items)
        # Stashed for --graph: the resolved lock-order graph rides along
        # with the call graph so the root-coverage gate can read it.
        graph.lock_order = lock_order

    # Protocol/determinism/durability shadows (R10/R11/R12): the static
    # mirrors of the replicated-degradation, bit-identical-resume, and
    # torn-write runtime contracts.
    if "R10" in config.rules:
        from .protocol import run_r10

        ran.add("R10")
        for path, items in run_r10(graph, config).items():
            extra.setdefault(path, []).extend(items)
    if "R11" in config.rules:
        from .determinism import nondet_sites, run_r11

        ran.add("R11")
        ack11: Set[Tuple[str, int]] = set()
        for fa in analyses:
            for s in fa.sups:
                if "R11" not in s.rules:
                    continue
                ack11.add((fa.path, s.line))
                if s.standalone:
                    ack11.add((fa.path, s.line + 1))
        for path, items in run_r11(graph, config, ack11).items():
            extra.setdefault(path, []).extend(items)
        # The R2x acknowledged-source contract, for determinism: a valid
        # R11 marker ON the nondet source kills the taint for every
        # caller, and the source is re-emitted as a suppressed finding
        # so the marker is never stale and the baseline documents the
        # acknowledged-nondeterminism inventory.
        src_lines = nondet_sites(graph, config)
        for fa in analyses:
            for sup in fa.sups:
                if "R11" not in sup.rules:
                    continue
                lines = [sup.line]
                if sup.standalone:
                    lines.append(sup.line + 1)
                for line in lines:
                    hit = src_lines.get((fa.path, line))
                    if hit is not None:
                        extra.setdefault(fa.path, []).append(
                            (
                                "R11",
                                line,
                                hit[0],
                                f"deliberate nondeterminism at its "
                                f"source ({hit[1]}): acknowledged — "
                                "sinks are not tainted by this site",
                            )
                        )
                        break
    if "R12" in config.rules:
        from .durability import run_r12

        ran.add("R12")
        for path, items in run_r12(graph, config).items():
            extra.setdefault(path, []).extend(items)

    # Trust-boundary shadows (R13/R14/R15): the static mirrors of the
    # network tier's auth-before-effect, journal-before-202, and
    # drain-safe-teardown runtime contracts.
    if "R13" in config.rules:
        from .trustflow import run_r13, untrusted_sites

        ran.add("R13")
        ack13: Set[Tuple[str, int]] = set()
        for fa in analyses:
            for s in fa.sups:
                if "R13" not in s.rules:
                    continue
                ack13.add((fa.path, s.line))
                if s.standalone:
                    ack13.add((fa.path, s.line + 1))
        for path, items in run_r13(graph, config, ack13).items():
            extra.setdefault(path, []).extend(items)
        # The R2x/R11 acknowledged-source contract, for request taint:
        # a valid R13 marker ON the untrusted source kills the taint
        # for every consumer, and the source is re-emitted as a
        # suppressed finding so the marker is never stale and the
        # baseline documents the acknowledged-input inventory.
        src13 = untrusted_sites(graph, config)
        for fa in analyses:
            for sup in fa.sups:
                if "R13" not in sup.rules:
                    continue
                lines = [sup.line]
                if sup.standalone:
                    lines.append(sup.line + 1)
                for line in lines:
                    hit = src13.get((fa.path, line))
                    if hit is not None:
                        extra.setdefault(fa.path, []).append(
                            (
                                "R13",
                                line,
                                hit[0],
                                f"deliberate untrusted input at its "
                                f"source ({hit[1]}): acknowledged — "
                                "sinks are not tainted by this site",
                            )
                        )
                        break
    if "R14" in config.rules:
        from .ordering import run_r14

        ran.add("R14")
        for path, items in run_r14(graph, config).items():
            extra.setdefault(path, []).extend(items)
    if "R15" in config.rules:
        from .lifecycle import run_r15

        ran.add("R15")
        for path, items in run_r15(graph, config).items():
            extra.setdefault(path, []).extend(items)

    reports: List[FileReport] = []
    for fa in analyses:
        # Every x-rule that ran is judged for stale markers — including
        # R2x in non-hot files: loop-call findings can't land there, but
        # acknowledged-source entries are emitted wherever a sync site
        # carries a marker, so an R2x marker with no finding under it is
        # genuinely stale (the acknowledged sync is gone) and the
        # inline-ignore inventory must not accrete.
        reports.append(
            finalize_report(fa, extra.get(fa.path, ()), set(ran))
        )
    # Findings about unscanned paths (the pyproject config itself, e.g.
    # a stale thread_roots pin) get a bare report — no inline
    # suppressions to match there.
    covered = {fa.path for fa in analyses}
    for path in sorted(set(extra) - covered):
        rep = FileReport(path=path)
        for rule, line, col, msg in sorted(
            extra[path], key=lambda f: (f[1], f[2], f[0])
        ):
            rep.findings.append(Finding(path, line, col, rule, msg))
        reports.append(rep)
    return reports, graph


def lint_project(
    paths: Optional[List[str]] = None,
    config: Optional[JaxlintConfig] = None,
    return_graph: bool = False,
):
    """Whole-program lint of ``paths`` (default: config paths): per-file
    rules + R1x/R2x/R4x, one parse per module."""
    from .cli import iter_python_files
    from .config import load_config

    if config is None:
        config = load_config(paths[0] if paths else ".")
    scan = paths or config.paths
    analyses: List[FileAnalysis] = []
    for ap, rel in iter_python_files(config.root, scan, config):
        with open(ap, "r", encoding="utf-8") as f:
            source = f.read()
        analyses.append(analyze_file(source, rel, config))
    reports, graph = analyze_project(analyses, config)
    if return_graph:
        return reports, graph
    return reports


def graph_json(
    paths: Optional[List[str]] = None,
    config: Optional[JaxlintConfig] = None,
) -> dict:
    """The resolved call graph + roots as a deterministic JSON dict
    (the ``--graph`` CLI output).  When R9 ran, the lock-order graph
    rides along with per-root transitive acquisitions, so the gate can
    assert every pinned thread root is covered."""
    _reports, graph = lint_project(paths, config, return_graph=True)
    data = graph.as_json()
    lock_order = getattr(graph, "lock_order", None)
    if lock_order is not None:
        lo = lock_order.as_json()
        lo["root_acquires"] = {
            root: sorted(lock_order.trans_acquires.get(root, ()))
            for root in data["thread_roots"]
        }
        data["lock_order"] = lo
    return data
