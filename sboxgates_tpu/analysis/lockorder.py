"""R9 — lock-acquisition-order and held-across-dispatch analysis.

Eight kinds of background threads (prefetcher, warmer, heartbeat,
deadline workers, abort watchers, mux/restart/fleet workers) share
locks with no ordering discipline beyond convention.  This pass builds
a **lock-acquisition-order graph** over the whole program: an edge
``A -> B`` means some path acquires ``B`` while holding ``A`` — either
lexically (``with A: ... with B:``) or through the call graph (a
function called under ``A`` transitively acquires ``B``).  Two
findings come out of it:

* **order cycles** — ``A -> B`` on one path and ``B -> A`` on another
  is the classic two-thread deadlock; the finding carries the witness
  cycle with each hop's acquisition site;
* **lock held across a blocking dispatch** — a ``with <lock>:`` body
  that reaches a ``guarded_dispatch``/``dispatch_with_retry``/verdict
  resolve (``[tool.jaxlint] blocking_calls``) blocks the lock for the
  whole deadline window, and the abandonment/degradation path that
  must then run CANNOT need that lock; holding one across the resolve
  deadlocks exactly when the resilience machinery is the thing trying
  to save the run.

Lock identities: module-level locks are ``module.name``; instance
locks are class-qualified (``module:Class.attr``) when acquired via
``self``, and attr-qualified (``*.attr``) otherwise.  Locks passed as
parameters have unknowable identity and stay out of the order graph
(they still suppress R4x).  Everything iterates sorted, so the graph
JSON and the findings are deterministic.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import ProjectGraph, FunctionInfo
from .config import JaxlintConfig
from .rules import dotted

RawFinding = Tuple[str, int, int, str]


@dataclass(frozen=True)
class OrderEdge:
    """``to`` acquired (or transitively acquirable) while ``frm`` held."""

    frm: str
    to: str
    path: str
    line: int
    col: int
    note: str  # "with" | "call <callee qualname>"


class LockOrderResult:
    def __init__(self) -> None:
        self.edges: List[OrderEdge] = []
        self.acquires: Dict[str, Set[str]] = {}  # fn key -> direct locks
        self.trans_acquires: Dict[str, Set[str]] = {}
        self.blocking_funcs: Set[str] = set()
        self.cycles: List[List[str]] = []
        self.findings: Dict[str, List[RawFinding]] = {}

    def as_json(self) -> dict:
        """Deterministic lock-order graph for ``--graph`` output and the
        root-coverage gate."""
        nodes = sorted(
            {e.frm for e in self.edges}
            | {e.to for e in self.edges}
            | {l for s in self.acquires.values() for l in s}
        )
        return {
            "locks": nodes,
            "edges": [
                {
                    "from": e.frm,
                    "to": e.to,
                    "path": e.path,
                    "line": e.line,
                    "note": e.note,
                }
                for e in sorted(
                    self.edges,
                    key=lambda e: (e.frm, e.to, e.path, e.line, e.col),
                )
            ],
            "cycles": self.cycles,
        }


def _lock_id(graph: ProjectGraph, fi: FunctionInfo,
             expr: ast.AST) -> Optional[str]:
    """Canonical lock identity of a with-item expression, or None when
    it is not a known lock (or a parameter lock of unknowable identity)."""
    name = dotted(expr)
    if name is None:
        return None
    if isinstance(expr, ast.Attribute):
        if expr.attr in graph.lock_attrs:
            if (
                isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and fi.cls is not None
            ):
                return f"{fi.module}:{fi.cls}.{expr.attr}"
            return f"*.{expr.attr}"
    if "." not in name and name in graph.lock_params.get(fi.key, ()):
        return None  # parameter lock: identity unknown at this site
    got = graph.resolve(fi.module, name)
    if got is not None:
        mod, sym = got
        mi = graph.modules.get(mod)
        if mi is not None and mi.assigns.get(sym) == "lock":
            return f"{mod}.{sym}"
    return None


class _LockWalk:
    """One function's body walk: direct acquisitions, nested-with order
    edges, and direct blocking calls under a held lock."""

    def __init__(self, graph: ProjectGraph, fi: FunctionInfo,
                 blocking: Set[str], result: LockOrderResult) -> None:
        self.g = graph
        self.fi = fi
        self.blocking = blocking
        self.res = result
        self.held: List[str] = []
        self.direct_blocks: List[Tuple[str, int, int, str]] = []
        #: the body names a blocking call at all (held or not) — seeds
        #: the transitive blocking_funcs fixpoint, so a lock-free
        #: wrapper around guarded_dispatch still taints its callers
        self.names_blocking = False

    def run(self) -> None:
        acq: Set[str] = set()
        self.res.acquires[self.fi.key] = acq
        self._walk_body(self.fi.node, acq)

    def _walk_body(self, node: ast.AST, acq: Set[str]) -> None:
        for child in ast.iter_child_nodes(node):
            self._walk(child, acq)

    def _walk(self, node: ast.AST, acq: Set[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # nested defs are their own graph nodes
        if isinstance(node, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in node.items:
                lid = _lock_id(self.g, self.fi, item.context_expr)
                if lid is None:
                    continue
                acq.add(lid)
                for h in self.held:
                    if h != lid:
                        self.res.edges.append(
                            OrderEdge(
                                frm=h,
                                to=lid,
                                path=self.fi.path,
                                line=item.context_expr.lineno,
                                col=item.context_expr.col_offset,
                                note="with",
                            )
                        )
                self.held.append(lid)
                pushed += 1
            for child in node.body:
                self._walk(child, acq)
            del self.held[len(self.held) - pushed:]
            for item in node.items:
                self._walk(item.context_expr, acq)
            return
        if isinstance(node, ast.Call):
            tail = (dotted(node.func) or "").rsplit(".", 1)[-1]
            if tail in self.blocking:
                self.names_blocking = True
                if self.held:
                    self.direct_blocks.append(
                        (self.held[-1], node.lineno, node.col_offset, tail)
                    )
        self._walk_body(node, acq)


def _find_cycles(edges: List[OrderEdge]) -> List[List[str]]:
    """Deterministic minimal cycles in the order graph: for each node in
    sorted order, the BFS-shortest path back to itself; canonicalized
    (rotated to the smallest member) and deduplicated."""
    adj: Dict[str, Set[str]] = {}
    for e in edges:
        adj.setdefault(e.frm, set()).add(e.to)
    cycles: List[List[str]] = []
    seen: Set[Tuple[str, ...]] = set()
    for start in sorted(adj):
        # BFS from start's successors back to start.
        prev: Dict[str, Optional[str]] = {}
        frontier = sorted(adj.get(start, ()))
        for n in frontier:
            prev.setdefault(n, None)
        found = None
        while frontier and found is None:
            nxt: List[str] = []
            for n in frontier:
                if n == start:
                    found = n
                    break
                for m in sorted(adj.get(n, ())):
                    if m not in prev:
                        prev[m] = n
                        nxt.append(m)
            frontier = nxt
        if found is None:
            continue
        # Reconstruct start -> ... -> start.
        path = [start]
        n: Optional[str] = prev.get(start)
        while n is not None:
            path.append(n)
            n = prev.get(n)
        path.reverse()  # [first successor, ..., start] -> chronological
        cycle = [start] + path[:-1] if len(path) > 1 else [start]
        lo = min(range(len(cycle)), key=lambda i: cycle[i])
        canon = tuple(cycle[lo:] + cycle[:lo])
        if canon in seen:
            continue
        seen.add(canon)
        cycles.append(list(canon))
    return cycles


def run_r9(
    graph: ProjectGraph,
    config: JaxlintConfig,
) -> Tuple[Dict[str, List[RawFinding]], LockOrderResult]:
    res = LockOrderResult()
    blocking_names = set(config.blocking_calls)
    walks: Dict[str, _LockWalk] = {}
    for fkey in sorted(graph.functions):
        w = _LockWalk(graph, graph.functions[fkey], blocking_names, res)
        w.run()
        walks[fkey] = w

    # Transitive acquisitions (call-graph fixpoint).
    res.trans_acquires = {
        k: set(v) for k, v in res.acquires.items()
    }
    changed = True
    while changed:
        changed = False
        for fkey in sorted(graph.functions):
            mine = res.trans_acquires[fkey]
            before = len(mine)
            for e in graph.out_edges.get(fkey, ()):
                mine |= res.trans_acquires.get(e.callee, set())
            if len(mine) != before:
                changed = True

    # Transitively-blocking functions: the function's own name matches,
    # or its body names a blocking call, or it calls a blocking function.
    for fkey in graph.functions:
        qual_tail = fkey.rsplit(".", 1)[-1].split(":")[-1]
        if qual_tail in blocking_names or walks[fkey].names_blocking:
            res.blocking_funcs.add(fkey)
    changed = True
    while changed:
        changed = False
        for fkey in sorted(graph.functions):
            if fkey in res.blocking_funcs:
                continue
            for e in graph.out_edges.get(fkey, ()):
                if e.callee in res.blocking_funcs:
                    res.blocking_funcs.add(fkey)
                    changed = True
                    break

    # Interprocedural order edges + held-across-dispatch findings.
    held_seen: Set[Tuple[str, int, str]] = set()
    for e in sorted(
        graph.edges, key=lambda e: (e.path, e.line, e.col, e.callee)
    ):
        caller = graph.functions.get(e.caller)
        if caller is None or e.callee not in graph.functions:
            continue
        held = [
            lid
            for lid in (
                _lock_id(graph, caller, x) for x in e.with_stack
            )
            if lid is not None
        ]
        if not held:
            continue
        callee = graph.functions[e.callee]
        for t in sorted(res.trans_acquires.get(e.callee, ())):
            for h in held:
                if h != t:
                    res.edges.append(
                        OrderEdge(
                            frm=h,
                            to=t,
                            path=e.path,
                            line=e.line,
                            col=e.col,
                            note=f"call {callee.qualname}",
                        )
                    )
        if e.callee in res.blocking_funcs:
            key = (e.path, e.line, held[-1])
            if key not in held_seen:
                held_seen.add(key)
                res.findings.setdefault(e.path, []).append(
                    (
                        "R9",
                        e.line,
                        e.col,
                        f"lock '{held[-1]}' is held across a blocking "
                        f"dispatch/resolve (via '{callee.qualname}') — "
                        "the deadline window blocks the lock, and the "
                        "abandonment/degradation path deadlocks if it "
                        "needs it; release before dispatching or "
                        "acknowledge with ignore[R9] and a reason",
                    )
                )
    # Direct blocking-call sites (the callee may be unresolvable —
    # e.g. ctx.guarded_dispatch on an opaque context object).
    for fkey in sorted(walks):
        w = walks[fkey]
        for lock, line, col, tail in w.direct_blocks:
            key = (w.fi.path, line, lock)
            if key in held_seen:
                continue
            held_seen.add(key)
            res.findings.setdefault(w.fi.path, []).append(
                (
                    "R9",
                    line,
                    col,
                    f"lock '{lock}' is held across the blocking call "
                    f"'{tail}' — the deadline window blocks the lock, "
                    "and the abandonment/degradation path deadlocks if "
                    "it needs it; release before dispatching or "
                    "acknowledge with ignore[R9] and a reason",
                )
            )

    # Cycles, each reported once at its first witness edge.
    res.cycles = _find_cycles(res.edges)
    by_pair: Dict[Tuple[str, str], OrderEdge] = {}
    for e in sorted(
        res.edges, key=lambda e: (e.path, e.line, e.col, e.frm, e.to)
    ):
        by_pair.setdefault((e.frm, e.to), e)
    for cycle in res.cycles:
        hops = list(zip(cycle, cycle[1:] + cycle[:1]))
        witnesses = [by_pair[h] for h in hops if h in by_pair]
        if not witnesses:
            continue
        site = min(witnesses, key=lambda e: (e.path, e.line, e.col))
        arrows = " -> ".join(cycle + [cycle[0]])
        detail = "; ".join(
            f"{b} acquired at {by_pair[(a, b)].path}:"
            f"{by_pair[(a, b)].line} while holding {a}"
            for a, b in hops
            if (a, b) in by_pair
        )
        res.findings.setdefault(site.path, []).append(
            (
                "R9",
                site.line,
                site.col,
                f"lock acquisition-order cycle: {arrows} ({detail}) — "
                "two threads interleaving these paths deadlock; impose "
                "one global acquisition order",
            )
        )
    return res.findings, res
