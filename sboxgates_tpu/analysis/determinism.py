"""R11: determinism taint (whole-program pass).

Bit-identical resume (PRs 3/13/14) and cross-process content-addressed
store keys (PR 15) both reduce to one invariant: every byte that lands
in the journal, a checkpoint, a canonical key, or a PRNG seed must be a
pure function of the run inputs.  A wall-clock read, an unseeded RNG,
``os.urandom``, a uuid, an unsorted directory scan, iteration over a
``set``, or ``id()`` anywhere upstream of those sinks silently breaks
the contract — the chaos matrices only catch it when a kill lands on
the exact divergent byte.

This pass reuses the R2x shape: nondeterministic *sources* seed a
per-function assignment-taint fixpoint (the R8 derivation machinery)
plus an interprocedural "returns a nondeterministic value" fixpoint
over the call graph; findings fire where a tainted expression is passed
to a *bit-identity sink* (``[tool.jaxlint] deterministic_sinks``).

Acknowledged sources follow the R2x on-source marker contract: a valid
``# jaxlint: ignore[R11] reason`` on the source line kills the taint
for every caller, and the source is re-emitted as a suppressed
"acknowledged" finding so the baseline documents the inventory and the
marker is never judged stale.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import ProjectGraph, iter_body_nodes
from .config import JaxlintConfig
from .rules import dotted

RawFinding = Tuple[str, int, int, str]

_TIME_CALLS = frozenset(
    {
        "time.time",
        "time.time_ns",
        "time.monotonic",
        "time.monotonic_ns",
        "time.perf_counter",
        "time.perf_counter_ns",
        "datetime.now",
        "datetime.datetime.now",
        "datetime.utcnow",
        "datetime.datetime.utcnow",
    }
)

#: RNG constructors that are deterministic WITH an explicit seed arg and
#: nondeterministic without one.
_SEEDABLE_CTORS = frozenset({"default_rng", "SeedSequence", "Random"})

_DIR_SCAN_TAILS = frozenset(
    {"listdir", "scandir", "glob", "iglob", "iterdir"}
)

_UUID_TAILS = frozenset({"uuid1", "uuid3", "uuid4", "uuid5"})


def _tail(name: Optional[str]) -> Optional[str]:
    return name.rsplit(".", 1)[-1] if name else None


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    return (
        isinstance(node, ast.Call) and dotted(node.func) == "set"
    )


def _classify_source(node: ast.Call,
                     sorted_wrapped: Set[int]) -> Optional[str]:
    """Human description if this call is a nondeterminism source."""
    name = dotted(node.func)
    if name is None:
        return None
    parts = name.split(".")
    tail = parts[-1]
    if name in _TIME_CALLS:
        return f"wall clock {name}()"
    if name == "os.urandom":
        return "os.urandom entropy"
    if name == "id":
        return "id() (address-dependent)"
    if parts[0] == "uuid" or tail in _UUID_TAILS:
        return f"uuid {name}()"
    if tail in _SEEDABLE_CTORS:
        if not node.args and not node.keywords:
            return f"unseeded {tail}()"
        return None
    if "random" in parts[:-1] or parts[0] == "random" and len(parts) > 1:
        return f"unseeded RNG {name}()"
    if parts[0] == "secrets":
        return f"secrets {name}()"
    if tail in _DIR_SCAN_TAILS:
        if id(node) not in sorted_wrapped:
            return f"unsorted directory scan {tail}()"
        return None
    if tail in ("list", "tuple") and node.args:
        if _is_set_expr(node.args[0]):
            return f"{tail}() over an unordered set"
    return None


def _sink_name(node: ast.Call, sinks: List[str]) -> Optional[str]:
    """The matching ``deterministic_sinks`` entry, if this call is a
    sink.  A dotted entry ("journal.append") requires the call tail to
    match its last component and the preceding component to appear in
    the receiver chain (``self.journal.append`` matches); a bare entry
    matches the call-name tail."""
    name = dotted(node.func)
    if name is None:
        return None
    parts = name.split(".")
    tail = parts[-1]
    for entry in sinks:
        if "." in entry:
            ehead, _, etail = entry.rpartition(".")
            if tail == etail and ehead in parts[:-1]:
                return entry
        elif tail == entry:
            return entry
    return None


class _FuncDet:
    """Per-function R11 state, built once; taint is recomputed cheaply
    on each interprocedural fixpoint round."""

    def __init__(self, graph: ProjectGraph, fkey: str,
                 config: JaxlintConfig,
                 acknowledged: Set[Tuple[str, int]]) -> None:
        fi = graph.functions[fkey]
        self.fi = fi
        self.calls = graph.call_index(fkey)
        self.assigns: List[Tuple[Set[str], ast.AST]] = []
        self.call_nodes: List[ast.Call] = []
        self.returns: List[ast.AST] = []
        self.set_loops: List[ast.For] = []
        sorted_wrapped: Set[int] = set()
        for node in iter_body_nodes(fi.node):
            if isinstance(node, ast.Call):
                self.call_nodes.append(node)
                if dotted(node.func) == "sorted":
                    for arg in node.args:
                        sorted_wrapped.add(id(arg))
            elif isinstance(node, ast.Assign):
                names = {
                    t.id for t in node.targets if isinstance(t, ast.Name)
                }
                if names:
                    self.assigns.append((names, node.value))
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                if isinstance(node.target, ast.Name):
                    self.assigns.append(({node.target.id}, node.value))
            elif isinstance(node, ast.NamedExpr):
                if isinstance(node.target, ast.Name):
                    self.assigns.append(({node.target.id}, node.value))
            elif isinstance(node, ast.Return) and node.value is not None:
                self.returns.append(node.value)
            elif isinstance(node, ast.For):
                if _is_set_expr(node.iter):
                    self.set_loops.append(node)
        #: id(call node) -> source description (acknowledged excluded)
        self.sources: Dict[int, str] = {}
        #: every source site, acknowledged or not: (line, col, desc)
        self.all_sites: List[Tuple[int, int, str]] = []
        for node in self.call_nodes:
            desc = _classify_source(node, sorted_wrapped)
            if desc is None:
                continue
            self.all_sites.append(
                (node.lineno, node.col_offset, desc)
            )
            if (fi.path, node.lineno) not in acknowledged:
                self.sources[id(node)] = desc
        self.tainted: Dict[str, str] = {}
        self.nondet_return: Optional[str] = None

    def _expr_taint(self, expr: ast.AST,
                    nondet_fns: Dict[str, str]) -> Optional[str]:
        """Witness description if this expression mentions a nondet
        source, a tainted local, or a call into a nondet function."""
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                desc = self.sources.get(id(node))
                if desc is not None:
                    return desc
                for callee in self.calls.get(
                    (node.lineno, node.col_offset), ()
                ):
                    w = nondet_fns.get(callee)
                    if w is not None:
                        return w
            elif isinstance(node, ast.Name):
                w = self.tainted.get(node.id)
                if w is not None:
                    return w
        return None

    def recompute(self, nondet_fns: Dict[str, str]) -> bool:
        """Refresh local taint + the nondet-return flag; True if the
        nondet-return status changed (drives the global fixpoint)."""
        self.tainted = {}
        for loop in self.set_loops:
            for t in ast.walk(loop.target):
                if isinstance(t, ast.Name):
                    self.tainted[t.id] = "iteration over an unordered set"
        changed = True
        while changed:
            changed = False
            for names, value in self.assigns:
                if names <= set(self.tainted):
                    continue
                w = self._expr_taint(value, nondet_fns)
                if w is not None:
                    for n in names:
                        self.tainted.setdefault(n, w)
                    changed = True
        ret: Optional[str] = None
        for value in self.returns:
            ret = self._expr_taint(value, nondet_fns)
            if ret is not None:
                break
        flipped = (ret is None) != (self.nondet_return is None)
        self.nondet_return = ret
        return flipped

    def sink_findings(self, config: JaxlintConfig,
                      nondet_fns: Dict[str, str]) -> List[RawFinding]:
        out: List[RawFinding] = []
        for node in self.call_nodes:
            sink = _sink_name(node, config.deterministic_sinks)
            if sink is None:
                continue
            args = list(node.args) + [kw.value for kw in node.keywords]
            for arg in args:
                w = self._expr_taint(arg, nondet_fns)
                if w is not None:
                    out.append(
                        (
                            "R11",
                            node.lineno,
                            node.col_offset,
                            f"nondeterministic value ({w}) flows into "
                            f"bit-identity sink {sink} — breaks "
                            "bit-identical resume / cross-process key "
                            "agreement; make the input deterministic or "
                            "acknowledge the SOURCE with ignore[R11] "
                            "and a reason",
                        )
                    )
                    break
        return out


def nondet_sites(graph: ProjectGraph, config: JaxlintConfig
                 ) -> Dict[Tuple[str, int], Tuple[int, str]]:
    """(path, line) -> (col, desc) for every nondeterminism source in
    the project, acknowledged or not — project.py uses this to emit the
    suppressed "acknowledged source" inventory entries (R2x contract)."""
    sites: Dict[Tuple[str, int], Tuple[int, str]] = {}
    for fkey in sorted(graph.functions):
        det = _FuncDet(graph, fkey, config, acknowledged=set())
        for line, col, desc in det.all_sites:
            key = (det.fi.path, line)
            if key not in sites or (col, desc) < sites[key]:
                sites[key] = (col, desc)
    return sites


def run_r11(graph: ProjectGraph, config: JaxlintConfig,
            acknowledged: Set[Tuple[str, int]]
            ) -> Dict[str, List[RawFinding]]:
    """R11 findings per project-relative path.

    ``acknowledged``: (path, line) pairs carrying a valid R11 marker —
    those sources taint nobody."""
    scans: Dict[str, _FuncDet] = {
        fkey: _FuncDet(graph, fkey, config, acknowledged)
        for fkey in sorted(graph.functions)
    }
    #: function key -> witness description for nondet-returning functions
    nondet_fns: Dict[str, str] = {}
    for _ in range(12):  # bounded interprocedural fixpoint
        changed = False
        for fkey in sorted(scans):
            det = scans[fkey]
            if det.recompute(nondet_fns):
                changed = True
            if det.nondet_return is not None:
                if nondet_fns.get(fkey) != det.nondet_return:
                    nondet_fns[fkey] = det.nondet_return
                    changed = True
            elif fkey in nondet_fns:
                del nondet_fns[fkey]
                changed = True
        if not changed:
            break

    out: Dict[str, List[RawFinding]] = {}
    for fkey in sorted(scans):
        det = scans[fkey]
        found = det.sink_findings(config, nondet_fns)
        if found:
            out.setdefault(det.fi.path, []).extend(found)
    return out
