"""jaxlint — JAX-aware static analysis for this codebase.

``python -m sboxgates_tpu.analysis [paths...]`` scans for the failure
modes that silently erase streaming-search throughput: recompilation
hazards (R1), host-device syncs inside hot loops (R2), tracer escapes
(R3), lock-discipline violations in thread targets (R4), and swallowed
exceptions (R5).  Configuration lives in ``[tool.jaxlint]`` in
pyproject.toml; suppressions are inline
``# jaxlint: ignore[RULE] reason`` comments (reason mandatory).

The runtime complements — :func:`sboxgates_tpu.utils.guards.recompile_guard`
and :func:`sboxgates_tpu.utils.guards.sync_guard` — catch what a static
pass cannot see; the tier-1 gate (tests/test_jaxlint.py) holds the tree
at zero unsuppressed findings.
"""

from .config import ALL_RULES, JaxlintConfig, load_config
from .rules import RULE_DOCS, FileReport, Finding, lint_source
from .cli import iter_python_files, lint_paths, main

__all__ = [
    "ALL_RULES",
    "JaxlintConfig",
    "load_config",
    "RULE_DOCS",
    "FileReport",
    "Finding",
    "lint_source",
    "iter_python_files",
    "lint_paths",
    "main",
]
