"""jaxlint — JAX-aware static analysis for this codebase.

``python -m sboxgates_tpu.analysis [paths...]`` scans for the failure
modes that silently erase streaming-search throughput: recompilation
hazards (R1), host-device syncs inside hot loops (R2), tracer escapes
(R3), lock-discipline violations in thread targets (R4), and swallowed
exceptions (R5).  The whole-program pass (``whole_program`` in
``[tool.jaxlint]``, or ``--whole-program``) parses every module once,
resolves imports into a project symbol table, builds a call graph with
thread-entry and jit-boundary roots, and runs the cross-module rules:
R4x (lock aliasing + transitive thread reachability), R1x (static-arg
tracking across modules), and R2x (interprocedural host-sync
detection); ``--graph`` dumps the resolved graph as JSON.
Configuration lives in ``[tool.jaxlint]`` in pyproject.toml;
suppressions are inline ``# jaxlint: ignore[RULE] reason`` comments
(reason mandatory).

The runtime complements — :func:`sboxgates_tpu.utils.guards.recompile_guard`
and :func:`sboxgates_tpu.utils.guards.sync_guard` — catch what a static
pass cannot see; the tier-1 gate (tests/test_jaxlint.py) holds the tree
at zero unsuppressed findings.
"""

from .config import ALL_RULES, CROSS_RULES, FILE_RULES, JaxlintConfig, load_config
from .rules import RULE_DOCS, FileReport, Finding, lint_source
from .cli import iter_python_files, lint_paths, main
from .project import graph_json, lint_project

__all__ = [
    "ALL_RULES",
    "CROSS_RULES",
    "FILE_RULES",
    "JaxlintConfig",
    "load_config",
    "RULE_DOCS",
    "FileReport",
    "Finding",
    "lint_source",
    "iter_python_files",
    "lint_paths",
    "main",
    "graph_json",
    "lint_project",
]
