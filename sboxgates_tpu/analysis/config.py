"""`[tool.jaxlint]` configuration.

One source of truth for the CLI and the tier-1 gate test: both load the
``[tool.jaxlint]`` table from the project's ``pyproject.toml``.  Python
3.10 has no ``tomllib``, so a minimal TOML-subset reader (string lists,
strings, booleans — exactly what the table uses) backs it up; when
``tomllib`` is importable it is preferred.
"""

from __future__ import annotations

import fnmatch
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional

#: Per-file rules.
FILE_RULES = ("R1", "R2", "R3", "R4", "R5", "R6")
#: Cross-module rules (whole-program pass only).  R7/R8/R9 are the
#: contract-verification passes: registry drift, bucket discipline,
#: lock ordering.  R10/R11/R12 are the protocol/determinism/durability
#: shadows: replicated-protocol divergence, determinism taint, and
#: durable-write discipline.  R13/R14/R15 are the network-tier
#: trust-boundary shadows: untrusted-input taint, admission-order
#: dominance, and resource lifecycle.
CROSS_RULES = (
    "R1x", "R2x", "R4x", "R7", "R8", "R9",
    "R10", "R11", "R12", "R13", "R14", "R15",
)
ALL_RULES = FILE_RULES + CROSS_RULES

#: Defaults mirror the committed pyproject table so API callers that never
#: touch a pyproject (unit tests on fixture snippets) see the same rules.
DEFAULT_HOT_MODULES = (
    "sboxgates_tpu/ops/*",
    "sboxgates_tpu/search/lut.py",
    "sboxgates_tpu/parallel/mesh.py",
)

#: Modules whose functions dispatch registered kernels: R7's
#: registry-bypass check and R8's bucket-discipline pass apply here.
DEFAULT_DISPATCH_MODULES = (
    "sboxgates_tpu/search/*",
    "sboxgates_tpu/ops/*",
)

#: Names whose presence in (or derivation into) a shape expression marks
#: it bucket-disciplined (R8).  Any name containing "bucket" counts too.
DEFAULT_BUCKET_SOURCES = (
    "bucket_size",
    "PIVOT_G_BUCKETS",
    "FLEET_BUCKETS",
    "STACKED_BUCKETS",
    "FLEET_LADDER",
)

#: Call names that block on a device resolve or a cross-rank agreement
#: (R9: a lock held across one deadlocks against the abandonment path).
DEFAULT_BLOCKING_CALLS = (
    "guarded_dispatch",
    "dispatch_with_retry",
    "replicated_dispatch_with_retry",
    "breach_verdict",
    "sync_verdict",
    "host_sync_deadline",
)

#: Call names whose RESULT differs per process (R10): a branch testing
#: one of these — or a local derived from one — is rank-gated control
#: flow.  Names that agree on every rank (``process_count``) are NOT
#: rank sources: branching on them is replicated, not divergent.
DEFAULT_RANK_SOURCES = (
    "process_index",
    "process_rank",
    "is_primary",
    "is_coordinator",
)

#: Agreement / collective entry points (R10): every process must reach
#: these in lockstep, so a call path gated on one side of a rank branch
#: hangs or splits the pod.  Device collectives are included — a
#: rank-gated collective is the launch-count bug class directly.
DEFAULT_AGREEMENT_SITES = (
    "breach_verdict",
    "journal_seq_check",
    "run_config_check",
    "_kv_exchange",
    "wait_at_barrier",
    "replicated_dispatch_with_retry",
    "sync_verdict",
    "process_allgather",
    "broadcast_one_to_all",
    "all_gather",
    "psum",
    "pmean",
    "pmax",
    "pmin",
)

#: Bit-identity sinks (R11): calls whose inputs must be reproducible
#: byte-for-byte across runs and processes.  A dotted entry like
#: "journal.append" matches attribute chains ending in ``append`` whose
#: receiver mentions ``journal``; a bare entry matches the call tail.
DEFAULT_DETERMINISTIC_SINKS = (
    "journal.append",
    "durable_write_text",
    "with_digest",
    "canonicalize",
    "exact_key",
    "exact_multi_key",
    "default_rng",
    "SeedSequence",
    "PRNGKey",
)

#: Persistence modules (R12): every truncating write / os.replace here
#: must route through the shared durable helper or carry a reason.
DEFAULT_DURABLE_MODULES = (
    "sboxgates_tpu/resilience/*",
    "sboxgates_tpu/store/*",
    "sboxgates_tpu/telemetry/*",
)

#: Functions exempt from R12 — the durable helper itself (its fdopen +
#: os.replace ARE the tmp+fsync+atomic-replace discipline).
DEFAULT_DURABLE_HELPERS = ("durable_write_text",)

#: Modules that parse network requests (R13 sources seed here; R14
#: dominance applies to these functions' bodies).
DEFAULT_HANDLER_MODULES = ("sboxgates_tpu/serve_net/*",)

#: Calls whose RESULT is request-derived (R13 sources).  Dotted entries
#: match like deterministic_sinks: "headers.get" matches
#: ``h.headers.get(...)``; a bare entry matches the call tail.
DEFAULT_UNTRUSTED_SOURCES = (
    "headers.get",
    "rfile.read",
    "urlsplit",
    "parse_qs",
    "recv",
)

#: Calls whose RESULT is trusted even when their inputs are tainted
#: (R13): schema validators, int/range coercion, canonical-key and
#: digest derivation, and the token-file-backed authenticator.
DEFAULT_SANITIZERS = (
    "int",
    "float",
    "len",
    "parse_sbox",
    "authenticate",
    "canonicalize",
    "exact_key",
    "exact_multi_key",
    "blake2b",
    "sha256",
    "hexdigest",
)

#: Sensitive sinks (R13): filesystem path construction, journal/store
#: record fields, the fault-scope tenant tag, and process spawns.
#: Dotted entries match like deterministic_sinks.
DEFAULT_TRUST_SINKS = (
    "path.join",
    "open",
    "journal.admit",
    "journal.append",
    "set_tenant",
    "subprocess.run",
    "subprocess.Popen",
    "subprocess.call",
    "os.system",
    "os.remove",
    "os.rename",
)

#: Authentication / rate-limit call sites (R14): every effectful call
#: in a handler body must be dominated by one.
DEFAULT_AUTH_SITES = ("authenticate", "allow")

#: Quota check sites (R14): fresh-admission effects must also be
#: dominated by one of these.
DEFAULT_QUOTA_SITES = ("active_jobs",)

#: Fsync'd admission-journal appends (R14): every 202-class response
#: write must be dominated by one.
DEFAULT_JOURNAL_SITES = ("journal.admit", "journal.append")

#: Effectful calls in handler bodies (R14): orchestrator enqueue/join
#: and durable admission records.
DEFAULT_EFFECT_SITES = ("orch.submit", "orch.join", "journal.admit")

#: Response-writing helpers (R14): a call with a constant 201/202
#: status argument is an admission acknowledgement.
DEFAULT_RESPONSE_SITES = ("_send_json", "send_response")

#: Resource constructors (R15): sockets, listeners, threads, temp
#: files.  A project class whose base's name tail matches one of these
#: counts too (``class Server(ThreadingHTTPServer)``).
DEFAULT_RESOURCE_CTORS = (
    "socket.socket",
    "create_connection",
    "ThreadingHTTPServer",
    "HTTPServer",
    "TCPServer",
    "Thread",
    "Timer",
    "mkstemp",
    "NamedTemporaryFile",
    "TemporaryFile",
)

#: Teardown registries (R15): handing a resource (or a closure over
#: one) to these counts as a release on all paths.
DEFAULT_TEARDOWN_REGISTRIES = (
    "drain_hooks",
    "_teardown",
    "atexit.register",
)


@dataclass
class JaxlintConfig:
    """Resolved analyzer configuration.

    ``hot_modules``: glob patterns (posix, relative to the project root)
    naming the modules where R2/R2x (host-device sync inside a loop)
    apply.  ``rules``: enabled rule IDs (per-file R1–R5 and cross-module
    R1x/R2x/R4x).  ``exclude``: glob patterns skipped when scanning
    directories.  ``paths``: default scan roots when the CLI is invoked
    without positional paths.  ``whole_program``: run the cross-module
    pass (call graph + R1x/R2x/R4x) by default.  ``thread_roots`` /
    ``jit_roots``: per-rule root extras for the call graph — function
    names ("Class.meth", "fn", or "pkg.mod:Class.meth") treated as
    thread entries (R4x) / jit boundaries beyond the auto-detected
    ``threading.Thread(target=...)`` and ``jax.jit`` sites.
    """

    hot_modules: List[str] = field(default_factory=lambda: list(DEFAULT_HOT_MODULES))
    rules: List[str] = field(default_factory=lambda: list(ALL_RULES))
    exclude: List[str] = field(default_factory=list)
    paths: List[str] = field(default_factory=lambda: ["sboxgates_tpu"])
    root: str = "."
    whole_program: bool = False
    thread_roots: List[str] = field(default_factory=list)
    jit_roots: List[str] = field(default_factory=list)
    dispatch_modules: List[str] = field(
        default_factory=lambda: list(DEFAULT_DISPATCH_MODULES)
    )
    bucket_sources: List[str] = field(
        default_factory=lambda: list(DEFAULT_BUCKET_SOURCES)
    )
    blocking_calls: List[str] = field(
        default_factory=lambda: list(DEFAULT_BLOCKING_CALLS)
    )
    rank_sources: List[str] = field(
        default_factory=lambda: list(DEFAULT_RANK_SOURCES)
    )
    agreement_sites: List[str] = field(
        default_factory=lambda: list(DEFAULT_AGREEMENT_SITES)
    )
    deterministic_sinks: List[str] = field(
        default_factory=lambda: list(DEFAULT_DETERMINISTIC_SINKS)
    )
    durable_modules: List[str] = field(
        default_factory=lambda: list(DEFAULT_DURABLE_MODULES)
    )
    durable_helpers: List[str] = field(
        default_factory=lambda: list(DEFAULT_DURABLE_HELPERS)
    )
    #: "site: reason" strings waiving chaos coverage for declared fault
    #: sites that cannot be exercised by an armed test.
    chaos_waivers: List[str] = field(default_factory=list)
    handler_modules: List[str] = field(
        default_factory=lambda: list(DEFAULT_HANDLER_MODULES)
    )
    untrusted_sources: List[str] = field(
        default_factory=lambda: list(DEFAULT_UNTRUSTED_SOURCES)
    )
    sanitizers: List[str] = field(
        default_factory=lambda: list(DEFAULT_SANITIZERS)
    )
    trust_sinks: List[str] = field(
        default_factory=lambda: list(DEFAULT_TRUST_SINKS)
    )
    auth_sites: List[str] = field(
        default_factory=lambda: list(DEFAULT_AUTH_SITES)
    )
    quota_sites: List[str] = field(
        default_factory=lambda: list(DEFAULT_QUOTA_SITES)
    )
    journal_sites: List[str] = field(
        default_factory=lambda: list(DEFAULT_JOURNAL_SITES)
    )
    effect_sites: List[str] = field(
        default_factory=lambda: list(DEFAULT_EFFECT_SITES)
    )
    response_sites: List[str] = field(
        default_factory=lambda: list(DEFAULT_RESPONSE_SITES)
    )
    resource_ctors: List[str] = field(
        default_factory=lambda: list(DEFAULT_RESOURCE_CTORS)
    )
    teardown_registries: List[str] = field(
        default_factory=lambda: list(DEFAULT_TEARDOWN_REGISTRIES)
    )

    def is_hot(self, relpath: str) -> bool:
        rp = relpath.replace(os.sep, "/")
        return any(fnmatch.fnmatch(rp, pat) for pat in self.hot_modules)

    def is_dispatch(self, relpath: str) -> bool:
        rp = relpath.replace(os.sep, "/")
        return any(fnmatch.fnmatch(rp, pat) for pat in self.dispatch_modules)

    def is_excluded(self, relpath: str) -> bool:
        rp = relpath.replace(os.sep, "/")
        return any(fnmatch.fnmatch(rp, pat) for pat in self.exclude)

    def is_durable(self, relpath: str) -> bool:
        rp = relpath.replace(os.sep, "/")
        return any(fnmatch.fnmatch(rp, pat) for pat in self.durable_modules)

    def is_handler(self, relpath: str) -> bool:
        rp = relpath.replace(os.sep, "/")
        return any(fnmatch.fnmatch(rp, pat) for pat in self.handler_modules)


_STR = r'"((?:[^"\\]|\\.)*)"'


def _parse_value(text: str):
    text = text.strip()
    if text.startswith("["):
        return re.findall(_STR, text)
    m = re.fullmatch(_STR, text)
    if m:
        return m.group(1)
    if text in ("true", "false"):
        return text == "true"
    try:
        return int(text)
    except ValueError:
        return text


def _read_table_fallback(text: str, table: str) -> Dict[str, object]:
    """Line-oriented TOML-subset reader for one ``[table]``.

    Handles ``key = "str"``, ``key = ["a", "b", ...]`` (possibly spanning
    lines), booleans, and integers; comments outside strings are dropped.
    """
    out: Dict[str, object] = {}
    in_table = False
    pending_key: Optional[str] = None
    pending_val = ""
    for raw in text.splitlines():
        line = raw
        # strip comments (a '#' not inside a quoted string)
        quoted = False
        for i, ch in enumerate(line):
            if ch == '"' and (i == 0 or line[i - 1] != "\\"):
                quoted = not quoted
            elif ch == "#" and not quoted:
                line = line[:i]
                break
        stripped = line.strip()
        if not stripped:
            continue
        if stripped.startswith("["):
            if pending_key is None:
                in_table = stripped == f"[{table}]"
                continue
            # else: '[' continues a multiline list value below
        if not in_table:
            continue
        if pending_key is not None:
            pending_val += " " + stripped
            if pending_val.count("[") <= pending_val.count("]"):
                out[pending_key] = _parse_value(pending_val)
                pending_key, pending_val = None, ""
            continue
        if "=" in stripped:
            key, _, val = stripped.partition("=")
            key, val = key.strip(), val.strip()
            if val.startswith("[") and val.count("[") > val.count("]"):
                pending_key, pending_val = key, val
            else:
                out[key] = _parse_value(val)
    return out


def _read_table(text: str, table: str) -> Dict[str, object]:
    try:
        import tomllib  # Python >= 3.11

        data = tomllib.loads(text)
        for part in table.split("."):
            data = data.get(part, {})
        return dict(data)
    except ImportError:
        return _read_table_fallback(text, table)


def find_pyproject(start: str) -> Optional[str]:
    """Nearest pyproject.toml at or above ``start``."""
    d = os.path.abspath(start)
    if os.path.isfile(d):
        d = os.path.dirname(d)
    while True:
        cand = os.path.join(d, "pyproject.toml")
        if os.path.isfile(cand):
            return cand
        parent = os.path.dirname(d)
        if parent == d:
            return None
        d = parent


def load_config(start: str = ".") -> JaxlintConfig:
    """Config from the nearest pyproject.toml's ``[tool.jaxlint]`` table
    (defaults when absent).  ``root`` is the directory holding the
    pyproject, so hot-module globs resolve against the project root no
    matter where the CLI is invoked from."""
    cfg = JaxlintConfig()
    pyproject = find_pyproject(start)
    if pyproject is None:
        cfg.root = os.path.abspath(start)
        return cfg
    cfg.root = os.path.dirname(pyproject)
    with open(pyproject, "r", encoding="utf-8") as f:
        table = _read_table(f.read(), "tool.jaxlint")
    for key in (
        "hot_modules", "rules", "exclude", "paths",
        "thread_roots", "jit_roots",
        "dispatch_modules", "bucket_sources", "blocking_calls",
        "rank_sources", "agreement_sites", "deterministic_sinks",
        "durable_modules", "durable_helpers", "chaos_waivers",
        "handler_modules", "untrusted_sources", "sanitizers",
        "trust_sinks", "auth_sites", "quota_sites", "journal_sites",
        "effect_sites", "response_sites", "resource_ctors",
        "teardown_registries",
    ):
        val = table.get(key)
        if isinstance(val, list) and all(isinstance(x, str) for x in val):
            setattr(cfg, key, list(val))
    if isinstance(table.get("whole_program"), bool):
        cfg.whole_program = table["whole_program"]
    bad = [r for r in cfg.rules if r not in ALL_RULES]
    if bad:
        raise ValueError(
            f"[tool.jaxlint] unknown rule ids {bad}; known: {list(ALL_RULES)}"
        )
    return cfg
