"""R7 — registry-drift analysis (whole-program contract verification).

The engine runs on implicit cross-layer contracts anchored in five
declared registries:

* ``KERNELS`` / ``FLEET_SHARED`` (search/warmup.py) — every jitted sweep
  entry point must dispatch through the registry (``kernel_call`` /
  ``stream_dispatch``), or warm coverage silently drifts;
* ``METRICS`` (telemetry/metrics.py) — every counter/histogram name must
  be declared and typed, or the registry-parity guarantees fork;
* ``KNOWN_SITES`` (resilience/faults.py) — every ``fault_point`` site
  must be documented, or ``SBG_FAULTS`` specs silently arm nothing;
* ``JOURNAL_CONFIG_KEYS`` / ``JOURNAL_KEY_DEFAULTS`` (cli.py) — every
  draw-stream-shaping Options field must be journaled, or a
  ``--resume-run`` replays a different stream;
* ``[tool.jaxlint] thread_roots`` — every ``threading.Thread`` entry
  must be pinned, or the R4x/R9 concurrency gates lapse when a spawn
  site is refactored.

The runtime parity tests check these per-test and only on exercised
paths; this pass proves them on ALL paths at lint time, in both
directions: a use site that bypasses/escapes its registry is a finding,
and a declared entry with no reachable use site is a finding too (dead
declarations let registries accrete as code is refactored away).

Registries are extracted structurally by their declared NAME wherever
they live, so fixture packs can declare miniature registries of their
own.  Everything iterates sorted; output is deterministic.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import (
    ProjectGraph,
    iter_body_nodes as _body_nodes,
    spec_matches_function,
    strip_locals,
)
from .config import JaxlintConfig
from .rules import _is_stats_base, _jit_call_of, dotted

RawFinding = Tuple[str, int, int, str]  # (rule, line, col, message)

#: Facade methods whose first argument names a metric.
_METRIC_METHODS = frozenset({"inc", "observe", "put", "ensure"})
#: Free-function increment helpers: ``bump(stats, name)`` and the
#: deadline module's ``_bump`` wrapper — the metric name is argument 1.
_BUMP_NAMES = frozenset({"bump", "_bump"})


@dataclass
class Declared:
    """One extracted registry: entry -> (declaring relpath, line)."""

    entries: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    module: Optional[str] = None  # declaring relpath; None = not found

    def add(self, name: str, path: str, line: int) -> None:
        self.entries.setdefault(name, (path, line))


@dataclass
class Registries:
    """Every declared registry plus the use-site inventories the drift
    checks compare against."""

    kernels: Declared = field(default_factory=Declared)
    fleet_shared: Declared = field(default_factory=Declared)
    metrics: Declared = field(default_factory=Declared)
    fault_sites: Declared = field(default_factory=Declared)
    journal_keys: Declared = field(default_factory=Declared)
    journal_defaults: Declared = field(default_factory=Declared)
    #: argparse destinations declared in the journal-keys module
    argparse_dests: Set[str] = field(default_factory=set)
    #: (module name, class name) owning a private MetricsRegistry
    #: (``declared=None``) — their ``self.stats`` names are off-schema
    private_stats_classes: Set[Tuple[str, str]] = field(default_factory=set)
    #: string constant (and f-string prefix base) -> (relpath, line)
    #: sites using it
    str_uses: Dict[str, Set[Tuple[str, int]]] = field(default_factory=dict)


# --------------------------------------------------------------------------
# extraction


def _const_str_elements(node: ast.AST) -> List[Tuple[str, int]]:
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return [
            (el.value, el.lineno)
            for el in node.elts
            if isinstance(el, ast.Constant) and isinstance(el.value, str)
        ]
    return []


def _const_dict_keys(node: ast.AST) -> List[Tuple[str, int]]:
    if isinstance(node, ast.Dict):
        return [
            (k.value, k.lineno)
            for k in node.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)
        ]
    return []


def _kerneldef_names(node: ast.AST) -> List[Tuple[str, int]]:
    """``KernelDef("name", ...)`` first-argument strings anywhere under
    the KERNELS assignment value (the registry is a dict comprehension
    over a tuple of KernelDefs)."""
    out: List[Tuple[str, int]] = []
    for n in ast.walk(node):
        if (
            isinstance(n, ast.Call)
            and (dotted(n.func) or "").rsplit(".", 1)[-1] == "KernelDef"
            and n.args
            and isinstance(n.args[0], ast.Constant)
            and isinstance(n.args[0].value, str)
        ):
            out.append((n.args[0].value, n.args[0].lineno))
    return out


def _argparse_dests(tree: ast.Module) -> Set[str]:
    """Destinations of every ``add_argument`` call in the module: the
    explicit ``dest=``, the first long option, a bare positional name,
    or the short option letter."""
    dests: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if not (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "add_argument"
        ):
            continue
        options = [
            a.value
            for a in node.args
            if isinstance(a, ast.Constant) and isinstance(a.value, str)
        ]
        explicit = None
        for kw in node.keywords:
            if kw.arg == "dest" and isinstance(kw.value, ast.Constant):
                explicit = kw.value.value
        if explicit:
            dests.add(explicit)
            continue
        longs = [o for o in options if o.startswith("--")]
        bare = [o for o in options if not o.startswith("-")]
        shorts = [o for o in options if o.startswith("-") and o not in longs]
        if longs:
            dests.add(longs[0].lstrip("-").replace("-", "_"))
        elif bare:
            dests.add(bare[0])
        elif shorts:
            dests.add(shorts[0].lstrip("-"))
    return dests


def _private_stats_classes(mname: str, tree: ast.Module,
                           out: Set[Tuple[str, str]]) -> None:
    """Classes assigning ``self.stats = MetricsRegistry(..., declared=None)``
    anywhere in a method: their counters are a private schema by design."""
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            if not (
                isinstance(node.value, ast.Call)
                and (dotted(node.value.func) or "").rsplit(".", 1)[-1]
                == "MetricsRegistry"
            ):
                continue
            declared_none = any(
                kw.arg == "declared"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is None
                for kw in node.value.keywords
            )
            if not declared_none:
                continue
            for t in node.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and t.attr == "stats"
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    out.add((mname, cls.name))


def _collect_str_uses(relpath: str, tree: ast.Module,
                      uses: Dict[str, Set[Tuple[str, int]]]) -> None:
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            uses.setdefault(node.value, set()).add((relpath, node.lineno))
            if "[" in node.value:
                uses.setdefault(
                    node.value.split("[", 1)[0], set()
                ).add((relpath, node.lineno))
        elif isinstance(node, ast.JoinedStr) and node.values:
            first = node.values[0]
            if isinstance(first, ast.Constant) and isinstance(
                first.value, str
            ):
                uses.setdefault(
                    first.value.split("[", 1)[0], set()
                ).add((relpath, node.lineno))


def extract_registries(graph: ProjectGraph) -> Registries:
    reg = Registries()
    slots = {
        "KERNELS": reg.kernels,
        "FLEET_SHARED": reg.fleet_shared,
        "METRICS": reg.metrics,
        "KNOWN_SITES": reg.fault_sites,
        "JOURNAL_CONFIG_KEYS": reg.journal_keys,
        "JOURNAL_KEY_DEFAULTS": reg.journal_defaults,
    }
    for mname in sorted(graph.modules):
        mi = graph.modules[mname]
        _collect_str_uses(mi.path, mi.tree, reg.str_uses)
        _private_stats_classes(mname, mi.tree, reg.private_stats_classes)
        for node in mi.tree.body:
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            value = node.value
            if value is None:
                continue
            for t in targets:
                if not isinstance(t, ast.Name) or t.id not in slots:
                    continue
                decl = slots[t.id]
                if decl.module is not None:  # first declaration wins
                    continue
                if t.id == "KERNELS":
                    found = _kerneldef_names(value) or _const_dict_keys(value)
                else:
                    found = (
                        _const_dict_keys(value)
                        or _const_str_elements(value)
                    )
                if not found:
                    continue
                decl.module = mi.path
                for name, line in found:
                    decl.add(name, mi.path, line)
        if reg.journal_keys.module == mi.path:
            reg.argparse_dests = _argparse_dests(mi.tree)
    return reg


# --------------------------------------------------------------------------
# use-site scans


def _metric_literal(expr: ast.AST) -> Optional[str]:
    """The statically-known base metric name of a name expression:
    ``"warm_hits"`` -> warm_hits, ``f"device_wait_s[{p}]"`` ->
    device_wait_s; None when the base itself is dynamic."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value.split("[", 1)[0]
    if isinstance(expr, ast.JoinedStr) and expr.values:
        first = expr.values[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            if "[" in first.value:
                return first.value.split("[", 1)[0]
    return None


def _receiver_mentions_global(expr: ast.AST) -> bool:
    name = dotted(expr)
    return name is not None and "GLOBAL" in name.split(".")


def _telemetry_exempt(relpath: str) -> bool:
    return "telemetry" in relpath.replace("\\", "/").split("/")


class _R7Scan:
    """One pass over every function body, collecting the use-site
    findings (bypass jit, undeclared metric/fault names, journal-key
    escapes)."""

    def __init__(self, graph: ProjectGraph, reg: Registries,
                 config: JaxlintConfig) -> None:
        self.g = graph
        self.reg = reg
        self.config = config
        self.out: Dict[str, List[RawFinding]] = {}

    def _flag(self, path: str, line: int, col: int, msg: str) -> None:
        self.out.setdefault(path, []).append(("R7", line, col, msg))

    def run(self) -> Dict[str, List[RawFinding]]:
        for fkey in sorted(self.g.functions):
            fi = self.g.functions[fkey]
            check_jit = (
                self.reg.kernels.module is not None
                and self.config.is_dispatch(fi.path)
                and fi.path != self.reg.kernels.module
            )
            check_journal = (
                self.reg.journal_keys.module is not None
                and fi.path == self.reg.journal_keys.module
            )
            for node in _body_nodes(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                if check_jit and _jit_call_of(node) is not None:
                    self._flag(
                        fi.path, node.lineno, node.col_offset,
                        "jit wrapper created at a dispatch site outside "
                        "the kernel registry "
                        f"({self.reg.kernels.module}): route the sweep "
                        "through kernel_call/stream_dispatch so warm "
                        "coverage and the compile-cache ladder hold on "
                        "this path too",
                    )
                self._check_metric(fi, node)
                self._check_fault(fi, node)
                if check_journal:
                    self._check_journal(fi, node)
        return self.out

    # -- metrics ----------------------------------------------------------

    def _check_metric(self, fi, call: ast.Call) -> None:
        if self.reg.metrics.module is None or _telemetry_exempt(fi.path):
            return
        name_expr: Optional[ast.AST] = None
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr in _METRIC_METHODS:
            recv = f.value
            if not _is_stats_base(recv):
                return
            if _receiver_mentions_global(recv):
                return  # GLOBAL is a declared=None registry by design
            if (
                isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id == "self"
                and (fi.module, fi.cls) in self.reg.private_stats_classes
            ):
                return  # the class's own private (declared=None) schema
            if call.args:
                name_expr = call.args[0]
        elif (dotted(f) or "").rsplit(".", 1)[-1] in _BUMP_NAMES:
            if len(call.args) >= 2:
                name_expr = call.args[1]
        if name_expr is None:
            return
        lit = _metric_literal(name_expr)
        if lit is None or lit in self.reg.metrics.entries:
            return
        self._flag(
            fi.path, name_expr.lineno, name_expr.col_offset,
            f"metric '{lit}' is not declared in METRICS "
            f"({self.reg.metrics.module}) — every counter/histogram "
            "must be declared and typed, or the schema silently forks "
            "(declare it, or route a private tally through a "
            "declared=None registry)",
        )

    # -- fault sites ------------------------------------------------------

    def _check_fault(self, fi, call: ast.Call) -> None:
        if self.reg.fault_sites.module is None:
            return
        if (dotted(call.func) or "").rsplit(".", 1)[-1] != "fault_point":
            return
        if not call.args:
            return
        a0 = call.args[0]
        if not (isinstance(a0, ast.Constant) and isinstance(a0.value, str)):
            return
        if a0.value in self.reg.fault_sites.entries:
            return
        self._flag(
            fi.path, call.lineno, call.col_offset,
            f"fault site '{a0.value}' is not declared in KNOWN_SITES "
            f"({self.reg.fault_sites.module}) — undocumented sites make "
            "SBG_FAULTS specs unguessable; add it to the declared set",
        )

    # -- journal keys -----------------------------------------------------

    def _check_journal(self, fi, call: ast.Call) -> None:
        if (dotted(call.func) or "").rsplit(".", 1)[-1] != "Options":
            return
        exprs = list(call.args) + [kw.value for kw in call.keywords]
        for expr in exprs:
            for n in ast.walk(expr):
                if (
                    isinstance(n, ast.Attribute)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "args"
                    and n.attr not in self.reg.journal_keys.entries
                ):
                    self._flag(
                        fi.path, n.lineno, n.col_offset,
                        f"Options field built from args.{n.attr} is "
                        "consumed by the journaled driver but "
                        f"'{n.attr}' is not in JOURNAL_CONFIG_KEYS — a "
                        "--resume-run would silently drop it; add the "
                        "key, or acknowledge with ignore[R7] if it "
                        "cannot shape the deterministic draw stream",
                    )


# --------------------------------------------------------------------------
# declaration-side checks (dead entries, drift between registries, pins)


def _dead_declarations(reg: Registries) -> Dict[str, List[RawFinding]]:
    out: Dict[str, List[RawFinding]] = {}

    def flag(path: str, line: int, msg: str) -> None:
        out.setdefault(path, []).append(("R7", line, 0, msg))

    def dead_scan(decl: Declared, what: str, hint: str) -> None:
        if decl.module is None:
            return
        for name in sorted(decl.entries):
            path, line = decl.entries[name]
            # Only the declaration site itself is excluded — a use
            # elsewhere in the declaring module still counts.
            used_in = reg.str_uses.get(name, set()) - {(path, line)}
            if not used_in:
                flag(
                    path, line,
                    f"{what} '{name}' has no reachable use site outside "
                    f"its declaration — dead declaration; {hint}",
                )

    dead_scan(
        reg.kernels, "kernel registry entry",
        "shrink the registry (or wire the kernel into a dispatcher)",
    )
    dead_scan(
        reg.metrics, "declared metric",
        "remove the declaration (nothing increments or observes it)",
    )
    dead_scan(
        reg.fault_sites, "declared fault site",
        "remove it from KNOWN_SITES (no fault_point names it)",
    )

    # FLEET_SHARED must stay a subset of the kernel registry.
    if (
        reg.fleet_shared.module is not None
        and reg.kernels.module is not None
    ):
        for name in sorted(reg.fleet_shared.entries):
            if name not in reg.kernels.entries:
                path, line = reg.fleet_shared.entries[name]
                flag(
                    path, line,
                    f"FLEET_SHARED declares shared operand axes for "
                    f"'{name}', which is not in the kernel registry — "
                    "the fleet warm specs enumerated from it would "
                    "build a kernel that cannot dispatch",
                )

    # Journal keys must be recordable (argparse destinations), and the
    # late-added defaults must name journaled keys.
    if reg.journal_keys.module is not None and reg.argparse_dests:
        for name in sorted(reg.journal_keys.entries):
            if name not in reg.argparse_dests:
                path, line = reg.journal_keys.entries[name]
                flag(
                    path, line,
                    f"JOURNAL_CONFIG_KEYS entry '{name}' matches no "
                    "argparse destination — recording "
                    "getattr(args, ...) would raise at run start; "
                    "remove or fix the key",
                )
    if reg.journal_defaults.module is not None:
        for name in sorted(reg.journal_defaults.entries):
            if (
                reg.journal_keys.module is not None
                and name not in reg.journal_keys.entries
            ):
                path, line = reg.journal_defaults.entries[name]
                flag(
                    path, line,
                    f"JOURNAL_KEY_DEFAULTS entry '{name}' is not in "
                    "JOURNAL_CONFIG_KEYS — the default would never be "
                    "applied on resume",
                )
    return out


#: Synthetic path for findings about the config itself (dead thread-root
#: pins live in pyproject.toml, which is not a scanned python file).
CONFIG_PATH = "pyproject.toml"


def _thread_pins(graph: ProjectGraph,
                 config: JaxlintConfig) -> Dict[str, List[RawFinding]]:
    out: Dict[str, List[RawFinding]] = {}
    specs = list(config.thread_roots)
    for tc in sorted(
        graph.thread_creations, key=lambda t: (t.path, t.line, t.col)
    ):
        if not tc.targets:
            out.setdefault(tc.path, []).append(
                (
                    "R7", tc.line, tc.col,
                    f"cannot statically resolve Thread target "
                    f"'{tc.raw}' — pin the entry function in "
                    "[tool.jaxlint] thread_roots so the R4x/R9 "
                    "concurrency gates cover it",
                )
            )
            continue
        pinned = any(
            spec_matches_function(spec, t)
            for spec in specs
            for t in tc.targets
        )
        if not pinned:
            qual = strip_locals(tc.targets[0].split(":", 1)[1])
            out.setdefault(tc.path, []).append(
                (
                    "R7", tc.line, tc.col,
                    f"thread entry '{qual}' is not pinned in "
                    "[tool.jaxlint] thread_roots — auto-detection "
                    "covers it today, but an unpinned root silently "
                    "drops out of the R4x/R9 gates when this spawn "
                    "site is refactored; pin it",
                )
            )
    # Stale pins: a spec naming no function is a refactored-away root.
    for spec in sorted(set(specs)):
        if not any(
            spec_matches_function(spec, key) for key in graph.functions
        ):
            out.setdefault(CONFIG_PATH, []).append(
                (
                    "R7", 1, 0,
                    f"[tool.jaxlint] thread_roots spec '{spec}' matches "
                    "no function in the scanned tree — stale pin; fix "
                    "the spec or remove it",
                )
            )
    return out


# --------------------------------------------------------------------------
# driver


def run_r7(
    graph: ProjectGraph,
    config: JaxlintConfig,
    registries: Optional[Registries] = None,
) -> Dict[str, List[RawFinding]]:
    """The full registry-drift pass: use-site escapes, dead
    declarations, and thread-root pinning.  Findings for
    :data:`CONFIG_PATH` describe the pyproject config itself."""
    reg = registries if registries is not None else extract_registries(graph)
    out = _R7Scan(graph, reg, config).run()
    for src in (_dead_declarations(reg), _thread_pins(graph, config)):
        for path, items in src.items():
            out.setdefault(path, []).extend(items)
    return out
