"""R13: untrusted-input taint (whole-program pass).

The network front door (PR 18) takes attacker-controlled bytes —
headers, body fields, query params, path segments — and the admission
pipeline turns them into filesystem paths, journal record fields, the
fault-scope tenant tag, and orchestrator state.  The runtime tests
pin a handful of those flows; this pass pins ALL of them: every value
produced by an ``untrusted_sources`` call in a ``handler_modules``
function is tainted, taint propagates through the assignment fixpoint,
through function returns, and through call arguments into callee
parameters, and a finding fires where a still-tainted expression
reaches a ``trust_sinks`` call — unless the value passed through a
declared ``sanitizers`` call (schema validators, int/range coercion,
canonical-key/digest derivation) on the way.

Sinks fire in ANY module: the taint originates at the network
boundary, but the dangerous join/open/record-write often lives in a
helper two modules away — that is exactly the flow an intraprocedural
linter cannot see.

Acknowledged sources follow the R2x/R11 on-source marker contract: a
valid ``# jaxlint: ignore[R13] reason`` on the source line kills the
taint for every consumer, and the source is re-emitted as a suppressed
"acknowledged" finding so the baseline documents the inventory and the
marker is never judged stale.  A marker on the SINK line suppresses
that one finding only (plain inline-suppression semantics).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import ProjectGraph, bind_call_args, iter_body_nodes
from .config import JaxlintConfig
from .rules import dotted

RawFinding = Tuple[str, int, int, str]


def site_name(node: ast.Call, entries: List[str]) -> Optional[str]:
    """The matching config entry if this call names a declared site.
    A dotted entry ("journal.admit") requires the call tail to match
    its last component and the preceding component to appear in the
    receiver chain (``self.journal.admit`` matches); a bare entry
    matches the call-name tail.  Shared by R13/R14 (same semantics as
    R11's deterministic_sinks matcher)."""
    name = dotted(node.func)
    if name is None:
        return None
    parts = name.split(".")
    tail = parts[-1]
    for entry in entries:
        if "." in entry:
            ehead, _, etail = entry.rpartition(".")
            if tail == etail and ehead in parts[:-1]:
                return entry
        elif tail == entry:
            return entry
    return None


class _FuncTrust:
    """Per-function R13 state, built once; taint is recomputed cheaply
    on each interprocedural fixpoint round."""

    def __init__(self, graph: ProjectGraph, fkey: str,
                 config: JaxlintConfig,
                 acknowledged: Set[Tuple[str, int]]) -> None:
        fi = graph.functions[fkey]
        self.fi = fi
        self.graph = graph
        self.config = config
        self.calls = graph.call_index(fkey)
        self.in_handler = config.is_handler(fi.path)
        self.assigns: List[Tuple[Set[str], ast.AST]] = []
        self.call_nodes: List[ast.Call] = []
        self.returns: List[ast.AST] = []
        for node in iter_body_nodes(fi.node):
            if isinstance(node, ast.Call):
                self.call_nodes.append(node)
            elif isinstance(node, ast.Assign):
                names: Set[str] = set()
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            names.add(n.id)
                if names:
                    self.assigns.append((names, node.value))
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                if isinstance(node.target, ast.Name):
                    self.assigns.append(({node.target.id}, node.value))
            elif isinstance(node, ast.NamedExpr):
                if isinstance(node.target, ast.Name):
                    self.assigns.append(({node.target.id}, node.value))
            elif isinstance(node, ast.Return) and node.value is not None:
                self.returns.append(node.value)
        #: id(call node) -> source description (acknowledged excluded;
        #: sources only seed in handler modules).
        self.sources: Dict[int, str] = {}
        #: every source site, acknowledged or not: (line, col, desc)
        self.all_sites: List[Tuple[int, int, str]] = []
        if self.in_handler:
            for node in self.call_nodes:
                entry = site_name(node, config.untrusted_sources)
                if entry is None:
                    continue
                desc = f"request input {entry}"
                self.all_sites.append(
                    (node.lineno, node.col_offset, desc)
                )
                if (fi.path, node.lineno) not in acknowledged:
                    self.sources[id(node)] = desc
        #: every resolved callee key — the round loop skips functions
        #: whose callees are all untainted and that have no sources or
        #: injected parameter taint (they cannot produce anything).
        self.callee_set: Set[str] = set()
        for keys in self.calls.values():
            self.callee_set.update(keys)
        #: parameter name -> witness, injected by the caller-side
        #: argument propagation between fixpoint rounds.
        self.param_taint: Dict[str, str] = {}
        self.tainted: Dict[str, str] = {}
        self.tainted_return: Optional[str] = None

    def _expr_taint(self, expr: ast.AST,
                    tainted_fns: Dict[str, str]) -> Optional[str]:
        """Witness description if this expression mentions an untrusted
        source, a tainted local/param, or a call into a tainted-return
        function — WITHOUT descending into sanitizer calls (their
        result is trusted by declaration) and WITHOUT propagating taint
        out of lookup-key positions: ``jobs[tainted]`` and
        ``jobs.get(tainted)`` read a record TRUSTED code stored — the
        attacker chooses which record, not its contents (which-record
        authorization is R14's domain, not taint's)."""
        stack: List[ast.AST] = [expr]
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(node, ast.Call):
                desc = self.sources.get(id(node))
                if desc is not None:
                    return desc
                if site_name(node, self.config.sanitizers) is not None:
                    continue  # trusted by declaration: skip the subtree
                callees = self.calls.get(
                    (node.lineno, node.col_offset), ()
                )
                if callees:
                    # Resolved project call: its return taint is the
                    # callee's computed summary (param taint flows in
                    # via arg_taints, out via tainted_fns) — do NOT
                    # also apply the lexical mentions-a-tainted-arg
                    # rule, which would re-taint values the callee
                    # provably sanitized (e.g. _parse_job(body)).
                    for callee in callees:
                        w = tainted_fns.get(callee)
                        if w is not None:
                            return w
                    stack.append(node.func)  # tainted receiver counts
                    continue
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("get", "pop")
                ):
                    stack.append(node.func)  # receiver taints; key not
                    continue
            elif isinstance(node, ast.Subscript):
                stack.append(node.value)  # container taints; slice not
                continue
            elif isinstance(node, ast.Name):
                w = self.tainted.get(node.id)
                if w is not None:
                    return w
            stack.extend(ast.iter_child_nodes(node))
        return None

    def recompute(self, tainted_fns: Dict[str, str]) -> bool:
        """Refresh local taint + the tainted-return flag; True if the
        return status changed (drives the global fixpoint)."""
        self.tainted = dict(self.param_taint)
        changed = True
        while changed:
            changed = False
            for names, value in self.assigns:
                if names <= set(self.tainted):
                    continue
                w = self._expr_taint(value, tainted_fns)
                if w is not None:
                    for n in names:
                        self.tainted.setdefault(n, w)
                    changed = True
        ret: Optional[str] = None
        for value in self.returns:
            ret = self._expr_taint(value, tainted_fns)
            if ret is not None:
                break
        flipped = (ret is None) != (self.tainted_return is None)
        self.tainted_return = ret
        return flipped

    def arg_taints(self, tainted_fns: Dict[str, str]
                   ) -> List[Tuple[str, str, str]]:
        """(callee key, param name, witness) for every tainted argument
        handed to a project function — the caller side of the
        interprocedural parameter-taint propagation."""
        out: List[Tuple[str, str, str]] = []
        for node in self.call_nodes:
            callees = self.calls.get((node.lineno, node.col_offset))
            if not callees:
                continue
            if site_name(node, self.config.sanitizers) is not None:
                continue
            for callee in callees:
                fi = self.graph.functions.get(callee)
                if fi is None:
                    continue
                for pname, arg in bind_call_args(fi, node):
                    w = self._expr_taint(arg, tainted_fns)
                    if w is not None:
                        out.append((callee, pname, w))
        return out

    def sink_findings(self, tainted_fns: Dict[str, str]
                      ) -> List[RawFinding]:
        out: List[RawFinding] = []
        for node in self.call_nodes:
            sink = site_name(node, self.config.trust_sinks)
            if sink is None:
                continue
            args = list(node.args) + [kw.value for kw in node.keywords]
            for arg in args:
                w = self._expr_taint(arg, tainted_fns)
                if w is not None:
                    out.append(
                        (
                            "R13",
                            node.lineno,
                            node.col_offset,
                            f"request-derived value ({w}) reaches "
                            f"sensitive sink {sink} without a declared "
                            "sanitizer — canonicalize/validate it "
                            "(int/range coercion, schema validator, "
                            "digest-derived id) or acknowledge the "
                            "SOURCE with ignore[R13] and a reason",
                        )
                    )
                    break
        return out


def untrusted_sites(graph: ProjectGraph, config: JaxlintConfig
                    ) -> Dict[Tuple[str, int], Tuple[int, str]]:
    """(path, line) -> (col, desc) for every untrusted-source site in
    the project, acknowledged or not — project.py uses this to emit the
    suppressed "acknowledged source" inventory entries (R2x contract)."""
    sites: Dict[Tuple[str, int], Tuple[int, str]] = {}
    for fkey in sorted(graph.functions):
        if not config.is_handler(graph.functions[fkey].path):
            continue  # sources only seed in handler modules
        scan = _FuncTrust(graph, fkey, config, acknowledged=set())
        for line, col, desc in scan.all_sites:
            key = (scan.fi.path, line)
            if key not in sites or (col, desc) < sites[key]:
                sites[key] = (col, desc)
    return sites


def run_r13(graph: ProjectGraph, config: JaxlintConfig,
            acknowledged: Set[Tuple[str, int]]
            ) -> Dict[str, List[RawFinding]]:
    """R13 findings per project-relative path.

    ``acknowledged``: (path, line) pairs carrying a valid R13 marker —
    those sources taint nobody."""
    scans: Dict[str, _FuncTrust] = {
        fkey: _FuncTrust(graph, fkey, config, acknowledged)
        for fkey in sorted(graph.functions)
    }
    #: function key -> witness for tainted-return functions
    tainted_fns: Dict[str, str] = {}

    def _inert(scan: _FuncTrust) -> bool:
        """No way for this function to hold or emit taint right now."""
        return (
            not scan.sources
            and not scan.param_taint
            and not scan.tainted
            and scan.tainted_return is None
            and not (scan.callee_set & tainted_fns.keys())
        )

    for _ in range(12):  # bounded interprocedural fixpoint
        changed = False
        for fkey in sorted(scans):
            scan = scans[fkey]
            if _inert(scan):
                continue
            if scan.recompute(tainted_fns):
                changed = True
            if scan.tainted_return is not None:
                if tainted_fns.get(fkey) != scan.tainted_return:
                    tainted_fns[fkey] = scan.tainted_return
                    changed = True
            elif fkey in tainted_fns:
                del tainted_fns[fkey]
                changed = True
        # caller -> callee parameter taint (monotone: params only gain)
        for fkey in sorted(scans):
            scan = scans[fkey]
            if _inert(scan):
                continue
            for callee, pname, w in scan.arg_taints(tainted_fns):
                dest = scans.get(callee)
                if dest is not None and pname not in dest.param_taint:
                    dest.param_taint[pname] = w
                    changed = True
        if not changed:
            break

    out: Dict[str, List[RawFinding]] = {}
    for fkey in sorted(scans):
        scan = scans[fkey]
        if _inert(scan):
            continue
        found = scan.sink_findings(tainted_fns)
        if found:
            out.setdefault(scan.fi.path, []).extend(found)
    return out
