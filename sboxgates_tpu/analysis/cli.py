"""``python -m sboxgates_tpu.analysis`` — the jaxlint CLI.

Scans the given paths (default: the ``paths`` from ``[tool.jaxlint]``),
prints findings in human or JSON form, and exits non-zero when any
unsuppressed finding remains.  ``--write-baseline``/``--baseline`` manage
the committed zero-findings baseline the tier-1 gate compares against.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Iterable, List, Optional

from .config import ALL_RULES, JaxlintConfig, load_config
from .rules import RULE_DOCS, FileReport, Finding, lint_source

BASELINE_SCHEMA = 1


def iter_python_files(root: str, paths: Iterable[str], config: JaxlintConfig):
    """Yields (abspath, relpath) for every .py under the scan paths, in
    sorted order, minus the config's ``exclude`` globs."""
    seen = set()
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap):
            cands = [ap]
        else:
            cands = []
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames.sort()
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        cands.append(os.path.join(dirpath, fn))
        for ap_file in cands:
            rel = os.path.relpath(ap_file, root).replace(os.sep, "/")
            if rel in seen or config.is_excluded(rel):
                continue
            seen.add(rel)
            yield ap_file, rel


def lint_paths(
    paths: Optional[List[str]] = None,
    config: Optional[JaxlintConfig] = None,
) -> List[FileReport]:
    """Library entry point: lint ``paths`` (default from config) and
    return per-file reports.  When the config enables ``whole_program``,
    the cross-module pass (call graph + R1x/R2x/R4x) runs too, sharing
    one parse per module with the per-file rules."""
    if config is None:
        config = load_config(paths[0] if paths else ".")
    if config.whole_program:
        from .project import lint_project

        return lint_project(paths, config)
    scan = paths or config.paths
    reports: List[FileReport] = []
    for ap, rel in iter_python_files(config.root, scan, config):
        with open(ap, "r", encoding="utf-8") as f:
            source = f.read()
        reports.append(lint_source(source, rel, config))
    return reports


def _flatten(reports: List[FileReport]):
    findings = [f for r in reports for f in r.findings]
    suppressed = [f for r in reports for f in r.suppressed]
    return findings, suppressed


def _as_payload(reports: List[FileReport]) -> dict:
    findings, suppressed = _flatten(reports)
    return {
        "schema": BASELINE_SCHEMA,
        "files_scanned": len(reports),
        "findings": [f.as_json() for f in findings],
        "suppressed": [f.as_json() for f in suppressed],
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m sboxgates_tpu.analysis",
        description="jaxlint: JAX-aware static analysis for sboxgates_tpu "
        "(recompile hazards, hot-loop syncs, tracer escapes, lock "
        "discipline, swallowed errors)",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        help="files/directories to scan (default: [tool.jaxlint] paths)",
    )
    ap.add_argument(
        "--format",
        "-f",
        choices=("human", "json"),
        default="human",
        help="output format",
    )
    ap.add_argument(
        "--rules",
        help="comma-separated rule ids to enable (default: config)",
    )
    ap.add_argument(
        "--baseline",
        metavar="FILE",
        help="compare against a committed baseline: exit 0 iff the "
        "unsuppressed findings exactly match the baseline's",
    )
    ap.add_argument(
        "--write-baseline",
        metavar="FILE",
        help="write the current findings as the new baseline and exit 0",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print the rule table"
    )
    wp = ap.add_mutually_exclusive_group()
    wp.add_argument(
        "--whole-program",
        action="store_true",
        default=None,
        help="run the cross-module pass (call graph + R1x/R2x/R4x) even "
        "if [tool.jaxlint] whole_program is off",
    )
    wp.add_argument(
        "--no-whole-program",
        action="store_true",
        help="per-file rules only, ignoring [tool.jaxlint] whole_program",
    )
    ap.add_argument(
        "--graph",
        action="store_true",
        help="dump the resolved call graph (functions, edges with "
        "lock/loop context, thread and jit roots) as deterministic JSON "
        "and exit",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid in (*ALL_RULES, "SUP", "ERR"):
            print(f"{rid:4s} {RULE_DOCS[rid]}")
        return 0

    start = args.paths[0] if args.paths else "."
    try:
        config = load_config(start)
    except ValueError as e:
        print(f"jaxlint: {e}", file=sys.stderr)
        return 2
    if args.rules:
        wanted = [r.strip() for r in args.rules.split(",") if r.strip()]
        bad = [r for r in wanted if r not in ALL_RULES]
        if bad:
            print(f"jaxlint: unknown rule ids {bad}", file=sys.stderr)
            return 2
        config.rules = wanted
    if args.whole_program:
        config.whole_program = True
    elif args.no_whole_program:
        config.whole_program = False

    if args.graph:
        from .project import graph_json

        json.dump(
            graph_json(args.paths or None, config),
            sys.stdout,
            indent=1,
            sort_keys=True,
        )
        print()
        return 0

    reports = lint_paths(args.paths or None, config)
    findings, suppressed = _flatten(reports)
    payload = _as_payload(reports)

    if args.write_baseline:
        with open(args.write_baseline, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
        print(
            f"jaxlint: wrote baseline ({len(findings)} findings) to "
            f"{args.write_baseline}"
        )
        return 0

    if args.format == "json":
        json.dump(payload, sys.stdout, indent=1, sort_keys=True)
        print()
    else:
        for f in findings:
            print(f.render())
        print(
            f"jaxlint: {len(findings)} finding(s), "
            f"{len(suppressed)} suppressed, "
            f"{len(reports)} file(s) scanned"
        )

    if args.baseline:
        try:
            with open(args.baseline, "r", encoding="utf-8") as f:
                base = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"jaxlint: cannot read baseline: {e}", file=sys.stderr)
            return 2
        base_set = {
            (d["path"], d["line"], d["rule"]) for d in base.get("findings", ())
        }
        now_set = {(f.path, f.line, f.rule) for f in findings}
        new = now_set - base_set
        fixed = base_set - now_set
        if new:
            print(
                f"jaxlint: {len(new)} finding(s) not in baseline",
                file=sys.stderr,
            )
        if fixed:
            # Exact match, both directions: a fixed-but-not-regenerated
            # baseline entry would silently mask a later regression at the
            # same (path, line, rule).
            print(
                f"jaxlint: {len(fixed)} baseline finding(s) no longer "
                "present — regenerate with --write-baseline",
                file=sys.stderr,
            )
        return 1 if (new or fixed) else 0

    return 1 if findings else 0
