"""``python -m sboxgates_tpu.analysis`` — the jaxlint CLI.

Scans the given paths (default: the ``paths`` from ``[tool.jaxlint]``),
prints findings in human or JSON form, and exits non-zero when any
unsuppressed finding remains.  ``--write-baseline``/``--baseline`` manage
the committed zero-findings baseline the tier-1 gate compares against.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Iterable, List, Optional

from .config import ALL_RULES, JaxlintConfig, load_config
from .rules import RULE_DOCS, FileReport, Finding, lint_source

BASELINE_SCHEMA = 1


def iter_python_files(root: str, paths: Iterable[str], config: JaxlintConfig):
    """Yields (abspath, relpath) for every .py under the scan paths, in
    sorted order, minus the config's ``exclude`` globs."""
    seen = set()
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap):
            cands = [ap]
        else:
            cands = []
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames.sort()
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        cands.append(os.path.join(dirpath, fn))
        for ap_file in cands:
            rel = os.path.relpath(ap_file, root).replace(os.sep, "/")
            if rel in seen or config.is_excluded(rel):
                continue
            seen.add(rel)
            yield ap_file, rel


def lint_paths(
    paths: Optional[List[str]] = None,
    config: Optional[JaxlintConfig] = None,
) -> List[FileReport]:
    """Library entry point: lint ``paths`` (default from config) and
    return per-file reports.  When the config enables ``whole_program``,
    the cross-module pass (call graph + R1x/R2x/R4x) runs too, sharing
    one parse per module with the per-file rules."""
    if config is None:
        config = load_config(paths[0] if paths else ".")
    if config.whole_program:
        from .project import lint_project

        return lint_project(paths, config)
    scan = paths or config.paths
    reports: List[FileReport] = []
    for ap, rel in iter_python_files(config.root, scan, config):
        with open(ap, "r", encoding="utf-8") as f:
            source = f.read()
        reports.append(lint_source(source, rel, config))
    return reports


def lint_ref(
    ref: str,
    config: JaxlintConfig,
    paths: Optional[List[str]] = None,
    sources_out: Optional[dict] = None,
) -> List[FileReport]:
    """Lints the tree as it exists at git ``ref`` (sources read via
    ``git show``, never touching the working tree), with the SAME
    current configuration — so ``--diff-base`` judges old code by
    today's contracts, which is exactly what an incremental gate wants.
    ``sources_out``, if given, is filled with relpath -> source lines so
    callers need not re-fetch the same blobs from git.  Raises
    ``RuntimeError`` with a one-line message on git failures."""
    import subprocess

    from .project import analyze_project
    from .rules import analyze_file, finalize_report

    proc = subprocess.run(
        ["git", "ls-tree", "-r", "--name-only", ref],
        cwd=config.root,
        capture_output=True,
        text=True,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"cannot list files at {ref!r}: "
            f"{proc.stderr.strip().splitlines()[-1] if proc.stderr.strip() else 'git failed'}"
        )
    # git ls-tree paths are repo-relative: absolute scan specs (which
    # iter_python_files accepts for the working tree) must be
    # relativized against the project root to match anything.
    scan = []
    for p in paths or config.paths:
        if os.path.isabs(p):
            p = os.path.relpath(p, config.root)
        scan.append(os.path.normpath(p).replace(os.sep, "/"))
    wanted = []
    for rel in sorted(proc.stdout.splitlines()):
        if not rel.endswith(".py") or config.is_excluded(rel):
            continue
        if any(
            s in (".", "") or rel == s or rel.startswith(s + "/")
            for s in scan
        ):
            wanted.append(rel)
    analyses = []
    for rel in wanted:
        show = subprocess.run(
            ["git", "show", f"{ref}:{rel}"],
            cwd=config.root,
            capture_output=True,
            text=True,
        )
        if show.returncode != 0:
            continue  # racy rename/submodule edge: treat as absent
        if sources_out is not None:
            sources_out[rel] = show.stdout.splitlines()
        analyses.append(analyze_file(show.stdout, rel, config))
    if config.whole_program:
        reports, _graph = analyze_project(analyses, config)
        return reports
    return [finalize_report(fa) for fa in analyses]


def _finding_keys(reports: List[FileReport], root: str,
                  ref: Optional[str] = None,
                  sources: Optional[dict] = None):
    """Content-keyed finding multiset: (path, rule, stripped source
    line).  Keying on the line TEXT instead of the number keeps
    unrelated edits above a finding from resurrecting it as "new" in
    differential mode.  ``sources`` seeds the relpath -> lines cache
    (lint_ref already fetched the base blobs once)."""
    import subprocess
    from collections import Counter

    sources = {} if sources is None else sources

    def line_text(path: str, line: int) -> str:
        if path not in sources:
            try:
                if ref is None:
                    with open(
                        os.path.join(root, path), "r", encoding="utf-8"
                    ) as f:
                        sources[path] = f.read().splitlines()
                else:
                    proc = subprocess.run(
                        ["git", "show", f"{ref}:{path}"],
                        cwd=root,
                        capture_output=True,
                        text=True,
                    )
                    sources[path] = (
                        proc.stdout.splitlines()
                        if proc.returncode == 0
                        else []
                    )
            except OSError:
                sources[path] = []
        lines = sources[path]
        if 1 <= line <= len(lines):
            return lines[line - 1].strip()
        return ""

    counts: Counter = Counter()
    keyed = []
    for r in reports:
        for f in r.findings:
            key = (f.path, f.rule, line_text(f.path, f.line))
            counts[key] += 1
            keyed.append((f, key))
    return counts, keyed


def _flatten(reports: List[FileReport]):
    findings = [f for r in reports for f in r.findings]
    suppressed = [f for r in reports for f in r.suppressed]
    return findings, suppressed


def _as_payload(reports: List[FileReport]) -> dict:
    findings, suppressed = _flatten(reports)
    return {
        "schema": BASELINE_SCHEMA,
        "files_scanned": len(reports),
        "findings": [f.as_json() for f in findings],
        "suppressed": [f.as_json() for f in suppressed],
    }


def _as_sarif(reports: List[FileReport],
              baseline_keys: frozenset = frozenset()) -> dict:
    """SARIF 2.1.0 view of the unsuppressed findings — the interchange
    format CI diff-annotation tooling consumes.

    ``baseline_keys``: (path, line, rule) triples from the committed
    ``--baseline``.  A finding the baseline already accounts for is
    still emitted (the log stays a complete scan record) but carries a
    ``suppressions`` entry of kind ``external`` (§3.27.23: suppressed
    outside the source, here by the baseline file), so CI annotators
    show only genuinely new results."""
    findings, _ = _flatten(reports)
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "jaxlint",
                        "rules": [
                            {
                                "id": rid,
                                "shortDescription": {
                                    "text": RULE_DOCS[rid]
                                },
                            }
                            for rid in sorted(RULE_DOCS)
                        ],
                    }
                },
                "results": [
                    {
                        "ruleId": f.rule,
                        "level": "warning",
                        "message": {"text": f.message},
                        "locations": [
                            {
                                "physicalLocation": {
                                    "artifactLocation": {"uri": f.path},
                                    "region": {
                                        "startLine": f.line,
                                        "startColumn": f.col + 1,
                                    },
                                }
                            }
                        ],
                        **(
                            {"suppressions": [{"kind": "external"}]}
                            if (f.path, f.line, f.rule) in baseline_keys
                            else {}
                        ),
                    }
                    for f in sorted(
                        findings,
                        key=lambda f: (f.path, f.line, f.col, f.rule),
                    )
                ],
            }
        ],
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m sboxgates_tpu.analysis",
        description="jaxlint: JAX-aware static analysis for sboxgates_tpu "
        "(recompile hazards, hot-loop syncs, tracer escapes, lock "
        "discipline, swallowed errors)",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        help="files/directories to scan (default: [tool.jaxlint] paths)",
    )
    ap.add_argument(
        "--format",
        "-f",
        choices=("human", "json"),
        default="human",
        help="output format",
    )
    ap.add_argument(
        "--rules",
        help="comma-separated rule ids to enable (default: config)",
    )
    ap.add_argument(
        "--baseline",
        metavar="FILE",
        help="compare against a committed baseline: exit 0 iff the "
        "unsuppressed findings exactly match the baseline's",
    )
    ap.add_argument(
        "--write-baseline",
        metavar="FILE",
        help="write the current findings as the new baseline and exit 0",
    )
    ap.add_argument(
        "--diff-base",
        metavar="REF",
        help="differential mode: report only findings introduced "
        "relative to git REF (both trees judged by the CURRENT config; "
        "findings matched by (path, rule, source-line text) so "
        "unrelated edits don't resurrect old ones) — fast incremental "
        "output for local iteration while the tier-1 gate stays on the "
        "zero-findings --baseline",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print the rule table"
    )
    wp = ap.add_mutually_exclusive_group()
    wp.add_argument(
        "--whole-program",
        action="store_true",
        default=None,
        help="run the cross-module pass (call graph + R1x/R2x/R4x) even "
        "if [tool.jaxlint] whole_program is off",
    )
    wp.add_argument(
        "--no-whole-program",
        action="store_true",
        help="per-file rules only, ignoring [tool.jaxlint] whole_program",
    )
    ap.add_argument(
        "--graph",
        action="store_true",
        help="dump the resolved call graph (functions, edges with "
        "lock/loop context, thread and jit roots) as deterministic JSON "
        "and exit",
    )
    ap.add_argument(
        "--sarif",
        metavar="FILE",
        help="also write the unsuppressed findings as SARIF 2.1.0 to "
        "FILE (CI diff annotation), alongside the chosen --format",
    )
    ap.add_argument(
        "--coverage",
        action="store_true",
        help="chaos-coverage report: cross-reference faults.KNOWN_SITES "
        "against the tests' arm()/SBG_FAULTS specs and [tool.jaxlint] "
        "chaos_waivers; exit 1 on unexercised sites or stale waivers",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid in (*ALL_RULES, "COV", "SUP", "ERR"):
            print(f"{rid:4s} {RULE_DOCS[rid]}")
        return 0

    start = args.paths[0] if args.paths else "."
    try:
        config = load_config(start)
    except ValueError as e:
        print(f"jaxlint: {e}", file=sys.stderr)
        return 2
    if args.rules:
        wanted = [r.strip() for r in args.rules.split(",") if r.strip()]
        bad = [r for r in wanted if r not in ALL_RULES]
        if bad:
            print(f"jaxlint: unknown rule ids {bad}", file=sys.stderr)
            return 2
        config.rules = wanted
    if args.whole_program:
        config.whole_program = True
    elif args.no_whole_program:
        config.whole_program = False

    if args.graph:
        from .project import graph_json

        json.dump(
            graph_json(args.paths or None, config),
            sys.stdout,
            indent=1,
            sort_keys=True,
        )
        print()
        return 0

    if args.coverage:
        from .durability import chaos_coverage
        from .project import lint_project

        config.whole_program = True  # the site registry needs the graph
        _reports, graph = lint_project(
            args.paths or None, config, return_graph=True
        )
        report = chaos_coverage(graph, config)
        if args.format == "json":
            json.dump(report, sys.stdout, indent=1, sort_keys=True)
            print()
        else:
            for name in sorted(report["sites"]):
                s = report["sites"][name]
                if s["armed_by"]:
                    how = f"armed by {', '.join(s['armed_by'])}"
                elif s["waiver"]:
                    how = f"waived: {s['waiver']}"
                else:
                    how = "UNCOVERED"
                print(f"{name:20s} {s['declared']:40s} {how}")
            for w in report["stale_waivers"]:
                print(f"stale waiver: {w}")
            print(
                f"jaxlint: {report['armed_total']}/"
                f"{report['declared_total']} fault sites armed, "
                f"{len(report['uncovered'])} uncovered, "
                f"{len(report['stale_waivers'])} stale waiver(s)"
            )
        return 1 if (
            report["uncovered"] or report["stale_waivers"]
        ) else 0

    reports = lint_paths(args.paths or None, config)
    findings, suppressed = _flatten(reports)
    payload = _as_payload(reports)

    # The baseline is read up front: the SARIF export marks
    # baseline-matched results as externally suppressed, so it needs
    # the key set before writing (the exit-code comparison below reuses
    # the same set).
    base_set: Optional[set] = None
    if args.baseline:
        try:
            with open(args.baseline, "r", encoding="utf-8") as f:
                base = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"jaxlint: cannot read baseline: {e}", file=sys.stderr)
            return 2
        base_set = {
            (d["path"], d["line"], d["rule"])
            for d in base.get("findings", ())
        }

    if args.sarif:
        with open(args.sarif, "w", encoding="utf-8") as f:
            json.dump(
                _as_sarif(reports, frozenset(base_set or ())),
                f, indent=1, sort_keys=True,
            )
            f.write("\n")

    if args.diff_base:
        base_sources: dict = {}
        try:
            base_reports = lint_ref(
                args.diff_base, config, args.paths or None,
                sources_out=base_sources,
            )
        except RuntimeError as e:
            print(f"jaxlint: {e}", file=sys.stderr)
            return 2
        base_counts, _ = _finding_keys(
            base_reports, config.root, ref=args.diff_base,
            sources=base_sources,
        )
        now_counts, keyed = _finding_keys(reports, config.root)
        budget = dict(base_counts)
        new: List[Finding] = []
        for f, key in keyed:
            if budget.get(key, 0) > 0:
                budget[key] -= 1
            else:
                new.append(f)
        new.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        if args.format == "json":
            json.dump(
                {
                    "diff_base": args.diff_base,
                    "new_findings": [f.as_json() for f in new],
                    "total_findings": len(findings),
                },
                sys.stdout, indent=1, sort_keys=True,
            )
            print()
        else:
            for f in new:
                print(f.render())
            print(
                f"jaxlint: {len(new)} finding(s) introduced since "
                f"{args.diff_base} ({len(findings)} total)"
            )
        return 1 if new else 0

    if args.write_baseline:
        with open(args.write_baseline, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
        print(
            f"jaxlint: wrote baseline ({len(findings)} findings) to "
            f"{args.write_baseline}"
        )
        return 0

    if args.format == "json":
        json.dump(payload, sys.stdout, indent=1, sort_keys=True)
        print()
    else:
        for f in findings:
            print(f.render())
        print(
            f"jaxlint: {len(findings)} finding(s), "
            f"{len(suppressed)} suppressed, "
            f"{len(reports)} file(s) scanned"
        )

    if base_set is not None:
        now_set = {(f.path, f.line, f.rule) for f in findings}
        new = now_set - base_set
        fixed = base_set - now_set
        if new:
            print(
                f"jaxlint: {len(new)} finding(s) not in baseline",
                file=sys.stderr,
            )
        if fixed:
            # Exact match, both directions: a fixed-but-not-regenerated
            # baseline entry would silently mask a later regression at the
            # same (path, line, rule).
            print(
                f"jaxlint: {len(fixed)} baseline finding(s) no longer "
                "present — regenerate with --write-baseline",
                file=sys.stderr,
            )
        return 1 if (new or fixed) else 0

    return 1 if findings else 0
