"""jaxlint rule implementations (stdlib ``ast`` only — no new deps).

The five rules target the JAX failure modes that erase streaming-search
throughput on real hardware:

R1  recompilation hazards — ``jax.jit`` wrapping inside a loop (a fresh
    compile cache per iteration), and call sites of jitted functions that
    pass an unhashable literal or a per-iteration-varying expression as a
    *static* argument (every distinct value is a full recompile).
R2  host-device synchronization inside a loop in a *hot* module
    (``[tool.jaxlint] hot_modules``): ``.block_until_ready()``,
    ``jax.device_get``, ``np.asarray``/``np.array`` on a non-host
    expression, ``.item()``, and ``int()``/``float()`` wrapped directly
    around a ``jax.*``/``jnp.*`` call.  Each sync stalls the dispatch
    pipeline; inside the streaming sweeps that is the whole ballgame.
R3  tracer escape — storing to ``self``/``global`` state, or creating a
    ``threading.Thread``, inside a jit-traced function; tracers that
    leak out of the trace die later with opaque errors (or silently
    capture a stale constant).
R4  lock discipline — module-level mutable state mutated inside a
    ``threading.Thread`` target without holding a ``Lock``/``Condition``
    belonging to the same module.
R5  swallowed errors — ``except Exception`` / bare ``except`` whose body
    neither re-raises nor logs.

Findings are suppressed inline with ``# jaxlint: ignore[R2] reason`` (the
reason is mandatory; a reason-less marker suppresses nothing and is itself
reported as SUP).  The suppression comment lives on the offending line or
on its own line directly above.
"""

from __future__ import annotations

import ast
import re
import tokenize
from dataclasses import dataclass, field
from io import StringIO
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .config import JaxlintConfig

#: Rule used for invalid/reason-less suppression markers; never
#: suppressible itself.
SUPPRESSION_RULE = "SUP"
#: Rule used for files that fail to parse.
PARSE_RULE = "ERR"

RULE_DOCS = {
    "R1": "recompilation hazard (jit-in-loop / unhashable or varying static arg)",
    "R2": "host-device sync inside a loop in a hot module",
    "R3": "tracer escape (self/global store or thread hand-off under jit trace)",
    "R4": "module state mutated in a thread target without its module lock",
    "R5": "except Exception/bare except that neither re-raises nor logs",
    "R6": (
        "direct stats-dict mutation outside telemetry/ (an unlocked "
        "read-modify-write loses updates under thread races; use the "
        "metrics facade: stats.inc/put/observe/ensure/merge)"
    ),
    "R1x": (
        "cross-module recompilation hazard (unhashable or loop-varying "
        "static arg at a call site of a jitted function defined elsewhere)"
    ),
    "R2x": (
        "interprocedural host sync: a hot-module loop calls a helper that "
        "transitively blocks on the device"
    ),
    "R4x": (
        "module state mutated on an unlocked path reachable from a thread "
        "entry (transitive reachability; locks may be imported, "
        "re-exported, or passed as parameters)"
    ),
    "R7": (
        "registry drift: a use site bypassing or undeclared in one of the "
        "declared registries (kernel registry / METRICS / fault sites / "
        "journal config keys / pinned thread roots), or a declared entry "
        "with no reachable use site"
    ),
    "R8": (
        "bucket discipline: an operand shape at a registered-kernel "
        "dispatch site derives from a non-bucketed dynamic value — every "
        "distinct shape is a fresh compile (pad to the declared bucket "
        "ladders: bucket_size/PIVOT_G_BUCKETS/FLEET_BUCKETS/"
        "STACKED_BUCKETS)"
    ),
    "R9": (
        "lock-order hazard: a cycle in the lock-acquisition-order graph "
        "over the thread roots (potential deadlock), or a lock held "
        "across a blocking dispatch/verdict resolve (deadlocks against "
        "the abandonment path)"
    ),
    "R10": (
        "replicated-protocol divergence: an agreement/collective site "
        "(breach_verdict, journal_seq_check, _kv_exchange, device "
        "collectives, replicated dispatch) reached from only one side of "
        "a rank-gated branch, or a device collective issued inside a "
        "host-agreement window — every process must issue the same "
        "agreement sequence or the pod hangs (launch-count lockstep)"
    ),
    "R11": (
        "determinism taint: a nondeterministic source (wall clock, "
        "unseeded RNG, os.urandom, uuid, unsorted directory scan, set "
        "iteration, id()) flows into a bit-identity sink (journal append, "
        "checkpoint bytes, canonical store keys, seed derivation) — "
        "breaks bit-identical resume and cross-process key agreement"
    ),
    "R12": (
        "durability discipline: a truncating open / json.dump / "
        "os.replace in a persistence module bypasses the shared "
        "tmp+fsync+atomic-replace helper (durable_write_text) — a kill "
        "mid-write leaves a torn file the recovery path must never see"
    ),
    "R13": (
        "untrusted-input taint: a request-derived value (headers, body "
        "fields, query params, path segments) reaches a sensitive sink "
        "(filesystem path construction, journal/store record fields, "
        "faults.set_tenant, process spawns) without passing a declared "
        "sanitizer (schema validator, int/range coercion, canonical-key "
        "or digest derivation) — path traversal and unvalidated tenant "
        "names, caught structurally"
    ),
    "R14": (
        "admission-order discipline: an effectful call in a handler "
        "body (orchestrator enqueue/join, durable admission record) not "
        "dominated by the auth+quota check sites, or a 2xx admission "
        "response not dominated by the fsync'd admission-journal append "
        "— the fail-closed-auth-before-effects and journal-before-202 "
        "contracts, on every path"
    ),
    "R15": (
        "resource lifecycle: a socket/listener/thread/temp-file "
        "acquisition not released on all exit paths (with/try-finally, "
        "ownership transfer via return or hand-off, teardown-registry "
        "registration, or a class teardown for self-stored resources) — "
        "a failed bind must never leak an ephemeral listener"
    ),
    "COV": (
        "chaos coverage: a declared fault site (faults.KNOWN_SITES) with "
        "no armed test and no [tool.jaxlint] chaos_waivers entry, or a "
        "stale waiver naming a site no longer declared"
    ),
    SUPPRESSION_RULE: (
        "malformed or unused jaxlint suppression (reason is mandatory; a "
        "marker whose finding no longer fires is itself a finding)"
    ),
    PARSE_RULE: "file failed to parse",
}


@dataclass(frozen=True)
class Finding:
    path: str  # project-relative posix path
    line: int
    col: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def as_json(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "rule": self.rule,
            "message": self.message,
        }


# --------------------------------------------------------------------------
# shared AST helpers


def dotted(node: ast.AST) -> Optional[str]:
    """'jax.jit' for Attribute/Name chains; None for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}
_PARTIAL_NAMES = {"functools.partial", "partial"}


def _jit_call_of(node: ast.AST) -> Optional[ast.Call]:
    """The Call node that *creates* a jitted function, if ``node`` is one.

    Matches ``jax.jit(...)`` / ``pjit(...)`` and
    ``functools.partial(jax.jit, ...)`` (decorator form).
    """
    if not isinstance(node, ast.Call):
        return None
    name = dotted(node.func)
    if name in _JIT_NAMES:
        return node
    if name in _PARTIAL_NAMES and node.args:
        if dotted(node.args[0]) in _JIT_NAMES:
            return node
    return None


def _is_jit_decorated(fn: ast.AST) -> bool:
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    for dec in fn.decorator_list:
        if dotted(dec) in _JIT_NAMES or _jit_call_of(dec) is not None:
            return True
    return False


def _static_params(fn: ast.FunctionDef, jit_call: ast.Call) -> Set[str]:
    """Parameter names marked static by a jit decorator Call."""
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    out: Set[str] = set()
    for kw in jit_call.keywords:
        if kw.arg == "static_argnames":
            for el in _const_strs(kw.value):
                out.add(el)
        elif kw.arg == "static_argnums":
            for n in _const_ints(kw.value):
                if 0 <= n < len(params):
                    out.add(params[n])
    return out


def _const_strs(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            el.value
            for el in node.elts
            if isinstance(el, ast.Constant) and isinstance(el.value, str)
        ]
    return []


def _const_ints(node: ast.AST) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            el.value
            for el in node.elts
            if isinstance(el, ast.Constant) and isinstance(el.value, int)
        ]
    return []


_UNHASHABLE_NODES = (
    ast.List,
    ast.Dict,
    ast.Set,
    ast.ListComp,
    ast.DictComp,
    ast.SetComp,
)


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _target_names(target: ast.AST) -> Set[str]:
    """Loop-target names: ``for i in ...`` / ``for a, (b, c) in ...``."""
    out: Set[str] = set()
    for n in ast.walk(target):
        if isinstance(n, ast.Name):
            out.add(n.id)
    return out


# --------------------------------------------------------------------------
# R1 — recompilation hazards


class _R1(ast.NodeVisitor):
    def __init__(self) -> None:
        self.findings: List[Tuple[int, int, str]] = []
        self._loop_vars: List[Set[str]] = []  # one frame per enclosing For
        self._in_loop = 0
        #: name -> static parameter names, for jit-decorated module/class fns
        self._static: Dict[str, Tuple[Set[str], List[str]]] = {}

    # -- pass 1: collect jitted defs with static args (any nesting level)
    def collect(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            for dec in node.decorator_list:
                call = _jit_call_of(dec)
                if call is None:
                    continue
                statics = _static_params(node, call)
                if statics:
                    params = [
                        a.arg for a in node.args.posonlyargs + node.args.args
                    ]
                    self._static[node.name] = (statics, params)

    # -- pass 2: walk, tracking loops
    def visit_For(self, node: ast.For) -> None:
        self._loop_vars.append(_target_names(node.target))
        self._in_loop += 1
        for child in node.body:
            self.visit(child)
        self._in_loop -= 1
        self._loop_vars.pop()
        # the else: body and the iterable run once, outside the loop
        for child in node.orelse:
            self.visit(child)
        self.visit(node.iter)

    def visit_While(self, node: ast.While) -> None:
        self._loop_vars.append(set())
        self._in_loop += 1
        # the test re-evaluates every iteration: it IS loop context
        self.visit(node.test)
        for child in node.body:
            self.visit(child)
        self._in_loop -= 1
        self._loop_vars.pop()
        for child in node.orelse:
            self.visit(child)

    def _all_loop_vars(self) -> Set[str]:
        out: Set[str] = set()
        for frame in self._loop_vars:
            out |= frame
        return out

    def visit_Call(self, node: ast.Call) -> None:
        if self._in_loop and _jit_call_of(node) is not None:
            self.findings.append(
                (
                    node.lineno,
                    node.col_offset,
                    "jit wrapper created inside a loop: each iteration gets a "
                    "fresh callable with an empty compile cache — hoist the "
                    "jax.jit(...) out of the loop (memoize by config key)",
                )
            )
        name = dotted(node.func)
        if name in self._static:
            statics, params = self._static[name]
            self._check_static_args(node, statics, params)
        self.generic_visit(node)

    def _check_static_args(
        self, call: ast.Call, statics: Set[str], params: List[str]
    ) -> None:
        bound: List[Tuple[str, ast.AST]] = []
        for i, arg in enumerate(call.args):
            if i < len(params):
                bound.append((params[i], arg))
        for kw in call.keywords:
            if kw.arg is not None:
                bound.append((kw.arg, kw.value))
        loop_vars = self._all_loop_vars()
        for pname, expr in bound:
            if pname not in statics:
                continue
            if isinstance(expr, _UNHASHABLE_NODES):
                self.findings.append(
                    (
                        expr.lineno,
                        expr.col_offset,
                        f"unhashable literal passed as static argument "
                        f"'{pname}': jit static args must be hashable "
                        "(use a tuple), and every new value recompiles",
                    )
                )
            elif loop_vars and (_names_in(expr) & loop_vars):
                self.findings.append(
                    (
                        expr.lineno,
                        expr.col_offset,
                        f"static argument '{pname}' varies with the "
                        "enclosing loop variable: every iteration triggers "
                        "a recompile — pass it as a traced arg or hoist it",
                    )
                )


# --------------------------------------------------------------------------
# R2 — host-device sync inside loops (hot modules only)

_SYNC_FUNCS = {
    "jax.device_get": "jax.device_get",
    "jax.block_until_ready": "jax.block_until_ready",
}
_ASARRAY_FUNCS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array"}
_HOSTY_CALLS = {"list", "tuple", "sorted", "range", "len", "dict", "zip"}


def _hosty_arg(node: ast.AST) -> bool:
    """True when the expression is clearly host data already (a display,
    a comprehension, or a list()/range()-style builtin call) — converting
    it cannot trigger a device sync."""
    if isinstance(
        node,
        (
            ast.List,
            ast.Tuple,
            ast.Dict,
            ast.Set,
            ast.ListComp,
            ast.SetComp,
            ast.GeneratorExp,
            ast.Constant,
        ),
    ):
        return True
    if isinstance(node, ast.Call) and dotted(node.func) in _HOSTY_CALLS:
        return True
    return False


def _contains_jax_call(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            name = dotted(n.func)
            if name and (name.startswith("jnp.") or name.startswith("jax.")):
                return True
    return False


def classify_sync(node: ast.Call) -> Optional[Tuple[str, str]]:
    """(kind, short description) when the call is a host-device sync
    pattern, else None.  ONE classifier for the per-file R2 and the
    cross-module R2x taint seeding — the two must never drift."""
    name = dotted(node.func)
    if name in _SYNC_FUNCS:
        return "sync_func", f"{name}()"
    if isinstance(node.func, ast.Attribute):
        if node.func.attr == "block_until_ready":
            return "block_until_ready", ".block_until_ready()"
        if node.func.attr == "item" and not node.args:
            return "item", ".item()"
    if name in _ASARRAY_FUNCS and node.args:
        if not _hosty_arg(node.args[0]):
            return "asarray", f"{name}() on a possibly-device value"
        return None
    if name in ("int", "float") and len(node.args) == 1:
        if _contains_jax_call(node.args[0]):
            return "cast", f"{name}() around a jax/jnp call"
    return None


class _R2(ast.NodeVisitor):
    def __init__(self) -> None:
        self.findings: List[Tuple[int, int, str]] = []
        self._in_loop = 0

    def visit_For(self, node: ast.For) -> None:
        self._in_loop += 1
        for child in node.body:
            self.visit(child)
        self._in_loop -= 1
        # the else: body and the iterable run once, outside the loop
        for child in node.orelse:
            self.visit(child)
        self.visit(node.iter)

    def visit_While(self, node: ast.While) -> None:
        self._in_loop += 1
        # the test re-evaluates every iteration: it IS loop context
        self.visit(node.test)
        for child in node.body:
            self.visit(child)
        self._in_loop -= 1
        for child in node.orelse:
            self.visit(child)

    def visit_Call(self, node: ast.Call) -> None:
        if self._in_loop:
            self._check(node)
        self.generic_visit(node)

    def _flag(self, node: ast.AST, msg: str) -> None:
        self.findings.append((node.lineno, node.col_offset, msg))

    def _check(self, node: ast.Call) -> None:
        got = classify_sync(node)
        if got is None:
            return
        kind, _desc = got
        name = dotted(node.func)
        if kind == "sync_func":
            self._flag(
                node,
                f"{name}() inside a loop in a hot module blocks on the "
                "device every iteration — batch the transfer or move the "
                "sync out of the loop",
            )
        elif kind == "block_until_ready":
            self._flag(
                node,
                ".block_until_ready() inside a loop in a hot module "
                "serializes host and device — sync once after the loop",
            )
        elif kind == "item":
            self._flag(
                node,
                ".item() inside a loop in a hot module is a scalar "
                "device->host transfer per iteration",
            )
        elif kind == "asarray":
            self._flag(
                node,
                f"{name}() on a possibly-device value inside a loop in "
                "a hot module forces a blocking device->host copy each "
                "iteration",
            )
        elif kind == "cast":
            self._flag(
                node,
                f"{name}() wrapped around a jax/jnp call inside a loop "
                "is a per-iteration device sync — keep the reduction on "
                "device and convert once after the loop",
            )


# --------------------------------------------------------------------------
# R3 — tracer escape


class _R3(ast.NodeVisitor):
    def __init__(self) -> None:
        self.findings: List[Tuple[int, int, str]] = []

    def run(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef) and _is_jit_decorated(node):
                self._scan_jitted(node)

    def _scan_jitted(self, fn: ast.FunctionDef) -> None:
        globals_declared: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                globals_declared.update(node.names)
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        self.findings.append(
                            (
                                t.lineno,
                                t.col_offset,
                                f"store to self.{t.attr} inside jit-traced "
                                f"'{fn.name}': the tracer outlives the trace "
                                "and poisons later calls — return the value "
                                "instead",
                            )
                        )
                    elif isinstance(t, ast.Name) and t.id in globals_declared:
                        self.findings.append(
                            (
                                t.lineno,
                                t.col_offset,
                                f"store to global '{t.id}' inside jit-traced "
                                f"'{fn.name}': tracers must not escape the "
                                "trace",
                            )
                        )
            elif isinstance(node, ast.Call):
                name = dotted(node.func)
                if name in ("threading.Thread", "Thread"):
                    self.findings.append(
                        (
                            node.lineno,
                            node.col_offset,
                            f"threading.Thread created inside jit-traced "
                            f"'{fn.name}': traced values crossing a thread "
                            "boundary are undefined — spawn threads outside "
                            "the traced function",
                        )
                    )


# --------------------------------------------------------------------------
# R4 — lock discipline in thread targets

_LOCK_CTORS = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "Lock",
    "RLock",
    "Condition",
}
_MUTABLE_CTORS = {
    "list",
    "dict",
    "set",
    "collections.defaultdict",
    "defaultdict",
    "collections.deque",
    "deque",
    "collections.Counter",
    "Counter",
}
_MUTATORS = {
    "append",
    "extend",
    "insert",
    "add",
    "update",
    "remove",
    "discard",
    "pop",
    "popitem",
    "popleft",
    "appendleft",
    "clear",
    "setdefault",
}


class _R4:
    def __init__(self) -> None:
        self.findings: List[Tuple[int, int, str]] = []

    def run(self, tree: ast.Module) -> None:
        module_locks: Set[str] = set()
        lock_attrs: Set[str] = set()
        module_mutables: Set[str] = set()
        module_names: Set[str] = set()
        funcs: Dict[str, ast.FunctionDef] = {}

        for node in tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if not isinstance(t, ast.Name):
                        continue
                    module_names.add(t.id)
                    val = node.value
                    vname = dotted(val.func) if isinstance(val, ast.Call) else None
                    if vname in _LOCK_CTORS:
                        module_locks.add(t.id)
                    elif isinstance(val, (ast.List, ast.Dict, ast.Set)) or (
                        vname in _MUTABLE_CTORS
                    ):
                        module_mutables.add(t.id)

        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef):
                funcs[node.name] = node
            elif isinstance(node, ast.Assign):
                # self._lock = threading.Lock() anywhere in the module
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                        and isinstance(node.value, ast.Call)
                        and dotted(node.value.func) in _LOCK_CTORS
                    ):
                        lock_attrs.add(t.attr)
            elif isinstance(node, ast.Global):
                # a module-level name rebound via `global` is mutable state
                # even when it's a plain scalar counter
                for name in node.names:
                    if name in module_names:
                        module_mutables.add(name)

        targets = self._thread_targets(tree)
        for tname in targets:
            fn = funcs.get(tname)
            if fn is not None:
                self._scan_target(
                    fn, module_mutables, module_locks, lock_attrs
                )

    def _thread_targets(self, tree: ast.Module) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if dotted(node.func) not in ("threading.Thread", "Thread"):
                continue
            for kw in node.keywords:
                if kw.arg != "target":
                    continue
                if isinstance(kw.value, ast.Name):
                    out.add(kw.value.id)
                elif isinstance(kw.value, ast.Attribute):
                    out.add(kw.value.attr)  # self._work -> method name
        return out

    def _scan_target(
        self,
        fn: ast.FunctionDef,
        mutables: Set[str],
        locks: Set[str],
        lock_attrs: Set[str],
    ) -> None:
        globals_declared: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                globals_declared.update(node.names)

        def held(expr: ast.AST) -> bool:
            if isinstance(expr, ast.Name) and expr.id in locks:
                return True
            if isinstance(expr, ast.Attribute) and expr.attr in lock_attrs:
                return True
            return False

        findings = self.findings

        def flag(node: ast.AST, what: str) -> None:
            findings.append(
                (
                    node.lineno,
                    node.col_offset,
                    f"thread target '{fn.name}' mutates module state "
                    f"{what} without holding a module Lock/Condition — "
                    "wrap the mutation in `with <lock>:`",
                )
            )

        def walk(node: ast.AST, locked: bool) -> None:
            if isinstance(node, ast.With):
                now_locked = locked or any(
                    held(item.context_expr) for item in node.items
                )
                for child in node.body:
                    walk(child, now_locked)
                return
            if not locked:
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for t in targets:
                        if (
                            isinstance(t, ast.Name)
                            and t.id in mutables
                            and t.id in globals_declared
                        ):
                            flag(t, f"'{t.id}'")
                        elif isinstance(t, ast.Subscript):
                            root = t.value
                            if (
                                isinstance(root, ast.Name)
                                and root.id in mutables
                            ):
                                flag(t, f"'{root.id}[...]'")
                elif isinstance(node, ast.Call):
                    f = node.func
                    if (
                        isinstance(f, ast.Attribute)
                        and f.attr in _MUTATORS
                        and isinstance(f.value, ast.Name)
                        and f.value.id in mutables
                    ):
                        flag(node, f"'{f.value.id}.{f.attr}()'")
            for child in ast.iter_child_nodes(node):
                walk(child, locked)

        for stmt in fn.body:
            walk(stmt, False)


# --------------------------------------------------------------------------
# R5 — swallowed exceptions

_LOGGY_PREFIXES = ("logging.", "logger.", "log.", "self.logger.", "self.log.")
_LOGGY_EXACT = {
    "warnings.warn",
    "traceback.print_exc",
    "traceback.print_exception",
    "print",
}


def _handler_is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare except
        return True
    if isinstance(t, ast.Name) and t.id == "Exception":
        return True
    if isinstance(t, ast.Tuple):
        return any(
            isinstance(el, ast.Name) and el.id == "Exception" for el in t.elts
        )
    return False


def _body_handles(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            if name is None:
                continue
            if name in _LOGGY_EXACT or name.startswith(_LOGGY_PREFIXES):
                return True
            # logging.getLogger(...).warning(...) style chains
            if isinstance(node.func, ast.Attribute) and node.func.attr in (
                "exception",
                "warning",
                "error",
                "critical",
            ):
                return True
    return False


class _R5(ast.NodeVisitor):
    def __init__(self) -> None:
        self.findings: List[Tuple[int, int, str]] = []

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if _handler_is_broad(node) and not _body_handles(node):
            what = "bare except" if node.type is None else "except Exception"
            self.findings.append(
                (
                    node.lineno,
                    node.col_offset,
                    f"{what} swallows errors silently — catch the specific "
                    "exception types, and log or re-raise",
                )
            )
        self.generic_visit(node)


# --------------------------------------------------------------------------
# R6 — stats mutation discipline (telemetry metrics facade)

#: ``X.stats.<method>(...)`` calls that mutate the mapping in place;
#: facade methods (inc/put/observe/ensure/merge/restore/fork) are the
#: sanctioned mutation surface and are not listed.
_R6_MUTATING_METHODS = {"update", "clear", "setdefault", "pop", "popitem"}


def _is_stats_base(node: ast.AST) -> bool:
    """True for the expressions R6 guards: an attribute named ``stats``
    (``ctx.stats``, ``self.stats``, ``rdv.stats``) or the bare parameter
    name ``stats`` the resilience/mesh helpers receive."""
    if isinstance(node, ast.Attribute) and node.attr == "stats":
        return True
    return isinstance(node, ast.Name) and node.id == "stats"


class _R6(ast.NodeVisitor):
    """Direct mutation of a stats mapping: subscript assignment /
    augmented assignment, or an in-place-mutating dict method call.
    Reads are fine; the telemetry facade methods are fine.  The rule is
    skipped inside ``telemetry/`` itself (the facade's own home)."""

    def __init__(self) -> None:
        self.findings: List[Tuple[int, int, str]] = []

    def _flag(self, node: ast.AST, what: str) -> None:
        self.findings.append(
            (
                node.lineno,
                node.col_offset,
                f"{what} mutates a stats dict directly — an unlocked "
                "read-modify-write loses updates when threads race; "
                "route it through the telemetry metrics facade "
                "(stats.inc/put/observe/ensure/merge)",
            )
        )

    def _check_target(self, target: ast.AST, node: ast.AST, what: str):
        # Only the assigned-to expression itself counts: recurse through
        # tuple/list/starred unpacking structure, then test whether the
        # leaf subscript's VALUE chain bottoms out at a stats base
        # (``ctx.stats["a"] = v``, ``ctx.stats["a"]["b"] = v``).  A
        # stats READ in the slice of an unrelated target
        # (``cache[ctx.stats["x"]] = v``) mutates ``cache``, not stats.
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._check_target(elt, node, what)
            return
        if isinstance(target, ast.Starred):
            self._check_target(target.value, node, what)
            return
        if isinstance(target, ast.Subscript):
            base = target.value
            while isinstance(base, ast.Subscript):
                base = base.value
            if _is_stats_base(base):
                self._flag(node, what)

    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            self._check_target(target, node, "subscript assignment")
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_target(node.target, node, "augmented assignment")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr in _R6_MUTATING_METHODS
            and _is_stats_base(f.value)
        ):
            self._flag(node, f".stats.{f.attr}() call")
        self.generic_visit(node)


def _r6_exempt(relpath: str) -> bool:
    """telemetry/ owns the facade; its internals mutate the underlying
    dict under the registry lock by design."""
    return "telemetry" in relpath.replace("\\", "/").split("/")


# --------------------------------------------------------------------------
# suppression comments

_SUPPRESS_RE = re.compile(
    r"#\s*jaxlint:\s*ignore\[([A-Za-z0-9_,\s]*)\]\s*(.*)$"
)


@dataclass
class _Suppression:
    line: int
    rules: Set[str]
    reason: str
    standalone: bool  # comment-only line: applies to the next line too


def scan_suppressions(
    source: str,
) -> Tuple[List[_Suppression], List[Tuple[int, int, str]]]:
    """All jaxlint suppression comments plus SUP findings for malformed
    ones (empty rule list or missing reason)."""
    sups: List[_Suppression] = []
    bad: List[Tuple[int, int, str]] = []
    try:
        tokens = tokenize.generate_tokens(StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if m is None:
                # only comments that *open with* an attempted directive are
                # malformed; prose mentioning the directive syntax is fine
                if re.match(r"#+\s*jaxlint\s*:", tok.string):
                    bad.append(
                        (
                            tok.start[0],
                            tok.start[1],
                            "unrecognized jaxlint marker; expected "
                            "'# jaxlint: ignore[RULE] reason'",
                        )
                    )
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            reason = m.group(2).strip()
            line_text = tok.line.strip()
            standalone = line_text.startswith("#")
            if not rules:
                bad.append(
                    (
                        tok.start[0],
                        tok.start[1],
                        "suppression names no rule: use "
                        "'# jaxlint: ignore[R2] reason'",
                    )
                )
                continue
            unknown = rules - set(RULE_DOCS)
            if unknown:
                bad.append(
                    (
                        tok.start[0],
                        tok.start[1],
                        f"suppression names unknown rule(s) "
                        f"{sorted(unknown)}",
                    )
                )
                continue
            if not reason:
                bad.append(
                    (
                        tok.start[0],
                        tok.start[1],
                        f"suppression of {sorted(rules)} lacks the "
                        "mandatory reason — say why the finding is safe",
                    )
                )
                continue
            sups.append(_Suppression(tok.start[0], rules, reason, standalone))
    except tokenize.TokenError:
        pass  # the ast parse will report the syntax problem
    return sups, bad


# --------------------------------------------------------------------------
# per-file driver


@dataclass
class FileReport:
    path: str
    findings: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    #: rules this scan actually executed against the file (per-file
    #: rules that applied, plus every cross-module pass when the
    #: whole-program driver ran) — the gate asserts registry parity on
    #: this set, so a rule silently dropping out of the default config
    #: is a test failure, not a quiet coverage loss
    checked: Set[str] = field(default_factory=set)


@dataclass
class FileAnalysis:
    """One file's parse + per-file raw findings, before suppression
    matching.  The whole-program pass (:mod:`.project`) reuses the
    parsed ``tree`` and the scanned suppressions, appends its
    cross-module raw findings, and finalizes — so each module is parsed
    exactly once no matter how many passes run over it."""

    path: str
    source: str
    tree: Optional[ast.Module]  # None on syntax error
    hot: bool
    #: (rule, line, col, message) from the per-file rules
    raw: List[Tuple[str, int, int, str]] = field(default_factory=list)
    sups: List["_Suppression"] = field(default_factory=list)
    bad_sups: List[Tuple[int, int, str]] = field(default_factory=list)
    #: rules whose absence of findings makes a marker provably stale
    checked: Set[str] = field(default_factory=set)
    #: set on syntax error; finalize short-circuits to this
    parse_finding: Optional[Finding] = None


def analyze_file(
    source: str,
    relpath: str,
    config: JaxlintConfig,
    hot: Optional[bool] = None,
    tree: Optional[ast.Module] = None,
) -> FileAnalysis:
    """Parses (or reuses ``tree``) and runs the per-file rules, returning
    the raw, un-suppressed analysis.  ``hot`` overrides the config's
    hot-module glob match (fixture tests exercise R2 on paths outside
    the configured globs)."""
    is_hot = config.is_hot(relpath) if hot is None else hot
    fa = FileAnalysis(path=relpath, source=source, tree=tree, hot=is_hot)
    if fa.tree is None:
        try:
            fa.tree = ast.parse(source)
        except SyntaxError as e:
            fa.parse_finding = Finding(
                relpath, e.lineno or 1, 0, PARSE_RULE,
                f"syntax error: {e.msg}",
            )
            return fa

    if "R1" in config.rules:
        r1 = _R1()
        r1.collect(fa.tree)
        r1.visit(fa.tree)
        fa.raw += [("R1", *f) for f in r1.findings]
    if "R2" in config.rules and is_hot:
        r2 = _R2()
        r2.visit(fa.tree)
        fa.raw += [("R2", *f) for f in r2.findings]
    if "R3" in config.rules:
        r3 = _R3()
        r3.run(fa.tree)
        fa.raw += [("R3", *f) for f in r3.findings]
    if "R4" in config.rules:
        r4 = _R4()
        r4.run(fa.tree)
        fa.raw += [("R4", *f) for f in r4.findings]
    if "R5" in config.rules:
        r5 = _R5()
        r5.visit(fa.tree)
        fa.raw += [("R5", *f) for f in r5.findings]
    if "R6" in config.rules and not _r6_exempt(relpath):
        r6 = _R6()
        r6.visit(fa.tree)
        fa.raw += [("R6", *f) for f in r6.findings]

    fa.sups, fa.bad_sups = scan_suppressions(source)
    # Unused-suppression eligibility: only rules this scan actually
    # executed count (R2 is skipped entirely in non-hot files, so its
    # markers can't be judged there; cross-module rule markers are only
    # judged when the whole-program pass runs and extends this set).
    fa.checked = {r for r in config.rules if r in ("R1", "R3", "R4", "R5")}
    if "R2" in config.rules and is_hot:
        fa.checked.add("R2")
    if "R6" in config.rules and not _r6_exempt(relpath):
        fa.checked.add("R6")
    return fa


def finalize_report(
    fa: FileAnalysis,
    extra_raw: Sequence[Tuple[str, int, int, str]] = (),
    extra_checked: Sequence[str] = (),
) -> FileReport:
    """Matches raw findings (per-file + ``extra_raw`` from cross-module
    passes) against the file's suppressions, and reports stale markers
    for every rule in ``checked`` ∪ ``extra_checked``."""
    report = FileReport(
        path=fa.path, checked=set(fa.checked) | set(extra_checked)
    )
    if fa.parse_finding is not None:
        report.findings.append(fa.parse_finding)
        return report

    by_line: Dict[int, List[_Suppression]] = {}
    for s in fa.sups:
        by_line.setdefault(s.line, []).append(s)
        if s.standalone:
            by_line.setdefault(s.line + 1, []).append(s)

    raw = list(fa.raw) + list(extra_raw)
    used: Set[Tuple[int, str]] = set()  # (id(suppression), rule) pairs
    for rule, line, col, msg in sorted(raw, key=lambda f: (f[1], f[2], f[0])):
        finding = Finding(fa.path, line, col, rule, msg)
        matching = [s for s in by_line.get(line, ()) if rule in s.rules]
        if matching:
            for s in matching:
                used.add((id(s), rule))
            report.suppressed.append(finding)
        else:
            report.findings.append(finding)

    # Unused-suppression detection: a well-formed marker naming a rule
    # that produced NO finding on its line(s) is stale — the hazard it
    # justified is gone (or moved), and a stale marker left behind would
    # silently swallow the next, different finding at that line.
    checked = set(fa.checked) | set(extra_checked)
    for s in fa.sups:
        stale = sorted(
            r for r in s.rules if r in checked and (id(s), r) not in used
        )
        if stale:
            report.findings.append(
                Finding(
                    fa.path,
                    s.line,
                    0,
                    SUPPRESSION_RULE,
                    f"unused suppression: no {', '.join(stale)} finding on "
                    "this line — the justified hazard is gone; remove the "
                    "stale '# jaxlint: ignore' marker",
                )
            )

    for line, col, msg in fa.bad_sups:
        report.findings.append(
            Finding(fa.path, line, col, SUPPRESSION_RULE, msg)
        )
    report.findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return report


def lint_source(
    source: str,
    relpath: str,
    config: JaxlintConfig,
    hot: Optional[bool] = None,
) -> FileReport:
    """Lints one file's source with the per-file rules (no cross-module
    analysis; see :func:`sboxgates_tpu.analysis.project.lint_project`)."""
    return finalize_report(analyze_file(source, relpath, config, hot=hot))
