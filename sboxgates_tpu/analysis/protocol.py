"""R10: replicated-protocol divergence (whole-program pass).

The replicated degradation protocol (PR 7) only works if every process
makes the same sequence of agreement calls: ``breach_verdict``,
``journal_seq_check``, ``run_config_check``, the ``_kv_exchange``
primitive, device collectives, and the replicated dispatch/retry
entries.  A call path that reaches one of those sites from only ONE
side of a rank-gated branch (``jax.process_index()``, ``is_primary()``,
or a local derived from them) means the primary blocks on an agreement
the secondaries never join — the pod hangs at the next barrier, or the
launch counts diverge and the runtime deadlocks inside a collective.

Three checks:

* **one-sided agreement** — an ``ast.If`` whose test derives from a
  rank source where exactly one side (lexically, or transitively
  through the call graph) reaches an agreement site.  Guard style
  (``if rank != 0: return`` followed by agreement code) is handled by
  treating the statements after a terminating body as the else side.
* **collective in a host-agreement window** — a device collective
  issued in a function that also speaks the coordination-service
  protocol directly (``wait_at_barrier`` / key-value ops).  The PR 7
  breach path exists precisely because a wedged device collective must
  be escaped via the *host* network; nesting one inside the host
  window re-introduces the deadlock the escape hatch is for.
* **rank-gated re-dispatch** — covered by the first check because the
  replicated dispatch/retry entries are agreement sites: re-issuing a
  sharded sweep from one rank only breaks launch-count lockstep.

Branches on replicated predicates (``process_count() <= 1`` and
friends) are NOT rank tests: every process takes the same side, so
there is nothing to diverge.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import ProjectGraph, iter_body_nodes
from .config import JaxlintConfig
from .rules import dotted

RawFinding = Tuple[str, int, int, str]

#: Device collectives: these resolve on the accelerator network, not the
#: host network, so they deadlock differently (and harder).
_DEVICE_COLLECTIVES = frozenset(
    {
        "process_allgather",
        "broadcast_one_to_all",
        "all_gather",
        "all_reduce",
        "psum",
        "pmean",
        "pmax",
        "pmin",
    }
)

#: Direct coordination-service protocol calls: a function issuing these
#: is inside a host-agreement window.
_HOST_WINDOW_TAILS = frozenset(
    {"wait_at_barrier", "blocking_key_value_get", "key_value_set"}
)


def _tail(name: Optional[str]) -> Optional[str]:
    return name.rsplit(".", 1)[-1] if name else None


def _rank_locals(fn_node: ast.AST, rank_sources: Set[str]) -> Set[str]:
    """Local names derived (transitively, via assignments in this
    function's own body) from a rank-source call — ``rank =
    jax.process_index()`` makes ``rank`` a rank-shaped value."""
    assigns: List[Tuple[Set[str], ast.AST]] = []
    for node in iter_body_nodes(fn_node):
        if isinstance(node, ast.Assign):
            names = {
                t.id for t in node.targets if isinstance(t, ast.Name)
            }
            if names:
                assigns.append((names, node.value))
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if isinstance(node.target, ast.Name):
                assigns.append(({node.target.id}, node.value))
        elif isinstance(node, ast.NamedExpr):
            if isinstance(node.target, ast.Name):
                assigns.append(({node.target.id}, node.value))
    derived: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for names, value in assigns:
            if names <= derived:
                continue
            if _mentions_rank(value, rank_sources, derived):
                derived |= names
                changed = True
    return derived


def _mentions_rank(expr: ast.AST, rank_sources: Set[str],
                   rank_locals: Set[str]) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            if _tail(dotted(node.func)) in rank_sources:
                return True
        elif isinstance(node, ast.Attribute):
            if node.attr in rank_sources:
                return True
        elif isinstance(node, ast.Name):
            if node.id in rank_sources or node.id in rank_locals:
                return True
    return False


def _terminates(stmts: List[ast.stmt]) -> bool:
    """Does this block unconditionally leave the enclosing block?"""
    if not stmts:
        return False
    return isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break)
    )


def _child_blocks(st: ast.stmt) -> List[List[ast.stmt]]:
    blocks: List[List[ast.stmt]] = []
    for attr in ("body", "orelse", "finalbody"):
        blk = getattr(st, attr, None)
        if blk and not isinstance(
            st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            blocks.append(blk)
    for h in getattr(st, "handlers", ()) or ():
        blocks.append(h.body)
    return blocks


class _FuncProtocol:
    """Per-function R10 state: rank-derived locals plus the (line, col)
    -> callee index used to resolve transitive agreement reach."""

    def __init__(self, graph: ProjectGraph, fkey: str,
                 config: JaxlintConfig,
                 reach: Dict[str, str]) -> None:
        self.fi = graph.functions[fkey]
        self.agreement = set(config.agreement_sites)
        self.rank_sources = set(config.rank_sources)
        self.reach = reach
        self.calls = graph.call_index(fkey)
        self.locals = _rank_locals(self.fi.node, self.rank_sources)

    def is_rank_test(self, test: ast.AST) -> bool:
        return _mentions_rank(test, self.rank_sources, self.locals)

    def side_events(self, stmts: List[ast.stmt]) -> Set[str]:
        """Agreement sites reached from this branch side: direct calls
        whose name tail is an agreement site, plus calls into functions
        the reach fixpoint marked as transitively reaching one."""
        events: Set[str] = set()
        stack: List[ast.AST] = list(stmts)
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(node, ast.Call):
                t = _tail(dotted(node.func))
                if t in self.agreement:
                    events.add(t)
                else:
                    for callee in self.calls.get(
                        (node.lineno, node.col_offset), ()
                    ):
                        w = self.reach.get(callee)
                        if w is not None:
                            events.add(w)
            stack.extend(ast.iter_child_nodes(node))
        return events


def _walk_blocks(scan: _FuncProtocol, stmts: List[ast.stmt],
                 out: List[RawFinding]) -> None:
    for i, st in enumerate(stmts):
        if isinstance(st, ast.If) and scan.is_rank_test(st.test):
            body_events = scan.side_events(st.body)
            if st.orelse:
                else_events = scan.side_events(st.orelse)
                shape = "the other side"
            elif _terminates(st.body):
                # guard style: `if rank != 0: return` — the code after
                # the guard is what the surviving side runs.
                else_events = scan.side_events(stmts[i + 1:])
                shape = "the path past the guard"
            else:
                else_events = set()
                shape = "the fall-through side"
            one_sided: Set[str] = set()
            if body_events and not else_events:
                one_sided, side = body_events, "one side"
            elif else_events and not body_events:
                one_sided, side = else_events, shape
            if one_sided:
                names = ", ".join(sorted(one_sided))
                out.append(
                    (
                        "R10",
                        st.lineno,
                        st.col_offset,
                        f"rank-gated branch reaches agreement site(s) "
                        f"{names} from {side} only — every process must "
                        "issue the same agreement/collective sequence "
                        "(launch-count lockstep), or acknowledge with "
                        "ignore[R10] and a reason",
                    )
                )
        for blk in _child_blocks(st):
            _walk_blocks(scan, blk, out)


def _host_window_findings(fi, out: List[RawFinding]) -> None:
    has_window = any(
        isinstance(n, ast.Call)
        and _tail(dotted(n.func)) in _HOST_WINDOW_TAILS
        for n in iter_body_nodes(fi.node)
    )
    if not has_window:
        return
    for node in iter_body_nodes(fi.node):
        if not isinstance(node, ast.Call):
            continue
        t = _tail(dotted(node.func))
        if t in _DEVICE_COLLECTIVES:
            out.append(
                (
                    "R10",
                    node.lineno,
                    node.col_offset,
                    f"device collective {t} issued inside a "
                    "host-agreement window (this function speaks the "
                    "coordination-service protocol directly) — a wedged "
                    "collective can no longer be escaped via the host "
                    "network, or acknowledge with ignore[R10] and a "
                    "reason",
                )
            )


def run_r10(graph: ProjectGraph,
            config: JaxlintConfig) -> Dict[str, List[RawFinding]]:
    """R10 findings per project-relative path."""
    agreement = set(config.agreement_sites)
    seeds: Dict[str, str] = {}
    for fkey in sorted(graph.functions):
        fi = graph.functions[fkey]
        for node in iter_body_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            t = _tail(dotted(node.func))
            if t in agreement:
                w = f"{t} (via {fi.path}:{node.lineno})"
                if fkey not in seeds or w < seeds[fkey]:
                    seeds[fkey] = w
    reach = graph.reach_witness(seeds)

    out: Dict[str, List[RawFinding]] = {}
    for fkey in sorted(graph.functions):
        fi = graph.functions[fkey]
        found: List[RawFinding] = []
        scan = _FuncProtocol(graph, fkey, config, reach)
        body = list(getattr(fi.node, "body", ()))
        _walk_blocks(scan, body, found)
        _host_window_findings(fi, found)
        if found:
            out.setdefault(fi.path, []).extend(found)
    return out
