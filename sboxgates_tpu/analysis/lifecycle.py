"""R15: resource lifecycle (whole-program pass).

The drain contract (PR 18) is a lifecycle fact: the listener socket is
closed before the orchestrator drains, the accept thread is joined,
and a failed bind never leaks an ephemeral listener.  The runtime
tests exercise the happy path; this pass makes the discipline a static
fact for every ``resource_ctors`` acquisition (sockets, listener
servers, threads, temp files) in the project:

* an acquisition bound to a local must be released on ALL exit paths —
  a ``with`` item, a release call (``close``/``shutdown``/``join``/…)
  on it inside a ``finally`` block, ownership transfer (returned, or
  passed as an argument to another call), or registration in a
  declared teardown registry (``teardown_registries``: the CLI's
  ``drain_hooks``, ``_teardown``, ``atexit.register``).  A straight-
  line ``close()`` with no ``finally`` does NOT count: the statement
  between acquire and close that raises is exactly the leaked-listener
  bug;
* an acquisition stored on ``self`` transfers ownership to the
  instance — accepted only when some method of the class actually
  releases that attribute (directly, through a local/loop variable
  derived from it, or by handing it to a teardown registry);
* an acquisition that is constructed and discarded
  (``Thread(...).start()``) can never be released by anyone — flagged
  at the constructor.

Daemon threads (``Thread(..., daemon=True)``) are exempt: their
lifecycle is the process's, by declaration.  A project class derived
from a declared constructor (``class Server(ThreadingHTTPServer)``)
is itself a resource constructor.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import ProjectGraph, iter_body_nodes
from .config import JaxlintConfig
from .rules import dotted
from .trustflow import site_name

RawFinding = Tuple[str, int, int, str]

#: Method tails that release/retire a resource.
_RELEASE_TAILS = frozenset(
    {
        "close", "shutdown", "server_close", "stop", "join", "cancel",
        "terminate", "release", "unlink", "remove", "cleanup", "kill",
    }
)


def _is_daemon_ctor(node: ast.Call) -> bool:
    for kw in node.keywords:
        if (
            kw.arg == "daemon"
            and isinstance(kw.value, ast.Constant)
            and kw.value.value is True
        ):
            return True
    return False


def _registry_call(node: ast.Call, registries: List[str]) -> bool:
    """Is this call a teardown-registry registration?  A bare entry
    ("drain_hooks") matches anywhere in the dotted chain (the
    ``drain_hooks.append(...)`` receiver); a dotted entry
    ("atexit.register") uses the declared-site semantics."""
    name = dotted(node.func)
    if name is None:
        return False
    parts = name.split(".")
    for entry in registries:
        if "." in entry:
            ehead, _, etail = entry.rpartition(".")
            if parts[-1] == etail and ehead in parts[:-1]:
                return True
        elif entry in parts:
            return True
    return False


def _mentions_name(expr: ast.AST, var: str) -> bool:
    return any(
        isinstance(n, ast.Name) and n.id == var
        for n in ast.walk(expr)
    )


def _mentions_attr(expr: ast.AST, attr: str) -> bool:
    return any(
        isinstance(n, ast.Attribute) and n.attr == attr
        for n in ast.walk(expr)
    )


def _release_of_var(node: ast.Call, var: str) -> bool:
    """``var.close()`` (receiver) or ``os.close(var)`` (argument of a
    release-tail call)."""
    name = dotted(node.func)
    if name is None:
        return False
    parts = name.split(".")
    if parts[-1] not in _RELEASE_TAILS:
        return False
    if var in parts[:-1]:
        return True
    return any(
        _mentions_name(a, var)
        for a in list(node.args) + [kw.value for kw in node.keywords]
    )


def derived_ctors(graph: ProjectGraph,
                  config: JaxlintConfig) -> List[str]:
    """resource_ctors plus every project class (transitively) derived
    from one — ``class Server(ThreadingHTTPServer)`` is a listener
    constructor too."""
    ctor_tails = {e.rsplit(".", 1)[-1] for e in config.resource_ctors}
    bases: Dict[str, Set[str]] = {}
    for mname in sorted(graph.modules):
        tree = graph.modules[mname].tree
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                tails = set()
                for b in node.bases:
                    d = dotted(b)
                    if d is not None:
                        tails.add(d.rsplit(".", 1)[-1])
                bases.setdefault(node.name, set()).update(tails)
    derived: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for cname in sorted(bases):
            if cname in derived:
                continue
            if bases[cname] & (ctor_tails | derived):
                derived.add(cname)
                changed = True
    return list(config.resource_ctors) + sorted(derived)


class _Acquisition:
    def __init__(self, node: ast.Call, entry: str) -> None:
        self.node = node
        self.entry = entry
        self.names: Set[str] = set()  # locals bound to the resource
        self.self_attrs: Set[str] = set()  # self attrs bound directly


def _class_releases(graph: ProjectGraph, module: str, cls: str,
                    attr: str, registries: List[str]) -> bool:
    """Does any method of (module, cls) release ``self.<attr>`` —
    directly, via a local derived from it, or by registering it in a
    teardown registry?"""
    for fkey in sorted(graph.functions):
        fi = graph.functions[fkey]
        if fi.module != module or fi.cls != cls:
            continue
        derived: Set[str] = set()
        for node in iter_body_nodes(fi.node):
            if isinstance(node, ast.Assign):
                if _mentions_attr(node.value, attr):
                    for t in node.targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                derived.add(n.id)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                if _mentions_attr(node.iter, attr):
                    for n in ast.walk(node.target):
                        if isinstance(n, ast.Name):
                            derived.add(n.id)
        for node in iter_body_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name is not None:
                parts = name.split(".")
                if parts[-1] in _RELEASE_TAILS and (
                    attr in parts[:-1]
                    or (set(parts[:-1]) & derived)
                ):
                    return True
            if _registry_call(node, registries):
                # ast.walk descends into lambda bodies, so a
                # `registry.append(lambda: self.x.close())` counts.
                for a in (
                    list(node.args)
                    + [kw.value for kw in node.keywords]
                ):
                    if _mentions_attr(a, attr):
                        return True
    return False


class _FuncLife:
    """R15 scan of one function body."""

    def __init__(self, graph: ProjectGraph, fkey: str,
                 config: JaxlintConfig, ctors: List[str]) -> None:
        self.graph = graph
        self.fi = graph.functions[fkey]
        self.config = config
        self.ctors = ctors

    def _ctor_calls(self) -> List[Tuple[ast.Call, str]]:
        out = []
        for node in iter_body_nodes(self.fi.node):
            if isinstance(node, ast.Call):
                entry = site_name(node, self.ctors)
                if entry is not None and not _is_daemon_ctor(node):
                    out.append((node, entry))
        out.sort(key=lambda p: (p[0].lineno, p[0].col_offset))
        return out

    def findings(self) -> List[RawFinding]:
        ctor_calls = self._ctor_calls()
        if not ctor_calls:
            return []
        fn = self.fi.node
        with_ids: Set[int] = set()
        arg_ids: Set[int] = set()
        return_ids: Set[int] = set()
        acquisitions: Dict[int, _Acquisition] = {}
        ctor_ids = {id(c) for c, _ in ctor_calls}

        for node in iter_body_nodes(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    for n in ast.walk(item.context_expr):
                        with_ids.add(id(n))
            elif isinstance(node, ast.Call):
                for a in (
                    list(node.args)
                    + [kw.value for kw in node.keywords]
                ):
                    for n in ast.walk(a):
                        if id(n) in ctor_ids:
                            arg_ids.add(id(n))
            elif isinstance(node, ast.Return) and node.value is not None:
                for n in ast.walk(node.value):
                    if id(n) in ctor_ids:
                        return_ids.add(id(n))
            elif isinstance(node, (ast.Assign, ast.AnnAssign,
                                   ast.NamedExpr)):
                value = node.value
                if value is None:
                    continue
                held = [
                    n for n in ast.walk(value) if id(n) in ctor_ids
                ]
                if not held:
                    continue
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for c in held:
                    acq = acquisitions.setdefault(
                        id(c),
                        _Acquisition(
                            c,
                            next(e for cc, e in ctor_calls if cc is c),
                        ),
                    )
                    for t in targets:
                        if isinstance(t, ast.Name):
                            acq.names.add(t.id)
                        elif isinstance(t, ast.Tuple):
                            for el in t.elts:
                                if isinstance(el, ast.Name):
                                    acq.names.add(el.id)
                        elif (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            acq.self_attrs.add(t.attr)

        out: List[RawFinding] = []
        for call, entry in ctor_calls:
            if id(call) in with_ids or id(call) in arg_ids:
                continue  # with-managed, or ownership handed to a call
            if id(call) in return_ids:
                continue  # ownership transfer to the caller
            acq = acquisitions.get(id(call))
            if acq is None:
                out.append(
                    (
                        "R15",
                        call.lineno,
                        call.col_offset,
                        f"resource {entry} is constructed and "
                        "discarded — nothing can ever release it; "
                        "bind it to an owner with a teardown path or "
                        "acknowledge with ignore[R15] and a reason",
                    )
                )
                continue
            reason = self._unreleased(acq)
            if reason is not None:
                out.append(
                    (
                        "R15",
                        call.lineno,
                        call.col_offset,
                        f"resource {entry} is not released on all "
                        f"exit paths ({reason}) — close it in a "
                        "finally/with, return it, register it in a "
                        "teardown registry "
                        f"({', '.join(self.config.teardown_registries)})"
                        ", or store it on an owner with a teardown "
                        "method; or acknowledge with ignore[R15] and "
                        "a reason",
                    )
                )
        return out

    def _aliases(self, names: Set[str]) -> Set[str]:
        """``names`` plus locals derived from them: assignment targets
        whose RHS mentions one, loop variables iterating over one."""
        fn = self.fi.node
        out = set(names)
        changed = bool(out)
        while changed:
            changed = False
            for node in iter_body_nodes(fn):
                src: Optional[ast.AST] = None
                tgt_names: Set[str] = set()
                if isinstance(node, ast.Assign):
                    src = node.value
                    for t in node.targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                tgt_names.add(n.id)
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    src = node.iter
                    for n in ast.walk(node.target):
                        if isinstance(n, ast.Name):
                            tgt_names.add(n.id)
                if src is None or tgt_names <= out:
                    continue
                if any(_mentions_name(src, v) for v in out):
                    out |= tgt_names
                    changed = True
        return out

    def _unreleased(self, acq: _Acquisition) -> Optional[str]:
        """None when the acquisition is safely released; otherwise a
        short reason naming what is missing."""
        fn = self.fi.node
        # Aliases (loop vars over a thread list, re-bound handles) are
        # honored for RELEASE sites only; ownership-transfer rules use
        # the directly-bound names, so a derived scalar passed to an
        # unrelated call does not launder the resource.
        aliases = self._aliases(acq.names)
        for var in sorted(aliases):
            for node in iter_body_nodes(fn):
                if isinstance(node, ast.Try):
                    # release inside finally covers every exit path
                    for st in node.finalbody:
                        for n in ast.walk(st):
                            if isinstance(n, ast.Call) and (
                                _release_of_var(n, var)
                            ):
                                return None
                elif isinstance(node, (ast.With, ast.AsyncWith)):
                    # `with s:` releases on all paths by construction
                    for item in node.items:
                        if _mentions_name(item.context_expr, var):
                            return None
                elif isinstance(node, ast.Call) and _registry_call(
                    node, self.config.teardown_registries
                ):
                    args = (
                        list(node.args)
                        + [kw.value for kw in node.keywords]
                    )
                    if any(_mentions_name(a, var) for a in args):
                        return None
        for var in sorted(acq.names):
            # ownership transfers
            for node in iter_body_nodes(fn):
                if isinstance(node, ast.Return) and node.value is not None:
                    if _mentions_name(node.value, var):
                        return None
                elif isinstance(node, ast.Call):
                    if _registry_call(
                        node, self.config.teardown_registries
                    ):
                        continue  # judged above, for every alias
                    args = (
                        list(node.args)
                        + [kw.value for kw in node.keywords]
                    )
                    # handed to another owner
                    if any(_mentions_name(a, var) for a in args):
                        return None
                elif isinstance(node, ast.Assign):
                    # re-binding to an attribute transfers ownership;
                    # self attrs additionally require a class teardown
                    for t in node.targets:
                        tgt = t
                        if isinstance(tgt, ast.Subscript):
                            tgt = tgt.value
                        if isinstance(
                            tgt, ast.Attribute
                        ) and _mentions_name(node.value, var):
                            if (
                                isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"
                            ):
                                acq.self_attrs.add(tgt.attr)
                            else:
                                return None
        for attr in sorted(acq.self_attrs):
            if self.fi.cls is not None and _class_releases(
                self.graph, self.fi.module, self.fi.cls, attr,
                self.config.teardown_registries,
            ):
                return None
            return (
                f"stored on self.{attr} but no method of "
                f"{self.fi.cls or 'its class'} releases it"
            )
        if acq.names:
            names = "/".join(sorted(acq.names))
            return (
                f"'{names}' has no finally-guarded release, return, "
                "or registry hand-off"
            )
        return "no release path found"


def run_r15(graph: ProjectGraph,
            config: JaxlintConfig) -> Dict[str, List[RawFinding]]:
    """R15 findings per project-relative path."""
    ctors = derived_ctors(graph, config)
    out: Dict[str, List[RawFinding]] = {}
    for fkey in sorted(graph.functions):
        scan = _FuncLife(graph, fkey, config, ctors)
        found = scan.findings()
        if found:
            out.setdefault(scan.fi.path, []).extend(found)
    return out
