"""R14: admission-order discipline (whole-program pass).

The PR 18 front door's robustness spine is an ORDER: authenticate and
rate-limit before anything else, quota before any fresh-admission
effect, the fsync'd admission-journal append before the orchestrator
enqueue and before the client's 202.  The runtime tests pin that order
by injecting faults between the steps; this pass pins it structurally:

* every **effectful call** (``effect_sites``: orchestrator
  enqueue/join, durable admission records) in a ``handler_modules``
  function must be *dominated* by an auth/rate site (``auth_sites``)
  AND a quota site (``quota_sites``) — the check ran on every path to
  the effect, not merely on some path;
* every **2xx admission response** (a ``response_sites`` call with a
  constant 201/202 status argument) must be dominated by a journal
  append (``journal_sites``) — a crash after an unjournaled 202 loses
  a job the client was told is admitted.

Dominance is computed by a lexical walk over the handler body (the R10
branch machinery): a site inside one arm of an ``if`` does not
dominate the code after it, a site in the test does, a terminating arm
passes the other arm's state through, a loop body dominates nothing
after the loop (zero iterations).  A check hoisted into a shared
helper still counts: the R10 transitive-reach witness machinery marks
every function that reaches a declared site, so ``self._auth(h)``
establishes auth because ``_auth`` reaches ``authenticate``.  Helpers
called only from dominated positions inherit their callers' state (a
bounded interprocedural entry-state fixpoint over the call graph), so
``_store_sbox`` — called only after auth+quota in ``_post_job`` — is
not re-flagged.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import ProjectGraph, iter_body_nodes
from .config import JaxlintConfig
from .trustflow import site_name

RawFinding = Tuple[str, int, int, str]

#: Dominance flag classes.
_AUTH, _QUOTA, _JOURNAL = "auth", "quota", "journal"


def _reach_maps(graph: ProjectGraph, config: JaxlintConfig
                ) -> Dict[str, Dict[str, str]]:
    """flag class -> {function key -> witness} for every function that
    (transitively) issues a call matching that class's sites — the R10
    reach machinery, seeded for all three classes in ONE body scan."""
    site_lists = {
        _AUTH: config.auth_sites,
        _QUOTA: config.quota_sites,
        _JOURNAL: config.journal_sites,
    }
    seeds: Dict[str, Dict[str, str]] = {f: {} for f in site_lists}
    for fkey in sorted(graph.functions):
        fi = graph.functions[fkey]
        for node in iter_body_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            for flag, sites in site_lists.items():
                entry = site_name(node, sites)
                if entry is not None:
                    w = f"{entry} (via {fi.path}:{node.lineno})"
                    cur = seeds[flag].get(fkey)
                    if cur is None or w < cur:
                        seeds[flag][fkey] = w
    return {
        flag: graph.reach_witness(seeds[flag]) for flag in site_lists
    }


def _const_status(node: ast.Call) -> Optional[int]:
    """The first constant-int argument of a response call (the status
    code position), or None when the status is not a literal."""
    for arg in list(node.args) + [kw.value for kw in node.keywords]:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, int):
            return int(arg.value)
    return None


class _FuncOrder:
    """One dominance walk over one handler function."""

    def __init__(self, graph: ProjectGraph, fkey: str,
                 config: JaxlintConfig,
                 reach: Dict[str, Dict[str, str]],
                 entry_flags: Set[str]) -> None:
        self.fi = graph.functions[fkey]
        self.config = config
        self.reach = reach
        self.calls = graph.call_index(fkey)
        self.entry_flags = set(entry_flags)
        self.findings: List[RawFinding] = []
        #: callee key -> intersection of flags held at its call sites
        #: (the entry-state propagation the fixpoint consumes).
        self.callsite_flags: Dict[str, Set[str]] = {}
        #: flag -> first establishment witness anywhere in the body
        #: (names the undominated path in the finding message).
        self.flag_sites: Dict[str, str] = {}

    # -- event classification ---------------------------------------------

    def _establishes(self, node: ast.Call) -> Set[str]:
        """Flag classes this call establishes, directly or because a
        resolved callee transitively reaches a declared site."""
        got: Set[str] = set()
        for flag, sites in (
            (_AUTH, self.config.auth_sites),
            (_QUOTA, self.config.quota_sites),
            (_JOURNAL, self.config.journal_sites),
        ):
            entry = site_name(node, sites)
            witness = f"{entry} at line {node.lineno}" if entry else None
            if witness is None:
                for callee in self.calls.get(
                    (node.lineno, node.col_offset), ()
                ):
                    w = self.reach[flag].get(callee)
                    if w is not None:
                        witness = w
                        break
            if witness is not None:
                got.add(flag)
                self.flag_sites.setdefault(flag, witness)
        return got

    def _calls_in(self, node: ast.AST) -> List[ast.Call]:
        """Call nodes evaluated when this statement/expression runs —
        nested defs and lambdas excluded (they run later, elsewhere)."""
        out: List[ast.Call] = []
        stack: List[ast.AST] = [node]
        while stack:
            n = stack.pop()
            if isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                    ast.ClassDef)
            ):
                continue
            if isinstance(n, ast.Call):
                out.append(n)
            stack.extend(ast.iter_child_nodes(n))
        out.sort(key=lambda c: (c.lineno, c.col_offset))
        return out

    def _events(self, node: ast.AST, flags: Set[str]) -> Set[str]:
        """Process every call evaluated by this statement: record
        callee entry states (pre-statement flags), establish new flags,
        then judge effect/response calls against the establisher-
        augmented flag set (an append-and-ack one-liner is in order)."""
        calls = self._calls_in(node)
        pre = set(flags)
        established: Set[str] = set()
        for c in calls:
            for callee in self.calls.get((c.lineno, c.col_offset), ()):
                cur = self.callsite_flags.get(callee)
                if cur is None:
                    self.callsite_flags[callee] = set(pre)
                else:
                    cur &= pre
            established |= self._establishes(c)
        held = flags | established
        for c in calls:
            self._judge(c, held)
        return established

    def _judge(self, node: ast.Call, flags: Set[str]) -> None:
        effect = site_name(node, self.config.effect_sites)
        if effect is not None:
            missing = [f for f in (_AUTH, _QUOTA) if f not in flags]
            if missing:
                hints = [
                    f"{self.flag_sites[f]} runs on another path"
                    if f in self.flag_sites
                    else f"no {f} site on any path"
                    for f in missing
                ]
                self.findings.append(
                    (
                        "R14",
                        node.lineno,
                        node.col_offset,
                        f"effectful call {effect} is not dominated by "
                        f"the {'/'.join(missing)} check site(s) "
                        f"({'; '.join(hints)}) — admission order is "
                        "auth -> quota -> fsync'd journal -> effect "
                        "(PR 18 contract); hoist the check or "
                        "acknowledge with ignore[R14] and a reason",
                    )
                )
        resp = site_name(node, self.config.response_sites)
        if resp is not None:
            status = _const_status(node)
            if status in (201, 202) and _JOURNAL not in flags:
                hint = (
                    f"{self.flag_sites[_JOURNAL]} runs on another path"
                    if _JOURNAL in self.flag_sites
                    else "no journal append on any path"
                )
                self.findings.append(
                    (
                        "R14",
                        node.lineno,
                        node.col_offset,
                        f"{status} admission response ({resp}) is not "
                        "dominated by the fsync'd admission-journal "
                        f"append ({hint}) — a crash after this "
                        "response loses a job the client was told is "
                        "admitted; append first or acknowledge with "
                        "ignore[R14] and a reason",
                    )
                )

    # -- the dominance walk -------------------------------------------------

    def run(self) -> None:
        self._scan(list(getattr(self.fi.node, "body", ())),
                   set(self.entry_flags))

    def _scan(self, stmts: List[ast.stmt],
              flags: Set[str]) -> Optional[Set[str]]:
        """Walk one block; returns the exit flag set, or None when the
        block unconditionally leaves the enclosing scope."""
        flags = set(flags)
        for st in stmts:
            if isinstance(
                st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            if isinstance(st, ast.If):
                flags |= self._events(st.test, flags)
                body_exit = self._scan(st.body, flags)
                else_exit = self._scan(st.orelse, flags)
                if body_exit is None and else_exit is None:
                    return None
                if body_exit is None:
                    flags = else_exit
                elif else_exit is None:
                    flags = body_exit
                else:
                    flags = body_exit & else_exit
            elif isinstance(st, ast.Try):
                body_exit = self._scan(st.body, flags)
                exits = []
                if body_exit is not None:
                    if st.orelse:
                        body_exit = self._scan(st.orelse, body_exit)
                    if body_exit is not None:
                        exits.append(body_exit)
                for h in st.handlers:
                    # a handler may catch BEFORE any body flag landed
                    h_exit = self._scan(h.body, flags)
                    if h_exit is not None:
                        exits.append(h_exit)
                after = (
                    set.intersection(*exits) if exits else None
                )
                if st.finalbody:
                    fin = self._scan(
                        st.finalbody,
                        after if after is not None else flags,
                    )
                    if fin is None or after is None:
                        return None
                    flags = fin
                else:
                    if after is None:
                        return None
                    flags = after
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                flags |= self._events(st.iter, flags)
                self._scan(st.body, flags)  # may run zero times
                self._scan(st.orelse, flags)
            elif isinstance(st, ast.While):
                flags |= self._events(st.test, flags)
                self._scan(st.body, flags)  # may run zero times
                self._scan(st.orelse, flags)
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    flags |= self._events(item.context_expr, flags)
                body_exit = self._scan(st.body, flags)
                if body_exit is None:
                    return None
                flags = body_exit
            else:
                flags |= self._events(st, flags)
                if isinstance(
                    st, (ast.Return, ast.Raise, ast.Continue, ast.Break)
                ):
                    return None
        return flags


def run_r14(graph: ProjectGraph,
            config: JaxlintConfig) -> Dict[str, List[RawFinding]]:
    """R14 findings per project-relative path."""
    handler_fns = [
        fkey
        for fkey in sorted(graph.functions)
        if config.is_handler(graph.functions[fkey].path)
    ]
    if not handler_fns:
        return {}
    reach = _reach_maps(graph, config)
    callers: Dict[str, Set[str]] = {}
    for e in graph.edges:
        callers.setdefault(e.callee, set()).add(e.caller)
    walked = set(handler_fns)
    entry: Dict[str, Set[str]] = {f: set() for f in handler_fns}
    for _ in range(12):  # bounded entry-state fixpoint (monotone)
        callsite: Dict[str, Set[str]] = {}
        for fkey in handler_fns:
            scan = _FuncOrder(graph, fkey, config, reach, entry[fkey])
            scan.run()
            for callee, fl in scan.callsite_flags.items():
                if callee in callsite:
                    callsite[callee] &= fl
                else:
                    callsite[callee] = set(fl)
        changed = False
        for fkey in handler_fns:
            cs = callers.get(fkey, set())
            # entry state is inherited only when EVERY caller is a
            # walked handler function whose call sites we observed —
            # an entry point (or a function reachable from outside the
            # handler tier) keeps the empty entry state.
            if cs and cs <= walked and fkey in callsite:
                new = callsite[fkey]
                if new != entry[fkey]:
                    entry[fkey] = new
                    changed = True
        if not changed:
            break

    out: Dict[str, List[RawFinding]] = {}
    for fkey in handler_fns:
        scan = _FuncOrder(graph, fkey, config, reach, entry[fkey])
        scan.run()
        if scan.findings:
            out.setdefault(scan.fi.path, []).extend(scan.findings)
    return out
