"""Lightweight per-phase wall-clock profiling.

The reference has no tracing at all — only verbosity-gated printf progress
lines (SURVEY §5; sboxgates.c:664,675,718,730).  The TPU build adds what the
reference lacks: per-phase timers around every sweep family plus the
candidate counters in ``SearchContext.stats``, so a run can report where its
wall time went (device sweeps vs. host control flow) and candidates/sec per
phase without external tooling.

Self-time accounting: a phase's recorded seconds exclude time spent inside
nested (child) phases, so the numbers are additive even though e.g. the
5-LUT sweep runs inside a mux-recursion phase.  Re-entrant phases (the
Kwan recursion) are safe for the same reason — each frame only accumulates
its own self time.

Overlap accounting (the pipelined host-stream drivers): per phase, the
consumer's blocking device syncs are recorded as *device-wait* intervals
(``add_wait``) and the background producer's chunk-generation spans as
*host-produce* intervals (``add_produce``, fed from another thread), both
on the same ``perf_counter`` clock.  ``hidden_s`` is the measured
interval intersection — host-produce wall time that actually elapsed
inside a device wait.  A strictly serial driver (pipeline_depth=1)
produces inline between syncs, its intervals never intersect a wait, and
``hidden_s`` is 0; a fully overlapping pipeline drives ``hidden_s``
toward ``host_produce_s``.  This is the number that shows whether the
async double-buffered pipeline is actually overlapping, even on hardware
where raw rates are noisy.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from ..telemetry import trace as _ttrace


class _OverlapStream:
    """Per-(phase, consumer) overlap accounting with bounded memory.

    Totals (wait/produce/stall seconds) accumulate as scalars at record
    time; interval lists are pending state kept ONLY for the two
    intersections (produce∩wait -> hidden, produce∩stall -> on-critical-
    path) and are folded into scalar accumulators as soon as no future
    interval can overlap them — an hours-long production run holds at
    most ~FOLD_AT intervals per stream instead of one tuple per chunk
    forever.

    Folding is safe because each stream is appended in monotonically
    non-decreasing time by exactly one thread (waits and stalls by the
    consumer, produces by that consumer's single producer): once both
    consumer streams have advanced past a produce span's end, no future
    wait/stall can reach back and overlap it, so its intersections are
    settled and it collapses into the accumulators.  Each produce span
    is folded exactly once and produce spans are mutually disjoint, so
    summing per-fold intersections is exact, not an approximation.
    """

    __slots__ = (
        "wait_s", "produce_s", "stall_s",
        "hidden_acc", "produce_merged_acc", "stall_produce_acc",
        "waits", "produces", "stalls",
        "last_wait_end", "last_stall_end", "last_produce_end",
    )

    FOLD_AT = 1024

    def __init__(self):
        self.wait_s = self.produce_s = self.stall_s = 0.0
        self.hidden_acc = 0.0
        self.produce_merged_acc = 0.0
        self.stall_produce_acc = 0.0
        self.waits: List[Tuple[float, float]] = []
        self.produces: List[Tuple[float, float]] = []
        self.stalls: List[Tuple[float, float]] = []
        self.last_wait_end = self.last_stall_end = 0.0
        self.last_produce_end = 0.0

    def fold(self, intersect, merged_len) -> None:
        """Collapse settled pending intervals into the accumulators."""
        if self.produces:
            # A produce span is settled once BOTH consumer streams have
            # recorded past its end (their future spans start no
            # earlier than their last end).
            w = min(self.last_wait_end, self.last_stall_end)
            idx = 0
            while idx < len(self.produces) and self.produces[idx][1] <= w:
                idx += 1
            if idx:
                done = self.produces[:idx]
                del self.produces[:idx]
                self.hidden_acc += intersect(self.waits, done)
                self.stall_produce_acc += intersect(self.stalls, done)
                self.produce_merged_acc += merged_len(done)
                # Drop consumer spans no remaining/future produce span
                # can overlap (future produces start at or after the
                # pending head / the last produce end).
                floor = (
                    self.produces[0][0] if self.produces
                    else self.last_produce_end
                )
                self.waits = [iv for iv in self.waits if iv[1] > floor]
                self.stalls = [iv for iv in self.stalls if iv[1] > floor]
        # Producer-less phases (the device-stream drivers record only
        # sync_verdict waits) never trigger the produce fold: bound them
        # by shedding the oldest consumer spans outright.  Totals are
        # already scalar-accumulated, and a live producer lags the
        # consumer by at most the bounded queue depth (<< FOLD_AT), so
        # spans this old can never intersect a future produce.
        for attr in ("waits", "stalls"):
            iv = getattr(self, attr)
            if len(iv) > self.FOLD_AT:
                del iv[: len(iv) - self.FOLD_AT // 2]

    def pending_size(self) -> int:
        return len(self.waits) + len(self.produces) + len(self.stalls)


class PhaseProfiler:
    """Accumulates self-time seconds and call counts per named phase.

    Thread-safe: the frame stack is thread-local (the batched-restart
    driver shares one profiler across its restart threads), and the
    accumulators are lock-protected.

    Usage::

        prof = PhaseProfiler()
        with prof.phase("lut5"):
            ...
        print(prof.report())
    """

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self.seconds: Dict[str, float] = {}
        self.calls: Dict[str, int] = {}
        # Overlap accounting for the pipelined streaming drivers: device
        # -wait (consumer blocking on a verdict), host-produce
        # (background chunk generation), and consumer-stall (consumer
        # blocked on the prefetch queue — or producing inline at
        # depth 1) (start, end) perf_counter intervals, keyed by
        # (phase, consumer) so concurrent drivers sharing a phase name
        # (parallel mux branches, batched restarts) never cross-
        # pollinate each other's intersections — branch A's produce span
        # falling inside branch B's device wait is NOT hidden work.
        # _OverlapStream keeps the memory bounded (intervals fold into
        # scalar accumulators once settled).
        self._overlap: Dict[Tuple[str, int], _OverlapStream] = {}
        self._tls = threading.local()
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()

    @property
    def _stack(self) -> List[List]:
        """Per-thread stack of [name, start_time, child_seconds] frames."""
        try:
            return self._tls.stack
        except AttributeError:
            self._tls.stack = []
            return self._tls.stack

    def phase(self, name: str) -> "_Phase":
        return _Phase(self, name)

    def add(self, name: str, seconds: float, calls: int = 1) -> None:
        with self._lock:
            self.seconds[name] = self.seconds.get(name, 0.0) + seconds
            self.calls[name] = self.calls.get(name, 0) + calls

    def _overlap_stream(self, name: str, consumer: Optional[int]):
        """The (phase, consumer) overlap stream; ``consumer`` identifies
        the consuming driver (defaults to the calling thread) so that
        concurrent drivers sharing a phase name stay separate."""
        key = (name, threading.get_ident() if consumer is None else consumer)
        stream = self._overlap.get(key)
        if stream is None:
            stream = self._overlap[key] = _OverlapStream()
        return stream

    def _trace_overlap(self, name: str, kind: str,
                       start: float, end: float) -> None:
        # Overlap streams double as span sources (--trace): each interval
        # becomes a wait/produce/stall span on the recording thread's
        # timeline, so the pipeline's producer/consumer interleaving is
        # visible in Perfetto, not just summed in the overlap table.
        # High-frequency -> trace buffers only, never the flight ring.
        tr = _ttrace.tracer()
        if tr.enabled:
            tr.record(f"{name}.{kind}", kind, start, end, flight=False)

    def add_wait(self, name: str, start: float, end: float,
                 consumer: Optional[int] = None) -> None:
        """Device-wait interval: consumer blocked on a device sync
        between perf_counter timestamps ``start`` and ``end``."""
        if not self.enabled:
            return
        self._trace_overlap(name, "wait", start, end)
        with self._lock:
            s = self._overlap_stream(name, consumer)
            s.wait_s += end - start
            s.waits.append((start, end))
            s.last_wait_end = max(s.last_wait_end, end)
            if s.pending_size() > _OverlapStream.FOLD_AT:
                s.fold(self._intersect, self._merged_len)

    def add_produce(self, name: str, start: float, end: float,
                    consumer: Optional[int] = None) -> None:
        """Host-produce interval: one chunk's generation span.  Called
        from the producer thread — ``consumer`` must carry the consuming
        driver's key (the prefetcher's owner records it at creation)."""
        if not self.enabled:
            return
        self._trace_overlap(name, "produce", start, end)
        with self._lock:
            s = self._overlap_stream(name, consumer)
            s.produce_s += end - start
            s.produces.append((start, end))
            s.last_produce_end = max(s.last_produce_end, end)
            if s.pending_size() > _OverlapStream.FOLD_AT:
                s.fold(self._intersect, self._merged_len)

    def add_stall(self, name: str, start: float, end: float,
                  consumer: Optional[int] = None) -> None:
        """Consumer-stall interval: time the consumer spent blocked in
        the prefetcher's get() — production on its critical path."""
        if not self.enabled:
            return
        self._trace_overlap(name, "stall", start, end)
        with self._lock:
            s = self._overlap_stream(name, consumer)
            s.stall_s += end - start
            s.stalls.append((start, end))
            s.last_stall_end = max(s.last_stall_end, end)
            if s.pending_size() > _OverlapStream.FOLD_AT:
                s.fold(self._intersect, self._merged_len)

    def snapshot(self) -> Dict[str, Tuple[float, int]]:
        """{phase: (self_seconds, calls)} for programmatic consumers."""
        return {
            k: (self.seconds[k], self.calls.get(k, 0))
            for k in self.seconds
        }

    @staticmethod
    def _merge(iv: List[Tuple[float, float]]) -> List[List[float]]:
        """Abutting/overlapping intervals merged into a disjoint set."""
        out: List[List[float]] = []
        for s, e in sorted(iv):
            if out and s <= out[-1][1]:
                out[-1][1] = max(out[-1][1], e)
            else:
                out.append([s, e])
        return out

    @classmethod
    def _merged_len(cls, iv: List[Tuple[float, float]]) -> float:
        """Total wall time covered by an interval set (merged length)."""
        return sum(e - s for s, e in cls._merge(iv))

    @classmethod
    def _intersect(cls, a: List[Tuple[float, float]],
                   b: List[Tuple[float, float]]) -> float:
        """Total length of the intersection of two interval sets (each
        set's intervals may abut/overlap; both are merged first)."""
        ma, mb = cls._merge(a), cls._merge(b)
        i = j = 0
        total = 0.0
        while i < len(ma) and j < len(mb):
            lo = max(ma[i][0], mb[j][0])
            hi = min(ma[i][1], mb[j][1])
            if hi > lo:
                total += hi - lo
            if ma[i][1] <= mb[j][1]:
                i += 1
            else:
                j += 1
        return total

    def overlap(self) -> Dict[str, Dict[str, float]]:
        """Per-phase overlap accounting for programmatic consumers:
        {phase: {device_wait_s, host_produce_s, consumer_stall_s,
        hidden_s, off_critical_path_s}}.

        ``hidden_s`` is the MEASURED intersection of producer spans with
        consumer device-wait spans — host-produce wall time that
        actually elapsed under a device sync.  ``off_critical_path_s``
        is the broader win, measured the same way: produce time that did
        NOT elapse inside a consumer stall (the consumer was busy
        dispatching/solving OR blocked on the device while the producer
        worked).  Interval intersection — not a produce-minus-stall
        duration difference — because stall totals also carry queue
        wakeup latency under CPU contention, which would eat real
        overlap.  A strictly serial driver produces inline inside get(),
        every produce span nests in its stall span, and both overlap
        numbers are exactly 0; a fully warmed pipeline's produce spans
        fall outside the (near-zero) stalls and ``off_critical_path_s``
        approaches ``host_produce_s``.

        Streams are kept per (phase, consumer) and each consumer's
        overlap is computed against its OWN producer/waits before the
        per-phase row sums the consumers — concurrent mux branches or
        batched restarts sharing a phase name cannot inflate each
        other's numbers."""
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            for (name, _consumer), s in self._overlap.items():
                hidden = s.hidden_acc + self._intersect(s.waits, s.produces)
                on_crit = (
                    s.stall_produce_acc
                    + self._intersect(s.stalls, s.produces)
                )
                # Merged produce length, not the raw sum: with the raw
                # sum, produce spans that overlap each other would
                # survive a stall that covers them all.
                merged = s.produce_merged_acc + self._merged_len(s.produces)
                row = out.setdefault(name, {
                    "device_wait_s": 0.0,
                    "host_produce_s": 0.0,
                    "consumer_stall_s": 0.0,
                    "hidden_s": 0.0,
                    "off_critical_path_s": 0.0,
                })
                row["device_wait_s"] += s.wait_s
                row["host_produce_s"] += s.produce_s
                row["consumer_stall_s"] += s.stall_s
                row["hidden_s"] += hidden
                row["off_critical_path_s"] += max(0.0, merged - on_crit)
        return out

    def report(self, stats: Optional[Dict[str, int]] = None) -> str:
        """Formatted table, hottest phase first.  ``stats`` (candidate
        counters named ``<phase>_candidates``, with any ``_sweep`` phase
        suffix stripped: ``pair_sweep`` -> ``pair_candidates``) adds a
        candidates/sec column where a counter matches a phase name."""
        wall = time.perf_counter() - self._t0
        lines = [
            "phase                     calls     self_s      %",
        ]
        total = sum(self.seconds.values())
        for name in sorted(self.seconds, key=self.seconds.get, reverse=True):
            sec = self.seconds[name]
            pct = 100.0 * sec / total if total > 0 else 0.0
            line = "%-24s %6d %10.3f %6.1f" % (
                name, self.calls.get(name, 0), sec, pct,
            )
            if stats:
                key = name.split(".")[0]
                if key.endswith("_sweep"):
                    key = key[: -len("_sweep")]
                cand = stats.get(f"{key}_candidates")
                # A parent phase whose time lives in child phases (e.g.
                # "lut7" over stageA/B) has ~no self time; a rate against
                # it would be meaningless noise.
                if cand and sec >= 0.01:
                    line += "   %.3g cand/s" % (cand / sec)
            lines.append(line)
        lines.append(
            "%-24s %6s %10.3f %6.1f   (wall %.3f s)"
            % ("total", "", total, 100.0 if total else 0.0, wall)
        )
        ov = self.overlap()
        if ov:
            # offcrit = produce time kept off the consumer's critical
            # path (see overlap()); offcrit% is the pipeline's score —
            # 0 for serial drivers, ->100 when fully overlapped.
            lines.append(
                "pipeline overlap          wait_s  produce_s   stall_s"
                "  offcrit_s  offcrit%"
            )
            for name in sorted(ov):
                o = ov[name]
                denom = o["host_produce_s"]
                lines.append(
                    "%-24s %8.3f %10.3f %9.3f %10.3f %9.1f"
                    % (
                        name,
                        o["device_wait_s"],
                        o["host_produce_s"],
                        o["consumer_stall_s"],
                        o["off_critical_path_s"],
                        100.0 * o["off_critical_path_s"] / denom
                        if denom > 0 else 0.0,
                    )
                )
        if stats:
            en = stats.get("engine_nodes", 0)
            pn = stats.get("python_nodes", 0)
            if en or pn:
                lines.append(
                    "engine-active nodes: %d/%d (%.1f%%), serviced device"
                    " requests: %d"
                    % (
                        en, en + pn,
                        100.0 * en / (en + pn),
                        stats.get("engine_devcalls", 0),
                    )
                )
        return "\n".join(lines)


class _Phase:
    __slots__ = ("_prof", "_name")

    def __init__(self, prof: PhaseProfiler, name: str):
        self._prof = prof
        self._name = name

    def __enter__(self):
        if self._prof.enabled:
            self._prof._stack.append([self._name, time.perf_counter(), 0.0])
        return self

    def __exit__(self, *exc):
        prof = self._prof
        if not prof.enabled:
            return False
        name, t0, child = prof._stack.pop()
        t1 = time.perf_counter()
        dt = t1 - t0
        prof.add(name, dt - child)
        if prof._stack:
            prof._stack[-1][2] += dt
        # Phase frames are trace spans too (--trace): the profiler is a
        # span SOURCE, giving the exported timeline the same phase
        # nesting the -vv table sums.  Trace buffers only (per-node
        # frequency would churn the bounded flight ring).
        tr = _ttrace.tracer()
        if tr.enabled:
            tr.record(name, "phase", t0, t1, flight=False)
        return False
