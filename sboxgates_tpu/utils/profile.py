"""Lightweight per-phase wall-clock profiling.

The reference has no tracing at all — only verbosity-gated printf progress
lines (SURVEY §5; sboxgates.c:664,675,718,730).  The TPU build adds what the
reference lacks: per-phase timers around every sweep family plus the
candidate counters in ``SearchContext.stats``, so a run can report where its
wall time went (device sweeps vs. host control flow) and candidates/sec per
phase without external tooling.

Self-time accounting: a phase's recorded seconds exclude time spent inside
nested (child) phases, so the numbers are additive even though e.g. the
5-LUT sweep runs inside a mux-recursion phase.  Re-entrant phases (the
Kwan recursion) are safe for the same reason — each frame only accumulates
its own self time.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple


class PhaseProfiler:
    """Accumulates self-time seconds and call counts per named phase.

    Thread-safe: the frame stack is thread-local (the batched-restart
    driver shares one profiler across its restart threads), and the
    accumulators are lock-protected.

    Usage::

        prof = PhaseProfiler()
        with prof.phase("lut5"):
            ...
        print(prof.report())
    """

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self.seconds: Dict[str, float] = {}
        self.calls: Dict[str, int] = {}
        self._tls = threading.local()
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()

    @property
    def _stack(self) -> List[List]:
        """Per-thread stack of [name, start_time, child_seconds] frames."""
        try:
            return self._tls.stack
        except AttributeError:
            self._tls.stack = []
            return self._tls.stack

    def phase(self, name: str) -> "_Phase":
        return _Phase(self, name)

    def add(self, name: str, seconds: float, calls: int = 1) -> None:
        with self._lock:
            self.seconds[name] = self.seconds.get(name, 0.0) + seconds
            self.calls[name] = self.calls.get(name, 0) + calls

    def snapshot(self) -> Dict[str, Tuple[float, int]]:
        """{phase: (self_seconds, calls)} for programmatic consumers."""
        return {
            k: (self.seconds[k], self.calls.get(k, 0))
            for k in self.seconds
        }

    def report(self, stats: Optional[Dict[str, int]] = None) -> str:
        """Formatted table, hottest phase first.  ``stats`` (candidate
        counters named ``<phase>_candidates``, with any ``_sweep`` phase
        suffix stripped: ``pair_sweep`` -> ``pair_candidates``) adds a
        candidates/sec column where a counter matches a phase name."""
        wall = time.perf_counter() - self._t0
        lines = [
            "phase                     calls     self_s      %",
        ]
        total = sum(self.seconds.values())
        for name in sorted(self.seconds, key=self.seconds.get, reverse=True):
            sec = self.seconds[name]
            pct = 100.0 * sec / total if total > 0 else 0.0
            line = "%-24s %6d %10.3f %6.1f" % (
                name, self.calls.get(name, 0), sec, pct,
            )
            if stats:
                key = name.split(".")[0]
                if key.endswith("_sweep"):
                    key = key[: -len("_sweep")]
                cand = stats.get(f"{key}_candidates")
                # A parent phase whose time lives in child phases (e.g.
                # "lut7" over stageA/B) has ~no self time; a rate against
                # it would be meaningless noise.
                if cand and sec >= 0.01:
                    line += "   %.3g cand/s" % (cand / sec)
            lines.append(line)
        lines.append(
            "%-24s %6s %10.3f %6.1f   (wall %.3f s)"
            % ("total", "", total, 100.0 if total else 0.0, wall)
        )
        if stats:
            en = stats.get("engine_nodes", 0)
            pn = stats.get("python_nodes", 0)
            if en or pn:
                lines.append(
                    "engine-active nodes: %d/%d (%.1f%%), serviced device"
                    " requests: %d"
                    % (
                        en, en + pn,
                        100.0 * en / (en + pn),
                        stats.get("engine_devcalls", 0),
                    )
                )
        return "\n".join(lines)


class _Phase:
    __slots__ = ("_prof", "_name")

    def __init__(self, prof: PhaseProfiler, name: str):
        self._prof = prof
        self._name = name

    def __enter__(self):
        if self._prof.enabled:
            self._prof._stack.append([self._name, time.perf_counter(), 0.0])
        return self

    def __exit__(self, *exc):
        prof = self._prof
        if not prof.enabled:
            return False
        name, t0, child = prof._stack.pop()
        dt = time.perf_counter() - t0
        prof.add(name, dt - child)
        if prof._stack:
            prof._stack[-1][2] += dt
        return False
