"""Shared utilities: sbox loading, phase profiling, and the runtime
jaxlint complements (:mod:`~sboxgates_tpu.utils.guards`)."""

from .guards import (  # noqa: F401
    GuardReport,
    RecompileError,
    SyncError,
    recompile_guard,
    sync_guard,
)
