"""Shared utilities: sbox loading, phase profiling, and the runtime
jaxlint complements (:mod:`~sboxgates_tpu.utils.guards`)."""

from .guards import (  # noqa: F401
    GuardReport,
    RecompileError,
    SyncError,
    jit_cache_size,
    recompile_guard,
    sync_guard,
)
