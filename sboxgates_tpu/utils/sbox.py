"""S-box input files: 2^n whitespace-separated hex values, 1 <= n <= 8.

Reference: load_sbox (sboxgates.c:988-1040).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


class SboxError(Exception):
    pass


def parse_sbox(text: str) -> Tuple[np.ndarray, int]:
    """Parses an S-box table; returns (sbox[256] uint8, num_inputs).

    Values beyond the table length are zero-filled, matching the reference's
    fixed 256-entry array.  The number of entries must be a power of two and
    every value must fit in a byte.
    """
    values = []
    for token in text.split():
        try:
            v = int(token, 16)
        except ValueError:
            break
        if v < 0 or v >= 256 or len(values) >= 256:
            break
        values.append(v)
    n = len(values)
    if n == 0 or (n & (n - 1)) != 0:
        raise SboxError("Bad number of items in target S-box.")
    num_inputs = n.bit_length() - 1
    sbox = np.zeros(256, dtype=np.uint8)
    sbox[:n] = values
    return sbox, num_inputs


def permuted_box(sbox: np.ndarray, num_inputs: int, p: int) -> np.ndarray:
    """The S-box with its input XOR-permuted by ``p`` — the single home
    of the ``--permute`` transform (reference: sboxgates.c:1021-1031),
    used both at load time and by the permutation-sweep driver."""
    if p >= (1 << num_inputs):
        raise SboxError(f"Bad permutation value: {p}")
    return sbox[np.arange(256) ^ (p & 0xFF)]


def load_sbox(path: str, permute: int = 0) -> Tuple[np.ndarray, int]:
    """Loads an S-box file, optionally XOR-permuting the input indices
    (reference: sboxgates.c:1021-1031)."""
    with open(path, "r", encoding="utf-8") as f:
        sbox, num_inputs = parse_sbox(f.read())
    if permute:
        sbox = permuted_box(sbox, num_inputs, permute)
    return sbox, num_inputs


def num_outputs(sbox: np.ndarray, num_inputs: int) -> int:
    """Index of the highest non-constant... highest set output bit + 1.

    Matches the reference's get_num_outputs (sboxgates.c:231-244): the
    number of outputs is determined by the highest output bit whose target
    truth table is not all-zero.
    """
    valid = sbox[: 1 << num_inputs]
    for bit in range(7, -1, -1):
        if ((valid >> bit) & 1).any():
            return bit + 1
    raise SboxError("S-box has no set output bits")
