"""Runtime guards for the two failure modes jaxlint can only partially
prove statically: silent recompiles (R1) and hidden host-device syncs
(R2).

:func:`recompile_guard` watches ``jax.jit`` compilation activity inside a
``with`` block — either per-function cache growth (``fns=...``, via the
jitted callable's ``_cache_size()``) or process-wide compile events (via
the ``jax_log_compiles`` logging channel) — and raises
:class:`RecompileError` when the count exceeds ``allowed``.

:func:`sync_guard` counts blocking device->host transfers inside a
``with`` block by wrapping ``jax.device_get``, ``jax.block_until_ready``
and ``np.asarray``/``np.array`` on ``jax.Array`` values, raising
:class:`SyncError` (``action="raise"``) or just tallying
(``action="count"``) for benchmark reporting.  It cannot see syncs that
bypass those entry points (``.item()``, ``float()`` on a device scalar
via ``__float__``, direct buffer protocol) — the static R2 pass covers
those shapes; together the two nets overlap.

Both guards are re-entrant-safe for nested use but not thread-safe:
install them from the consumer thread that owns the region under test
(bench.py's timing loops, the streaming tests).  With the hung-dispatch
deadline armed (:mod:`sboxgates_tpu.resilience.deadline`), guarded sweep
resolves execute on a short-lived ``sbg-deadline`` worker thread; the
sync wrappers still count those transfers (the patch is process-global),
so the tallies stay complete — only strict ``action="raise"`` delivery
moves to the resolving thread, where the driver surfaces it.  The
deadline guard's own activity is reported separately
(``dispatch_retries`` / ``deadline_breaches`` in the context stats and
the bench output).
"""

from __future__ import annotations

import contextlib
import logging
from dataclasses import dataclass, field
from typing import Callable, Iterator, List, Optional, Sequence


class RecompileError(RuntimeError):
    """An unexpected jax.jit compilation happened inside a recompile_guard."""


def jit_cache_size(fn) -> Optional[int]:
    """Compiled-signature count of a ``jax.jit`` callable, or None when
    unavailable — the same per-function counter :func:`recompile_guard`
    uses for its ``fns=`` mode.  Unwraps ``functools.partial`` so a
    statically-bound kernel reports its underlying jit cache.  The
    compile-latency telemetry (``SearchContext.kernel_call``) samples
    this around each lazy dispatch to attribute compile stalls."""
    f = getattr(fn, "func", fn)
    try:
        return f._cache_size()
    except AttributeError:
        return None


class SyncError(RuntimeError):
    """An unexpected host-device sync happened inside a sync_guard."""


@dataclass
class GuardReport:
    """Mutable tally yielded by both guards."""

    compiles: int = 0
    syncs: int = 0
    events: List[str] = field(default_factory=list)

    def note(self, kind: str, detail: str) -> None:
        if kind == "compile":
            self.compiles += 1
        else:
            self.syncs += 1
        if len(self.events) < 200:  # bounded: long bench runs
            self.events.append(f"{kind}: {detail}")


class _CompileLogCounter(logging.Handler):
    """Counts 'Compiling <name> ...' records on the jax logger tree.

    Compiles performed by the background kernel warmer's worker thread
    (``sbg-warmup``) are excluded: they are BY DESIGN off the critical
    path — the guard's contract is "nothing on the dispatch path
    compiles", and a warm set scheduled mid-region (entering a new
    bucket schedules its successors) must not fail it.  Logging handlers
    run synchronously on the emitting thread, so the thread name
    identifies the compiler."""

    def __init__(self, report: GuardReport) -> None:
        super().__init__(level=logging.DEBUG)
        self.report = report

    def emit(self, record: logging.LogRecord) -> None:
        import threading

        if threading.current_thread().name == "sbg-warmup":
            return
        msg = record.getMessage()
        if msg.startswith("Compiling "):
            self.report.note("compile", msg.split(" in ")[0][:160])


@contextlib.contextmanager
def recompile_guard(
    fns: Sequence[Callable] = (),
    allowed: int = 0,
    label: str = "",
) -> Iterator[GuardReport]:
    """Raises :class:`RecompileError` when more than ``allowed`` new
    compilations happen inside the block.

    With ``fns`` (jitted callables), growth is measured per function via
    ``_cache_size()`` — precise, zero overhead, immune to other threads'
    compiles.  Without ``fns``, every compile in the process is counted
    through the ``jax_log_compiles`` logging channel (which this guard
    enables for the duration of the block).

    The canonical bug this catches: a per-call-varying Python scalar
    passed as a static arg, which grows the jit cache by one entry per
    call — invisible in tests with one call, catastrophic in a streaming
    loop on real hardware.
    """
    import jax

    report = GuardReport()
    tracked = [f for f in fns if hasattr(f, "_cache_size")]
    if fns and not tracked:
        raise TypeError(
            "recompile_guard(fns=...) requires jax.jit-wrapped callables "
            "(objects with _cache_size)"
        )
    before = [f._cache_size() for f in tracked]
    handler: Optional[_CompileLogCounter] = None
    prev_log = None
    jax_logger = logging.getLogger("jax")
    prev_handlers: List[logging.Handler] = []
    if not tracked:
        handler = _CompileLogCounter(report)
        # The compile records are emitted at WARNING only when
        # jax_log_compiles is on; flip it for the duration, and swap out
        # jax's own stderr handler so the guard doesn't spray one WARNING
        # line per compile while counting them.
        prev_handlers, jax_logger.handlers = jax_logger.handlers, [handler]
        prev_log = jax.config.jax_log_compiles
        jax.config.update("jax_log_compiles", True)
    try:
        yield report
    finally:
        if handler is not None:
            jax.config.update("jax_log_compiles", prev_log)
            jax_logger.handlers = prev_handlers
    if tracked:
        for f, b in zip(tracked, before):
            grew = f._cache_size() - b
            if grew > 0:
                report.note(
                    "compile",
                    f"{getattr(f, '__name__', repr(f))}: cache "
                    f"{b} -> {b + grew}",
                )
    if report.compiles > allowed:
        where = f" in {label}" if label else ""
        raise RecompileError(
            f"{report.compiles} jit compilation(s){where} (allowed "
            f"{allowed}) — a static arg is probably varying per call; "
            f"events: {report.events[:8]}"
        )


class _SyncPatches:
    """Wraps the module-level sync entry points, counting (and optionally
    rejecting) calls whose operand is a device array."""

    def __init__(self, report: GuardReport, action: str, allowed: int):
        self.report = report
        self.action = action
        self.allowed = allowed
        self._saved: List = []

    def _hit(self, what: str) -> None:
        self.report.note("sync", what)
        if self.action == "raise" and self.report.syncs > self.allowed:
            raise SyncError(
                f"host-device sync #{self.report.syncs} (allowed "
                f"{self.allowed}): {what} — batch the transfer or move it "
                "out of the guarded region"
            )

    def install(self) -> None:
        import jax
        import numpy as np

        def wrap(mod, name, is_device_value):
            orig = getattr(mod, name)

            def wrapper(x, *a, **k):
                if is_device_value(x):
                    self._hit(f"{mod.__name__}.{name}")
                return orig(x, *a, **k)

            wrapper.__wrapped__ = orig
            self._saved.append((mod, name, orig))
            setattr(mod, name, wrapper)

        def is_jax_array(x) -> bool:
            return isinstance(x, jax.Array)

        def contains_jax_array(x) -> bool:
            if isinstance(x, jax.Array):
                return True
            if isinstance(x, (list, tuple)):
                return any(contains_jax_array(e) for e in x)
            return False

        wrap(jax, "device_get", contains_jax_array)
        wrap(jax, "block_until_ready", contains_jax_array)
        wrap(np, "asarray", is_jax_array)
        wrap(np, "array", is_jax_array)

    def uninstall(self) -> None:
        for mod, name, orig in reversed(self._saved):
            setattr(mod, name, orig)
        self._saved.clear()


@contextlib.contextmanager
def sync_guard(
    allowed: int = 0,
    action: str = "raise",
    label: str = "",
) -> Iterator[GuardReport]:
    """Counts blocking device->host transfers inside the block.

    ``action="raise"`` raises :class:`SyncError` on the first transfer
    past ``allowed`` (streaming tests: prove a region never syncs);
    ``action="count"`` only tallies into the yielded
    :class:`GuardReport` (bench.py: report sync pressure alongside
    throughput).  The patches are process-global while installed —
    guard one region at a time, from the thread that owns it.
    """
    if action not in ("raise", "count"):
        raise ValueError(f"sync_guard action must be raise|count, got {action!r}")
    report = GuardReport()
    patches = _SyncPatches(report, action, allowed)
    patches.install()
    try:
        yield report
    finally:
        patches.uninstall()
    if action == "raise" and report.syncs > allowed:
        where = f" in {label}" if label else ""
        raise SyncError(
            f"{report.syncs} host-device sync(s){where} (allowed {allowed}); "
            f"events: {report.events[:8]}"
        )
