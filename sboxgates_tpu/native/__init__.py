"""ctypes bindings for the native host runtime (csrc/runtime.cpp).

The library is built on demand with g++ the first time it's needed and
cached next to this file.  Every entry point has a pure-Python fallback in
the main package, so the framework degrades gracefully when no C++
toolchain is present: callers check :func:`available` or catch
:class:`NativeUnavailable`.

Exposed surface (mirrors the C ABI):

- :func:`fingerprint`          — Speck-round hash of a byte string
- :func:`combinations_from_rank` — stream k-combinations lexicographically
- :func:`execute_circuit`      — bitslice interpreter for a gate program
- :func:`lut5_search_cpu`      — reference-shaped CPU 5-LUT search
  (the measured baseline for bench.py)
- :func:`gate_step`            — fused gate-mode search node (steps 1-4)
  for small states, bit-identical to the jitted kernel's selection
- :func:`lut_step`             — the LUT-mode head counterpart (steps 1-3
  + 3-LUT + small-space 5-LUT), bit-identical to lut_step_stream
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import sys
import threading
from typing import Optional, Tuple

import numpy as np

_logger = logging.getLogger(__name__)

# Process-wide tally of device-work-service failures surfaced by the
# engine bail path.  Mirrors parallel.mesh._note_pallas_fallback:
# warnings.warn is deduplicated to one line per process by the default
# filter, which hides *repeated* silent degradations to the Python
# engine, so the visibility line is a rate-limited stderr print keyed
# off a locked counter instead.
_SERVICE_FAILURES = 0
_SERVICE_FAIL_LOCK = threading.Lock()
_SERVICE_FAIL_PRINT_FIRST = 5
_SERVICE_FAIL_PRINT_EVERY = 100


def service_failure_count() -> int:
    """How many native-engine calls bailed because the attached
    device-work service raised, in this process."""
    return _SERVICE_FAILURES


def _note_service_failure(exc: BaseException) -> None:
    global _SERVICE_FAILURES
    with _SERVICE_FAIL_LOCK:
        _SERVICE_FAILURES += 1
        n = _SERVICE_FAILURES
    # Structured telemetry alongside the rate-limited stderr line: a
    # trace/flight instant plus a process-global counter surfaced by
    # heartbeat lines and metrics.json (the stderr line only helps if
    # someone was watching the terminal).
    from ..telemetry import metrics as _tmetrics
    from ..telemetry import trace as _ttrace

    _tmetrics.GLOBAL.inc("native_service_failures")
    _ttrace.instant(
        "native.service_failure", "fallback", error=repr(exc)[:200], n=n
    )
    if n <= _SERVICE_FAIL_PRINT_FIRST or n % _SERVICE_FAIL_PRINT_EVERY == 0:
        print(
            f"sboxgates_tpu: device-work service failed inside the native "
            f"engine ({exc!r}); the search fell back to the Python engine "
            f"[failure #{n} this process]",
            file=sys.stderr,
            flush=True,
        )

_HERE = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_HERE, "libsboxg_runtime.so")
# Source candidates: repo layout first, then a copy dropped next to this
# module (how an installed wheel/sdist can ship the runtime — see
# MANIFEST.in / pyproject packaging notes).
_SRC_CANDIDATES = (
    os.path.join(_HERE, "..", "..", "csrc", "runtime.cpp"),
    os.path.join(_HERE, "runtime.cpp"),
)
_SRC_PATH = next(
    (p for p in _SRC_CANDIDATES if os.path.exists(p)), _SRC_CANDIDATES[0]
)

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


class NativeUnavailable(RuntimeError):
    """The native runtime could not be built or loaded."""


# Device-work continuation callback for the LUT engine (the C side's
# sbg_eng_devcb): (handle, kind, tables*, g, target*, mask*, inbits*,
# n_inbits, arg0, rng, slot, resp*) -> rc.  See csrc/runtime.cpp for the
# kind and resp encodings.
ENG_DEVCB = ctypes.CFUNCTYPE(
    ctypes.c_int32,
    ctypes.c_void_p,  # handle
    ctypes.c_int32,   # kind
    ctypes.c_void_p,  # tables (uint32[g, 8] view)
    ctypes.c_int32,   # g
    ctypes.c_void_p,  # target (uint32[8] view)
    ctypes.c_void_p,  # mask
    ctypes.c_void_p,  # inbits (int32[n_inbits])
    ctypes.c_int32,   # n_inbits
    ctypes.c_int64,   # arg0 (kind 2: overflow chunk start rank)
    ctypes.c_uint64,  # rng (engine-stream draw; reserved)
    ctypes.c_int32,   # slot (branch id; reserved)
    ctypes.c_void_p,  # resp (int32[12] out)
)


def _as_i32(ptr, n):
    return np.ctypeslib.as_array(
        ctypes.cast(ptr, ctypes.POINTER(ctypes.c_int32)), shape=(n,)
    )


def _as_u32(ptr, shape):
    return np.ctypeslib.as_array(
        ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint32)), shape=shape
    )


def make_eng_devcb(service):
    """Wraps a Python device-work service into the C callback ABI;
    returns (callback, pending) where ``pending`` holds a
    KeyboardInterrupt/SystemExit captured inside the callback for the
    caller to re-raise once the ctypes engine call returns (raising
    across the C frame is not an option).

    ``service(kind, tables, g, target, mask, inbits, arg0, rng, slot)``
    receives COPIES of the engine's live tables / target / mask (the
    originals live on the C++ stack) and returns None on a miss or the
    flat hit tuple to write into resp[1:] ([fo, fi, a..e] for 5-LUT,
    [fo, fm, fi, a..g] for 7-LUT).  Ordinary exceptions are caught and
    reported as a service failure — the engine then bails to the Python
    engine, so a broken service degrades to round-3 behavior instead of
    crashing.  Interrupts also make the engine bail (the fastest unwind)
    but are re-raised by the caller, so Ctrl-C still stops the run."""
    pending = {"exc": None, "service_exc": None}

    def cb(
        handle, kind, tables_p, g, target_p, mask_p, inbits_p, n_inbits,
        arg0, rng, slot, resp_p,
    ):
        try:
            tables = _as_u32(tables_p, (g, 8)).copy()
            target = _as_u32(target_p, (8,)).copy()
            mask = _as_u32(mask_p, (8,)).copy()
            inbits = (
                [int(x) for x in _as_i32(inbits_p, n_inbits)]
                if n_inbits
                else []
            )
            out = service(
                kind, tables, g, target, mask, inbits, int(arg0), int(rng),
                int(slot),
            )
            resp = _as_i32(resp_p, 12)
            if out is None:
                resp[0] = 0
            else:
                resp[0] = 1
                resp[1 : 1 + len(out)] = np.asarray(out, dtype=np.int64)
            return 0
        except Exception as e:
            # An exception must not unwind across the C frame; the specific
            # type is unknowable (the service is user code), so: record it
            # for the caller to surface once the ctypes call returns, log
            # the traceback, and report failure — the engine then bails to
            # the Python engine (degrades instead of crashing).
            pending["service_exc"] = e
            _logger.exception(
                "device-work service failed inside the native LUT engine"
            )
            return 1
        except BaseException as e:  # KeyboardInterrupt / SystemExit
            pending["exc"] = e
            return 1

    return ENG_DEVCB(cb), pending


def _build() -> Optional[str]:
    """Compiles the shared library; returns an error string or None."""
    src = os.path.abspath(_SRC_PATH)
    if not os.path.exists(src):
        return f"source not found: {src}"
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O3",
        "-march=native",
        "-std=c++17",
        "-pthread",  # std::thread (sbg_lut5_search_cpu_mt)
        "-shared",
        "-fPIC",
        "-o",
        _LIB_PATH,
        src,
    ]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        return f"compiler launch failed: {e}"
    if proc.returncode != 0:
        return f"compile failed: {proc.stderr[-2000:]}"
    return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_error
    with _lock:
        if _lib is not None:
            return _lib
        if os.environ.get("SBG_DISABLE_NATIVE"):
            # Simulated-unavailable: never build or dlopen (tests drive
            # the multi-host heterogeneous-availability agreement with
            # this; users force the device kernels).  Not cached in
            # _build_error so unsetting the variable re-enables loading.
            return None
        if _build_error is not None:
            return None
        src_mtime = (
            os.path.getmtime(_SRC_PATH) if os.path.exists(_SRC_PATH) else 0
        )
        if (
            not os.path.exists(_LIB_PATH)
            or os.path.getmtime(_LIB_PATH) < src_mtime
        ):
            _build_error = _build()
            if _build_error is not None:
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            _build_error = f"dlopen failed: {e}"
            return None

        lib.sbg_fingerprint.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_uint64,
        ]
        lib.sbg_fingerprint.restype = ctypes.c_uint32

        lib.sbg_n_choose_k.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
        lib.sbg_n_choose_k.restype = ctypes.c_uint64

        lib.sbg_combinations_from_rank.argtypes = [
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_uint64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.sbg_combinations_from_rank.restype = ctypes.c_int64

        lib.sbg_execute_circuit.argtypes = [
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.sbg_execute_circuit.restype = ctypes.c_int32

        lib.sbg_lut5_search_cpu.argtypes = [
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.sbg_lut5_search_cpu.restype = ctypes.c_int64

        lib.sbg_lut5_search_cpu_mt.argtypes = [
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.sbg_lut5_search_cpu_mt.restype = ctypes.c_int64

        lib.sbg_gate_step.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_void_p,
        ]
        lib.sbg_gate_step.restype = None

        lib.sbg_lut_step.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_void_p,
        ]
        lib.sbg_lut_step.restype = None

        lib.sbg_lut7_stage_a.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
        ]
        lib.sbg_lut7_stage_a.restype = ctypes.c_int64

        lib.sbg_lut7_solve_small.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_void_p,
        ]
        lib.sbg_lut7_solve_small.restype = None

        lib.sbg_gate_engine.argtypes = [
            ctypes.c_void_p,  # tables
            ctypes.c_int32,   # g
            ctypes.c_int32,   # num_inputs
            ctypes.c_int32,   # max_gates
            ctypes.c_int64,   # sat_metric
            ctypes.c_int64,   # max_sat_metric
            ctypes.c_int32,   # metric
            ctypes.c_void_p,  # target
            ctypes.c_void_p,  # mask
            ctypes.c_void_p,  # pair_mt
            ctypes.c_void_p,  # pair_ops
            ctypes.c_void_p,  # not_mt
            ctypes.c_void_p,  # not_ops
            ctypes.c_void_p,  # triple_mt
            ctypes.c_void_p,  # tri_ops
            ctypes.c_void_p,  # inbits
            ctypes.c_int32,   # n_inbits
            ctypes.c_int32,   # randomize
            ctypes.c_uint64,  # rng_seed
            ctypes.c_void_p,  # out_gid
            ctypes.c_void_p,  # added
            ctypes.c_void_p,  # stats
        ]
        lib.sbg_gate_engine.restype = ctypes.c_int64

        lib.sbg_lut_engine.argtypes = [
            ctypes.c_void_p,  # tables
            ctypes.c_int32,   # g
            ctypes.c_int32,   # num_inputs
            ctypes.c_int32,   # max_gates
            ctypes.c_int64,   # sat_metric
            ctypes.c_int64,   # max_sat_metric
            ctypes.c_int32,   # metric
            ctypes.c_void_p,  # target
            ctypes.c_void_p,  # mask
            ctypes.c_void_p,  # pair_mt
            ctypes.c_void_p,  # pair_ops
            ctypes.c_void_p,  # w_tab
            ctypes.c_void_p,  # m_tab
            ctypes.c_void_p,  # idx_tab
            ctypes.c_void_p,  # orders
            ctypes.c_void_p,  # wo_tab
            ctypes.c_void_p,  # wm_tab
            ctypes.c_void_p,  # g_tab
            ctypes.c_int32,   # n_sigma
            ctypes.c_void_p,  # inbits
            ctypes.c_int32,   # n_inbits
            ctypes.c_int32,   # randomize
            ctypes.c_uint64,  # rng_seed
            ctypes.c_int32,   # mux_threads (>1 = threaded outermost mux)
            ENG_DEVCB,        # devcb (None = bail on device-work nodes)
            ctypes.c_void_p,  # devcb_handle
            ctypes.c_void_p,  # out_gid
            ctypes.c_void_p,  # added
            ctypes.c_void_p,  # stats
        ]
        lib.sbg_lut_engine.restype = ctypes.c_int64

        _lib = lib
        return lib


def available() -> bool:
    return _load() is not None


def _disabled_reason() -> Optional[str]:
    if os.environ.get("SBG_DISABLE_NATIVE"):
        return "disabled via SBG_DISABLE_NATIVE"
    return None


def build_error() -> Optional[str]:
    reason = _disabled_reason()
    if reason is not None:
        return reason
    _load()
    return _build_error


def _require() -> ctypes.CDLL:
    lib = _load()
    if lib is None:
        raise NativeUnavailable(_build_error or "unknown load failure")
    return lib


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def _buf(arr: np.ndarray, dtype) -> np.ndarray:
    """Contiguous buffer of exactly ``dtype`` (no-op on the fast path).
    For the hot per-search-node entry points, operands are passed as raw
    addresses (c_void_p argtypes): building typed POINTERs costs ~3.5 us
    per operand and the node steps run tens of thousands of times per
    search."""
    if arr.dtype != dtype or not arr.flags["C_CONTIGUOUS"]:
        arr = np.ascontiguousarray(arr, dtype=dtype)
    return arr


def _words(arr: np.ndarray) -> np.ndarray:
    """256-bit truth-table operand: accepts the uint32[..., 8] layout or
    its uint64[..., 4] view — identical bytes on the little-endian hosts
    this targets (the tables32_to_64 assumption).  Never converts values:
    a dtype cast here would silently corrupt the tables."""
    if not arr.flags["C_CONTIGUOUS"]:
        arr = np.ascontiguousarray(arr)
    if arr.dtype != np.uint32 and arr.dtype != np.uint64:
        raise TypeError(f"table operand must be uint32/uint64, got {arr.dtype}")
    return arr


# -- wrappers -------------------------------------------------------------


def fingerprint(data: bytes) -> int:
    lib = _require()
    buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
    return int(lib.sbg_fingerprint(buf, len(data)))


def n_choose_k(n: int, k: int) -> int:
    return int(_require().sbg_n_choose_k(n, k))


def combinations_from_rank(
    g: int, k: int, rank: int, count: int
) -> np.ndarray:
    """Up to ``count`` consecutive lexicographic k-combinations of
    {0..g-1} starting at ``rank``, as int32[written, k]."""
    lib = _require()
    out = np.empty((count, k), dtype=np.int32)
    written = lib.sbg_combinations_from_rank(
        g, k, rank, count, _ptr(out, ctypes.c_int32)
    )
    return out[:written]


def execute_circuit(
    types: np.ndarray,
    in1: np.ndarray,
    in2: np.ndarray,
    in3: np.ndarray,
    funcs: np.ndarray,
    input_tables64: np.ndarray,
) -> np.ndarray:
    """Evaluates every gate's 256-bit truth table; returns uint64[G, 4]."""
    lib = _require()
    g = len(types)
    types = np.ascontiguousarray(types, dtype=np.int32)
    in1 = np.ascontiguousarray(in1, dtype=np.int32)
    in2 = np.ascontiguousarray(in2, dtype=np.int32)
    in3 = np.ascontiguousarray(in3, dtype=np.int32)
    funcs = np.ascontiguousarray(funcs, dtype=np.uint8)
    itab = np.ascontiguousarray(input_tables64, dtype=np.uint64)
    out = np.empty((g, 4), dtype=np.uint64)
    rc = lib.sbg_execute_circuit(
        g,
        _ptr(types, ctypes.c_int32),
        _ptr(in1, ctypes.c_int32),
        _ptr(in2, ctypes.c_int32),
        _ptr(in3, ctypes.c_int32),
        _ptr(funcs, ctypes.c_uint8),
        _ptr(itab, ctypes.c_uint64),
        _ptr(out, ctypes.c_uint64),
    )
    if rc != 0:
        raise ValueError("malformed circuit program")
    return out


def lut5_search_cpu(
    tables64: np.ndarray,
    target64: np.ndarray,
    mask64: np.ndarray,
    combos: np.ndarray,
) -> Tuple[int, Optional[dict]]:
    """Reference-shaped serial 5-LUT search over the given combinations.

    Returns (hit_index, decomposition) with hit_index -1 when no
    combination admits a decomposition."""
    lib = _require()
    tables64 = np.ascontiguousarray(tables64, dtype=np.uint64)
    target64 = np.ascontiguousarray(target64, dtype=np.uint64)
    mask64 = np.ascontiguousarray(mask64, dtype=np.uint64)
    combos = np.ascontiguousarray(combos, dtype=np.int32)
    res = np.zeros(7, dtype=np.int32)
    idx = lib.sbg_lut5_search_cpu(
        _ptr(tables64, ctypes.c_uint64),
        tables64.shape[0],
        _ptr(target64, ctypes.c_uint64),
        _ptr(mask64, ctypes.c_uint64),
        _ptr(combos, ctypes.c_int32),
        combos.shape[0],
        _ptr(res, ctypes.c_int32),
    )
    if idx < 0:
        return -1, None
    return int(idx), {
        "func_outer": int(res[0]),
        "func_inner": int(res[1]),
        "gates": tuple(int(x) for x in res[2:7]),
    }


def lut5_search_cpu_mt(
    tables64: np.ndarray,
    target64: np.ndarray,
    mask64: np.ndarray,
    combos: np.ndarray,
    n_threads: int,
) -> Tuple[int, Optional[dict]]:
    """Threaded :func:`lut5_search_cpu` (disjoint contiguous slices, one
    OS thread per slice — the reference's N-rank operating point on the
    host's real cores).  The returned hit is the global first in combo
    order, identical to the serial scan's."""
    lib = _require()
    tables64 = np.ascontiguousarray(tables64, dtype=np.uint64)
    target64 = np.ascontiguousarray(target64, dtype=np.uint64)
    mask64 = np.ascontiguousarray(mask64, dtype=np.uint64)
    combos = np.ascontiguousarray(combos, dtype=np.int32)
    res = np.zeros(7, dtype=np.int32)
    idx = lib.sbg_lut5_search_cpu_mt(
        _ptr(tables64, ctypes.c_uint64),
        tables64.shape[0],
        _ptr(target64, ctypes.c_uint64),
        _ptr(mask64, ctypes.c_uint64),
        _ptr(combos, ctypes.c_int32),
        combos.shape[0],
        int(n_threads),
        _ptr(res, ctypes.c_int32),
    )
    if idx < 0:
        return -1, None
    return int(idx), {
        "func_outer": int(res[0]),
        "func_inner": int(res[1]),
        "gates": tuple(int(x) for x in res[2:7]),
    }


class GateStepCaller:
    """Per-context fast path for :func:`gate_step`: pre-resolves the match
    tables' raw addresses once (holding the buffers alive) so each node
    call only touches the three per-call operands.  The caller must pass
    contiguous uint32/uint64 table operands (``State.live_tables`` slices
    and numpy target/mask arrays are)."""

    __slots__ = ("_fn", "_bufs", "pair_a", "not_a", "triple_a")

    def __init__(
        self,
        pair_table: np.ndarray,
        not_table: Optional[np.ndarray],
        triple_table: Optional[np.ndarray],
    ):
        self._fn = _require().sbg_gate_step
        pair_table = _buf(pair_table, np.int16)
        not_table = (
            None if not_table is None else _buf(not_table, np.int16)
        )
        triple_table = (
            None if triple_table is None else _buf(triple_table, np.int16)
        )
        self._bufs = (pair_table, not_table, triple_table)  # keep alive
        self.pair_a = pair_table.ctypes.data
        self.not_a = None if not_table is None else not_table.ctypes.data
        self.triple_a = (
            None if triple_table is None else triple_table.ctypes.data
        )

    def __call__(
        self, tables, g, bucket, target, mask, use_not, use_triple,
        total3, chunk3, seed,
    ) -> np.ndarray:
        # Raw-address ABI: a non-contiguous or wrong-dtype operand would
        # make the C side read garbage silently, so check the contract
        # here (assert: stripped under -O, negligible vs the C work).
        assert (
            tables.flags["C_CONTIGUOUS"]
            and target.flags["C_CONTIGUOUS"]
            and mask.flags["C_CONTIGUOUS"]
        ), "gate_step operands must be C-contiguous"
        assert (
            tables.dtype in (np.uint32, np.uint64)
            and target.dtype in (np.uint32, np.uint64)
            and mask.dtype in (np.uint32, np.uint64)
        ), "gate_step operands must be uint32/uint64"
        # The C side reads g rows of 32 bytes from tables and one 32-byte
        # table from each of target/mask.
        assert (
            tables.shape[0] >= g
            and tables.shape[-1] * tables.itemsize == 32
            and target.nbytes == 32
            and mask.nbytes == 32
        ), "gate_step operand shapes do not match the 32-byte-row ABI"
        out = np.zeros(4, dtype=np.int32)
        self._fn(
            tables.ctypes.data,
            g,
            bucket,
            target.ctypes.data,
            mask.ctypes.data,
            self.pair_a,
            self.not_a if use_not else None,
            self.triple_a if use_triple else None,
            total3,
            chunk3,
            seed,
            out.ctypes.data,
        )
        return out


class GateEngineCaller:
    """Per-context entry to the native gate-mode search ENGINE
    (csrc sbg_gate_engine): the whole create_circuit recursion for
    non-LUT searches runs in C++, and only the final adopted gate
    additions come back for the Python State to replay (re-verifying).
    Caches the match tables and entry-materialization op rows once.

    Op row encoding (int32[8], one per match-table slot):
    [num_inputs, fun1, fun2, not_a, not_b, not_c, not_out, perm] with
    perm packing the entry's operand order two bits per slot — exactly
    what State.add_boolfunc_2/3 + decode_pair/triple_hit do in Python.
    """

    __slots__ = ("_fn", "_bufs", "pair_mt_a", "pair_ops_a", "not_mt_a",
                 "not_ops_a", "tri_mt_a", "tri_ops_a")

    @staticmethod
    def _ops_array(entries) -> np.ndarray:
        ops = np.zeros((max(len(entries), 1), 8), dtype=np.int32)
        for i, e in enumerate(entries):
            f = e.fun
            perm = 0
            for slot, p in enumerate(e.perm):
                perm |= (p & 3) << (2 * slot)
            ops[i] = (
                f.num_inputs, f.fun1,
                0 if f.fun2 is None else f.fun2,
                int(f.not_a), int(f.not_b), int(f.not_c), int(f.not_out),
                perm,
            )
        return ops

    def __init__(self, pair_table, pair_entries, not_table, not_entries,
                 triple_table, triple_entries):
        self._fn = _require().sbg_gate_engine
        pair_mt = _buf(pair_table, np.int16)
        pair_ops = self._ops_array(pair_entries)
        not_mt = None if not_table is None else _buf(not_table, np.int16)
        not_ops = self._ops_array(not_entries)
        tri_mt = (
            None if triple_table is None else _buf(triple_table, np.int16)
        )
        tri_ops = self._ops_array(triple_entries)
        self._bufs = (pair_mt, pair_ops, not_mt, not_ops, tri_mt, tri_ops)
        self.pair_mt_a = pair_mt.ctypes.data
        self.pair_ops_a = pair_ops.ctypes.data
        self.not_mt_a = None if not_mt is None else not_mt.ctypes.data
        self.not_ops_a = not_ops.ctypes.data
        self.tri_mt_a = None if tri_mt is None else tri_mt.ctypes.data
        self.tri_ops_a = tri_ops.ctypes.data

    def __call__(
        self, tables, g, num_inputs, max_gates, sat_metric, max_sat_metric,
        metric, target, mask, inbits, randomize, rng_seed, use_not,
    ):
        """Returns (out_gid, added int32[n,4], stats int64[3]); out_gid is
        NO_GATE (0xFFFF) when the search found nothing."""
        assert tables.flags["C_CONTIGUOUS"] and tables.shape[0] >= g
        assert tables.shape[-1] * tables.itemsize == 32
        inb = np.ascontiguousarray(
            np.asarray(list(inbits) or [0], dtype=np.int32)
        )
        out_gid = np.full(1, 0xFFFF, dtype=np.int32)
        added = np.zeros((max_gates + 8, 5), dtype=np.int32)
        stats = np.zeros(8, dtype=np.int64)
        n = self._fn(
            tables.ctypes.data,
            g,
            num_inputs,
            max_gates,
            sat_metric,
            max_sat_metric,
            metric,
            target.ctypes.data,
            mask.ctypes.data,
            self.pair_mt_a,
            self.pair_ops_a,
            self.not_mt_a if use_not else None,
            self.not_ops_a,
            self.tri_mt_a,
            self.tri_ops_a,
            inb.ctypes.data,
            len(inbits),
            int(bool(randomize)),
            rng_seed & 0xFFFFFFFFFFFFFFFF,
            out_gid.ctypes.data,
            added.ctypes.data,
            stats.ctypes.data,
        )
        if n < 0:
            return 0xFFFF, added[:0], stats
        return int(out_gid[0]), added[: int(n)], stats


class LutEngineCaller:
    """Per-context entry to the native LUT-mode search engine
    (csrc sbg_lut_engine): the whole LUT-mode create_circuit recursion.
    Device-work nodes (pivot-sized 5-LUT space, in-kernel solver
    overflow, staged 7-LUT) are serviced through the ``service``
    continuation callback and the native recursion resumes in place;
    without one (or when the service fails) the engine returns BAILED
    and the caller reruns through the Python engine."""

    BAILED = object()

    __slots__ = ("_fn", "_bufs", "_addrs")

    def __init__(self, pair_table, pair_entries):
        from ..ops import sweeps

        self._fn = _require().sbg_lut_engine
        pair_mt = _buf(pair_table, np.int16)
        pair_ops = GateEngineCaller._ops_array(pair_entries)
        _, w_tab, m_tab = sweeps.lut5_split_tables()
        idx_tab, _ = sweeps.lut7_pair_tables()
        orders, wo_tab, wm_tab, g_tab = sweeps.lut7_split_tables()
        bufs = (
            pair_mt,
            pair_ops,
            _buf(w_tab, np.uint32),
            _buf(m_tab, np.uint32),
            _buf(idx_tab, np.int32),
            _buf(np.asarray(orders), np.int32),
            _buf(wo_tab, np.uint32),
            _buf(wm_tab, np.uint32),
            _buf(g_tab, np.uint32),
        )
        self._bufs = bufs
        self._addrs = tuple(b.ctypes.data for b in bufs)

    def __call__(
        self, tables, g, num_inputs, max_gates, sat_metric, max_sat_metric,
        metric, target, mask, inbits, randomize, rng_seed, service=None,
        mux_threads=1, devcb=None,
    ):
        """Returns (out_gid, added int32[n,5], stats int64[8]) or
        (BAILED, None, stats) when the search needed device work and no
        service was attached (or it failed).  ``devcb`` is a pre-wrapped
        (callback, pending) pair from :func:`make_eng_devcb` — the hot
        path, with the wrapper's lifetime owned by the caller's context
        (the caller itself caches nothing: a per-caller cache would pin
        every dead context's service for the process lifetime).
        ``service`` alternatively wraps a raw callable per call.
        ``mux_threads > 1`` fans the outermost mux's branches out over
        C++ threads — the service must then be thread-safe
        (kwan._lut_engine_service isolates per-call views when the
        lever is on)."""
        assert tables.flags["C_CONTIGUOUS"] and tables.shape[0] >= g
        assert tables.shape[-1] * tables.itemsize == 32
        inb = np.ascontiguousarray(
            np.asarray(list(inbits) or [0], dtype=np.int32)
        )
        out_gid = np.full(1, 0xFFFF, dtype=np.int32)
        added = np.zeros((max_gates + 8, 5), dtype=np.int32)
        stats = np.zeros(8, dtype=np.int64)
        n_sigma = self._bufs[4].shape[0]
        # The CFUNCTYPE object must stay referenced for the whole engine
        # call — the C side holds only the bare function pointer; the
        # local variables keep it alive here, its owner (the context's
        # service-cache entry, or this frame for a raw `service`) beyond.
        pending = None
        if devcb is not None:
            cb, pending = devcb
        elif service is not None:
            cb, pending = make_eng_devcb(service)
        else:
            cb = None
        n = self._fn(
            tables.ctypes.data,
            g,
            num_inputs,
            max_gates,
            sat_metric,
            max_sat_metric,
            metric,
            target.ctypes.data,
            mask.ctypes.data,
            *self._addrs,
            n_sigma,
            inb.ctypes.data,
            len(inbits),
            int(bool(randomize)),
            rng_seed & 0xFFFFFFFFFFFFFFFF,
            int(mux_threads),
            cb,
            None,
            out_gid.ctypes.data,
            added.ctypes.data,
            stats.ctypes.data,
        )
        if pending is not None and pending["exc"] is not None:
            exc, pending["exc"] = pending["exc"], None
            raise exc
        if pending is not None and pending.get("service_exc") is not None:
            # The engine already bailed to the Python fallback (round-3
            # behavior); make the degradation and its cause visible at the
            # call site instead of only in the callback's log record.
            sexc, pending["service_exc"] = pending["service_exc"], None
            _note_service_failure(sexc)
        if n == -2:
            return self.BAILED, None, stats
        if n < 0:
            return 0xFFFF, added[:0], stats
        return int(out_gid[0]), added[: int(n)], stats


def gate_step(
    tables64: np.ndarray,
    g: int,
    bucket: int,
    target64: np.ndarray,
    mask64: np.ndarray,
    pair_table: np.ndarray,
    not_table: Optional[np.ndarray],
    triple_table: Optional[np.ndarray],
    total3: int,
    chunk3: int,
    seed: int,
) -> np.ndarray:
    """One fused gate-mode search node (steps 1-4) on the host.

    Same int32[4] verdict encoding and bit-identical candidate selection
    as ``sweeps.gate_step_stream`` — see the C entry point's docs.  Match
    tables are int16 arrays from ``SearchContext`` (None disables the
    NOT-pair / triple stages).  Table operands accept the uint32[..., 8]
    layout or its uint64[..., 4] view (same bytes).

    One-shot form of :class:`GateStepCaller` (which encodes the C ABI
    exactly once); hot per-node loops should hold a caller instead."""
    caller = GateStepCaller(pair_table, not_table, triple_table)
    return caller(
        _words(tables64),
        g,
        bucket,
        _words(target64),
        _words(mask64),
        not_table is not None,
        triple_table is not None,
        total3,
        chunk3,
        seed,
    )


def lut_step(
    tables64: np.ndarray,
    g: int,
    bucket: int,
    target64: np.ndarray,
    mask64: np.ndarray,
    pair_table: np.ndarray,
    excl: np.ndarray,
    total3: int,
    chunk3: int,
    has5: bool,
    total5: int,
    chunk5: int,
    solve_rows: int,
    w_tab: np.ndarray,
    m_tab: np.ndarray,
    seed: int,
) -> np.ndarray:
    """One fused LUT-mode head (steps 1-3 + 3-LUT + small-space 5-LUT) on
    the host: same int32[8] verdict encoding and bit-identical candidate
    selection as ``sweeps.lut_step_stream``.  ``excl`` is the list of
    mux-used input bit gate ids (applied by the 5-LUT stream only)."""
    lib = _require()
    tables64 = _words(tables64)
    target64 = _words(target64)
    mask64 = _words(mask64)
    pair_table = _buf(pair_table, np.int16)
    excl = _buf(excl, np.int32)
    w_tab = _buf(w_tab, np.uint32)
    m_tab = _buf(m_tab, np.uint32)
    out = np.zeros(8, dtype=np.int32)
    lib.sbg_lut_step(
        tables64.ctypes.data,
        g,
        bucket,
        target64.ctypes.data,
        mask64.ctypes.data,
        pair_table.ctypes.data,
        excl.ctypes.data,
        excl.shape[0],
        total3,
        chunk3,
        1 if has5 else 0,
        total5,
        chunk5,
        solve_rows,
        w_tab.ctypes.data,
        m_tab.ctypes.data,
        seed,
        out.ctypes.data,
    )
    return out


def lut7_stage_a(
    tables64: np.ndarray,
    g: int,
    target64: np.ndarray,
    mask64: np.ndarray,
    excl: np.ndarray,
    total7: int,
    chunk7: int,
    solve7: int,
    seed: int,
):
    """Host 7-LUT stage A: feasibility over C(g,7) ranks [0, chunk7) with
    the kernel's exact top-``solve7`` compaction order.  Returns
    (nfeas, ranks[int32, take], req1[uint32, take, 4], req0[...])."""
    lib = _require()
    tables64 = _words(tables64)
    target64 = _words(target64)
    mask64 = _words(mask64)
    excl = _buf(excl, np.int32)
    nfeas = np.zeros(1, dtype=np.int64)
    ranks = np.zeros(solve7, dtype=np.int32)
    req1 = np.zeros((solve7, 4), dtype=np.uint32)
    req0 = np.zeros((solve7, 4), dtype=np.uint32)
    take = lib.sbg_lut7_stage_a(
        tables64.ctypes.data,
        g,
        target64.ctypes.data,
        mask64.ctypes.data,
        excl.ctypes.data,
        excl.shape[0],
        total7,
        chunk7,
        solve7,
        seed,
        nfeas.ctypes.data,
        ranks.ctypes.data,
        req1.ctypes.data,
        req0.ctypes.data,
    )
    return int(nfeas[0]), ranks[:take], req1[:take], req0[:take]


def lut7_solve_small(
    req1: np.ndarray,
    req0: np.ndarray,
    solve7: int,
    idx_tab: np.ndarray,
    seed: int,
) -> np.ndarray:
    """Host 7-LUT stage-B solve for a small hit list: int32[4]
    [found, best_t, sigma, fo*256+fm], bit-identical to
    ``sweeps.lut7_solve`` on the same rows (pass the already-xored solver
    seed)."""
    lib = _require()
    req1 = _buf(req1, np.uint32)
    req0 = _buf(req0, np.uint32)
    if req1.shape[0] > 256:
        raise ValueError(f"at most 256 rows, got {req1.shape[0]}")
    idx_tab = _buf(idx_tab, np.int32)
    out = np.zeros(4, dtype=np.int32)
    lib.sbg_lut7_solve_small(
        req1.ctypes.data,
        req0.ctypes.data,
        req1.shape[0],
        solve7,
        idx_tab.ctypes.data,
        idx_tab.shape[0],
        seed,
        out.ctypes.data,
    )
    return out


def tables32_to_64(tables32: np.ndarray) -> np.ndarray:
    """uint32[..., 8] ttables -> the uint64[..., 4] layout the C ABI uses."""
    t = np.ascontiguousarray(tables32, dtype=np.uint32)
    assert t.shape[-1] == 8
    return t.view(np.uint64) if t.dtype.byteorder in ("=", "<", "|") else (
        t.astype("<u4").view(np.uint64)
    )
