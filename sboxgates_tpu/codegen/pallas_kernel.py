"""Pallas TPU kernel compilation of discovered circuits.

The reference emits CUDA where every LUT gate is an inline-PTX ``lop3.b32``
instruction (convert_graph.c:136-141) so circuits run natively on NVIDIA
hardware.  The TPU counterpart: the circuit unrolls into a Pallas kernel of
elementwise uint32 VPU ops over blocks of bitsliced words — one kernel
launch evaluates ``32 * W`` S-box inputs with no intermediate HBM traffic
(every gate value lives in registers/VMEM for the lifetime of a block).

The generated kernel computes all outputs in one pass; gate chains map to
the VPU the same way LOP3 chains map to the CUDA integer pipe.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..core import boolfunc as bf
from ..core import ttable as tt
from ..graph.state import State
from .executor import output_bits

BLOCK = 1024  # words per grid step; 32k evaluations per block


def compile_pallas(
    st: State, block: int = BLOCK, interpret: Optional[bool] = None
) -> Callable:
    """Builds ``fn(inputs) -> outputs`` backed by a Pallas TPU kernel.

    ``inputs``: uint32[num_inputs, W]; returns uint32[num_outputs, W] in
    ``output_bits(st)`` order.  W is padded to a multiple of ``block``
    internally (the pad is sliced off the output).  ``interpret=True``
    runs the kernel in interpreter mode; the default (None) follows the
    backend — compiled on TPU, interpreted on CPU, where pallas_call
    supports nothing else (so the README snippet runs anywhere).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() == "cpu"

    gates = [(g.type, g.in1, g.in2, g.in3, g.function) for g in st.gates]
    n_in = st.num_inputs
    outs = [st.outputs[b] for b in output_bits(st)]
    n_out = len(outs)

    def kernel(in_ref, out_ref):
        vals = [in_ref[i, :] for i in range(n_in)]
        for gtype, i1, i2, i3, func in gates[n_in:]:
            if gtype == bf.NOT:
                vals.append(~vals[i1])
            elif gtype == bf.LUT:
                vals.append(tt.eval_lut(func, vals[i1], vals[i2], vals[i3]))
            else:
                vals.append(tt.eval_gate2(gtype, vals[i1], vals[i2]))
        for row, o in enumerate(outs):
            out_ref[row, :] = vals[o]

    @jax.jit
    def fn(inputs):
        w = inputs.shape[1]
        wp = -(-w // block) * block
        if wp != w:
            inputs = jnp.pad(inputs, ((0, 0), (0, wp - w)))
        grid = (wp // block,)
        out = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[pl.BlockSpec((n_in, block), lambda i: (0, i))],
            out_specs=pl.BlockSpec((n_out, block), lambda i: (0, i)),
            out_shape=jax.ShapeDtypeStruct((n_out, wp), inputs.dtype),
            interpret=interpret,
        )(inputs)
        return out[:, :w] if wp != w else out

    return fn
