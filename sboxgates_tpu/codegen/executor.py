"""Circuit execution backends.

The reference's only way to *run* a discovered circuit is to emit C/CUDA and
compile it externally (convert_graph.c + the recompile tests in
.travis.yml:44-51).  Here circuits execute directly:

- :func:`compile_circuit` builds a jitted jax.numpy bitslice evaluator — the
  circuit unrolls into a chain of elementwise uint32 ops that XLA fuses into
  a handful of kernels (each lane bit is one evaluation; a [W]-word input
  batch evaluates 32*W S-box inputs at once).
- :func:`eval_sbox` runs the circuit over all 2^n inputs and returns the
  S-box table it implements (the independent verifier used by tests).
- :func:`execute_native` drives the C++ bitslice interpreter
  (csrc/runtime.cpp) over the 256-position truth-table domain.

See :mod:`sboxgates_tpu.codegen.pallas_kernel` for the Pallas TPU kernel
variant (the reference's CUDA-LOP3 counterpart).
"""

from __future__ import annotations

from typing import Callable, List, Tuple

import numpy as np

from ..core import boolfunc as bf
from ..core import ttable as tt
from ..graph.state import NO_GATE, State


def gate_arrays(st: State) -> Tuple[np.ndarray, ...]:
    """(types, in1, in2, in3, funcs) int32/uint8 arrays describing the
    circuit program (shared with the native interpreter's ABI)."""
    types = np.array([g.type for g in st.gates], dtype=np.int32)

    def arr(f):
        return np.array(
            [f(g) if f(g) != NO_GATE else -1 for g in st.gates], dtype=np.int32
        )

    in1 = arr(lambda g: g.in1)
    in2 = arr(lambda g: g.in2)
    in3 = arr(lambda g: g.in3)
    funcs = np.array([g.function for g in st.gates], dtype=np.uint8)
    return types, in1, in2, in3, funcs


def output_bits(st: State) -> List[int]:
    return [b for b in range(8) if st.outputs[b] != NO_GATE]


def compile_circuit(st: State, jit: bool = True) -> Callable:
    """Builds ``fn(inputs) -> outputs``: a bitslice evaluator.

    ``inputs``: unsigned integer array ``[num_inputs, ...]`` — bit j of lane
    word ``inputs[i]`` is input variable i of evaluation j.  Returns
    ``[num_outputs, ...]`` in ``output_bits(st)`` order.  The circuit is
    unrolled at trace time; XLA fuses the whole gate chain.
    """
    import jax
    import jax.numpy as jnp

    gates = [
        (g.type, g.in1, g.in2, g.in3, g.function)
        for g in st.gates
    ]
    n_in = st.num_inputs
    outs = [st.outputs[b] for b in output_bits(st)]

    def fn(inputs):
        vals = [inputs[i] for i in range(n_in)]
        for gtype, i1, i2, i3, func in gates[n_in:]:
            if gtype == bf.NOT:
                vals.append(~vals[i1])
            elif gtype == bf.LUT:
                vals.append(tt.eval_lut(func, vals[i1], vals[i2], vals[i3]))
            else:
                vals.append(tt.eval_gate2(gtype, vals[i1], vals[i2]))
        return jnp.stack([vals[o] for o in outs])

    return jax.jit(fn) if jit else fn


def eval_sbox(st: State) -> np.ndarray:
    """Evaluates the circuit over all 2^n inputs; returns the uint8 S-box
    table it implements (bits assembled from the circuit's output map)."""
    n = st.num_inputs
    fn = compile_circuit(st)
    inputs = np.stack([np.asarray(tt.input_table(i)) for i in range(n)])
    out = np.asarray(fn(inputs))  # [n_out, 8] uint32 truth tables
    bits = output_bits(st)
    table = np.zeros(256, dtype=np.uint8)
    for row, b in enumerate(bits):
        table |= tt.to_bits(out[row]).astype(np.uint8) << b
    return table[: 1 << n]


def execute_native(st: State) -> np.ndarray:
    """Runs the C++ interpreter; returns every gate's truth table as
    uint32[G, 8] (must equal ``st.live_tables()``)."""
    from .. import native

    types, in1, in2, in3, funcs = gate_arrays(st)
    itab = native.tables32_to_64(st.tables[: st.num_inputs])
    out64 = native.execute_circuit(types, in1, in2, in3, funcs, itab)
    return np.ascontiguousarray(out64).view(np.uint32).reshape(-1, 8)
