"""Codegen / conversion backends (reference: convert_graph.c).

Emitters (text, format-compatible with the reference):
- :func:`sboxgates_tpu.codegen.dot.digraph_text` — Graphviz DOT.
- :func:`sboxgates_tpu.codegen.c_emit.c_function_text` — self-contained C
  bitslice function, or CUDA with inline-PTX ``lop3.b32`` LUT macros when
  the circuit contains LUT gates.

Executors (TPU-native replacements for the reference's "compile the emitted
CUDA" workflow — circuits run directly on-chip):
- :func:`sboxgates_tpu.codegen.executor.compile_circuit` — jitted jax.numpy
  bitslice evaluator.
- :func:`sboxgates_tpu.codegen.pallas_kernel.compile_pallas` — a Pallas TPU
  kernel evaluating the circuit over blocks of bitsliced words.
- :func:`sboxgates_tpu.codegen.executor.execute_native` — the C++
  interpreter from csrc/runtime.cpp (host validation path).
"""

from .c_emit import c_function_text
from .dot import digraph_text
from .executor import compile_circuit, eval_sbox, execute_native, gate_arrays

__all__ = [
    "c_function_text",
    "digraph_text",
    "compile_circuit",
    "eval_sbox",
    "execute_native",
    "gate_arrays",
]
