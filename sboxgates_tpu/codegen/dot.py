"""Graphviz DOT emission (reference: print_digraph, convert_graph.c:48-85).

Byte-compatible with the reference's output: one node per gate labeled with
the gate-type name (underscores as spaces), ``IN i`` for inputs, the hex
function byte for LUTs; edges from each gate's inputs, and ``outN`` sinks
for the output map.
"""

from __future__ import annotations

from ..core import boolfunc as bf
from ..graph.state import NO_GATE, State


def digraph_text(st: State) -> str:
    lines = ["digraph sbox {"]
    for gid, g in enumerate(st.gates):
        if g.type == bf.IN:
            name = f"IN {gid}"
        elif g.type == bf.LUT:
            name = "0x%02x" % g.function
        else:
            name = bf.GATE_NAMES[g.type].replace("_", " ")
        lines.append(f'  gt{gid} [label="{name}"];')
    for gid in range(st.num_inputs, st.num_gates):
        g = st.gates[gid]
        for src in (g.in1, g.in2, g.in3):
            if src != NO_GATE:
                lines.append(f"  gt{src} -> gt{gid};")
    for bit in range(8):
        if st.outputs[bit] != NO_GATE:
            lines.append(f"  gt{st.outputs[bit]} -> out{bit};")
    lines.append("}")
    return "\n".join(lines) + "\n"
