"""C / CUDA source emission (reference: print_c_function,
convert_graph.c:109-229).

Emits a self-contained bitslice function: plain C with ``unsigned long long``
lanes, or — when the circuit contains 3-input LUT gates — CUDA where each
LUT is an inline-PTX ``lop3.b32`` macro, matching the reference's output
format statement for statement.

One deliberate deviation: the reference counts outputs by scanning only the
first ``num_inputs`` output slots (convert_graph.c:121,164 — harmless for
every stock S-box but wrong for circuits with more outputs than inputs);
this emitter scans all 8.
"""

from __future__ import annotations

from typing import List

from ..core import boolfunc as bf
from ..graph.state import NO_GATE, State

_EXPR = {
    bf.FALSE_GATE: "{o} = 0;",
    bf.AND: "{o} = {a} & {b};",
    bf.A_AND_NOT_B: "{o} = {a} & ~{b};",
    bf.A: "{o} = {a};",
    bf.NOT_A_AND_B: "{o} = ~{a} & {b};",
    bf.B: "{o} = {b};",
    bf.XOR: "{o} = {a} ^ {b};",
    bf.OR: "{o} = {a} | {b};",
    bf.NOR: "{o} = ~({a} | {b});",
    bf.XNOR: "{o} = ({a} & {b}) | (~{a} & ~{b});",
    bf.NOT_B: "{o} = ~{b};",
    bf.A_OR_NOT_B: "{o} = {a} | ~{b};",
    bf.NOT_A: "{o} = ~{a};",
    bf.NOT_A_OR_B: "{o} = ~{a} | {b};",
    bf.NAND: "{o} = ~({a} & {b});",
    bf.TRUE_GATE: "{o} = ~0;",
    bf.NOT: "{o} = ~{a};",
}

TYPE = "bit_t"


def _var_name(st: State, gid: int, ptr_out: bool) -> str:
    """Variable naming (reference: get_c_variable_name,
    convert_graph.c:93-107): inputs are struct fields, output gates are the
    out parameters, everything else numbered temporaries."""
    if gid < st.num_inputs:
        return f"in.b{gid}"
    for bit in range(8):
        if st.outputs[bit] == gid:
            return ("*" if ptr_out else "") + f"out{bit}"
    return f"var{gid}"


def _needs_decl(st: State, gid: int) -> bool:
    return gid >= st.num_inputs and all(st.outputs[b] != gid for b in range(8))


def c_function_text(st: State) -> str:
    """Returns the complete C (or CUDA) source text for the circuit.

    Raises ValueError when the circuit has no outputs (the reference prints
    an error and returns false, convert_graph.c:127-130).
    """
    cuda = any(g.type == bf.LUT for g in st.gates)
    out_bits = [b for b in range(8) if st.outputs[b] != NO_GATE]
    if not out_bits:
        raise ValueError("no output gates in circuit")
    ptr_ret = len(out_bits) > 1

    lines: List[str] = []
    if cuda:
        lines.append(
            '#define LUT(a,b,c,d,e) asm("lop3.b32 %0, %1, %2, %3, "#e";" : '
            '"=r"(a): "r"(b), "r"(c), "r"(d));'
        )
        lines.append(f"typedef int {TYPE};")
    else:
        lines.append(f"typedef unsigned long long int {TYPE};")
    lines.append("typedef struct {")
    for i in range(st.num_inputs):
        lines.append(f"  {TYPE} b{i};")
    lines.append("} bits;")

    qual = "__device__ __forceinline__ " if cuda else ""
    if ptr_ret:
        sig = f"{qual}void s(bits in"
        for b in out_bits:
            sig += f", {TYPE} *out{b}"
        sig += ") {"
    else:
        sig = f"{qual}{TYPE} s{out_bits[0]}(bits in) {{"
    lines.append(sig)

    for gid in range(st.num_inputs, st.num_gates):
        g = st.gates[gid]
        a = _var_name(st, g.in1, ptr_ret) if g.in1 != NO_GATE else ""
        b = _var_name(st, g.in2, ptr_ret) if g.in2 != NO_GATE else ""
        c = _var_name(st, g.in3, ptr_ret) if g.in3 != NO_GATE else ""
        o = _var_name(st, gid, ptr_ret)
        decl = _needs_decl(st, gid)
        start = f"  {TYPE} " if (decl or not o.startswith("*")) else "  "
        if g.type == bf.LUT:
            # Declare unless the target is the dereferenced out-parameter:
            # the reference emits a declaration even then
            # (convert_graph.c:217), which shadows the parameter and is
            # invalid C — corrected here.  Single-output return variables
            # (plain `out0`) DO need the declaration.
            decl_s = f"{TYPE} {o}; " if (decl or not o.startswith("*")) else ""
            lines.append(f"  {decl_s}LUT({o}, {a}, {b}, {c}, 0x%02x);" % g.function)
        else:
            lines.append(start + _EXPR[g.type].format(o=o, a=a, b=b))
        if not decl and not ptr_ret:
            lines.append(f"  return {o};")
    lines.append("}")
    return "\n".join(lines) + "\n"
