"""Deterministic fault injection.

Production code calls :func:`fault_point` at named host-side sites; when a
site is armed, the configured action fires there — letting tests kill the
process at arbitrary points and prove that checkpoints stay intact and
``--resume-run`` reproduces the uninterrupted result bit-for-bit.

Registered sites (the registry is open — any dotted name works, these are
the ones production code fires today):

========================  =====================================================
``ckpt.write``            mid-way through writing a checkpoint's temp file
``ckpt.replace``          after the temp file is durable, before ``os.replace``
``journal.append``        after a journal record reaches disk
``search.round``          between beam-search rounds (after the round record)
``search.node``           entering one ``create_circuit`` search node
``prefetch.produce``      producing one chunk in the streaming prefetcher
``dispatch.sweep``        issuing/resolving one device sweep dispatch
``native.devcb``          servicing one native-engine device-work callback
``warmup.compile``        one background AOT kernel compile (KernelWarmer)
``dist.verdict``          entering one replicated breach-verdict barrier
``serve.admit``           admitting one job into the serve-mode queue
``serve.preempt``         a serve job's journal-boundary control point
``serve.requeue``         requeuing a preempted/failed serve job
``serve.drain``           entering a serve-mode graceful drain
``serve.wave``            a lane entering its merged serve wave
``store.get``             entering a result-store lookup
``store.put``             before a result-store entry write
``store.index``           before a result-store index append
``net.accept``            dispatching one admission-API HTTP request
``net.auth``              checking one admission request's bearer token
``net.body``              reading one admission request's body
``net.admit_journal``     after an admission-journal record reaches disk
========================  =====================================================

Arming — ``SBG_FAULTS`` (read at first use) or :func:`arm`::

    SBG_FAULTS="site:action[@when][,site:action[@when]...]"

``action`` is ``raise`` (raise :class:`InjectedFault`), ``crash``
(``os._exit``, the uncatchable analog of SIGKILL/preemption), or ``hang``
(block forever — what a dead tunnel or wedged device RPC looks like).
``when`` selects hits of the site, counted from 1: ``N`` fires on exactly
the Nth hit, ``N+`` on the Nth and every later one; omitted means ``1+``
(every hit).  Hit counting is per-process and thread-safe; with a fixed
seed the schedules are deterministic, so the same spec kills the same
point every run.

Rank targeting — a site name may carry an ``@rank:N`` suffix
(``dispatch.sweep@rank:1:hang@2``): the fault then fires only on the
process whose distributed rank is ``N`` (``set_rank``, called by
``parallel.distributed.initialize``; overridable via ``SBG_FAULT_RANK``
for single-process tests).  This is how the multi-process harness hangs
or kills exactly one rank of a pod to exercise the replicated abort
protocol deterministically — every process can share one ``SBG_FAULTS``
value.  Hit counting for a rank-targeted site happens only on the
matching rank.

Job targeting — a site name may carry an ``@job:ID`` suffix
(``serve.preempt@job:j03:raise@2``): the fault then fires only on a
thread currently running serve-mode job ``ID`` (:func:`set_job`, called
by the serve orchestrator's worker threads around each job attempt;
overridable via ``SBG_FAULT_JOB`` for single-job tests).  This is how
the serve-mode chaos matrix preempts, kills, or poisons exactly one
tenant's job on a deterministic schedule while its neighbors run
undisturbed — the job-queue analog of ``@rank:N``.  Hit counting for a
job-targeted site happens only on threads running the matching job.

Tenant targeting — a site name may carry an ``@tenant:NAME`` suffix
(``net.auth@tenant:acme:raise``): the fault then fires only on a thread
currently serving tenant ``NAME`` (:func:`set_tenant`, called by the
network admission handler after authentication; overridable via
``SBG_FAULT_TENANT`` for single-tenant subprocess tests).  This is how
the admission chaos matrix rejects, kills, or stalls exactly one
tenant's traffic while the other tenants' requests flow undisturbed —
the front-door analog of ``@job:ID``.  Hit counting for a
tenant-targeted site happens only on threads serving the matching
tenant.
"""

from __future__ import annotations

import os
import re
import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional

CRASH_EXIT_CODE = 17

ACTIONS = ("raise", "crash", "hang")

#: Documented sites (informational; fault_point accepts any name).
KNOWN_SITES = (
    "ckpt.write",
    "ckpt.replace",
    "journal.append",
    "search.round",
    "search.node",
    "prefetch.produce",
    "dispatch.sweep",
    "native.devcb",
    "warmup.compile",
    "dist.verdict",
    "serve.admit",
    "serve.preempt",
    "serve.requeue",
    "serve.drain",
    "serve.wave",
    "store.get",
    "store.put",
    "store.index",
    "net.accept",
    "net.auth",
    "net.body",
    "net.admit_journal",
    "order.score",
)


class InjectedFault(RuntimeError):
    """Raised by an armed ``raise`` fault site."""


@dataclass(frozen=True)
class _Spec:
    action: str
    first: int       # 1-based hit ordinal the fault starts firing at
    once: bool       # True: fire on exactly `first`; False: `first` onward

    def fires(self, hit: int) -> bool:
        return hit == self.first if self.once else hit >= self.first


_WHEN_RE = re.compile(r"^(\d+)(\+?)$")
_RANK_RE = re.compile(r"@rank:(\d+)$")
_JOB_RE = re.compile(r"@job:([A-Za-z0-9_.\-]+)$")
_TENANT_RE = re.compile(r"@tenant:([A-Za-z0-9_.\-]+)$")

_lock = threading.Lock()
_specs: Dict[str, _Spec] = {}
_hits: Dict[str, int] = {}
_env_loaded = False
_rank: Optional[int] = None
#: Thread-local current serve-job id (set_job) for @job:ID matching —
#: per-THREAD, not per-process: the serve orchestrator runs many
#: tenants' jobs concurrently in one process, and a job-targeted fault
#: must fire only on the thread actually running that job.
_job_local = threading.local()
#: Thread-local current tenant (set_tenant) for @tenant:NAME matching —
#: per-THREAD like the job pin: the admission server handles many
#: tenants' requests concurrently in one process, and a tenant-targeted
#: fault must fire only on the thread serving that tenant.
_tenant_local = threading.local()
#: True when any armed site is rank-/job-/tenant-targeted — recomputed
#: under _lock by every _specs mutation, so fault_point's fast path
#: reads ONE bool per kind instead of iterating _specs (which background
#: threads would race against a concurrent arm()/disarm() resize).
_rank_targeted = False
_job_targeted = False
_tenant_targeted = False


def _note_specs_changed() -> None:
    """Caller holds _lock: refresh the rank-/job-/tenant-targeting
    flags."""
    global _rank_targeted, _job_targeted, _tenant_targeted
    _rank_targeted = any("@rank:" in s for s in _specs)
    _job_targeted = any("@job:" in s for s in _specs)
    _tenant_targeted = any("@tenant:" in s for s in _specs)


def set_rank(rank: Optional[int]) -> None:
    """Pins this process's distributed rank for ``@rank:N``-targeted
    sites (called by ``parallel.distributed.initialize``); ``None``
    restores the environment-variable fallback (tests)."""
    global _rank
    _rank = None if rank is None else int(rank)


def set_job(job_id: Optional[str]) -> None:
    """Pins the CALLING THREAD's serve-job id for ``@job:ID``-targeted
    sites (called by the serve orchestrator's worker threads around each
    job attempt); ``None`` clears it.  Thread-local by design — see
    :data:`_job_local`."""
    _job_local.job = None if job_id is None else str(job_id)


def _current_job() -> Optional[str]:
    """Job id used for ``@job:ID`` matching: the thread's :func:`set_job`
    value, else the ``SBG_FAULT_JOB`` environment fallback (single-job
    subprocess tests), else None (no job-qualified lookup)."""
    job = getattr(_job_local, "job", None)
    if job is not None:
        return job
    return os.environ.get("SBG_FAULT_JOB")


def current_job() -> Optional[str]:
    """The calling thread's :func:`set_job` pin (no env fallback) —
    for carrying the pin onto work handed to another thread (the result
    store's background writer keeps publishes @job:ID-targetable)."""
    return getattr(_job_local, "job", None)


def set_tenant(tenant: Optional[str]) -> None:
    """Pins the CALLING THREAD's tenant for ``@tenant:NAME``-targeted
    sites (called by the admission handler once a request's token
    resolves to a tenant); ``None`` clears it.  Thread-local by design —
    see :data:`_tenant_local`."""
    _tenant_local.tenant = None if tenant is None else str(tenant)


def _current_tenant() -> Optional[str]:
    """Tenant used for ``@tenant:NAME`` matching: the thread's
    :func:`set_tenant` value, else the ``SBG_FAULT_TENANT`` environment
    fallback (single-tenant subprocess tests), else None (no
    tenant-qualified lookup)."""
    tenant = getattr(_tenant_local, "tenant", None)
    if tenant is not None:
        return tenant
    return os.environ.get("SBG_FAULT_TENANT")


def current_tenant() -> Optional[str]:
    """The calling thread's :func:`set_tenant` pin (no env fallback) —
    for carrying the pin onto work handed to another thread."""
    return getattr(_tenant_local, "tenant", None)


def _process_rank() -> int:
    """Rank used for ``@rank:N`` matching: explicit :func:`set_rank` >
    ``SBG_FAULT_RANK`` > ``JAX_PROCESS_ID`` > 0.  Never imports jax — the
    unarmed fault fast path must stay a dict lookup."""
    if _rank is not None:
        return _rank
    for var in ("SBG_FAULT_RANK", "JAX_PROCESS_ID"):
        v = os.environ.get(var)
        if v is not None:
            try:
                return int(v)
            except ValueError:
                pass
    return 0


def parse_spec(text: str) -> Dict[str, _Spec]:
    """Parses an ``SBG_FAULTS`` value; raises ValueError on bad syntax."""
    out: Dict[str, _Spec] = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        # rsplit: the SITE itself may contain ':' (the @rank:N suffix).
        fields = part.rsplit(":", 1)
        if len(fields) != 2 or not fields[0]:
            raise ValueError(
                f"bad fault spec {part!r}: expected "
                "'site[@rank:N|@job:ID|@tenant:NAME]:action[@when]'"
            )
        site, action = fields
        if ":" in site and not (
            _RANK_RE.search(site) or _JOB_RE.search(site)
            or _TENANT_RE.search(site)
        ):
            raise ValueError(
                f"bad fault site {site!r} in {part!r}: a ':' in a site "
                "name is only valid as an '@rank:N', '@job:ID', or "
                "'@tenant:NAME' suffix"
            )
        when = "1+"
        if "@" in action:
            action, _, when = action.partition("@")
        if action not in ACTIONS:
            raise ValueError(
                f"bad fault action {action!r} in {part!r}: "
                f"expected one of {ACTIONS}"
            )
        m = _WHEN_RE.match(when)
        if m is None or int(m.group(1)) < 1:
            raise ValueError(
                f"bad fault trigger {when!r} in {part!r}: expected 'N' or 'N+'"
            )
        out[site] = _Spec(action, int(m.group(1)), once=m.group(2) != "+")
    return out


def _load_env() -> None:
    global _env_loaded
    if _env_loaded:
        return
    _env_loaded = True
    text = os.environ.get("SBG_FAULTS", "")
    if text:
        _specs.update(parse_spec(text))
    _note_specs_changed()


def arm(site: str, action: str, when: str = "1+") -> None:
    """Programmatically arms one site (tests; pair with :func:`disarm`)."""
    spec = parse_spec(f"{site}:{action}@{when}")
    with _lock:
        _load_env()
        _specs.update(spec)
        _note_specs_changed()


def disarm(site: Optional[str] = None) -> None:
    """Disarms one site (or all) and resets its hit counter(s)."""
    global _env_loaded
    with _lock:
        if site is None:
            _specs.clear()
            _hits.clear()
            _env_loaded = True  # a full reset also drops the env spec
        else:
            _specs.pop(site, None)
            _hits.pop(site, None)
        _note_specs_changed()


def hit_count(site: str) -> int:
    """Hits recorded so far at ``site`` (armed sites only)."""
    with _lock:
        return _hits.get(site, 0)


def fault_point(site: str) -> None:
    """Marks a named fault site; fires the armed action, if any.

    The unarmed fast path is one or two dict lookups (the plain name and
    this process's ``@rank:N``-qualified variant) — cheap enough for
    per-chunk and per-node call sites.
    """
    if not _env_loaded and not _specs:
        with _lock:
            _load_env()
    # The plain name and this process's rank-qualified / this thread's
    # job-qualified variants are all live when armed — arming "X"
    # pod-wide AND "X@rank:N" for one rank (or "X@job:ID" for one serve
    # job) honors every schedule (each keeps its own hit counter; the
    # plain spec fires first on a tie).  The qualified lookups happen
    # only when some armed site carries that kind of target, so the
    # common unarmed path stays a few dict gets.
    names = [site]
    if _rank_targeted:
        names.append(f"{site}@rank:{_process_rank()}")
    if _job_targeted:
        job = _current_job()
        if job is not None:
            names.append(f"{site}@job:{job}")
    if _tenant_targeted:
        tenant = _current_tenant()
        if tenant is not None:
            names.append(f"{site}@tenant:{tenant}")
    if all(_specs.get(n) is None for n in names):
        return
    spec = None
    hit = 0
    with _lock:
        # Re-read under the lock: a concurrent disarm() may have won.
        for n in names:
            s = _specs.get(n)
            if s is None:
                continue
            h = _hits.get(n, 0) + 1
            _hits[n] = h
            if spec is None and s.fires(h):
                spec, hit, site = s, h, n
    if spec is None:
        return
    if spec.action == "raise":
        raise InjectedFault(f"injected fault at {site} (hit {hit})")
    if spec.action == "crash":
        # The uncatchable death: no atexit, no finally, no flush beyond
        # this marker — exactly what preemption looks like to the files
        # on disk.  The flight recorder still dumps FIRST (deliberately:
        # real preemption on managed pods delivers SIGTERM before
        # SIGKILL, and the dump is the post-mortem that grace window
        # exists for); its incident hooks also force a final heartbeat
        # line, so the injected-crash tests can assert both artifacts.
        from ..telemetry import flight as _tflight

        _tflight.flight_dump(
            "injected_crash", extra={"site": site, "hit": hit}
        )
        print(
            f"[sbg-fault] crash at {site} (hit {hit})",
            flush=True,
        )
        os._exit(CRASH_EXIT_CODE)
    # hang: block forever in small sleeps (a daemon worker thread parked
    # here is abandonable; a caller under a deadline() guard times out).
    while True:
        time.sleep(0.05)
