"""Deterministic fault injection.

Production code calls :func:`fault_point` at named host-side sites; when a
site is armed, the configured action fires there — letting tests kill the
process at arbitrary points and prove that checkpoints stay intact and
``--resume-run`` reproduces the uninterrupted result bit-for-bit.

Registered sites (the registry is open — any dotted name works, these are
the ones production code fires today):

========================  =====================================================
``ckpt.write``            mid-way through writing a checkpoint's temp file
``ckpt.replace``          after the temp file is durable, before ``os.replace``
``journal.append``        after a journal record reaches disk
``search.round``          between beam-search rounds (after the round record)
``search.node``           entering one ``create_circuit`` search node
``prefetch.produce``      producing one chunk in the streaming prefetcher
``dispatch.sweep``        issuing/resolving one device sweep dispatch
``native.devcb``          servicing one native-engine device-work callback
``warmup.compile``        one background AOT kernel compile (KernelWarmer)
========================  =====================================================

Arming — ``SBG_FAULTS`` (read at first use) or :func:`arm`::

    SBG_FAULTS="site:action[@when][,site:action[@when]...]"

``action`` is ``raise`` (raise :class:`InjectedFault`), ``crash``
(``os._exit``, the uncatchable analog of SIGKILL/preemption), or ``hang``
(block forever — what a dead tunnel or wedged device RPC looks like).
``when`` selects hits of the site, counted from 1: ``N`` fires on exactly
the Nth hit, ``N+`` on the Nth and every later one; omitted means ``1+``
(every hit).  Hit counting is per-process and thread-safe; with a fixed
seed the schedules are deterministic, so the same spec kills the same
point every run.
"""

from __future__ import annotations

import os
import re
import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional

CRASH_EXIT_CODE = 17

ACTIONS = ("raise", "crash", "hang")

#: Documented sites (informational; fault_point accepts any name).
KNOWN_SITES = (
    "ckpt.write",
    "ckpt.replace",
    "journal.append",
    "search.round",
    "search.node",
    "prefetch.produce",
    "dispatch.sweep",
    "native.devcb",
    "warmup.compile",
)


class InjectedFault(RuntimeError):
    """Raised by an armed ``raise`` fault site."""


@dataclass(frozen=True)
class _Spec:
    action: str
    first: int       # 1-based hit ordinal the fault starts firing at
    once: bool       # True: fire on exactly `first`; False: `first` onward

    def fires(self, hit: int) -> bool:
        return hit == self.first if self.once else hit >= self.first


_WHEN_RE = re.compile(r"^(\d+)(\+?)$")

_lock = threading.Lock()
_specs: Dict[str, _Spec] = {}
_hits: Dict[str, int] = {}
_env_loaded = False


def parse_spec(text: str) -> Dict[str, _Spec]:
    """Parses an ``SBG_FAULTS`` value; raises ValueError on bad syntax."""
    out: Dict[str, _Spec] = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) != 2:
            raise ValueError(
                f"bad fault spec {part!r}: expected 'site:action[@when]'"
            )
        site, action = fields
        when = "1+"
        if "@" in action:
            action, _, when = action.partition("@")
        if action not in ACTIONS:
            raise ValueError(
                f"bad fault action {action!r} in {part!r}: "
                f"expected one of {ACTIONS}"
            )
        m = _WHEN_RE.match(when)
        if m is None or int(m.group(1)) < 1:
            raise ValueError(
                f"bad fault trigger {when!r} in {part!r}: expected 'N' or 'N+'"
            )
        out[site] = _Spec(action, int(m.group(1)), once=m.group(2) != "+")
    return out


def _load_env() -> None:
    global _env_loaded
    if _env_loaded:
        return
    _env_loaded = True
    text = os.environ.get("SBG_FAULTS", "")
    if text:
        _specs.update(parse_spec(text))


def arm(site: str, action: str, when: str = "1+") -> None:
    """Programmatically arms one site (tests; pair with :func:`disarm`)."""
    spec = parse_spec(f"{site}:{action}@{when}")
    with _lock:
        _load_env()
        _specs.update(spec)


def disarm(site: Optional[str] = None) -> None:
    """Disarms one site (or all) and resets its hit counter(s)."""
    global _env_loaded
    with _lock:
        if site is None:
            _specs.clear()
            _hits.clear()
            _env_loaded = True  # a full reset also drops the env spec
        else:
            _specs.pop(site, None)
            _hits.pop(site, None)


def hit_count(site: str) -> int:
    """Hits recorded so far at ``site`` (armed sites only)."""
    with _lock:
        return _hits.get(site, 0)


def fault_point(site: str) -> None:
    """Marks a named fault site; fires the armed action, if any.

    The unarmed fast path is one dict lookup — cheap enough for
    per-chunk and per-node call sites.
    """
    if not _env_loaded and not _specs:
        with _lock:
            _load_env()
    spec = _specs.get(site)
    if spec is None:
        return
    with _lock:
        # Re-read under the lock: a concurrent disarm() may have won.
        spec = _specs.get(site)
        if spec is None:
            return
        hit = _hits.get(site, 0) + 1
        _hits[site] = hit
        fire = spec.fires(hit)
    if not fire:
        return
    if spec.action == "raise":
        raise InjectedFault(f"injected fault at {site} (hit {hit})")
    if spec.action == "crash":
        # The uncatchable death: no atexit, no finally, no flush beyond
        # this marker — exactly what preemption looks like to the files
        # on disk.
        print(
            f"[sbg-fault] crash at {site} (hit {hit})",
            flush=True,
        )
        os._exit(CRASH_EXIT_CODE)
    # hang: block forever in small sleeps (a daemon worker thread parked
    # here is abandonable; a caller under a deadline() guard times out).
    while True:
        time.sleep(0.05)
