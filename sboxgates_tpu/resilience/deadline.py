"""Hung-dispatch deadlines with retry/backoff.

Generalized from bench.py's ad-hoc tunnel-death watchdog (observed live:
a dropped tunnel leaves an XLA device RPC blocked FOREVER — device calls
are not interruptible, so without a deadline the whole search hangs past
any external timeout).  :func:`dispatch_with_retry` runs one blocking
device-sweep resolve in an abandonable worker thread: on budget breach it
raises :class:`DispatchTimeout`, re-issues the dispatch with exponential
backoff, and after the retry budget re-raises so the calling driver can
degrade to its host-fallback path (see ``search.lut.lut5_search``).

Multi-host note: a process-spanning mesh runs its sweeps as pod-wide
collectives, so abort/retry decisions MUST be replicated — a process that
locally times out and re-issues while its peers keep waiting deadlocks
the collective.  The guard is therefore disabled on process-spanning
meshes unless explicitly forced (``SBG_DISPATCH_TIMEOUT_MULTIHOST=1``,
for deployments whose budgets and clocks are tight enough that every
process breaches together); the retry *schedule* itself is deterministic
(fixed budget, fixed backoff), never derived from locally divergent
state, so forced mode keeps processes aligned when their breaches do
coincide.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from .faults import fault_point

logger = logging.getLogger(__name__)


class DispatchTimeout(RuntimeError):
    """A device dispatch exceeded its deadline budget (retries included)."""


# Parallel mux-branch threads call dispatch_with_retry with ONE shared
# ctx.stats dict; an unlocked read-modify-write on the breach/retry
# counters loses increments exactly when breaches coincide (the case the
# counters exist to expose).  Same pattern as mesh._PALLAS_LOCK.
_stats_lock = threading.Lock()


@dataclass
class DeadlineConfig:
    """Deadline policy for blocking device-sweep resolves.

    ``budget_s <= 0`` disables the guard entirely (the default: deadlines
    are an operational opt-in — SBG_DISPATCH_TIMEOUT_S or
    ``Options.dispatch_timeout_s`` / ``--dispatch-timeout``)."""

    budget_s: float = 0.0
    retries: int = 2
    backoff_s: float = 0.25
    multihost: bool = False

    @property
    def enabled(self) -> bool:
        return self.budget_s > 0


def config_from_env() -> DeadlineConfig:
    """SBG_DISPATCH_TIMEOUT_S / SBG_DISPATCH_RETRIES /
    SBG_DISPATCH_BACKOFF_S / SBG_DISPATCH_TIMEOUT_MULTIHOST."""
    return DeadlineConfig(
        budget_s=float(os.environ.get("SBG_DISPATCH_TIMEOUT_S", "0")),
        retries=max(0, int(os.environ.get("SBG_DISPATCH_RETRIES", "2"))),
        backoff_s=float(os.environ.get("SBG_DISPATCH_BACKOFF_S", "0.25")),
        multihost=os.environ.get("SBG_DISPATCH_TIMEOUT_MULTIHOST", "0") == "1",
    )


def run_with_deadline(fn: Callable, budget_s: float, label: str = ""):
    """Runs ``fn()`` in a daemon worker, waiting at most ``budget_s``
    seconds.  On breach the worker is abandoned (a blocked device RPC
    cannot be interrupted; the daemon thread parks on it harmlessly) and
    :class:`DispatchTimeout` is raised in the caller."""
    if budget_s <= 0:
        return fn()
    box: dict = {}
    done = threading.Event()

    def work() -> None:
        try:
            box["value"] = fn()
        except BaseException as e:  # delivered to the caller below
            box["error"] = e
        finally:
            done.set()

    worker = threading.Thread(target=work, name="sbg-deadline", daemon=True)
    worker.start()
    if not done.wait(budget_s):
        raise DispatchTimeout(
            f"device dispatch{f' [{label}]' if label else ''} exceeded its "
            f"{budget_s:g}s deadline (hung RPC / dead tunnel?)"
        )
    if "error" in box:
        raise box["error"]
    return box["value"]


def dispatch_with_retry(
    fn: Callable,
    cfg: Optional[DeadlineConfig],
    stats: Optional[dict] = None,
    label: str = "",
    on_retry: Optional[Callable[[], None]] = None,
    site: str = "dispatch.sweep",
):
    """One guarded device-sweep resolve: deadline, retry, backoff.

    Every attempt first marks the ``dispatch.sweep`` fault site (so
    crash/raise injection works with or without deadlines armed), then
    runs ``fn`` under :func:`run_with_deadline`.  A breach increments
    ``stats['deadline_breaches']``; each retry increments
    ``stats['dispatch_retries']``, sleeps the exponentially-growing
    backoff, calls ``on_retry`` (re-issue the dispatch — retrying a
    resolve whose underlying RPC is already wedged would just block on
    the same corpse), and tries again.  After ``cfg.retries`` retries the
    final :class:`DispatchTimeout` propagates so the caller can degrade
    to its host-fallback path.

    ``cfg=None`` (or a disabled config) short-circuits to an inline call
    — zero threads, zero overhead beyond the fault-site lookup.
    """

    def attempt():
        fault_point(site)
        return fn()

    if cfg is None or not cfg.enabled:
        return attempt()
    delay = cfg.backoff_s
    for k in range(cfg.retries + 1):
        try:
            return run_with_deadline(attempt, cfg.budget_s, label)
        except DispatchTimeout as e:
            if stats is not None:
                with _stats_lock:
                    stats["deadline_breaches"] = (
                        stats.get("deadline_breaches", 0) + 1
                    )
            if k == cfg.retries:
                logger.warning(
                    "%s; %d retr%s exhausted", e, cfg.retries,
                    "y" if cfg.retries == 1 else "ies",
                )
                raise
            if stats is not None:
                with _stats_lock:
                    stats["dispatch_retries"] = (
                        stats.get("dispatch_retries", 0) + 1
                    )
            logger.warning("%s; retry %d/%d in %.2fs", e, k + 1,
                           cfg.retries, delay)
            time.sleep(delay)
            delay *= 2
            if on_retry is not None:
                on_retry()
    raise AssertionError("unreachable")
