"""Hung-dispatch deadlines with retry/backoff.

Generalized from bench.py's ad-hoc tunnel-death watchdog (observed live:
a dropped tunnel leaves an XLA device RPC blocked FOREVER — device calls
are not interruptible, so without a deadline the whole search hangs past
any external timeout).  :func:`dispatch_with_retry` runs one blocking
device-sweep resolve in an abandonable worker thread: on budget breach it
raises :class:`DispatchTimeout`, re-issues the dispatch with exponential
backoff, and after the retry budget re-raises so the calling driver can
degrade to its host-fallback path (see ``search.lut.lut5_search``).

Multi-host note: a process-spanning mesh runs its sweeps as pod-wide
collectives, so abort/retry decisions MUST be replicated — a process that
locally times out and re-issues while its peers keep waiting deadlocks
the collective.  :func:`replicated_dispatch_with_retry` is the
process-spanning variant: every guarded window ends in ONE verdict
barrier (``verdict``, normally
:func:`sboxgates_tpu.parallel.distributed.breach_verdict`) where each
host reports breach-vs-ok for its in-flight resolve and learns the
agreed verdict (breach if ANY host breached).  On an agreed breach ALL
hosts abandon the window together, re-issue on the same deterministic
backoff schedule, and — when the schedule exhausts — raise
:class:`DispatchTimeout` on every host in the same window, so the
callers' host-fallback degradation (and the ``ctx.device_degraded``
circuit breaker) flips in lockstep across the pod.  The barrier itself
runs in an abandonable ``sbg-abort-watch`` worker under the same budget:
a peer that cannot reach the barrier (killed rank, dead coordinator) is
indistinguishable from a breach and is treated as one, so the survivors
abort together instead of waiting forever.  The guard is ON by default
on process-spanning meshes whenever a deadline budget is configured;
``SBG_DISPATCH_TIMEOUT_MULTIHOST=0`` opts a deployment out.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from ..telemetry import flight as _tflight
from ..telemetry import metrics as _tmetrics
from ..telemetry import trace as _ttrace
from .faults import fault_point

logger = logging.getLogger(__name__)


class DispatchTimeout(RuntimeError):
    """A device dispatch exceeded its deadline budget (retries included)."""


def _bump(stats, key: str, by: int = 1) -> None:
    """Atomic counter increment through the telemetry facade: the ctx
    registry increments under its own lock; plain dicts (tests,
    per-attempt scratch) share the facade's module lock.  Parallel mux
    threads hit these counters with ONE shared stats object, and an
    unlocked read-modify-write would lose increments exactly when
    breaches coincide — the case the counters exist to expose."""
    _tmetrics.bump(stats, key, by)


def _flight_exhausted(reason: str, stats, label: str, windows: int) -> None:
    """Retry-schedule exhaustion is a flight-recorder incident: the dump
    carries the recent dispatch/deadline spans plus the breaching
    window's label, so a dead run leaves a post-mortem naming the span
    that killed it."""
    _ttrace.instant("deadline.exhausted", "deadline",
                    label=label, windows=windows)
    path = _tflight.flight_dump(
        reason,
        registry=stats if isinstance(stats, _tmetrics.MetricsRegistry)
        else None,
        extra={"label": label, "windows": windows},
    )
    if path is not None:
        _bump(stats, "flight_dumps")


@dataclass
class DeadlineConfig:
    """Deadline policy for blocking device-sweep resolves.

    ``budget_s <= 0`` disables the guard entirely (the default: deadlines
    are an operational opt-in — SBG_DISPATCH_TIMEOUT_S or
    ``Options.dispatch_timeout_s`` / ``--dispatch-timeout``)."""

    budget_s: float = 0.0
    retries: int = 2
    backoff_s: float = 0.25
    #: Guard process-spanning meshes too (the replicated-verdict abort
    #: protocol keeps abort/retry/degrade decisions in lockstep).  ON by
    #: default; ``SBG_DISPATCH_TIMEOUT_MULTIHOST=0`` opts out for
    #: deployments that prefer an unguarded pod.
    multihost: bool = True

    @property
    def enabled(self) -> bool:
        return self.budget_s > 0


def config_from_env() -> DeadlineConfig:
    """SBG_DISPATCH_TIMEOUT_S / SBG_DISPATCH_RETRIES /
    SBG_DISPATCH_BACKOFF_S / SBG_DISPATCH_TIMEOUT_MULTIHOST (opt-out)."""
    return DeadlineConfig(
        budget_s=float(os.environ.get("SBG_DISPATCH_TIMEOUT_S", "0")),
        retries=max(0, int(os.environ.get("SBG_DISPATCH_RETRIES", "2"))),
        backoff_s=float(os.environ.get("SBG_DISPATCH_BACKOFF_S", "0.25")),
        multihost=os.environ.get("SBG_DISPATCH_TIMEOUT_MULTIHOST", "1")
        != "0",
    )


def run_with_deadline(fn: Callable, budget_s: float, label: str = ""):
    """Runs ``fn()`` in a daemon worker, waiting at most ``budget_s``
    seconds.  On breach the worker is abandoned (a blocked device RPC
    cannot be interrupted; the daemon thread parks on it harmlessly) and
    :class:`DispatchTimeout` is raised in the caller."""
    if budget_s <= 0:
        return fn()
    box: dict = {}
    done = threading.Event()

    def work() -> None:
        try:
            box["value"] = fn()
        except BaseException as e:  # delivered to the caller below
            box["error"] = e
        finally:
            done.set()

    worker = threading.Thread(target=work, name="sbg-deadline", daemon=True)
    worker.start()
    if not done.wait(budget_s):
        raise DispatchTimeout(
            f"device dispatch{f' [{label}]' if label else ''} exceeded its "
            f"{budget_s:g}s deadline (hung RPC / dead tunnel?)"
        )
    if "error" in box:
        raise box["error"]
    return box["value"]


def dispatch_with_retry(
    fn: Callable,
    cfg: Optional[DeadlineConfig],
    stats: Optional[dict] = None,
    label: str = "",
    on_retry: Optional[Callable[[], None]] = None,
    site: str = "dispatch.sweep",
    lanes: Sequence[str] = (),
    flight_reason: str = "deadline_exhausted",
):
    """One guarded device-sweep resolve: deadline, retry, backoff.

    Every attempt first marks the ``dispatch.sweep`` fault site (so
    crash/raise injection works with or without deadlines armed), then
    runs ``fn`` under :func:`run_with_deadline`.  A breach increments
    ``stats['deadline_breaches']``; each retry increments
    ``stats['dispatch_retries']``, sleeps the exponentially-growing
    backoff, calls ``on_retry`` (re-issue the dispatch — retrying a
    resolve whose underlying RPC is already wedged would just block on
    the same corpse), and tries again.  After ``cfg.retries`` retries the
    final :class:`DispatchTimeout` propagates so the caller can degrade
    to its host-fallback path.

    ``lanes`` (the :func:`wave_dispatch_with_retry` form) attributes the
    window to a merged wave's lanes: breaches log/trace/flight-dump the
    lane list and the final :class:`DispatchTimeout` names every lane.
    ``cfg=None`` (or a disabled config) short-circuits to an inline call
    — zero threads, zero overhead beyond the fault-site lookup.
    """
    lane_tag = f" lanes={list(lanes)}" if lanes else ""

    def attempt():
        fault_point(site)
        return fn()

    if cfg is None or not cfg.enabled:
        return attempt()
    delay = cfg.backoff_s
    for k in range(cfg.retries + 1):
        try:
            return run_with_deadline(attempt, cfg.budget_s, label)
        except DispatchTimeout as e:
            _bump(stats, "deadline_breaches")
            _ttrace.instant("deadline.breach", "deadline", label=label,
                            attempt=k,
                            **({"lanes": list(lanes)} if lanes else {}))
            if k == cfg.retries:
                logger.warning(
                    "%s;%s %d retr%s exhausted", e, lane_tag, cfg.retries,
                    "y" if cfg.retries == 1 else "ies",
                )
                _flight_exhausted(
                    flight_reason, stats, f"{label}{lane_tag}",
                    cfg.retries + 1,
                )
                if lanes:
                    raise DispatchTimeout(f"{e}{lane_tag}") from None
                raise
            _bump(stats, "dispatch_retries")
            logger.warning("%s;%s retry %d/%d in %.2fs", e, lane_tag,
                           k + 1, cfg.retries, delay)
            time.sleep(delay)
            delay *= 2
            if on_retry is not None:
                on_retry()
    raise AssertionError("unreachable")


def wave_dispatch_with_retry(
    fn: Callable,
    cfg: Optional[DeadlineConfig],
    stats: Optional[dict] = None,
    label: str = "",
    lanes: Sequence[str] = (),
    on_retry: Optional[Callable[[], None]] = None,
):
    """One guarded window for a WHOLE merged fleet/serve wave dispatch.

    A merged wave resolve carries every lane's sweep in one device call,
    so guarding it lane-by-lane is impossible (there is one RPC) and
    guarding it per-submitter would park one abandonable worker per lane
    on the same corpse.  This is :func:`dispatch_with_retry`'s schedule
    applied to the single merged resolve, with the breach attributed to
    every lane riding the window: the raised :class:`DispatchTimeout`
    names the lanes (the per-lane drivers receiving it degrade/fail
    individually, which is where per-job retry/quarantine policy
    applies), the ``deadline.breach`` trace instant carries the lane
    list, and the exhaustion flight dump records it.  Counters: one
    ``deadline_breaches`` per breached window (the window IS the
    dispatch), ``dispatch_retries`` per re-issue."""
    return dispatch_with_retry(
        fn, cfg, stats=stats, label=label, on_retry=on_retry,
        lanes=lanes, flight_reason="wave_deadline_exhausted",
    )


def verdict_transport_timeout(budget_s: float) -> float:
    """How long the verdict TRANSPORT (the coordination-service barrier
    in ``distributed.breach_verdict``) may wait for peers: two window
    budgets — a peer that resolved instantly and one that breached at
    the full budget enter the same barrier one budget apart — plus one
    second of exchange slack.  ONE function shared by the transport and
    the abort watcher's abandon bound (which adds its own margin on
    top), so the two deadlines can never be tuned apart: a watcher that
    gives up before the transport would have completed splits the
    agreement."""
    return 2.0 * max(budget_s, 0.0) + 1.0


def _verdict_barrier(
    verdict: Callable[[bool], bool], breached: bool, budget_s: float,
    label: str = "",
) -> bool:
    """One replicated verdict-barrier round: report this host's
    breach-vs-ok, learn the agreed verdict.

    The barrier is itself a cross-host wait, and the failure it exists to
    survive (a killed rank, a dead coordinator) makes it unreachable — so
    it runs in its own abandonable ``sbg-abort-watch`` worker bounded by
    :func:`verdict_transport_timeout` (twice the window budget: a
    healthy peer may enter its verdict up to one full window later than
    us — its resolve ran the whole budget before breaching) PLUS a fixed
    margin, and only a barrier unreachable past that IS an agreed
    breach: the peers that cannot answer are exactly the ones the abort
    protocol must write off.  The margin ordering is load-bearing — the
    watcher must outlast the transport's own deadline
    (``breach_verdict`` waits exactly ``verdict_transport_timeout``), or
    one rank could abandon a barrier its peers go on to complete,
    splitting the "agreed" verdict and re-creating the unreplicated
    abort this protocol exists to prevent.  Marks the ``dist.verdict`` fault site on
    the watcher before entering the barrier (hang/crash injection there
    exercises the unreachable-barrier path deterministically).  Barrier
    errors other than a timeout propagate — a verdict transport raising
    is a loud configuration/runtime bug, not a breach signal.
    """
    box: dict = {}
    done = threading.Event()

    def _abort_watch() -> None:
        try:
            fault_point("dist.verdict")
            box["value"] = bool(verdict(breached))
        except BaseException as e:  # delivered below
            box["error"] = e
        finally:
            done.set()

    worker = threading.Thread(
        target=_abort_watch, name="sbg-abort-watch", daemon=True
    )
    worker.start()
    abandon_s = verdict_transport_timeout(budget_s) + 5.0
    if not done.wait(abandon_s):
        logger.warning(
            "verdict barrier%s unreachable within %.2gs (killed rank / "
            "dead coordinator?); treating the window as an agreed breach",
            f" [{label}]" if label else "", abandon_s,
        )
        return True
    if "error" in box:
        raise box["error"]
    return box["value"]


def replicated_dispatch_with_retry(
    fn: Callable,
    cfg: Optional[DeadlineConfig],
    verdict: Callable[[bool], bool],
    stats: Optional[dict] = None,
    label: str = "",
    on_retry: Optional[Callable[[], None]] = None,
    site: str = "dispatch.sweep",
):
    """Process-spanning counterpart of :func:`dispatch_with_retry`: the
    replicated degradation protocol.

    Every attempt window runs the blocking resolve under the deadline,
    then joins exactly ONE verdict barrier (one barrier per window, never
    per chunk — the sharded streams sweep many chunks inside one
    resolve, and the barrier rides the resolve): each host reports
    breach-vs-ok and ``verdict`` returns the agreed outcome.  On an
    agreed OK the local result is returned (it is replicated by
    construction — the sharded kernels all-gather their verdicts).  On an
    agreed breach EVERY host — including ones whose local resolve
    completed — abandons the window, sleeps the same deterministic
    backoff, re-issues via ``on_retry``, and tries again; when the
    schedule exhausts, every host raises :class:`DispatchTimeout` in the
    same window, so driver degradation to the host-fallback paths (and
    the ``ctx.device_degraded`` circuit-breaker flip) happens in
    lockstep.

    Counters (under the shared stats lock): ``breach_barriers`` (verdict
    rounds joined), ``deadline_breaches`` (local breaches),
    ``replicated_aborts`` (windows abandoned on an agreed breach, local
    or remote), ``dispatch_retries`` (re-issues), and ``degraded_ranks``
    (this rank exhausting its schedule and raising).

    ``cfg=None`` / disabled short-circuits inline with zero barriers,
    exactly like the single-host guard.
    """

    def attempt():
        fault_point(site)
        return fn()

    if cfg is None or not cfg.enabled:
        return attempt()
    delay = cfg.backoff_s
    for k in range(cfg.retries + 1):
        breached = False
        value = None
        with _ttrace.span("deadline.window", "deadline",
                          label=label, attempt=k) as sp:
            try:
                value = run_with_deadline(attempt, cfg.budget_s, label)
            except DispatchTimeout:
                breached = True
                _bump(stats, "deadline_breaches")
            agreed = _verdict_barrier(
                verdict, breached, cfg.budget_s, label
            )
            _bump(stats, "breach_barriers")
            sp.set(local_breach=breached, agreed_breach=agreed)
        if not agreed:
            return value
        _bump(stats, "replicated_aborts")
        if k == cfg.retries:
            _bump(stats, "degraded_ranks")
            logger.warning(
                "replicated abort%s: agreed breach window %d/%d — retry "
                "schedule exhausted, every rank degrades together",
                f" [{label}]" if label else "", k + 1, cfg.retries + 1,
            )
            _flight_exhausted(
                "replicated_degradation", stats, label, cfg.retries + 1
            )
            raise DispatchTimeout(
                f"device dispatch{f' [{label}]' if label else ''} "
                f"abandoned by replicated agreement after "
                f"{cfg.retries + 1} windows of {cfg.budget_s:g}s"
            )
        _bump(stats, "dispatch_retries")
        logger.warning(
            "replicated abort%s: agreed breach (local %s); retry %d/%d "
            "in %.2fs", f" [{label}]" if label else "",
            "breach" if breached else "ok", k + 1, cfg.retries, delay,
        )
        time.sleep(delay)
        delay *= 2
        if on_retry is not None:
            on_retry()
    raise AssertionError("unreachable")
