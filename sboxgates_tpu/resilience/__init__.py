"""Resilience subsystem: crash-safe checkpoints, exact search resume,
deterministic fault injection, and hung-dispatch deadlines.

Real S-box searches run for hours-to-days; at production scale preemption,
hung device dispatches, and partial writes are routine events, not edge
cases.  This package makes every one of them survivable:

- :mod:`checkpoint` — durable XML state writes (write-to-temp + fsync +
  ``os.replace`` with an integrity digest) and :func:`latest_valid_state`
  recovery of the newest intact checkpoint in a directory.
- :mod:`journal` — :class:`SearchJournal`, an append-only fsync'd JSONL
  (plus an atomically-replaced snapshot) recording round/iteration
  progress, beam membership, budget ratchets, and the host PRNG position,
  so ``--resume-run DIR`` continues a killed search with bit-identical
  final circuits.
- :mod:`faults` — named deterministic fault sites armed via
  ``SBG_FAULTS=site:action@when`` (actions: raise / crash / hang), used
  by the kill→resume tests to die at arbitrary points and prove recovery.
- :mod:`deadline` — :func:`dispatch_with_retry`, the reusable
  hung-dispatch guard (generalized from bench.py's ad-hoc tunnel-death
  watchdog): a blocked device sweep raises :class:`DispatchTimeout`
  within the configured budget, retries with exponential backoff, and the
  search drivers then degrade to the host-fallback path.  On
  process-spanning meshes, :func:`replicated_dispatch_with_retry` makes
  the abort/retry/degrade decisions by pod-wide agreement (one
  breach-verdict barrier per guarded window), so every rank abandons a
  hung collective together instead of one host deadlocking the others.
"""

from .checkpoint import (
    IntegrityError,
    durable_write_text,
    latest_valid_state,
    verify_digest,
    with_digest,
)
from .deadline import (
    DeadlineConfig,
    DispatchTimeout,
    dispatch_with_retry,
    replicated_dispatch_with_retry,
)
from .faults import InjectedFault, arm, disarm, fault_point
from .journal import SearchJournal

__all__ = [
    "IntegrityError",
    "durable_write_text",
    "latest_valid_state",
    "verify_digest",
    "with_digest",
    "DeadlineConfig",
    "DispatchTimeout",
    "dispatch_with_retry",
    "replicated_dispatch_with_retry",
    "InjectedFault",
    "arm",
    "disarm",
    "fault_point",
    "SearchJournal",
]
