"""The search journal: exact resume for interrupted searches.

A :class:`SearchJournal` is an append-only JSONL file in the run's output
directory, fsync'd per record, plus an atomically-replaced JSON snapshot
(so even a torn JSONL tail — the worst a crash can do to an append — loses
at most the record being written, and the reader tolerates that).

Records capture everything the drivers need to continue a killed search
such that the final circuits are **bit-identical** to an uninterrupted run
with the same seed:

- ``run_start`` — the search configuration (inputs, flags, the
  materialized seed) so ``--resume-run DIR`` can rebuild the
  ``SearchContext`` without the original command line;
- ``round_done`` / ``iter_done`` / ``mb_round_done`` — completed progress
  units: beam membership (by checkpoint filename — the states themselves
  live in the durable XML checkpoints), budget ratchets, and the host
  PRNG position (bit-generator state **plus** the unconsumed tail of the
  context's batched kernel-seed buffer — dropping the buffer would shift
  every later draw);
- ``run_done`` — the completed run's final beam, so a resume of a
  finished run is a no-op.

Granularity is the driver's natural unit (an iteration for the one-output
driver, a beam round for the full-graph and multibox drivers): a kill
anywhere inside a unit re-runs that unit from its recorded PRNG state,
which reproduces it exactly.

Ownership model (coordinator-owned journals): every journal has exactly
ONE writer — its coordinator.  For a single-process run that is the
process; for a pod-wide multi-host run the primary rank owns the run
journal and the non-primary ranks hold the READONLY view (restore
without racing the writer).  Process-spanning sweeps that shard JOBS
across ranks (``--shard-sweep``) decompose into per-job journals keyed
by job id under the run directory: each rank coordinates — and
journals — only the jobs of its own slice (:func:`shard_dir` for the
per-rank run journal, :meth:`SearchJournal.for_job` for a job's
journal), so ``--resume-run`` restores every shard exactly with no
cross-rank write contention.  The multibox one-output driver uses the
same per-job journals (one per box, under the box's checkpoint
subdirectory) whether sharded or not.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from ..telemetry import metrics as _tmetrics
from ..telemetry import trace as _ttrace
from .checkpoint import clean_stale_tmp, durable_write_text
from .faults import fault_point

#: Version 2: per-job / per-shard journal layout (shard-NN run journals,
#: job_done / jobs_done records, ``shard_sweep`` + ``shard_processes``
#: in the recorded configuration).
JOURNAL_VERSION = 2
JOURNAL_NAME = "search.journal.jsonl"
SNAPSHOT_NAME = "search.journal.json"
#: Snapshot refresh cadence (appends).  The JSONL is the source of truth
#: (fsync'd per record, torn tail truncated on resume); the snapshot is
#: the fallback for an unreadable JSONL, and a snapshot that lags by a
#: few records only makes a resume re-run those units deterministically
#: — correct, just redone — so it need not ride every append.
SNAPSHOT_EVERY = 8


class JournalError(Exception):
    """The journal is missing, unreadable, or inconsistent."""


def shard_dir(root: str, rank: int) -> str:
    """Per-rank run-journal directory of a job-sharded sweep: rank ``r``
    coordinates (and journals) its slice under ``root/shard-0r/``."""
    return os.path.join(root, f"shard-{rank:02d}")


class SearchJournal:
    """Append-only run journal; see the module docstring.

    Use :meth:`start` for a fresh run (truncates any previous journal in
    the directory and writes ``run_start``) and :meth:`resume` to
    continue one (cleans stale checkpoint temp files, replays the
    records).  Single-writer by design: only the primary process of a
    multi-host run journals (``distributed.is_primary``); peers validate
    the broadcast sequence number instead
    (``distributed.journal_seq_check``).
    """

    def __init__(
        self, directory: str, records: List[dict], readonly: bool = False,
        ckpt_root: Optional[str] = None,
    ):
        self.directory = directory
        self.records = records
        #: Read-only journals restore progress but never write: the
        #: non-coordinator processes of a multi-host resume share the run
        #: directory for restore, while writes stay coordinator-owned.
        self.readonly = readonly
        #: Root the recorded checkpoint paths resolve against.  Defaults
        #: to the journal's own directory; per-shard run journals
        #: (``shard_dir``) set it to the run's top-level --output-dir,
        #: where the per-box checkpoint subdirectories actually live.
        self.ckpt_root = ckpt_root
        #: True when this handle continued an existing journal (resume)
        #: rather than starting a fresh one — per-job journals derive
        #: their own fresh-vs-resume behavior from the run journal's.
        self.resumed = False
        self._unsnapshotted = 0

    # -- construction ------------------------------------------------------

    @classmethod
    def start(
        cls, directory: str, config: Dict[str, Any],
        ckpt_root: Optional[str] = None,
    ) -> "SearchJournal":
        os.makedirs(directory, exist_ok=True)
        j = cls(directory, [], ckpt_root=ckpt_root)
        # A new run in the directory owns it: drop the previous run's
        # snapshot FIRST (a crash between the truncate and the run_start
        # append must not leave an empty JSONL next to a stale snapshot
        # that a later resume would silently resurrect), then truncate.
        try:
            os.unlink(os.path.join(directory, SNAPSHOT_NAME))
        except FileNotFoundError:
            pass
        durable_write_text(j._path, "")
        j.append("run_start", version=JOURNAL_VERSION, config=config)
        return j

    @classmethod
    def resume(
        cls, directory: str, readonly: bool = False,
        ckpt_root: Optional[str] = None,
    ) -> "SearchJournal":
        records = cls.load_records(directory)
        if not records or records[0].get("type") != "run_start":
            raise JournalError(
                f"no resumable journal in {directory!r} "
                f"(missing run_start record)"
            )
        j = cls(directory, records, readonly=readonly, ckpt_root=ckpt_root)
        j.resumed = True
        if not readonly:
            # Re-materialize the JSONL as exactly the parsed records: a
            # crash mid-append can leave a torn, newline-less tail, and
            # appending onto that fragment would weld the next record to
            # garbage — silently truncating the journal at the NEXT
            # resume to wherever the weld sits.  Best-effort: when
            # several processes of a multi-host resume race through here
            # against one shared directory, the losers' rewrites may
            # fail (identical content either way) — the parsed records
            # already in memory are authoritative.
            try:
                clean_stale_tmp(directory)
                durable_write_text(
                    j._path,
                    "".join(
                        json.dumps(r, sort_keys=True) + "\n" for r in records
                    ),
                )
            except OSError as e:
                import logging

                logging.getLogger(__name__).warning(
                    "journal tail cleanup in %s failed (%s); continuing "
                    "with the parsed records", directory, e,
                )
        return j

    @classmethod
    def for_job(
        cls, root: str, job_id: str, config: Dict[str, Any], *,
        resume: bool, readonly: bool = False,
    ) -> "SearchJournal":
        """One JOB's journal under ``root/job_id/`` — the per-job half of
        the coordinator-owned layout.  Exactly one rank (the job's
        coordinator) holds the writable handle; a rank that only needs to
        replay the job's progress for lockstep (the non-primary view of a
        pod-wide run) passes ``readonly=True``.

        ``resume=False`` starts fresh (truncating any stale journal a
        previous run left in the job directory); ``resume=True``
        continues the existing journal, or starts fresh when the job
        never journaled before the kill — re-running such a job from its
        recorded PRNG position reproduces it exactly.  A readonly view of
        a job with no journal yet is an empty no-op handle."""
        d = os.path.join(root, job_id)
        if readonly:
            # A readonly view of a FRESH run must be empty even if a
            # stale journal from a previous run still sits in the job
            # directory — only the coordinator's start() truncates it,
            # and racing that truncation would replay stale progress.
            if resume:
                try:
                    return cls.resume(d, readonly=True)
                except JournalError:
                    pass
            return cls(d, [], readonly=True)
        if resume:
            try:
                return cls.resume(d)
            except JournalError:
                pass
        return cls.start(d, config)

    @property
    def writable(self) -> bool:
        return not self.readonly

    @staticmethod
    def load_records(directory: str) -> List[dict]:
        """Journal records, tolerating a torn final JSONL line; falls back
        to the atomic snapshot when the JSONL itself is unreadable.  The
        snapshot may lag the JSONL by up to ``SNAPSHOT_EVERY`` records —
        resuming from the earlier prefix just re-runs those units
        deterministically."""
        path = os.path.join(directory, JOURNAL_NAME)
        records: List[dict] = []
        try:
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        records.append(json.loads(line))
                    except json.JSONDecodeError:
                        break  # torn tail: the snapshot/earlier lines rule
        except OSError:
            records = []
        if records:
            return records
        snap = os.path.join(directory, SNAPSHOT_NAME)
        try:
            with open(snap, "r", encoding="utf-8") as f:
                data = json.load(f)
            return list(data.get("records", []))
        except (OSError, json.JSONDecodeError):
            return []

    # -- writing -----------------------------------------------------------

    @property
    def _path(self) -> str:
        return os.path.join(self.directory, JOURNAL_NAME)

    @property
    def seq(self) -> int:
        return len(self.records)

    def append(self, rtype: str, **payload: Any) -> dict:
        """Appends one fsync'd record, refreshes the atomic snapshot
        (every ``SNAPSHOT_EVERY`` appends, plus the run boundaries), and
        fires the ``journal.append`` fault site (after the record is
        durable — a crash there proves the record survives).  On a
        read-only journal this is a no-op."""
        rec = {"seq": self.seq, "type": rtype, **payload}
        if self.readonly:
            return rec
        line = json.dumps(rec, sort_keys=True)
        # Journal appends are trace spans (cat "journal"): the fsync is
        # real wall time on the driver's critical path, and the append
        # sequence is the backbone a flight-recorder dump correlates
        # dispatch activity against.
        with _ttrace.span(f"journal[{rtype}]", "journal",
                          seq=rec["seq"], dir=self.directory):
            with open(self._path, "a", encoding="utf-8") as f:
                f.write(line + "\n")
                f.flush()
                os.fsync(f.fileno())
        # Process-global tally (the journal has no ctx): heartbeat lines
        # and metrics.json surface it under "process".
        _tmetrics.GLOBAL.inc("journal_appends")
        self.records.append(rec)
        self._unsnapshotted += 1
        if (
            self._unsnapshotted >= SNAPSHOT_EVERY
            or rtype in ("run_start", "run_done")
        ):
            self._unsnapshotted = 0
            durable_write_text(
                os.path.join(self.directory, SNAPSHOT_NAME),
                json.dumps(
                    {"version": JOURNAL_VERSION, "records": self.records},
                    sort_keys=True,
                )
                + "\n",
            )
        fault_point("journal.append")
        return rec

    # -- reading -----------------------------------------------------------

    def last(self, rtype: str) -> Optional[dict]:
        for rec in reversed(self.records):
            if rec.get("type") == rtype:
                return rec
        return None

    def of_type(self, rtype: str) -> List[dict]:
        return [r for r in self.records if r.get("type") == rtype]

    @property
    def config(self) -> Dict[str, Any]:
        return self.records[0]["config"] if self.records else {}

    @property
    def complete(self) -> bool:
        return self.last("run_done") is not None

    def load_checkpoint(self, filename: str):
        """Loads a beam-member checkpoint recorded by filename (resolved
        against ``ckpt_root`` when set — per-shard run journals record
        paths relative to the run's top-level output directory)."""
        from ..graph.xmlio import load_state

        return load_state(
            os.path.join(self.ckpt_root or self.directory, filename)
        )
