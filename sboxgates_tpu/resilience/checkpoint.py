"""Durable checkpoint writes and torn-file recovery.

The reference's ``save_state`` (state.c:107-125) — and our port until this
module — truncated the target file in place, so a crash mid-write corrupts
the only copy.  Here every checkpoint write is write-to-temp + ``fsync`` +
``os.replace`` (atomic on POSIX within one filesystem) + best-effort
directory fsync, so at every instant the path holds either the complete
old bytes or the complete new bytes — never a torn file.

Integrity is verified on load through a digest recorded *inside* the file
as a trailing XML comment (``<!-- sbg:sha256=... -->``), which the
reference binary's parser ignores — interop with the reference format is
unchanged in both directions (its files simply carry no digest and are
validated structurally by the loader).

:func:`latest_valid_state` is the recovery entry point: the newest
checkpoint in a directory that passes digest + structural validation.
"""

from __future__ import annotations

import hashlib
import os
import re
import tempfile
from typing import Optional, Tuple

from .faults import fault_point

#: Temp-file prefix for in-flight writes; a crash can strand these, and
#: :func:`clean_stale_tmp` (called on resume) removes them.
TMP_PREFIX = ".sbg-tmp-"

# Process umask, sampled once at import (the get-is-a-set dance is not
# thread-safe, so it must not run per write): mkstemp creates 0600 temp
# files, and os.replace would carry that onto the published checkpoint —
# unreadable to the peers / reference tooling that could read the
# umask-governed files open(path, "w") used to produce.
_UMASK = os.umask(0)
os.umask(_UMASK)

_DIGEST_RE = re.compile(r"<!-- sbg:sha256=([0-9a-f]{64}) -->\s*\Z")


class IntegrityError(Exception):
    """A checkpoint's recorded digest does not match its contents."""


def digest_text(text: str) -> str:
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


def with_digest(text: str) -> str:
    """Appends the integrity digest as a trailing XML comment."""
    return f"{text}<!-- sbg:sha256={digest_text(text)} -->\n"


def split_digest(raw: str) -> Tuple[str, Optional[str]]:
    """(body, digest-or-None) — body is the text the digest covers."""
    m = _DIGEST_RE.search(raw)
    if m is None:
        return raw, None
    return raw[: m.start()], m.group(1)


def verify_digest(raw: str) -> str:
    """Returns the digest-covered body; raises :class:`IntegrityError` on
    mismatch.  Files without a recorded digest (e.g. written by the
    reference binary) pass through unchanged — the structural loader
    still validates them."""
    body, digest = split_digest(raw)
    if digest is not None and digest_text(body) != digest:
        raise IntegrityError(
            f"checkpoint digest mismatch (recorded {digest[:12]}..., "
            f"computed {digest_text(body)[:12]}...): torn or corrupted file"
        )
    return body


def _fsync_dir(directory: str) -> None:
    """Best-effort directory fsync so the rename itself is durable."""
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return  # some filesystems refuse O_RDONLY on directories
    try:
        os.fsync(fd)
    except OSError:
        pass  # fsync-on-dir unsupported: the rename is still atomic
    finally:
        os.close(fd)


def durable_write_text(
    path: str, text: str, fault_sites: Tuple[Optional[str], Optional[str]] = (None, None)
) -> None:
    """Atomically replaces ``path`` with ``text``.

    Write order: temp file in the same directory (same filesystem, so the
    final rename is atomic), content, ``fsync``, ``os.replace``, directory
    fsync.  ``fault_sites`` names the (mid-content, pre-replace) fault
    sites — checkpoint writes pass ``("ckpt.write", "ckpt.replace")``: a
    crash at the first leaves a torn *temp* file and the old checkpoint
    untouched; at the second, the complete old file.
    """
    directory = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(prefix=TMP_PREFIX, suffix=".tmp", dir=directory)
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            half = len(text) // 2
            f.write(text[:half])
            f.flush()
            if fault_sites[0]:
                fault_point(fault_sites[0])
            f.write(text[half:])
            f.flush()
            os.fchmod(f.fileno(), 0o666 & ~_UMASK)
            os.fsync(f.fileno())
        if fault_sites[1]:
            fault_point(fault_sites[1])
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(directory)


def clean_stale_tmp(directory: str) -> int:
    """Removes in-flight temp files stranded by a crash; returns the
    count.  Safe at resume time: no writer is live."""
    removed = 0
    try:
        # Sorted: removal order (and therefore the OSError fallback
        # behavior) must not depend on filesystem enumeration order.
        names = sorted(os.listdir(directory))
    except OSError:
        return 0
    for name in names:
        if name.startswith(TMP_PREFIX):
            try:
                os.unlink(os.path.join(directory, name))
                removed += 1
            except OSError:
                pass  # already gone or unremovable; not ours to fail on
    return removed


def latest_valid_state(directory: str):
    """(path, State) of the newest intact checkpoint in ``directory``, or
    None when no XML file there passes validation.

    "Intact" = digest verified (when recorded) and structurally loadable
    (:func:`sboxgates_tpu.graph.xmlio.load_state`); torn, truncated, or
    corrupted files are skipped, so recovery falls back file by file to
    the newest checkpoint that survived the crash.
    """
    from ..graph.xmlio import StateLoadError, load_state

    try:
        # Sorted: ties in the (mtime, path) recovery ordering below must
        # break identically on every platform — resume picks the same
        # checkpoint regardless of directory enumeration order.
        names = [
            n for n in sorted(os.listdir(directory))
            if n.endswith(".xml") and not n.startswith(TMP_PREFIX)
        ]
    except OSError:
        return None

    def mtime(p: str) -> float:
        try:
            return os.path.getmtime(p)
        except OSError:
            return 0.0

    paths = [os.path.join(directory, n) for n in names]
    paths.sort(key=lambda p: (mtime(p), p), reverse=True)
    for path in paths:
        try:
            return path, load_state(path)
        except (OSError, StateLoadError):
            continue
    return None
