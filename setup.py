"""Packaging hook: ship the native runtime source inside the package.

``csrc/runtime.cpp`` is the canonical source, built on demand by
``sboxgates_tpu.native`` with the host's C++ compiler.  Installed
environments don't have the repo's ``csrc/`` directory, so ``build_py``
drops a copy at ``sboxgates_tpu/native/runtime.cpp`` — the loader's
second candidate path (see ``native._SRC_CANDIDATES``).  Metadata lives
in pyproject.toml.
"""

import os
import shutil

from setuptools import setup
from setuptools.command.build_py import build_py


class build_py_with_runtime(build_py):
    def run(self):
        super().run()
        here = os.path.dirname(os.path.abspath(__file__))
        src = os.path.join(here, "csrc", "runtime.cpp")
        dst_dir = os.path.join(self.build_lib, "sboxgates_tpu", "native")
        if os.path.exists(src) and os.path.isdir(dst_dir):
            shutil.copy(src, os.path.join(dst_dir, "runtime.cpp"))


setup(cmdclass={"build_py": build_py_with_runtime})
