#!/usr/bin/env python
"""Benchmark: 5-LUT candidate sweep throughput on the AES S-box.

The north-star metric (BASELINE.json) is LUT candidates/sec/chip on the
Rijndael S-box.  One candidate = one 5-combination of gates examined for a
LUT(LUT(a,b,c),d,e) decomposition of target output bit 0 — the unit the
reference's search_5lut partitions over MPI ranks (lut.c:116-249).

Two measurements:

- **device**: the framework's real search path — one `lut5_search` call,
  which sweeps the full C(G,5) space inside a single jitted while_loop
  dispatch with device-side unranking (sboxgates_tpu.search.lut).
- **cpu baseline**: the reference-shaped single-core C++ loop
  (csrc/runtime.cpp: sbg_lut5_search_cpu — same semantics and per-candidate
  work shape as the reference's serial inner loop; the reference binary
  itself needs MPI + libxml2, not present in this image).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
import time

import numpy as np

G = 80          # gates in the bench state (mid-LUT-search scale): C(80,5) = 24,040,016
CPU_COMBOS = 1 << 16
REPEATS = 3     # timed full-space sweeps (device path)


def build_state():
    from sboxgates_tpu.core import boolfunc as bf
    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.graph.state import GATES, State
    from sboxgates_tpu.utils.sbox import parse_sbox

    with open("sboxes/rijndael.txt") as f:
        sbox, n = parse_sbox(f.read())
    st = State.init_inputs(n)
    rng = np.random.default_rng(0)
    while st.num_gates < G:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    return st, tt.target_table(sbox, 0), tt.mask_table(n)


def bench_device(st, target, mask) -> float:
    """Full C(G,5) sweep throughput (candidates/sec/chip) through the real
    search path: one `lut5_search` call sweeps the whole space inside a
    single jitted while_loop dispatch (device-side unranking; no hit for
    AES bit 0 over XOR layers, so the full space is examined)."""
    import jax

    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.lut import lut5_search

    # The jitted stream executes on a single chip (no mesh plan), so the
    # per-chip rate is the measured rate regardless of how many devices the
    # host exposes.
    n_chips = 1
    ctx = SearchContext(Options(seed=1, lut_graph=True))

    def run():
        # AES bit 0 over XOR layers admits no 5-LUT: a hit means the bench
        # state is wrong and the sweep stopped early.
        if lut5_search(ctx, st, target, mask, []) is not None:
            raise RuntimeError("unexpected 5-LUT hit in bench state")

    run()  # warmup/compile
    base = ctx.stats["lut5_candidates"]
    t0 = time.perf_counter()
    for _ in range(REPEATS):
        run()
    dt = time.perf_counter() - t0
    return (ctx.stats["lut5_candidates"] - base) / dt / n_chips


def bench_cpu_baseline(st, target, mask) -> float:
    """Reference-shaped serial C++ loop, candidates/sec on one core."""
    from sboxgates_tpu import native
    from sboxgates_tpu.ops import combinatorics as comb

    if not native.available():
        return float("nan")
    combos = comb.CombinationStream(G, 5).next_chunk(CPU_COMBOS)
    t64 = native.tables32_to_64(st.live_tables())
    tg64 = native.tables32_to_64(np.asarray(target))
    mk64 = native.tables32_to_64(np.asarray(mask))
    native.lut5_search_cpu(t64, tg64, mk64, combos[:1024])  # warmup
    t0 = time.perf_counter()
    idx, _ = native.lut5_search_cpu(t64, tg64, mk64, combos)
    dt = time.perf_counter() - t0
    if idx != -1:
        raise RuntimeError("unexpected 5-LUT hit in CPU baseline state")
    return combos.shape[0] / dt


def main() -> None:
    st, target, mask = build_state()
    cpu = bench_cpu_baseline(st, target, mask)
    dev = bench_device(st, target, mask)
    vs = dev / cpu if cpu == cpu and cpu > 0 else float("nan")
    print(
        json.dumps(
            {
                "metric": "lut5_candidates_per_sec_per_chip_aes",
                "value": round(dev, 1),
                "unit": "candidates/s",
                "vs_baseline": round(vs, 3) if vs == vs else None,
            }
        )
    )


if __name__ == "__main__":
    main()
