#!/usr/bin/env python
"""Benchmark: 5-LUT candidate sweep throughput on the AES S-box.

The north-star metric (BASELINE.json) is LUT candidates/sec/chip on the
Rijndael S-box.  One candidate = one 5-combination of gates examined for a
LUT(LUT(a,b,c),d,e) decomposition of target output bit 0 — the unit the
reference's search_5lut partitions over MPI ranks (lut.c:116-249).

Two measurements:

- **device**: the framework's fused filter+solve sweep
  (sboxgates_tpu.parallel.mesh.lut5_fused_step) streamed over the full
  C(G,5) space on the default JAX backend, end to end (host combination
  streaming included).
- **cpu baseline**: the reference-shaped single-core C++ loop
  (csrc/runtime.cpp: sbg_lut5_search_cpu — same semantics and per-candidate
  work shape as the reference's serial inner loop; the reference binary
  itself needs MPI + libxml2, not present in this image).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import numpy as np

G = 40          # gates in the bench state: C(40,5) = 658,008 candidates
CHUNK = 1 << 17
CPU_COMBOS = 1 << 16
REPEATS = 3     # timed full-space sweeps (device path)


def build_state():
    from sboxgates_tpu.core import boolfunc as bf
    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.graph.state import GATES, State
    from sboxgates_tpu.utils.sbox import parse_sbox

    with open("sboxes/rijndael.txt") as f:
        sbox, n = parse_sbox(f.read())
    st = State.init_inputs(n)
    rng = np.random.default_rng(0)
    while st.num_gates < G:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    return st, tt.target_table(sbox, 0), tt.mask_table(n)


def bench_device(st, target, mask) -> float:
    """Full C(G,5) sweep throughput (candidates/sec/chip) on the default
    JAX backend."""
    import jax

    from sboxgates_tpu.ops import combinatorics as comb
    from sboxgates_tpu.ops import sweeps
    from sboxgates_tpu.parallel.mesh import lut5_fused_step

    n_chips = max(1, jax.local_device_count())
    _, w_tab, m_tab = sweeps.lut5_split_tables()
    tables = np.zeros((64, 8), dtype=np.uint32)
    tables[:G] = st.live_tables()
    jt = jax.device_put(tables)
    jtarget, jmask = jax.device_put(np.asarray(target)), jax.device_put(np.asarray(mask))
    jw, jm = jax.device_put(w_tab), jax.device_put(m_tab)

    def sweep() -> int:
        stream = comb.CombinationStream(G, 5)
        n = 0
        while True:
            chunk = stream.next_chunk(CHUNK)
            if chunk is None:
                return n
            padded, nvalid = comb.pad_rows(chunk, CHUNK)
            valid = np.arange(CHUNK) < nvalid
            found, _, _ = lut5_fused_step(
                jt, jax.device_put(padded), jax.device_put(valid),
                jtarget, jmask, jw, jm, 7,
            )
            n += nvalid
            assert not bool(found)  # AES bit 0 from XOR layers: no hit

    sweep()  # warmup: jit compile + cache combination chunks
    t0 = time.perf_counter()
    total = sum(sweep() for _ in range(REPEATS))
    dt = time.perf_counter() - t0
    return total / dt / n_chips


def bench_cpu_baseline(st, target, mask) -> float:
    """Reference-shaped serial C++ loop, candidates/sec on one core."""
    from sboxgates_tpu import native
    from sboxgates_tpu.ops import combinatorics as comb

    if not native.available():
        return float("nan")
    combos = comb.CombinationStream(G, 5).next_chunk(CPU_COMBOS)
    t64 = native.tables32_to_64(st.live_tables())
    tg64 = native.tables32_to_64(np.asarray(target))
    mk64 = native.tables32_to_64(np.asarray(mask))
    native.lut5_search_cpu(t64, tg64, mk64, combos[:1024])  # warmup
    t0 = time.perf_counter()
    idx, _ = native.lut5_search_cpu(t64, tg64, mk64, combos)
    dt = time.perf_counter() - t0
    assert idx == -1
    return combos.shape[0] / dt


def main() -> None:
    st, target, mask = build_state()
    cpu = bench_cpu_baseline(st, target, mask)
    dev = bench_device(st, target, mask)
    vs = dev / cpu if cpu == cpu and cpu > 0 else float("nan")
    print(
        json.dumps(
            {
                "metric": "lut5_candidates_per_sec_per_chip_aes",
                "value": round(dev, 1),
                "unit": "candidates/s",
                "vs_baseline": round(vs, 3) if vs == vs else None,
            }
        )
    )


if __name__ == "__main__":
    main()
