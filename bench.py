#!/usr/bin/env python
"""Benchmark suite: the BASELINE.json envelope on one chip.

Headline metric (BASELINE.json north star): 5-LUT candidates/sec/chip on
the AES (Rijndael) S-box, measured through the real search driver at G=200
gates — one `lut5_search` call sweeps the full C(200,5) = 2.5e9 space via
the MXU pivot stream.  `vs_baseline` divides by the measured single-core
CPU rate of the reference-shaped C++ inner loop (csrc/runtime.cpp:
sbg_lut5_search_cpu — same semantics and per-candidate work shape as the
reference's serial loop, lut.c:116-249; the reference binary itself needs
MPI + libxml2, not in this image).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The full benchmark detail (G=500 sweep slice, gate-mode sweep rates
native vs device, DES S1 end-to-end wall times + solution quality on the
reference's CI configs (.travis.yml:40-48), the capped 7-LUT search, the
batch axis at pivot size, the BASELINE config-4/5 drivers (8-box DES
batch, 64-permutation sweep), and Pallas circuit-execution throughput)
is written to BENCH_DETAIL.json next to this file.  Rate entries carry
{value: median, min, max} spreads so tuning signal is distinguishable
from the link's throttle noise.
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import time

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
# Background kernel warmup stays off in the bench process: a warmer
# AOT-compiling the next bucket's ladder would contend for CPU inside
# measured windows.  --cold-start measures the compile-latency subsystem
# explicitly, in subprocesses it controls.
os.environ.setdefault("SBG_WARMUP", "0")

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
# SBG_BENCH_SMOKE=1: a CPU-sized dry run of the FULL main bench path
# (every entry, shrunk problem sizes, results to BENCH_SMOKE.json) so a
# code change can be validated end to end before the one shot at real
# silicon.  Never used for recorded numbers.
SMOKE = bool(os.environ.get("SBG_BENCH_SMOKE"))
G_HEAD = 60 if SMOKE else 200  # headline: C(200,5) = 2,535,650,040
CPU_COMBOS = 1 << 12 if SMOKE else 1 << 16
REPEATS = 2 if SMOKE else 3
# The reference is always run with many MPI ranks (.travis.yml:40-48); a
# modern 2-socket node commonly exposes 64+ cores.  vs_baseline is
# per-core (the honest unit we can measure on this 1-core host); the
# detail entry also reports the rate scaled to this many cores as the
# whole-node yardstick, assuming linear MPI scaling (the reference's
# sweep is embarrassingly parallel with no cross-rank traffic until a
# hit, so linear is the right model).
SOCKET_CORES = 64
# Per-entry watchdog budget (main path): generous vs the slowest healthy
# entry; entries that legitimately run longer pass budget= explicitly.
ENTRY_BUDGET_S = 900.0

# --- shared BENCH_*.json schema -------------------------------------------
# Every writer prepends ONE meta entry (entries[0], metric "meta"): the
# schema version, the t1-normalization convention every promotion
# decision uses, and where counters come from (the telemetry metrics
# registry — ctx.stats IS a registry snapshot source, not bespoke
# per-script accounting).  tests/test_telemetry.py rejects schema drift.
BENCH_SCHEMA = 1
BENCH_META_KEYS = (
    "metric", "schema", "t1_normalization", "counters_source", "smoke",
)


def bench_meta(**extra) -> dict:
    meta = {
        "metric": "meta",
        "schema": BENCH_SCHEMA,
        "t1_normalization": (
            "promotion decisions compare each entry's best/t1 ratio "
            "measured in its own window, never raw cand/s across windows"
        ),
        "counters_source": "telemetry.metrics registry (ctx.stats)",
        "smoke": SMOKE,
    }
    meta.update(extra)
    return meta


def with_meta(entries) -> list:
    """The shared meta block as ``entries[0]`` (idempotent; copies so
    callers' lists — and their ``detail[-1]`` reads — stay untouched)."""
    out = list(entries)
    if not out or out[0].get("metric") != "meta":
        out.insert(0, bench_meta())
    return out


def _spread(fn, n=REPEATS):
    """n timed reps -> {value: median, min, max} (throttle diagnostics:
    the tunnel chip varies ~2x between bursts; medians with spread make
    tuning signal distinguishable from noise)."""
    vals = sorted(fn() for _ in range(n))
    return {
        "value": vals[n // 2],
        "min": vals[0],
        "max": vals[-1],
        "reps": n,
    }


def build_state(g):
    from sboxgates_tpu.core import boolfunc as bf
    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.graph.state import GATES, State
    from sboxgates_tpu.utils.sbox import parse_sbox

    with open(os.path.join(HERE, "sboxes/rijndael.txt")) as f:
        sbox, n = parse_sbox(f.read())
    st = State.init_inputs(n)
    rng = np.random.default_rng(0)
    while st.num_gates < g:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    return st, tt.target_table(sbox, 0), tt.mask_table(n)


def bench_lut5_device(g, config=None) -> dict:
    """Full C(g,5) sweep through the real search path (candidates/s/chip).
    AES bit 0 over XOR layers admits no 5-LUT, so the whole space is swept.

    ``config`` (a bench_pivot_tile_batch ``best_config`` dict) re-drives
    the sweep under the A/B's winning lever settings via the production
    env levers — the capture half of the armed decision rule."""
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.lut import lut5_search

    st, target, mask = build_state(g)
    ctx = SearchContext(Options(seed=1, lut_graph=True))
    env = {}
    if config:
        env = {
            "SBG_PIVOT_TILE_BATCH": str(config["tile_batch"]),
            "SBG_PIVOT_PIPELINE": "1" if config["pipeline"] else "0",
            "SBG_PIVOT_BACKEND": config["backend"],
        }
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        def run():
            if lut5_search(ctx, st, target, mask, []) is not None:
                raise RuntimeError("unexpected 5-LUT hit in bench state")

        run()  # warmup/compile

        def one():
            base = ctx.stats["lut5_candidates"]
            t0 = time.perf_counter()
            run()
            dt = time.perf_counter() - t0
            return (ctx.stats["lut5_candidates"] - base) / dt

        s = _spread(one)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    suffix = "_best" if config else ""
    entry = {"metric": f"lut5_sweep_g{g}{suffix}", **s, "unit": "cand/s",
             "space": math.comb(g, 5),
             "seconds_per_sweep": math.comb(g, 5) / s["value"]}
    if config:
        entry["config"] = config
    return entry


# The decisive variant set: plain vs the four traffic levers (the
# fused kernel, its minimal-surface hedge, and the bf16 / fp8 count
# matrices).  Small enough that a minutes-long tunnel window warms and
# measures ALL of it — the armed decision (flip pivot_backend()'s
# default to any winner) needs nothing else.
CORE_VARIANTS = [
    (1, False, "xla"),
    (1, False, "xla_bf16"), (1, False, "xla_f8"),
    (1, False, "pallas"), (1, False, "pallas_pre"),
]
# The tuning ladder: the round-4-measured xla levers (re-measurement,
# not decision), lever compositions, and the pallas block shapes — each
# "pallas[_pre]:BLxBH" is a distinct static jit config, so one longer
# window captures the whole kernel tuning surface.  t1 rides along so
# the entry is self-contained against throttle drift.  Chip-only
# beyond the xla levers: in smoke the kernels run INTERPRETED (minutes
# per sweep) and the core entry already covers the code paths.
LADDER_VARIANTS = [
    (1, False, "xla"), (1, True, "xla"), (2, False, "xla"),
    (2, True, "xla"), (4, False, "xla"), (4, True, "xla"),
] + ([] if SMOKE else [
    (1, True, "xla_bf16"),
    (1, True, "pallas"),
    (1, False, "pallas:128x128"), (1, False, "pallas:128x256"),
    (1, False, "pallas_pre:128x128"),
    (1, False, "pallas_pre:128x256"),
    (1, False, "pallas_pre:256x256"),
])


def bench_pivot_tile_batch(variants=None, metric="pivot_tile_batch_ab") -> dict:
    """A/B of the pivot stream's ROOFLINE levers: full C(200,5) sweeps
    over (tile_batch, pipeline, backend) variants, interleaved
    same-process so throttle drift hits all variants equally.  Keys:
    t<T> = plain, t<T>p = pipelined, _<backend> suffix for non-xla;
    ``best``/``best_variant``/``best_config`` name the winning
    configuration (what the search path should default to).

    Two registered entries split the window risk: ``pivot_core_ab``
    (CORE_VARIANTS — the armed decision set, warmed and measured first
    so a short window still decides) and ``pivot_block_ladder``
    (LADDER_VARIANTS — tuning surface).  Each is self-contained with
    its own t1 baseline."""
    import jax.numpy as jnp

    from sboxgates_tpu.ops import sweeps
    from sboxgates_tpu.search.lut import PivotOperands, pivot_tile_shape

    g = G_HEAD
    st, target, mask = build_state(g)
    tl, th = pivot_tile_shape(g)
    tables = np.zeros((512, 8), np.uint32)
    tables[:g] = st.live_tables()
    ops = PivotOperands(
        g, tl, th, [], jnp.asarray(tables), target, mask, jnp.asarray
    )
    _, w_tab, m_tab = sweeps.lut5_split_tables()
    jw, jm = jnp.asarray(w_tab), jnp.asarray(m_tab)
    space = math.comb(g, 5)

    def sweep(tb, pl, backend):
        v = np.asarray(
            sweeps.lut5_pivot_stream(
                *ops.stream_args(), 0, ops.t_real, jw, jm, 1,
                tl=tl, th=th, tile_batch=tb, pipeline=pl, backend=backend,
            )
        )
        assert int(v[0]) == 0, "unexpected hit in bench state"

    out = {"metric": metric, "unit": "cand/s", "state_g": g}
    if variants is None:
        variants = CORE_VARIANTS + [
            v for v in LADDER_VARIANTS if v not in CORE_VARIANTS
        ]

    def vkey(v):
        k = f"t{v[0]}{'p' if v[1] else ''}"
        if v[2] != "xla":
            k += "_" + v[2].replace(":", "_")
        return k

    warmed = []
    for v in variants:
        # A variant whose backend fails to lower (e.g. the pallas kernel
        # on an unsupported toolchain) drops out of the A/B instead of
        # killing the whole entry.
        try:
            sweep(*v)  # compile/warm
            warmed.append(v)
        except Exception as e:
            out[f"{vkey(v)}_error"] = repr(e)[:300]
    variants = warmed
    if not variants:
        # Keep the collected per-variant *_error diagnostics in the
        # entry instead of losing them to run()'s exception handler.
        out["error"] = "every pivot-stream variant failed to warm"
        return out

    def one(tb, pl, backend):
        t0 = time.perf_counter()
        sweep(tb, pl, backend)
        return space / (time.perf_counter() - t0)

    # Round-robin the reps across variants so throttle drift hits all
    # of them equally (contiguous blocks would confound the A/B with
    # the chip's burst-vs-steady phases).
    rates = {v: [] for v in variants}
    for _ in range(REPEATS):
        for v in variants:
            rates[v].append(one(*v))
    best = best_v = None
    for v in variants:
        vals = sorted(rates[v])
        key = vkey(v)
        out[key] = vals[len(vals) // 2]
        out[f"{key}_spread"] = [vals[0], vals[-1]]
        if best is None or out[key] > out[best]:
            best, best_v = key, v
    # value = the t1 baseline when it survived, else the best variant
    # (a None value would NaN-poison ratio consumers).
    out["best"] = out[best]
    out["best_variant"] = best
    # Structured form of the winner so main() can re-drive the headline
    # sweep under it without reverse-parsing the key (the armed decision
    # rule: any variant beating t1 flips the production default).
    out["best_config"] = {
        "tile_batch": best_v[0], "pipeline": best_v[1],
        "backend": best_v[2],
    }
    out["value"] = out.get("t1", out[best])
    return out


def _mesh_scaling_worker() -> dict:
    """Measures the sharded SPMD streams at 1/2/4/8 virtual CPU devices
    (runs inside the subprocess bench_mesh_scaling spawns).

    The host has ONE physical core, so the devices timeshare it and the
    ideal result is CONSTANT total throughput as devices are added (work
    conservation).  The reported efficiency — rate(N) / rate(1) — is
    therefore a measurement of the SPMD program's own overhead
    (GSPMD partitioning, the per-round psum'd found flag, padding, and
    the all-gathered verdicts), which is the property that transfers to
    a real multi-chip mesh; it cannot measure real speedup without one.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp  # noqa: F401

    from sboxgates_tpu.ops import sweeps
    from sboxgates_tpu.parallel import MeshPlan, make_mesh
    from sboxgates_tpu.parallel.mesh import (
        sharded_feasible_stream,
        sharded_pivot_stream,
    )
    from sboxgates_tpu.search.context import SearchContext
    from sboxgates_tpu.search.lut import PivotOperands, pivot_tile_shape

    g = G_HEAD
    st, target, mask = build_state(g)
    # PRODUCTION tile shape: the SPMD overhead being measured is the
    # per-round psum barrier + gathers, and its relative cost depends
    # directly on how much work one round holds — a smaller test tile
    # would overstate it 16x (measured: 128x128 tiles show 0.60
    # efficiency at 8 devices where production tiles amortize the same
    # barrier over 16x the candidates).
    tl, th = pivot_tile_shape(g)
    _, w_tab, m_tab = sweeps.lut5_split_tables()
    tables_np = np.zeros((512, 8), np.uint32)
    tables_np[:g] = st.live_tables()
    binom = sweeps.binom_table()
    excl = SearchContext.excl_array([])

    # Window of consecutive FULL tiles (mid-space): boundary tiles are
    # mostly padding and would measure per-tile overhead, not rate.
    PIVOT_TILES = 16
    descs = sweeps.pivot_tile_descs(g, tl, th, [])
    sizes = (
        (descs[:, 2] - descs[:, 1]).astype(np.int64)
        * (descs[:, 4] - descs[:, 3]).astype(np.int64)
    )
    full = np.flatnonzero(
        np.convolve((sizes == tl * th).astype(int),
                    np.ones(PIVOT_TILES, int), "valid") == PIVOT_TILES
    )
    w0 = int(full[len(full) // 2])
    pivot_cands = int(sizes[w0 : w0 + PIVOT_TILES].sum())

    FEAS_CHUNK = 131072
    FEAS_TOTAL = 4 * FEAS_CHUNK
    DEVICE_COUNTS = (1, 2, 4, 8)

    setups = {}
    for dc in DEVICE_COUNTS:
        plan = MeshPlan(make_mesh(jax.devices()[:dc]))
        ops = PivotOperands(
            g, tl, th, [], plan.replicate(tables_np), target, mask,
            plan.replicate,
        )
        jw, jm = plan.replicate(w_tab), plan.replicate(m_tab)
        # Feasible-stream chunk rounded to a device multiple exactly as
        # the search driver rounds it (context.feasible_stream_driver).
        chunk = -(-FEAS_CHUNK // dc) * dc
        fargs = (
            plan.replicate(tables_np), plan.replicate(binom), g,
            plan.replicate(np.asarray(target)),
            plan.replicate(np.asarray(mask)), plan.replicate(excl),
            0, FEAS_TOTAL,
        )

        def pivot_once(plan=plan, ops=ops, jw=jw, jm=jm):
            t0 = time.perf_counter()
            v = np.asarray(
                sharded_pivot_stream(
                    plan, *ops.stream_args(), w0, w0 + PIVOT_TILES, jw, jm,
                    1, tl=tl, th=th,
                )
            )
            dt = time.perf_counter() - t0
            assert (v[:, 0] == 0).all(), "unexpected hit in bench state"
            return pivot_cands / dt

        def feas_once(plan=plan, fargs=fargs, chunk=chunk):
            t0 = time.perf_counter()
            verdict, _, _, _ = sharded_feasible_stream(
                plan, *fargs, k=5, chunk=chunk
            )
            vec = np.asarray(verdict)
            dt = time.perf_counter() - t0
            assert int(vec[0]) == 0, "unexpected feasible hit"
            return int(vec[2]) / dt

        pivot_once(), feas_once()  # compile/warm
        setups[dc] = (pivot_once, feas_once)

    # Round-robin the reps across device counts so load drift on the
    # shared host hits every count equally (a sequential 1->8 order
    # would confound scaling with drift).
    pivot_rates = {dc: [] for dc in DEVICE_COUNTS}
    feas_rates = {dc: [] for dc in DEVICE_COUNTS}
    for _ in range(REPEATS):
        for dc in DEVICE_COUNTS:
            pivot_rates[dc].append(setups[dc][0]())
            feas_rates[dc].append(setups[dc][1]())

    out = {
        "metric": "cpu_mesh_scaling",
        "unit": "efficiency_vs_1dev",
        "state_g": g,
        "tile_shape": [tl, th],
        "window_tiles": [w0, w0 + PIVOT_TILES],
        "physical_cores": os.cpu_count() or 1,
        "note": (
            "8 virtual XLA CPU devices timesharing {} physical core(s): "
            "ideal is flat total throughput; efficiency = rate(N)/rate(1)"
            " measures SPMD overhead, not real scale-out speedup"
        ).format(os.cpu_count() or 1),
    }
    pivot_med, feas_med = {}, {}
    for dc in DEVICE_COUNTS:
        pv, fv = sorted(pivot_rates[dc]), sorted(feas_rates[dc])
        pivot_med[dc], feas_med[dc] = pv[len(pv) // 2], fv[len(fv) // 2]
        out[f"pivot_rate_d{dc}"] = {
            "value": pivot_med[dc], "min": pv[0], "max": pv[-1],
            "reps": REPEATS,
        }
        out[f"feasible_rate_d{dc}"] = {
            "value": feas_med[dc], "min": fv[0], "max": fv[-1],
            "reps": REPEATS,
        }
    for dc in DEVICE_COUNTS[1:]:
        out[f"pivot_eff_d{dc}"] = pivot_med[dc] / pivot_med[1]
        out[f"feasible_eff_d{dc}"] = feas_med[dc] / feas_med[1]
    out["value"] = out["pivot_eff_d8"]
    return out


def _gather_bench_worker(pid: int, port: str) -> None:
    """One process of the 2-process gather-compaction bench (spawned by
    bench_gather_compaction; env pins CPU + 4 virtual devices before jax
    import).  Times the multi-host sharded feasible stream with the
    compacted O(GATHER_ROWS)-per-device gather vs the full-chunk gather
    on identical no-hit sweeps, interleaved.  Process 0 prints the JSON
    entry."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    from sboxgates_tpu.ops import sweeps
    from sboxgates_tpu.parallel import MeshPlan, distributed as dist, make_mesh
    from sboxgates_tpu.parallel.mesh import GATHER_ROWS, sharded_feasible_stream
    from sboxgates_tpu.search.context import SearchContext

    dist.initialize(f"127.0.0.1:{port}", 2, pid)
    assert jax.process_count() == 2
    plan = MeshPlan(make_mesh())
    n_dev = len(jax.devices())

    g = 64
    st, target, mask = build_state(g)
    tables_np = np.zeros((512, 8), np.uint32)
    tables_np[:g] = st.live_tables()
    chunk = 131072
    total = 4 * chunk
    fargs = (
        plan.replicate(tables_np), plan.replicate(sweeps.binom_table()), g,
        plan.replicate(np.asarray(target)),
        plan.replicate(np.asarray(mask)),
        plan.replicate(SearchContext.excl_array([])),
        0, total,
    )

    def run(compact):
        t0 = time.perf_counter()
        out = sharded_feasible_stream(
            plan, *fargs, k=5, chunk=chunk, compact=compact
        )
        vec = np.asarray(out[0])
        dt = time.perf_counter() - t0
        assert int(vec[0]) == 0, "unexpected feasible hit"
        return dt

    run(True), run(False)  # compile/warm both variants
    ct, ft = [], []
    for _ in range(REPEATS):
        ct.append(run(True))
        ft.append(run(False))
    ct.sort()
    ft.sort()
    if pid == 0:
        per = chunk // n_dev
        k_rows = min(GATHER_ROWS, per)
        entry = {
            "metric": "gather_compaction_2proc",
            "value": ct[len(ct) // 2], "unit": "s",
            "min": ct[0], "max": ct[-1], "reps": REPEATS,
            "full_gather_s": ft[len(ft) // 2],
            "full_gather_spread": [ft[0], ft[-1]],
            "speedup_vs_full": ft[len(ft) // 2] / ct[len(ct) // 2],
            "rows_shipped_compact": n_dev * k_rows,
            "rows_shipped_full": chunk,
            "note": (
                "2 CPU processes / loopback transport on one host — the "
                "row-count reduction ({}x) is exact; the wall-time delta "
                "understates a real DCN's"
            ).format(chunk // (n_dev * k_rows)),
        }
        print("GATHERBENCH " + json.dumps(entry), flush=True)


def bench_gather_compaction() -> dict:
    """Multi-host gather compaction cost (VERDICT r3 weak item 5): a
    2-process CPU run (4 virtual devices each, 8-device global mesh)
    times the compacted vs full-chunk cross-process gather of the
    sharded feasible stream.  Needs no accelerator."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--gather-bench-worker", str(i), port],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=1200)[0] for p in procs]
    if any(p.returncode != 0 for p in procs):
        raise RuntimeError(f"gather bench worker failed: {outs[0][-400:]}"
                           f" / {outs[1][-400:]}")
    for out in outs:
        for line in out.splitlines():
            if line.startswith("GATHERBENCH "):
                return json.loads(line[len("GATHERBENCH "):])
    raise RuntimeError(f"no GATHERBENCH line: {outs}")


def _cold_start_worker() -> None:
    """Child half of :func:`bench_cold_start`: measures time from process
    entry to the first resolved sweep dispatch of a fresh search context
    — the user-visible time-to-first-candidate cost that the persistent
    compilation cache (SBG_COMPILE_CACHE, set by the parent) turns from
    an XLA compile into an executable deserialize.  Prints one JSON line
    {t_import_s, t_first_dispatch_s, kernel_compiles, compile_stall_s}.
    """
    t0 = time.perf_counter()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    from sboxgates_tpu.search.warmup import configure_compile_cache

    configure_compile_cache(os.environ.get("SBG_COMPILE_CACHE"))
    from sboxgates_tpu.core import boolfunc as bf
    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.graph.state import GATES, State
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.lut import lut3_search

    t_import = time.perf_counter() - t0
    rng = np.random.default_rng(11)
    st = State.init_inputs(8)
    while st.num_gates < 24:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    target = np.asarray(rng.integers(0, 2**32, size=8), dtype=np.uint32)
    ctx = SearchContext(Options(
        seed=11, lut_graph=True, randomize=False, host_small_steps=False,
        warmup=False,
    ))
    lut3_search(ctx, st, target, tt.mask_table(8), [])
    print("COLDSTART " + json.dumps({
        "t_import_s": round(t_import, 4),
        "t_first_dispatch_s": round(time.perf_counter() - t0, 4),
        "kernel_compiles": ctx.stats["kernel_compiles"],
        "compile_stall_s": round(ctx.stats["compile_stall_s"], 4),
    }), flush=True)


def bench_cold_start() -> list:
    """Cold vs warm persistent-compile-cache time-to-first-dispatch.

    Runs :func:`_cold_start_worker` twice in fresh subprocesses sharing
    one empty cache directory: the first run pays the full XLA compiles
    and populates the cache; the second — the restart / ``--resume-run``
    shape — deserializes them.  The delta is exactly the compile latency
    the persistent cache removes from a restarted search's critical
    path."""
    import subprocess
    import sys
    import tempfile

    entries = []
    with tempfile.TemporaryDirectory(prefix="sbg_coldstart_") as cache:
        env = {
            k: v for k, v in os.environ.items() if k != "XLA_FLAGS"
        }
        env["JAX_PLATFORMS"] = "cpu"
        env["SBG_COMPILE_CACHE"] = cache
        # The measurement is THIS process tree's cache, not the repo's.
        env.pop("JAX_COMPILATION_CACHE_DIR", None)
        results = []
        for arm in ("cold", "warm"):
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--cold-start-worker"],
                capture_output=True, text=True, timeout=1200, env=env,
            )
            if r.returncode != 0:
                raise RuntimeError(
                    f"cold-start worker ({arm}) failed: {r.stderr[-800:]}"
                )
            line = next(
                ln for ln in r.stdout.splitlines()
                if ln.startswith("COLDSTART ")
            )
            results.append(json.loads(line[len("COLDSTART "):]))
        cold, warm = results
        entries.append({
            "metric": "cold_start_first_dispatch", "unit": "s",
            "value": cold["t_first_dispatch_s"], **{
                f"cold_{k}": v for k, v in cold.items()
            },
        })
        entries.append({
            "metric": "warm_start_first_dispatch", "unit": "s",
            "value": warm["t_first_dispatch_s"], **{
                f"warm_{k}": v for k, v in warm.items()
            },
        })
        stall_saved = cold["compile_stall_s"] - warm["compile_stall_s"]
        entries.append({
            "metric": "cold_start_speedup",
            "unit": "x (cold/warm time-to-first-dispatch)",
            "value": (
                round(cold["t_first_dispatch_s"]
                      / warm["t_first_dispatch_s"], 3)
                if warm["t_first_dispatch_s"] > 0 else None
            ),
            "compile_stall_saved_s": round(stall_saved, 4),
        })
    return entries


def _round_chain_problem(n_rounds: int, gates0: int, seed: int = 7):
    """A planted greedy chain: ``n_rounds`` targets, each realizable as
    one 3-LUT over the state as it stands at that round (gates append as
    the chain progresses, so later targets reference earlier planted
    gates).  Returns (start state, [(target, mask), ...])."""
    from sboxgates_tpu.core import boolfunc as bf
    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.graph.state import GATES, State

    rng = np.random.default_rng(seed)
    st = State.init_inputs(8)
    while st.num_gates < gates0:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    mask = tt.mask_table(8)
    sim = st.copy()
    rounds = []
    for _ in range(n_rounds):
        # Sorted BEFORE building the target: the simulated append uses
        # the same operand order, so the planted table and the chain's
        # appended table agree for non-symmetric functions too.
        a, b, c = sorted(
            int(x) for x in rng.choice(sim.num_gates, size=3, replace=False)
        )
        func = int(rng.integers(1, 255))
        tgt = tt.eval_lut(func, sim.table(a), sim.table(b), sim.table(c))
        rounds.append((tgt, mask))
        sim.add_lut(func, a, b, c)
    return st, rounds


def bench_device_rounds(n_fused: int = 8, n_rounds: int = None) -> list:
    """Fused multi-round driver vs the per-round loop
    (BENCH_MULTIROUND.json): the dispatch-count half of the multi-round
    tentpole, measurable on any backend.

    Both arms run the SAME planted greedy chain through
    ``search.rounds.run_round_chain`` — the per-round arm with
    ``rounds_per_dispatch=1`` (one device dispatch, one verdict sync,
    and one table upload per round: the historical shape), the fused arm
    with ``rounds_per_dispatch=N`` (the device advances sweep → verdict
    → append for N rounds per dispatch).  Counters come straight from
    the telemetry registry: ``device_dispatches``, the
    ``device_wait_s[round_driver]`` histogram count (the host-sync
    count), and the candidate totals for the cand/s column.  On CPU CI
    the cand/s ratio is noise — the hardware-independent claim is the
    ~1/N dispatch/sync ratio with bit-identical circuits; the cand/s
    column is wired so real silicon can advance the carried headline."""
    from sboxgates_tpu.search import Options, SearchContext, run_round_chain

    # Sized to stay inside the 64-gate table bucket for every window
    # (g0 + rounds + 2*N <= 64): the A/B then compiles exactly TWO
    # round_driver executables (the N=1 and N=8 rungs) — the dispatch
    # ratio is size-independent, and CPU CI pays seconds, not minutes,
    # of XLA compile for the heavy fused while_loop.  ``n_rounds``
    # overrides the default sizing (the --check drift gate pins a small
    # fixed chain: its gated ratios are size-independent, and the gate
    # runs on every tier-1 pass).
    if n_rounds is None:
        n_rounds = 24 if SMOKE else 32
    gates0 = 12
    entries = []
    arms = {}
    for label, n_per in (("per_round", 1), (f"fused_{n_fused}", n_fused)):
        # Warm pass (fresh problem copy) takes the jit compiles; the
        # measured pass reruns the identical chain on warm executables.
        for measured in (False, True):
            st, rounds = _round_chain_problem(n_rounds, gates0)
            ctx = SearchContext(Options(
                lut_graph=True, randomize=False, warmup=False,
                parallel_mux=False,
            ))
            t0 = time.perf_counter()
            outs = run_round_chain(
                ctx, st, rounds, rounds_per_dispatch=n_per
            )
            dt = time.perf_counter() - t0
        cand = int(ctx.stats["lut3_candidates"]) + int(
            ctx.stats["lut5_candidates"]
        )
        hist = ctx.stats.histograms().get("device_wait_s[round_driver]")
        syncs = int(hist["count"]) if hist else 0
        rpd = ctx.stats.histograms().get("rounds_per_dispatch")
        arms[label] = {
            "dispatches": int(ctx.stats["device_dispatches"]),
            "syncs": syncs,
            "sig": (tuple(outs), st.tables.tobytes()),
            "dt": dt,
            "cand": cand,
        }
        entries.append({
            "metric": f"device_rounds_{label}",
            "unit": "cand/s",
            "value": round(cand / dt) if dt > 0 else None,
            "rounds": n_rounds,
            "rounds_per_dispatch": n_per,
            "device_dispatches": arms[label]["dispatches"],
            "host_syncs": syncs,
            "rounds_on_device": int(ctx.stats["round_driver_rounds"]),
            "host_fallback_rounds": int(
                ctx.stats["round_driver_fallbacks"]
            ),
            "mean_rounds_per_dispatch": (
                round(rpd["total"] / rpd["count"], 3)
                if rpd and rpd["count"] else None
            ),
            "wall_s": round(dt, 4),
        })
    per, fused = arms["per_round"], arms[f"fused_{n_fused}"]
    identical = per["sig"] == fused["sig"]
    entries.append({
        "metric": "device_rounds_dispatch_ratio",
        "unit": "fused/per-round dispatches",
        "value": round(fused["dispatches"] / per["dispatches"], 4),
        "expected": round(1.0 / n_fused, 4),
        "sync_ratio": (
            round(fused["syncs"] / per["syncs"], 4) if per["syncs"] else None
        ),
        "speedup_wall": (
            round(per["dt"] / fused["dt"], 3) if fused["dt"] > 0 else None
        ),
        "circuits_bit_identical": identical,
    })
    if not identical:
        raise AssertionError(
            "fused round driver diverged from the per-round loop"
        )
    return entries


def _fleet_split_worker() -> list:
    """(jobs, candidates) fleet-mesh device-split sweep — runs inside
    the ``bench.py --fleet-split-worker`` subprocess (8 virtual CPU
    devices).  For each split, times (a) one 64-lane stacked gate-step
    sweep and (b) an 8-job device-routed toy fleet, asserting the
    circuits are identical across splits (the split changes placement,
    never results)."""
    from sboxgates_tpu.core import boolfunc as bf
    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.graph.state import GATES, State
    from sboxgates_tpu.parallel import FleetPlan, make_fleet_mesh
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.fleet import fleet_gate_step, toy_fleet_boxes
    from sboxgates_tpu.search.multibox import search_boxes_one_output

    def grow(g, seed):
        rng = np.random.default_rng(seed)
        st = State.init_inputs(8)
        while st.num_gates < g:
            a, b = rng.choice(st.num_gates, size=2, replace=False)
            st.add_gate(bf.XOR, int(a), int(b), GATES)
        return st

    dev = dict(
        seed=7, lut_graph=True, randomize=False, host_small_steps=False,
        native_engine=False,
    )
    mask = tt.mask_table(8)
    sts = [grow(20, s) for s in range(64)]
    rows = []
    base_step = None
    base_sig = None
    for cands in (1, 2, 4):
        plan = FleetPlan(make_fleet_mesh(candidates=cands))
        gctx = SearchContext(
            Options(seed=7, randomize=False, host_small_steps=False,
                    native_engine=False),
            fleet_plan=plan,
        )
        jobs = [(st, st.table(12).copy(), mask) for st in sts]
        out = fleet_gate_step(gctx, jobs)  # warm the split's executable
        t0 = time.perf_counter()
        out = fleet_gate_step(gctx, jobs)
        dt_step = time.perf_counter() - t0
        if base_step is None:
            base_step = out
        else:
            assert (out == base_step).all(), "split changed verdicts"
        fctx = SearchContext(
            Options(fleet=True, iterations=1, **dev), fleet_plan=plan
        )
        t0 = time.perf_counter()
        res = search_boxes_one_output(
            fctx, toy_fleet_boxes(8), 0, save_dir=None,
            log=lambda s: None, batched="fleet",
        )
        dt_fleet = time.perf_counter() - t0
        sig = {
            name: [
                [(g.type, g.in1, g.in2, g.in3, g.function)
                 for g in s.gates]
                for s in sts_
            ]
            for name, sts_ in res.items()
        }
        if base_sig is None:
            base_sig = sig
        else:
            assert sig == base_sig, "split changed circuits"
        rows.append({
            "job_shards": plan.n_job_shards,
            "candidate_shards": plan.n_candidate_shards,
            "stacked_step_wall_s": round(dt_step, 4),
            "fleet_8job_wall_s": round(dt_fleet, 3),
        })
    return rows


def bench_fleet() -> list:
    """Fleet-batched search ladder (BENCH_FLEET.json): jobs/hour,
    per-round device dispatch counts, and the stacked jobs-bucket
    ladder.

    Four sections:

    - ``fleet_dispatch_ladder`` — device-routed toy fleets, where every
      node head dispatches: records total rendezvous device dispatches
      (groups) per fleet size and the ratio vs the 1-job baseline.  The
      O(N)->O(1) claim is ``dispatch_ratio_vs_1job`` staying O(1): a
      fleet of N merges its same-kind sweeps, so total dispatches track
      the LONGEST job, not the sum (acceptance: <= 2x at 8 jobs; the
      256-job rung additionally asserts bit-identical circuits vs the
      serial loop — the stacked-wrapper acceptance gate).
    - ``fleet_stacked_ladder`` — the stacked-operand single-kernel
      sweep (``fleet_gate_step``) at 64/256/1024 lanes: ONE device
      dispatch per rung (``dispatch_ratio_vs_flat_slices`` vs the
      32-lane slicing a flat-capped fleet would need), per-lane verdict
      parity vs the per-job kernel, and a t1-normalized jobs/hour
      headline (t1 = the serial per-job dispatch loop, same window).
      The stacked-vs-flat crossover is read from ``vs_flat_slices``.
    - ``fleet_candidate_split`` — the 2-D (jobs, candidates) device
      split measured at (8,1)/(4,2)/(2,4) over 8 virtual CPU devices
      (subprocess), both for the stacked step and an 8-job toy fleet.
    - ``fleet_des_jobs_ladder`` — the production configuration (8 DES
      boxes, LUT mode, native-routed heads): jobs/hour at 1/8/64 jobs,
      fleet vs the serial per-job loop (the t1 baseline measured in the
      same window), with bit-equality of the per-box best gate counts
      asserted between arms.
    """
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.multibox import (
        load_box_jobs,
        search_boxes_one_output,
    )

    entries = []

    # -- section 1: dispatch counts, device-routed toys ------------------
    dev = dict(
        seed=7, lut_graph=True, randomize=False, host_small_steps=False,
        native_engine=False,
    )

    def run_toys(n_jobs):
        from sboxgates_tpu.search.fleet import toy_fleet_boxes

        boxes = toy_fleet_boxes(min(n_jobs, 8))
        iters = max(1, n_jobs // len(boxes))
        ctx = SearchContext(Options(fleet=True, iterations=iters, **dev))
        t0 = time.perf_counter()
        res = search_boxes_one_output(
            ctx, boxes, 0, save_dir=None, log=lambda s: None,
            batched="fleet",
        )
        dt = time.perf_counter() - t0
        assert all(sts for sts in res.values())
        return dt, ctx.stats

    ladder = (1, 8, 16) if SMOKE else (1, 8, 64, 256)
    run_toys(ladder[1])  # warm the kernel shapes out of the timed arms
    run_toys(ladder[0])
    base_dispatches = None
    for n_jobs in ladder:
        dt, st = run_toys(n_jobs)
        dispatches = st.get("device_dispatches", 0)
        if base_dispatches is None:
            base_dispatches = max(dispatches, 1)
        e = {
            "metric": f"fleet_dispatch_ladder_{n_jobs}job",
            "unit": "device dispatches (total for the fleet)",
            "value": dispatches,
            "jobs": n_jobs,
            "wall_s": round(dt, 3),
            "jobs_per_hour": round(n_jobs / dt * 3600, 1),
            "sweep_submits": st.get("fleet_submits", 0),
            "merged_rows_per_dispatch": round(
                st.get("fleet_lanes", 0)
                / max(st.get("fleet_dispatches", 0), 1), 2,
            ),
            "stacked_dispatches": st.get("fleet_stacked_dispatches", 0),
            "dispatch_ratio_vs_1job": round(
                dispatches / base_dispatches, 2
            ),
        }
        if n_jobs > 32:
            # The stacked-wrapper acceptance gate: a >32-job wave's
            # merged sweeps dispatch through the stacked jobs buckets
            # (no 32-lane slicing), bit-identical to the serial loop.
            from sboxgates_tpu.search.fleet import toy_fleet_boxes

            boxes = toy_fleet_boxes(8)
            iters = n_jobs // len(boxes)
            ctx_s = SearchContext(Options(iterations=iters, **dev))
            res_s = search_boxes_one_output(
                ctx_s, boxes, 0, save_dir=None, log=lambda s: None,
                batched=False,
            )
            ctx_f = SearchContext(
                Options(fleet=True, iterations=iters, **dev)
            )
            res_f = search_boxes_one_output(
                ctx_f, toy_fleet_boxes(8), 0, save_dir=None,
                log=lambda s: None, batched="fleet",
            )
            sig = lambda res: {  # noqa: E731
                name: [
                    [(g.type, g.in1, g.in2, g.in3, g.function)
                     for g in s.gates]
                    for s in sts
                ]
                for name, sts in res.items()
            }
            assert sig(res_f) == sig(res_s)
            e["gates_bitidentical_vs_serial"] = True
            e["stacked_dispatches"] = ctx_f.stats.get(
                "fleet_stacked_dispatches", 0
            )
        entries.append(e)

    # -- section 1b: the stacked jobs-bucket ladder (single kernel) ------
    from sboxgates_tpu.core import boolfunc as _bf
    from sboxgates_tpu.core import ttable as _tt
    from sboxgates_tpu.graph.state import GATES as _GATES, State as _State
    from sboxgates_tpu.search.fleet import fleet_gate_step

    def _grow_state(g, seed):
        rng = np.random.default_rng(seed)
        st = _State.init_inputs(8)
        while st.num_gates < g:
            a, b = rng.choice(st.num_gates, size=2, replace=False)
            st.add_gate(_bf.XOR, int(a), int(b), _GATES)
        return st

    gmask = _tt.mask_table(8)
    stacked_ladder = (64, 128) if SMOKE else (64, 256, 1024)
    sts_all = [_grow_state(20, s) for s in range(max(stacked_ladder))]
    gjobs_all = [
        (st, st.table(12).copy(), gmask) for st in sts_all
    ]
    sctx = SearchContext(Options(
        seed=7, randomize=False, host_small_steps=False,
        native_engine=False,
    ))
    # Parity spot check: stacked verdicts == per-job kernel verdicts.
    probe = fleet_gate_step(sctx, gjobs_all[:3])
    for (st, t, m), row in zip(gjobs_all[:3], probe):
        step, x0, _ = sctx.gate_step(st, t, m)
        assert int(row[0]) == step and int(row[1]) == x0
    for lanes in stacked_ladder:
        jobs = gjobs_all[:lanes]
        fleet_gate_step(sctx, jobs)  # warm the compiled shape
        d0 = sctx.stats["device_dispatches"]
        t0 = time.perf_counter()
        fleet_gate_step(sctx, jobs)
        dt = time.perf_counter() - t0
        dispatches = sctx.stats["device_dispatches"] - d0
        # Flat-capped arm: the same wave as ceil(n/32) 32-lane slices
        # (the pre-PR-8 dispatch shape at this fleet size).
        slices = [
            jobs[lo : lo + 32] for lo in range(0, lanes, 32)
        ]
        for sl in slices[:1]:
            fleet_gate_step(sctx, sl)  # warm the slice shape
        t0 = time.perf_counter()
        for sl in slices:
            fleet_gate_step(sctx, sl)
        dt_flat = time.perf_counter() - t0
        # t1 arm: the serial per-job dispatch loop, same window.
        t0 = time.perf_counter()
        for st, t, m in jobs:
            sctx.gate_step(st, t, m)
        dt_serial = time.perf_counter() - t0
        entries.append({
            "metric": f"fleet_stacked_ladder_{lanes}lane",
            "unit": "jobs/hour (one stacked node sweep per job, "
                    "t1-normalized)",
            "value": round(lanes / dt * 3600, 1),
            "lanes": lanes,
            "device_dispatches": dispatches,
            "dispatch_ratio_vs_flat_slices": round(
                dispatches / len(slices), 3
            ),
            "wall_s": round(dt, 4),
            "flat_slices": len(slices),
            "flat_slices_wall_s": round(dt_flat, 4),
            "vs_flat_slices": round(dt_flat / dt, 3),
            "t1_wall_s": round(dt_serial, 4),
            "vs_t1": round(dt_serial / dt, 3),
        })

    # -- section 1c: (jobs, candidates) device-split sweep ---------------
    # Spawned with 8 virtual CPU devices (this process may own only 1):
    # the 2-D fleet mesh's candidate axis, exercised at every split.
    import subprocess
    import sys as _sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    r = subprocess.run(
        [_sys.executable, os.path.abspath(__file__),
         "--fleet-split-worker"],
        capture_output=True, text=True, timeout=2400, env=env,
    )
    if r.returncode != 0:
        raise RuntimeError(f"fleet split worker failed: {r.stderr[-800:]}")
    for row in json.loads(r.stdout.strip().splitlines()[-1]):
        entries.append({
            "metric": (
                "fleet_candidate_split_"
                f"{row['job_shards']}x{row['candidate_shards']}"
            ),
            "unit": "s (64-lane stacked step / 8-job toy fleet walls)",
            "value": row["stacked_step_wall_s"],
            **row,
        })

    # -- section 2: the DES fleet, production configuration --------------
    paths = [os.path.join(HERE, f"sboxes/des_s{i}.txt") for i in range(1, 9)]

    def run_des(n_jobs, fleet):
        boxes = load_box_jobs(paths[: min(n_jobs, 8)])
        iters = max(1, n_jobs // len(boxes))
        ctx = SearchContext(Options(
            seed=7, lut_graph=True, randomize=False, iterations=iters,
            fleet=fleet,
        ))
        t0 = time.perf_counter()
        res = search_boxes_one_output(
            ctx, boxes, 0, save_dir=None, log=lambda s: None,
            batched="fleet" if fleet else False,
        )
        dt = time.perf_counter() - t0
        gates = {
            n: (min(s.num_gates - s.num_inputs for s in sts) if sts else None)
            for n, sts in res.items()
        }
        return dt, gates

    des_ladder = (1, 8) if SMOKE else (1, 8, 64)
    run_des(des_ladder[1], True)  # warm
    headline = None
    for n_jobs in des_ladder:
        fdt, fgates = run_des(n_jobs, True)
        sdt, sgates = run_des(n_jobs, False)
        assert fgates == sgates, (fgates, sgates)
        e = {
            "metric": f"fleet_des_jobs_ladder_{n_jobs}job",
            "unit": "jobs/hour",
            "value": round(n_jobs / fdt * 3600, 1),
            "jobs": n_jobs,
            "wall_s": round(fdt, 3),
            # t1 = the serial per-job loop, measured in this window.
            "t1_jobs_per_hour": round(n_jobs / sdt * 3600, 1),
            "t1_wall_s": round(sdt, 3),
            "vs_t1": round(sdt / fdt, 3),
            "gates": fgates,
        }
        entries.append(e)
        if n_jobs == 8:
            headline = e
    top_jobs = ladder[-1]
    entries.append({
        "metric": "fleet_headline",
        "unit": "jobs/hour (8-job DES fleet, t1-normalized)",
        "value": headline["value"],
        "vs_t1": headline["vs_t1"],
        "dispatch_ratio_8job_vs_1job": next(
            e["dispatch_ratio_vs_1job"] for e in entries
            if e["metric"] == "fleet_dispatch_ladder_8job"
        ),
        # The stacked-wrapper acceptance: the widest fleet's per-round
        # node sweeps stay O(1) dispatches (no 32-lane slicing).
        f"dispatch_ratio_{top_jobs}job_vs_1job": next(
            e["dispatch_ratio_vs_1job"] for e in entries
            if e["metric"] == f"fleet_dispatch_ladder_{top_jobs}job"
        ),
        # Stacked-vs-flat crossover (vs_flat_slices > 1 = stacked
        # faster): on CPU the two are within noise at 64 lanes and flat
        # slicing wins wall-clock at 1024 (no link latency to amortize
        # — same caveat as the pipeline bench); the dispatch-count
        # column is the hardware-independent half of the claim.
        "stacked_vs_flat_slices_by_rung": {
            str(e["lanes"]): e["vs_flat_slices"] for e in entries
            if e["metric"].startswith("fleet_stacked_ladder_")
        },
        "smoke": SMOKE,
    })
    return entries


def bench_mesh_scaling() -> dict:
    """CPU-mesh relative scaling of the sharded pivot / feasible streams
    (VERDICT r3 item 3): spawns a subprocess pinned to CPU with 8 virtual
    XLA devices (this process may own the accelerator backend) and runs
    :func:`_mesh_scaling_worker` there.  Needs no accelerator — runs in
    the degraded tunnel-down capture too."""
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--mesh-scaling-worker"],
        capture_output=True, text=True, timeout=2400, env=env,
    )
    if r.returncode != 0:
        raise RuntimeError(f"mesh worker failed: {r.stderr[-800:]}")
    return json.loads(r.stdout.strip().splitlines()[-1])


def bench_lut5_g500_slice(n_tiles=8 if SMOKE else 1500) -> dict:
    """Pivot-stream slice at the reference's MAX_GATES=500 scale: sweeps
    `n_tiles` mid-range tiles of the C(500,5)=2.55e11 space and reports the
    real-candidate rate (full-space sweeps take ~1.5 min/call)."""
    import jax.numpy as jnp

    from sboxgates_tpu.ops import sweeps
    from sboxgates_tpu.search.lut import PivotOperands, pivot_tile_shape

    g = 500
    st, target, mask = build_state(g)
    tl, th = pivot_tile_shape(g)
    tables = np.zeros((512, 8), np.uint32)
    tables[:g] = st.live_tables()
    ops = PivotOperands(
        g, tl, th, [], jnp.asarray(tables), target, mask, jnp.asarray
    )
    t_real = ops.t_real
    sizes = np.diff(ops.size_cum)
    _, w_tab, m_tab = sweeps.lut5_split_tables()
    jw, jm = jnp.asarray(w_tab), jnp.asarray(m_tab)
    start = t_real // 2
    end = min(start + n_tiles, t_real)

    def run():
        return np.asarray(
            sweeps.lut5_pivot_stream(
                *ops.stream_args(), start, end, jw, jm, 1, tl=tl, th=th,
            )
        )

    run()
    t0 = time.perf_counter()
    v = run()
    dt = time.perf_counter() - t0
    assert int(v[0]) == 0, "unexpected hit in bench slice"
    real = int(sizes[start:end].sum())
    rate = real / dt
    return {
        "metric": "lut5_sweep_g500_slice", "value": rate, "unit": "cand/s",
        "space": math.comb(g, 5),
        "est_full_sweep_seconds": math.comb(g, 5) / rate,
    }


def bench_host_stream_pipeline(g=None, strict_guards=False) -> list:
    """Serial-vs-pipelined A/B of the host-chunked 5-LUT fallback
    (search.lut._lut5_search_host): the same full no-hit C(g,5) sweep
    driven at pipeline_depth=1 (the historical strictly-serial driver)
    and at the default depth 2 (async double-buffered chunk pipeline —
    background unrank/filter/pad producer + multiple filter dispatches
    in flight), interleaved in one window so throttle drift hits both
    arms equally.  Reports host-stream candidates/sec for each arm, the
    speedup, and the profiler's overlap accounting (device-wait,
    host-produce, consumer-stall, and off-critical-path seconds) — the
    latter shows the pipeline working even where raw rates are noisy
    (e.g. CPU-only CI): off_critical_path_s -> host_produce_s means the
    consumer never waited for combination generation.

    Production only routes here past int32 rank arithmetic
    (C(g,5) >= 2**31, i.e. g >= 386); driving the driver directly at a
    small g keeps the entry minutes-scale while exercising the identical
    code path and per-chunk work shape."""
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search import lut as slut

    if g is None:
        g = 40 if SMOKE else 64
    st, target, mask = build_state(g)

    def sweep(depth):
        ctx = SearchContext(Options(seed=1, lut_graph=True,
                                    pipeline_depth=depth))
        t0 = time.perf_counter()
        res = slut._lut5_search_host(ctx, st, target, mask, [])
        dt = time.perf_counter() - t0
        assert res is None, "unexpected 5-LUT hit in bench state"
        return ctx.stats["lut5_candidates"] / dt, ctx

    sweep(2)  # warmup/compile (depth 1 shares the jitted filter)
    rates = {1: [], 2: []}
    overlap = None
    # Runtime jaxlint complements over the measured window: steady state
    # must not recompile (a varying static arg here would silently halve
    # the pipelined arm), and the per-chunk verdict syncs are tallied so
    # a regression that adds hidden per-chunk transfers shows up in the
    # report.  --sync-guard makes both fail loudly instead of reporting.
    from sboxgates_tpu.utils import recompile_guard, sync_guard

    compile_budget = 0 if strict_guards else (1 << 30)
    sync_budget = 0 if strict_guards else (1 << 30)
    if strict_guards:
        # strict mode still permits the deliberate per-chunk verdict
        # syncs: every chunk resolves one compact feasibility verdict
        # (see the jaxlint R2 suppressions in search/lut.py), so budget
        # proportional to the swept space, not zero.
        per_sweep_chunks = -(-math.comb(g, 5) // slut.LUT5_CHUNK) + 2
        sync_budget = 4 * REPEATS * 2 * per_sweep_chunks
    with recompile_guard(allowed=compile_budget, label="host-stream bench") \
            as creport, \
            sync_guard(allowed=sync_budget, action="raise",
                       label="host-stream bench") as sreport:
        for _ in range(REPEATS):
            rates[1].append(sweep(1)[0])
            r2, c2 = sweep(2)
            rates[2].append(r2)
            overlap = c2.prof.overlap().get("lut5.host_stream")

    def spread(vals):
        vals = sorted(vals)
        return {"value": vals[len(vals) // 2], "min": vals[0],
                "max": vals[-1], "reps": len(vals)}

    s1, s2 = spread(rates[1]), spread(rates[2])
    space = math.comb(g, 5)

    # Telemetry overhead A/B (the acceptance gate for the telemetry
    # subsystem): one pipelined sweep per arm under its own sync_guard —
    # everything OFF (the production default; registry + flight ring
    # only) vs the full observability stack ON: the process tracer,
    # attribution lazy cost capture, and a live /status endpoint
    # serving throughout the sweep.  Spans and status snapshots read host state
    # only, so the sync counts MUST be identical (asserted: zero extra
    # host syncs); the wall-time delta is the <=1% budget, reported as
    # a fraction of the everything-off rate.
    from sboxgates_tpu.telemetry import attribution as tattr
    from sboxgates_tpu.telemetry import trace as ttrace
    from sboxgates_tpu.telemetry.status import StatusServer

    tr = ttrace.tracer()
    assert not tr.enabled, "tracer unexpectedly on in the bench process"
    with sync_guard(allowed=1 << 30, action="count",
                    label="telemetry-off") as s_off:
        r_off, _ = sweep(2)
    tr.reset()
    tr.enabled = True
    lazy_before = tattr.lazy_capture_enabled()
    tattr.set_lazy_capture(True)
    status = None
    try:
        with sync_guard(allowed=1 << 30, action="count",
                        label="telemetry-on") as s_on:
            ctx_on = SearchContext(Options(seed=1, lut_graph=True,
                                           pipeline_depth=2))
            status = StatusServer(ctx_on.stats, port=0).start()
            t0 = time.perf_counter()
            res = slut._lut5_search_host(ctx_on, st, target, mask, [])
            dt = time.perf_counter() - t0
            assert res is None, "unexpected 5-LUT hit in bench state"
            r_on, c_on = ctx_on.stats["lut5_candidates"] / dt, ctx_on
        # Success path only: one poll proving the endpoint serves the
        # live registry (a failed arm must surface ITS error, not a
        # poll error masking it from a finally).
        import urllib.request

        with urllib.request.urlopen(
            f"http://127.0.0.1:{status.port}/status", timeout=10
        ) as resp:
            assert json.load(resp)["counters"].get(
                "lut5_candidates", 0
            ) > 0
    finally:
        tr.enabled = False
        tattr.set_lazy_capture(lazy_before)
        if status is not None:
            # Unconditional: no dangling serve thread or socket past
            # this entry, whichever way the arm ended.
            status.shutdown()
    extra_syncs = s_on.syncs - s_off.syncs
    assert extra_syncs == 0, (
        f"tracing added {extra_syncs} host syncs — spans must never "
        "touch the device"
    )
    dispatch_spans = sum(
        1 for e in tr.events() if e[1] == "dispatch"
    )
    telemetry_entry = {
        "metric": "telemetry_overhead",
        "trace_off_cand_s": r_off,
        "trace_on_cand_s": r_on,
        # Positive = tracing cost; single-rep arms, so noise dominates
        # on CPU — the acceptance read is "within 1% or below noise".
        "overhead_frac": round(1.0 - r_on / r_off, 4),
        "extra_syncs_trace_on": extra_syncs,
        "trace_dispatch_spans": dispatch_spans,
        "dispatch_counter": c_on.stats.get("device_dispatches", 0),
        "unit": "fraction of trace-off cand/s",
    }

    return [
        {"metric": "lut5_host_stream_serial", **s1, "unit": "cand/s",
         "space": space, "pipeline_depth": 1},
        {"metric": "lut5_host_stream_pipelined", **s2, "unit": "cand/s",
         "space": space, "pipeline_depth": 2,
         "speedup_vs_serial": round(s2["value"] / s1["value"], 3),
         # Last pipelined sweep's per-phase overlap accounting:
         # off_critical_path_s -> host_produce_s means the consumer
         # never waited for combination generation.
         "overlap": overlap,
         # Runtime-guard tallies over the measured window (jaxlint's
         # runtime complement): compiles after warmup mean a static arg
         # is churning; syncs are the deliberate per-chunk verdicts.
         "steady_state_compiles": creport.compiles,
         "steady_state_syncs": sreport.syncs,
         # Hung-dispatch deadline activity (resilience.deadline) over the
         # same window: nonzero here on real hardware means the tunnel or
         # device stalled mid-bench and the guard retried/degraded —
         # throughput numbers from such a window are suspect.
         "dispatch_retries": c2.stats.get("dispatch_retries", 0),
         "deadline_breaches": c2.stats.get("deadline_breaches", 0),
         # Replicated degradation protocol counters (nonzero only on
         # process-spanning meshes; zero here documents the single-host
         # zero-barrier contract — and a nonzero value on a pod bench
         # window means ranks were aborting in agreement mid-measure).
         "breach_barriers": c2.stats.get("breach_barriers", 0),
         "replicated_aborts": c2.stats.get("replicated_aborts", 0),
         "degraded_ranks": c2.stats.get("degraded_ranks", 0),
         "guard_mode": "strict" if strict_guards else "count"},
        telemetry_entry,
    ]


def bench_degrade_protocol(windows: int = None) -> list:
    """Per-dispatch verdict-barrier overhead of the replicated
    degradation protocol (BENCH_DEGRADE.json).

    Three arms over the same no-op dispatch (the dispatch is free, so
    the window cost IS the guard/protocol overhead):

    - ``deadline_guard_window`` — plain :func:`dispatch_with_retry`
      (one abandonable worker per window): the single-host baseline.
    - ``verdict_barrier_local`` — :func:`replicated_dispatch_with_retry`
      with the real ``distributed.breach_verdict`` in a single-process
      runtime (its zero-round-trip fast path) plus the
      ``sbg-abort-watch`` worker: the protocol's bookkeeping floor.
    - ``verdict_barrier_loopback`` — a 2-party in-process loopback
      verdict (queue handoff to a live peer thread): the cross-thread
      rendezvous a coordinator exchange rides on; a real pod adds one
      coordinator RTT over DCN on top.

    The protocol takes ONE barrier per guarded WINDOW — a sharded stream
    resolve sweeps its whole rank window (many chunks) inside one
    guarded dispatch — so these per-window costs amortize over the
    in-dispatch chunk loop rather than multiplying it.

    A final injected-hang sequence captures the protocol counters
    (breach_barriers / replicated_aborts / degraded_ranks) exactly as a
    degraded rank reports them in ctx.stats / --host-stream output."""
    import queue
    import threading

    from sboxgates_tpu.parallel import distributed as dist
    from sboxgates_tpu.resilience import faults
    from sboxgates_tpu.resilience.deadline import (
        DeadlineConfig,
        DispatchTimeout,
        dispatch_with_retry,
        replicated_dispatch_with_retry,
    )

    if windows is None:
        windows = 50 if SMOKE else 200
    cfg = DeadlineConfig(budget_s=30.0, retries=0)

    def timed(run_window):
        # Median over REPEATS batches of `windows` windows each.
        vals = []
        for _ in range(REPEATS):
            t0 = time.perf_counter()
            for _ in range(windows):
                run_window()
            vals.append((time.perf_counter() - t0) / windows)
        vals.sort()
        return vals[len(vals) // 2]

    base = timed(lambda: dispatch_with_retry(lambda: None, cfg))
    local = timed(
        lambda: replicated_dispatch_with_retry(
            lambda: None, cfg, verdict=dist.breach_verdict
        )
    )

    q_in: "queue.Queue" = queue.Queue()
    q_out: "queue.Queue" = queue.Queue()

    def peer():
        while True:
            b = q_in.get()
            if b is None:
                return
            q_out.put(bool(b))  # peer reports ok; agreement = any()

    t = threading.Thread(target=peer, name="bench-verdict-peer",
                         daemon=True)
    t.start()

    def loopback_verdict(breached):
        q_in.put(breached)
        return bool(q_out.get())

    loop = timed(
        lambda: replicated_dispatch_with_retry(
            lambda: None, cfg, verdict=loopback_verdict
        )
    )
    q_in.put(None)
    t.join(timeout=5)

    # Counter capture: one injected-hang schedule through the protocol.
    faults.disarm("dispatch.sweep")
    faults.arm("dispatch.sweep", "hang")
    stats: dict = {}
    try:
        replicated_dispatch_with_retry(
            lambda: None,
            DeadlineConfig(budget_s=0.05, retries=2, backoff_s=0.01),
            verdict=lambda breached: breached,
            stats=stats,
        )
        raise AssertionError("injected hang did not breach")
    except DispatchTimeout:
        pass
    finally:
        faults.disarm("dispatch.sweep")

    return [
        {"metric": "deadline_guard_window", "value": base,
         "unit": "s/dispatch", "windows": windows},
        {"metric": "verdict_barrier_local", "value": local,
         "unit": "s/dispatch", "overhead_vs_guard_s": local - base,
         "windows": windows},
        {"metric": "verdict_barrier_loopback", "value": loop,
         "unit": "s/dispatch", "overhead_vs_guard_s": loop - base,
         "windows": windows,
         "note": "in-process 2-party rendezvous; a real pod adds one "
                 "coordinator RTT over DCN per window"},
        {"metric": "replicated_degrade_counters",
         "breach_barriers": stats.get("breach_barriers", 0),
         "replicated_aborts": stats.get("replicated_aborts", 0),
         "degraded_ranks": stats.get("degraded_ranks", 0),
         "dispatch_retries": stats.get("dispatch_retries", 0),
         "deadline_breaches": stats.get("deadline_breaches", 0)},
    ]


def bench_cpu_baseline() -> list:
    """Reference-shaped C++ loop, candidates/sec — measured on the SAME
    G=200 state as the headline device sweep (the per-candidate cost
    depends on the state's feasibility rate, so a different G would not
    be apples-to-apples) over a uniform random sample of the C(200,5)
    space (a contiguous prefix would over-represent low-index gates).

    Two entries: ``cpu_core_lut5`` (one core, the per-core unit) and
    ``cpu_socket_lut5`` (sbg_lut5_search_cpu_mt threaded over every core
    os.cpu_count() reports — the reference's N-rank operating point,
    MEASURED on this host rather than assumed; on a 1-core bench host
    the two coincide and the 64-core figure remains an extrapolation,
    labeled as such)."""
    from sboxgates_tpu import native

    st, target, mask = build_state(G_HEAD)
    if not native.available():
        return [{"metric": "cpu_core_lut5", "value": float("nan"),
                 "unit": "cand/s"}]
    rng = np.random.default_rng(1)
    picks = np.stack(
        [rng.choice(G_HEAD, size=5, replace=False) for _ in range(CPU_COMBOS)]
    )
    combos = np.ascontiguousarray(np.sort(picks, axis=1).astype(np.int32))
    t64 = native.tables32_to_64(st.live_tables())
    tg64 = native.tables32_to_64(np.asarray(target))
    mk64 = native.tables32_to_64(np.asarray(mask))
    native.lut5_search_cpu(t64, tg64, mk64, combos[:1024])  # warmup

    # 16 passes per timed rep: one pass over 64k combos at ~66M cand/s is
    # ~1 ms — too short against timer/scheduler noise for a stable median.
    passes = 16

    def one(threads=1):
        t0 = time.perf_counter()
        for _ in range(passes):
            if threads == 1:
                idx, _ = native.lut5_search_cpu(t64, tg64, mk64, combos)
            else:
                idx, _ = native.lut5_search_cpu_mt(
                    t64, tg64, mk64, combos, threads
                )
            if idx != -1:
                raise RuntimeError(
                    "unexpected 5-LUT hit in CPU baseline state"
                )
        dt = time.perf_counter() - t0
        return passes * combos.shape[0] / dt

    s = _spread(one)
    core = {"metric": "cpu_core_lut5", **s, "unit": "cand/s",
            "state_g": G_HEAD, "sampled_combos": int(combos.shape[0]),
            "socket_cores_extrapolation": SOCKET_CORES,
            "socket_scaled_cand_per_sec": s["value"] * SOCKET_CORES}
    ncores = os.cpu_count() or 1
    if ncores > 1:
        native.lut5_search_cpu_mt(t64, tg64, mk64, combos[:4096], ncores)
    ssock = _spread(lambda: one(ncores)) if ncores > 1 else dict(s)
    socket = {
        "metric": "cpu_socket_lut5", **ssock, "unit": "cand/s",
        "state_g": G_HEAD, "cores_measured": ncores,
        "per_core": ssock["value"] / ncores,
        "scaling_vs_one_core": ssock["value"] / s["value"],
        "note": (
            "measured with os.cpu_count()={} threads on this host; the "
            "{}-core figure in cpu_core_lut5 is an extrapolation"
        ).format(ncores, SOCKET_CORES),
    }
    return [core, socket]


def bench_gate_mode_sweeps() -> dict:
    """Gate-mode (non-LUT) throughput: the native fused node step (the
    engine's actual path for gate mode at every state size, mesh or not
    — README "Execution placement policy") and the device kernels (the
    ``host_small_steps=False`` opt-out: per-stage pair/triple sweeps and
    the fused single-dispatch step), at G=200 (reference hot loops
    sboxgates.c:323-435)."""
    from sboxgates_tpu.search import Options, SearchContext

    st, target, mask = build_state(G_HEAD)

    # Engine path: one full-miss native node = C(G,2) pairs + C(G,3)
    # triples swept on the host.
    nctx = SearchContext(Options(seed=1))
    native = {"value": float("nan")}
    if nctx.uses_native_step(st):
        nctx._gate_step_native(st, target, mask)  # warm

        def one_native():
            base = nctx.stats["triple_candidates"]
            t0 = time.perf_counter()
            nctx._gate_step_native(st, target, mask)
            return (nctx.stats["triple_candidates"] - base) / (
                time.perf_counter() - t0
            )

        native = _spread(one_native)

    ctx = SearchContext(Options(seed=1, host_small_steps=False))

    ctx.pair_search(st, target, mask, use_not_table=False)  # warmup

    def one_pair():
        base = ctx.stats["pair_candidates"]
        t0 = time.perf_counter()
        for _ in range(10):
            ctx.pair_search(st, target, mask, use_not_table=False)
        return (ctx.stats["pair_candidates"] - base) / (
            time.perf_counter() - t0
        )

    pair = _spread(one_pair)

    ctx.triple_search(st, target, mask)  # warmup

    def one_triple():
        base = ctx.stats["triple_candidates"]
        t0 = time.perf_counter()
        ctx.triple_search(st, target, mask)
        return (ctx.stats["triple_candidates"] - base) / (
            time.perf_counter() - t0
        )

    tri = _spread(one_triple)

    # The fused single-dispatch node step (gate_step_stream) — what a
    # host_small_steps=False run actually pays per gate-mode node, and
    # the honest device-side comparison point for the README placement
    # policy (the per-stage kernels above pay one dispatch per stage).
    ctx.gate_step(st, target, mask)  # warmup

    def one_fused():
        base = ctx.stats["triple_candidates"]
        t0 = time.perf_counter()
        ctx.gate_step(st, target, mask)
        return (ctx.stats["triple_candidates"] - base) / (
            time.perf_counter() - t0
        )

    fused = _spread(one_fused)

    def span(s):
        return [s.get("min"), s.get("max")]

    return {
        "metric": "gate_mode_sweeps",
        "native_node_triples_per_sec": native["value"],
        "native_spread": span(native),
        "device_pair_candidates_per_sec": pair["value"],
        "device_pair_spread": span(pair),
        "device_triple_candidates_per_sec": tri["value"],
        "device_triple_spread": span(tri),
        "device_fused_step_triples_per_sec": fused["value"],
        "device_fused_step_spread": [fused["min"], fused["max"]],
        "unit": "cand/s",
    }


def bench_lut7() -> dict:
    """7-LUT phase rates: stage-A feasibility stream (lut.c:290-327) and
    stage-B decomposition solve over the hit list (lut.c:416-475)."""
    import jax.numpy as jnp

    from sboxgates_tpu.ops import sweeps
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.context import LUT7_SOLVE_CHUNK

    st, target, mask = build_state(40 if SMOKE else 60)  # C(60,7) = 386M
    ctx = SearchContext(Options(seed=1, lut_graph=True))
    prebuilt = ctx.stream_args(st, target, mask, [], 7)
    ctx.feasible_stream_driver(st, target, mask, [], k=7, prebuilt=prebuilt)
    t0 = time.perf_counter()
    found, _, _, _, _, examined, _ = ctx.feasible_stream_driver(
        st, target, mask, [], k=7, prebuilt=prebuilt
    )
    dt = time.perf_counter() - t0
    stage_a = examined / dt

    # Stage B on all-conflicting constraints: no early hit, so every
    # (ordering x outer x middle) function pair is scanned — worst case.
    t = LUT7_SOLVE_CHUNK
    rng = np.random.default_rng(0)
    r1 = rng.integers(0, 2**32, size=(t, 4), dtype=np.uint32)
    r0 = (~r1).astype(np.uint32)
    idx_tab, pp_tab = sweeps.lut7_pair_tables()
    args = (jnp.asarray(r1), jnp.asarray(r0), jnp.asarray(idx_tab),
            jnp.asarray(pp_tab))
    np.asarray(sweeps.lut7_solve(*args, 1))
    t0 = time.perf_counter()
    v = sweeps.lut7_solve(*args, 2)
    np.asarray(v)
    dt = time.perf_counter() - t0
    return {"metric": "lut7_phase_g60", "value": stage_a, "unit": "cand/s",
            "found": bool(found),
            "stage_b_tuples_per_sec": t / dt,
            "stage_b_rows": t}


def _search_des_s1(**opt_kwargs):
    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.search import (
        Options,
        SearchContext,
        generate_graph_one_output,
        make_targets,
    )
    from sboxgates_tpu.graph.state import State
    from sboxgates_tpu.utils.sbox import parse_sbox

    with open(os.path.join(HERE, "sboxes/des_s1.txt")) as f:
        sbox, n = parse_sbox(f.read())
    targets = make_targets(sbox)
    ctx = SearchContext(Options(seed=42, **opt_kwargs))
    st = State.init_inputs(n)
    t0 = time.perf_counter()
    results = generate_graph_one_output(
        ctx, st, targets, 0, save_dir=None, log=lambda s: None
    )
    dt = time.perf_counter() - t0
    best = results[-1] if results else None
    return dt, best


def bench_des_s1_lut():
    """End-to-end wall time + solution quality for the reference's LUT CI
    config (.travis.yml:48: mpirun -N 10 ... -l -o 0 des_s1).  Runs twice:
    the first run pays one-time jit tracing/compilation (amortized across
    a session and partly cached on disk), the second is the steady-state
    wall time.  Returns the best state so the Pallas bench can execute the
    searched circuit."""
    cold, best = _search_des_s1(lut_graph=True, iterations=1)
    times = []
    for _ in range(REPEATS):
        warm, best2 = _search_des_s1(lut_graph=True, iterations=1)
        times.append(warm)
        best = best2 or best
    times.sort()
    entry = {
        "metric": "des_s1_bit0_lut",
        "value": times[len(times) // 2], "unit": "s",
        "min": times[0], "max": times[-1], "reps": REPEATS,
        "cold_first_run_s": cold,
        "gates": best.num_gates - best.num_inputs if best else None,
    }
    return entry, best


def bench_des_s1_sat_not() -> dict:
    """The gate-mode SAT+NOT CI config (.travis.yml:40: mpirun -N 4
    -i 3 -o 0 -s -n des_s1).  The whole ~40k-node recursion runs in the
    native engine (sbg_gate_engine — gate mode never justifies a device
    dispatch), so the measurement is backend-independent: the honest
    comparison point against the reference's own CPU/MPI run of the
    same config.  Engine vs per-node-step Python driving measured
    10.9x (2.39 s -> 0.22 s)."""
    from sboxgates_tpu import native

    if not native.available():
        # Without the native runtime every node would be a device dispatch
        # — hours of link RTT, measuring the network instead of the search.
        raise RuntimeError(
            f"native runtime unavailable: {native.build_error()}"
        )
    times = []
    best = None
    for _ in range(REPEATS + 1):  # first rep warms the process
        dt, best = _search_des_s1(metric=1, try_nots=True, iterations=3)
        times.append(dt)
    times = sorted(times[1:])
    return {
        "metric": "des_s1_bit0_sat_not_i3",
        "value": times[len(times) // 2], "unit": "s",
        "min": times[0], "max": times[-1], "reps": REPEATS,
        "gates": best.num_gates - best.num_inputs if best else None,
        "sat_metric": best.sat_metric if best else None,
    }


def bench_des_s1_full_graph() -> dict:
    """The third reference CI config (.travis.yml:43: mpirun -N 4
    -a 10694 -i 3 -p 63 des_s1): the full 4-output beam search with a
    restricted gate set and a permuted input.  Gate mode, so the whole
    run executes in the native engine — backend-independent."""
    from sboxgates_tpu import native
    from sboxgates_tpu.search import Options, SearchContext, generate_graph, make_targets
    from sboxgates_tpu.graph.state import State
    from sboxgates_tpu.utils.sbox import load_sbox

    if not native.available():
        # Without the native engine every node is a device dispatch; the
        # 4-output beam search would run for hours measuring the link.
        raise RuntimeError(
            f"native runtime unavailable: {native.build_error()}"
        )
    sbox, n = load_sbox(os.path.join(HERE, "sboxes/des_s1.txt"), permute=63)
    targets = make_targets(sbox)

    def one():
        ctx = SearchContext(
            Options(seed=42, iterations=3, avail_gates_bitfield=10694)
        )
        st = State.init_inputs(n)
        t0 = time.perf_counter()
        beam = generate_graph(
            ctx, st, targets, save_dir=None, log=lambda s: None
        )
        return time.perf_counter() - t0, beam[0] if beam else None

    times = []
    best = None
    for _ in range(REPEATS):
        dt, best = one()
        times.append(dt)
    times.sort()
    return {
        "metric": "des_s1_full_graph_a10694_p63_i3",
        "value": times[len(times) // 2], "unit": "s",
        "min": times[0], "max": times[-1], "reps": REPEATS,
        "gates": best.num_gates - best.num_inputs if best else None,
        "outputs": 4,
    }


def bench_des_s1_outputs_batched() -> dict:
    """Batch-parallel axis (BASELINE configs 4-5): all four DES S1 output
    bits searched as ONE concurrent LUT batch (rendezvous-merged device
    dispatches + native heads) vs. the same four searches run serially.
    The reference has no such axis — its only parallelism is MPI ranks
    inside one search (sboxgates.c:619-642).

    Measured r2: at DES-S1 state sizes the native host routing makes the
    threaded batch ~1.4x SLOWER than serial on this 1-core host (LUT
    nodes this small make almost no dispatches; threads only contend
    for the core).  Gate-mode batches are auto-serialized on 1-core
    hosts (run_batched_circuits); LUT mode keeps threads because its
    states can grow into the dispatch-bound regime where they win
    (bench_batch_axis_pivot measures that crossover), so this entry
    records the price of the flag at the small end — an honest negative
    result, not a bug."""
    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.graph.state import State
    from sboxgates_tpu.search import (
        Options, SearchContext, make_targets, sbox_num_outputs,
    )
    from sboxgates_tpu.search.batched import run_batched_circuits
    from sboxgates_tpu.search.kwan import create_circuit
    from sboxgates_tpu.utils.sbox import parse_sbox

    with open(os.path.join(HERE, "sboxes/des_s1.txt")) as f:
        sbox, n = parse_sbox(f.read())
    targets = make_targets(sbox)
    outs = sbox_num_outputs(targets)
    mask = tt.mask_table(n)

    def batched_run():
        ctx = SearchContext(Options(seed=7, lut_graph=True))
        st = State.init_inputs(n)
        jobs = [(st.copy(), targets[o], mask) for o in range(outs)]
        t0 = time.perf_counter()
        results = run_batched_circuits(ctx, jobs)
        dt = time.perf_counter() - t0
        gates = [
            r[0].num_gates - r[0].num_inputs
            for r in results if r[1] != 0xFFFF
        ]
        return dt, gates

    def serial_run():
        ctx = SearchContext(Options(seed=7, lut_graph=True))
        st = State.init_inputs(n)
        t0 = time.perf_counter()
        gates = []
        for o in range(outs):
            nst = st.copy()
            if create_circuit(ctx, nst, targets[o], mask, []) != 0xFFFF:
                gates.append(nst.num_gates - nst.num_inputs)
        return time.perf_counter() - t0, gates

    # Warm BOTH paths before timing: the rendezvous merges sweeps into
    # batch shapes the serial path never compiles, so each needs its own
    # warm pass for a fair comparison.
    batched_run()
    serial_run()
    bdt, bgates = batched_run()
    sdt, sgates = serial_run()
    return {
        "metric": "des_s1_all_outputs_lut",
        "value": bdt, "unit": "s",
        "batched_gates": bgates,
        "serial_s": sdt, "serial_gates": sgates,
        "outputs": outs,
    }


def bench_lut7_break_even() -> dict:
    """Re-measures the host-vs-device stage-B routing threshold
    (context.NATIVE_LUT7_SOLVE_MAX) with spread: per-row host solve cost
    on worst-case all-conflicting rows, device dispatch wall time at the
    smallest compiled size, and the implied break-even row count.  The
    constant cites this entry."""
    import jax.numpy as jnp

    from sboxgates_tpu import native
    from sboxgates_tpu.ops import sweeps
    from sboxgates_tpu.search.context import LUT7_SOLVE_SIZES

    if not native.available():
        return {"metric": "lut7_break_even", "error": "native unavailable"}
    rng = np.random.default_rng(0)
    rows = 24
    r1 = rng.integers(0, 2**32, size=(rows, 4), dtype=np.uint32)
    r0 = (~r1).astype(np.uint32)
    idx_tab, pp_tab = sweeps.lut7_pair_tables()
    from sboxgates_tpu.search.context import LUT7_HEAD_SOLVE_ROWS
    native.lut7_solve_small(r1, r0, LUT7_HEAD_SOLVE_ROWS, idx_tab, 1)  # warm

    def host_one():
        t0 = time.perf_counter()
        native.lut7_solve_small(r1, r0, LUT7_HEAD_SOLVE_ROWS, idx_tab, 1)
        return (time.perf_counter() - t0) / rows

    host = _spread(host_one)

    size = LUT7_SOLVE_SIZES[0]
    p1 = np.full((size, 4), 0xFFFFFFFF, np.uint32)
    p1[:rows] = r1
    p0 = np.full((size, 4), 0xFFFFFFFF, np.uint32)
    p0[:rows] = r0
    args = (jnp.asarray(p1), jnp.asarray(p0), jnp.asarray(idx_tab),
            jnp.asarray(pp_tab))
    np.asarray(sweeps.lut7_solve(*args, 1))  # warm

    def dev_one():
        t0 = time.perf_counter()
        np.asarray(sweeps.lut7_solve(*args, 2))
        return time.perf_counter() - t0

    dev = _spread(dev_one)
    break_even = dev["value"] / host["value"] if host["value"] > 0 else None
    return {
        "metric": "lut7_break_even",
        "value": break_even, "unit": "rows",
        "host_s_per_row": host["value"],
        "host_spread": [host["min"], host["max"]],
        "device_dispatch_s": dev["value"],
        "device_spread": [dev["min"], dev["max"]],
        "device_rows": size,
    }


def bench_lut7_capped_search() -> dict:
    """An actual capped 7-LUT search end to end (VERDICT r2 item 5): a
    planted LUT(LUT,LUT,g) target over a G=40 XOR state floods stage A —
    the 100k hit cap (reference: lut.c:291,316) binds after ~3% of
    C(40,7) — and stage B sweeps the capped list to the first solving
    chunk.  Reports wall time and the stage split."""
    import time as _t

    from sboxgates_tpu.core import boolfunc as bf
    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.graph.state import GATES, State
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.context import LUT7_CAP
    from sboxgates_tpu.search.lut import lut7_search

    rng = np.random.default_rng(5)
    st = State.init_inputs(8)
    while st.num_gates < 40:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    # Plant on the rank-0 tuple (0..6): stage A still floods to the cap
    # (the XOR span makes most tuples feasible), but the planted
    # decomposition is guaranteed inside the capped list and stage B's
    # first chunk solves — the tuple's rank in C(40,7) order would
    # otherwise (~20M for mid-index gates) fall outside the 100k cap and
    # the sweep would grind through nothing but unsolvable rows.
    outer = tt.eval_lut(0x96, st.table(0), st.table(1), st.table(2))
    middle = tt.eval_lut(0xE8, st.table(3), st.table(4), st.table(5))
    target = tt.eval_lut(0xCA, outer, middle, st.table(6))
    mask = tt.mask_table(8)

    def run():
        ctx = SearchContext(Options(seed=1, lut_graph=True, randomize=False))
        t0 = _t.perf_counter()
        res = lut7_search(ctx, st, target, mask, [])
        dt = _t.perf_counter() - t0
        if res is None:
            raise RuntimeError("capped 7-LUT search found nothing")
        return dt, ctx

    run()  # warm
    dt, ctx = run()
    prof = {
        name: round(secs, 3)
        for name, (secs, _calls) in ctx.prof.snapshot().items()
        if name.startswith("lut7")
    }
    return {
        "metric": "lut7_capped_search_g40",
        "value": dt, "unit": "s",
        "cap": LUT7_CAP,
        "stage_a_candidates": ctx.stats["lut7_candidates"],
        "stage_b_rows_solved": ctx.stats["lut7_solved"],
        "phases": prof,
    }


def bench_engine_pivot_ab() -> dict:
    """Native-engine continuation vs Python recursion at device-work
    scale (VERDICT r3 item 4): a G=50 planted-5-LUT search (pivot-sized
    space, so the node needs a device sweep) run both ways, interleaved.
    The engine must stay active through the serviced dispatch —
    engine-active node fraction 1.0, no discarded exploration — and not
    cost wall time vs the Python path driving the same sweep."""
    import sys as _sys

    _sys.path.insert(0, os.path.join(HERE, "tests"))
    from planted import build_planted_lut5

    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.kwan import create_circuit

    def run(engine):
        st, target, mask = build_planted_lut5()
        # Engine arm: parallel_mux off so the routing predicate engages
        # the engine at device-work nodes (with mux threads attached
        # those nodes stay on the Python path by design).  Python arm:
        # the production default (mux-concurrency threads on accelerator
        # backends) — the configuration the engine arm must beat.
        ctx = SearchContext(
            Options(seed=2, lut_graph=True, randomize=False,
                    native_engine=engine,
                    parallel_mux=False if engine else None)
        )
        t0 = time.perf_counter()
        out = create_circuit(ctx, st, target, mask, [])
        dt = time.perf_counter() - t0
        assert out != 0xFFFF
        return dt, ctx

    run(True)  # warm/compile
    run(False)
    etimes, ptimes = [], []
    ectx = None
    for _ in range(REPEATS):
        edt, ectx = run(True)
        pdt, _ = run(False)
        etimes.append(edt)
        ptimes.append(pdt)
    etimes.sort()
    ptimes.sort()
    en = ectx.stats.get("engine_nodes", 0)
    pn = ectx.stats.get("python_nodes", 0)
    return {
        "metric": "engine_pivot_ab_g50",
        "value": etimes[len(etimes) // 2], "unit": "s",
        "min": etimes[0], "max": etimes[-1], "reps": REPEATS,
        "python_s": ptimes[len(ptimes) // 2],
        "python_spread": [ptimes[0], ptimes[-1]],
        "engine_wins": etimes[len(etimes) // 2] <= ptimes[len(ptimes) // 2],
        "engine_devcalls": ectx.stats.get("engine_devcalls", 0),
        "engine_active_fraction": en / (en + pn) if (en + pn) else None,
    }


def bench_engine_mux_threads() -> dict:
    """A/B of the engine's threaded mux fan-out (SBG_ENGINE_MUX_THREADS):
    a budget-capped unrealizable target over a G=50 planted state makes
    the engine walk its full mux tree with one serviced pivot sweep at
    the root and one per first-level branch (9 devcalls) — the workload
    the lever exists to overlap.  Staged-7-LUT requests are suppressed
    via the service seam so the measurement isolates branch-dispatch
    overlap (and stays CPU-feasible in smoke runs); both arms share the
    suppression, and their results are bit-identical (parity-tested)."""
    import sys as _sys
    from functools import reduce

    _sys.path.insert(0, os.path.join(HERE, "tests"))
    from planted import build_planted_lut5

    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.kwan import _lut_engine_service, create_circuit

    def run(threads):
        os.environ["SBG_ENGINE_MUX_THREADS"] = str(threads)
        try:
            st, _, mask = build_planted_lut5()
            miss = reduce(
                lambda a, b: np.asarray(a) & np.asarray(b),
                [np.asarray(st.table(i)) for i in range(8)],
            )
            st.max_gates = st.num_gates + 3
            ctx = SearchContext(
                Options(seed=2, lut_graph=True, randomize=False,
                        parallel_mux=False)
            )
            real = _lut_engine_service(ctx, threaded=threads > 1)

            def wrapped(kind, *args):
                return None if kind == 3 else real(kind, *args)

            ctx._lut_engine_service_fn = (ctx, wrapped)
            t0 = time.perf_counter()
            out = create_circuit(ctx, st, miss, mask, [])
            dt = time.perf_counter() - t0
            assert out == 0xFFFF, "miss target unexpectedly realized"
            return dt, ctx.stats.get("engine_devcalls", 0)
        finally:
            os.environ.pop("SBG_ENGINE_MUX_THREADS", None)

    run(1)  # warm/compile
    run(8)
    stimes, ttimes = [], []
    devcalls = 0
    for _ in range(REPEATS):
        sdt, devcalls = run(1)
        tdt, _ = run(8)
        stimes.append(sdt)
        ttimes.append(tdt)
    stimes.sort()
    ttimes.sort()
    return {
        "metric": "engine_mux_threads_ab_g50",
        "value": ttimes[len(ttimes) // 2], "unit": "s",
        "min": ttimes[0], "max": ttimes[-1], "reps": REPEATS,
        "serial_s": stimes[len(stimes) // 2],
        "serial_spread": [stimes[0], stimes[-1]],
        "threaded_wins": ttimes[len(ttimes) // 2] < stimes[len(stimes) // 2],
        "devcalls_per_run": devcalls,
    }


def bench_order_ab() -> list:
    """``--order-ab``: lexicographic vs spectral candidate ordering over
    a planted serve mix (four mixed-gate G=24 states, targets planted on
    the HIGHEST gates so they sit at the tail of the lex rank space —
    the regime best-first ordering exists for).  Reports per-target
    candidates-scanned-to-first-hit and p50/p99 time-to-first-hit for
    both arms, plus the three structural fields ``--check order`` gates
    on: the exhaustive hit set is unchanged (7-LUT collector, every hit,
    both orders), spectral scans <= lex on >= 3 of the 4 planted targets
    (dispatch-count-based, so it holds on CPU CI), and two spectral runs
    are bit-identical (same hit, same draw/dispatch counts).

    The 5-LUT stream chunk is shrunk to 1024 ranks for this section
    (saved/restored) so C(24,5) = 42504 spans many chunks — with the
    production 128Ki chunk these spaces are one dispatch and ordering
    correctly never engages; the production win regime is G >= ~90."""
    import sys as _sys

    _sys.path.insert(0, os.path.join(HERE, "tests"))
    from planted import build_planted_lut7, verify_lut5_result

    from sboxgates_tpu.core import boolfunc as bf
    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.graph.state import GATES, State
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search import context as sctx
    from sboxgates_tpu.search import lut as slut

    def planted(seed):
        rng = np.random.default_rng(seed)
        st = State.init_inputs(8)
        funs = [bf.AND, bf.OR, bf.XOR, bf.A_AND_NOT_B]
        while st.num_gates < 24:
            a, b = rng.choice(st.num_gates, size=2, replace=False)
            st.add_gate(funs[rng.integers(len(funs))], int(a), int(b), GATES)
        outer = tt.eval_lut(0x2D, st.table(19), st.table(21), st.table(23))
        target = tt.eval_lut(0xB4, outer, st.table(20), st.table(22))
        return st, target, tt.mask_table(8)

    def run(order, seed):
        st, target, mask = planted(seed)
        ctx = SearchContext(Options(seed=7, candidate_order=order))
        t0 = time.perf_counter()
        res = slut.lut5_search(ctx, st, target, mask, [])
        dt = time.perf_counter() - t0
        assert res is not None and verify_lut5_result(st, target, mask, res)
        sig = (tuple(int(x) for x in res["gates"]),
               int(res["func_outer"]), int(res["func_inner"]),
               ctx.stats["lut5_candidates"],
               ctx.stats.get("order_tier_dispatches", 0))
        return dt, ctx.stats["lut5_candidates"], sig

    saved = sctx.STREAM_CHUNK[5]
    sctx.STREAM_CHUNK[5] = 1024
    try:
        seeds = (3, 6, 7, 10)
        run("lex", seeds[0])  # warm/compile both arms
        run("spectral", seeds[0])
        targets, lex_t, spec_t = [], [], []
        wins = 0
        deterministic = True
        for seed in seeds:
            ldt, lscans, _ = run("lex", seed)
            sdt, sscans, sig1 = run("spectral", seed)
            _, _, sig2 = run("spectral", seed)
            deterministic = deterministic and sig1 == sig2
            wins += sscans <= lscans
            lex_t.append(ldt)
            spec_t.append(sdt)
            targets.append({
                "seed": seed, "lex_scans": lscans, "spectral_scans": sscans,
                "lex_ttfh_s": ldt, "spectral_ttfh_s": sdt,
            })

        # Hit-SET equivalence at the one driver that collects every hit
        # rather than stopping at the first: C(22,7) = 170544 spans six
        # 7-LUT stream chunks at the production chunk size, so the tier
        # drivers genuinely reorder without the shrunk-chunk override.
        st7, target7, mask7 = build_planted_lut7(22)
        rows = {}
        for order in ("lex", "spectral"):
            ctx = SearchContext(Options(seed=7, candidate_order=order))
            combos, req1, req0 = slut._lut7_collect_hits(
                ctx, st7, target7, mask7, []
            )
            rows[order] = {
                (tuple(int(x) for x in c),
                 np.asarray(a).tobytes(), np.asarray(b).tobytes())
                for c, a, b in zip(combos, req1, req0)
            }
        hit_set_equal = bool(rows["lex"]) and rows["lex"] == rows["spectral"]
    finally:
        sctx.STREAM_CHUNK[5] = saved

    lex_t.sort()
    spec_t.sort()
    n = len(seeds)
    return [{
        "metric": "order_ab",
        "value": spec_t[n // 2], "unit": "s",
        "lex_ttfh_p50_s": lex_t[n // 2], "lex_ttfh_p99_s": lex_t[-1],
        "spectral_ttfh_p50_s": spec_t[n // 2],
        "spectral_ttfh_p99_s": spec_t[-1],
        "spectral_wins": wins, "targets_total": n,
        "exhaustive_hit_set_equal": hit_set_equal,
        "spectral_scans_leq_lex_on_planted": wins >= 3,
        "ordering_deterministic_across_runs": deterministic,
        "targets": targets,
    }]


def bench_batch_axis_pivot() -> dict:
    """The batch axis in its claimed win regime (VERDICT r2 item 4):
    pivot-sized states (G=140, C(140,5)=416M — every node makes real
    device dispatches) searched as R=4 concurrent restarts
    (run_batched_circuits: threads overlapping device round trips;
    variable-shape pivot sweeps run per-thread) vs the same 4 jobs
    serially.  Budgets are capped at G+2 so each attempt sweeps its
    pivot space, muxes shallowly, and fails — a bounded worst-case node
    workload, identical across modes."""
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.batched import run_batched_circuits
    from sboxgates_tpu.search.kwan import create_circuit

    g = 60 if SMOKE else 140
    st, target, mask = build_state(g)

    def make_jobs():
        jobs = []
        for _ in range(4):
            nst = st.copy()
            nst.max_gates = g + 2
            jobs.append((nst, target, mask))
        return jobs

    def batched_run():
        ctx = SearchContext(Options(seed=5, lut_graph=True))
        t0 = time.perf_counter()
        run_batched_circuits(ctx, make_jobs())
        return time.perf_counter() - t0

    def serial_run():
        ctx = SearchContext(Options(seed=5, lut_graph=True))
        t0 = time.perf_counter()
        for nst, tg, mk in make_jobs():
            create_circuit(ctx, nst, tg, mk, [])
        return time.perf_counter() - t0

    batched_run()  # warm both paths' kernel shapes
    serial_run()
    b = _spread(batched_run)
    s = _spread(serial_run)
    return {
        "metric": "batch_axis_pivot_g140_r4",
        "value": b["value"], "unit": "s",
        "batched_spread": [b["min"], b["max"]],
        "serial_s": s["value"], "serial_spread": [s["min"], s["max"]],
        "batched_wins": b["value"] < s["value"],
    }


def bench_multibox_des() -> dict:
    """BASELINE config 4: all eight DES S-boxes, output bit 0, LUT mode —
    one rendezvous batch vs the reference-shaped serial loop (one box at
    a time)."""
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.multibox import (
        load_box_jobs,
        search_boxes_one_output,
    )

    paths = [os.path.join(HERE, f"sboxes/des_s{i}.txt") for i in range(1, 9)]

    def run(batched):
        boxes = load_box_jobs(paths)
        ctx = SearchContext(Options(seed=7, lut_graph=True))
        t0 = time.perf_counter()
        res = search_boxes_one_output(
            ctx, boxes, 0, save_dir=None, log=lambda s: None, batched=batched
        )
        dt = time.perf_counter() - t0
        gates = {
            n: (min(s.num_gates - s.num_inputs for s in sts) if sts else None)
            for n, sts in res.items()
        }
        return dt, gates

    run(True)  # warm
    run(False)
    # Interleaved reps so host load drift hits both arms equally.
    btimes, stimes = [], []
    bgates = sgates = None
    for _ in range(REPEATS):
        bdt, bgates = run(True)
        sdt, sgates = run(False)
        btimes.append(bdt)
        stimes.append(sdt)
    btimes.sort()
    stimes.sort()
    return {
        "metric": "des_s1_s8_batched_lut",
        "value": btimes[len(btimes) // 2], "unit": "s",
        "min": btimes[0], "max": btimes[-1], "reps": REPEATS,
        "serial_s": stimes[len(stimes) // 2],
        "serial_spread": [stimes[0], stimes[-1]],
        "batched_wins": btimes[len(btimes) // 2] < stimes[len(stimes) // 2],
        "batched_gates": bgates, "serial_gates": sgates,
    }


def bench_permute_sweep() -> dict:
    """BASELINE config 5: the full --permute sweep of DES S1 (all 64 input
    permutations), output bit 0, LUT mode, batched vs serial."""
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.multibox import (
        permute_sweep_jobs,
        search_boxes_one_output,
    )
    from sboxgates_tpu.utils.sbox import load_sbox

    sbox, n = load_sbox(os.path.join(HERE, "sboxes/des_s1.txt"))

    def run(batched):
        boxes = permute_sweep_jobs(sbox, n)
        ctx = SearchContext(Options(seed=7, lut_graph=True))
        t0 = time.perf_counter()
        res = search_boxes_one_output(
            ctx, boxes, 0, save_dir=None, log=lambda s: None, batched=batched
        )
        dt = time.perf_counter() - t0
        best = min(
            (min(s.num_gates - s.num_inputs for s in sts), name)
            for name, sts in res.items() if sts
        )
        return dt, best

    run(True)  # warm both paths' kernel shapes
    run(False)
    # Interleaved reps so host load drift hits both arms equally.
    btimes, stimes = [], []
    bbest = sbest = None
    for _ in range(REPEATS):
        bdt, bbest = run(True)
        sdt, sbest = run(False)
        btimes.append(bdt)
        stimes.append(sdt)
    btimes.sort()
    stimes.sort()
    # value = the default configuration's wall time: permutation sweeps
    # resolve batched=None to the serial loop (multibox.permute_sweep_jobs
    # prefer_serial — set from this very measurement).
    return {
        "metric": "permute_sweep_des_s1_p64",
        "value": stimes[len(stimes) // 2], "unit": "s",
        "min": stimes[0], "max": stimes[-1], "reps": REPEATS,
        "default": "serial",
        "batched_s": btimes[len(btimes) // 2],
        "batched_spread": [btimes[0], btimes[-1]],
        "batched_wins": btimes[len(btimes) // 2] < stimes[len(stimes) // 2],
        "best_gates_batched": bbest, "best_gates_serial": sbest,
        "permutations": 1 << n,
    }


def bench_pallas_deep() -> dict:
    """Pallas vs jnp on a DEEP circuit (300 gates, the regime where VMEM
    residency should matter): a long gate chain exceeds what XLA keeps in
    one fusion, so the jnp evaluator's intermediates spill to HBM while
    the Pallas kernel holds every gate value in VMEM for the block
    (VERDICT r2 weak item 9: find the regime where Pallas wins, or state
    that XLA already fuses this workload)."""
    import jax
    import jax.numpy as jnp

    from sboxgates_tpu.core import boolfunc as bf
    from sboxgates_tpu.graph.state import GATES, State
    from sboxgates_tpu.codegen.executor import compile_circuit
    from sboxgates_tpu.codegen.pallas_kernel import compile_pallas

    rng = np.random.default_rng(0)
    st = State.init_inputs(8)
    funs = [bf.XOR, bf.AND, bf.OR]
    while st.num_gates < 308:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(funs[rng.integers(3)], int(a), int(b), GATES)
    st.outputs[0] = st.num_gates - 1

    on_tpu = jax.default_backend() != "cpu"
    # CPU runs use the Pallas interpreter (per-op Python) — keep the
    # problem tiny there; the real measurement is the on-chip one.
    w = (1 << 18) if on_tpu else (1 << 12)
    inputs = jnp.asarray(
        rng.integers(0, 2**32, size=(8, w), dtype=np.uint32)
    )
    loops = 32 if on_tpu else 2
    pfn = compile_pallas(st, interpret=not on_tpu)
    jfn = compile_circuit(st)

    rates = []
    for fn in (pfn, jfn):

        @jax.jit
        def looped(x, f=fn):
            def body(i, acc):
                return acc ^ f(x ^ i.astype(jnp.uint32))

            acc = jax.lax.fori_loop(1, loops, body, f(x))
            return acc.sum(dtype=jnp.uint32)

        jax.block_until_ready(looped(inputs))

        def one(lp=looped):
            t0 = time.perf_counter()
            out = lp(inputs)
            jax.block_until_ready(out)
            return loops * 32 * w / (time.perf_counter() - t0)

        rates.append(_spread(one))
    pallas, jnp_r = rates
    return {
        "metric": "pallas_deep_circuit_exec",
        "value": pallas["value"], "unit": "evals/s",
        "pallas_spread": [pallas["min"], pallas["max"]],
        "jnp_evals_per_sec": jnp_r["value"],
        "jnp_spread": [jnp_r["min"], jnp_r["max"]],
        "gates": st.num_gates - st.num_inputs,
        "pallas_wins": pallas["value"] > jnp_r["value"],
        "interpret": not on_tpu,
    }


def bench_pallas_exec(best) -> dict:
    """Circuit-execution throughput of the Pallas kernel backend on a
    searched DES S1 LUT circuit (the reference's CUDA-LOP3 counterpart,
    convert_graph.c:136-159) vs the jitted jnp bitslice evaluator."""
    import jax

    from sboxgates_tpu.codegen.executor import compile_circuit
    from sboxgates_tpu.codegen.pallas_kernel import compile_pallas

    if best is None:
        return {"metric": "pallas_circuit_exec", "value": float("nan"),
                "unit": "evals/s"}
    import jax.numpy as jnp

    n_in = best.num_inputs
    w = 1 << 18   # words per evaluation pass: 32 * 2^18 = 8.4M inputs
    rng = np.random.default_rng(0)
    inputs = jnp.asarray(
        rng.integers(0, 2**32, size=(n_in, w), dtype=np.uint32)
    )
    on_tpu = jax.default_backend() != "cpu"
    # Passes fused into ONE dispatch (lax.fori_loop) so the measurement
    # amortizes the dispatch/link round trip and times circuit execution,
    # not the tunnel.  CPU/interpret runs have no dispatch latency to
    # amortize — 64 interpreter passes would just take 64x longer.
    loops = 64 if on_tpu else 2
    pfn = compile_pallas(best, interpret=not on_tpu)
    jfn = compile_circuit(best)

    rates = []
    for fn in (pfn, jfn):

        @jax.jit
        def looped(x, f=fn):
            # vary the input each pass so no iteration can be folded away
            def body(i, acc):
                return acc ^ f(x ^ i.astype(jnp.uint32))

            acc = jax.lax.fori_loop(1, loops, body, f(x))
            return acc.sum(dtype=jnp.uint32)

        jax.block_until_ready(looped(inputs))  # compile
        t0 = time.perf_counter()
        for _ in range(REPEATS):
            out = looped(inputs)
        jax.block_until_ready(out)
        rates.append(
            REPEATS * loops * 32 * w / (time.perf_counter() - t0)
        )
    pallas_rate, jnp_rate = rates
    return {
        "metric": "pallas_circuit_exec", "value": pallas_rate,
        "unit": "evals/s", "jnp_evals_per_sec": jnp_rate,
        "gates": best.num_gates - best.num_inputs, "interpret": not on_tpu,
    }


def _serve_standalone_digests(tmp_dir, sbox_path, output, seed):
    """Bit-identity reference for one serve job: the same one-output
    search on a FRESH context with the same seed (mirrors the chaos
    matrix in tests/test_serve.py; bench must not import tests/)."""
    import hashlib

    from sboxgates_tpu.graph.state import State
    from sboxgates_tpu.search import (
        Options,
        SearchContext,
        generate_graph_one_output,
        make_targets,
    )
    from sboxgates_tpu.utils.sbox import load_sbox

    ctx = SearchContext(Options(seed=seed))
    sbox, num_inputs = load_sbox(sbox_path, 0)
    st = State.init_inputs(num_inputs)
    os.makedirs(tmp_dir, exist_ok=True)
    generate_graph_one_output(
        ctx, st, make_targets(sbox), output, save_dir=tmp_dir,
        log=lambda s: None, journal=None,
    )
    return {
        f: hashlib.sha256(
            open(os.path.join(tmp_dir, f), "rb").read()
        ).hexdigest()
        for f in sorted(os.listdir(tmp_dir)) if f.endswith(".xml")
    }


def _serve_job_set(n_jobs):
    des = os.path.join(HERE, "sboxes", "des_s1.txt")
    fa = os.path.join(HERE, "sboxes", "crypto1_fa.txt")
    jobs = []
    for i in range(n_jobs):
        path, output = (des, i % 4) if i % 3 else (fa, 0)
        jobs.append((f"j{i:02d}", path, output, f"tenant{i % 3}"))
    return jobs


def _run_serve_arm(root, jobs, lanes, seed=9, retries=2):
    """One serve-orchestrator run over the job set; returns (wall_s,
    final status view, base-context registry)."""
    from sboxgates_tpu.resilience.deadline import DeadlineConfig
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.serve import ServeJob, ServeOrchestrator

    ctx = SearchContext(Options(seed=seed))
    orch = ServeOrchestrator(
        ctx, root, lanes=lanes,
        deadline=DeadlineConfig(retries=retries, backoff_s=0.05),
        log=lambda s: None,
    )
    for job_id, path, output, tenant in jobs:
        orch.submit(ServeJob(
            job_id=job_id, sbox_path=path, output=output, tenant=tenant,
        ))
    t0 = time.perf_counter()
    orch.start()
    view = orch.run_until_idle(timeout_s=ENTRY_BUDGET_S)
    wall = time.perf_counter() - t0
    orch.stop()
    return wall, view, ctx.stats, orch


def _toy_serve_files(work, n=8):
    """The fleet toy corpus written as S-box files: 3-input searches
    whose node sweeps make REAL device dispatches under the
    device-routed options (the workload the merged-wave dispatch ratio
    is measured on — same generator as the fleet bench ladder)."""
    from sboxgates_tpu.search.fleet import toy_fleet_boxes

    paths = []
    for i, bj in enumerate(toy_fleet_boxes(n)):
        p = os.path.join(work, f"toy{i}.txt")
        with open(p, "w") as f:
            f.write(" ".join("%02x" % v for v in bj.sbox[:8]))
        paths.append(p)
    return paths


def _run_serve_dev_arm(root, paths, lanes, merge, seed=9, chain_rounds=0):
    """One device-routed serve arm (node heads dispatch instead of
    routing native, so wave merging is measurable); returns (wall_s,
    view, stats)."""
    from sboxgates_tpu.resilience.deadline import DeadlineConfig
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.serve import ServeJob, ServeOrchestrator

    ctx = SearchContext(Options(
        seed=seed, lut_graph=True, randomize=False,
        host_small_steps=False, native_engine=False, warmup=False,
        chain_rounds=chain_rounds,
    ))
    orch = ServeOrchestrator(
        ctx, root, lanes=lanes,
        deadline=DeadlineConfig(retries=2, backoff_s=0.05),
        log=lambda s: None, merge=merge,
    )
    output = -1 if chain_rounds else 0
    for i, p in enumerate(paths):
        orch.submit(ServeJob(
            job_id=f"t{i:02d}", sbox_path=p, output=output,
            tenant=f"ten{i % 3}",
        ))
    t0 = time.perf_counter()
    orch.start()
    view = orch.run_until_idle(timeout_s=ENTRY_BUDGET_S)
    wall = time.perf_counter() - t0
    orch.stop()
    return wall, view, ctx.stats


def bench_serve(n_jobs: int = None) -> list:
    """``bench.py --serve``: the serve-mode load generator
    (BENCH_SERVE.json).

    Five arms over synthetic multi-tenant job mixes (DES S1 outputs +
    the Crypto-1 fa filter for the scheduling arms; the device-routed
    toy corpus for the dispatch-ratio arms):

    1. ``serve_serial_t1`` — the same job set on ONE lane, measured in
       the same window: the t1 baseline (the serial loop an operator
       would run without the orchestrator).
    2. ``serve_load`` — the multi-lane queue: jobs/hour, p99
       time-to-first-hit and queue-wait quantiles read STRAIGHT from
       the telemetry registry snapshot (no bespoke accounting), plus
       the serve counters.  CPU caveat (same as the fleet ladder): the
       lanes are host threads contending for the GIL, so multi-lane
       jobs/hour can trail t1 on CPU CI — the structural gates
       (everything completes, nothing quarantined) are the
       hardware-independent half; the lane win needs network-attached
       silicon where jobs are dispatch-latency-bound.
    3. ``serve_chaos`` — an 8-job run under a deterministic
       preempt/kill/requeue fault schedule plus one poison tenant:
       gates that every surviving job's final circuits are
       bit-identical to standalone runs and the poison job is
       quarantined without collateral damage.
    4. ``serve_merged`` — the fleet-merged wave ratio: the same
       device-routed 8-job set as one merged wave vs per-thread lanes;
       jobs/hour, p99 ttfh, and the per-wave device-dispatch ratio
       (structurally gated — merging engaged and at least halved the
       dispatches; in lockstep it reaches ~1/lanes).
    5. ``serve_chained`` — round chains stacked on the wave
       (``Options.chain_rounds``): merged chained all-outputs jobs vs
       per-thread one-round chains; the combined ratio approaches
       1 / (lanes x rounds_per_dispatch) and is gated at the lane
       factor.
    """
    import shutil
    import tempfile

    from sboxgates_tpu.resilience import faults
    from sboxgates_tpu.search.serve import DONE, QUARANTINED

    n_jobs = n_jobs or (8 if SMOKE else 16)
    lanes = 4
    work = tempfile.mkdtemp(prefix="sbg-serve-bench-")
    out = []
    try:
        jobs = _serve_job_set(n_jobs)
        # Arm 1: t1 = one lane, same window.
        t1_wall, t1_view, _, _ = _run_serve_arm(
            os.path.join(work, "t1"), jobs, lanes=1
        )
        t1_done = t1_view["counts"][DONE]
        out.append({
            "metric": "serve_serial_t1", "jobs": n_jobs, "lanes": 1,
            "completed": t1_done, "wall_s": round(t1_wall, 3),
            "value": round(3600.0 * t1_done / t1_wall, 1),
            "unit": "jobs/hour (1 lane, t1 baseline)",
        })
        # Arm 2: the multi-lane load run.
        wall, view, stats, _ = _run_serve_arm(
            os.path.join(work, "load"), jobs, lanes=lanes
        )
        done = view["counts"][DONE]
        hists = stats.histograms()
        ttfh = hists.get("job_time_to_first_hit_s", {})
        qwait = hists.get("serve_queue_wait_s", {})
        out.append({
            "metric": "serve_load", "jobs": n_jobs, "lanes": lanes,
            "completed": done, "all_completed": done == n_jobs,
            "quarantined": view["counts"][QUARANTINED],
            "wall_s": round(wall, 3),
            "value": round(3600.0 * done / wall, 1),
            "unit": "jobs/hour",
            "vs_t1": round(t1_wall / wall, 3),
            "p50_ttfh_s": ttfh.get("p50"),
            "p99_ttfh_s": ttfh.get("p99"),
            "p50_queue_wait_s": qwait.get("p50"),
            "p99_queue_wait_s": qwait.get("p99"),
            "serve_jobs_admitted": stats.get("serve_jobs_admitted", 0),
            "serve_preemptions": stats.get("serve_preemptions", 0),
        })
        # Arm 3: chaos + poison isolation, bit-identity gated.
        cjobs = _serve_job_set(8)
        faults.disarm()
        # One-output jobs have ONE progress record per attempt, so the
        # preempt schedules fire on the first boundary (a requeued
        # attempt re-reaches it, exercising resume-under-preemption).
        for victim, when in (("j01", "1"), ("j03", "1")):
            faults.arm(f"serve.preempt@job:{victim}", "raise", when)
        faults.arm("search.node@job:j05", "raise", "2")
        faults.arm("search.node@job:poison", "raise", "1+")
        try:
            croot = os.path.join(work, "chaos")
            cwall, cview, cstats, orch = _run_serve_arm(
                croot,
                cjobs + [("poison", _serve_job_set(1)[0][1], 0, "evil")],
                lanes=3, retries=2,
            )
        finally:
            faults.disarm()
        healthy_done = all(
            cview["jobs"][j[0]]["state"] == DONE for j in cjobs
        )
        quarantined = cview["jobs"]["poison"]["state"] == QUARANTINED
        identical = True
        if healthy_done:
            import hashlib as _hl

            for job_id, path, output, _tenant in cjobs:
                seed = int(orch._jobs[job_id].seed)
                ref = _serve_standalone_digests(
                    os.path.join(work, f"ref-{job_id}"), path, output,
                    seed,
                )
                got = {
                    f: _hl.sha256(open(
                        os.path.join(croot, job_id, f), "rb"
                    ).read()).hexdigest()
                    for f in sorted(os.listdir(
                        os.path.join(croot, job_id)
                    )) if f.endswith(".xml")
                }
                identical = identical and got == ref
        out.append({
            "metric": "serve_chaos", "jobs": len(cjobs) + 1,
            "lanes": 3, "wall_s": round(cwall, 3),
            "value": int(cstats.get("serve_preemptions", 0)),
            "unit": "preemptions (chaos schedule)",
            "bit_identical": bool(healthy_done and identical),
            "quarantine_isolated": bool(quarantined and healthy_done),
            "serve_quarantined": cstats.get("serve_quarantined", 0),
        })
        # Arm 4: the fleet-merged wave ratio — same device-routed job
        # set, per-thread lanes vs one merged wave.  The dispatch ratio
        # is the hardware-independent half of the claim (the PR 8/11
        # convention): an 8-tenant same-bucket wave's sweeps collapse
        # toward ONE dispatch per round, ~1/lanes of the per-thread
        # arm's device_dispatches, on CPU CI and silicon alike.
        mpaths = _toy_serve_files(work, 8)
        uwall, uview, ustats = _run_serve_dev_arm(
            os.path.join(work, "unmerged"), mpaths, lanes=8, merge=False,
        )
        mwall, mview, mstats = _run_serve_dev_arm(
            os.path.join(work, "merged"), mpaths, lanes=8, merge=True,
        )
        from sboxgates_tpu.search.serve import DONE as _DONE

        m_done = mview["counts"][_DONE]
        u_done = uview["counts"][_DONE]
        ratio = (
            mstats.get("device_dispatches", 0)
            / max(1, ustats.get("device_dispatches", 0))
        )
        mhists = mstats.histograms()
        mttfh = mhists.get("job_time_to_first_hit_s", {})
        uttfh = ustats.histograms().get("job_time_to_first_hit_s", {})
        out.append({
            "metric": "serve_merged", "jobs": 8, "lanes": 8,
            "value": round(ratio, 4),
            "unit": "device-dispatch ratio, merged wave vs per-thread "
                    "lanes (same job set)",
            "all_completed": m_done == 8 and u_done == 8,
            # The structural gate: merging engaged AND at least halved
            # the dispatch count (in lockstep it reaches ~1/lanes; the
            # band absorbs retirement-skew singletons).
            "merged_dispatches_halved": bool(
                mstats.get("serve_merged_dispatches", 0) > 0
                and 2 * mstats.get("device_dispatches", 0)
                <= ustats.get("device_dispatches", 0)
            ),
            "merged_wall_s": round(mwall, 3),
            "per_thread_wall_s": round(uwall, 3),
            "jobs_per_hour_merged": round(3600.0 * m_done / mwall, 1),
            "jobs_per_hour_per_thread": round(3600.0 * u_done / uwall, 1),
            "p99_ttfh_s_merged": mttfh.get("p99"),
            "p99_ttfh_s_per_thread": uttfh.get("p99"),
            "serve_merged_dispatches": mstats.get(
                "serve_merged_dispatches", 0
            ),
            "device_dispatches_merged": mstats.get("device_dispatches", 0),
            "device_dispatches_per_thread": ustats.get(
                "device_dispatches", 0
            ),
            "wave_lanes_p50": mhists.get(
                "serve_wave_lanes", {}
            ).get("p50"),
        })
        # Arm 5: round chains stacked on the wave — chained all-outputs
        # jobs (Options.chain_rounds) in a merged wave vs the same
        # chains per-thread at one round per dispatch: the combined
        # ratio approaches 1 / (lanes x rounds_per_dispatch).
        cpaths = _toy_serve_files(work, 4)
        c1wall, c1view, c1stats = _run_serve_dev_arm(
            os.path.join(work, "chain1"), cpaths, lanes=4, merge=False,
            chain_rounds=1,
        )
        c8wall, c8view, c8stats = _run_serve_dev_arm(
            os.path.join(work, "chain8"), cpaths, lanes=4, merge=True,
            chain_rounds=8,
        )
        cratio = (
            c8stats.get("device_dispatches", 0)
            / max(1, c1stats.get("device_dispatches", 0))
        )
        out.append({
            "metric": "serve_chained", "jobs": 4, "lanes": 4,
            "chain_rounds": 8,
            "value": round(cratio, 4),
            "unit": "device-dispatch ratio, merged chained wave vs "
                    "per-thread one-round chains (same job set)",
            "all_completed": (
                c8view["counts"][_DONE] == 4
                and c1view["counts"][_DONE] == 4
            ),
            # lanes x rounds compose: the merged chained run must beat
            # the per-thread per-round run by at least the lane factor.
            "combined_ratio_ok": bool(
                c8stats.get("serve_merged_dispatches", 0) > 0
                and 4 * c8stats.get("device_dispatches", 0)
                <= c1stats.get("device_dispatches", 0)
            ),
            "device_dispatches_chained_merged": c8stats.get(
                "device_dispatches", 0
            ),
            "device_dispatches_per_round": c1stats.get(
                "device_dispatches", 0
            ),
            "round_driver_rounds": c8stats.get("round_driver_rounds", 0),
            "wall_s_merged": round(c8wall, 3),
            "wall_s_per_round": round(c1wall, 3),
        })
    finally:
        shutil.rmtree(work, ignore_errors=True)
    return out


def _run_store_serve_arm(root, store_dir, jobs, lanes=4, seed=9,
                         dev=False, batches=1):
    """One serve arm with a result store attached (``jobs`` is a list of
    per-batch job lists when ``batches`` > 1 — later batches admit after
    the earlier ones completed and their results flushed to the store).
    Returns (wall_s, view, stats, orch)."""
    from sboxgates_tpu.resilience.deadline import DeadlineConfig
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.serve import ServeJob, ServeOrchestrator

    opts = dict(seed=seed, result_store=store_dir)
    if dev:
        opts.update(
            lut_graph=True, randomize=False, host_small_steps=False,
            native_engine=False, warmup=False,
        )
    ctx = SearchContext(Options(**opts))
    orch = ServeOrchestrator(
        ctx, root, lanes=lanes,
        deadline=DeadlineConfig(retries=2, backoff_s=0.05),
        log=lambda s: None,
    )
    batched = jobs if batches > 1 else [jobs]
    t0 = time.perf_counter()
    view = None
    for batch in batched:
        for job_id, path, output, tenant, permute in batch:
            orch.submit(ServeJob(
                job_id=job_id, sbox_path=path, output=output,
                tenant=tenant, permute=permute,
            ))
        orch.start()
        view = orch.run_until_idle(timeout_s=ENTRY_BUDGET_S)
        ctx.result_store.flush()
    wall = time.perf_counter() - t0
    orch.stop()
    return wall, view, ctx.stats, orch


def bench_store() -> list:
    """``bench.py --store``: the content-addressed result store
    (BENCH_STORE.json).

    1. ``store_cold_vs_warm`` — the device-routed toy job set COLD (all
       misses: real searches, real dispatches) then WARM in a fresh
       serve root against the now-populated store: warm-hit latency
       (the ``store_get_s`` histogram: canonicalize + read + rewrite +
       all-2^8-inputs re-verify) vs the cold search wall, and the p99
       time-to-first-hit delta between the arms.  Structurally gated —
       machine-independent by construction: every warm job hit, the
       warm arm issued ZERO device dispatches, and every hit circuit is
       bit-identical to the cold run's.
    2. ``store_hit_ratio`` — a repeat-heavy tenant mix in two admission
       batches: batch 2 re-submits six of batch 1's queries EXACTLY
       from other tenants (different job ids, different seeds —
       full-circuit hits don't depend on the seed), one in a different
       CANONICAL frame (the same S-box bit under an input XOR-permute —
       the cross-frame merge the canonical keys exist for), and one
       truly novel S-box; gate: exactly the seven repeats hit.
    """
    import shutil
    import tempfile

    from sboxgates_tpu.search.serve import DONE

    work = tempfile.mkdtemp(prefix="sbg-store-bench-")
    out = []
    try:
        store_dir = os.path.join(work, "store")
        n = 8
        paths = _toy_serve_files(work, n)
        tjobs = [
            (f"t{i:02d}", p, 0, f"ten{i % 3}", 0)
            for i, p in enumerate(paths)
        ]
        cwall, cview, cstats, corch = _run_store_serve_arm(
            os.path.join(work, "cold"), store_dir, tjobs, dev=True,
        )
        wwall, wview, wstats, worch = _run_store_serve_arm(
            os.path.join(work, "warm"), store_dir, tjobs, dev=True,
        )
        import hashlib as _hl

        def _digests(root, job_id):
            d = os.path.join(root, job_id)
            return {
                f: _hl.sha256(
                    open(os.path.join(d, f), "rb").read()
                ).hexdigest()
                for f in sorted(os.listdir(d)) if f.endswith(".xml")
            }

        identical = all(
            all(
                _digests(corch.root, j[0]).get(f) == dg
                for f, dg in _digests(worch.root, j[0]).items()
            )
            for j in tjobs
        )
        get_h = wstats.histograms().get("store_get_s", {})
        cttfh = cstats.histograms().get("job_time_to_first_hit_s", {})
        wttfh = wstats.histograms().get("job_time_to_first_hit_s", {})
        out.append({
            "metric": "store_cold_vs_warm", "jobs": n,
            "value": round(cwall / max(wwall, 1e-9), 2),
            "unit": "cold-search wall / warm-hit wall (same job set)",
            "all_hits": int(wstats.get("store_hits", 0)) == n,
            "zero_device_dispatches": int(
                wstats.get("device_dispatches", 0)
            ) == 0,
            "bit_identical": bool(
                identical
                and cview["counts"][DONE] == n
                and wview["counts"][DONE] == n
            ),
            "cold_wall_s": round(cwall, 3),
            "warm_wall_s": round(wwall, 3),
            "hit_p50_s": get_h.get("p50"),
            "hit_p99_s": get_h.get("p99"),
            "p99_ttfh_s_cold": cttfh.get("p99"),
            "p99_ttfh_s_warm": wttfh.get("p99"),
            "device_dispatches_cold": int(
                cstats.get("device_dispatches", 0)
            ),
            "store_puts_cold": int(cstats.get("store_puts", 0)),
        })
        # Arm 2: the repeat-heavy mix.  Seven of batch 2's eight
        # queries repeat batch 1: six exactly (other tenants/seeds) and
        # one in a different CANONICAL frame — 'c0' asks for the same
        # DES bit under an input XOR-permute, which the canonical keys
        # merge by design.  'n0' (a different S-box) is the one true
        # novelty.
        des = os.path.join(HERE, "sboxes", "des_s1.txt")
        fa = os.path.join(HERE, "sboxes", "crypto1_fa.txt")
        batch1 = [
            (f"a{i}", des, i, "acme", 0) for i in range(4)
        ]
        batch2 = [
            ("b0", des, 0, "blue", 0), ("b1", des, 1, "blue", 0),
            ("b2", des, 2, "core", 0), ("b3", des, 3, "core", 0),
            ("b4", des, 0, "dine", 0), ("b5", des, 1, "dine", 0),
            ("c0", des, 0, "core", 1), ("n0", fa, 0, "blue", 0),
        ]
        rwall, rview, rstats, rorch = _run_store_serve_arm(
            os.path.join(work, "ratio"), os.path.join(work, "store2"),
            [batch1, batch2], batches=2,
        )
        hits = int(rstats.get("store_hits", 0))
        total = len(batch1) + len(batch2)
        out.append({
            "metric": "store_hit_ratio",
            "jobs": total,
            "value": round(hits / len(batch2), 3),
            "unit": "hit ratio over the repeat batch (7 of 8 repeat: "
                    "6 exact + 1 canonical-frame)",
            "ratio_ok": bool(
                hits == 7 and rview["counts"][DONE] == total
            ),
            "store_hits": hits,
            "store_misses": int(rstats.get("store_misses", 0)),
            "wall_s": round(rwall, 3),
        })
    finally:
        shutil.rmtree(work, ignore_errors=True)
    return out


def _serve_net_distinct_queries(k):
    """K genuinely distinct 3-input queries as wire-format S-box texts:
    candidate output-0 truth tables deduped by their CANONICAL key (the
    toy fleet corpus is useless here — its boxes are all complement-
    equivalent on any one output bit, which the canonical keys merge
    by design)."""
    from sboxgates_tpu.core import canon
    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.graph.state import GATES
    from sboxgates_tpu.utils.sbox import parse_sbox

    mask = tt.mask_table(3)
    seen, queries = set(), []
    for t in (0x96, 0xe8, 0xca, 0x80, 0x88, 0x68, 0x6a, 0xea,
              0xf8, 0x9e, 0x7e, 0x1e):
        text = " ".join("%02x" % ((t >> i) & 1) for i in range(8))
        sbox, _n = parse_sbox(text)
        key, _ = canon.canonicalize(
            tt.target_table(sbox, 0), mask, GATES
        )
        if key in seen:
            continue
        seen.add(key)
        queries.append(text)
        if len(queries) == k:
            break
    return queries


def _serve_net_stack(work, sub, store_dir=None, seed=9, lanes=4):
    """One in-process admission stack (context + orchestrator +
    AdmissionServer on an ephemeral loopback port), NOT started."""
    from sboxgates_tpu.resilience.deadline import DeadlineConfig
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.serve import ServeOrchestrator
    from sboxgates_tpu.serve_net import TokenStore, write_token_file
    from sboxgates_tpu.serve_net.server import AdmissionServer

    opts = dict(
        seed=seed, lut_graph=True, randomize=False,
        host_small_steps=False, native_engine=False, warmup=False,
    )
    if store_dir is not None:
        opts["result_store"] = store_dir
    ctx = SearchContext(Options(**opts))
    orch = ServeOrchestrator(
        ctx, os.path.join(work, sub), lanes=lanes,
        deadline=DeadlineConfig(retries=2, backoff_s=0.05),
        log=lambda s: None,
    )
    tok = os.path.join(work, "tokens.json")
    if not os.path.exists(tok):
        write_token_file(tok, {
            f"ten{i}": {"token": f"tok{i}", "max_jobs": 64,
                        "rate_per_s": 5000.0, "burst": 2000}
            for i in range(3)
        })
    srv = AdmissionServer(
        orch, TokenStore.load(tok), ctx.stats, orch.root,
        log=lambda s: None,
    )
    return ctx, orch, srv


def _net_post(port, token, sbox_text, idem=None, wait_s=None):
    """One closed-loop client round trip: POST the query, then ride the
    long-poll GET to terminal.  Returns (post_status, final_doc)."""
    import http.client

    headers = {"Authorization": f"Bearer {token}"}
    if idem:
        headers["Idempotency-Key"] = idem
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    try:
        c.request("POST", "/v1/jobs",
                  body=json.dumps({"sbox": sbox_text, "output": 0}),
                  headers=headers)
        r = c.getresponse()
        status, doc = r.status, json.loads(r.read().decode("utf-8"))
        while wait_s and status < 400 and doc.get("state") not in (
            "done", "quarantined"
        ):
            c.request(
                "GET", f"/v1/jobs/{doc['job_id']}?wait={wait_s}",
                headers=headers,
            )
            r = c.getresponse()
            doc = json.loads(r.read().decode("utf-8"))
        return status, doc
    finally:
        c.close()


def bench_serve_net() -> list:
    """``bench.py --serve-net``: the network admission front door
    (BENCH_NET.json).

    1. ``serve_net_load`` — closed-loop loopback clients (one thread per
       tenant connection) posting a zipf-repeat query mix through the
       REAL HTTP surface and long-polling each job to done: admitted
       jobs/hour, admission p99 (the ``net_admit_s`` histogram), and
       the repeat-hit ratio headline.  Structural gates: every request
       completed with a circuit, and the whole mix admitted exactly ONE
       search per distinct canonical query.
    2. ``serve_net_repeat`` — a fresh stack against the populated
       result store answers a repeat POST with 200 + the circuit and
       ZERO device dispatches end to end.
    3. ``serve_net_duplicate`` — N barrier-released concurrent POSTs of
       one identical query admit exactly one search; the rest join.
    4. ``serve_net_drain`` — jobs admitted mid-load survive the drain
       (listener closed first, orchestrator drained second) and the
       next boot's journal replay runs every one to completion.
    """
    import shutil
    import tempfile
    import threading

    work = tempfile.mkdtemp(prefix="sbg-net-bench-")
    out = []
    try:
        # Arm 1: the zipf-repeat closed loop.
        queries = _serve_net_distinct_queries(4)
        # Zipf-ish repeat weights over the distinct queries: the head
        # query dominates, the tail is cold — the serve-cache shape.
        mix = [queries[j] for j, w in enumerate((8, 4, 2, 1))
               for _ in range(w)]
        clients = 5
        per_client = len(mix) // clients + 1
        store_dir = os.path.join(work, "store")
        ctx, orch, srv = _serve_net_stack(work, "load", store_dir)
        srv.start()
        orch.start()
        results = [[] for _ in range(clients)]

        def run_client(i):
            for j in range(per_client):
                q = mix[(i * per_client + j) % len(mix)]
                results[i].append(
                    _net_post(srv.port, f"tok{i % 3}", q, wait_s=30)
                )

        t0 = time.perf_counter()
        threads = [
            threading.Thread(target=run_client, args=(i,))
            for i in range(clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(ENTRY_BUDGET_S)
        wall = time.perf_counter() - t0
        flat = [r for rows in results for r in rows]
        completed = sum(
            1 for s, d in flat
            if s in (200, 202) and d.get("state") == "done"
            and d.get("circuits")
        )
        admitted = int(ctx.stats.get("net_jobs_admitted", 0))
        repeats = int(ctx.stats.get("net_repeat_hits", 0))
        admit_h = ctx.stats.histograms().get("net_admit_s", {})
        srv.close()
        orch.run_until_idle(timeout_s=ENTRY_BUDGET_S)
        orch.stop()
        ctx.result_store.flush()
        requests = clients * per_client
        out.append({
            "metric": "serve_net_load",
            "value": round(requests / max(wall, 1e-9) * 3600.0, 1),
            "unit": "client requests served to done per hour "
                    "(zipf-repeat mix, closed-loop loopback clients)",
            "requests": requests,
            "distinct_queries": len(queries),
            "all_completed": completed == requests,
            "one_search_per_query": admitted == len(queries),
            "hit_ratio": round(repeats / max(requests, 1), 3),
            "admission_p50_s": admit_h.get("p50"),
            "admission_p99_s": admit_h.get("p99"),
            "wall_s": round(wall, 3),
        })
        # Arm 2: the stored-query repeat — fresh stack, same store.
        ctx2, orch2, srv2 = _serve_net_stack(work, "warm", store_dir)
        srv2.start()
        orch2.start()
        s, d = _net_post(srv2.port, "tok0", queries[0], wait_s=30)
        srv2.close()
        orch2.run_until_idle(timeout_s=ENTRY_BUDGET_S)
        orch2.stop()
        out.append({
            "metric": "serve_net_repeat",
            "value": int(ctx2.stats.get("device_dispatches", 0)),
            "unit": "device dispatches answering a stored query "
                    "over HTTP (gated at zero)",
            "zero_device_dispatches_on_repeat": bool(
                s == 200 and d.get("state") == "done"
                and d.get("store") == "hit" and d.get("circuits")
                and int(ctx2.stats.get("device_dispatches", 0)) == 0
            ),
            "status": s,
        })
        # Arm 3: concurrent duplicates — one search, the rest join.
        ctx3, orch3, srv3 = _serve_net_stack(work, "dup")
        srv3.start()
        orch3.start()
        n = 6
        barrier = threading.Barrier(n)
        dup = [None] * n

        def dup_client(i):
            barrier.wait()
            dup[i] = _net_post(
                srv3.port, "tok0", queries[1], idem="dup", wait_s=30
            )

        threads = [
            threading.Thread(target=dup_client, args=(i,))
            for i in range(n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(ENTRY_BUDGET_S)
        admitted3 = int(ctx3.stats.get("net_jobs_admitted", 0))
        srv3.close()
        orch3.run_until_idle(timeout_s=ENTRY_BUDGET_S)
        orch3.stop()
        out.append({
            "metric": "serve_net_duplicate",
            "value": admitted3,
            "unit": f"searches admitted for {n} concurrent identical "
                    "POSTs (gated at one)",
            "no_duplicate_search": bool(
                admitted3 == 1
                and all(r and r[0] in (200, 202) for r in dup)
                and len({r[1]["job_id"] for r in dup}) == 1
            ),
        })
        # Arm 4: drain mid-load, replay next boot.
        from sboxgates_tpu.serve_net.admission import pending_jobs

        ctx4, orch4, srv4 = _serve_net_stack(work, "drain")
        srv4.start()  # scheduler NOT started: jobs stay admitted/queued
        admitted4 = []
        for j, q in enumerate(queries[:3]):
            s, d = _net_post(srv4.port, "tok1", q, idem=f"dr{j}")
            if s == 202:
                admitted4.append(d["job_id"])
        srv4.close()
        orch4.drain(timeout_s=10.0)
        survived = set(pending_jobs(orch4.root)) == set(admitted4)
        ctx5, orch5, srv5 = _serve_net_stack(work, "drain")
        replayed = srv5.replay()
        orch5.start()
        view5 = orch5.run_until_idle(timeout_s=ENTRY_BUDGET_S)
        orch5.stop()
        done5 = sum(
            1 for jid in admitted4
            if view5["jobs"].get(jid, {}).get("state") == "done"
        )
        out.append({
            "metric": "serve_net_drain",
            "value": done5,
            "unit": f"of {len(admitted4)} drained-mid-load jobs "
                    "completed by the next boot's journal replay",
            "drain_loses_nothing": bool(
                survived
                and len(admitted4) == 3
                and set(replayed) == set(admitted4)
                and done5 == len(admitted4)
            ),
        })
    finally:
        shutil.rmtree(work, ignore_errors=True)
    return out


def bench_roofline() -> list:
    """Measured roofline placement for EVERY kernel in the ``KERNELS``
    registry (BENCH_ROOFLINE.json) — the maintained successor to
    ROOFLINE.md's hand-derived single-kernel memo.

    For each registered kernel the entry (a) captures XLA's own
    ``cost_analysis()`` / ``memory_analysis()`` at compile time through
    the telemetry attribution layer (the same capture production runs
    make), and (b) measures resolved per-dispatch wall time —
    ``kernel_call`` + ``block_until_ready`` — into a dedicated join
    registry, so the achieved FLOP/s / bytes/s rates are end-to-end,
    not async-issue latencies.  Kernels the real drivers dispatch
    unconditionally are driven through the real drivers (gate/LUT node
    heads, the streams, the pivot path, the fused round driver); the
    conditional tails (solvers, overflow re-drives, the filter heads,
    the 64-bit-rank stream) are dispatched directly with
    registry-validated operands.

    On CPU CI the absolute rates are plumbing-grade; the entry's
    hardware-independent claims are coverage (every registry kernel has
    a (kernel, bucket) cost row) and the placement arithmetic.  On
    silicon the same mode writes the real roofline."""
    import jax

    from sboxgates_tpu.core import boolfunc as bf
    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.graph.state import GATES, State
    from sboxgates_tpu.ops import sweeps
    from sboxgates_tpu.search import Options, SearchContext, run_round_chain
    from sboxgates_tpu.search import context as C
    from sboxgates_tpu.search import lut as slut
    from sboxgates_tpu.search.warmup import KERNELS
    from sboxgates_tpu.telemetry import attribution as tattr
    from sboxgates_tpu.telemetry import metrics as tmetrics

    tattr.reset()
    tattr.note_backend(jax.default_backend())
    tattr.set_lazy_capture(True)
    join = tmetrics.MetricsRegistry(declared=None)
    reps = 2 if SMOKE else 3

    def grow(n, seed=0):
        rng = np.random.default_rng(seed)
        st = State.init_inputs(8)
        while st.num_gates < n:
            a, b = rng.choice(st.num_gates, size=2, replace=False)
            st.add_gate(bf.XOR, int(a), int(b), GATES)
        return st

    mask = tt.mask_table(8)
    miss = np.zeros(8, dtype=np.uint32)  # unrealizable: full sweeps

    def instrument(ctx):
        """Swaps ctx.kernel_call for a resolving, latency-observing
        wrapper (warm passes run first on the plain method, so compile
        stalls never pollute the measured distribution)."""
        orig = ctx.kernel_call

        def timed(name, statics, args, g=None, _orig=orig):
            t0 = time.perf_counter()
            out = _orig(name, statics, args, g=g)
            jax.block_until_ready(out)
            # Same (kernel, bucket) member key the attribution join
            # prefers — resolved wall time, one histogram per row.
            b = tattr.derive_bucket(args)
            key = (
                f"dispatch_latency_s[{name}/{b}]" if b is not None
                else f"dispatch_latency_s[{name}]"
            )
            join.observe(key, time.perf_counter() - t0)
            return out

        ctx.kernel_call = timed

    # -- gate-mode node heads ---------------------------------------------
    ctxg = SearchContext(Options(
        seed=1, randomize=False, host_small_steps=False,
        parallel_mux=False,
    ))
    stg = grow(20)

    def gate_drivers():
        ctxg.gate_step(stg, miss, mask)
        ctxg.pair_search(stg, miss, mask, False)
        ctxg.triple_search(stg, miss, mask)

    gate_drivers()  # warm: compiles happen here, costs captured
    instrument(ctxg)
    for _ in range(reps):
        gate_drivers()

    # -- LUT-mode heads, streams, pivot path ------------------------------
    ctx = SearchContext(Options(
        seed=1, lut_graph=True, randomize=False, host_small_steps=False,
        parallel_mux=False,
    ))
    st16, st24 = grow(16, seed=1), grow(24, seed=2)
    st50 = grow(50, seed=3)
    live50 = np.asarray(st50.live_tables())
    # Planted 5-LUT hit ((a^b^c)^(d^e) decomposes), so the pivot sweep
    # exits on an early tile instead of walking all of C(50,5) on CPU.
    hit5 = (live50[10] ^ live50[20] ^ live50[30] ^ live50[40]
            ^ live50[49]).astype(np.uint32)

    def lut_drivers():
        ctx.lut_step(st24, miss, mask, [])           # lut_step_stream
        ctx.lut7_step(st16, miss, mask, [])          # lut7_step_stream
        slut.lut3_search(ctx, st24, miss, mask, [])  # lut3_stream
        slut.lut5_search(ctx, st24, miss, mask, [])  # lut5_stream
        slut.lut5_search(ctx, st50, hit5, mask, [])  # pivot cells+stream

    # -- conditional tails, dispatched directly ---------------------------
    binom = sweeps.binom_table()
    blo, bhi = sweeps.binom_table_wide()
    _, w_tab, m_tab = sweeps.lut5_split_tables()
    idx_tab, pp_tab = sweeps.lut7_pair_tables()
    excl = SearchContext.excl_array([])
    tab24 = np.zeros((C.bucket_size(24), 8), dtype=np.uint32)
    tab24[:24] = np.asarray(st24.live_tables())
    infeasible = np.uint32(0xFFFFFFFF)
    tl, th = slut.pivot_tile_shape(50)
    p2pad, tpad = slut.pivot_padded_shapes(50, tl, th)

    def tail_dispatches():
        ctx.kernel_call(
            "feasible_stream", dict(k=5, chunk=4096),
            (tab24, binom, 24, miss, mask, excl, 0, 4096), g=24,
        )
        ctx.kernel_call(
            "feasible_stream_wide", dict(k=5, chunk=4096, backend="xla"),
            (tab24, blo, bhi, 24, miss, mask, excl, 0, 0, 4096, 0), g=24,
        )
        ctx.kernel_call(
            "lut_filter", {},
            (tab24, np.zeros((1024, 7), np.int32),
             np.ones(1024, bool), miss, mask), g=24,
        )
        ctx.kernel_call(
            "lut5_filter", dict(backend="xla"),
            (tab24, np.zeros((1024, 5), np.int32),
             np.ones(1024, bool), miss, mask), g=24,
        )
        ctx.kernel_call(
            "lut5_solve", {},
            (np.full(1024, infeasible), np.full(1024, infeasible),
             w_tab, m_tab, 0), g=24,
        )
        ctx.kernel_call(
            "lut7_solve", {},
            (np.full((256, 4), infeasible), np.full((256, 4), infeasible),
             idx_tab, pp_tab, 0), g=24,
        )
        cells = np.zeros((4, p2pad, 8), np.uint32)
        ctx.kernel_call(
            "lut5_pivot_tile", dict(tl=tl, th=th),
            (np.zeros((C.bucket_size(50), 8), np.uint32), cells, cells,
             cells, np.zeros(p2pad, bool), np.zeros(p2pad, bool),
             np.zeros((tpad, 5), np.int32), 0), g=50,
        )

    lut_drivers()
    tail_dispatches()
    instrument(ctx)
    for _ in range(reps):
        lut_drivers()
        tail_dispatches()

    # -- fused round driver (real chain driver) ---------------------------
    ctxr = SearchContext(Options(
        lut_graph=True, randomize=False, warmup=False, parallel_mux=False,
    ))
    str_, rounds = _round_chain_problem(8, 12)
    run_round_chain(ctxr, str_, rounds, rounds_per_dispatch=4)  # warm
    instrument(ctxr)
    str2, rounds2 = _round_chain_problem(8, 12)
    run_round_chain(ctxr, str2, rounds2, rounds_per_dispatch=4)

    rows = tattr.table(join)
    covered = {r["kernel"] for r in rows}
    missing = sorted(set(KERNELS) - covered)
    entries = [
        {"metric": f"roofline_{r['kernel']}", "unit": "roofline row", **r}
        for r in rows
    ]
    entries.append({
        "metric": "roofline_coverage",
        "unit": "kernels",
        "value": len(covered),
        "registry_kernels": len(KERNELS),
        "missing": missing,
        "backend": tattr.backend(),
        "peaks": tattr.peaks(),
    })
    if missing:
        raise AssertionError(
            f"roofline coverage hole: no cost row for {missing}"
        )
    return entries


# --- drift gates (bench.py --check) ---------------------------------------
#
# The repo carries 13 committed BENCH_*.json files and, until this
# comparator, zero automated regression detection over them.  --check
# re-runs a CHEAP section and diffs its t1-normalized / structural
# headline metrics against the committed baseline with explicit noise
# bands, exiting nonzero on regression.  Only window-normalized ratios
# are gated (dispatch ratios, speedups) — raw cand/s across machines or
# throttle windows is exactly the comparison the t1 convention forbids.

#: name -> (runner, baseline file, [(metric, field, band, direction)]).
#: direction "lower": regression = new > base*(1+band);
#: "higher": regression = new < base*(1-band);
#: "exact": regression = new != base.
BENCH_CHECKS = {
    "multiround": (
        # Fixed small chain: the gated dispatch/sync ratios are
        # size-independent, and this section rides every tier-1 run.
        lambda: bench_device_rounds(8, n_rounds=16),
        "BENCH_MULTIROUND.json",
        [
            ("device_rounds_dispatch_ratio", "value", 0.01, "lower"),
            ("device_rounds_dispatch_ratio", "sync_ratio", 0.01, "lower"),
            ("device_rounds_dispatch_ratio", "circuits_bit_identical",
             0.0, "exact"),
        ],
    ),
    "serve": (
        # Small fixed job set: the gated fields are structural (did
        # everything complete; did chaos recovery stay bit-identical;
        # did the poison job quarantine cleanly) — machine-independent
        # by construction, like the multiround dispatch ratios.
        lambda: bench_serve(8),
        "BENCH_SERVE.json",
        [
            ("serve_load", "all_completed", 0.0, "exact"),
            ("serve_chaos", "bit_identical", 0.0, "exact"),
            ("serve_chaos", "quarantine_isolated", 0.0, "exact"),
            # Fleet-merged waves: merging engaged and the wave's
            # device-dispatch count at most half the per-thread arm's
            # (structural, machine-independent — it reaches ~1/lanes in
            # lockstep; the boolean absorbs retirement-skew noise).
            ("serve_merged", "all_completed", 0.0, "exact"),
            ("serve_merged", "merged_dispatches_halved", 0.0, "exact"),
            # Chained waves: lanes x rounds_per_dispatch compose.
            ("serve_chained", "all_completed", 0.0, "exact"),
            ("serve_chained", "combined_ratio_ok", 0.0, "exact"),
        ],
    ),
    "store": (
        # The result-store drift gate: structural, machine-independent
        # fields only — every warm query hit, the hit path issued ZERO
        # device dispatches, hit circuits byte-equal the cold search's,
        # and the repeat-heavy mix hit exactly its repeats.
        bench_store,
        "BENCH_STORE.json",
        [
            ("store_cold_vs_warm", "all_hits", 0.0, "exact"),
            ("store_cold_vs_warm", "zero_device_dispatches",
             0.0, "exact"),
            ("store_cold_vs_warm", "bit_identical", 0.0, "exact"),
            ("store_hit_ratio", "ratio_ok", 0.0, "exact"),
        ],
    ),
    "net": (
        # The admission-service drift gate: structural, machine-
        # independent fields only — every closed-loop request completed,
        # the zipf mix admitted one search per distinct query, a stored
        # repeat answered over HTTP with zero device dispatches,
        # concurrent duplicates shared one search, and a drain lost no
        # admitted job across restart.
        bench_serve_net,
        "BENCH_NET.json",
        [
            ("serve_net_load", "all_completed", 0.0, "exact"),
            ("serve_net_load", "one_search_per_query", 0.0, "exact"),
            ("serve_net_repeat", "zero_device_dispatches_on_repeat",
             0.0, "exact"),
            ("serve_net_duplicate", "no_duplicate_search", 0.0, "exact"),
            ("serve_net_drain", "drain_loses_nothing", 0.0, "exact"),
        ],
    ),
    "order": (
        # Candidate-ordering drift gate: structural, machine-independent
        # fields only — the exhaustive 7-LUT hit set is unchanged under
        # spectral order, spectral scans <= lex (by dispatch/candidate
        # COUNT, not wall time) on >= 3 of 4 planted targets, and two
        # spectral runs are bit-identical.
        bench_order_ab,
        "BENCH_ORDER.json",
        [
            ("order_ab", "exhaustive_hit_set_equal", 0.0, "exact"),
            ("order_ab", "spectral_scans_leq_lex_on_planted",
             0.0, "exact"),
            ("order_ab", "ordering_deterministic_across_runs",
             0.0, "exact"),
        ],
    ),
    "hoststream": (
        bench_host_stream_pipeline,
        "BENCH_PIPELINE.json",
        [
            # Generous band: CPU-CI speedups breathe with load; the
            # gate exists to catch the pipeline silently serializing
            # (ratio collapsing toward <= 1), not 10% noise.
            ("lut5_host_stream_pipelined", "speedup_vs_serial",
             0.35, "higher"),
        ],
    ),
}


def bench_check(sections=None) -> int:
    """``bench.py --check [section...]``: the perf-drift gate.  Returns
    the process exit code (0 = inside every noise band)."""
    sections = list(sections) if sections else ["multiround"]
    report, regressions = [], []
    for name in sections:
        if name not in BENCH_CHECKS:
            print(json.dumps({
                "metric": "bench_check", "error": f"unknown section {name}",
                "known": sorted(BENCH_CHECKS),
            }))
            return 2
        runner, baseline_file, gates = BENCH_CHECKS[name]
        path = os.path.join(HERE, baseline_file)
        if not os.path.exists(path):
            report.append({
                "section": name, "status": "no-baseline",
                "baseline": baseline_file,
            })
            continue
        with open(path) as f:
            base_entries = json.load(f)
        entries = runner()

        def field_of(entries_, metric, field):
            for e in entries_:
                if e.get("metric") == metric and field in e:
                    return e[field]
            return None

        for metric, field, band, direction in gates:
            base = field_of(base_entries, metric, field)
            new = field_of(entries, metric, field)
            row = {
                "section": name, "metric": metric, "field": field,
                "baseline": base, "measured": new, "band": band,
                "direction": direction,
            }
            if base is None or new is None:
                row["status"] = "skipped (missing value)"
            else:
                if direction == "exact":
                    bad = new != base
                elif direction == "lower":
                    bad = new > base * (1.0 + band)
                else:  # "higher"
                    bad = new < base * (1.0 - band)
                row["status"] = "REGRESSED" if bad else "ok"
                if bad:
                    regressions.append(row)
            report.append(row)
    print(json.dumps({
        "metric": "bench_check",
        "sections": sections,
        "gates": report,
        "regressions": len(regressions),
        "ok": not regressions,
    }, indent=1))
    return 1 if regressions else 0


def _backend_alive(timeout_s: float = 120.0):
    """Probes device availability in a subprocess with a hard timeout.

    The accelerator rides a network tunnel; if its relay is down, the
    first backend touch HANGS rather than erroring.  A hung bench run is
    worse than a failed one — probe first and fail fast.  Returns None
    when healthy, else a diagnostic string."""
    import subprocess
    import sys

    code = (
        "import jax\n"
        "jax.devices()\n"
        "import jax.numpy as jnp\n"
        "print(int((jnp.zeros(4) + 1).sum()))\n"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return f"device probe hung past {timeout_s:.0f}s (tunnel down?)"
    if r.returncode == 0 and r.stdout.strip().endswith("4"):
        return None
    return (
        f"device probe failed rc={r.returncode}: "
        + r.stderr.strip()[-500:]
    )


def main() -> None:
    import sys

    if "--mesh-scaling-worker" in sys.argv:
        # Subprocess mode (bench_mesh_scaling): env already pins CPU; the
        # config update inside the worker guards against the axon
        # sitecustomize re-forcing the tunnel backend.
        print(json.dumps(_mesh_scaling_worker()))
        return
    if "--gather-bench-worker" in sys.argv:
        i = sys.argv.index("--gather-bench-worker")
        _gather_bench_worker(int(sys.argv[i + 1]), sys.argv[i + 2])
        return
    if "--cold-start-worker" in sys.argv:
        _cold_start_worker()
        return
    if "--fleet-split-worker" in sys.argv:
        # Subprocess mode (bench_fleet section 1c): env already pins CPU
        # with 8 virtual devices; guard against the axon sitecustomize
        # re-forcing the tunnel backend.
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(_fleet_split_worker()))
        return
    if "--fleet" in sys.argv:
        # Standalone mode: the fleet-batched search ladder (jobs/hour +
        # device dispatch counts at 1/8/64/256 jobs, the 64/256/1024-
        # lane stacked jobs-bucket ladder, and the (jobs, candidates)
        # device-split sweep), written to BENCH_FLEET.json.  Honors
        # JAX_PLATFORMS — on a CPU-only box run `JAX_PLATFORMS=cpu
        # python bench.py --fleet` (optionally SBG_BENCH_SMOKE=1 for
        # the short ladder).
        if SMOKE:
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax

            jax.config.update("jax_platforms", "cpu")
        detail = bench_fleet()
        with open(os.path.join(HERE, "BENCH_FLEET.json"), "w") as f:
            json.dump(with_meta(detail), f, indent=1)
        print(json.dumps(detail[-1]))
        return
    if "--serve" in sys.argv:
        # Standalone mode: the serve-mode load generator (multi-tenant
        # queue jobs/hour + p99 time-to-first-hit from the registry
        # snapshot, t1 = same jobs on one lane, chaos arm bit-identity
        # gated), written to BENCH_SERVE.json.  CPU-safe.
        if SMOKE or os.environ.get("JAX_PLATFORMS", "") == "cpu":
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax

            jax.config.update("jax_platforms", "cpu")
        detail = bench_serve()
        with open(os.path.join(HERE, "BENCH_SERVE.json"), "w") as f:
            json.dump(with_meta(detail), f, indent=1)
        print(json.dumps(detail[1]))
        return
    if "--order-ab" in sys.argv:
        # Standalone mode: the lex-vs-spectral candidate-ordering A/B
        # over the planted serve mix (p50/p99 time-to-first-hit +
        # candidates-scanned-to-first-hit per arm, hit-set/determinism
        # structural fields), written to BENCH_ORDER.json.  CPU-safe.
        if SMOKE or os.environ.get("JAX_PLATFORMS", "") == "cpu":
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax

            jax.config.update("jax_platforms", "cpu")
        detail = bench_order_ab()
        with open(os.path.join(HERE, "BENCH_ORDER.json"), "w") as f:
            json.dump(with_meta(detail), f, indent=1)
        print(json.dumps(detail[0]))
        return
    if "--store" in sys.argv and "--check" not in sys.argv:
        # Standalone mode: the content-addressed result store A/B
        # (cold-miss search vs warm-hit lookup, hit latency quantiles,
        # repeat-heavy hit ratio, p99 ttfh delta), written to
        # BENCH_STORE.json.  CPU-safe.
        if SMOKE or os.environ.get("JAX_PLATFORMS", "") == "cpu":
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax

            jax.config.update("jax_platforms", "cpu")
        detail = bench_store()
        with open(os.path.join(HERE, "BENCH_STORE.json"), "w") as f:
            json.dump(with_meta(detail), f, indent=1)
        print(json.dumps(detail[0]))
        return
    if "--serve-net" in sys.argv:
        # Standalone mode: the network admission front door (closed-
        # loop loopback clients, admission p99 + jobs/hour under a
        # zipf-repeat mix, stored-repeat zero-dispatch, concurrent-
        # duplicate single-search, drain/replay loss-free), written to
        # BENCH_NET.json.  CPU-safe.
        if SMOKE or os.environ.get("JAX_PLATFORMS", "") == "cpu":
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax

            jax.config.update("jax_platforms", "cpu")
        detail = bench_serve_net()
        with open(os.path.join(HERE, "BENCH_NET.json"), "w") as f:
            json.dump(with_meta(detail), f, indent=1)
        print(json.dumps(detail[0]))
        return
    if "--device-rounds" in sys.argv:
        # Standalone mode: fused multi-round driver vs the per-round
        # loop (one host sync per N rounds vs one per round), written to
        # BENCH_MULTIROUND.json.  Honors JAX_PLATFORMS; an optional
        # integer after the flag sets N (default 8).  Composition notes:
        # the chain driver is a per-thread dispatcher, so --device-rounds
        # measures single-job shape; fleet-merged chains and journal
        # resume are exercised by tests/test_resume.py, not timed here.
        i = sys.argv.index("--device-rounds")
        n_fused = 8
        if i + 1 < len(sys.argv) and sys.argv[i + 1].isdigit():
            # N=1 is accepted (the degenerate fused==per-round case);
            # nothing is silently coerced.
            n_fused = max(1, int(sys.argv[i + 1]))
        detail = bench_device_rounds(n_fused)
        with open(os.path.join(HERE, "BENCH_MULTIROUND.json"), "w") as f:
            json.dump(with_meta(detail), f, indent=1)
        print(json.dumps(detail[-1]))
        return
    if "--check" in sys.argv:
        # Drift gate: re-run a cheap section, diff its t1-normalized /
        # structural headline metrics against the committed BENCH_*.json
        # baseline with explicit noise bands, exit nonzero on
        # regression.  CPU-safe (the tier-1 suite runs the multiround
        # section on every verify).
        if SMOKE or os.environ.get("JAX_PLATFORMS", "") == "cpu":
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax

            jax.config.update("jax_platforms", "cpu")
        i = sys.argv.index("--check")
        sections = []
        for a in sys.argv[i + 1:]:
            if a.startswith("-"):
                break
            sections.append(a)
        raise SystemExit(bench_check(sections or None))
    if "--roofline" in sys.argv:
        # Standalone mode: the measured roofline for every registry
        # kernel (BENCH_ROOFLINE.json) — ROOFLINE.md's maintained
        # successor.  Honors JAX_PLATFORMS; CPU runs exercise coverage
        # and the placement arithmetic, silicon runs write the real
        # numbers.
        if SMOKE:
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax

            jax.config.update("jax_platforms", "cpu")
        detail = bench_roofline()
        with open(os.path.join(HERE, "BENCH_ROOFLINE.json"), "w") as f:
            json.dump(with_meta(detail), f, indent=1)
        print(json.dumps(detail[-1]))
        return
    if "--cold-start" in sys.argv:
        # Standalone mode: cold vs warm persistent-compile-cache
        # time-to-first-dispatch (the restart / --resume-run shape),
        # written to BENCH_COLDSTART.json.  Needs no accelerator.
        detail = bench_cold_start()
        with open(os.path.join(HERE, "BENCH_COLDSTART.json"), "w") as f:
            json.dump(with_meta(detail), f, indent=1)
        print(json.dumps(detail[-1]))
        return
    if "--host-stream" in sys.argv:
        # Standalone mode: just the serial-vs-pipelined host-stream A/B
        # (the before/after evidence for the async chunk pipeline),
        # written to BENCH_PIPELINE.json.  Honors JAX_PLATFORMS — on a
        # CPU-only box run `JAX_PLATFORMS=cpu python bench.py
        # --host-stream` (optionally SBG_BENCH_SMOKE=1 for the small g).
        # Add --sync-guard to run the measured window under strict
        # runtime guards: zero steady-state recompiles, syncs bounded by
        # the deliberate per-chunk verdict count — violations raise
        # instead of being tallied into the report.
        if SMOKE:
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax

            jax.config.update("jax_platforms", "cpu")
        detail = bench_host_stream_pipeline(
            strict_guards="--sync-guard" in sys.argv
        )
        with open(os.path.join(HERE, "BENCH_PIPELINE.json"), "w") as f:
            json.dump(with_meta(detail), f, indent=1)
        # Replicated-degradation protocol overhead + counters ride the
        # same mode (the deadline-guard counters already report here).
        degrade = bench_degrade_protocol()
        with open(os.path.join(HERE, "BENCH_DEGRADE.json"), "w") as f:
            json.dump(with_meta(degrade), f, indent=1)
        pipelined = next(
            e for e in detail
            if e.get("metric") == "lut5_host_stream_pipelined"
        )
        telem = next(
            (e for e in detail if e.get("metric") == "telemetry_overhead"),
            {},
        )
        print(json.dumps({
            "metric": "lut5_host_stream_speedup",
            "value": pipelined.get("speedup_vs_serial"),
            "unit": "x (pipelined vs serial cand/s)",
            "overlap": pipelined.get("overlap"),
            "dispatch_retries": pipelined.get("dispatch_retries"),
            "deadline_breaches": pipelined.get("deadline_breaches"),
            "breach_barriers": pipelined.get("breach_barriers"),
            "replicated_aborts": pipelined.get("replicated_aborts"),
            "degraded_ranks": pipelined.get("degraded_ranks"),
            "verdict_barrier_overhead_s": degrade[2].get(
                "overhead_vs_guard_s"
            ),
            "telemetry_overhead_frac": telem.get("overhead_frac"),
            "telemetry_extra_syncs": telem.get("extra_syncs_trace_on"),
        }))
        return

    def _last_committed_onchip():
        """Provenance of the last *committed* on-chip headline: value,
        commit, capture date — carried on the degraded headline line so
        a tunnel-down round still transports the evidence (VERDICT r4
        item 8).  Best-effort: absent keys on any failure."""
        out = {}
        try:
            # Read the blob at HEAD, not the working tree: a fresh
            # uncommitted capture must not be stamped with the previous
            # commit's hash/date (value and provenance stay consistent).
            r = subprocess.run(
                ["git", "show", "HEAD:BENCH_DETAIL.json"],
                cwd=HERE, capture_output=True, text=True, timeout=30,
            )
            if r.returncode != 0:
                return out
            for e in json.loads(r.stdout):
                m = str(e.get("metric", ""))
                # Same promote-only-if-greater rule as _headline_line: a
                # committed _best entry that lost end-to-end must not
                # override the committed plain headline.
                if (m.startswith("lut5_sweep_g") and "slice" not in m
                        and e.get("value") is not None
                        and e["value"] > out.get(
                            "last_committed_value", float("-inf"))):
                    out["last_committed_value"] = e["value"]
                    out["last_committed_metric"] = m
        except Exception:
            return out
        try:
            r = subprocess.run(
                ["git", "log", "-1", "--format=%h %cI", "--",
                 "BENCH_DETAIL.json"],
                cwd=HERE, capture_output=True, text=True, timeout=30,
            )
            if r.returncode == 0 and r.stdout.strip():
                commit, captured_at = r.stdout.split()
                out["commit"] = commit
                out["captured_at"] = captured_at
        except Exception:
            pass
        return out

    if SMOKE:
        # CPU dry run of the full main path: pin the CPU backend (env
        # alone is not enough — the axon sitecustomize re-forces the
        # tunnel platform at interpreter start) and skip the probe.
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        why_dead = None
    else:
        why_dead = _backend_alive()
    if why_dead is not None:
        # Still record what needs no accelerator — the pure-native CPU
        # baseline and the backend-independent gate-mode config (every
        # node of des_s1 SAT+NOT routes to the native host runtime) — to
        # a SEPARATE file, so the last full on-chip BENCH_DETAIL.json
        # survives in the tree instead of being clobbered by a degraded
        # run.  Pin jax to CPU first: with the tunnel down, ANY touch of
        # the accelerator backend hangs, and context setup places arrays.
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        detail = [{"metric": "backend_unreachable", "error": why_dead}]

        def des_s1_lut():
            # With the native LUT engine, DES-class LUT searches make no
            # device dispatches at all, so this entry is backend-
            # independent too.
            entry, _ = bench_des_s1_lut()
            return entry

        def lut7_capped_cpu():
            # Never chip-captured (VERDICT r3 weak 6): a CPU-backend
            # number bounds the cost until the tunnel returns — the chip
            # runs stage A sharded and stage B as device matmuls.
            e = bench_lut7_capped_search()
            e["backend"] = "cpu"
            return e

        for fn in (bench_cpu_baseline, bench_des_s1_sat_not,
                   bench_des_s1_full_graph, bench_lut7_break_even,
                   des_s1_lut, bench_multibox_des, bench_permute_sweep,
                   bench_engine_pivot_ab, lut7_capped_cpu,
                   bench_mesh_scaling, bench_gather_compaction):
            try:
                r = fn()
                detail.extend(r if isinstance(r, list) else [r])
            except Exception as e:
                detail.append({"metric": fn.__name__, "error": repr(e)})
            # Incremental to a .partial file, renamed over the real one
            # only at completion (same protocol as the main path): a
            # hang loses nothing AND never clobbers the last complete
            # capture with a truncated file.
            with open(
                os.path.join(HERE, "BENCH_UNREACHABLE.partial.json"), "w"
            ) as f:
                json.dump(with_meta(detail), f, indent=1)
        os.replace(
            os.path.join(HERE, "BENCH_UNREACHABLE.partial.json"),
            os.path.join(HERE, "BENCH_UNREACHABLE.json"),
        )
        line = {
            "metric": "lut5_candidates_per_sec_per_chip_aes",
            "value": None,
            "unit": "candidates/s",
            "vs_baseline": None,
            "error": why_dead
            + "; last full on-chip run is committed in git"
            " (BENCH_DETAIL.json)",
        }
        # Transport the provenance instead of a pointer the reader must
        # chase (VERDICT r4 item 8): a null round still names the last
        # committed on-chip headline, its commit, and its capture date.
        line.update(_last_committed_onchip())
        print(json.dumps(line))
        return

    detail = []

    def flush(final=False):
        # Incremental flush goes to a .partial file so a mid-run death
        # keeps everything captured so far WITHOUT clobbering the last
        # complete BENCH_DETAIL.json; the real file is written (and the
        # partial removed) only when the whole run finishes.
        # Smoke runs must never clobber the real on-chip capture.
        name = "BENCH_SMOKE" if SMOKE else "BENCH_DETAIL"
        partial = os.path.join(HERE, f"{name}.partial.json")
        with open(partial, "w") as f:
            json.dump(with_meta(detail), f, indent=1)
        if final:
            os.replace(partial, os.path.join(HERE, f"{name}.json"))

    def _headline_line():
        """The ONE driver-facing JSON line, computed from whatever
        entries have been captured so far (so the watchdog can emit it
        from a partial run)."""
        dev = best = cpu_rate = float("nan")
        cfg = None
        for e in detail:
            if e.get("metric") == f"lut5_sweep_g{G_HEAD}" and "value" in e:
                dev = e["value"]
            if (e.get("metric") == f"lut5_sweep_g{G_HEAD}_best"
                    and "value" in e
                    and (best != best or e["value"] > best)):
                best, cfg = e["value"], e.get("config")
            if e.get("metric") == "cpu_core_lut5" and "value" in e:
                cpu_rate = e["value"]
        # The headline is the production configuration's rate: when the
        # A/B's winner was re-captured through the real driver and beats
        # plain, that IS the production config (the decision rule flips
        # the default to it).
        line_cfg, plain = None, dev
        if best == best and (dev != dev or best > dev):
            dev, line_cfg = best, cfg
        finite = dev == dev and cpu_rate == cpu_rate and cpu_rate > 0
        line = {
            "metric": "lut5_candidates_per_sec_per_chip_aes",
            "value": round(dev, 1) if dev == dev else None,
            "unit": "candidates/s",
            "vs_baseline": round(dev / cpu_rate, 3) if finite else None,
        }
        if line_cfg:
            line["config"] = line_cfg
            # The default flip is a separate reviewed code change, so a
            # promoted best can overstate CURRENT production-default
            # throughput — carry the plain-default rate too, making the
            # line self-describing without chasing BENCH_DETAIL
            # (ADVICE round 5).
            if plain == plain:
                line["value_plain"] = round(plain, 1)
        return line

    # Mid-run tunnel death watchdog (observed live in round 4: the
    # start-of-run probe passed, the first four entries captured, then
    # the tunnel dropped and the fifth entry's RPC blocked FOREVER —
    # XLA device calls are not interruptible, so without this the
    # whole run, partial capture and headline included, would hang past
    # the driver's timeout and record null).  Each run() arms a
    # per-entry deadline; a daemon thread watches it, and on breach
    # salvages the partial capture to BENCH_ABORTED.json, prints the
    # headline line from the entries already captured, and _exits (the
    # only way out of a blocked RPC).
    import threading

    watchdog = {"deadline": None, "entry": ""}
    # Serializes detail/flush between the main thread and the watchdog:
    # without it, an entry finishing right at its budget races run()'s
    # finally-flush against the salvage flush on the same .partial file
    # (interleaved json.dump = corrupt file, plus an abort of a run
    # that had just recovered).
    wd_lock = threading.Lock()

    def _watch():
        while True:
            time.sleep(10)
            d = watchdog["deadline"]
            if d is not None and time.time() > d:
                with wd_lock:
                    # Re-check under the lock: the entry may have
                    # completed (and disarmed) while we acquired it.
                    d = watchdog["deadline"]
                    if d is None or time.time() <= d:
                        continue
                    detail.append({
                        "metric": watchdog["entry"],
                        "error": "entry exceeded its watchdog budget "
                                 "(tunnel died mid-run?); run aborted, "
                                 "partial capture salvaged",
                    })
                    flush()
                    with open(
                        os.path.join(HERE, "BENCH_ABORTED.json"), "w"
                    ) as f:
                        json.dump(with_meta(detail), f, indent=1)
                    line = _headline_line()
                    line["error"] = (
                        f"aborted: {watchdog['entry']} hung past its "
                        "budget; captured entries in BENCH_ABORTED.json"
                    )
                    print(json.dumps(line), flush=True)
                    os._exit(2)

    threading.Thread(target=_watch, daemon=True).start()

    def run(fn, *a, budget=ENTRY_BUDGET_S, label=None, **k):
        t0 = time.perf_counter()
        name = label or fn.__name__
        # Arm under the same lock the watchdog checks/disarms under —
        # one protocol for all three transitions.
        with wd_lock:
            watchdog["entry"] = name
            watchdog["deadline"] = time.time() + budget
        r, entries = None, None
        try:
            r = fn(*a, **k)
            entries = r if isinstance(r, list) else [r]
        except Exception as e:  # record, never break the headline line
            entries = [{"metric": name, "error": repr(e)}]
        except BaseException as e:
            # KeyboardInterrupt / SystemExit: still persist an error
            # record for this entry, then re-raise (the finally below
            # flushes whatever the run has).
            entries = [{"metric": name, "error": repr(e)}]
            raise
        finally:
            with wd_lock:
                watchdog["deadline"] = None
                if entries is not None:
                    detail.extend(entries)
                flush()
            print(
                f"[bench] {name}: "
                f"{time.perf_counter() - t0:.1f}s",
                file=sys.stderr,
            )
        return r

    # The CPU baseline is seconds of pure-native work and supplies the
    # headline's vs_baseline — run it first so ANY later salvage (the
    # watchdog os._exit path never returns to this function) still
    # carries the ratio.  Then the chip-decisive entries: tunnel windows
    # can be minutes long (round-4 lesson), so the armed decision runs
    # as a small CORE A/B first (5 variants), the headline next, and
    # the block-shape tuning ladder after — a short window decides even
    # if it dies before the ladder.  In SMOKE the pallas variants run
    # INTERPRETED at minutes per sweep, so the multi-variant entries
    # get subprocess-tier budgets either way.
    run(bench_cpu_baseline)
    ab = run(
        bench_pivot_tile_batch, CORE_VARIANTS, "pivot_core_ab",
        # On chip the 5-variant core is minutes and the tight budget
        # salvages dead-tunnel windows fast; in SMOKE the two pallas
        # variants run INTERPRETED at minutes per sweep and need the
        # subprocess-tier budget.
        budget=3600.0 if SMOKE else 1800.0, label="pivot_core_ab",
    )
    run(bench_lut5_device, G_HEAD)

    def _winning_cfg(entry):
        # The armed decision applies ON CHIP ONLY: in SMOKE the A/B runs
        # on CPU (interpreted pallas, opposite lever signs — the
        # round-4 lesson), and promoting a CPU winner onto the
        # driver-facing per-chip headline would be exactly the
        # CPU-sign-driven decision the per-backend defaults exist to
        # prevent.  On-chip t1 (tile_batch=1, pipeline off) IS the
        # production default, so "beats t1" = "beats production".
        if SMOKE:
            return None, 0.0, 0.0
        e = entry or {}
        cfg, t1 = e.get("best_config"), e.get("t1")
        if cfg and e.get("best_variant") != "t1" and (
            t1 is None or e["best"] > t1
        ):
            # Third element: the winner's t1-normalized ratio (entry best
            # / entry t1).  Each entry re-measures t1 in its own window
            # precisely because throttle drift between windows skews raw
            # cand/s; cross-entry promotion decisions must compare these
            # ratios, not raw rates (ADVICE round 5).  0.0 when the entry
            # has no t1 baseline — such a winner never supersedes one
            # measured against its own baseline.
            return cfg, e["best"], (e["best"] / t1 if t1 else 0.0)
        return None, 0.0, 0.0

    cfg, _cfg_rate, cfg_ratio = _winning_cfg(ab)
    if cfg:
        # The armed decision rule's capture half: a variant beat plain,
        # so record the headline sweep under the winning config in the
        # same window (the default flip itself is a reviewed code
        # change; this preserves the evidence even if the tunnel dies).
        run(bench_lut5_device, G_HEAD, cfg)
    lad = run(
        bench_pivot_tile_batch, LADDER_VARIANTS, "pivot_block_ladder",
        budget=3600.0, label="pivot_block_ladder",
    )
    lcfg, _lrate, lratio = _winning_cfg(lad)
    # t1-normalized promotion: the ladder ran in a different window than
    # the core A/B, so raw cand/s across the two entries is throttle-
    # drift-contaminated; compare each winner against its own window's
    # t1 baseline instead (ADVICE round 5).
    if lcfg and lratio > cfg_ratio and lcfg != cfg:
        run(bench_lut5_device, G_HEAD, lcfg)
    run(bench_lut5_g500_slice)
    run(bench_host_stream_pipeline)
    run(bench_gate_mode_sweeps)
    run(bench_lut7)
    best = None

    def des_s1_bit0_lut():
        # run()-compatible wrapper: captures the best circuit for the
        # pallas-exec bench while routing through the one watchdog/flush
        # protocol.
        nonlocal best
        entry, best = bench_des_s1_lut()
        return entry

    run(des_s1_bit0_lut)
    run(bench_des_s1_sat_not)
    run(bench_des_s1_full_graph)
    run(bench_des_s1_outputs_batched)
    run(bench_lut7_break_even)
    run(bench_lut7_capped_search)
    run(bench_engine_pivot_ab, budget=1800.0)
    run(bench_engine_mux_threads)
    run(bench_batch_axis_pivot)
    run(bench_multibox_des)
    run(bench_permute_sweep)
    run(bench_pallas_exec, best)
    run(bench_pallas_deep)
    if not SMOKE:
        # Already-validated CPU-subprocess entries (~30 min); the smoke
        # run's job is the chip-path code above.
        run(bench_mesh_scaling, budget=3600.0)
        run(bench_gather_compaction, budget=1800.0)
    flush(final=True)

    print(json.dumps(_headline_line()))


if __name__ == "__main__":
    main()
