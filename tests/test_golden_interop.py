"""Golden interop tests against the REFERENCE's own serialization code.

Round-1 verdict item 4: "byte-exact XML/fingerprint interop" was a claim
without a test.  Here the reference's state.c (truncated above its
libxml-dependent loader, so no external deps) is compiled at test time
into a shared object straight from /root/reference — never copied into the
repo — and every assertion compares our Python implementation against the
reference binary code itself:

- ``state_fingerprint``  == reference ``state_fingerprint`` (state.c:68-105)
- ``state_filename``     == the file name reference ``save_state`` creates
- ``state_to_xml``       == the bytes reference ``save_state`` writes
- our ``state_from_xml`` loads reference-written files (resume interop)
"""

import ctypes
import os
import struct
import subprocess

import numpy as np
import pytest

from sboxgates_tpu.core import boolfunc as bf
from sboxgates_tpu.graph.state import GATES, MAX_GATES, NO_GATE, State
from sboxgates_tpu.graph import xmlio

REFERENCE = "/root/reference"
HERE = os.path.dirname(os.path.abspath(__file__))

GATE_BYTES = 64          # sizeof(gate): 32B table + fields, 32B-aligned
STATE_HEADER_BYTES = 32  # ints + counts + outputs, padded to gate alignment


@pytest.fixture(scope="module")
def golden(tmp_path_factory):
    """Builds the reference serialization code into golden.so."""
    src = os.path.join(REFERENCE, "state.c")
    if not os.path.exists(src):
        pytest.skip("reference tree not available")
    tmp = tmp_path_factory.mktemp("golden")
    text = open(src).read()
    cut = text.index("#define LOAD_STATE_RETURN_ON_ERROR")
    (tmp / "state_trunc.c").write_text(text[:cut])
    # Empty stubs satisfy state.c's unconditional libxml includes; nothing
    # in the truncated TU uses libxml symbols.
    (tmp / "libxml").mkdir()
    (tmp / "libxml" / "parser.h").write_text("")
    (tmp / "libxml" / "tree.h").write_text("")
    so = tmp / "golden.so"
    subprocess.run(
        [
            "gcc", "-O2", "-fPIC", "-shared",
            "-I", str(tmp), "-I", REFERENCE,
            "-o", str(so), os.path.join(HERE, "golden_shim.c"),
        ],
        check=True,
        capture_output=True,
    )
    lib = ctypes.CDLL(str(so))
    lib.golden_fingerprint.restype = ctypes.c_uint32
    lib.golden_fingerprint.argtypes = [ctypes.c_char_p]
    lib.golden_save.argtypes = [ctypes.c_char_p]
    lib.golden_sat_metric.restype = ctypes.c_int
    lib.golden_sizeof_state.restype = ctypes.c_uint64
    lib.golden_sizeof_gate.restype = ctypes.c_uint64
    assert lib.golden_sizeof_gate() == GATE_BYTES
    assert (
        lib.golden_sizeof_state()
        == STATE_HEADER_BYTES + GATE_BYTES * MAX_GATES
    )
    return lib, tmp


def pack_c_state(st: State) -> bytes:
    """Marshals a State into the reference's in-memory struct layout."""
    parts = [
        struct.pack(
            "<iiHH8H4x",
            st.max_sat_metric if st.max_sat_metric < 2**31 else 2**31 - 1,
            st.sat_metric,
            st.max_gates & 0xFFFF,
            st.num_gates & 0xFFFF,
            *[o & 0xFFFF for o in st.outputs],
        )
    ]
    for i, g in enumerate(st.gates):
        parts.append(st.tables[i].astype("<u4").tobytes())
        parts.append(
            struct.pack(
                "<iHHHB21x",
                g.type,
                g.in1 & 0xFFFF,
                g.in2 & 0xFFFF,
                g.in3 & 0xFFFF,
                g.function & 0xFF,
            )
        )
    parts.append(b"\x00" * (GATE_BYTES * (MAX_GATES - st.num_gates)))
    data = b"".join(parts)
    assert len(data) == STATE_HEADER_BYTES + GATE_BYTES * MAX_GATES
    return data


def _example_states():
    """A spread of states: searched gate circuit, LUT circuit, randomized
    XOR layers with various output maps."""
    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.search import (
        Options,
        SearchContext,
        generate_graph_one_output,
        make_targets,
    )
    from sboxgates_tpu.utils.sbox import load_sbox

    out = []
    sbox, n = load_sbox(os.path.join(HERE, "data", "crypto1_fa.txt"))
    targets = make_targets(sbox)
    for kw in ({}, {"lut_graph": True}):
        ctx = SearchContext(Options(seed=3, **kw))
        st = State.init_inputs(n)
        res = generate_graph_one_output(
            ctx, st, targets, 0, save_dir=None, log=lambda s: None
        )
        assert res
        out.append(res[-1])

    rng = np.random.default_rng(7)
    for gcount, outputs in ((9, [8]), (14, [13, 12, 10])):
        st = State.init_inputs(8)
        while st.num_gates < gcount:
            a, b = rng.choice(st.num_gates, size=2, replace=False)
            st.add_gate(bf.XOR, int(a), int(b), GATES)
        for bit, gid in enumerate(outputs):
            st.outputs[bit] = gid
        out.append(st)
    return out


def test_fingerprint_matches_reference(golden):
    lib, _ = golden
    for st in _example_states():
        ours = xmlio.state_fingerprint(st)
        ref = lib.golden_fingerprint(pack_c_state(st))
        assert ours == ref, (
            f"fingerprint mismatch: ours {ours:08x} != reference {ref:08x}"
        )


def test_save_matches_reference(golden, tmp_path):
    """Reference save_state and ours produce the identical filename and
    identical file bytes."""
    lib, _ = golden
    for i, st in enumerate(_example_states()):
        d = tmp_path / str(i)
        d.mkdir()
        cwd = os.getcwd()
        os.chdir(d)
        try:
            lib.golden_save(pack_c_state(st))
        finally:
            os.chdir(cwd)
        produced = os.listdir(d)
        assert len(produced) == 1
        assert produced[0] == xmlio.state_filename(st)
        ref_bytes = (d / produced[0]).read_text()
        assert ref_bytes == xmlio.state_to_xml(st)


def test_load_reference_written_state(golden, tmp_path):
    """Resume interop: our loader reconstructs a reference-written file
    (tables recomputed, not stored — state.c:338-356)."""
    lib, _ = golden
    st = _example_states()[0]
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        lib.golden_save(pack_c_state(st))
    finally:
        os.chdir(cwd)
    (name,) = os.listdir(tmp_path)
    loaded = xmlio.load_state(str(tmp_path / name))
    assert loaded.num_gates == st.num_gates
    assert loaded.outputs == st.outputs
    assert np.array_equal(loaded.live_tables(), st.live_tables())
    assert xmlio.state_fingerprint(loaded) == xmlio.state_fingerprint(st)


def test_sat_metric_matches_reference(golden):
    lib, _ = golden
    from sboxgates_tpu.graph.state import SAT_METRIC

    for gtype, weight in SAT_METRIC.items():
        if gtype == bf.IN:
            continue  # reference asserts on IN; ours returns 0
        assert lib.golden_sat_metric(gtype) == weight
