"""CLI contract tests — the reference's CI matrix (.travis.yml:26-51)
translated to in-process invocations of sboxgates_tpu.cli.main."""

import os
import subprocess
import tempfile

import numpy as np
import pytest

from sboxgates_tpu.cli import main
from sboxgates_tpu.core import ttable as tt
from sboxgates_tpu.graph.state import NO_GATE
from sboxgates_tpu.graph.xmlio import load_state
from sboxgates_tpu.utils.sbox import load_sbox

DATA = os.path.join(os.path.dirname(__file__), "data")
DES = os.path.join(DATA, "des_s1.txt")
FA = os.path.join(DATA, "crypto1_fa.txt")


# -- negative/validation contract (.travis.yml:27-39) ---------------------


@pytest.mark.parametrize(
    "argv",
    [
        [],                         # missing input
        ["-a", "-123", DES],        # bad -a
        ["-a", "65536", DES],
        ["-i", "0", DES],
        ["-i", "-123", DES],
        ["-o", "-123", DES],
        ["-o", "8", DES],
        ["-p", "-123", DES],
        ["-p", "256", DES],
        ["-c", "-d", "test.xml"],   # exclusive
        ["-l", "-s", DES],          # exclusive
        ["nonexisting.txt"],
        ["-o", "7", DES],           # DES S1 has only 4 outputs
    ],
)
def test_invalid_invocations_fail(argv):
    assert main(argv) != 0


def test_bad_candidate_order_is_one_line_error(capsys):
    """An unknown --candidate-order value is rejected before any engine
    work with a one-line error naming the value — never a traceback,
    never a silently-lexicographic run under a typo'd 'spectral'."""
    capsys.readouterr()
    rc = main(["--candidate-order", "spectrall", DES])
    assert rc != 0
    err = capsys.readouterr().err
    assert "spectrall" in err
    assert err.strip().count("\n") == 0
    assert "Traceback" not in err


def test_truncated_graph_file_is_one_line_error(tmp_path, capsys):
    """-g on a truncated or corrupt XML state exits nonzero with a
    one-line error naming the file and the parse failure — never a
    traceback."""
    d = str(tmp_path)
    files = _run_search(d, ["-i", "1", "-o", "0", "--seed", "5", FA])
    good = os.path.join(d, files[0])
    bad = os.path.join(d, "truncated.xml")
    with open(good) as src, open(bad, "w") as dst:
        dst.write(src.read()[:60])
    capsys.readouterr()  # drop the search output
    rc = main(["-g", bad, FA, "--output-dir", d])
    assert rc != 0
    err = capsys.readouterr().err
    assert bad in err
    assert err.strip().count("\n") == 0  # exactly one line
    assert "Traceback" not in err
    # Digest-verified corruption reports the same way.
    body = open(good).read()
    with open(bad, "w") as dst:
        dst.write(body.replace('type="IN"', 'type="NO"', 1))
    rc = main(["-g", bad, FA, "--output-dir", d])
    assert rc != 0
    err = capsys.readouterr().err
    assert bad in err and "Traceback" not in err
    # The -c/-d conversion path names the file too.
    rc = main(["-d", bad])
    assert rc != 0
    err = capsys.readouterr().err
    assert bad in err and "Traceback" not in err


def test_resume_run_shard_sweep_validation(tmp_path, capsys):
    """--resume-run + --shard-sweep is no longer rejected outright
    (job-sharded sweeps journal per shard and resume); validation now
    covers the per-job layout: a missing journal is a one-line error,
    an explicit --shard-sweep contradicting a non-sharded journal is a
    one-line error, and a sharded journal records its process count and
    rejects a resume with a different one."""
    rc = main(["--resume-run", "/tmp/does-not-exist", "--shard-sweep"])
    assert rc != 0
    err = capsys.readouterr().err
    assert "no resumable journal" in err
    assert err.strip().count("\n") == 0
    assert "Traceback" not in err

    # A sharded run records shard_processes; resuming under a different
    # process count is rejected (slice assignment is round-robin by
    # rank, so the shards would not line up).
    import json

    d = str(tmp_path)
    rc = main([FA, "--permute-sweep", "--shard-sweep", "-o", "0", "-l",
               "--seed", "3", "--output-dir", d])
    assert rc == 0
    jpath = os.path.join(d, "search.journal.jsonl")
    recs = [json.loads(line) for line in open(jpath)]
    assert recs[0]["config"]["shard_sweep"] is True
    assert recs[0]["config"]["shard_processes"] == 1
    assert os.path.isdir(os.path.join(d, "shard-00"))
    recs[0]["config"]["shard_processes"] = 4
    with open(jpath, "w") as f:
        f.writelines(json.dumps(r) + "\n" for r in recs)
    os.unlink(os.path.join(d, "search.journal.json"))  # stale snapshot
    capsys.readouterr()
    rc = main(["--resume-run", d])
    assert rc != 0
    err = capsys.readouterr().err
    assert "4-process" in err and "process count" in err
    assert err.strip().count("\n") == 0
    assert "Traceback" not in err


def test_resume_journal_without_new_fleet_keys(tmp_path, capsys):
    """A version-2 journal written before the fleet shaping keys
    existed resumes with their defaults (1 candidate shard, 256-job
    waves — the values every earlier build effectively ran with, so the
    draw stream replays bit-identically) instead of being rejected as
    an incompatible build."""
    import json

    d = str(tmp_path)
    rc = main([FA, "-i", "1", "-o", "0", "-l", "--seed", "3",
               "--output-dir", d])
    assert rc == 0
    jpath = os.path.join(d, "search.journal.jsonl")
    recs = [json.loads(line) for line in open(jpath)]
    for key in ("fleet_candidates", "fleet_max_wave"):
        assert key in recs[0]["config"]
        del recs[0]["config"][key]
    with open(jpath, "w") as f:
        f.writelines(json.dumps(r) + "\n" for r in recs)
    os.unlink(os.path.join(d, "search.journal.json"))  # stale snapshot
    capsys.readouterr()
    rc = main(["--resume-run", d])
    assert rc == 0
    out = capsys.readouterr()
    assert "incompatible build" not in out.err
    assert "nothing to resume" in out.out


def test_fleet_incoherent_flag_combos_rejected(tmp_path, monkeypatch,
                                               capsys):
    """--fleet contradicts --serial-jobs (nothing to merge) and --mesh
    (the fleet builds its own 2-D mesh); the fleet shaping values are
    validated: each is a one-line error, no traceback, and NO journal
    files (the device plans validate before the journal is created — a
    run that never started must not leave a journal recording it).
    (--fleet --shard-sweep, rejected before PR 8, now COMPOSES: one
    local fleet per process — covered by
    test_cli_fleet_shard_sweep_composes.)"""
    monkeypatch.chdir(tmp_path)
    for argv in (
        ["--fleet", "--serial-jobs", DES, FA],
        ["--fleet", "--mesh", DES, FA],
        ["--fleet", "--fleet-candidates", "0", DES, FA],
        ["--fleet", "--fleet-max-wave", "0", DES, FA],
        ["--fleet", "--fleet-candidates", "3", DES, FA],
    ):
        rc = main(argv)
        assert rc != 0, argv
        err = capsys.readouterr().err
        assert err.strip().count("\n") == 0, argv  # exactly one line
        assert "Traceback" not in err
        assert not list(tmp_path.glob("search.journal.*")), argv


def test_cli_fleet_end_to_end(tmp_path, monkeypatch):
    """--fleet runs a 2-box sweep through the fleet dispatcher and
    writes per-box state files like the serial driver."""
    monkeypatch.chdir(tmp_path)
    rc = main(["--fleet", "-o", "0", "-i", "1", "-l", "--seed", "2",
               "--output-dir", str(tmp_path), DES, FA])
    assert rc == 0
    assert list((tmp_path / "des_s1").glob("*.xml"))
    assert list((tmp_path / "crypto1_fa").glob("*.xml"))


def test_cli_fleet_shard_sweep_composes(tmp_path, monkeypatch, capsys):
    """--fleet --shard-sweep (single process) runs the slice as a local
    fleet: the sweep completes, the journal records both flags plus the
    fleet shaping keys (wave size / candidate split are draw-stream
    shaping, so --resume-run must restore them)."""
    monkeypatch.chdir(tmp_path)
    rc = main(["--fleet", "--shard-sweep", "-o", "0", "-i", "1", "-l",
               "--seed", "2", "--fleet-max-wave", "8",
               "--output-dir", str(tmp_path), DES, FA])
    import json

    assert rc == 0, capsys.readouterr().err
    assert list((tmp_path / "des_s1").glob("*.xml"))
    assert list((tmp_path / "crypto1_fa").glob("*.xml"))
    recs = [
        json.loads(line)
        for line in open(tmp_path / "search.journal.jsonl")
    ]
    cfg = recs[0]["config"]
    assert cfg["fleet"] is True and cfg["shard_sweep"] is True
    assert cfg["fleet_max_wave"] == 8
    assert cfg["fleet_candidates"] == 1


def test_serve_incoherent_flag_combos_rejected(tmp_path, monkeypatch,
                                               capsys):
    """--serve owns scheduling: the conflicting mode flags, a missing
    --output-dir, and bad policy values are each a one-line error with
    no traceback and no stranded journal files."""
    monkeypatch.chdir(tmp_path)
    d = str(tmp_path / "out")
    for argv in (
        ["--serve", DES],                                # no output dir
        ["--serve", "--fleet", DES, "--output-dir", d],
        ["--serve", "--mesh", DES, "--output-dir", d],
        ["--serve", "--shard-sweep", DES, FA, "--output-dir", d],
        ["--serve", "--batch-iterations", DES, "--output-dir", d],
        ["--serve", "--permute-sweep", DES, "--output-dir", d],
        ["--serve", "--serial-jobs", DES, "--output-dir", d],
        ["--serve", "--serve-lanes", "0", DES, "--output-dir", d],
        ["--serve", "--serve-retries", "-1", DES, "--output-dir", d],
        ["--serve", "--serve-timeout", "0", DES, "--output-dir", d],
        ["--serve", "--resume-run", d],
        ["--serve", "--coordinator", "x:1", DES, "--output-dir", d],
        ["--serve-no-merge", DES],                       # needs --serve
        ["--chain-rounds", "-1", DES],
        ["--chain-rounds", "4", DES],                    # needs -l
        ["-l", "--chain-rounds", "4", "-i", "2", DES],   # needs -i 1
        ["-l", "--chain-rounds", "4", "-o", "0", DES],   # all-outputs only
    ):
        rc = main(argv)
        assert rc != 0, argv
        err = capsys.readouterr().err
        assert err.strip().count("\n") == 0, (argv, err)
        assert "Traceback" not in err
        assert not list(tmp_path.glob("search.journal.*")), argv


def test_cli_serve_end_to_end_and_resume_rejected(tmp_path, capsys):
    """--serve runs each input as one queue job (per-job journals and
    artifacts under DIR/<job-id>/), records the serve keys in the run
    journal, and a later --resume-run DIR is a one-line error naming
    the per-job resume path."""
    import json

    d = str(tmp_path)
    rc = main([DES, FA, "-o", "0", "--serve", "--serve-lanes", "2",
               "--seed", "5", "--output-dir", d])
    assert rc == 0, capsys.readouterr().err
    for jdir in ("job00-des_s1", "job01-crypto1_fa"):
        names = os.listdir(os.path.join(d, jdir))
        assert "search.journal.jsonl" in names
        assert "metrics.json" in names
        assert any(n.endswith(".xml") for n in names), names
    recs = [
        json.loads(line)
        for line in open(os.path.join(d, "search.journal.jsonl"))
    ]
    cfg = recs[0]["config"]
    assert cfg["serve"] is True
    assert cfg["serve_lanes"] == 2
    assert cfg["serve_retries"] == 2
    assert cfg["serve_timeout"] is None
    assert recs[-1]["type"] == "run_done"
    capsys.readouterr()
    rc = main(["--resume-run", d])
    assert rc != 0
    err = capsys.readouterr().err
    assert "serve" in err and "--resume-run" in err
    assert err.strip().count("\n") == 0
    assert "Traceback" not in err


def test_resume_journal_without_serve_keys(tmp_path, capsys):
    """A version-2 journal written before the serve keys existed
    resumes with their defaults (serve off — the value every earlier
    build effectively ran with) instead of being rejected as an
    incompatible build."""
    import json

    d = str(tmp_path)
    rc = main([FA, "-i", "1", "-o", "0", "-l", "--seed", "3",
               "--output-dir", d])
    assert rc == 0
    jpath = os.path.join(d, "search.journal.jsonl")
    recs = [json.loads(line) for line in open(jpath)]
    for key in ("serve", "serve_lanes", "serve_retries",
                "serve_timeout"):
        assert key in recs[0]["config"]
        del recs[0]["config"][key]
    with open(jpath, "w") as f:
        f.writelines(json.dumps(r) + "\n" for r in recs)
    os.unlink(os.path.join(d, "search.journal.json"))  # stale snapshot
    capsys.readouterr()
    rc = main(["--resume-run", d])
    assert rc == 0
    out = capsys.readouterr()
    assert "incompatible build" not in out.err
    assert "nothing to resume" in out.out


def test_result_store_validation_one_line_errors(tmp_path, capsys):
    """--result-store contract: rejected on -c/-d conversion, and a
    serve run needs the explicit --output-dir (the specific error names
    the store) — each a one-line error, no traceback, no stranded
    journal files."""
    import shutil

    monkey_dir = str(tmp_path / "store")
    d = str(tmp_path)
    files = _run_search(d, ["-i", "1", "-o", "0", "--seed", "5", FA])
    xml = os.path.join(d, files[0])
    capsys.readouterr()
    for argv in (
        ["-c", xml, "--result-store", monkey_dir],
        ["-d", xml, "--result-store", monkey_dir],
        ["--serve", DES, "--result-store", monkey_dir],
    ):
        rc = main(argv)
        assert rc != 0, argv
        err = capsys.readouterr().err
        assert err.strip().count("\n") == 0, (argv, err)
        assert "Traceback" not in err
        assert "result-store" in err or "result store" in err, err
    shutil.rmtree(monkey_dir, ignore_errors=True)


def test_cli_result_store_publish_then_serve_hit(tmp_path, capsys):
    """End-to-end through the CLI: a plain search with --result-store
    publishes its circuit; a serve run against the same store answers
    the repeat query from it (store=hit journal, store keys journaled,
    SBG_RESULT_STORE env default honored)."""
    import json

    store = str(tmp_path / "store")
    d1 = str(tmp_path / "r1")
    rc = main([FA, "-i", "1", "-o", "0", "--seed", "5",
               "--output-dir", d1, "--result-store", store])
    assert rc == 0, capsys.readouterr().err
    assert os.path.isdir(os.path.join(store, "objects"))
    recs = [json.loads(line) for line in
            open(os.path.join(d1, "search.journal.jsonl"))]
    assert recs[0]["config"]["result_store"] == store
    d2 = str(tmp_path / "r2")
    rc = main([FA, "-o", "0", "--serve", "--seed", "5",
               "--output-dir", d2, "--result-store", store])
    assert rc == 0, capsys.readouterr().err
    jdir = os.path.join(d2, "job00-crypto1_fa")
    jrecs = [json.loads(line) for line in
             open(os.path.join(jdir, "search.journal.jsonl"))]
    assert jrecs[0]["config"]["store"] == "hit"
    assert any(n.endswith(".xml") for n in os.listdir(jdir))


def test_resume_journal_without_result_store_key(tmp_path, capsys):
    """A version-2 journal written before the result_store key existed
    resumes with its default (no store — the value every earlier build
    effectively ran with) instead of being rejected."""
    import json

    d = str(tmp_path)
    rc = main([FA, "-i", "1", "-o", "0", "-l", "--seed", "3",
               "--output-dir", d])
    assert rc == 0
    jpath = os.path.join(d, "search.journal.jsonl")
    recs = [json.loads(line) for line in open(jpath)]
    assert "result_store" in recs[0]["config"]
    del recs[0]["config"]["result_store"]
    with open(jpath, "w") as f:
        f.writelines(json.dumps(r) + "\n" for r in recs)
    os.unlink(os.path.join(d, "search.journal.json"))  # stale snapshot
    capsys.readouterr()
    rc = main(["--resume-run", d])
    assert rc == 0
    out = capsys.readouterr()
    assert "incompatible build" not in out.err
    assert "nothing to resume" in out.out


def test_help_exits_zero():
    with pytest.raises(SystemExit) as e:
        main(["--help"])
    assert e.value.code == 0


# -- functional runs (.travis.yml:40-50 analogues) ------------------------


def _run_search(tmp, argv):
    rc = main(argv + ["--output-dir", tmp])
    assert rc == 0
    return [f for f in sorted(os.listdir(tmp)) if f.endswith(".xml")]


def test_single_output_sat_not_search():
    """mpirun -N 4 ... -i 1 -o 0 -s -n des_s1 analogue."""
    with tempfile.TemporaryDirectory() as tmp:
        files = _run_search(
            tmp, ["-i", "1", "-o", "0", "-s", "-n", "--seed", "5", DES]
        )
        assert files
        st = load_state(os.path.join(tmp, files[0]))
        sbox, n = load_sbox(DES)
        gid = st.outputs[0]
        assert gid != NO_GATE
        assert bool(
            tt.eq_mask(st.table(gid), tt.target_table(sbox, 0), tt.mask_table(n))
        )


def test_resume_from_graph():
    """Resume a saved single-output state (-g) and search another output."""
    with tempfile.TemporaryDirectory() as tmp:
        files = _run_search(tmp, ["-i", "1", "-o", "0", "--seed", "5", FA])
        resume = os.path.join(tmp, files[-1])
        rc = main(
            ["-i", "1", "-o", "0", "--seed", "6", "-g", resume, FA,
             "--output-dir", tmp]
        )
        assert rc == 0


def test_full_graph_restricted_gates_permute():
    """-a 10694 -p 63 analogue on the small 4-input box (permute 15)."""
    with tempfile.TemporaryDirectory() as tmp:
        files = _run_search(
            tmp, ["-a", "10694", "-i", "1", "-p", "15", "--seed", "4", FA]
        )
        assert files
        st = load_state(os.path.join(tmp, files[-1]))
        sbox, n = load_sbox(FA, permute=15)
        for bit in range(8):
            if st.outputs[bit] != NO_GATE:
                assert bool(
                    tt.eq_mask(
                        st.table(st.outputs[bit]),
                        tt.target_table(sbox, bit),
                        tt.mask_table(n),
                    )
                )


def test_lut_search_and_convert_roundtrip():
    """-l -o 0 search, then -d (DOT) and -c (CUDA) conversion of the result."""
    import io
    from contextlib import redirect_stdout

    with tempfile.TemporaryDirectory() as tmp:
        files = _run_search(tmp, ["-l", "-o", "0", "--seed", "7", FA])
        xml = os.path.join(tmp, files[-1])

        buf = io.StringIO()
        with redirect_stdout(buf):
            assert main(["-d", xml]) == 0
        assert buf.getvalue().startswith("digraph sbox {")

        buf = io.StringIO()
        with redirect_stdout(buf):
            assert main(["-c", xml]) == 0
        out = buf.getvalue()
        assert "lop3.b32" in out or "typedef unsigned long long int" in out


def test_cli_subprocess_help():
    """python -m sboxgates_tpu --help exits 0 (the smoke test)."""
    r = subprocess.run(
        ["python", "-m", "sboxgates_tpu", "--help"],
        capture_output=True,
        text=True,
        cwd="/root/repo",
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0
    assert "sboxgates" in r.stdout


# -- platform pin + device probe + compile cache (ISSUE 5) ----------------


def test_cli_unreachable_platform_is_one_line_error():
    """With no reachable device platform the CLI exits nonzero with a
    one-line error instead of hanging in backend init (the round-5
    VERDICT tunnel-down hang)."""
    env = {**os.environ, "JAX_PLATFORMS": "bogus_tunnel",
           "SBG_DEVICE_PROBE_TIMEOUT_S": "30"}
    r = subprocess.run(
        ["python", "-m", "sboxgates_tpu", DES, "-o", "0", "-l"],
        capture_output=True, text=True, cwd="/root/repo", env=env,
        timeout=120,
    )
    assert r.returncode == 1
    assert "Error: device platform initialization failed" in r.stderr
    assert len(r.stderr.strip().splitlines()) == 1
    assert "Traceback" not in r.stderr


def test_cli_poisoned_plugin_env_reaches_validation(tmp_path):
    """A sitecustomize that re-forces the platform at interpreter start
    (the accelerator tunnel's registration hook) must not defeat
    JAX_PLATFORMS=cpu: the CLI's env+config double pin restores the
    requested platform and the run proceeds through backend init to
    argument validation instead of hanging."""
    (tmp_path / "sitecustomize.py").write_text(
        "import jax\n"
        "jax.config.update('jax_platforms', 'bogus_tunnel')\n"
    )
    pypath = f"{tmp_path}:/root/repo"
    if os.environ.get("PYTHONPATH"):
        pypath += ":" + os.environ["PYTHONPATH"]
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": pypath,
           "SBG_WARMUP": "0"}
    # -o 7 passes flag validation and is rejected only AFTER backend
    # init + S-box load — reaching that error proves the pin carried
    # the process through the probe.
    r = subprocess.run(
        ["python", "-m", "sboxgates_tpu", DES, "-o", "7"],
        capture_output=True, text=True, cwd="/root/repo", env=env,
        timeout=120,
    )
    assert r.returncode == 1
    assert "only has 4 outputs" in r.stderr


def test_cli_compile_cache_under_explicit_output_dir(tmp_path):
    """An explicitly-set --output-dir hosts the default persistent
    compile cache (xla_cache/), so a restarted or resumed run reuses
    every previously built executable."""
    d = str(tmp_path)
    files = _run_search(d, ["-i", "1", "-o", "0", "--seed", "5", FA])
    assert files
    cache = os.path.join(d, "xla_cache")
    assert os.path.isdir(cache)


def test_cli_no_warmup_and_explicit_compile_cache(tmp_path):
    """--no-warmup and --compile-cache DIR are honored; an empty
    --compile-cache disables the default."""
    d = str(tmp_path)
    cache = os.path.join(d, "elsewhere")
    rc = main(["-i", "1", "-o", "0", "--seed", "5", "--no-warmup",
               "--compile-cache", cache, FA, "--output-dir", d])
    assert rc == 0
    assert os.path.isdir(cache)
    d2 = os.path.join(d, "run2")
    os.makedirs(d2)
    rc = main(["-i", "1", "-o", "0", "--seed", "5",
               "--compile-cache", "", FA, "--output-dir", d2])
    assert rc == 0
    assert not os.path.isdir(os.path.join(d2, "xla_cache"))


# -- the network admission flags (--serve-port / --serve-token-file) -------


def test_serve_net_flag_rejections_one_line(tmp_path, monkeypatch,
                                            capsys):
    """The admission-server flags fail closed at the CLI: every
    incoherent combination, unusable token file, and unusable port is
    a one-line error BEFORE the engine spins up — never a traceback,
    never an open (unauthenticated) listener."""
    import json
    import socket

    monkeypatch.chdir(tmp_path)
    d = str(tmp_path / "out")
    tok = str(tmp_path / "tokens.json")
    with open(tok, "w") as f:
        json.dump({"version": 1,
                   "tenants": {"a": {"token": "t"}}}, f)
    os.chmod(tok, 0o600)
    # A bound socket makes "port in use" deterministic.
    taken = socket.socket()
    taken.bind(("127.0.0.1", 0))
    busy = str(taken.getsockname()[1])
    bad = str(tmp_path / "nope.json")
    world = str(tmp_path / "world.json")
    with open(world, "w") as f:
        f.write("{}")
    os.chmod(world, 0o666)
    serve = ["--serve", DES, "--output-dir", d]
    try:
        for argv in (
            ["--serve-port", "0", DES],              # needs --serve
            ["--serve-token-file", tok] + serve,     # needs --serve-port
            ["--serve-port", "0"] + serve,           # needs token file
            ["--serve-port", "70000",
             "--serve-token-file", tok] + serve,     # bad port
            ["--serve-port", "0",
             "--serve-token-file", bad] + serve,     # missing file
            ["--serve-port", "0",
             "--serve-token-file", world] + serve,   # world-writable
            ["--serve-port", busy,
             "--serve-token-file", tok] + serve,     # port in use
        ):
            rc = main(argv)
            assert rc != 0, argv
            err = capsys.readouterr().err
            assert err.strip().count("\n") == 0, (argv, err)
            assert "Traceback" not in err
            assert not list(tmp_path.glob("search.journal.*")), argv
    finally:
        taken.close()


def test_resume_journal_without_serve_net_keys(tmp_path, capsys):
    """A run journal written before serve_port/serve_token_file existed
    resumes with their defaults (no admission server) instead of being
    rejected as an incompatible build — the same back-compat contract
    the serve keys themselves got."""
    import json

    d = str(tmp_path)
    rc = main([FA, "-i", "1", "-o", "0", "-l", "--seed", "3",
               "--output-dir", d])
    assert rc == 0
    jpath = os.path.join(d, "search.journal.jsonl")
    recs = [json.loads(line) for line in open(jpath)]
    for key in ("serve_port", "serve_token_file"):
        assert key in recs[0]["config"]
        assert recs[0]["config"][key] is None
        del recs[0]["config"][key]
    with open(jpath, "w") as f:
        f.writelines(json.dumps(r) + "\n" for r in recs)
    os.unlink(os.path.join(d, "search.journal.json"))  # stale snapshot
    capsys.readouterr()
    rc = main(["--resume-run", d])
    assert rc == 0
    out = capsys.readouterr()
    assert "incompatible build" not in out.err
    assert "nothing to resume" in out.out
