"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so multi-chip sharding paths can
be exercised without TPU hardware.  These env vars must be set before jax is
imported anywhere in the test process.
"""

import os

# Force CPU: the environment may register an 'axon' TPU-tunnel backend that
# (a) supports only one client process and (b) programmatically overrides
# JAX_PLATFORMS at interpreter start — so both the env var and the config
# must be pinned before any backend initializes.  Tests never touch the TPU.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Persistent compilation cache: the suite compiles dozens of kernel shapes;
# reruns should pay compile cost once per machine, not per run.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(__file__), "..", ".jax_cache"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")

# Background kernel warmup off by default: the suite builds hundreds of
# small contexts, and each would otherwise schedule an AOT compile of the
# next bucket's whole sweep ladder — background CPU work that slows every
# test and contaminates timing-sensitive ones.  The dedicated warmup tests
# re-enable it per-test (SBG_WARMUP=1 via monkeypatch before the context
# is built).
os.environ.setdefault("SBG_WARMUP", "0")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _restore_compile_cache_dir():
    """A CLI run with an explicit --output-dir points the persistent
    compile cache there (by design); in-process cli.main() tests must not
    leave the rest of the suite caching into deleted tmp directories."""
    old = jax.config.jax_compilation_cache_dir
    yield
    if jax.config.jax_compilation_cache_dir != old:
        jax.config.update("jax_compilation_cache_dir", old)


@pytest.fixture
def rng():
    return np.random.default_rng(12345)


@pytest.fixture
def aes_sbox():
    from sboxgates_tpu.utils.sbox import load_sbox

    sbox, n = load_sbox(os.path.join(os.path.dirname(__file__), "data", "rijndael.txt"))
    assert n == 8
    return sbox


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running search tests")
