"""Spectral best-first candidate-ordering tests (ops/spectral.py +
the tier-segment drivers in search/lut.py).

Three layers, mirroring the feature's contract:

* **Spectral math is exact**: the packed WHT is the real transform
  (involution, naive-matrix parity), gate scores equal the direct
  masked popcount correlation (XLA and Pallas-interpret bit-identical),
  and span scores equal brute-forced XOR-span correlations.
* **Ordering is a partition**: tier segments cover [0, n) exactly once,
  best tier first, deterministically.
* **Ordering never changes results**: lex and spectral sweeps return
  the identical exhaustive hit set, the spectrally-ordered first hit
  verifies, a SIGTERM'd spectral run resumes bit-identical, and the
  ``order.score`` chaos site surfaces scoring faults loudly.
"""

import hashlib
import os

import jax.numpy as jnp
import numpy as np
import pytest

from planted import (
    build_planted_lut5_small,
    build_planted_lut7,
    verify_lut5_result,
)
from sboxgates_tpu.core import boolfunc as bf
from sboxgates_tpu.core import ttable as tt
from sboxgates_tpu.graph.state import GATES, State
from sboxgates_tpu.ops import combinatorics as comb
from sboxgates_tpu.ops import spectral
from sboxgates_tpu.resilience import faults
from sboxgates_tpu.resilience.faults import InjectedFault
from sboxgates_tpu.search import context as sctx
from sboxgates_tpu.search import lut as slut
from sboxgates_tpu.search.context import Options, SearchContext

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DES = os.path.join(ROOT, "tests", "data", "des_s1.txt")


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.disarm()
    yield
    faults.disarm()


@pytest.fixture
def small_chunks(monkeypatch):
    """Shrink the 5-LUT stream chunk so the planted G=24 space
    (C(24,5) = 42504) spans many chunks — the regime where the tier
    drivers actually reorder (a single-chunk sweep is one dispatch and
    correctly stays lexicographic)."""
    monkeypatch.setitem(sctx.STREAM_CHUNK, 5, 1024)


# ---------------------------------------------------------------- math


def test_wht_involution_and_naive_parity():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(-40, 40, size=(3, 256)).astype(np.int32))
    assert (spectral.wht(spectral.wht(x)) == 256 * x).all()
    # Against the naive H[i, j] = (-1)^popcount(i & j) matrix at n=16.
    y = rng.integers(-9, 9, size=16).astype(np.int64)
    idx = np.arange(16)
    H = (-1) ** np.array(
        [[bin(i & j).count("1") for j in idx] for i in idx]
    )
    got = np.asarray(spectral.wht(jnp.asarray(y.astype(np.int32))))
    assert np.array_equal(got, H @ y)


def test_gate_scores_equal_direct_popcount_and_pallas_parity():
    rng = np.random.default_rng(1)
    tables = rng.integers(0, 2**32, size=(64, 8), dtype=np.uint32)
    target = rng.integers(0, 2**32, size=(8,), dtype=np.uint32)
    mask = rng.integers(0, 2**32, size=(8,), dtype=np.uint32)

    def lanes(words):
        return (
            (words[..., :, None] >> np.arange(32, dtype=np.uint32)) & 1
        ).reshape(*words.shape[:-1], -1)

    tb, tg, mk = lanes(tables), lanes(target[None])[0], lanes(mask[None])[0]
    agree = ((tb == tg[None]) & (mk[None] == 1)).sum(-1)
    ref = np.abs(agree - (mk.sum() - agree))
    xla = np.asarray(
        spectral.gate_scores(
            jnp.asarray(tables), jnp.asarray(target), jnp.asarray(mask)
        )
    )
    assert np.array_equal(ref, xla)
    pal = np.asarray(
        spectral.gate_scores(
            jnp.asarray(tables), jnp.asarray(target), jnp.asarray(mask),
            backend="pallas", interpret=True,
        )
    )
    assert np.array_equal(ref, pal)


def test_span_scores_equal_bruteforced_xor_span():
    rng = np.random.default_rng(2)
    tables = rng.integers(0, 2**32, size=(5, 8), dtype=np.uint32)
    target = rng.integers(0, 2**32, size=(8,), dtype=np.uint32)
    mask = rng.integers(0, 2**32, size=(8,), dtype=np.uint32)

    def lanes(words):
        return (
            (words[..., :, None] >> np.arange(32, dtype=np.uint32)) & 1
        ).reshape(*words.shape[:-1], -1).astype(np.int64)

    for k in (2, 3):
        tb = lanes(tables[:k])
        tg, mk = lanes(target[None])[0], lanes(mask[None])[0]
        best = 0
        for S in range(1, 1 << k):
            x = np.zeros(256, dtype=np.int64)
            for i in range(k):
                if S >> i & 1:
                    x ^= tb[i]
            best = max(
                best, abs(int((mk * (1 - 2 * tg) * (1 - 2 * x)).sum()))
            )
        got = np.asarray(
            spectral.span_scores(
                jnp.asarray(tables[:k][:, :, None]),
                jnp.asarray(target), jnp.asarray(mask),
            )
        )
        assert got.shape == (1,) and int(got[0]) == best, k


# ----------------------------------------------------- tier partition


def test_tier_segments_partition_and_order_property():
    rng = np.random.default_rng(3)
    for trial in range(50):
        n = int(rng.integers(1, 40))
        scores = rng.integers(0, 257, size=n)
        segs = comb.tier_segments(scores, n)
        # Exhaustive partition of [0, n): the ordering contract.
        covered = sorted((lo, hi) for lo, hi, _ in segs)
        assert covered[0][0] == 0 and covered[-1][1] == n
        assert all(
            covered[i][1] == covered[i + 1][0]
            for i in range(len(covered) - 1)
        )
        # Best-first: tier descending, rank ascending within a tier.
        keys = [(-t, lo) for lo, hi, t in segs]
        assert keys == sorted(keys), segs
        # Deterministic: same scores, same segments.
        assert segs == comb.tier_segments(scores.copy(), n)


def test_flat_scores_collapse_to_lexicographic():
    segs = comb.tier_segments(np.full(7, 42), 7)
    assert segs == [(0, 7, 0)]


# ------------------------------------------------ driver equivalence


def _search_planted(order, seed=7):
    st, target, mask = build_planted_lut5_small()
    ctx = SearchContext(Options(seed=seed, candidate_order=order))
    res = slut.lut5_search(ctx, st, target, mask, [])
    return st, target, mask, ctx, res


def test_spectral_first_hit_verifies_and_is_deterministic(small_chunks):
    st, target, mask, ctx, res = _search_planted("spectral")
    assert res is not None and verify_lut5_result(st, target, mask, res)
    assert ctx.stats["order_tier_dispatches"] >= 1
    assert "order_score_s" in ctx.stats.histograms()
    assert ctx.status_state()["candidate_order"] == "spectral"
    # Deterministic across runs: same hit, same dispatch/draw counts.
    _, _, _, ctx2, res2 = _search_planted("spectral")
    assert tuple(res2["gates"]) == tuple(res["gates"])
    assert res2["func_outer"] == res["func_outer"]
    assert res2["func_inner"] == res["func_inner"]
    for key in (
        "lut5_candidates", "order_tier_dispatches", "order_first_hit_tier",
    ):
        assert ctx2.stats[key] == ctx.stats[key], key


def test_lex_and_spectral_exhaust_identically_on_no_hit(small_chunks):
    """Run-to-exhaustion equivalence on the 5-LUT stream: an
    unrealizable target forces both orders through the ENTIRE rank
    space, and the candidate tallies must agree exactly (the segments
    partition the space; nothing is skipped or double-swept)."""
    st, _, mask = build_planted_lut5_small()
    rng = np.random.default_rng(99)
    target = rng.integers(0, 2**32, size=8, dtype=np.uint32)
    counts = {}
    for order in ("lex", "spectral"):
        ctx = SearchContext(Options(seed=7, candidate_order=order))
        assert slut.lut5_search(ctx, st, target, mask, []) is None
        counts[order] = ctx.stats["lut5_candidates"]
    assert counts["lex"] == counts["spectral"] == comb.n_choose_k(24, 5)


def test_lut7_exhaustive_hit_set_identical():
    """The 7-LUT stage-A collector under spectral order returns the
    IDENTICAL hit set as lexicographic order (C(22,7) = 170544 spans
    six stream chunks, so the tier drivers genuinely reorder) — the
    exhaustive-equivalence contract at the one driver that collects
    every hit rather than stopping at the first."""
    st, target, mask = build_planted_lut7(22)
    got = {}
    for order in ("lex", "spectral"):
        ctx = SearchContext(Options(seed=7, candidate_order=order))
        combos, req1, req0 = slut._lut7_collect_hits(
            ctx, st, target, mask, []
        )
        assert 0 < len(combos) < sctx.LUT7_CAP
        rows = {
            (
                tuple(int(x) for x in c),
                np.asarray(a).tobytes(),
                np.asarray(b).tobytes(),
            )
            for c, a, b in zip(combos, req1, req0)
        }
        assert len(rows) == len(combos)
        got[order] = rows
        if order == "spectral":
            assert ctx.stats["order_tier_dispatches"] >= 2
    assert got["lex"] == got["spectral"]


def test_spectral_finds_deep_planted_hit_with_fewer_scans(small_chunks):
    """A target planted on the HIGHEST gates of a mixed-gate state sits
    at the tail of the lexicographic rank space; the spectral tiers
    front-load it (scores differentiate because the nonlinear gates
    correlate unevenly with the target — an all-XOR state scores 0
    everywhere and correctly collapses to lex).  Weak inequality is the
    hard guarantee — scores are a heuristic — but this fixture is
    constructed so the win is strict (5120 lex scans vs 1024)."""
    rng = np.random.default_rng(3)
    st = State.init_inputs(8)
    funs = [bf.AND, bf.OR, bf.XOR, bf.A_AND_NOT_B]
    while st.num_gates < 24:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(funs[rng.integers(len(funs))], int(a), int(b), GATES)
    outer = tt.eval_lut(0x2D, st.table(19), st.table(21), st.table(23))
    target = tt.eval_lut(0xB4, outer, st.table(20), st.table(22))
    mask = tt.mask_table(8)
    scans = {}
    for order in ("lex", "spectral"):
        ctx = SearchContext(Options(seed=7, candidate_order=order))
        res = slut.lut5_search(ctx, st, target, mask, [])
        assert res is not None and verify_lut5_result(st, target, mask, res)
        scans[order] = ctx.stats["lut5_candidates"]
    assert scans["spectral"] < scans["lex"], scans


def test_order_score_chaos_site_surfaces_loudly(small_chunks):
    """Chaos: a fault injected at the scoring dispatch must surface as
    the InjectedFault itself — never a silently-wrong order or a
    half-scored sweep — and the next (disarmed) run completes."""
    st, target, mask = build_planted_lut5_small()
    faults.arm("order.score", "raise", "1")
    try:
        ctx = SearchContext(Options(seed=7, candidate_order="spectral"))
        with pytest.raises(InjectedFault):
            slut.lut5_search(ctx, st, target, mask, [])
    finally:
        faults.disarm()
    ctx = SearchContext(Options(seed=7, candidate_order="spectral"))
    res = slut.lut5_search(ctx, st, target, mask, [])
    assert res is not None and verify_lut5_result(st, target, mask, res)


# ------------------------------------------------------------- resume


def _xml_digests(d):
    return {
        f: hashlib.sha256(
            open(os.path.join(d, f), "rb").read()
        ).hexdigest()
        for f in sorted(os.listdir(d))
        if f.endswith(".xml")
    }


def test_spectral_killed_run_resumes_bit_identical(tmp_path, monkeypatch):
    """A spectral LUT-mode search killed during a checkpoint write
    resumes (with candidate_order restored FROM THE JOURNAL) to final
    checkpoints bit-identical to the uninterrupted spectral run — the
    draw-stream-shaping journal registration doing its job."""
    import json

    from sboxgates_tpu.cli import main

    monkeypatch.setitem(sctx.STREAM_CHUNK, 5, 128)
    argv = [DES, "-o", "0", "-i", "2", "--seed", "11", "-l",
            "--candidate-order", "spectral"]
    ok = str(tmp_path / "ok")
    os.makedirs(ok)
    assert main(argv + ["--output-dir", ok]) == 0
    killed = str(tmp_path / "killed")
    os.makedirs(killed)
    faults.arm("ckpt.write", "raise", "1")
    try:
        with pytest.raises(InjectedFault):
            main(argv + ["--output-dir", killed])
    finally:
        faults.disarm()
    doc = json.load(
        open(os.path.join(killed, "search.journal.json"), encoding="utf-8")
    )
    cfg = doc["records"][0]["config"]
    assert cfg["candidate_order"] == "spectral"
    assert main(["--resume-run", killed]) == 0
    assert _xml_digests(killed) == _xml_digests(ok)
