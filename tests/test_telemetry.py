"""Unified telemetry subsystem: tracing, metrics registry, heartbeat,
flight recorder, and the bench meta schema.

Coverage map (ISSUE 9 acceptance criteria):

- Perfetto-export schema: event types, monotonic timestamps, thread
  ids (``test_perfetto_export_schema``).
- Registry parity: every counter a tier-1-shaped run increments is
  declared, named, and typed (``test_registry_parity_*``) — the same
  pattern as the kernel warm-registry parity test.
- Dispatch spans reconcile exactly with the ``device_dispatches``
  counter, on the per-thread path AND through a fleet run
  (``test_dispatch_span_reconciliation*``).
- Telemetry off adds zero extra host syncs
  (``test_trace_adds_zero_host_syncs``).
- Flight recorder under fault injection: dumps produced at
  ``dispatch.sweep`` (in-process hang -> deadline exhaustion) and
  ``ckpt.write`` (subprocess crash), valid JSON, bounded, containing
  the breaching span; the crash also leaves a final heartbeat line.
- Bench writers share one meta block; schema drift is rejected
  (``test_bench_meta_schema``).
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from sboxgates_tpu.telemetry import flight as tflight
from sboxgates_tpu.telemetry import metrics as tmetrics
from sboxgates_tpu.telemetry import trace as ttrace
from sboxgates_tpu.telemetry.heartbeat import Heartbeat

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
SBOXES = os.path.join(REPO, "sboxes")


@pytest.fixture(autouse=True)
def _clean_telemetry_state():
    """Every test starts from a quiet process tracer/recorder and leaves
    it quiet (both are process-global by design)."""
    tr = ttrace.tracer()
    fr = tflight.flight_recorder()
    tr.enabled = False
    tr.reset()
    fr.reset()
    fr.configure(None)
    fr.clear_hooks()
    yield
    tr.enabled = False
    tr.reset()
    fr.reset()
    fr.configure(None)
    fr.clear_hooks()
    ttrace.set_rank(None)


# -------------------------------------------------------------------------
# tracer
# -------------------------------------------------------------------------


def test_tracer_records_spans_across_threads():
    tr = ttrace.tracer()
    tr.enabled = True

    with ttrace.span("dispatch[x]", "dispatch", kernel="x") as sp:
        sp.set(warm="hit")

    def worker():
        with ttrace.span("dispatch[y]", "dispatch", kernel="y"):
            pass

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    evs = tr.events()
    assert len(evs) == 2
    names = {e[0] for e in evs}
    assert names == {"dispatch[x]", "dispatch[y]"}
    tids = {e[4] for e in evs}
    assert len(tids) == 2  # one buffer per thread
    x = next(e for e in evs if e[0] == "dispatch[x]")
    assert x[5] == {"kernel": "x", "warm": "hit"}
    # time-ordered, spans carry durations
    assert evs[0][2] <= evs[1][2]
    assert all(e[3] >= 0 for e in evs)


def test_tracer_disabled_records_nothing_but_flight_ring():
    tr = ttrace.tracer()
    assert not tr.enabled
    with ttrace.span("dispatch[x]", "dispatch"):
        pass
    ttrace.instant("mark", "journal")
    # high-frequency form: no flight, disabled -> shared no-op handle
    h = ttrace.span("phase", "phase", _flight=False)
    assert h is ttrace.trace_null()
    assert tr.events() == []
    ring = tflight.flight_recorder().events()
    assert {e[0] for e in ring} == {"dispatch[x]", "mark"}


def test_perfetto_export_schema(tmp_path):
    """Chrome/Perfetto trace-event contract: metadata + X/i events,
    microsecond timestamps that are monotone non-negative, integer
    thread ids, pid = process rank."""
    ttrace.set_rank(2)
    tr = ttrace.tracer()
    tr.enabled = True
    with ttrace.span("dispatch[k]", "dispatch", kernel="k", g=64):
        time.sleep(0.001)
    ttrace.instant("deadline.breach", "deadline", label="w")
    with ttrace.span("journal[round_done]", "journal"):
        pass
    path = tr.export(str(tmp_path / "trace.json"))
    doc = json.load(open(path))
    evs = doc["traceEvents"]
    assert isinstance(evs, list) and evs
    metas = [e for e in evs if e["ph"] == "M"]
    assert any(e["name"] == "process_name" for e in metas)
    rest = [e for e in evs if e["ph"] != "M"]
    assert {e["ph"] for e in rest} <= {"X", "i"}
    last_ts = -1.0
    for e in rest:
        assert set(e) >= {"name", "cat", "ts", "pid", "tid"}
        assert isinstance(e["tid"], int)
        assert e["pid"] == 2
        assert e["ts"] >= 0.0
        assert e["ts"] >= last_ts  # exported time-ordered
        last_ts = e["ts"]
        if e["ph"] == "X":
            assert e["dur"] >= 0.0
        else:
            assert e["s"] == "t"
    span = next(e for e in rest if e["name"] == "dispatch[k]")
    assert span["args"] == {"kernel": "k", "g": 64}


# -------------------------------------------------------------------------
# metrics registry
# -------------------------------------------------------------------------


def test_registry_increments_are_atomic_across_threads():
    r = tmetrics.context_registry()

    def w():
        for _ in range(2000):
            r.inc("lut5_candidates")

    threads = [threading.Thread(target=w) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert r["lut5_candidates"] == 16000


def test_registry_reads_like_the_dict_it_replaced():
    r = tmetrics.context_registry()
    r.inc("pair_candidates", 7)
    assert r["pair_candidates"] == 7
    assert r.get("nope", 3) == 3
    assert "pair_candidates" in r
    assert dict(r)["pair_candidates"] == 7
    assert dict.fromkeys(r, 0)["device_dispatches"] == 0
    assert sum(v for k, v in r.items() if k.endswith("_candidates")) == 7
    # engine bail path: snapshot + restore
    snap = dict(r)
    r.inc("pair_candidates", 100)
    r.restore(snap)
    assert r["pair_candidates"] == 7
    # RestartContext views: fork zeroed, merge atomic
    f = r.fork()
    assert f["pair_candidates"] == 0
    f.inc("pair_candidates", 2)
    f.observe("device_wait_s[test]", 0.5)
    r.merge(f)
    assert r["pair_candidates"] == 9
    assert r.histograms()["device_wait_s[test]"]["count"] == 1


def test_registry_flags_undeclared_counters():
    r = tmetrics.context_registry()
    r.inc("device_dispatches")
    r.observe("device_wait_s[lut5.stream]", 0.1)  # bracketed family ok
    assert r.undeclared() == set()
    r.inc("totally_unknown_counter")
    assert r.undeclared() == {"totally_unknown_counter"}


def test_histogram_buckets_and_stats():
    h = tmetrics.Histogram(bounds=(0.1, 1.0))
    for v in (0.05, 0.5, 0.5, 5.0):
        h.observe(v)
    assert h.counts == [1, 2, 1]
    snap = h.snapshot()
    assert snap["count"] == 4
    assert snap["min"] == 0.05 and snap["max"] == 5.0
    assert abs(snap["mean"] - 6.05 / 4) < 1e-12


def test_bump_accepts_dicts_and_registries():
    d = {}
    tmetrics.bump(d, "x", 2)
    tmetrics.bump(d, "x")
    assert d == {"x": 3}
    r = tmetrics.MetricsRegistry(declared=None)
    tmetrics.bump(r, "x", 5)
    assert r["x"] == 5
    tmetrics.bump(None, "x")  # no-op


# -------------------------------------------------------------------------
# registry parity: tier-1-shaped runs increment only declared counters
# -------------------------------------------------------------------------


def _load_box(name):
    from sboxgates_tpu.utils.sbox import load_sbox

    return load_sbox(os.path.join(SBOXES, f"{name}.txt"))


def test_registry_parity_native_search():
    """A real (native-engine) one-output search touches only declared
    counters — the registry-parity gate for the host path."""
    from sboxgates_tpu.search import (
        Options,
        SearchContext,
        generate_graph_one_output,
        make_targets,
    )
    from sboxgates_tpu.graph.state import State

    sbox, n = _load_box("crypto1_fa")
    ctx = SearchContext(Options(seed=3))
    generate_graph_one_output(
        ctx, State.init_inputs(n), make_targets(sbox), 0, save_dir=None,
        log=lambda s: None,
    )
    assert ctx.stats["pair_candidates"] > 0
    assert ctx.stats.undeclared() == set(), ctx.stats.undeclared()


def test_registry_parity_device_dispatch_path():
    """The device-kernel path (head sweeps + LUT streams, warm-registry
    telemetry included) also stays inside the declared schema."""
    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search import lut as slut
    from sboxgates_tpu.graph.state import GATES, State
    from sboxgates_tpu.core import boolfunc as bf

    ctx = SearchContext(Options(
        seed=5, lut_graph=True, randomize=False, host_small_steps=False,
    ))
    rng = np.random.default_rng(0)
    st = State.init_inputs(8)
    while st.num_gates < 24:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    target = np.zeros(8, dtype=np.uint32)  # unrealizable: full sweeps
    mask = tt.mask_table(8)
    ctx.lut_step(st, target, mask, [])
    slut.lut5_search(ctx, st, target, mask, [])
    assert ctx.stats["device_dispatches"] > 0
    assert ctx.stats.undeclared() == set(), ctx.stats.undeclared()


# -------------------------------------------------------------------------
# dispatch-span / counter reconciliation
# -------------------------------------------------------------------------


def _dispatch_spans():
    return [e for e in ttrace.tracer().events() if e[1] == "dispatch"]


def test_dispatch_span_reconciliation_direct_path():
    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.graph.state import GATES, State
    from sboxgates_tpu.core import boolfunc as bf

    ctx = SearchContext(Options(seed=1, host_small_steps=False))
    rng = np.random.default_rng(0)
    st = State.init_inputs(8)
    while st.num_gates < 20:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    target = np.zeros(8, dtype=np.uint32)
    mask = tt.mask_table(8)
    tr = ttrace.tracer()
    tr.enabled = True
    ctx.pair_search(st, target, mask, False)
    ctx.gate_step(st, target, mask)
    ctx.triple_search(st, target, mask)
    assert ctx.stats["device_dispatches"] >= 3
    assert len(_dispatch_spans()) == ctx.stats["device_dispatches"]


def test_dispatch_span_reconciliation_fleet_run():
    """The acceptance shape: a fleet (merged-dispatch) run's dispatch
    spans reconcile exactly with the device_dispatches counter, and the
    merging itself is visible (spans with merged lanes > 1)."""
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.fleet import toy_fleet_boxes
    from sboxgates_tpu.search.multibox import search_boxes_one_output

    ctx = SearchContext(Options(
        seed=11, lut_graph=True, randomize=False, host_small_steps=False,
        native_engine=False, fleet=True, trace=True,
    ))
    tr = ttrace.tracer()
    tr.reset()
    res = search_boxes_one_output(
        ctx, toy_fleet_boxes(4), 0, save_dir=None, log=lambda s: None,
        batched="fleet",
    )
    assert all(sts for sts in res.values())
    spans = _dispatch_spans()
    assert ctx.stats["device_dispatches"] > 0
    assert len(spans) == ctx.stats["device_dispatches"]
    merged = [
        e for e in spans if e[5] is not None and e[5].get("merged", 0) > 1
    ]
    assert merged, "no merged fleet dispatch span recorded"
    # ttfh histograms observed per job
    hists = ctx.stats.histograms()
    assert hists.get("job_time_to_first_hit_s", {}).get("count", 0) >= 4


def test_trace_adds_zero_host_syncs():
    """Tracing on vs off must not change the number of blocking
    device->host transfers — spans time host-side events only."""
    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.graph.state import GATES, State
    from sboxgates_tpu.core import boolfunc as bf
    from sboxgates_tpu.utils import sync_guard

    def syncs(trace_on):
        ctx = SearchContext(Options(seed=1, host_small_steps=False))
        rng = np.random.default_rng(0)
        st = State.init_inputs(8)
        while st.num_gates < 20:
            a, b = rng.choice(st.num_gates, size=2, replace=False)
            st.add_gate(bf.XOR, int(a), int(b), GATES)
        target = np.zeros(8, dtype=np.uint32)
        mask = tt.mask_table(8)
        ttrace.tracer().enabled = trace_on
        with sync_guard(allowed=1 << 30, action="count") as rep:
            ctx.gate_step(st, target, mask)
            ctx.pair_search(st, target, mask, False)
        ttrace.tracer().enabled = False
        return rep.syncs

    assert syncs(False) == syncs(True)


# -------------------------------------------------------------------------
# heartbeat
# -------------------------------------------------------------------------


def test_heartbeat_lines_and_atomic_snapshot(tmp_path):
    r = tmetrics.context_registry()
    r.inc("device_dispatches", 5)
    hb = Heartbeat(r, str(tmp_path), interval_s=0.05, rank=1).start()
    time.sleep(0.25)
    snap_path = hb.stop()
    lines = [
        json.loads(ln)
        for ln in open(tmp_path / "telemetry.jsonl", encoding="utf-8")
    ]
    assert lines[0]["kind"] == "start"
    assert lines[-1]["kind"] == "final"
    assert len(lines) >= 3  # start + >=1 beat + final
    for ln in lines:
        assert ln["rank"] == 1
        assert ln["counters"]["device_dispatches"] == 5
        assert "process" in ln and "uptime_s" in ln
    snap = json.load(open(snap_path))
    assert snap["counters"]["device_dispatches"] == 5
    assert "histograms" in snap and snap["rank"] == 1
    assert not os.path.exists(str(snap_path) + ".tmp")


def test_heartbeat_resume_appends(tmp_path):
    r = tmetrics.MetricsRegistry(declared=None)
    hb1 = Heartbeat(r, str(tmp_path), interval_s=0, rank=0).start()
    hb1.stop(snapshot=False)
    tflight.flight_recorder().clear_hooks()
    n1 = len(open(tmp_path / "telemetry.jsonl").readlines())
    hb2 = Heartbeat(
        r, str(tmp_path), interval_s=0, rank=0, resume=True
    ).start()
    hb2.stop(snapshot=False)
    n2 = len(open(tmp_path / "telemetry.jsonl").readlines())
    assert n2 > n1  # appended after the prior run's tail, not truncated


# -------------------------------------------------------------------------
# flight recorder
# -------------------------------------------------------------------------


def test_flight_dump_on_deadline_exhaustion(tmp_path):
    """SBG_FAULTS at dispatch.sweep (hang) + a tiny deadline budget:
    the exhausted retry schedule dumps a valid, bounded post-mortem
    containing the breaching span."""
    from sboxgates_tpu.resilience import faults
    from sboxgates_tpu.resilience.deadline import (
        DeadlineConfig,
        DispatchTimeout,
        dispatch_with_retry,
    )

    tflight.configure(str(tmp_path), rank=0)
    stats = tmetrics.context_registry()
    faults.disarm("dispatch.sweep")
    faults.arm("dispatch.sweep", "hang")
    try:
        with pytest.raises(DispatchTimeout):
            dispatch_with_retry(
                lambda: None,
                DeadlineConfig(budget_s=0.05, retries=1, backoff_s=0.01),
                stats=stats,
                label="lut5.pivot.test",
            )
    finally:
        faults.disarm("dispatch.sweep")
    dumps = sorted(tmp_path.glob("flight-rank00-*.json"))
    assert len(dumps) == 1
    assert dumps[0].stat().st_size <= tflight.DUMP_MAX_BYTES + 4096
    doc = json.load(open(dumps[0]))
    assert doc["reason"] == "deadline_exhausted"
    assert doc["extra"]["label"] == "lut5.pivot.test"
    assert doc["rank"] == 0
    # the breaching span: the exhaustion instant naming the label, plus
    # the per-attempt breach events, are in the ring
    names = [e["name"] for e in doc["events"]]
    assert "deadline.exhausted" in names
    assert names.count("deadline.breach") == 2  # budget + 1 retry
    exh = next(e for e in doc["events"] if e["name"] == "deadline.exhausted")
    assert exh["args"]["label"] == "lut5.pivot.test"
    # counter snapshot rode along
    assert doc["counters"]["deadline_breaches"] == 2
    assert stats["flight_dumps"] == 1


def test_flight_dump_bounded_under_flood(tmp_path):
    fr = tflight.flight_recorder()
    fr.configure(str(tmp_path), rank=0)
    for i in range(20000):
        fr.note(f"e{i}", "dispatch", float(i), 0.001, {"x": "y" * 50})
    path = fr.dump("flood_test")
    assert path is not None
    assert os.path.getsize(path) <= tflight.DUMP_MAX_BYTES + 4096
    doc = json.load(open(path))
    assert len(doc["events"]) <= tflight.RING_CAP


def test_flight_dump_without_directory_is_noop():
    assert tflight.flight_dump("nowhere") is None


def test_circuit_breaker_trip_dumps(tmp_path):
    from sboxgates_tpu.search import Options, SearchContext

    tflight.configure(str(tmp_path), rank=0)
    ctx = SearchContext(Options(seed=1))
    ctx.trip_device_breaker()
    assert ctx.device_degraded
    assert ctx.stats["circuit_breaker_trips"] == 1
    dumps = list(tmp_path.glob("flight-rank00-*.json"))
    assert len(dumps) == 1
    assert json.load(open(dumps[0]))["reason"] == "circuit_breaker"


def test_flight_dump_and_final_heartbeat_on_injected_crash(tmp_path):
    """The killed-run acceptance clause: a fault-injected crash
    (SBG_FAULTS=ckpt.write:crash) through the real CLI leaves BOTH a
    flight-recorder dump and a final (incident) heartbeat line."""
    from sboxgates_tpu.resilience.faults import CRASH_EXIT_CODE

    outdir = tmp_path / "run"
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        SBG_WARMUP="0",
        SBG_FAULTS="ckpt.write:crash",
        SBG_COMPILE_CACHE="",
    )
    proc = subprocess.run(
        [
            sys.executable, "-m", "sboxgates_tpu",
            os.path.join(SBOXES, "crypto1_fa.txt"),
            "--seed", "7", "-o", "0",
            "--output-dir", str(outdir),
            "--metrics-interval", "300",
        ],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == CRASH_EXIT_CODE, proc.stderr
    dumps = list(outdir.glob("flight-rank00-*.json"))
    assert dumps, os.listdir(outdir)
    doc = json.load(open(dumps[0]))
    assert doc["reason"] == "injected_crash"
    assert doc["extra"]["site"] == "ckpt.write"
    # journal appends from the run are in the ring (post-mortem context)
    assert any(e["cat"] == "journal" for e in doc["events"])
    lines = [
        json.loads(ln)
        for ln in open(outdir / "telemetry.jsonl", encoding="utf-8")
    ]
    assert lines[0]["kind"] == "start"
    assert lines[-1]["kind"] == "incident:injected_crash"


# -------------------------------------------------------------------------
# fallback signals are structured events
# -------------------------------------------------------------------------


def test_pallas_fallback_emits_structured_event():
    from sboxgates_tpu.parallel import mesh

    tr = ttrace.tracer()
    tr.enabled = True
    stats = tmetrics.context_registry()
    before = tmetrics.GLOBAL.get("pivot_pallas_fallbacks", 0)
    mesh._note_pallas_fallback("pallas", stats)
    assert stats["pivot_pallas_fallbacks"] == 1
    assert tmetrics.GLOBAL["pivot_pallas_fallbacks"] == before + 1
    ev = [e for e in tr.events() if e[0] == "pallas_fallback"]
    assert len(ev) == 1 and ev[0][1] == "fallback"
    assert ev[0][5]["backend"] == "pallas"


def test_journal_append_emits_span(tmp_path):
    from sboxgates_tpu.resilience.journal import SearchJournal

    tr = ttrace.tracer()
    tr.enabled = True
    j = SearchJournal.start(str(tmp_path), config={"seed": 1})
    j.append("round_done", beam=[])
    names = [e[0] for e in tr.events() if e[1] == "journal"]
    assert "journal[run_start]" in names
    assert "journal[round_done]" in names


# -------------------------------------------------------------------------
# bench meta schema
# -------------------------------------------------------------------------


def test_bench_meta_schema():
    """Every BENCH_*.json writer shares one meta block; this test is the
    drift gate — new keys or a schema bump must be made here too."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    meta = bench.bench_meta()
    assert tuple(sorted(meta)) == tuple(sorted(bench.BENCH_META_KEYS))
    assert meta["metric"] == "meta"
    assert meta["schema"] == bench.BENCH_SCHEMA == 1
    assert isinstance(meta["t1_normalization"], str)
    assert "telemetry.metrics" in meta["counters_source"]
    entries = [{"metric": "x", "value": 1}]
    out = bench.with_meta(entries)
    assert out[0]["metric"] == "meta" and out[1]["metric"] == "x"
    assert entries[0]["metric"] == "x"  # caller's list untouched
    again = bench.with_meta(out)
    assert [e["metric"] for e in again] == ["meta", "x"]  # idempotent
