"""Unified telemetry subsystem: tracing, metrics registry, heartbeat,
flight recorder, and the bench meta schema.

Coverage map (ISSUE 9 acceptance criteria):

- Perfetto-export schema: event types, monotonic timestamps, thread
  ids (``test_perfetto_export_schema``).
- Registry parity: every counter a tier-1-shaped run increments is
  declared, named, and typed (``test_registry_parity_*``) — the same
  pattern as the kernel warm-registry parity test.
- Dispatch spans reconcile exactly with the ``device_dispatches``
  counter, on the per-thread path AND through a fleet run
  (``test_dispatch_span_reconciliation*``).
- Telemetry off adds zero extra host syncs
  (``test_trace_adds_zero_host_syncs``).
- Flight recorder under fault injection: dumps produced at
  ``dispatch.sweep`` (in-process hang -> deadline exhaustion) and
  ``ckpt.write`` (subprocess crash), valid JSON, bounded, containing
  the breaching span; the crash also leaves a final heartbeat line.
- Bench writers share one meta block; schema drift is rejected
  (``test_bench_meta_schema``).
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from sboxgates_tpu.telemetry import attribution as tattr
from sboxgates_tpu.telemetry import flight as tflight
from sboxgates_tpu.telemetry import metrics as tmetrics
from sboxgates_tpu.telemetry import trace as ttrace
from sboxgates_tpu.telemetry.heartbeat import Heartbeat

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
SBOXES = os.path.join(REPO, "sboxes")


@pytest.fixture(autouse=True)
def _clean_telemetry_state():
    """Every test starts from a quiet process tracer/recorder and leaves
    it quiet (both are process-global by design)."""
    tr = ttrace.tracer()
    fr = tflight.flight_recorder()
    tr.enabled = False
    tr.reset()
    fr.reset()
    fr.configure(None)
    fr.clear_hooks()
    tattr.reset()
    lazy_was = tattr.lazy_capture_enabled()
    yield
    tr.enabled = False
    tr.reset()
    fr.reset()
    fr.configure(None)
    fr.clear_hooks()
    ttrace.set_rank(None)
    tattr.reset()
    tattr.set_lazy_capture(lazy_was)


# -------------------------------------------------------------------------
# tracer
# -------------------------------------------------------------------------


def test_tracer_records_spans_across_threads():
    tr = ttrace.tracer()
    tr.enabled = True

    with ttrace.span("dispatch[x]", "dispatch", kernel="x") as sp:
        sp.set(warm="hit")

    def worker():
        with ttrace.span("dispatch[y]", "dispatch", kernel="y"):
            pass

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    evs = tr.events()
    assert len(evs) == 2
    names = {e[0] for e in evs}
    assert names == {"dispatch[x]", "dispatch[y]"}
    tids = {e[4] for e in evs}
    assert len(tids) == 2  # one buffer per thread
    x = next(e for e in evs if e[0] == "dispatch[x]")
    assert x[5] == {"kernel": "x", "warm": "hit"}
    # time-ordered, spans carry durations
    assert evs[0][2] <= evs[1][2]
    assert all(e[3] >= 0 for e in evs)


def test_tracer_disabled_records_nothing_but_flight_ring():
    tr = ttrace.tracer()
    assert not tr.enabled
    with ttrace.span("dispatch[x]", "dispatch"):
        pass
    ttrace.instant("mark", "journal")
    # high-frequency form: no flight, disabled -> shared no-op handle
    h = ttrace.span("phase", "phase", _flight=False)
    assert h is ttrace.trace_null()
    assert tr.events() == []
    ring = tflight.flight_recorder().events()
    assert {e[0] for e in ring} == {"dispatch[x]", "mark"}


def test_perfetto_export_schema(tmp_path):
    """Chrome/Perfetto trace-event contract: metadata + X/i events,
    microsecond timestamps that are monotone non-negative, integer
    thread ids, pid = process rank."""
    ttrace.set_rank(2)
    tr = ttrace.tracer()
    tr.enabled = True
    with ttrace.span("dispatch[k]", "dispatch", kernel="k", g=64):
        time.sleep(0.001)
    ttrace.instant("deadline.breach", "deadline", label="w")
    with ttrace.span("journal[round_done]", "journal"):
        pass
    path = tr.export(str(tmp_path / "trace.json"))
    doc = json.load(open(path))
    evs = doc["traceEvents"]
    assert isinstance(evs, list) and evs
    metas = [e for e in evs if e["ph"] == "M"]
    assert any(e["name"] == "process_name" for e in metas)
    rest = [e for e in evs if e["ph"] != "M"]
    assert {e["ph"] for e in rest} <= {"X", "i"}
    last_ts = -1.0
    for e in rest:
        assert set(e) >= {"name", "cat", "ts", "pid", "tid"}
        assert isinstance(e["tid"], int)
        assert e["pid"] == 2
        assert e["ts"] >= 0.0
        assert e["ts"] >= last_ts  # exported time-ordered
        last_ts = e["ts"]
        if e["ph"] == "X":
            assert e["dur"] >= 0.0
        else:
            assert e["s"] == "t"
    span = next(e for e in rest if e["name"] == "dispatch[k]")
    assert span["args"] == {"kernel": "k", "g": 64}


# -------------------------------------------------------------------------
# metrics registry
# -------------------------------------------------------------------------


def test_registry_increments_are_atomic_across_threads():
    r = tmetrics.context_registry()

    def w():
        for _ in range(2000):
            r.inc("lut5_candidates")

    threads = [threading.Thread(target=w) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert r["lut5_candidates"] == 16000


def test_registry_reads_like_the_dict_it_replaced():
    r = tmetrics.context_registry()
    r.inc("pair_candidates", 7)
    assert r["pair_candidates"] == 7
    assert r.get("nope", 3) == 3
    assert "pair_candidates" in r
    assert dict(r)["pair_candidates"] == 7
    assert dict.fromkeys(r, 0)["device_dispatches"] == 0
    assert sum(v for k, v in r.items() if k.endswith("_candidates")) == 7
    # engine bail path: snapshot + restore
    snap = dict(r)
    r.inc("pair_candidates", 100)
    r.restore(snap)
    assert r["pair_candidates"] == 7
    # RestartContext views: fork zeroed, merge atomic
    f = r.fork()
    assert f["pair_candidates"] == 0
    f.inc("pair_candidates", 2)
    f.observe("device_wait_s[test]", 0.5)
    r.merge(f)
    assert r["pair_candidates"] == 9
    assert r.histograms()["device_wait_s[test]"]["count"] == 1


def test_registry_flags_undeclared_counters():
    r = tmetrics.context_registry()
    r.inc("device_dispatches")
    r.observe("device_wait_s[lut5.stream]", 0.1)  # bracketed family ok
    assert r.undeclared() == set()
    r.inc("totally_unknown_counter")
    assert r.undeclared() == {"totally_unknown_counter"}


def test_histogram_buckets_and_stats():
    h = tmetrics.Histogram(bounds=(0.1, 1.0))
    for v in (0.05, 0.5, 0.5, 5.0):
        h.observe(v)
    assert h.counts == [1, 2, 1]
    snap = h.snapshot()
    assert snap["count"] == 4
    assert snap["min"] == 0.05 and snap["max"] == 5.0
    assert abs(snap["mean"] - 6.05 / 4) < 1e-12


def test_histogram_quantiles_exact_interpolation():
    """Bucket-interpolated p50/p90/p99 against hand-computed values.

    bounds (1, 2, 4): observations 0.5, 1.5, 1.5, 3.0 land as
    counts [1, 2, 1, 0].  p50 target rank = 2 -> bucket (1, 2] with
    cum_before 1, count 2: 1 + (2-1)*(2-1)/2 = 1.5 exactly.  p90 rank
    3.6 -> bucket (2, 4]: 2 + 2*(3.6-3)/1 = 3.2, clamped to max 3.0.
    p99 rank 3.96 -> same bucket -> clamp to 3.0."""
    h = tmetrics.Histogram(bounds=(1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 1.5, 3.0):
        h.observe(v)
    assert h.counts == [1, 2, 1, 0]
    assert h.quantile(0.50) == pytest.approx(1.5)
    assert h.quantile(0.90) == pytest.approx(3.0)  # clamped to max
    assert h.quantile(0.99) == pytest.approx(3.0)
    snap = h.snapshot()
    assert snap["p50"] == pytest.approx(1.5)
    assert snap["p90"] == pytest.approx(3.0)
    assert snap["p99"] == pytest.approx(3.0)


def test_histogram_quantiles_one_bucket_edge_case():
    """All observations inside one interior bucket: interpolation stays
    inside it and the clamp pins the estimate to the observed range.
    bounds (1, 2): 1.2, 1.4, 1.6, 1.8 -> counts [0, 4, 0].  p50 rank 2:
    1 + (2-1)*2/4 = 1.5; p99 rank 3.96: 1.99 -> clamped to max 1.8."""
    h = tmetrics.Histogram(bounds=(1.0, 2.0))
    for v in (1.2, 1.4, 1.6, 1.8):
        h.observe(v)
    assert h.quantile(0.50) == pytest.approx(1.5)
    assert h.quantile(0.99) == pytest.approx(1.8)
    # A single observation: every quantile IS that observation.
    h1 = tmetrics.Histogram(bounds=(10.0,))
    h1.observe(3.0)
    for q in (0.5, 0.9, 0.99):
        assert h1.quantile(q) == pytest.approx(3.0)


def test_histogram_quantiles_overflow_bucket_edge_case():
    """Ranks landing in the unbounded overflow bucket return the
    observed max — there is no upper edge to interpolate toward."""
    h = tmetrics.Histogram(bounds=(1.0,))
    for v in (0.5, 5.0, 9.0):
        h.observe(v)
    assert h.counts == [1, 2]
    assert h.quantile(0.50) == pytest.approx(9.0)  # rank 1.5 -> overflow
    assert h.quantile(0.99) == pytest.approx(9.0)
    # Empty histogram: NaN, and snapshot omits the quantile keys.
    h0 = tmetrics.Histogram()
    assert h0.quantile(0.5) != h0.quantile(0.5)  # NaN
    assert "p50" not in h0.snapshot()


def test_bump_accepts_dicts_and_registries():
    d = {}
    tmetrics.bump(d, "x", 2)
    tmetrics.bump(d, "x")
    assert d == {"x": 3}
    r = tmetrics.MetricsRegistry(declared=None)
    tmetrics.bump(r, "x", 5)
    assert r["x"] == 5
    tmetrics.bump(None, "x")  # no-op


# -------------------------------------------------------------------------
# registry parity: tier-1-shaped runs increment only declared counters
# -------------------------------------------------------------------------


def _load_box(name):
    from sboxgates_tpu.utils.sbox import load_sbox

    return load_sbox(os.path.join(SBOXES, f"{name}.txt"))


def test_registry_parity_native_search():
    """A real (native-engine) one-output search touches only declared
    counters — the registry-parity gate for the host path."""
    from sboxgates_tpu.search import (
        Options,
        SearchContext,
        generate_graph_one_output,
        make_targets,
    )
    from sboxgates_tpu.graph.state import State

    sbox, n = _load_box("crypto1_fa")
    ctx = SearchContext(Options(seed=3))
    generate_graph_one_output(
        ctx, State.init_inputs(n), make_targets(sbox), 0, save_dir=None,
        log=lambda s: None,
    )
    assert ctx.stats["pair_candidates"] > 0
    assert ctx.stats.undeclared() == set(), ctx.stats.undeclared()


def test_registry_parity_device_dispatch_path():
    """The device-kernel path (head sweeps + LUT streams, warm-registry
    telemetry included) also stays inside the declared schema."""
    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search import lut as slut
    from sboxgates_tpu.graph.state import GATES, State
    from sboxgates_tpu.core import boolfunc as bf

    ctx = SearchContext(Options(
        seed=5, lut_graph=True, randomize=False, host_small_steps=False,
    ))
    rng = np.random.default_rng(0)
    st = State.init_inputs(8)
    while st.num_gates < 24:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    target = np.zeros(8, dtype=np.uint32)  # unrealizable: full sweeps
    mask = tt.mask_table(8)
    ctx.lut_step(st, target, mask, [])
    slut.lut5_search(ctx, st, target, mask, [])
    assert ctx.stats["device_dispatches"] > 0
    assert ctx.stats.undeclared() == set(), ctx.stats.undeclared()


# -------------------------------------------------------------------------
# dispatch-span / counter reconciliation
# -------------------------------------------------------------------------


def _dispatch_spans():
    return [e for e in ttrace.tracer().events() if e[1] == "dispatch"]


def test_dispatch_span_reconciliation_direct_path():
    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.graph.state import GATES, State
    from sboxgates_tpu.core import boolfunc as bf

    ctx = SearchContext(Options(seed=1, host_small_steps=False))
    rng = np.random.default_rng(0)
    st = State.init_inputs(8)
    while st.num_gates < 20:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    target = np.zeros(8, dtype=np.uint32)
    mask = tt.mask_table(8)
    tr = ttrace.tracer()
    tr.enabled = True
    ctx.pair_search(st, target, mask, False)
    ctx.gate_step(st, target, mask)
    ctx.triple_search(st, target, mask)
    assert ctx.stats["device_dispatches"] >= 3
    assert len(_dispatch_spans()) == ctx.stats["device_dispatches"]


def test_dispatch_span_reconciliation_fleet_run():
    """The acceptance shape: a fleet (merged-dispatch) run's dispatch
    spans reconcile exactly with the device_dispatches counter, and the
    merging itself is visible (spans with merged lanes > 1)."""
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.fleet import toy_fleet_boxes
    from sboxgates_tpu.search.multibox import search_boxes_one_output

    ctx = SearchContext(Options(
        seed=11, lut_graph=True, randomize=False, host_small_steps=False,
        native_engine=False, fleet=True, trace=True,
    ))
    tr = ttrace.tracer()
    tr.reset()
    res = search_boxes_one_output(
        ctx, toy_fleet_boxes(4), 0, save_dir=None, log=lambda s: None,
        batched="fleet",
    )
    assert all(sts for sts in res.values())
    spans = _dispatch_spans()
    assert ctx.stats["device_dispatches"] > 0
    assert len(spans) == ctx.stats["device_dispatches"]
    merged = [
        e for e in spans if e[5] is not None and e[5].get("merged", 0) > 1
    ]
    assert merged, "no merged fleet dispatch span recorded"
    # ttfh histograms observed per job
    hists = ctx.stats.histograms()
    assert hists.get("job_time_to_first_hit_s", {}).get("count", 0) >= 4


def test_trace_adds_zero_host_syncs():
    """Tracing on vs off must not change the number of blocking
    device->host transfers — spans time host-side events only."""
    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.graph.state import GATES, State
    from sboxgates_tpu.core import boolfunc as bf
    from sboxgates_tpu.utils import sync_guard

    def syncs(trace_on):
        ctx = SearchContext(Options(seed=1, host_small_steps=False))
        rng = np.random.default_rng(0)
        st = State.init_inputs(8)
        while st.num_gates < 20:
            a, b = rng.choice(st.num_gates, size=2, replace=False)
            st.add_gate(bf.XOR, int(a), int(b), GATES)
        target = np.zeros(8, dtype=np.uint32)
        mask = tt.mask_table(8)
        ttrace.tracer().enabled = trace_on
        with sync_guard(allowed=1 << 30, action="count") as rep:
            ctx.gate_step(st, target, mask)
            ctx.pair_search(st, target, mask, False)
        ttrace.tracer().enabled = False
        return rep.syncs

    assert syncs(False) == syncs(True)


# -------------------------------------------------------------------------
# heartbeat
# -------------------------------------------------------------------------


def test_heartbeat_lines_and_atomic_snapshot(tmp_path):
    r = tmetrics.context_registry()
    r.inc("device_dispatches", 5)
    hb = Heartbeat(r, str(tmp_path), interval_s=0.05, rank=1).start()
    time.sleep(0.25)
    snap_path = hb.stop()
    lines = [
        json.loads(ln)
        for ln in open(tmp_path / "telemetry.jsonl", encoding="utf-8")
    ]
    assert lines[0]["kind"] == "start"
    assert lines[-1]["kind"] == "final"
    assert len(lines) >= 3  # start + >=1 beat + final
    for ln in lines:
        assert ln["rank"] == 1
        assert ln["counters"]["device_dispatches"] == 5
        assert "process" in ln and "uptime_s" in ln
    snap = json.load(open(snap_path))
    assert snap["counters"]["device_dispatches"] == 5
    assert "histograms" in snap and snap["rank"] == 1
    assert not os.path.exists(str(snap_path) + ".tmp")


def test_heartbeat_resume_appends(tmp_path):
    r = tmetrics.MetricsRegistry(declared=None)
    hb1 = Heartbeat(r, str(tmp_path), interval_s=0, rank=0).start()
    hb1.stop(snapshot=False)
    tflight.flight_recorder().clear_hooks()
    n1 = len(open(tmp_path / "telemetry.jsonl").readlines())
    hb2 = Heartbeat(
        r, str(tmp_path), interval_s=0, rank=0, resume=True
    ).start()
    hb2.stop(snapshot=False)
    n2 = len(open(tmp_path / "telemetry.jsonl").readlines())
    assert n2 > n1  # appended after the prior run's tail, not truncated


# -------------------------------------------------------------------------
# flight recorder
# -------------------------------------------------------------------------


def test_flight_dump_on_deadline_exhaustion(tmp_path):
    """SBG_FAULTS at dispatch.sweep (hang) + a tiny deadline budget:
    the exhausted retry schedule dumps a valid, bounded post-mortem
    containing the breaching span."""
    from sboxgates_tpu.resilience import faults
    from sboxgates_tpu.resilience.deadline import (
        DeadlineConfig,
        DispatchTimeout,
        dispatch_with_retry,
    )

    tflight.configure(str(tmp_path), rank=0)
    stats = tmetrics.context_registry()
    faults.disarm("dispatch.sweep")
    faults.arm("dispatch.sweep", "hang")
    try:
        with pytest.raises(DispatchTimeout):
            dispatch_with_retry(
                lambda: None,
                DeadlineConfig(budget_s=0.05, retries=1, backoff_s=0.01),
                stats=stats,
                label="lut5.pivot.test",
            )
    finally:
        faults.disarm("dispatch.sweep")
    dumps = sorted(tmp_path.glob("flight-rank00-*.json"))
    assert len(dumps) == 1
    assert dumps[0].stat().st_size <= tflight.DUMP_MAX_BYTES + 4096
    doc = json.load(open(dumps[0]))
    assert doc["reason"] == "deadline_exhausted"
    assert doc["extra"]["label"] == "lut5.pivot.test"
    assert doc["rank"] == 0
    # the breaching span: the exhaustion instant naming the label, plus
    # the per-attempt breach events, are in the ring
    names = [e["name"] for e in doc["events"]]
    assert "deadline.exhausted" in names
    assert names.count("deadline.breach") == 2  # budget + 1 retry
    exh = next(e for e in doc["events"] if e["name"] == "deadline.exhausted")
    assert exh["args"]["label"] == "lut5.pivot.test"
    # counter snapshot rode along
    assert doc["counters"]["deadline_breaches"] == 2
    assert stats["flight_dumps"] == 1


def test_flight_dump_bounded_under_flood(tmp_path):
    fr = tflight.flight_recorder()
    fr.configure(str(tmp_path), rank=0)
    for i in range(20000):
        fr.note(f"e{i}", "dispatch", float(i), 0.001, {"x": "y" * 50})
    path = fr.dump("flood_test")
    assert path is not None
    assert os.path.getsize(path) <= tflight.DUMP_MAX_BYTES + 4096
    doc = json.load(open(path))
    assert len(doc["events"]) <= tflight.RING_CAP


def test_flight_dump_without_directory_is_noop():
    assert tflight.flight_dump("nowhere") is None


def test_circuit_breaker_trip_dumps(tmp_path):
    from sboxgates_tpu.search import Options, SearchContext

    tflight.configure(str(tmp_path), rank=0)
    ctx = SearchContext(Options(seed=1))
    ctx.trip_device_breaker()
    assert ctx.device_degraded
    assert ctx.stats["circuit_breaker_trips"] == 1
    dumps = list(tmp_path.glob("flight-rank00-*.json"))
    assert len(dumps) == 1
    assert json.load(open(dumps[0]))["reason"] == "circuit_breaker"


def test_flight_dump_and_final_heartbeat_on_injected_crash(tmp_path):
    """The killed-run acceptance clause: a fault-injected crash
    (SBG_FAULTS=ckpt.write:crash) through the real CLI leaves BOTH a
    flight-recorder dump and a final (incident) heartbeat line."""
    from sboxgates_tpu.resilience.faults import CRASH_EXIT_CODE

    outdir = tmp_path / "run"
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        SBG_WARMUP="0",
        SBG_FAULTS="ckpt.write:crash",
        SBG_COMPILE_CACHE="",
    )
    proc = subprocess.run(
        [
            sys.executable, "-m", "sboxgates_tpu",
            os.path.join(SBOXES, "crypto1_fa.txt"),
            "--seed", "7", "-o", "0",
            "--output-dir", str(outdir),
            "--metrics-interval", "300",
        ],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == CRASH_EXIT_CODE, proc.stderr
    dumps = list(outdir.glob("flight-rank00-*.json"))
    assert dumps, os.listdir(outdir)
    doc = json.load(open(dumps[0]))
    assert doc["reason"] == "injected_crash"
    assert doc["extra"]["site"] == "ckpt.write"
    # journal appends from the run are in the ring (post-mortem context)
    assert any(e["cat"] == "journal" for e in doc["events"])
    lines = [
        json.loads(ln)
        for ln in open(outdir / "telemetry.jsonl", encoding="utf-8")
    ]
    assert lines[0]["kind"] == "start"
    assert lines[-1]["kind"] == "incident:injected_crash"


# -------------------------------------------------------------------------
# fallback signals are structured events
# -------------------------------------------------------------------------


def test_pallas_fallback_emits_structured_event():
    from sboxgates_tpu.parallel import mesh

    tr = ttrace.tracer()
    tr.enabled = True
    stats = tmetrics.context_registry()
    before = tmetrics.GLOBAL.get("pivot_pallas_fallbacks", 0)
    mesh._note_pallas_fallback("pallas", stats)
    assert stats["pivot_pallas_fallbacks"] == 1
    assert tmetrics.GLOBAL["pivot_pallas_fallbacks"] == before + 1
    ev = [e for e in tr.events() if e[0] == "pallas_fallback"]
    assert len(ev) == 1 and ev[0][1] == "fallback"
    assert ev[0][5]["backend"] == "pallas"


def test_journal_append_emits_span(tmp_path):
    from sboxgates_tpu.resilience.journal import SearchJournal

    tr = ttrace.tracer()
    tr.enabled = True
    j = SearchJournal.start(str(tmp_path), config={"seed": 1})
    j.append("round_done", beam=[])
    names = [e[0] for e in tr.events() if e[1] == "journal"]
    assert "journal[run_start]" in names
    assert "journal[round_done]" in names


# -------------------------------------------------------------------------
# performance attribution (roofline rows)
# -------------------------------------------------------------------------


class _FakeCompiled:
    """Duck-typed stand-in for an XLA Compiled (attribution never
    imports jax, so neither must its unit test)."""

    def __init__(self, flops, nbytes):
        self._flops, self._bytes = flops, nbytes

    def cost_analysis(self):
        return {"flops": self._flops, "bytes accessed": self._bytes}

    def memory_analysis(self):
        class M:
            argument_size_in_bytes = 100
            output_size_in_bytes = 20
            temp_size_in_bytes = 8

        return M()


def test_attribution_capture_join_and_placement():
    tattr.note_backend("cpu")
    pk = tattr.peaks()
    ridge = pk["flops_per_s"] / pk["bytes_per_s"]
    # One kernel well above the ridge (compute-bound), one well below
    # (memory-bound), both with latencies close to their model time.
    hi_ai = _FakeCompiled(flops=1e9, nbytes=1e9 / (ridge * 10))
    lo_ai = _FakeCompiled(flops=1e6, nbytes=1e6 / (ridge / 10))
    assert tattr.capture("k_mxu", hi_ai, (np.zeros((64, 8)),))
    assert tattr.capture("k_hbm", lo_ai, (np.zeros((512, 8)),))
    assert tattr.have("k_mxu", 64) and tattr.have("k_hbm", 512)
    reg = tmetrics.MetricsRegistry(declared=None)
    for _ in range(4):
        reg.observe("dispatch_latency_s[k_mxu]", 1e9 / pk["flops_per_s"])
        reg.observe(
            "dispatch_latency_s[k_hbm]",
            (1e6 / (ridge / 10)) / pk["bytes_per_s"],
        )
    rows = {r["kernel"]: r for r in tattr.table(reg)}
    assert rows["k_mxu"]["roofline"] == "compute-bound"
    assert rows["k_hbm"]["roofline"] == "memory-bound"
    assert rows["k_mxu"]["bucket"] == 64
    assert rows["k_mxu"]["dispatches"] == 4
    assert rows["k_mxu"]["achieved_flops_per_s"] == pytest.approx(
        pk["flops_per_s"]
    )
    assert rows["k_mxu"]["roofline_utilization"] == pytest.approx(1.0)
    assert rows["k_mxu"]["peak_memory_bytes"] == 128
    # arithmetic intensity is flops/bytes
    assert rows["k_mxu"]["arithmetic_intensity"] == pytest.approx(
        ridge * 10
    )


def test_attribution_dispatch_bound_placement():
    tattr.note_backend("cpu")
    pk = tattr.peaks()
    fake = _FakeCompiled(flops=1e6, nbytes=1e3)
    tattr.capture("k_rtt", fake, (np.zeros((64, 8)),))
    reg = tmetrics.MetricsRegistry(declared=None)
    # latency 1000x the model time: the link, not the chip, is the wall
    model = 1e6 / pk["flops_per_s"]
    reg.observe("dispatch_latency_s[k_rtt]", model * 1000)
    (row,) = tattr.table(reg)
    assert row["roofline"] == "dispatch-bound"


def test_attribution_capture_never_raises():
    class Broken:
        def cost_analysis(self):
            raise RuntimeError("no analysis on this backend")

    assert tattr.capture("k_bad", Broken(), ()) is False
    assert tattr.table(None) == []
    # zero-cost analysis is "no row", not a nonsense row
    assert tattr.capture(
        "k_zero", _FakeCompiled(0.0, 0.0), ()
    ) is False


def test_attribution_real_kernel_lazy_capture_and_span_args():
    """The production capture path: a lazy compile at kernel_call
    (persistent cache on -> lazy capture enabled) produces a cost row,
    metrics.json grows the attribution section, and later dispatch
    spans carry the cost args."""
    from sboxgates_tpu.core import boolfunc as bf
    from sboxgates_tpu.core import ttable as tt
    from sboxgates_tpu.graph.state import GATES, State
    from sboxgates_tpu.ops import sweeps
    from sboxgates_tpu.search import Options, SearchContext

    tattr.set_lazy_capture(True)
    # Earlier tests in this process may already have compiled the
    # kernel at this shape; the capture point IS the compile, so force
    # one (the persistent cache makes it a deserialize).
    sweeps.gate_step_stream.clear_cache()
    ctx = SearchContext(Options(
        seed=2, randomize=False, host_small_steps=False,
        parallel_mux=False,
    ))
    rng = np.random.default_rng(0)
    st = State.init_inputs(8)
    while st.num_gates < 20:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    target = np.zeros(8, dtype=np.uint32)
    mask = tt.mask_table(8)
    from sboxgates_tpu.telemetry.status import StatusServer

    tr = ttrace.tracer()
    tr.enabled = True
    srv = StatusServer(ctx.stats, port=0).start()
    try:
        ctx.gate_step(st, target, mask)  # compile -> capture
        assert tattr.have("gate_step_stream", 64)
        ctx.gate_step(st, target, mask)  # captured -> span cost args
    finally:
        srv.shutdown()
    spans = [e for e in tr.events() if e[0] == "dispatch[gate_step_stream]"]
    assert spans[-1][5].get("flops", 0) > 0
    assert spans[-1][5].get("bytes_accessed", 0) > 0
    # The span-count == device_dispatches parity gate holds with
    # attribution and the status endpoint enabled (acceptance clause).
    all_spans = [e for e in tr.events() if e[1] == "dispatch"]
    assert len(all_spans) == ctx.stats["device_dispatches"]
    # the latest sweep's gate count feeds the /status coverage
    # denominator (a context attribute, never a registry scalar — the
    # native/device stats-parity contract compares full scalar dicts)
    assert ctx.last_dispatch_gates == 20
    assert "last_dispatch_gates" in ctx.status_state()
    assert ctx.stats.undeclared() == set()
    rows = tattr.table(ctx.stats)
    row = next(r for r in rows if r["kernel"] == "gate_step_stream")
    assert row["source"] == "lazy"
    assert row["dispatches"] == 2
    assert row["roofline"] in (
        "compute-bound", "memory-bound", "dispatch-bound"
    )


def test_attribution_in_metrics_snapshot(tmp_path):
    tattr.note_backend("cpu")
    tattr.capture("k1", _FakeCompiled(1e6, 1e5), (np.zeros((64, 8)),))
    reg = tmetrics.context_registry()
    reg.observe("dispatch_latency_s[k1]", 0.01)
    hb = Heartbeat(reg, str(tmp_path), interval_s=0, rank=0).start()
    snap_path = hb.stop()
    snap = json.load(open(snap_path))
    att = snap["attribution"]
    assert att["backend"] == "cpu"
    assert att["rows"] and att["rows"][0]["kernel"] == "k1"
    assert att["rows"][0]["roofline"]
    # heartbeat lines carry quantile summaries, not raw tallies
    lines = [
        json.loads(ln)
        for ln in open(tmp_path / "telemetry.jsonl", encoding="utf-8")
    ]
    q = lines[-1]["quantiles"]["dispatch_latency_s[k1]"]
    assert {"count", "p50", "p90", "p99"} <= set(q)


def test_warmup_aot_compile_captures_cost(monkeypatch):
    """The warmer's AOT builds are the zero-extra-cost capture point:
    a warmed bucket build leaves (kernel, bucket) cost rows without
    lazy capture ever being enabled."""
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search import warmup as W

    monkeypatch.setenv("SBG_WARMUP", "1")
    assert not tattr.lazy_capture_enabled()
    W.drop_warm_cache()
    plan = W.WarmPlan.from_context(SearchContext(Options(seed=1)))
    warmer = W.KernelWarmer(plan)
    try:
        warmer.prewarm(2)  # gate-mode set at g=2 -> the 64 bucket
        assert warmer.wait_idle(120.0)
    finally:
        warmer.shutdown()
    assert tattr.have("gate_step_stream", 64)
    row = next(
        r for r in tattr.table(None)
        if r["kernel"] == "gate_step_stream"
    )
    assert row["source"] == "warmup"
    assert row["flops"] > 0


# -------------------------------------------------------------------------
# bench meta schema
# -------------------------------------------------------------------------


def test_bench_meta_schema():
    """Every BENCH_*.json writer shares one meta block; this test is the
    drift gate — new keys or a schema bump must be made here too."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    meta = bench.bench_meta()
    assert tuple(sorted(meta)) == tuple(sorted(bench.BENCH_META_KEYS))
    assert meta["metric"] == "meta"
    assert meta["schema"] == bench.BENCH_SCHEMA == 1
    assert isinstance(meta["t1_normalization"], str)
    assert "telemetry.metrics" in meta["counters_source"]
    entries = [{"metric": "x", "value": 1}]
    out = bench.with_meta(entries)
    assert out[0]["metric"] == "meta" and out[1]["metric"] == "x"
    assert entries[0]["metric"] == "x"  # caller's list untouched
    again = bench.with_meta(out)
    assert [e["metric"] for e in again] == ["meta", "x"]  # idempotent
