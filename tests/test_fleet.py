"""Fleet-batched search tests (ISSUE 6): the job batch axis.

Headline properties:

- PARITY: an 8-job DES fleet produces circuits bit-identical to the
  serial per-job loop (and the fleet mesh changes nothing), including a
  ragged fleet whose jobs finish at different rounds under done-masking.
- WARM SHAPES: fleet kernels are warm-registry citizens keyed on
  (jobs_bucket, bucket) — a warmed fleet bucket crossing performs ZERO
  steady-state compiles under a strict ``recompile_guard``.
- The dispatch merging itself: N jobs' same-kind node sweeps execute as
  one vmapped dispatch (submits >> dispatches).
"""

import os

import numpy as np
import pytest

from planted import build_planted_lut5_small
from sboxgates_tpu.core import boolfunc as bf
from sboxgates_tpu.core import ttable as tt
from sboxgates_tpu.graph.state import GATES, NO_GATE, State
from sboxgates_tpu.search import Options, SearchContext, warmup
from sboxgates_tpu.search.fleet import (
    FLEET_BUCKETS,
    FleetRendezvous,
    fleet_bucket,
    prev_fleet_bucket,
    run_fleet_circuits,
)
from sboxgates_tpu.search.multibox import (
    BoxJob,
    load_box_jobs,
    search_boxes_all_outputs,
    search_boxes_one_output,
)
from sboxgates_tpu.utils import recompile_guard

SBOXES = os.path.join(os.path.dirname(__file__), "..", "sboxes")

#: Device-dispatch configuration: node heads dispatch to the (CPU)
#: device instead of routing native, so the fleet rendezvous actually
#: merges sweeps.  randomize=False makes per-job results independent of
#: seed-block bookkeeping differences.
DEV = dict(
    seed=11, lut_graph=True, randomize=False, host_small_steps=False,
    native_engine=False,
)


def _boxes(names):
    return load_box_jobs([os.path.join(SBOXES, f"{n}.txt") for n in names])


def _toy_boxes(n=8):
    """The shared fixture corpus (cheap 3-input searches with real
    dispatches in the DEV configuration) — same generator the bench's
    dispatch ladder measures."""
    from sboxgates_tpu.search.fleet import toy_fleet_boxes

    return toy_fleet_boxes(n)


def _sig(res):
    return {
        name: [
            [(g.type, g.in1, g.in2, g.in3, g.function) for g in s.gates]
            for s in sts
        ]
        for name, sts in res.items()
    }


def _grow(g, seed=5):
    rng = np.random.default_rng(seed)
    st = State.init_inputs(8)
    while st.num_gates < g:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    return st


# -------------------------------------------------------------------------
# Parity: fleet == serial per-job loop
# -------------------------------------------------------------------------


def test_fleet_bucket_resolution():
    assert fleet_bucket(1) == 1
    assert fleet_bucket(3) == 4
    assert fleet_bucket(8) == 8
    assert fleet_bucket(9) == 16
    # bucket respects mesh job shards
    assert fleet_bucket(3, shards=8) == 8
    assert fleet_bucket(FLEET_BUCKETS[-1] + 1, shards=4) % 4 == 0
    assert prev_fleet_bucket(8) == 4
    assert prev_fleet_bucket(1) is None


def test_fleet_parity_des8_vs_serial():
    """The acceptance gate: all 8 DES S-boxes, output bit 0, as one
    fleet — circuits bit-identical to the serial per-job loop."""
    names = [f"des_s{i}" for i in range(1, 9)]
    ctx_s = SearchContext(Options(seed=11, lut_graph=True, randomize=False))
    res_s = search_boxes_one_output(
        ctx_s, _boxes(names), 0, save_dir=None, log=lambda s: None,
        batched=False,
    )
    ctx_f = SearchContext(
        Options(seed=11, lut_graph=True, randomize=False, fleet=True)
    )
    res_f = search_boxes_one_output(
        ctx_f, _boxes(names), 0, save_dir=None, log=lambda s: None,
    )
    assert _sig(res_f) == _sig(res_s)
    for sts in res_f.values():
        assert sts  # every box solved


def test_fleet_device_dispatch_parity_and_merging():
    """Device-routed toy fleet: bit-identical to the serial loop, and
    the jobs' sweeps actually merged (one vmapped dispatch serves many
    submits)."""
    ctx_s = SearchContext(Options(**DEV))
    res_s = search_boxes_one_output(
        ctx_s, _toy_boxes(), 0, save_dir=None, log=lambda s: None,
        batched=False,
    )
    ctx_f = SearchContext(Options(fleet=True, **DEV))
    res_f = search_boxes_one_output(
        ctx_f, _toy_boxes(), 0, save_dir=None, log=lambda s: None,
        batched="fleet",
    )
    assert _sig(res_f) == _sig(res_s)
    st = ctx_f.stats
    assert st["fleet_submits"] > 0
    # Merging: strictly fewer device dispatches than sweep submissions.
    assert st["fleet_rounds"] < st["fleet_submits"]
    assert st["fleet_dispatches"] >= 1
    assert st["fleet_lanes"] >= 2 * st["fleet_dispatches"]


def test_fleet_ragged_done_masking(tmp_path):
    """Ragged fleet through the lockstep all-outputs driver: boxes
    finish at different rounds (ident3 completes via step-1 reuse,
    parmaj3 needs real gates), jobs retire mid-wave as their searches
    end — results bit-identical to the rendezvous-batched driver, which
    shares the seed discipline."""
    ident = np.zeros(256, dtype=np.uint8)
    ident[:8] = np.arange(8)
    boxes = lambda: [BoxJob("ident3", ident.copy(), 3)] + _toy_boxes(3)  # noqa: E731
    ctx_b = SearchContext(Options(**DEV))
    res_b = search_boxes_all_outputs(
        ctx_b, boxes(), save_dir=str(tmp_path / "b"), log=lambda s: None,
        batched=True,
    )
    ctx_f = SearchContext(Options(fleet=True, **DEV))
    res_f = search_boxes_all_outputs(
        ctx_f, boxes(), save_dir=str(tmp_path / "f"), log=lambda s: None,
    )
    assert _sig(res_f) == _sig(res_b)
    for name, sts in res_f.items():
        assert sts, f"{name}: incomplete"
    assert ctx_f.stats["fleet_rounds"] < ctx_f.stats["fleet_submits"]


def test_fleet_mesh_sharded_parity():
    """P("jobs")-sharded fleet (2-D mesh over the 8 virtual devices) is
    bit-identical to the unsharded fleet and to the serial loop."""
    from sboxgates_tpu.parallel import FleetPlan, make_fleet_mesh

    plan = FleetPlan(make_fleet_mesh())
    assert plan.n_job_shards >= 1
    ctx_s = SearchContext(Options(**DEV))
    res_s = search_boxes_one_output(
        ctx_s, _toy_boxes(4), 0, save_dir=None, log=lambda s: None,
        batched=False,
    )
    ctx_p = SearchContext(Options(fleet=True, **DEV), fleet_plan=plan)
    res_p = search_boxes_one_output(
        ctx_p, _toy_boxes(4), 0, save_dir=None, log=lambda s: None,
    )
    assert _sig(res_p) == _sig(res_s)
    assert ctx_p.stats["fleet_dispatches"] >= 1


def test_fleet_mesh_excludes_candidate_mesh():
    from sboxgates_tpu.parallel import FleetPlan, MeshPlan, make_fleet_mesh, make_mesh

    # Rejected at CONSTRUCTION (either form), so every driver behaves
    # identically — the orchestrator cannot silently fall back serial.
    with pytest.raises(ValueError):
        SearchContext(
            Options(seed=1), mesh_plan=MeshPlan(make_mesh()),
            fleet_plan=FleetPlan(make_fleet_mesh()),
        )
    with pytest.raises(ValueError):
        SearchContext(
            Options(seed=1, fleet=True), mesh_plan=MeshPlan(make_mesh())
        )
    # An explicit batched="fleet" on a plain mesh context is rejected by
    # the driver-level mode resolution.
    ctx = SearchContext(Options(seed=1), mesh_plan=MeshPlan(make_mesh()))
    with pytest.raises(ValueError):
        search_boxes_one_output(
            ctx, _toy_boxes(2), 0, save_dir=None, log=lambda s: None,
            batched="fleet",
        )


# -------------------------------------------------------------------------
# Warm shapes: (jobs_bucket, bucket)-keyed fleet kernels
# -------------------------------------------------------------------------


def _fleet_warm_ctx(monkeypatch, **kw):
    monkeypatch.setenv("SBG_WARMUP", "1")
    opt = dict(DEV, fleet=True)
    opt.update(kw)
    ctx = SearchContext(Options(**opt))
    assert ctx.warmer is not None and ctx.warmer.enabled
    return ctx


def test_fleet_bucket_crossing_zero_compiles(monkeypatch):
    """A warmed fleet crossing BOTH axes — the table bucket (64 -> 512)
    and the jobs bucket (4 -> 2, jobs retiring) — performs zero
    steady-state compiles: the (jobs_bucket, bucket) warm specs serve
    the dispatches with AOT executables."""
    ctx = _fleet_warm_ctx(monkeypatch, lut_graph=False)
    mask = tt.mask_table(8)
    st63 = _grow(63)
    t63 = st63.table(50).copy()
    try:
        # Entry wave: 4 jobs at bucket 64 — one fleet dispatch each job
        # (the target matches an existing gate, so each search is a
        # single gate_step submit).  Schedules the warm cross product
        # {bucket, next bucket} x {lanes, prev lanes}.
        res = run_fleet_circuits(
            ctx, [(st63.copy(), t63, mask) for _ in range(4)]
        )
        assert all(out == 50 for _, out in res)
        assert ctx.warmer.wait_idle(300), "warmer never went idle"
        ws = ctx.warmup_stats()
        assert ws["warm_failed"] == 0, ws
        assert ws["warm_compiled"] >= 4, ws

        st65 = _grow(65)
        t65 = st65.table(50).copy()
        # The eager per-node helpers (combo grid, validity arange) for
        # bucket 512 compile outside the guarded region: the guard gates
        # the DISPATCH path, which is what the fleet warms.
        ctx._node_operands(st65, t65, mask)
        # Steady state: run each crossing shape once (warm-served, but
        # each first entry to a (bucket, lanes) cell schedules ITS
        # successors on the background worker — those compiles must
        # drain before a process-wide zero-compile guard).
        run_fleet_circuits(ctx, [(st65.copy(), t65, mask) for _ in range(4)])
        run_fleet_circuits(ctx, [(st63.copy(), t63, mask) for _ in range(2)])
        assert ctx.warmer.wait_idle(300)
        h0 = ctx.stats["fleet_warm_hits"]
        with recompile_guard(allowed=0, label="fleet bucket crossing") as rep:
            # Gate-bucket crossing at held lanes.
            res = run_fleet_circuits(
                ctx, [(st65.copy(), t65, mask) for _ in range(4)]
            )
            assert all(out == 50 for _, out in res)
            # Jobs-bucket crossing (fleet shrank 4 -> 2) at the old
            # gate bucket — the diagonal was warmed too.
            res = run_fleet_circuits(
                ctx, [(st63.copy(), t63, mask) for _ in range(2)]
            )
            assert all(out == 50 for _, out in res)
        assert rep.compiles == 0
        assert ctx.stats["fleet_warm_hits"] >= h0 + 2
        assert ctx.warmup_stats().get("warm_aval_mismatches", 0) == 0
    finally:
        ctx.warmer.shutdown()


def test_fleet_lut_head_warm_hit(monkeypatch):
    """LUT-mode fleet: a warmed (jobs_bucket, bucket) set serves the
    fused head dispatch compile-free."""
    st0, target, mask = build_planted_lut5_small()
    ctx = _fleet_warm_ctx(monkeypatch)
    try:
        jobs = lambda: [(st0.copy(), target, mask) for _ in range(4)]  # noqa: E731
        res1 = run_fleet_circuits(ctx, jobs())
        assert all(out != NO_GATE for _, out in res1)
        assert ctx.warmer.wait_idle(300)
        ctx._node_operands(st0, target, mask)
        with recompile_guard(allowed=0, label="warmed lut fleet wave") as rep:
            res2 = run_fleet_circuits(ctx, jobs())
        assert rep.compiles == 0
        assert ctx.stats["fleet_warm_hits"] >= 1
        assert [o for _, o in res2] == [o for _, o in res1]
    finally:
        ctx.warmer.shutdown()


def test_fleet_registry_parity(monkeypatch):
    """Live fleet submissions must agree with the warm registry: every
    merged kernel's shared-argument tuple matches FLEET_SHARED (the
    table fleet_warm_specs enumerates from), and the dispatcher's warm
    key for each group is exactly a fleet_warm_specs key for that
    (g, lanes)."""
    recorded = []
    orig = FleetRendezvous._run_group

    def spy(self, key, entries):
        recorded.append((key, tuple(entries[0]["shared"]),
                         len(entries[0]["args"]),
                         max((e.get("g") or 0) for e in entries),
                         len(entries)))
        return orig(self, key, entries)

    monkeypatch.setattr(FleetRendezvous, "_run_group", spy)
    ctx = SearchContext(Options(fleet=True, **DEV))
    search_boxes_one_output(
        ctx, _toy_boxes(4), 0, save_dir=None, log=lambda s: None,
    )
    gctx = SearchContext(Options(fleet=True, **dict(DEV, lut_graph=False)))
    st = _grow(24)
    run_fleet_circuits(
        gctx, [(st.copy(), st.table(20).copy(), tt.mask_table(8))
               for _ in range(2)]
    )
    assert recorded, "no fleet groups dispatched"
    plans = {
        True: warmup.WarmPlan.from_context(ctx),
        False: warmup.WarmPlan.from_context(gctx),
    }
    seen = set()
    for key, shared, nargs, g, n in recorded:
        name = key[0]
        seen.add(name)
        if name in warmup.FLEET_SHARED:
            assert shared == warmup.FLEET_SHARED[name], name
        if name not in warmup.FLEET_SHARED or n < 2 or not g:
            continue
        lanes = fleet_bucket(n)
        plan = plans[name != "gate_step_stream"]
        specs = warmup.fleet_warm_specs(plan, g, lanes)
        keys = {k for k, *_ in specs}
        spec_sigs = {
            (k[1], k[2], k[4]) for k in keys
        }
        assert (name, key[1], lanes) in spec_sigs, (
            f"fleet dispatch {name} g={g} lanes={lanes} has no warm spec "
            "— live call sites and FLEET_SHARED/warm_specs drifted"
        )
    assert "lut_step_stream" in seen


# -------------------------------------------------------------------------
# The stacked lockstep step + fleet table cache
# -------------------------------------------------------------------------


def test_fleet_gate_step_done_masking():
    """The single-kernel [jobs, bucket, 8] lockstep sweep: per-job
    verdicts match the per-job kernel, retired lanes ride as masked
    no-op rows, and the stacked-table cache is content-keyed."""
    from sboxgates_tpu.search.fleet import fleet_gate_step

    ctx = SearchContext(Options(**dict(DEV, lut_graph=False)))
    sts = [_grow(20, seed=s) for s in range(3)]
    jobs = [
        (st, st.table(12).copy(), tt.mask_table(8)) for st in sts
    ]
    out = fleet_gate_step(ctx, jobs)
    assert out.shape[0] == 3
    for (st, t, m), row in zip(jobs, out):
        step, x0, _ = ctx.gate_step(st, t, m)
        assert int(row[0]) == step and int(row[1]) == x0
    # done-masking: retired lanes are zeroed, live lanes unchanged.
    out2 = fleet_gate_step(ctx, jobs, done=[False, True, False])
    assert (out2[1] == 0).all()
    assert (out2[0] == out[0]).all() and (out2[2] == out[2]).all()
    # stacked-table cache: same fleet content -> resident stack reused
    # (a retired lane contributes a stable placeholder digest, so
    # retirement does not churn the key); mutation always re-uploads.
    h0, m0 = ctx.fleet_stack.hits, ctx.fleet_stack.misses
    ctx.fleet_device_tables(sts, done=[False, True, False])
    ctx.fleet_device_tables(sts, done=[False, True, False])
    assert ctx.fleet_stack.hits >= h0 + 1
    m1 = ctx.fleet_stack.misses
    sts[0].add_gate(bf.XOR, 0, 1, GATES)
    ctx.fleet_device_tables(sts, done=[False, True, False])
    assert ctx.fleet_stack.misses == m1 + 1
    # Explicit lifecycle control drops the stacked buffers too.
    ctx.invalidate_device_tables()
    ctx.fleet_device_tables(sts, done=[False, True, False])
    assert ctx.fleet_stack.misses == m1 + 2


def test_fleet_gate_step_sharded_matches():
    from sboxgates_tpu.parallel import FleetPlan, make_fleet_mesh
    from sboxgates_tpu.search.fleet import fleet_gate_step

    ctx = SearchContext(Options(**dict(DEV, lut_graph=False)))
    ctx_p = SearchContext(
        Options(**dict(DEV, lut_graph=False)),
        fleet_plan=FleetPlan(make_fleet_mesh()),
    )
    sts = [_grow(20, seed=s) for s in range(3)]
    jobs = [(st, st.table(12).copy(), tt.mask_table(8)) for st in sts]
    a = fleet_gate_step(ctx, jobs)
    b = fleet_gate_step(ctx_p, jobs)
    np.testing.assert_array_equal(a, b)
