"""Fleet-batched search tests (ISSUE 6): the job batch axis.

Headline properties:

- PARITY: an 8-job DES fleet produces circuits bit-identical to the
  serial per-job loop (and the fleet mesh changes nothing), including a
  ragged fleet whose jobs finish at different rounds under done-masking.
- WARM SHAPES: fleet kernels are warm-registry citizens keyed on
  (jobs_bucket, bucket) — a warmed fleet bucket crossing performs ZERO
  steady-state compiles under a strict ``recompile_guard``.
- The dispatch merging itself: N jobs' same-kind node sweeps execute as
  one vmapped dispatch (submits >> dispatches).
"""

import os
import threading

import numpy as np
import pytest

from planted import build_planted_lut5, build_planted_lut5_small, \
    build_planted_lut7
from sboxgates_tpu.core import boolfunc as bf
from sboxgates_tpu.core import ttable as tt
from sboxgates_tpu.graph.state import GATES, NO_GATE, State
from sboxgates_tpu.search import Options, SearchContext, warmup
from sboxgates_tpu.search.fleet import (
    FLEET_BUCKETS,
    FLEET_LADDER,
    STACKED_BUCKETS,
    FleetRendezvous,
    _run_fleet_wave,
    fleet_bucket,
    fleet_gate_step,
    fleet_lut7_step,
    fleet_pivot_step,
    prev_fleet_bucket,
    run_fleet_circuits,
)
from sboxgates_tpu.search.multibox import (
    BoxJob,
    load_box_jobs,
    search_boxes_all_outputs,
    search_boxes_one_output,
)
from sboxgates_tpu.utils import recompile_guard

SBOXES = os.path.join(os.path.dirname(__file__), "..", "sboxes")

#: Device-dispatch configuration: node heads dispatch to the (CPU)
#: device instead of routing native, so the fleet rendezvous actually
#: merges sweeps.  randomize=False makes per-job results independent of
#: seed-block bookkeeping differences.
DEV = dict(
    seed=11, lut_graph=True, randomize=False, host_small_steps=False,
    native_engine=False,
)


def _boxes(names):
    return load_box_jobs([os.path.join(SBOXES, f"{n}.txt") for n in names])


def _toy_boxes(n=8):
    """The shared fixture corpus (cheap 3-input searches with real
    dispatches in the DEV configuration) — same generator the bench's
    dispatch ladder measures."""
    from sboxgates_tpu.search.fleet import toy_fleet_boxes

    return toy_fleet_boxes(n)


def _sig(res):
    return {
        name: [
            [(g.type, g.in1, g.in2, g.in3, g.function) for g in s.gates]
            for s in sts
        ]
        for name, sts in res.items()
    }


def _grow(g, seed=5):
    rng = np.random.default_rng(seed)
    st = State.init_inputs(8)
    while st.num_gates < g:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(bf.XOR, int(a), int(b), GATES)
    return st


# -------------------------------------------------------------------------
# Parity: fleet == serial per-job loop
# -------------------------------------------------------------------------


def test_fleet_bucket_resolution():
    assert fleet_bucket(1) == 1
    assert fleet_bucket(3) == 4
    assert fleet_bucket(8) == 8
    assert fleet_bucket(9) == 16
    # bucket respects mesh job shards
    assert fleet_bucket(3, shards=8) == 8
    assert fleet_bucket(FLEET_BUCKETS[-1] + 1, shards=4) % 4 == 0
    assert prev_fleet_bucket(8) == 4
    assert prev_fleet_bucket(1) is None


def test_fleet_parity_des8_vs_serial():
    """The acceptance gate: all 8 DES S-boxes, output bit 0, as one
    fleet — circuits bit-identical to the serial per-job loop."""
    names = [f"des_s{i}" for i in range(1, 9)]
    ctx_s = SearchContext(Options(seed=11, lut_graph=True, randomize=False))
    res_s = search_boxes_one_output(
        ctx_s, _boxes(names), 0, save_dir=None, log=lambda s: None,
        batched=False,
    )
    ctx_f = SearchContext(
        Options(seed=11, lut_graph=True, randomize=False, fleet=True)
    )
    res_f = search_boxes_one_output(
        ctx_f, _boxes(names), 0, save_dir=None, log=lambda s: None,
    )
    assert _sig(res_f) == _sig(res_s)
    for sts in res_f.values():
        assert sts  # every box solved


def test_fleet_device_dispatch_parity_and_merging():
    """Device-routed toy fleet: bit-identical to the serial loop, and
    the jobs' sweeps actually merged (one vmapped dispatch serves many
    submits)."""
    ctx_s = SearchContext(Options(**DEV))
    res_s = search_boxes_one_output(
        ctx_s, _toy_boxes(), 0, save_dir=None, log=lambda s: None,
        batched=False,
    )
    ctx_f = SearchContext(Options(fleet=True, **DEV))
    res_f = search_boxes_one_output(
        ctx_f, _toy_boxes(), 0, save_dir=None, log=lambda s: None,
        batched="fleet",
    )
    assert _sig(res_f) == _sig(res_s)
    st = ctx_f.stats
    assert st["fleet_submits"] > 0
    # Merging: strictly fewer device dispatches than sweep submissions.
    assert st["fleet_rounds"] < st["fleet_submits"]
    assert st["fleet_dispatches"] >= 1
    assert st["fleet_lanes"] >= 2 * st["fleet_dispatches"]


def test_fleet_ragged_done_masking(tmp_path):
    """Ragged fleet through the lockstep all-outputs driver: boxes
    finish at different rounds (ident3 completes via step-1 reuse,
    parmaj3 needs real gates), jobs retire mid-wave as their searches
    end — results bit-identical to the rendezvous-batched driver, which
    shares the seed discipline."""
    ident = np.zeros(256, dtype=np.uint8)
    ident[:8] = np.arange(8)
    boxes = lambda: [BoxJob("ident3", ident.copy(), 3)] + _toy_boxes(3)  # noqa: E731
    ctx_b = SearchContext(Options(**DEV))
    res_b = search_boxes_all_outputs(
        ctx_b, boxes(), save_dir=str(tmp_path / "b"), log=lambda s: None,
        batched=True,
    )
    ctx_f = SearchContext(Options(fleet=True, **DEV))
    res_f = search_boxes_all_outputs(
        ctx_f, boxes(), save_dir=str(tmp_path / "f"), log=lambda s: None,
    )
    assert _sig(res_f) == _sig(res_b)
    for name, sts in res_f.items():
        assert sts, f"{name}: incomplete"
    assert ctx_f.stats["fleet_rounds"] < ctx_f.stats["fleet_submits"]


def test_fleet_mesh_sharded_parity():
    """P("jobs")-sharded fleet (2-D mesh over the 8 virtual devices) is
    bit-identical to the unsharded fleet and to the serial loop."""
    from sboxgates_tpu.parallel import FleetPlan, make_fleet_mesh

    plan = FleetPlan(make_fleet_mesh())
    assert plan.n_job_shards >= 1
    ctx_s = SearchContext(Options(**DEV))
    res_s = search_boxes_one_output(
        ctx_s, _toy_boxes(4), 0, save_dir=None, log=lambda s: None,
        batched=False,
    )
    ctx_p = SearchContext(Options(fleet=True, **DEV), fleet_plan=plan)
    res_p = search_boxes_one_output(
        ctx_p, _toy_boxes(4), 0, save_dir=None, log=lambda s: None,
    )
    assert _sig(res_p) == _sig(res_s)
    assert ctx_p.stats["fleet_dispatches"] >= 1


def test_fleet_mesh_excludes_candidate_mesh():
    from sboxgates_tpu.parallel import FleetPlan, MeshPlan, make_fleet_mesh, make_mesh

    # Rejected at CONSTRUCTION (either form), so every driver behaves
    # identically — the orchestrator cannot silently fall back serial.
    with pytest.raises(ValueError):
        SearchContext(
            Options(seed=1), mesh_plan=MeshPlan(make_mesh()),
            fleet_plan=FleetPlan(make_fleet_mesh()),
        )
    with pytest.raises(ValueError):
        SearchContext(
            Options(seed=1, fleet=True), mesh_plan=MeshPlan(make_mesh())
        )
    # An explicit batched="fleet" on a plain mesh context is rejected by
    # the driver-level mode resolution.
    ctx = SearchContext(Options(seed=1), mesh_plan=MeshPlan(make_mesh()))
    with pytest.raises(ValueError):
        search_boxes_one_output(
            ctx, _toy_boxes(2), 0, save_dir=None, log=lambda s: None,
            batched="fleet",
        )


# -------------------------------------------------------------------------
# Warm shapes: (jobs_bucket, bucket)-keyed fleet kernels
# -------------------------------------------------------------------------


def _fleet_warm_ctx(monkeypatch, **kw):
    monkeypatch.setenv("SBG_WARMUP", "1")
    opt = dict(DEV, fleet=True)
    opt.update(kw)
    ctx = SearchContext(Options(**opt))
    assert ctx.warmer is not None and ctx.warmer.enabled
    return ctx


def test_fleet_bucket_crossing_zero_compiles(monkeypatch):
    """A warmed fleet crossing BOTH axes — the table bucket (64 -> 512)
    and the jobs bucket (4 -> 2, jobs retiring) — performs zero
    steady-state compiles: the (jobs_bucket, bucket) warm specs serve
    the dispatches with AOT executables."""
    ctx = _fleet_warm_ctx(monkeypatch, lut_graph=False)
    mask = tt.mask_table(8)
    st63 = _grow(63)
    t63 = st63.table(50).copy()
    try:
        # Entry wave: 4 jobs at bucket 64 — one fleet dispatch each job
        # (the target matches an existing gate, so each search is a
        # single gate_step submit).  Schedules the warm cross product
        # {bucket, next bucket} x {lanes, prev lanes}.
        res = run_fleet_circuits(
            ctx, [(st63.copy(), t63, mask) for _ in range(4)]
        )
        assert all(out == 50 for _, out in res)
        assert ctx.warmer.wait_idle(300), "warmer never went idle"
        ws = ctx.warmup_stats()
        assert ws["warm_failed"] == 0, ws
        assert ws["warm_compiled"] >= 4, ws

        st65 = _grow(65)
        t65 = st65.table(50).copy()
        # The eager per-node helpers (combo grid, validity arange) for
        # bucket 512 compile outside the guarded region: the guard gates
        # the DISPATCH path, which is what the fleet warms.
        ctx._node_operands(st65, t65, mask)
        # Steady state: run each crossing shape once (warm-served, but
        # each first entry to a (bucket, lanes) cell schedules ITS
        # successors on the background worker — those compiles must
        # drain before a process-wide zero-compile guard).
        run_fleet_circuits(ctx, [(st65.copy(), t65, mask) for _ in range(4)])
        run_fleet_circuits(ctx, [(st63.copy(), t63, mask) for _ in range(2)])
        assert ctx.warmer.wait_idle(300)
        h0 = ctx.stats["fleet_warm_hits"]
        with recompile_guard(allowed=0, label="fleet bucket crossing") as rep:
            # Gate-bucket crossing at held lanes.
            res = run_fleet_circuits(
                ctx, [(st65.copy(), t65, mask) for _ in range(4)]
            )
            assert all(out == 50 for _, out in res)
            # Jobs-bucket crossing (fleet shrank 4 -> 2) at the old
            # gate bucket — the diagonal was warmed too.
            res = run_fleet_circuits(
                ctx, [(st63.copy(), t63, mask) for _ in range(2)]
            )
            assert all(out == 50 for _, out in res)
        assert rep.compiles == 0
        assert ctx.stats["fleet_warm_hits"] >= h0 + 2
        assert ctx.warmup_stats().get("warm_aval_mismatches", 0) == 0
    finally:
        ctx.warmer.shutdown()


def test_fleet_lut_head_warm_hit(monkeypatch):
    """LUT-mode fleet: a warmed (jobs_bucket, bucket) set serves the
    fused head dispatch compile-free."""
    st0, target, mask = build_planted_lut5_small()
    ctx = _fleet_warm_ctx(monkeypatch)
    try:
        jobs = lambda: [(st0.copy(), target, mask) for _ in range(4)]  # noqa: E731
        res1 = run_fleet_circuits(ctx, jobs())
        assert all(out != NO_GATE for _, out in res1)
        assert ctx.warmer.wait_idle(300)
        ctx._node_operands(st0, target, mask)
        with recompile_guard(allowed=0, label="warmed lut fleet wave") as rep:
            res2 = run_fleet_circuits(ctx, jobs())
        assert rep.compiles == 0
        assert ctx.stats["fleet_warm_hits"] >= 1
        assert [o for _, o in res2] == [o for _, o in res1]
    finally:
        ctx.warmer.shutdown()


def test_fleet_registry_parity(monkeypatch):
    """Live fleet submissions must agree with the warm registry: every
    merged kernel's shared-argument tuple matches FLEET_SHARED (the
    table fleet_warm_specs enumerates from), and the dispatcher's warm
    key for each group is exactly a fleet_warm_specs key for that
    (g, lanes)."""
    recorded = []
    orig = FleetRendezvous._run_group

    def spy(self, key, entries):
        recorded.append((key, tuple(entries[0]["shared"]),
                         len(entries[0]["args"]),
                         max((e.get("g") or 0) for e in entries),
                         len(entries)))
        return orig(self, key, entries)

    monkeypatch.setattr(FleetRendezvous, "_run_group", spy)
    ctx = SearchContext(Options(fleet=True, **DEV))
    search_boxes_one_output(
        ctx, _toy_boxes(4), 0, save_dir=None, log=lambda s: None,
    )
    gctx = SearchContext(Options(fleet=True, **dict(DEV, lut_graph=False)))
    st = _grow(24)
    run_fleet_circuits(
        gctx, [(st.copy(), st.table(20).copy(), tt.mask_table(8))
               for _ in range(2)]
    )
    assert recorded, "no fleet groups dispatched"
    plans = {
        True: warmup.WarmPlan.from_context(ctx),
        False: warmup.WarmPlan.from_context(gctx),
    }
    seen = set()
    for key, shared, nargs, g, n in recorded:
        name = key[0]
        seen.add(name)
        if name in warmup.FLEET_SHARED:
            assert shared == warmup.FLEET_SHARED[name], name
        if name not in warmup.FLEET_SHARED or n < 2 or not g:
            continue
        lanes = fleet_bucket(n)
        plan = plans[name != "gate_step_stream"]
        specs = warmup.fleet_warm_specs(plan, g, lanes)
        keys = {k for k, *_ in specs}
        spec_sigs = {
            (k[1], k[2], k[4]) for k in keys
        }
        assert (name, key[1], lanes) in spec_sigs, (
            f"fleet dispatch {name} g={g} lanes={lanes} has no warm spec "
            "— live call sites and FLEET_SHARED/warm_specs drifted"
        )
    assert "lut_step_stream" in seen


# -------------------------------------------------------------------------
# The stacked lockstep step + fleet table cache
# -------------------------------------------------------------------------


def test_fleet_gate_step_done_masking():
    """The single-kernel [jobs, bucket, 8] lockstep sweep: per-job
    verdicts match the per-job kernel, retired lanes ride as masked
    no-op rows, and the stacked-table cache is content-keyed."""
    from sboxgates_tpu.search.fleet import fleet_gate_step

    ctx = SearchContext(Options(**dict(DEV, lut_graph=False)))
    sts = [_grow(20, seed=s) for s in range(3)]
    jobs = [
        (st, st.table(12).copy(), tt.mask_table(8)) for st in sts
    ]
    out = fleet_gate_step(ctx, jobs)
    assert out.shape[0] == 3
    for (st, t, m), row in zip(jobs, out):
        step, x0, _ = ctx.gate_step(st, t, m)
        assert int(row[0]) == step and int(row[1]) == x0
    # done-masking: retired lanes are zeroed, live lanes unchanged.
    out2 = fleet_gate_step(ctx, jobs, done=[False, True, False])
    assert (out2[1] == 0).all()
    assert (out2[0] == out[0]).all() and (out2[2] == out[2]).all()
    # stacked-table cache: same fleet content -> resident stack reused
    # (a retired lane contributes a stable placeholder digest, so
    # retirement does not churn the key); mutation always re-uploads.
    h0, m0 = ctx.fleet_stack.hits, ctx.fleet_stack.misses
    ctx.fleet_device_tables(sts, done=[False, True, False])
    ctx.fleet_device_tables(sts, done=[False, True, False])
    assert ctx.fleet_stack.hits >= h0 + 1
    m1 = ctx.fleet_stack.misses
    sts[0].add_gate(bf.XOR, 0, 1, GATES)
    ctx.fleet_device_tables(sts, done=[False, True, False])
    assert ctx.fleet_stack.misses == m1 + 1
    # Explicit lifecycle control drops the stacked buffers too.
    ctx.invalidate_device_tables()
    ctx.fleet_device_tables(sts, done=[False, True, False])
    assert ctx.fleet_stack.misses == m1 + 2


def test_fleet_lut_step_done_masking():
    """Stacked LUT node head (lut_step_stream): per-job verdict rows
    match the per-job fused head, retired lanes ride as zeroed no-op
    rows, and mixed static shape classes are rejected — same contract
    as fleet_gate_step."""
    from sboxgates_tpu.search.fleet import fleet_lut_step

    ctx = SearchContext(Options(**DEV))
    sts = [_grow(20, seed=s) for s in range(3)]
    jobs = [(st, st.table(12).copy(), tt.mask_table(8)) for st in sts]
    out = fleet_lut_step(ctx, jobs)
    assert out.shape == (3, 8)
    for (st, t, m), row in zip(jobs, out):
        np.testing.assert_array_equal(row, ctx.lut_step(st, t, m, []))
    out2 = fleet_lut_step(ctx, jobs, done=[True, False, True])
    assert (out2[0] == 0).all() and (out2[2] == 0).all()
    np.testing.assert_array_equal(out2[1], out[1])
    # Live jobs must share one (chunk3, chunk5, has5) class.
    mixed = jobs[:1] + [(_grow(60, seed=9), jobs[0][1], jobs[0][2])]
    with pytest.raises(ValueError, match="static shape class"):
        fleet_lut_step(ctx, mixed)
    # ...but a done lane's gate count doesn't constrain the class.
    out3 = fleet_lut_step(ctx, mixed, done=[False, True])
    np.testing.assert_array_equal(out3[0], out[0])
    assert (out3[1] == 0).all()


def test_fleet_gate_step_sharded_matches():
    from sboxgates_tpu.parallel import FleetPlan, make_fleet_mesh
    from sboxgates_tpu.search.fleet import fleet_gate_step

    ctx = SearchContext(Options(**dict(DEV, lut_graph=False)))
    ctx_p = SearchContext(
        Options(**dict(DEV, lut_graph=False)),
        fleet_plan=FleetPlan(make_fleet_mesh()),
    )
    sts = [_grow(20, seed=s) for s in range(3)]
    jobs = [(st, st.table(12).copy(), tt.mask_table(8)) for st in sts]
    a = fleet_gate_step(ctx, jobs)
    b = fleet_gate_step(ctx_p, jobs)
    np.testing.assert_array_equal(a, b)


def test_fleet_candidate_split_matches():
    """(jobs, candidates) device split inside the fleet mesh: the same
    stacked step under a (4, 2) split is bit-identical to the
    all-jobs (8, 1) split and to the unsharded dispatch."""
    from sboxgates_tpu.parallel import FleetPlan, make_fleet_mesh

    plan = FleetPlan(make_fleet_mesh(candidates=2))
    assert plan.n_candidate_shards == 2 and plan.n_job_shards >= 1
    assert "x2" in plan.describe()
    ctx = SearchContext(Options(**dict(DEV, lut_graph=False)))
    ctx_c = SearchContext(
        Options(**dict(DEV, lut_graph=False)), fleet_plan=plan
    )
    sts = [_grow(20, seed=s) for s in range(4)]
    jobs = [(st, st.table(12).copy(), tt.mask_table(8)) for st in sts]
    np.testing.assert_array_equal(
        fleet_gate_step(ctx, jobs), fleet_gate_step(ctx_c, jobs)
    )


# -------------------------------------------------------------------------
# Jobs-bucket ladder: stacked dispatch past the flat 32-lane cap
# -------------------------------------------------------------------------


def test_fleet_ladder_and_wave_routing():
    """The jobs-bucket ladder reaches the stacked rungs, and every
    public entry point routes oversized waves through the wave splitter
    — the old 'split into waves' ValueError fires only on the internal
    single-wave path (regression for the public-entry raise)."""
    assert FLEET_LADDER[: len(FLEET_BUCKETS)] == FLEET_BUCKETS
    assert fleet_bucket(33) == 64
    assert fleet_bucket(1000) == 1024
    assert prev_fleet_bucket(64) == 32
    assert STACKED_BUCKETS[0] > FLEET_BUCKETS[-1]

    ctx = SearchContext(Options(fleet=True, fleet_max_wave=2, **DEV))
    st, target, mask = build_planted_lut5_small()
    jobs = [(st.copy(), target, mask) for _ in range(5)]
    # Internal single-wave path: still raises past the cap.
    with pytest.raises(ValueError, match="split into waves"):
        _run_fleet_wave(ctx, jobs)
    # Public entry: splits into ceil(5/2)=3 waves and completes.
    res = run_fleet_circuits(ctx, [(s.copy(), t, m) for s, t, m in jobs])
    assert all(out != NO_GATE for _, out in res)

    # Driver-level entry (multibox) routes through the same splitter.
    ctx2 = SearchContext(Options(fleet=True, fleet_max_wave=2, **DEV))
    res2 = search_boxes_one_output(
        ctx2, _toy_boxes(3), 0, save_dir=None, log=lambda s: None,
    )
    assert all(sts for sts in res2.values())


def test_fleet_stacked_rendezvous_group():
    """A >32-job fleet wave dispatches its merged node sweeps through
    the STACKED wrapper — one device dispatch for the whole group, no
    32-lane slicing — with circuits identical to the serial loop."""
    ctx = SearchContext(Options(fleet=True, **dict(DEV, lut_graph=False)))
    st40 = [_grow(20, seed=s) for s in range(40)]
    jobs = [(st, st.table(12).copy(), tt.mask_table(8)) for st in st40]
    res = run_fleet_circuits(ctx, jobs)
    st = ctx.stats
    assert st["fleet_stacked_dispatches"] >= 1
    # The 40-lane group was ONE stacked dispatch (64-lane bucket), not
    # two 32-lane slices: every fleet dispatch is one compiled call.
    assert st["fleet_dispatches"] + st["fleet_singletons"] <= 2
    assert st["fleet_lanes"] >= 64 and st["fleet_submits"] == 40
    # Serial comparison: bit-identical per-job outcomes and circuits.
    ctx_s = SearchContext(Options(**dict(DEV, lut_graph=False)))
    from sboxgates_tpu.search.kwan import create_circuit

    for i, (nst, out) in enumerate(res):
        sst = _grow(20, seed=i)
        sout = create_circuit(
            ctx_s, sst, sst.table(12).copy(), tt.mask_table(8), []
        )
        assert sout == out
        assert [
            (g.type, g.in1, g.in2, g.in3, g.function) for g in nst.gates
        ] == [
            (g.type, g.in1, g.in2, g.in3, g.function) for g in sst.gates
        ]


# -------------------------------------------------------------------------
# Stacked streams: ragged-retirement property tests (pivot + 7-LUT)
# -------------------------------------------------------------------------


def _grow_lut7_job(seed):
    """16-gate mixed state with a planted LUT(LUT,LUT,·) target — small
    enough that lut_head_has7 holds (single-chunk 7-LUT space)."""
    rng = np.random.default_rng(seed)
    st = State.init_inputs(8)
    funs = [bf.AND, bf.OR, bf.XOR]
    while st.num_gates < 16:
        a, b = rng.choice(st.num_gates, size=2, replace=False)
        st.add_gate(funs[rng.integers(3)], int(a), int(b), GATES)
    outer = tt.eval_lut(0x96, st.table(3), st.table(5), st.table(9))
    middle = tt.eval_lut(0xE8, st.table(2), st.table(8), st.table(12))
    target = tt.eval_lut(0xCA, outer, middle, st.table(14))
    return st, target, tt.mask_table(8)


def test_fleet_lut7_stacked_ragged_parity():
    """Random done-mask patterns across jobs buckets: the stacked
    7-LUT step's per-lane verdicts are bit-identical to the per-job
    kernel's, retired lanes zeroed — the ragged-retirement property for
    the 7-LUT stacked stream."""
    ctx = SearchContext(Options(**DEV))
    jobs = [_grow_lut7_job(s) for s in range(5)]
    serial = [
        np.asarray(ctx.lut7_step(st, t, m, [])) for st, t, m in jobs
    ]
    assert any(int(v[0]) == 1 for v in serial)  # planted hits fire
    rng = np.random.default_rng(0)
    masks = [np.zeros(5, bool)] + [
        rng.random(5) < 0.5 for _ in range(3)
    ]
    for done in masks:
        for take in (5, 3):  # crosses the jobs bucket 8 -> 4
            d = list(done[:take])
            out = fleet_lut7_step(ctx, jobs[:take], done=d)
            for i in range(take):
                if d[i]:
                    assert (out[i] == 0).all()
                else:
                    np.testing.assert_array_equal(out[i], serial[i])


def test_fleet_pivot_stacked_ragged_parity():
    """Random done-mask patterns for the stacked pivot stream: per-lane
    verdict rows (including the planted HIT and its decode payload)
    bit-identical to the per-job pivot stream over the same tile
    window; retired lanes ride as zeroed no-ops."""
    from sboxgates_tpu.ops import sweeps
    from sboxgates_tpu.search import lut as L

    ctx = SearchContext(Options(**DEV))
    st, target, mask = build_planted_lut5()
    g = st.num_gates
    tl, th = L.pivot_tile_shape(g)
    ops = L.PivotOperands(
        g, tl, th, [], ctx.device_tables(st), target, mask,
        ctx.place_replicated, kernel_call=ctx.kernel_call,
    )
    _, w_tab, m_tab = sweeps.lut5_split_tables()
    jw = ctx.place_replicated(w_tab)
    jm = ctx.place_replicated(m_tab)

    def serial_window(start, end):
        return np.asarray(ctx.kernel_call(
            "lut5_pivot_stream",
            dict(tl=tl, th=th, tile_batch=L.pivot_tile_batch(),
                 pipeline=L.pivot_pipeline(), backend="xla"),
            (*ops.stream_args(), start, end, jw, jm, -1), g=g,
        ))

    # Window [16, 19) holds the planted hit (tile 18); [0, 3) does not.
    hit_v = serial_window(16, 19)
    miss_v = serial_window(0, 3)
    assert int(hit_v[0]) == 1 and int(miss_v[0]) == 0
    jobs = [(st.copy(), target.copy(), mask) for _ in range(3)]
    rng = np.random.default_rng(1)
    for done in [np.zeros(3, bool)] + [rng.random(3) < 0.5 for _ in range(2)]:
        d = list(done)
        out = fleet_pivot_step(ctx, jobs, done=d, start_t=16, t_limit=3)
        for i in range(3):
            expect = np.zeros(9, np.int32) if d[i] else hit_v
            np.testing.assert_array_equal(out[i], expect)
    out0 = fleet_pivot_step(ctx, jobs, done=[False, True, False], t_limit=3)
    np.testing.assert_array_equal(out0[0], miss_v)
    assert (out0[1] == 0).all()


def test_fleet_pivot_warm_crossing_zero_compiles(monkeypatch):
    """The (jobs_bucket, pivot_g_bucket) warm keys: a warmed stacked
    pivot fleet crossing EITHER axis — the pivot g-bucket (64 -> 96,
    tables 64 -> 512) or the jobs bucket (2 -> 1, jobs retiring) —
    performs zero steady-state compiles under a strict
    ``recompile_guard``: the stacked pivot executables are AOT-built by
    the warmer from ``fleet_warm_specs``."""
    # Narrow the warm enumeration to the pivot kernels so the
    # background sets compile within test time; the other heads' warm
    # coverage has its own gates above.
    monkeypatch.setattr(warmup, "FLEET_SHARED", {
        k: warmup.FLEET_SHARED[k]
        for k in ("pivot_pair_cells", "lut5_pivot_stream")
    })
    monkeypatch.setenv("SBG_WARMUP", "1")
    ctx = SearchContext(Options(fleet=True, **DEV))
    assert ctx.warmer is not None and ctx.warmer.enabled
    st50, t50, mask = build_planted_lut5()
    st70 = st50.copy()
    rng = np.random.default_rng(9)
    while st70.num_gates < 70:
        a, b = rng.choice(st70.num_gates, size=2, replace=False)
        st70.add_gate(bf.XOR, int(a), int(b), GATES)
    t70 = st70.table(60).copy()
    from sboxgates_tpu.search.lut import pivot_g_bucket

    assert pivot_g_bucket(st50.num_gates) == 64
    assert pivot_g_bucket(st70.num_gates) == 96
    jobs50 = lambda: [(st50.copy(), t50, mask) for _ in range(2)]  # noqa: E731
    jobs70 = lambda: [(st70.copy(), t70, mask) for _ in range(2)]  # noqa: E731
    try:
        # Entry: 2 lanes at pivot bucket 64 — schedules the stacked
        # warm cross product {g, next bucket entry} x {2, 1 lanes},
        # including the next PIVOT bucket's stream avals.
        base = fleet_pivot_step(ctx, jobs50(), t_limit=1)
        assert ctx.warmer.wait_idle(600), "warmer never went idle"
        ws = ctx.warmup_stats()
        assert ws["warm_failed"] == 0, ws
        assert ws["warm_compiled"] >= 4, ws
        # Run each crossing shape once (warm-served; first entries
        # schedule THEIR successors, which must drain before a
        # process-wide zero-compile guard).
        fleet_pivot_step(ctx, jobs70(), t_limit=1)
        fleet_pivot_step(ctx, jobs50()[:1], t_limit=1)
        assert ctx.warmer.wait_idle(600)
        h0 = ctx.stats["warm_hits"]
        with recompile_guard(allowed=0, label="stacked pivot crossing") as rep:
            # Pivot-g-bucket crossing at held lanes (64 -> 96).
            out70 = fleet_pivot_step(ctx, jobs70(), t_limit=1)
            # Jobs-bucket crossing (2 -> 1) at the old pivot bucket.
            out50 = fleet_pivot_step(ctx, jobs50()[:1], t_limit=1)
        assert rep.compiles == 0
        assert out70.shape == (2, 9) and out50.shape == (1, 9)
        np.testing.assert_array_equal(out50[0], base[0])
        assert ctx.stats["warm_hits"] >= h0 + 4
        assert ctx.warmup_stats().get("warm_aval_mismatches", 0) == 0
    finally:
        ctx.warmer.shutdown()


def test_fleet_staged_lut7_stream_merge():
    """The staged 7-LUT collection path (feasible_stream — a pytree-
    output kernel) folds into the fleet axis: two concurrent jobs'
    stage-A streams merge through the rendezvous and the found
    decompositions are identical to the serial search."""
    from sboxgates_tpu.search.batched import RestartContext
    from sboxgates_tpu.search.lut import lut7_search

    st, target, mask = build_planted_lut7()
    ctx_s = SearchContext(Options(**DEV))
    expect = lut7_search(ctx_s, st.copy(), target, mask, [])
    assert expect is not None

    ctx = SearchContext(Options(fleet=True, **DEV))
    rdv = FleetRendezvous(2, warmer=None)
    results = [None, None]
    errors = []

    def worker(i):
        try:
            rctx = RestartContext(ctx, 100 + i, rdv)
            results[i] = lut7_search(rctx, st.copy(), target, mask, [])
        except BaseException as e:  # surfaced after join
            errors.append(e)
        finally:
            rdv.finish()

    threads = [
        threading.Thread(target=worker, args=(i,)) for i in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    assert results[0] == expect and results[1] == expect
    # The stage-A feasibility streams (and the stage-B solves) actually
    # merged: at least one multi-lane fleet dispatch happened.
    assert rdv.stats["fleet_dispatches"] >= 1
    assert rdv.stats["batched_rows"] >= 2


def test_fleet_workers_joined_when_start_fails(monkeypatch):
    """Regression (jaxlint R15): a mid-loop ``Thread.start()`` failure
    inside a fleet wave joins the already-running workers before the
    exception propagates (same contract as the batched driver)."""
    import time

    from sboxgates_tpu.search import kwan

    ctx = SearchContext(Options(fleet=True, **DEV))
    st, target, mask = build_planted_lut5_small()
    jobs = [(st.copy(), target, mask) for _ in range(2)]

    first_worker_finished = threading.Event()

    def slow_create(rctx, nst, t, m, gates):
        time.sleep(0.2)
        first_worker_finished.set()
        return NO_GATE

    monkeypatch.setattr(kwan, "create_circuit", slow_create)

    real_start = threading.Thread.start
    started = []

    def flaky_start(self):
        if started:
            raise RuntimeError("can't start new thread")
        started.append(self)
        real_start(self)

    monkeypatch.setattr(threading.Thread, "start", flaky_start)
    with pytest.raises(RuntimeError, match="can't start new thread"):
        _run_fleet_wave(ctx, jobs)
    assert first_worker_finished.is_set()
    assert not started[0].is_alive()
