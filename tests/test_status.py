"""Live introspection + perf-drift gates (ISSUE 12): the /status
endpoint, the heartbeat watcher, preemption observability, and
``bench.py --check``.

Coverage map:

- StatusServer in-process: schema-stable JSON snapshot, coverage/ETA
  derivation, request counting, clean shutdown with no dangling thread
  (``test_status_server_*``).
- The acceptance shape: a subprocess CLI run with ``--status-port 0``
  reports the bound port via the heartbeat start line, serves /status
  mid-search, and the polled counters reconcile (monotone) with the
  final ``metrics.json`` written at teardown
  (``test_status_endpoint_subprocess``).
- Preemption: a SIGTERM'd run leaves a flight-recorder dump AND a
  terminal heartbeat record + metrics.json — the managed-pod grace
  window artifact (``test_sigterm_dumps_flight_and_final_heartbeat``).
- Watcher: ``python -m sboxgates_tpu.telemetry.watch DIR --once``
  renders a dead run's last record from the heartbeat JSONL alone
  (``test_watch_renders_dead_run``).
- Drift gate: ``bench.py --check multiround`` re-runs the cheapest
  bench section and exits 0 against the committed baseline — the gate
  itself is exercised on every tier-1 run
  (``test_bench_check_multiround_gate``).
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from sboxgates_tpu.telemetry import metrics as tmetrics
from sboxgates_tpu.telemetry import status as tstatus

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
SBOXES = os.path.join(REPO, "sboxes")

#: Top-level /status keys (schema stability: additions bump this test
#: AND tstatus.STATUS_SCHEMA consciously, never by accident).
STATUS_KEYS = {
    "schema", "time_unix", "uptime_s", "counters", "histograms",
    "coverage", "attribution",
}


def _get_status(port, timeout=10):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/status", timeout=timeout
    ) as resp:
        return json.load(resp)


# -------------------------------------------------------------------------
# in-process server
# -------------------------------------------------------------------------


def test_status_server_snapshot_schema_and_shutdown():
    reg = tmetrics.context_registry()
    reg.inc("device_dispatches", 3)
    reg.inc("lut5_candidates", 1000)
    reg.observe("dispatch_latency_s[lut5_stream]", 0.01)
    srv = tstatus.StatusServer(
        reg, port=0, extra={"engine": lambda: {"fleet": False}},
        gates_fn=lambda: 24,
    ).start()
    try:
        doc = _get_status(srv.port)
        assert set(doc) == STATUS_KEYS | {"engine"}
        assert doc["schema"] == tstatus.STATUS_SCHEMA
        assert doc["counters"]["device_dispatches"] == 3
        # histogram quantiles ride the registry snapshot
        h = doc["histograms"]["dispatch_latency_s[lut5_stream]"]
        assert {"p50", "p90", "p99"} <= set(h)
        # coverage: examined vs |C(g, k)| with derived ETA
        cov = doc["coverage"]["lut5_candidates"]
        assert cov["examined"] == 1000
        assert cov["current_space"] == 42504  # C(24, 5)
        assert cov["eta_current_space_s"] > 0
        assert doc["engine"] == {"fleet": False}
        # requests are counted through the declared registry
        assert reg["status_requests"] == 1
        assert reg.undeclared() == set()
        # 404 off-path
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/nope", timeout=5
            )
    finally:
        srv.shutdown()
    assert not any(
        t.name == "sbg-status" for t in threading.enumerate()
    ), "status server thread survived shutdown"
    # idempotent
    srv.shutdown()


def test_status_provider_failure_degrades_to_error_note():
    reg = tmetrics.MetricsRegistry(declared=None)

    def boom():
        raise RuntimeError("provider died")

    srv = tstatus.StatusServer(reg, port=0, extra={"bad": boom}).start()
    try:
        doc = _get_status(srv.port)
        assert "error" in doc["bad"]
        assert "counters" in doc  # rest of the snapshot intact
    finally:
        srv.shutdown()


def test_coverage_derivation_edge_cases():
    # No gate count -> examined/rate only; g below k -> no space row.
    cov = tstatus.coverage({"lut5_candidates": 10}, uptime_s=2.0)
    assert cov["lut5_candidates"]["examined"] == 10
    assert cov["lut5_candidates"]["rate_per_s"] == 5.0
    assert "current_space" not in cov["lut5_candidates"]
    cov = tstatus.coverage({"lut7_candidates": 5}, uptime_s=1.0, g=4)
    assert "current_space" not in cov["lut7_candidates"]  # g < k
    cov = tstatus.coverage({}, uptime_s=1.0)
    assert cov == {}


# -------------------------------------------------------------------------
# subprocess acceptance shapes (status endpoint, SIGTERM)
# -------------------------------------------------------------------------


def _spawn_search(outdir, extra_args=()):
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", SBG_WARMUP="0")
    return subprocess.Popen(
        [
            sys.executable, "-m", "sboxgates_tpu",
            os.path.join(SBOXES, "crypto1_fa.txt"),
            "--seed", "7", "-o", "0",
            # Effectively unbounded: the test decides when the run ends
            # (poll + SIGTERM); each restart iteration returns to Python
            # so signals are handled promptly.
            "-i", "1000000",
            "--output-dir", str(outdir),
            "--metrics-interval", "300",
            *extra_args,
        ],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True,
    )


def _wait_for_start_line(outdir, proc, timeout=180):
    """The heartbeat start line (carries the run config)."""
    path = os.path.join(outdir, "telemetry.jsonl")
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise AssertionError(
                f"search exited early rc={proc.returncode}: "
                f"{proc.stderr.read()[-2000:]}"
            )
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("kind") == "start":
                        return rec
        time.sleep(0.2)
    raise AssertionError("no heartbeat start line within timeout")


def _read_jsonl(path):
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out


@pytest.fixture(scope="module")
def sigterm_run(tmp_path_factory):
    """ONE subprocess search serving both acceptance shapes (suite-time
    budget: the tier-1 window is tight, and the status poll and the
    SIGTERM artifacts are observations of the same run): spawn with
    --status-port 0, poll /status mid-search, SIGTERM, collect."""
    outdir = tmp_path_factory.mktemp("status") / "run"
    proc = _spawn_search(outdir, ("--status-port", "0"))
    doc = None
    try:
        start = _wait_for_start_line(outdir, proc)
        port = start["config"]["status_port"]
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                doc = _get_status(port, timeout=5)
                break
            except OSError:
                time.sleep(0.2)
        # Give the search a beat so the final snapshot strictly
        # dominates the polled one on at least one counter.
        time.sleep(0.3)
    finally:
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=120)
    return {
        "outdir": outdir,
        "start": start,
        "status": doc,
        "returncode": proc.returncode,
    }


def test_status_endpoint_subprocess(sigterm_run):
    """--status-port 0: the bound port rides the heartbeat start
    config; /status serves mid-search; polled counters reconcile
    (monotone) with the final metrics.json written at teardown."""
    port = sigterm_run["start"]["config"]["status_port"]
    assert isinstance(port, int) and port > 0
    doc = sigterm_run["status"]
    assert doc is not None, "endpoint never answered"
    assert STATUS_KEYS | {"engine"} <= set(doc)
    assert doc["schema"] == tstatus.STATUS_SCHEMA
    assert doc["engine"]["lut_graph"] is False
    outdir = sigterm_run["outdir"]
    snap_path = outdir / "metrics.json"
    assert snap_path.exists(), os.listdir(outdir)
    final = json.load(open(snap_path))
    # Counter parity: every counter the live snapshot showed exists in
    # the final snapshot at an equal-or-later value (counters are
    # monotone).
    for name, v in doc["counters"].items():
        assert final["counters"].get(name, 0) >= v, name
    assert "attribution" in final


def test_sigterm_dumps_flight_and_final_heartbeat(sigterm_run):
    """The preemption satellite: managed pods deliver SIGTERM before
    the kill; the grace-window handler must leave a flight dump and a
    terminal heartbeat record (plus the metrics.json snapshot), then
    exit with the conventional killed-by-SIGTERM status."""
    assert sigterm_run["returncode"] == -signal.SIGTERM
    outdir = sigterm_run["outdir"]
    dumps = list(outdir.glob("flight-rank00-*.json"))
    assert dumps, os.listdir(outdir)
    doc = json.load(open(dumps[0]))
    assert doc["reason"] == "signal:SIGTERM"
    lines = _read_jsonl(outdir / "telemetry.jsonl")
    kinds = [ln["kind"] for ln in lines]
    assert kinds[0] == "start"
    assert "incident:signal:SIGTERM" in kinds
    assert kinds[-1] == "final"  # the forced final line made it out
    assert (outdir / "metrics.json").exists()


# -------------------------------------------------------------------------
# watcher
# -------------------------------------------------------------------------


def test_watch_renders_dead_run(tmp_path):
    """The watcher works on runs it didn't start and on dead runs: it
    reads only the heartbeat JSONL."""
    from sboxgates_tpu.telemetry.heartbeat import Heartbeat

    reg = tmetrics.context_registry()
    reg.inc("device_dispatches", 42)
    reg.observe("dispatch_latency_s[lut5_stream]", 0.02)
    hb = Heartbeat(reg, str(tmp_path), interval_s=0, rank=0).start()
    hb.stop(snapshot=False)
    proc = subprocess.run(
        [
            sys.executable, "-m", "sboxgates_tpu.telemetry.watch",
            str(tmp_path), "--once",
        ],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, proc.stderr
    assert "terminal record" in proc.stdout
    assert "device_dispatches" in proc.stdout
    assert "42" in proc.stdout
    assert "dispatch_latency_s" in proc.stdout  # quantile row rendered


def test_watch_tail_follows_appends(tmp_path):
    from sboxgates_tpu.telemetry import watch as twatch

    path = tmp_path / "telemetry.jsonl"
    path.write_text(json.dumps({"kind": "start", "seq": 0}) + "\n")
    tail = twatch.Tail(str(path), poll_s=0.05).start()
    try:
        first = tail.records.get(timeout=5)
        assert first["kind"] == "start"
        with open(path, "a") as f:
            f.write(json.dumps({"kind": "beat", "seq": 1}) + "\n")
        second = tail.records.get(timeout=5)
        assert second["seq"] == 1
    finally:
        tail.stop()
    assert not any(
        t.name == "sbg-watch-tail" for t in threading.enumerate()
    )


def test_watch_missing_dir_fails_cleanly(tmp_path):
    from sboxgates_tpu.telemetry import watch as twatch

    assert twatch.main([str(tmp_path / "nope"), "--once"]) == 1


# -------------------------------------------------------------------------
# perf-drift gate
# -------------------------------------------------------------------------


def test_bench_check_multiround_gate():
    """The drift gate gating itself: the cheapest bench section re-runs
    against its committed baseline on every tier-1 pass, so a change
    that breaks the 1/N dispatch ratio (or the comparator) fails here."""
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", SBG_BENCH_SMOKE="1", SBG_WARMUP="0")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--check", "multiround"],
        env=env, capture_output=True, text=True, timeout=600, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["ok"] is True
    assert doc["regressions"] == 0
    gated = {(g["metric"], g["field"]) for g in doc["gates"]}
    assert ("device_rounds_dispatch_ratio", "value") in gated


def test_bench_check_unknown_section_errors(capsys):
    # In-process (bench is already importable in the test process): the
    # comparator refuses unknown sections with exit code 2.
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    assert bench.bench_check(["nonesuch"]) == 2
    out = json.loads(capsys.readouterr().out)
    assert "unknown section" in out["error"]
    assert "multiround" in out["known"]
