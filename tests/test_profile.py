"""Per-phase profiler: unit behavior and search integration.

The reference has no tracing subsystem (SURVEY §5); the TPU build adds
self-time phase timers threaded through every sweep driver.
"""

import os
import time

from sboxgates_tpu.graph.state import State
from sboxgates_tpu.search import (
    Options,
    SearchContext,
    generate_graph_one_output,
    make_targets,
)
from sboxgates_tpu.utils.profile import PhaseProfiler
from sboxgates_tpu.utils.sbox import load_sbox

DATA = os.path.join(os.path.dirname(__file__), "data")


def test_self_time_excludes_children():
    prof = PhaseProfiler()
    t0 = time.perf_counter()
    with prof.phase("outer"):
        time.sleep(0.02)
        with prof.phase("inner"):
            time.sleep(0.05)
    wall = time.perf_counter() - t0
    assert prof.calls["outer"] == 1
    assert prof.calls["inner"] == 1
    assert prof.seconds["inner"] >= 0.05
    # Structural property (robust to scheduler jitter): self-times are
    # additive — outer self + inner self ≈ total wall, so outer self
    # excludes the inner sleep.
    assert prof.seconds["outer"] <= wall - prof.seconds["inner"] + 0.001


def test_reentrant_phase_is_additive():
    prof = PhaseProfiler()

    def recurse(depth):
        with prof.phase("rec"):
            time.sleep(0.01)
            if depth:
                recurse(depth - 1)

    t0 = time.perf_counter()
    recurse(3)
    wall = time.perf_counter() - t0
    assert prof.calls["rec"] == 4
    # Self-times sum to the wall spent inside, not 4x it (robust bound:
    # the whole call tree ran once, so self-time can't exceed its wall).
    assert 0.04 <= prof.seconds["rec"] <= wall + 0.001


def test_threaded_phases_stay_sane():
    """Restart threads share one profiler: per-thread stacks must keep
    self-times non-negative and additive."""
    import threading

    prof = PhaseProfiler()

    def worker():
        for _ in range(20):
            with prof.phase("outer"):
                with prof.phase("inner"):
                    time.sleep(0.001)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert prof.calls["outer"] == 80
    assert prof.calls["inner"] == 80
    assert prof.seconds["outer"] >= 0
    assert prof.seconds["inner"] >= 0.08


def test_disabled_profiler_records_nothing():
    prof = PhaseProfiler(enabled=False)
    with prof.phase("x"):
        pass
    assert prof.seconds == {}


def test_report_formats_and_ranks():
    prof = PhaseProfiler()
    prof.add("slow", 2.0, calls=3)
    prof.add("fast", 0.5)
    text = prof.report({"slow_candidates": 1000})
    lines = text.splitlines()
    assert lines[1].startswith("slow")
    assert "cand/s" in lines[1]
    assert lines[2].startswith("fast")
    assert lines[-1].startswith("total")


def test_search_populates_phases():
    """A real LUT search must record the sweep phases with nonzero time."""
    sbox, n = load_sbox(os.path.join(DATA, "des_s1.txt"))
    targets = make_targets(sbox)
    ctx = SearchContext(Options(seed=3, lut_graph=True))
    st = State.init_inputs(n)
    results = generate_graph_one_output(
        ctx, st, targets, 1, save_dir=None, log=lambda s: None
    )
    assert results
    snap = ctx.prof.snapshot()
    # LUT mode runs the native engine when available (whole recursion in
    # one phase), else the fused head per node — native on the host when
    # available, otherwise the device dispatch.
    if ctx.uses_native_engine(results[-1]):
        head = "lut_engine_native"
    elif ctx.uses_native_step(results[-1]):
        head = "lut_step_native"
    else:
        head = "lut_step"

    assert snap[head][0] > 0 and snap[head][1] >= 1
    assert snap["kwan_host"][0] > 0
    # Phases appear in the report with the candidate-rate column.
    assert head in ctx.prof.report(ctx.stats)


def test_heartbeat_throttled(capsys, monkeypatch):
    """heartbeat() prints a progress line at most once per period, only
    at verbosity >= 2; the throttle is RUN-level — RestartContext views
    share it by reference, so concurrent branches can't each print."""
    import time as _time

    from sboxgates_tpu.graph.state import State
    from sboxgates_tpu.search import Options, SearchContext
    from sboxgates_tpu.search.batched import Rendezvous, RestartContext

    clock = {"t": 1000.0}
    monkeypatch.setattr(_time, "monotonic", lambda: clock["t"])

    st = State.init_inputs(4)
    ctx = SearchContext(Options(verbosity=2, heartbeat_s=60.0))
    ctx.stats["lut5_candidates"] = 12345
    ctx.heartbeat(st)  # arms; silent
    clock["t"] += 30
    ctx.heartbeat(st)  # mid-period; silent
    assert capsys.readouterr().out == ""
    clock["t"] += 31
    ctx.heartbeat(st)  # past the period; prints
    out = capsys.readouterr().out
    assert "[ hb ]" in out and "steps=3" in out and "G=4" in out
    ctx.heartbeat(st)  # re-armed; silent again
    assert capsys.readouterr().out == ""

    # A RestartContext view (mux branch / threaded engine service)
    # shares the run-level throttle: its call right after the base's
    # beat stays silent, and when the period passes it prints the
    # RUN-level step count (5 calls so far + its own).
    view = RestartContext(ctx, 7, Rendezvous(1))
    view.heartbeat(st)
    assert capsys.readouterr().out == ""
    clock["t"] += 61
    view.heartbeat(st)
    out = capsys.readouterr().out
    assert "steps=6" in out

    quiet = SearchContext(Options(verbosity=1, heartbeat_s=60.0))
    quiet.heartbeat(st)
    clock["t"] += 120
    quiet.heartbeat(st)
    assert capsys.readouterr().out == ""
