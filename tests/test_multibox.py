"""Multi-S-box and permutation-sweep driver tests (BASELINE configs 4-5;
reference counterpart: one process per box / per -p value,
sboxgates.c:661-688, 1021-1031)."""

import os

import numpy as np
import pytest

from sboxgates_tpu.core import ttable as tt
from sboxgates_tpu.graph.state import NO_GATE
from sboxgates_tpu.search import Options, SearchContext
from sboxgates_tpu.search.multibox import (
    BoxJob,
    load_box_jobs,
    permute_sweep_jobs,
    permuted_box,
    search_boxes_all_outputs,
    search_boxes_one_output,
)
from sboxgates_tpu.utils.sbox import load_sbox

SBOXES = os.path.join(os.path.dirname(__file__), "..", "sboxes")


def _boxes(names, permute=0):
    return load_box_jobs(
        [os.path.join(SBOXES, f"{n}.txt") for n in names], permute
    )


def _assert_realizes(box, st, output):
    gid = st.outputs[output]
    assert gid != NO_GATE
    assert bool(
        tt.eq_mask(st.table(gid), box.targets[output], box.mask)
    ), f"{box.name} output {output} not realized"


def test_auto_batched_defaults():
    """batched=None resolution: multi-box sweeps batch, permutation
    sweeps run the serial loop (the measured default —
    permute_sweep_des_s1_p64: host-routed jobs have no dispatches to
    merge), and an explicit batched=True overrides it."""
    from sboxgates_tpu.search.multibox import _auto_batched

    sbox, n = load_sbox(os.path.join(SBOXES, "des_s1.txt"))
    ctx = SearchContext(Options(seed=1))
    multi = _boxes(["des_s1", "des_s2"])
    sweep = permute_sweep_jobs(sbox, n)
    assert _auto_batched(ctx, None, multi) is True
    assert _auto_batched(ctx, None, sweep) is False
    assert _auto_batched(ctx, True, sweep) is True
    assert _auto_batched(ctx, False, multi) is False


def test_permuted_box_is_input_xor():
    sbox, n = load_sbox(os.path.join(SBOXES, "des_s1.txt"))
    p = 0b101101
    perm = permuted_box(sbox, n, p)
    for i in range(1 << n):
        assert perm[i] == sbox[i ^ p]
    from sboxgates_tpu.utils.sbox import SboxError

    with pytest.raises(SboxError):
        permuted_box(sbox, n, 1 << n)


def test_des_s2_s8_tables_are_standard():
    """Every DES S-box row (row-major 4x16 layout, same as the
    reference's des_s1.txt) must be a permutation of 0..15 — the FIPS
    46-3 structural invariant."""
    for i in range(1, 9):
        sbox, n = load_sbox(os.path.join(SBOXES, f"des_s{i}.txt"))
        assert n == 6
        tab = sbox[:64].reshape(4, 16)
        for row in tab:
            assert sorted(row.tolist()) == list(range(16))


@pytest.mark.parametrize("batched", [False, True])
def test_multibox_one_output(batched):
    """DES S1+S2+S3 LUT search, one output: every box gets a valid
    circuit in both execution modes."""
    boxes = _boxes(["des_s1", "des_s2", "des_s3"])
    ctx = SearchContext(Options(seed=11, lut_graph=True))
    res = search_boxes_one_output(
        ctx, boxes, 0, save_dir=None, log=lambda s: None, batched=batched
    )
    for box in boxes:
        states = res[box.name]
        assert states, f"{box.name}: nothing found"
        for st in states:
            _assert_realizes(box, st, 0)


def test_multibox_one_output_bad_bit():
    boxes = _boxes(["des_s1"])  # 4 outputs
    ctx = SearchContext(Options(seed=1))
    with pytest.raises(ValueError):
        search_boxes_one_output(
            ctx, boxes, 7, save_dir=None, log=lambda s: None, batched=False
        )


def test_multibox_all_outputs_lockstep(tmp_path):
    """Full-graph lockstep beam over two boxes with different output
    counts and round depths (3-bit identity completes via step-1 reuse;
    parity/majority needs real gates): all outputs of both realized,
    checkpoints in per-box subdirectories, the faster box drops out of
    later rounds.  Tiny 3-input boxes keep the per-round thread batches
    small — the full-size regime is bench.py's job."""
    ident = np.zeros(256, dtype=np.uint8)
    ident[:8] = np.arange(8)
    pm = np.zeros(256, dtype=np.uint8)
    for i in range(8):
        x0, x1, x2 = i & 1, (i >> 1) & 1, (i >> 2) & 1
        parity = x0 ^ x1 ^ x2
        major = (x0 + x1 + x2) >= 2
        pm[i] = parity | (major << 1)
    boxes = [BoxJob("ident3", ident, 3), BoxJob("parmaj3", pm, 3)]
    ctx = SearchContext(Options(seed=7))
    res = search_boxes_all_outputs(
        ctx, boxes, save_dir=str(tmp_path), log=lambda s: None, batched=True
    )
    for box in boxes:
        states = res[box.name]
        assert states, f"{box.name}: incomplete"
        for output in range(box.n_out):
            _assert_realizes(box, states[0], output)
        assert (tmp_path / box.name).is_dir()
        assert list((tmp_path / box.name).glob("*.xml"))


def test_permute_sweep_targets():
    """Each sweep job's targets are the permuted box's targets, and a
    circuit found for permutation p realizes the p-permuted function."""
    sbox, n = load_sbox(os.path.join(SBOXES, "crypto1_fa.txt"))
    jobs = permute_sweep_jobs(sbox, n)
    assert len(jobs) == 1 << n
    assert jobs[5].name == "p05"
    ctx = SearchContext(Options(seed=3))
    res = search_boxes_one_output(
        ctx, jobs[:4], 0, save_dir=None, log=lambda s: None, batched=True
    )
    for box in jobs[:4]:
        states = res[box.name]
        assert states
        _assert_realizes(box, states[0], 0)


def test_multibox_under_mesh_serial():
    """Config 4 under --mesh: jobs run serially through the mesh-sharded
    engine (auto batched=False); every box still gets a verified
    circuit."""
    from sboxgates_tpu.parallel import MeshPlan, make_mesh

    boxes = _boxes(["crypto1_fa", "crypto1_fb"])
    ctx = SearchContext(
        Options(seed=5, lut_graph=True), mesh_plan=MeshPlan(make_mesh())
    )
    res = search_boxes_one_output(
        ctx, boxes, 0, save_dir=None, log=lambda s: None
    )
    for box in boxes:
        states = res[box.name]
        assert states, f"{box.name}: nothing found"
        _assert_realizes(box, states[0], 0)


def test_multibox_mesh_guard():
    """Explicit batched=True under a mesh is rejected (host threads
    cannot share GSPMD-owned devices)."""
    from sboxgates_tpu.parallel import MeshPlan, make_mesh

    ctx = SearchContext(Options(seed=1), mesh_plan=MeshPlan(make_mesh()))
    boxes = [BoxJob("id", np.arange(256, dtype=np.uint8), 8)]
    with pytest.raises(ValueError):
        search_boxes_one_output(
            ctx, boxes, 0, save_dir=None, log=lambda s: None, batched=True
        )


def test_cli_multibox_contract(tmp_path, monkeypatch):
    """CLI validation: multiple inputs reject -c/-g; --permute-sweep
    rejects -p and multiple inputs; a real 2-box run writes per-box
    subdirectories."""
    from sboxgates_tpu.cli import main

    s1 = os.path.join(SBOXES, "des_s1.txt")
    s2 = os.path.join(SBOXES, "des_s2.txt")
    assert main(["-c", s1, s2]) != 0
    assert main(["-g", "x.xml", s1, s2]) != 0
    assert main(["--permute-sweep", "-p", "3", s1]) != 0
    assert main(["--permute-sweep", s1, s2]) != 0
    assert main(["--shard-sweep", "-o", "0", s1]) != 0  # nothing to shard
    monkeypatch.chdir(tmp_path)
    rc = main(["-o", "0", "-i", "1", "-l", "--seed", "2",
               "--output-dir", str(tmp_path), s1, s2])
    assert rc == 0
    assert list((tmp_path / "des_s1").glob("*.xml"))
    assert list((tmp_path / "des_s2").glob("*.xml"))


def test_process_slice_single_process():
    """Single process: the slice is the whole list (identity)."""
    from sboxgates_tpu.search.multibox import process_slice

    boxes = _boxes(["des_s1", "des_s2"])
    assert [b.name for b in process_slice(boxes)] == ["des_s1", "des_s2"]
