"""Per-rule jaxlint coverage over the fixture snippets.

Every rule is demonstrated by a violation fixture (exact rule IDs and
line numbers asserted) with a clean twin that must scan empty; the
suppression fixture locks in the inline-ignore syntax and the
mandatory-reason enforcement.  Fixtures are read as text, never
imported.
"""

import os

import pytest

from sboxgates_tpu.analysis import JaxlintConfig, lint_source
from sboxgates_tpu.analysis.rules import SUPPRESSION_RULE

FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")


def lint_fixture(name, **kwargs):
    path = os.path.join(FIXTURES, name)
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    # hot=True so R2 applies to fixture paths outside the configured
    # hot-module globs
    return lint_source(source, name, JaxlintConfig(), hot=True, **kwargs)


def found(report):
    return [(f.rule, f.line) for f in report.findings]


VIOLATIONS = {
    "r1_violation.py": [("R1", 22), ("R1", 27), ("R1", 33)],
    "r2_violation.py": [
        ("R2", 11),
        ("R2", 20),
        ("R2", 21),
        ("R2", 28),
        ("R2", 35),
    ],
    "r3_violation.py": [("R3", 15), ("R3", 23), ("R3", 29)],
    "r4_violation.py": [("R4", 13), ("R4", 14), ("R4", 19)],
    "r5_violation.py": [("R5", 9), ("R5", 18)],
}


@pytest.mark.parametrize("name,expected", sorted(VIOLATIONS.items()))
def test_violation_fixture_exact_findings(name, expected):
    assert found(lint_fixture(name)) == expected


@pytest.mark.parametrize(
    "name",
    ["r1_clean.py", "r2_clean.py", "r3_clean.py", "r4_clean.py", "r5_clean.py"],
)
def test_clean_twin_scans_empty(name):
    report = lint_fixture(name)
    assert found(report) == []
    assert report.suppressed == []


def test_rule_messages_name_the_hazard():
    messages = {f.rule: f.message for f in lint_fixture("r1_violation.py").findings}
    assert "recompile" in messages["R1"] or "compile" in messages["R1"]
    r2 = lint_fixture("r2_violation.py").findings[0]
    assert "loop" in r2.message and "hot" in r2.message


def test_suppression_with_reason_suppresses():
    report = lint_fixture("suppressions.py")
    # probe_a (same-line form) and probe_b (standalone-comment form) are
    # suppressed; both retain the finding in the suppressed list
    assert [(f.rule, f.line) for f in report.suppressed] == [
        ("R5", 7),
        ("R5", 15),
    ]


def test_reasonless_and_unknown_rule_suppressions_do_not_suppress():
    report = lint_fixture("suppressions.py")
    got = found(report)
    # probe_c: reason missing -> R5 stays, plus a SUP finding
    assert ("R5", 22) in got and (SUPPRESSION_RULE, 22) in got
    # probe_d: unknown rule id -> R5 stays, plus a SUP finding
    assert ("R5", 29) in got and (SUPPRESSION_RULE, 29) in got
    # and nothing else leaks through
    assert len(got) == 4


def test_unused_suppression_is_a_finding():
    """A well-formed marker whose finding no longer fires is itself
    reported (the deferred unused-suppression rule)."""
    report = lint_fixture("unused_suppression.py")
    got = found(report)
    # probe_stale (same-line) and probe_stale_standalone: no R5 finding
    # on the marked lines -> the markers are stale
    assert (SUPPRESSION_RULE, 7) in got
    assert (SUPPRESSION_RULE, 14) in got
    # probe_partial: R5 fires (and stays suppressed) but R3 never did —
    # the marker is flagged for its unused half only
    assert (SUPPRESSION_RULE, 22) in got
    assert [(f.rule, f.line) for f in report.suppressed] == [("R5", 22)]
    assert len(got) == 3
    msgs = [f.message for f in report.findings]
    assert all("unused suppression" in m for m in msgs)
    assert any("R3" in m for m in msgs)


def test_used_suppressions_are_not_flagged():
    report = lint_fixture("unused_clean.py")
    assert found(report) == []
    assert [(f.rule, f.line) for f in report.suppressed] == [
        ("R5", 9),
        ("R5", 17),
    ]


def test_unused_suppression_respects_checked_rules():
    """A marker for a rule this scan did not execute (R2 in a non-hot
    file, or a rule disabled by config) is not judged stale."""
    src = (
        "for x in items:\n"
        "    # jaxlint: ignore[R2] verdict sync, measured\n"
        "    v = np.asarray(x)\n"
    )
    # Hot file: R2 fires on the asarray line and the marker is used.
    hot = lint_source(src, "hot.py", JaxlintConfig(), hot=True)
    assert found(hot) == []
    assert [(f.rule, f.line) for f in hot.suppressed] == [("R2", 3)]
    # Non-hot file: R2 never ran, so the marker cannot be judged stale.
    cold = lint_source(src, "cold.py", JaxlintConfig(), hot=False)
    assert found(cold) == []
    # Rule disabled entirely: same reasoning.
    off = lint_source(
        src, "hot.py", JaxlintConfig(rules=["R1", "R3", "R4", "R5"]),
        hot=True,
    )
    assert found(off) == []


def test_rule_subset_config():
    report = lint_source(
        open(os.path.join(FIXTURES, "r5_violation.py")).read(),
        "r5_violation.py",
        JaxlintConfig(rules=["R1"]),
    )
    assert found(report) == []


def test_r2_requires_hot_module():
    source = open(os.path.join(FIXTURES, "r2_violation.py")).read()
    cfg = JaxlintConfig(hot_modules=["somewhere_else/*"])
    assert found(lint_source(source, "r2_violation.py", cfg)) == []
    cfg_hot = JaxlintConfig(hot_modules=["r2_*.py"])
    assert len(found(lint_source(source, "r2_violation.py", cfg_hot))) == 5


def test_syntax_error_reported_not_raised():
    report = lint_source("def broken(:\n", "bad.py", JaxlintConfig())
    assert [f.rule for f in report.findings] == ["ERR"]
