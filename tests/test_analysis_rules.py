"""Per-rule jaxlint coverage over the fixture snippets.

Every rule is demonstrated by a violation fixture (exact rule IDs and
line numbers asserted) with a clean twin that must scan empty; the
suppression fixture locks in the inline-ignore syntax and the
mandatory-reason enforcement.  The cross-module rules (R1x/R2x/R4x)
use multi-file mini-package packs, linted whole-program with the pack
directory as the project root.  Fixtures are read as text, never
imported.
"""

import os

import pytest

from sboxgates_tpu.analysis import JaxlintConfig, lint_source
from sboxgates_tpu.analysis.config import ALL_RULES
from sboxgates_tpu.analysis.project import lint_project
from sboxgates_tpu.analysis.rules import SUPPRESSION_RULE

FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")

#: The pre-contract rule set for the legacy multi-file packs: R7's
#: thread-pin gate would otherwise (correctly) flag the deliberately
#: unpinned Thread targets those packs spawn to exercise R4/R4x.
LEGACY_RULES = [
    r for r in ALL_RULES
    if r not in ("R7", "R8", "R9", "R10", "R11", "R12",
                 "R13", "R14", "R15")
]


def lint_fixture(name, **kwargs):
    path = os.path.join(FIXTURES, name)
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    # hot=True so R2 applies to fixture paths outside the configured
    # hot-module globs
    return lint_source(source, name, JaxlintConfig(), hot=True, **kwargs)


def lint_pack(name, hot_modules=(), rules=None, **cfg_kwargs):
    """Whole-program lint of one multi-file fixture pack."""
    cfg = JaxlintConfig(
        root=os.path.join(FIXTURES, name),
        paths=["."],
        rules=list(LEGACY_RULES if rules is None else rules),
        hot_modules=list(hot_modules),
        whole_program=True,
        **cfg_kwargs,
    )
    return lint_project(config=cfg)


def pack_found(reports):
    return sorted(
        (f.rule, r.path, f.line) for r in reports for f in r.findings
    )


def found(report):
    return [(f.rule, f.line) for f in report.findings]


VIOLATIONS = {
    "r1_violation.py": [("R1", 22), ("R1", 27), ("R1", 33)],
    "r2_violation.py": [
        ("R2", 11),
        ("R2", 20),
        ("R2", 21),
        ("R2", 28),
        ("R2", 35),
    ],
    "r3_violation.py": [("R3", 15), ("R3", 23), ("R3", 29)],
    "r4_violation.py": [("R4", 13), ("R4", 14), ("R4", 19)],
    "r5_violation.py": [("R5", 9), ("R5", 18)],
    "r6_violation.py": [
        ("R6", 6),
        ("R6", 10),
        ("R6", 14),
        ("R6", 18),
        ("R6", 22),
        ("R6", 26),
    ],
}


@pytest.mark.parametrize("name,expected", sorted(VIOLATIONS.items()))
def test_violation_fixture_exact_findings(name, expected):
    assert found(lint_fixture(name)) == expected


@pytest.mark.parametrize(
    "name",
    ["r1_clean.py", "r2_clean.py", "r3_clean.py", "r4_clean.py",
     "r5_clean.py", "r6_clean.py"],
)
def test_clean_twin_scans_empty(name):
    report = lint_fixture(name)
    assert found(report) == []
    assert report.suppressed == []


def test_rule_messages_name_the_hazard():
    messages = {f.rule: f.message for f in lint_fixture("r1_violation.py").findings}
    assert "recompile" in messages["R1"] or "compile" in messages["R1"]
    r2 = lint_fixture("r2_violation.py").findings[0]
    assert "loop" in r2.message and "hot" in r2.message


def test_suppression_with_reason_suppresses():
    report = lint_fixture("suppressions.py")
    # probe_a (same-line form) and probe_b (standalone-comment form) are
    # suppressed; both retain the finding in the suppressed list
    assert [(f.rule, f.line) for f in report.suppressed] == [
        ("R5", 7),
        ("R5", 15),
    ]


def test_reasonless_and_unknown_rule_suppressions_do_not_suppress():
    report = lint_fixture("suppressions.py")
    got = found(report)
    # probe_c: reason missing -> R5 stays, plus a SUP finding
    assert ("R5", 22) in got and (SUPPRESSION_RULE, 22) in got
    # probe_d: unknown rule id -> R5 stays, plus a SUP finding
    assert ("R5", 29) in got and (SUPPRESSION_RULE, 29) in got
    # and nothing else leaks through
    assert len(got) == 4


def test_unused_suppression_is_a_finding():
    """A well-formed marker whose finding no longer fires is itself
    reported (the deferred unused-suppression rule)."""
    report = lint_fixture("unused_suppression.py")
    got = found(report)
    # probe_stale (same-line) and probe_stale_standalone: no R5 finding
    # on the marked lines -> the markers are stale
    assert (SUPPRESSION_RULE, 7) in got
    assert (SUPPRESSION_RULE, 14) in got
    # probe_partial: R5 fires (and stays suppressed) but R3 never did —
    # the marker is flagged for its unused half only
    assert (SUPPRESSION_RULE, 22) in got
    assert [(f.rule, f.line) for f in report.suppressed] == [("R5", 22)]
    assert len(got) == 3
    msgs = [f.message for f in report.findings]
    assert all("unused suppression" in m for m in msgs)
    assert any("R3" in m for m in msgs)


def test_used_suppressions_are_not_flagged():
    report = lint_fixture("unused_clean.py")
    assert found(report) == []
    assert [(f.rule, f.line) for f in report.suppressed] == [
        ("R5", 9),
        ("R5", 17),
    ]


def test_unused_suppression_respects_checked_rules():
    """A marker for a rule this scan did not execute (R2 in a non-hot
    file, or a rule disabled by config) is not judged stale."""
    src = (
        "for x in items:\n"
        "    # jaxlint: ignore[R2] verdict sync, measured\n"
        "    v = np.asarray(x)\n"
    )
    # Hot file: R2 fires on the asarray line and the marker is used.
    hot = lint_source(src, "hot.py", JaxlintConfig(), hot=True)
    assert found(hot) == []
    assert [(f.rule, f.line) for f in hot.suppressed] == [("R2", 3)]
    # Non-hot file: R2 never ran, so the marker cannot be judged stale.
    cold = lint_source(src, "cold.py", JaxlintConfig(), hot=False)
    assert found(cold) == []
    # Rule disabled entirely: same reasoning.
    off = lint_source(
        src, "hot.py", JaxlintConfig(rules=["R1", "R3", "R4", "R5"]),
        hot=True,
    )
    assert found(off) == []


def test_rule_subset_config():
    report = lint_source(
        open(os.path.join(FIXTURES, "r5_violation.py")).read(),
        "r5_violation.py",
        JaxlintConfig(rules=["R1"]),
    )
    assert found(report) == []


def test_r2_requires_hot_module():
    source = open(os.path.join(FIXTURES, "r2_violation.py")).read()
    cfg = JaxlintConfig(hot_modules=["somewhere_else/*"])
    assert found(lint_source(source, "r2_violation.py", cfg)) == []
    cfg_hot = JaxlintConfig(hot_modules=["r2_*.py"])
    assert len(found(lint_source(source, "r2_violation.py", cfg_hot))) == 5


def test_syntax_error_reported_not_raised():
    report = lint_source("def broken(:\n", "bad.py", JaxlintConfig())
    assert [f.rule for f in report.findings] == ["ERR"]


# -- cross-module rule packs (whole-program pass) --------------------------

X_VIOLATIONS = {
    # pack -> (hot globs, exact sorted (rule, file, line))
    "r4x_violation": (
        (),
        [("R4x", "state.py", 17), ("R4x", "worker.py", 21)],
    ),
    "r1x_violation": (
        (),
        [
            ("R1x", "driver.py", 9),
            ("R1x", "driver.py", 10),
            ("R1x", "driver.py", 12),
        ],
    ),
    "r2x_violation": (
        ("*hot*",),
        [("R2x", "hot_driver.py", 9), ("R2x", "hot_driver.py", 10)],
    ),
}


@pytest.mark.parametrize("name", sorted(X_VIOLATIONS))
def test_xrule_violation_pack_exact_findings(name):
    hot, expected = X_VIOLATIONS[name]
    assert pack_found(lint_pack(name, hot)) == expected


@pytest.mark.parametrize(
    "name,hot",
    [("r4x_clean", ()), ("r1x_clean", ()), ("r2x_clean", ("*hot*",))],
)
def test_xrule_clean_twin_scans_empty(name, hot):
    reports = lint_pack(name, hot)
    assert pack_found(reports) == []


def test_r4x_matches_the_native_ok_false_negative_shape():
    """The r4x_violation pack mirrors the known pre-fix false negative
    (ops/combinatorics._native_stream_available mutating _native_ok from
    the prefetch thread via _work -> _produce -> next_chunk): the
    finding names the thread root and the transitive path."""
    reports = lint_pack("r4x_violation")
    msgs = {
        f.line: f.message for r in reports for f in r.findings
        if r.path == "state.py"
    }
    m = msgs[17]
    assert "Prefetcher._work" in m  # the thread entry
    assert "_produce" in m and "next_chunk" in m  # the transitive path
    assert "_probe_ok" in m


def test_r4x_clean_demonstrates_lock_aliasing_and_parameter_locks():
    """The clean twin guards the same mutations with an IMPORTED lock
    and a PARAMETER lock — both count as held (the per-file R4 would
    miss both)."""
    src = open(
        os.path.join(FIXTURES, "r4x_clean", "state.py"), encoding="utf-8"
    ).read()
    assert "from .locks import PROBE_LOCK" in src
    assert "def record(lock, n):" in src
    assert pack_found(lint_pack("r4x_clean")) == []


def test_r2x_message_names_the_sync_witness():
    reports = lint_pack("r2x_violation", ("*hot*",))
    msgs = [f.message for r in reports for f in r.findings]
    assert any("helpers.py" in m and ".item()" in m for m in msgs)


def test_r2x_acknowledged_source_marker_is_used_not_stale():
    """An R2x marker on the sync source kills the taint for every
    caller and is recorded as a suppressed acknowledged-source entry —
    never reported as an unused suppression."""
    reports = lint_pack("r2x_clean", ("*hot*",))
    sup = [
        (f.rule, r.path, f.line) for r in reports for f in r.suppressed
    ]
    assert sup == [("R2x", "helpers.py", 8)]


def test_xrule_findings_suppressible_inline(tmp_path):
    """R4x findings honor the existing ignore[RULE] syntax."""
    pack = tmp_path / "pack"
    pack.mkdir()
    (pack / "state.py").write_text(
        "import threading\n"
        "_flag = None\n"
        "def probe():\n"
        "    global _flag\n"
        "    # jaxlint: ignore[R4x] benign idempotent probe, worst case a duplicate write\n"
        "    _flag = True\n"
        "def work():\n"
        "    probe()\n"
        "def spawn():\n"
        "    threading.Thread(target=work).start()\n"
    )
    cfg = JaxlintConfig(
        root=str(pack), paths=["."], rules=list(LEGACY_RULES),
        whole_program=True,
    )
    reports = lint_project(config=cfg)
    assert pack_found(reports) == []
    assert [
        (f.rule, f.line) for r in reports for f in r.suppressed
    ] == [("R4x", 6)]


def test_xrule_markers_not_judged_stale_without_whole_program(tmp_path):
    """A marker for a cross-module rule is only judged (used or stale)
    when the whole-program pass actually ran; the per-file pass leaves
    it alone, and a whole-program run flags a stale one."""
    pack = tmp_path / "pack"
    pack.mkdir()
    (pack / "mod.py").write_text(
        "def quiet():\n"
        "    # jaxlint: ignore[R4x] left over from a removed mutation\n"
        "    return 1\n"
    )
    src = (pack / "mod.py").read_text()
    per_file = lint_source(src, "mod.py", JaxlintConfig())
    assert found(per_file) == []  # not judged: R4x never ran
    cfg = JaxlintConfig(
        root=str(pack), paths=["."], rules=list(LEGACY_RULES),
        whole_program=True,
    )
    reports = lint_project(config=cfg)
    got = pack_found(reports)
    assert got == [(SUPPRESSION_RULE, "mod.py", 2)]


def test_r2x_for_else_body_is_not_in_the_loop(tmp_path):
    """A call in a for-else clause runs once, after the loop — it must
    not fire R2x's inside-a-loop check (regression: the body scan used
    to visit orelse with the loop context still active)."""
    pack = tmp_path / "pack"
    pack.mkdir()
    (pack / "helpers.py").write_text(
        "def fetch(v):\n    return v.item()\n"
    )
    (pack / "hot_driver.py").write_text(
        "from .helpers import fetch\n"
        "def drain(batch):\n"
        "    for v in batch:\n"
        "        pass\n"
        "    else:\n"
        "        return fetch(batch)\n"
    )
    cfg = JaxlintConfig(
        root=str(pack), paths=["."], rules=list(LEGACY_RULES),
        hot_modules=["*hot*"], whole_program=True,
    )
    assert pack_found(lint_project(config=cfg)) == []


def test_r4x_local_shadowing_is_not_module_state(tmp_path):
    """A local variable (or parameter) shadowing a module-level mutable
    name refers to the LOCAL — mutating it from a thread is fine and
    must not resolve through the project symbol table."""
    pack = tmp_path / "pack"
    pack.mkdir()
    (pack / "state.py").write_text("EVENTS = []\n")
    (pack / "worker.py").write_text(
        "import threading\n"
        "from .state import EVENTS\n"
        "def work():\n"
        "    EVENTS = []\n"
        "    EVENTS.append(1)\n"
        "    EVENTS[0] = 2\n"
        "def spawn():\n"
        "    threading.Thread(target=work).start()\n"
    )
    cfg = JaxlintConfig(
        root=str(pack), paths=["."], rules=list(LEGACY_RULES),
        whole_program=True,
    )
    assert pack_found(lint_project(config=cfg)) == []


def test_r2_for_else_body_is_not_in_the_loop():
    """Per-file R2 parity with R2x: a sync in a for-else clause runs
    once, after the loop — not a per-iteration stall."""
    src = (
        "def drain(batch, v):\n"
        "    for x in batch:\n"
        "        pass\n"
        "    else:\n"
        "        return v.item()\n"
    )
    report = lint_source(src, "hot.py", JaxlintConfig(), hot=True)
    assert found(report) == []
    # ...while the while-TEST re-evaluates per iteration and stays R2
    src2 = "def drain(v):\n    while v.item():\n        pass\n"
    report2 = lint_source(src2, "hot.py", JaxlintConfig(), hot=True)
    assert found(report2) == [("R2", 2)]


def test_r2x_shadowed_callable_is_not_the_imported_helper(tmp_path):
    """A parameter shadowing an imported sync-tainted function means
    the loop calls the PARAMETER — no R2x."""
    pack = tmp_path / "pack"
    pack.mkdir()
    (pack / "helpers.py").write_text(
        "def fetch(v):\n    return v.item()\n"
    )
    (pack / "hot_driver.py").write_text(
        "from .helpers import fetch\n"
        "def drain(batch, fetch):\n"
        "    for v in batch:\n"
        "        fetch(v)\n"
    )
    cfg = JaxlintConfig(
        root=str(pack), paths=["."], rules=list(LEGACY_RULES),
        hot_modules=["*hot*"], whole_program=True,
    )
    assert pack_found(lint_project(config=cfg)) == []


def test_r4x_tuple_unpacked_local_shadows_module_state(tmp_path):
    """Tuple-unpacking assignment binds locals too: `EVENTS, x = [], 1`
    shadows module EVENTS for the rest of the function."""
    pack = tmp_path / "pack"
    pack.mkdir()
    (pack / "state.py").write_text("EVENTS = []\n")
    (pack / "worker.py").write_text(
        "import threading\n"
        "from .state import EVENTS\n"
        "def work():\n"
        "    EVENTS, x = [], 1\n"
        "    EVENTS.append(x)\n"
        "def spawn():\n"
        "    threading.Thread(target=work).start()\n"
    )
    cfg = JaxlintConfig(
        root=str(pack), paths=["."], rules=list(LEGACY_RULES),
        whole_program=True,
    )
    assert pack_found(lint_project(config=cfg)) == []


def test_r4x_sees_aliased_threading_import(tmp_path):
    """`import threading as th; th.Thread(target=...)` registers the
    target as a thread root all the same."""
    pack = tmp_path / "pack"
    pack.mkdir()
    (pack / "worker.py").write_text(
        "import threading as th\n"
        "_flag = None\n"
        "def probe():\n"
        "    global _flag\n"
        "    _flag = True\n"
        "def work():\n"
        "    probe()\n"
        "def spawn():\n"
        "    th.Thread(target=work).start()\n"
    )
    cfg = JaxlintConfig(
        root=str(pack), paths=["."], rules=list(LEGACY_RULES),
        whole_program=True,
    )
    assert pack_found(lint_project(config=cfg)) == [
        ("R4x", "worker.py", 5)
    ]


def test_r2x_stale_acknowledged_source_marker_is_flagged(tmp_path):
    """An R2x marker whose sync is gone is stale even in a NON-hot file
    (acknowledged-source entries are emitted regardless of hotness, so
    the inventory must not accrete)."""
    pack = tmp_path / "pack"
    pack.mkdir()
    (pack / "cold.py").write_text(
        "def fetch(v):\n"
        "    # jaxlint: ignore[R2x] acknowledged sync that no longer exists\n"
        "    return v\n"
    )
    cfg = JaxlintConfig(
        root=str(pack), paths=["."], rules=list(LEGACY_RULES),
        whole_program=True,
    )
    assert pack_found(lint_project(config=cfg)) == [
        (SUPPRESSION_RULE, "cold.py", 2)
    ]


def test_r1x_annassign_jit_alias_tracks_statics(tmp_path):
    """`jfit: Callable = jax.jit(fn, static_argnames=...)` at module
    scope carries its statics to cross-module call sites."""
    pack = tmp_path / "pack"
    pack.mkdir()
    (pack / "kernels.py").write_text(
        "from typing import Callable\n"
        "import jax\n"
        "def plain(x, k):\n"
        "    return x\n"
        "jfit: Callable = jax.jit(plain, static_argnames=('k',))\n"
    )
    (pack / "driver.py").write_text(
        "from .kernels import jfit\n"
        "def run(xs):\n"
        "    for i in range(4):\n"
        "        jfit(xs, k=i)\n"
    )
    cfg = JaxlintConfig(
        root=str(pack), paths=["."], rules=list(LEGACY_RULES),
        whole_program=True,
    )
    assert pack_found(lint_project(config=cfg)) == [
        ("R1x", "driver.py", 4)
    ]


def test_r2x_while_test_is_in_the_loop(tmp_path):
    """A while-loop's test re-evaluates every iteration: a sync-tainted
    helper called there must fire R2x (parity with the per-file R2,
    which treats the test as loop context)."""
    pack = tmp_path / "pack"
    pack.mkdir()
    (pack / "helpers.py").write_text(
        "def pending(v):\n    return v.item()\n"
    )
    (pack / "hot_driver.py").write_text(
        "from .helpers import pending\n"
        "def drain(v):\n"
        "    while pending(v):\n"
        "        pass\n"
    )
    cfg = JaxlintConfig(
        root=str(pack), paths=["."], rules=list(LEGACY_RULES),
        hot_modules=["*hot*"], whole_program=True,
    )
    assert pack_found(lint_project(config=cfg)) == [
        ("R2x", "hot_driver.py", 3)
    ]


def test_pack_scan_is_deterministic():
    a = pack_found(lint_pack("r4x_violation"))
    b = pack_found(lint_pack("r4x_violation"))
    assert a == b
    msgs_a = [
        f.message for r in lint_pack("r4x_violation") for f in r.findings
    ]
    msgs_b = [
        f.message for r in lint_pack("r4x_violation") for f in r.findings
    ]
    assert msgs_a == msgs_b


# -- contract-verification packs (R7–R12) ----------------------------------

#: pack -> (config kwargs, exact sorted (rule, file, line)).  The clean
#: twins run under the same kwargs as their dirty pack unless listed.
CONTRACT_PACKS = {
    "r7_violation": (
        dict(rules=["R7"], dispatch_modules=["*"], thread_roots=[]),
        [
            ("R7", "driver.py", 13),   # registry-bypassing jax.jit
            ("R7", "driver.py", 19),   # undeclared metric
            ("R7", "driver.py", 24),   # undeclared fault site
            ("R7", "journal.py", 6),   # journal key with no argparse dest
            ("R7", "journal.py", 9),   # default for a non-journaled key
            ("R7", "journal.py", 29),  # Options field not journaled
            ("R7", "registry.py", 23),  # dead kernel declaration
            ("R7", "registry.py", 29),  # FLEET_SHARED outside KERNELS
            ("R7", "worker.py", 10),   # unpinned thread entry
        ],
    ),
    "r8_violation": (
        dict(rules=["R8"], dispatch_modules=["*"]),
        [
            ("R8", "driver.py", 10),  # shape from len()-derived local
            ("R8", "driver.py", 16),  # inline len() + loop variable
            ("R8", "driver.py", 21),  # parameter-shaped operand
        ],
    ),
    "r9_violation": (
        dict(rules=["R9"], thread_roots=["forward", "backward"]),
        [
            ("R9", "workers.py", 13),  # order cycle, first witness hop
            ("R9", "workers.py", 25),  # lock held across the resolve
        ],
    ),
    "r10_violation": (
        dict(rules=["R10"]),
        [
            ("R10", "protocol.py", 17),  # agreement from one side only
            ("R10", "protocol.py", 23),  # early-return guard, transitive
            ("R10", "protocol.py", 30),  # collective in host window
        ],
    ),
    "r11_violation": (
        dict(rules=["R11"]),
        [
            ("R11", "driver.py", 9),   # wall clock via clock.stamp()
            ("R11", "driver.py", 16),  # urandom seed into default_rng
            ("R11", "driver.py", 21),  # unsorted listdir -> canonicalize
            ("R11", "driver.py", 30),  # set iteration -> journal.append
        ],
    ),
    "r12_violation": (
        dict(rules=["R12"], durable_modules=["*"]),
        [
            ("R12", "persist.py", 8),   # truncating open("w")
            ("R12", "persist.py", 9),   # json.dump to the stream
            ("R12", "persist.py", 10),  # raw os.replace
        ],
    ),
    "r13_violation": (
        dict(rules=["R13"], handler_modules=["handler.py"]),
        [
            ("R13", "handler.py", 14),  # raw header name into path.join
            ("R13", "records.py", 14),  # body taint via param, cross-module
        ],
    ),
    "r14_violation": (
        dict(rules=["R14"], handler_modules=["handler.py"]),
        [
            ("R14", "handler.py", 11),  # effect with no checks at all
            ("R14", "handler.py", 17),  # checks in one if-arm only
            ("R14", "handler.py", 23),  # 202 with no journal append
        ],
    ),
    "r15_violation": (
        dict(rules=["R15"]),
        [
            ("R15", "resources.py", 12),  # straight-line close
            ("R15", "resources.py", 19),  # straight-line join of list
            ("R15", "resources.py", 27),  # constructed and discarded
            ("R15", "resources.py", 32),  # self-stored, no teardown
        ],
    ),
}

CONTRACT_CLEAN = {
    "r7_clean": dict(rules=["R7"], dispatch_modules=["*"],
                     thread_roots=["work"]),
    "r8_clean": dict(rules=["R8"], dispatch_modules=["*"]),
    "r9_clean": dict(rules=["R9"],
                     thread_roots=["forward", "also_forward"]),
    "r10_clean": dict(rules=["R10"]),
    "r11_clean": dict(rules=["R11"]),
    "r12_clean": dict(rules=["R12"], durable_modules=["*"],
                      durable_helpers=["durable_write_text"]),
    "r13_clean": dict(rules=["R13"], handler_modules=["handler.py"]),
    "r14_clean": dict(rules=["R14"], handler_modules=["handler.py"]),
    "r15_clean": dict(rules=["R15"]),
}


@pytest.mark.parametrize("name", sorted(CONTRACT_PACKS))
def test_contract_violation_pack_exact_findings(name):
    kwargs, expected = CONTRACT_PACKS[name]
    assert pack_found(lint_pack(name, **kwargs)) == expected


@pytest.mark.parametrize("name", sorted(CONTRACT_CLEAN))
def test_contract_clean_twin_scans_empty(name):
    reports = lint_pack(name, **CONTRACT_CLEAN[name])
    assert pack_found(reports) == []
    assert [f for r in reports for f in r.suppressed] == []


def test_r7_messages_name_the_registry_and_contract():
    kwargs, _ = CONTRACT_PACKS["r7_violation"]
    reports = lint_pack("r7_violation", **kwargs)
    by_site = {
        (r.path, f.line): f.message
        for r in reports
        for f in r.findings
    }
    # The bypass finding names the registry's home module.
    m = by_site[("driver.py", 13)]
    assert "registry.py" in m and "kernel_call" in m
    # The drift findings name the violated registry and the entry.
    assert "sweep_total" in by_site[("driver.py", 19)]
    assert "METRICS" in by_site[("driver.py", 19)]
    assert "ckpt.rename" in by_site[("driver.py", 24)]
    assert "KNOWN_SITES" in by_site[("driver.py", 24)]
    assert "orphan_sweep" in by_site[("registry.py", 23)]
    assert "ghost_sweep" in by_site[("registry.py", 29)]
    assert "thread_roots" in by_site[("worker.py", 10)]


def test_r7_clean_exempts_private_declared_none_registry():
    """The clean driver's Rendezvous tallies into its own
    MetricsRegistry(declared=None) — a private schema by design, never
    held to METRICS."""
    src = open(
        os.path.join(FIXTURES, "r7_clean", "driver.py"), encoding="utf-8"
    ).read()
    assert "declared=None" in src and 'inc("submits")' in src
    kwargs = CONTRACT_CLEAN["r7_clean"]
    assert pack_found(lint_pack("r7_clean", **kwargs)) == []


def test_r7_stale_thread_pin_is_flagged():
    """A thread_roots spec matching no function is itself a finding,
    attributed to the config file (how the stale
    run_fleet_circuits.worker pin from PR 8's refactor was caught)."""
    kwargs = dict(CONTRACT_CLEAN["r7_clean"])
    kwargs["thread_roots"] = ["work", "Retired._gone"]
    got = pack_found(lint_pack("r7_clean", **kwargs))
    assert got == [("R7", "pyproject.toml", 1)]
    reports = lint_pack("r7_clean", **kwargs)
    msgs = [
        f.message for r in reports for f in r.findings
        if r.path == "pyproject.toml"
    ]
    assert "Retired._gone" in msgs[0] and "stale" in msgs[0]


def test_r9_cycle_message_carries_the_witness():
    kwargs, _ = CONTRACT_PACKS["r9_violation"]
    reports = lint_pack("r9_violation", **kwargs)
    cycle_msgs = [
        f.message for r in reports for f in r.findings
        if "cycle" in f.message
    ]
    assert len(cycle_msgs) == 1
    m = cycle_msgs[0]
    # The witness cycle, with both hops' acquisition sites.
    assert "locks.ALPHA -> locks.BETA -> locks.ALPHA" in m
    assert "workers.py:13" in m and "workers.py:19" in m


def test_r8_findings_suppressible_inline(tmp_path):
    """A deliberately unbucketed shape is acknowledged with
    ignore[R8] + reason, exactly like every other rule."""
    pack = tmp_path / "pack"
    pack.mkdir()
    (pack / "kernels.py").write_text(
        "def kernel_call(name, *ops):\n    return name, ops\n"
    )
    (pack / "driver.py").write_text(
        "import numpy as np\n"
        "from .kernels import kernel_call\n"
        "def probe(n):\n"
        "    # jaxlint: ignore[R8] one-off capability probe, runs once per process\n"
        "    ops = np.zeros((n, 8))\n"
        "    kernel_call('gate_sweep', ops)\n"
    )
    cfg = JaxlintConfig(
        root=str(pack), paths=["."], rules=["R8"],
        dispatch_modules=["*"], whole_program=True,
    )
    reports = lint_project(config=cfg)
    assert pack_found(reports) == []
    assert [
        (f.rule, r.path, f.line) for r in reports for f in r.suppressed
    ] == [("R8", "driver.py", 5)]


def test_r9_held_lock_suppressible_inline(tmp_path):
    pack = tmp_path / "pack"
    pack.mkdir()
    (pack / "mod.py").write_text(
        "import threading\n"
        "GUARD = threading.Lock()\n"
        "def resolve(ctx, ops):\n"
        "    with GUARD:\n"
        "        # jaxlint: ignore[R9] probe path has no deadline budget; nothing can abandon it\n"
        "        return ctx.guarded_dispatch('gate_sweep', ops)\n"
    )
    cfg = JaxlintConfig(
        root=str(pack), paths=["."], rules=["R9"], whole_program=True,
    )
    reports = lint_project(config=cfg)
    assert pack_found(reports) == []
    assert [
        (f.rule, r.path, f.line) for r in reports for f in r.suppressed
    ] == [("R9", "mod.py", 6)]


def test_r10_messages_name_sites_side_and_contract():
    kwargs, _ = CONTRACT_PACKS["r10_violation"]
    reports = lint_pack("r10_violation", **kwargs)
    by_site = {
        (r.path, f.line): f.message
        for r in reports
        for f in r.findings
    }
    m = by_site[("protocol.py", 17)]
    assert "breach_verdict" in m and "launch-count lockstep" in m
    # Guard style: the flagged side is the fall-through past the early
    # return, and the agreement site is reached TRANSITIVELY (the
    # witness names the carrier's call site).
    m = by_site[("protocol.py", 23)]
    assert "breach_verdict (via protocol.py:9)" in m
    assert "the path past the guard" in m
    m = by_site[("protocol.py", 30)]
    assert "process_allgather" in m and "host-agreement window" in m


def test_r11_messages_carry_source_witness_and_sink():
    kwargs, _ = CONTRACT_PACKS["r11_violation"]
    reports = lint_pack("r11_violation", **kwargs)
    by_site = {
        (r.path, f.line): f.message
        for r in reports
        for f in r.findings
    }
    # Interprocedural: the wall clock hides behind clock.stamp().
    m = by_site[("driver.py", 9)]
    assert "wall clock time.time()" in m and "journal.append" in m
    assert "os.urandom" in by_site[("driver.py", 16)]
    assert "default_rng" in by_site[("driver.py", 16)]
    m = by_site[("driver.py", 21)]
    assert "unsorted directory scan listdir()" in m
    assert "canonicalize" in m
    assert "unordered set" in by_site[("driver.py", 30)]


def test_r12_messages_point_at_the_durable_helper():
    kwargs, _ = CONTRACT_PACKS["r12_violation"]
    reports = lint_pack("r12_violation", **kwargs)
    by_site = {
        (r.path, f.line): f.message
        for r in reports
        for f in r.findings
    }
    assert "truncating open(mode='w')" in by_site[("persist.py", 8)]
    assert "json.dump" in by_site[("persist.py", 9)]
    assert "os.replace" in by_site[("persist.py", 10)]
    for m in by_site.values():
        assert "durable" in m


def test_r10_findings_suppressible_inline(tmp_path):
    pack = tmp_path / "pack"
    pack.mkdir()
    (pack / "mod.py").write_text(
        "import jax\n"
        "def breach_verdict(flag):\n"
        "    return bool(flag)\n"
        "def gated(flag):\n"
        "    # jaxlint: ignore[R10] primary-only verdict is re-broadcast to every rank by the caller\n"
        "    if jax.process_index() == 0:\n"
        "        breach_verdict(flag)\n"
    )
    cfg = JaxlintConfig(
        root=str(pack), paths=["."], rules=["R10"], whole_program=True,
    )
    reports = lint_project(config=cfg)
    assert pack_found(reports) == []
    assert [
        (f.rule, r.path, f.line) for r in reports for f in r.suppressed
    ] == [("R10", "mod.py", 6)]


def test_r11_acknowledged_source_suppresses_downstream_sinks(tmp_path):
    """The R11 contract: the marker goes on the SOURCE, which silences
    every sink it taints — and the acknowledged source itself lands in
    the suppressed inventory so the marker can never go stale
    silently."""
    pack = tmp_path / "pack"
    pack.mkdir()
    (pack / "mod.py").write_text(
        "import time\n"
        "def record(journal):\n"
        "    t = time.time()  # jaxlint: ignore[R11] operator-facing stamp, never replayed or keyed on\n"
        "    journal.append('note', t=t)\n"
    )
    cfg = JaxlintConfig(
        root=str(pack), paths=["."], rules=["R11"], whole_program=True,
    )
    reports = lint_project(config=cfg)
    assert pack_found(reports) == []
    sup = [
        (f.rule, r.path, f.line, f.message)
        for r in reports for f in r.suppressed
    ]
    assert [(s[0], s[1], s[2]) for s in sup] == [("R11", "mod.py", 3)]
    assert "acknowledged" in sup[0][3]


def test_r11_stale_acknowledged_source_marker_is_flagged(tmp_path):
    pack = tmp_path / "pack"
    pack.mkdir()
    (pack / "mod.py").write_text(
        "def record(journal, t):\n"
        "    # jaxlint: ignore[R11] nothing nondeterministic here\n"
        "    journal.append('note', t=t)\n"
    )
    cfg = JaxlintConfig(
        root=str(pack), paths=["."], rules=["R11"], whole_program=True,
    )
    assert pack_found(lint_project(config=cfg)) == [
        ("SUP", "mod.py", 2)
    ]


def test_r12_findings_suppressible_inline(tmp_path):
    pack = tmp_path / "pack"
    pack.mkdir()
    (pack / "mod.py").write_text(
        "import os\n"
        "def quarantine(src, dst):\n"
        "    # jaxlint: ignore[R12] rename of already-durable bytes — nothing to tear\n"
        "    os.replace(src, dst)\n"
    )
    cfg = JaxlintConfig(
        root=str(pack), paths=["."], rules=["R12"],
        durable_modules=["*"], whole_program=True,
    )
    reports = lint_project(config=cfg)
    assert pack_found(reports) == []
    assert [
        (f.rule, r.path, f.line) for r in reports for f in r.suppressed
    ] == [("R12", "mod.py", 4)]


def test_contract_pack_scan_is_deterministic():
    for name, (kwargs, _) in sorted(CONTRACT_PACKS.items()):
        a = [
            (r.path, f.line, f.message)
            for r in lint_pack(name, **kwargs)
            for f in r.findings
        ]
        b = [
            (r.path, f.line, f.message)
            for r in lint_pack(name, **kwargs)
            for f in r.findings
        ]
        assert a == b


def test_r8_free_function_reshape_array_operand_is_not_an_axis(tmp_path):
    """np.reshape(arr, shape): only the shape is provenance-checked —
    the array operand must not be misread as an axis (while the method
    form x.reshape(a, b) checks every argument)."""
    pack = tmp_path / "pack"
    pack.mkdir()
    (pack / "kernels.py").write_text(
        "def kernel_call(name, *ops):\n    return name, ops\n"
    )
    (pack / "driver.py").write_text(
        "import numpy as np\n"
        "from .kernels import kernel_call\n"
        "def ok(arr, bucket):\n"
        "    ops = np.reshape(arr, (bucket, 8))\n"
        "    kernel_call('gate_sweep', ops)\n"
        "def bad(arr, n):\n"
        "    ops = arr.reshape(n, 8)\n"
        "    kernel_call('gate_sweep', ops)\n"
    )
    cfg = JaxlintConfig(
        root=str(pack), paths=["."], rules=["R8"],
        dispatch_modules=["*"], whole_program=True,
    )
    assert pack_found(lint_project(config=cfg)) == [
        ("R8", "driver.py", 7)
    ]


def test_r7_same_module_use_is_not_a_dead_declaration(tmp_path):
    """A registry entry used elsewhere in its OWN declaring module is
    live — only the declaration site itself is excluded from the
    use-site census."""
    pack = tmp_path / "pack"
    pack.mkdir()
    (pack / "registry.py").write_text(
        "from collections import namedtuple\n"
        "KernelDef = namedtuple('KernelDef', ['name'])\n"
        "KERNELS = {d.name: d for d in (KernelDef('gate_sweep'),)}\n"
        "def kernel_call(name, *ops):\n"
        "    return KERNELS[name], ops\n"
        "def drive(ops):\n"
        "    return kernel_call('gate_sweep', ops)\n"
    )
    cfg = JaxlintConfig(
        root=str(pack), paths=["."], rules=["R7"],
        dispatch_modules=[], whole_program=True,
    )
    assert pack_found(lint_project(config=cfg)) == []


def test_r9_blocking_call_behind_lockfree_wrapper_still_fires(tmp_path):
    """A helper that wraps guarded_dispatch with no lock of its own is
    transitively blocking — a caller holding a lock across the WRAPPER
    is the same hazard as the direct call."""
    pack = tmp_path / "pack"
    pack.mkdir()
    (pack / "mod.py").write_text(
        "import threading\n"
        "GUARD = threading.Lock()\n"
        "def helper(ctx, ops):\n"
        "    return ctx.guarded_dispatch('gate_sweep', ops)\n"
        "def outer(ctx, ops):\n"
        "    with GUARD:\n"
        "        return helper(ctx, ops)\n"
    )
    cfg = JaxlintConfig(
        root=str(pack), paths=["."], rules=["R9"], whole_program=True,
    )
    got = pack_found(lint_project(config=cfg))
    assert got == [("R9", "mod.py", 7)]


def test_r8_constant_assigned_local_is_static(tmp_path):
    """n = 128 is one shape forever — flagging it would force a
    spurious ignore on innocuous code."""
    pack = tmp_path / "pack"
    pack.mkdir()
    (pack / "kernels.py").write_text(
        "def kernel_call(name, *ops):\n    return name, ops\n"
    )
    (pack / "driver.py").write_text(
        "import numpy as np\n"
        "from .kernels import kernel_call\n"
        "def probe():\n"
        "    n = 128\n"
        "    buf = np.zeros((n, 4))\n"
        "    kernel_call('gate_sweep', buf)\n"
        "def churn(items):\n"
        "    n = 128\n"
        "    n = len(items)\n"
        "    buf = np.zeros((n, 4))\n"
        "    kernel_call('gate_sweep', buf)\n"
    )
    cfg = JaxlintConfig(
        root=str(pack), paths=["."], rules=["R8"],
        dispatch_modules=["*"], whole_program=True,
    )
    # probe's constant n is quiet; churn's rebound-dynamic n still fires
    assert pack_found(lint_project(config=cfg)) == [
        ("R8", "driver.py", 10)
    ]


# -- trust-boundary packs (R13-R15, ISSUE 19) ------------------------------


def test_r13_messages_carry_witness_sink_and_remedy():
    kwargs, _ = CONTRACT_PACKS["r13_violation"]
    reports = lint_pack("r13_violation", **kwargs)
    by_site = {
        (r.path, f.line): f.message
        for r in reports
        for f in r.findings
    }
    m = by_site[("handler.py", 14)]
    assert "headers.get" in m and "path.join" in m and "sanitizer" in m
    # The cross-module sink names the ORIGINAL request source, not the
    # intermediate parameter.
    m = by_site[("records.py", 14)]
    assert "rfile.read" in m and "journal.append" in m


def test_r13_acknowledged_source_kills_taint_and_stays_inventoried():
    """The R2x/R11 on-source marker contract for R13: a marker on the
    SOURCE line suppresses every downstream sink finding, and the
    source re-emits as a suppressed "acknowledged" entry so the marker
    is never stale."""
    kwargs, expected = CONTRACT_PACKS["r13_violation"]
    reports = lint_pack("r13_violation", **kwargs)
    assert pack_found(reports) == expected  # no post_acked sink finding
    sups = [
        (f.rule, r.path, f.line, f.message)
        for r in reports
        for f in r.suppressed
    ]
    assert [(s[0], s[1], s[2]) for s in sups] == [
        ("R13", "handler.py", 20)
    ]
    assert "acknowledged" in sups[0][3]


def test_r14_messages_hint_at_the_other_path():
    kwargs, _ = CONTRACT_PACKS["r14_violation"]
    reports = lint_pack("r14_violation", **kwargs)
    by_site = {
        (r.path, f.line): f.message
        for r in reports
        for f in r.findings
    }
    # No check anywhere: the message says so outright.
    assert "no auth site on any path" in by_site[("handler.py", 11)]
    # One-sided check: the message names where the check DOES run.
    m = by_site[("handler.py", 17)]
    assert "runs on another path" in m and "line 15" in m
    # Unjournaled 202: names the crash-loses-a-job consequence.
    m = by_site[("handler.py", 23)]
    assert "no journal append on any path" in m and "crash" in m


def test_r14_inline_suppression_covers_deliberate_effects():
    kwargs, _ = CONTRACT_PACKS["r14_violation"]
    reports = lint_pack("r14_violation", **kwargs)
    sups = [
        (f.rule, r.path, f.line)
        for r in reports
        for f in r.suppressed
    ]
    assert ("R14", "handler.py", 27) in sups


def test_r14_clean_twin_hoists_auth_into_shared_helper():
    """The clean twin's auth check lives in ``_auth`` — dominance must
    credit the helper call via the call graph's reach map, or every
    real-world refactor would need a marker."""
    src = open(
        os.path.join(FIXTURES, "r14_clean", "handler.py"),
        encoding="utf-8",
    ).read()
    assert "def _auth" in src and "self._auth(h)" in src
    assert pack_found(
        lint_pack("r14_clean", **CONTRACT_CLEAN["r14_clean"])
    ) == []


def test_r15_messages_and_inline_suppression():
    kwargs, _ = CONTRACT_PACKS["r15_violation"]
    reports = lint_pack("r15_violation", **kwargs)
    by_site = {
        (r.path, f.line): f.message
        for r in reports
        for f in r.findings
    }
    assert "socket.socket" in by_site[("resources.py", 12)]
    assert "finally" in by_site[("resources.py", 12)]
    assert "discarded" in by_site[("resources.py", 27)]
    assert "self.srv" in by_site[("resources.py", 32)]
    sups = [
        (f.rule, r.path, f.line)
        for r in reports
        for f in r.suppressed
    ]
    assert ("R15", "resources.py", 36) in sups
