"""Lock-order cycle + lock held across a blocking resolve (dirty
twin): ``forward`` acquires ALPHA then BETA while ``backward`` does the
reverse — two threads interleaving them deadlock — and ``resolve``
holds ALPHA across a guarded dispatch, which deadlocks against the
abandonment path exactly when it needs the lock."""
import threading

from .locks import ALPHA, BETA


def forward(items):
    with ALPHA:
        with BETA:
            return list(items)


def backward(items):
    with BETA:
        with ALPHA:
            return list(items)


def resolve(ctx, ops):
    with ALPHA:
        return ctx.guarded_dispatch("gate_sweep", ops)


def spawn():
    threading.Thread(target=forward, args=([],)).start()
    threading.Thread(target=backward, args=([],)).start()
