"""The durable twin: all replacement writes ride the exempt helper."""

import json
import os
import tempfile


def durable_write_text(path, text):
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".")
    with os.fdopen(fd, "w", encoding="utf-8") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def publish(path, payload):
    durable_write_text(path, json.dumps(payload))


def append_event(path, line):
    with open(path, "a", encoding="utf-8") as f:
        f.write(line)
