"""Registry-disciplined dispatch side (clean twin): every metric is
declared, every fault site is known, no jit wrapper is built outside
the registry home, and a private (``declared=None``) registry's own
counters are exempt by design."""
from .registry import KERNELS, MetricsRegistry, fault_point


def kernel_call(name, args):
    return KERNELS[name].name, args


def run(xs):
    return kernel_call("gate_sweep", xs)


def tally(stats, n):
    stats.inc("sweeps", n)


def probe():
    fault_point("ckpt.write")


class Rendezvous:
    def __init__(self):
        self.stats = MetricsRegistry({"submits": 0}, declared=None)

    def submit(self):
        self.stats.inc("submits")
