"""Journal-key contract held (clean twin): every Options field built
from args is journaled, and every key has an argparse destination."""
import argparse

JOURNAL_CONFIG_KEYS = (
    "seed",
)

JOURNAL_KEY_DEFAULTS = {"seed": None}


def build_parser():
    p = argparse.ArgumentParser()
    p.add_argument("--seed", type=int)
    return p


class Options:
    def __init__(self, seed=None, verbosity=0):
        self.seed = seed
        self.verbosity = verbosity


def main(argv):
    args = build_parser().parse_args(argv)
    return Options(seed=args.seed, verbosity=0)
