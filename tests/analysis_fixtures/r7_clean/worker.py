"""Pinned thread entry (clean twin — the pack config pins 'work')."""
import threading


def work():
    return None


def spawn():
    threading.Thread(target=work).start()
