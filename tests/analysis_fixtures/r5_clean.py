# jaxlint R5 clean twin: narrow catches, logged or re-raised.
import logging

logger = logging.getLogger(__name__)


def probe_backend():
    try:
        import does_not_exist  # noqa: F401

        return True
    except ImportError as e:
        logger.warning("backend probe failed: %r", e)
        return False


def best_effort_cleanup(path):
    import os

    try:
        os.unlink(path)
    except OSError:
        pass  # narrow type: fine


def wrapped(fn):
    try:
        return fn()
    except Exception:
        logger.exception("fn failed")  # broad but logged: fine
        raise
