"""Unbucketed operand shapes at dispatch sites (dirty twin): every
distinct shape value compiles a fresh executable."""
import numpy as np

from .kernels import kernel_call


def sweep(items):
    n = len(items)
    ops = np.zeros((n, 8))
    return kernel_call("gate_sweep", ops)


def resweep(chunks):
    for chunk in chunks:
        pad = np.zeros((len(chunk), 8))
        kernel_call("gate_sweep", pad)


def grow(count):
    buf = np.ones((count, 8))
    return kernel_call("gate_sweep", buf)
