"""The clean twin: every request-derived value is laundered through a
declared sanitizer (int coercion, digest derivation) before any sink,
and a record looked up BY a tainted key is not itself tainted."""
import hashlib
import os

from records import record_job


class Handler:
    def post(self, h):
        idx = int(h.headers.get("X-Index", "0"))
        body = h.rfile.read(64)
        path = os.path.join("/jobs", f"job-{idx}")
        tag = hashlib.blake2b(body).hexdigest()
        record_job(tag)
        return path

    def get(self, h):
        job_id = h.headers.get("X-Job-Id")
        job = self.jobs.get(job_id)
        record_job(job)
