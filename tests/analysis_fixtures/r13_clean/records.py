"""Sink module, identical to the dirty pack's: silent because no
caller hands it a raw request value."""


class Journal:
    def append(self, rec):
        self.rec = rec


journal = Journal()


def record_job(body):
    journal.append({"raw": body})
