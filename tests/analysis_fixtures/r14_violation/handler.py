"""Admission handlers that break the auth -> quota -> journal order:
an unguarded effect, a one-sided (non-dominating) check, a 202 written
before the journal append, and an acknowledged deliberate site."""


class Handler:
    def _send_json(self, h, status, doc):
        pass

    def post_unchecked(self, h):
        self.orch.submit(h.job)

    def post_one_sided(self, h):
        if h.token:
            self.authenticate(h)
            self.active_jobs(h)
        self.orch.submit(h.job)

    def post_unjournaled(self, h):
        self.authenticate(h)
        self.active_jobs(h)
        self.orch.submit(h.job)
        self._send_json(h, 202, {})

    def post_acked(self, h):
        # jaxlint: ignore[R14] demo deliberate replay path: checks ran at the original accept
        self.orch.submit(h.job)
