# jaxlint R2 clean twin: same work, syncs hoisted out of the loops.
import jax
import jax.numpy as jnp
import numpy as np


def stream(chunks, kernel):
    outs = [kernel(c) for c in chunks]
    resolved = np.asarray(jnp.stack(outs))  # one sync after the loop
    return [v for v in resolved if v[0]]


def batched_verdict(kernel, xs):
    out = kernel(jnp.stack(xs))
    out.block_until_ready()  # outside any loop: a deliberate barrier
    return out


def host_side_loop(rows):
    total = 0
    for r in rows:
        total += int(r[0])  # host numpy scalar: no device involved
        arr = np.asarray([1, 2, 3])  # list literal: host data
    return total, arr


def device_reduction(xs):
    total = jnp.zeros(())
    for x in xs:
        total = total + jnp.sum(x)  # stays on device
    return float(total)  # single sync at the end
