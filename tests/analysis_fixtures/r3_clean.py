# jaxlint R3 clean twin: state updates happen outside the trace.
import threading

import jax
import jax.numpy as jnp

_LAST = None


class Model:
    @jax.jit
    def forward(self, x):
        h = x * 2
        return h.sum(), h  # caller stores concrete outputs

    def run(self, x):
        out, h = self.forward(x)
        self.cache = h  # concrete jax.Array, outside the trace: fine
        return out


def remember(x):
    global _LAST
    _LAST = x  # not a traced function: fine
    return x


def spawn_worker(payload):
    t = threading.Thread(target=print, args=(payload,))  # not traced: fine
    t.start()
    return t
