"""Dirty twin: sync-tainted helpers.  This module is NOT hot and the
syncs are not in loops, so the per-file R2 never fires here — the taint
only matters at the hot-module call sites in hot_driver.py."""


def fetch(v):
    return v.item()  # sync-taints fetch (and transitively its callers)


def relay(v):
    return fetch(v)
