"""Dirty twin: a hot-module loop calling sync-tainted helpers."""

from .helpers import fetch, relay


def drain(batch):
    total = 0
    for v in batch:
        total += fetch(v)  # R2x: helper syncs (directly)
        total += relay(v)  # R2x: helper syncs (transitively)
    return total
