# jaxlint unused-suppression fixture.  Read as text — never imported.


def probe_stale():
    try:
        import maybe_missing  # noqa: F401
    except ImportError:  # jaxlint: ignore[R5] handler narrowed long ago; marker left behind
        return False


def probe_stale_standalone():
    try:
        import maybe_missing  # noqa: F401
    # jaxlint: ignore[R5] standalone form, equally stale
    except ImportError:
        return False


def probe_partial():
    try:
        import maybe_missing  # noqa: F401
    except Exception:  # jaxlint: ignore[R5,R3] R5 fires here, R3 never did
        return False
