"""Clean twin: the sync is acknowledged AT ITS SOURCE — the taint dies
here for every caller, and the marker is counted used (it shows up as a
suppressed acknowledged-source entry, never as stale)."""


def fetch(v):
    # jaxlint: ignore[R2x] deliberate per-item verdict pull; measured off the critical path
    return v.item()


def relay(v):
    return fetch(v)
