"""Clean twin: the same hot loop; the helper's sync is acknowledged at
its source, so no call-site finding fires."""

from .helpers import fetch, relay


def drain(batch):
    total = 0
    for v in batch:
        total += fetch(v)
        total += relay(v)
    return total
