# jaxlint R6 clean twin: mutation through the metrics facade, reads
# stay plain.  Read as text — never imported.


def count_dispatch(ctx):
    ctx.stats.inc("device_dispatches")


def reset_counter(ctx, before):
    ctx.stats.put("lut7_candidates", before)


def bump_param(stats, key):
    from sboxgates_tpu.telemetry.metrics import bump

    bump(stats, key)


def seed_counters(rdv):
    rdv.stats.ensure("submits", "dispatches")


def read_counters(ctx):
    # Reads (subscript, get, iteration, dict()) are not mutations.
    total = ctx.stats["device_dispatches"] + ctx.stats.get("warm_hits", 0)
    return total, dict(ctx.stats)


def index_by_counter(ctx, cache, value):
    # A stats READ in the slice of an unrelated target mutates the
    # target (cache), not stats.
    cache[ctx.stats["warm_hits"]] = value
