"""Module-level locks shared by the pack's two worker paths."""
import threading

ALPHA = threading.Lock()
BETA = threading.Lock()
