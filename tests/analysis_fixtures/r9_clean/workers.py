"""Disciplined locking (clean twin): both paths acquire in the SAME
global order (ALPHA before BETA), and the blocking resolve runs only
after the lock is released — the staging pattern R9 asks for."""
import threading

from .locks import ALPHA, BETA


def forward(items):
    with ALPHA:
        with BETA:
            return list(items)


def also_forward(items):
    with ALPHA:
        with BETA:
            return list(items)


def resolve(ctx, ops):
    with ALPHA:
        staged = list(ops)
    return ctx.guarded_dispatch("gate_sweep", staged)


def spawn():
    threading.Thread(target=forward, args=([],)).start()
    threading.Thread(target=also_forward, args=([],)).start()
