# jaxlint R4 fixture: thread targets mutating module state lockless.
# Read as text — never imported.
import threading

RESULTS = []
COUNTS = {}
_TOTAL = 0
_lock = threading.Lock()


def worker(job):
    out = job()
    RESULTS.append(out)  # line 13: no lock held
    COUNTS[job.__name__] = out  # line 14: no lock held


def tally(n):
    global _TOTAL
    _TOTAL += n  # line 19: lost-update race on the module counter


def launch(jobs):
    threads = [threading.Thread(target=worker, args=(j,)) for j in jobs]
    threads.append(threading.Thread(target=tally, args=(1,)))
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return RESULTS
