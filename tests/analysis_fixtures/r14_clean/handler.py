"""The clean twin: the auth check is HOISTED into a shared helper —
the call graph's transitive-reach map still establishes the flag at
the ``self._auth(h)`` call site — quota guards in the branch test, the
journal append precedes both the enqueue and the 202."""


class Handler:
    def _send_json(self, h, status, doc):
        pass

    def _auth(self, h):
        self.authenticate(h)

    def post(self, h):
        self._auth(h)
        if self.active_jobs(h) > 0:
            raise ValueError("over quota")
        self.journal.admit(h.job)
        self.orch.submit(h.job)
        self._send_json(h, 202, {})
