"""Nondeterministic values flowing into bit-identity sinks."""

import os

from .clock import stamp


def record(journal, payload):
    journal.append("done", t=stamp())


def derive_key(parts):
    import numpy as np

    seed = int.from_bytes(os.urandom(4), "big")
    return np.random.default_rng(seed)


def manifest(directory):
    names = os.listdir(directory)
    return canonicalize(names)


def canonicalize(parts):
    return "|".join(parts)


def fan_out(journal, items):
    for item in set(items):
        journal.append("item", name=item)
