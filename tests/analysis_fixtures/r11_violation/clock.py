"""Nondeterminism source hidden behind a project-local helper."""

import time


def stamp():
    return time.time()
