"""The clean twin: every acquisition is released on all exit paths
(finally / with), transferred to the caller, exempt by declaration
(daemon), registered with a teardown registry, or released by a class
teardown method."""
import socket
import threading

from http.server import HTTPServer


def probe(host):
    s = socket.socket()
    try:
        s.connect((host, 80))
        s.send(b"ping")
    finally:
        s.close()


def scoped(host):
    with socket.socket() as s:
        s.connect((host, 80))


def make_worker():
    t = threading.Thread(target=print)
    return t


def daemon_watcher():
    t = threading.Thread(target=print, daemon=True)
    t.start()


def registered(drain_hooks):
    s = socket.socket()
    drain_hooks.append(s.close)
    return None


class Holder:
    def open_server(self):
        self.srv = HTTPServer(("", 0), None)

    def close(self):
        self.srv.server_close()
