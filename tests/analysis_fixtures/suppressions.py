# jaxlint suppression-syntax fixture.  Read as text — never imported.


def probe_a():
    try:
        import maybe_missing  # noqa: F401
    except Exception:  # jaxlint: ignore[R5] optional dep probe; absence is the common case
        return False


def probe_b():
    try:
        import maybe_missing  # noqa: F401
    # jaxlint: ignore[R5] standalone-comment form, applies to the next line
    except Exception:
        return False


def probe_c():
    try:
        import maybe_missing  # noqa: F401
    except Exception:  # jaxlint: ignore[R5]
        return False  # missing reason above: NOT suppressed, plus SUP


def probe_d():
    try:
        import maybe_missing  # noqa: F401
    except Exception:  # jaxlint: ignore[R99] no such rule
        return False  # unknown rule: NOT suppressed, plus SUP
