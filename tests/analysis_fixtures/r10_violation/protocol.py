"""Rank-gated agreement divergence: each shape R10 must flag."""

import jax
from jax.experimental import multihost_utils


def agree(flag):
    # Transitive carrier: reaches the agreement site for its callers.
    return breach_verdict(flag)


def breach_verdict(flag):
    return bool(flag)


def one_sided(flag):
    if jax.process_index() == 0:
        breach_verdict(flag)


def guard_style(flag):
    rank = jax.process_index()
    if rank != 0:
        return None
    return agree(flag)


def collective_in_host_window(client, x):
    client.wait_at_barrier("sync", 1000)
    return multihost_utils.process_allgather(x)
