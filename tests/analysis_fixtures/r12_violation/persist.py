"""In-place truncating writes inside a durable module."""

import json
import os


def publish(path, payload):
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f)
    os.replace(path + ".new", path)


def append_event(path, line):
    with open(path, "a", encoding="utf-8") as f:
        f.write(line)
