"""NOT a handler module: the journal sink fires here only because the
caller's request-derived argument taints the parameter."""


class Journal:
    def append(self, rec):
        self.rec = rec


journal = Journal()


def record_job(body):
    journal.append({"raw": body})
