"""A network handler whose request-derived values reach sensitive
sinks raw: one sink in this module, one two calls away (the flow an
intraprocedural linter cannot see), plus an acknowledged source whose
marker kills the taint and re-emits as a suppressed inventory entry."""
import os

from records import record_job


class Handler:
    def post(self, h):
        name = h.headers.get("X-Job-Name")
        body = h.rfile.read(64)
        path = os.path.join("/jobs", name)
        record_job(body)
        return path

    def post_acked(self, h):
        # jaxlint: ignore[R13] demo acknowledged source: the tag is recorded verbatim by design
        tag = h.headers.get("X-Tag")
        record_job(tag)
