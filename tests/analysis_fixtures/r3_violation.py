# jaxlint R3 fixture: tracer escape from jit-traced functions.  Read as
# text — never imported.
import threading

import jax
import jax.numpy as jnp

_LAST = None


class Model:
    @jax.jit
    def forward(self, x):
        h = x * 2
        self.cache = h  # line 15: tracer stored on self
        return h.sum()


@jax.jit
def leak_global(x):
    global _LAST
    y = x + 1
    _LAST = y  # line 23: tracer stored in a global
    return y


@jax.jit
def thread_handoff(x):
    t = threading.Thread(target=print, args=(x,))  # line 29: tracer to thread
    t.start()
    return x
