# jaxlint unused-suppression clean twin: every marker still earns its
# keep (the suppressed finding fires on its line).  Read as text — never
# imported.


def probe_used():
    try:
        import maybe_missing  # noqa: F401
    except Exception:  # jaxlint: ignore[R5] optional dep probe; absence is the common case
        return False


def probe_used_standalone():
    try:
        import maybe_missing  # noqa: F401
    # jaxlint: ignore[R5] standalone-comment form, applies to the next line
    except Exception:
        return False
