"""Clean twin: the same thread entry, mutations routed through locks."""

import threading

from .locks import EVENTS_LOCK
from .state import Stream, record


class Prefetcher:
    def __init__(self):
        self.stream = Stream()
        self._thread = threading.Thread(target=self._work, daemon=True)

    def _work(self):
        while True:
            item = self._produce()
            if item is None:
                return

    def _produce(self):
        chunk = self.stream.next_chunk()
        record(EVENTS_LOCK, len(chunk))
        return chunk
