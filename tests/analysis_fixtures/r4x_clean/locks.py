"""Clean twin: the locks live in their own module (aliasing case)."""

import threading

PROBE_LOCK = threading.Lock()
EVENTS_LOCK = threading.Lock()
