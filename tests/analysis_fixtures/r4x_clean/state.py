"""Clean twin: same shapes as r4x_violation, every mutation guarded.

``probe`` holds a lock IMPORTED from a sibling module; ``record`` holds
a lock received as a PARAMETER (the call site in worker.py passes a
known lock) — both count as held for R4x.
"""

from .locks import PROBE_LOCK

_probe_ok = None
EVENTS = []


def probe():
    global _probe_ok
    if _probe_ok is None:
        with PROBE_LOCK:  # imported lock: cross-module aliasing
            if _probe_ok is None:
                _probe_ok = True
    return _probe_ok


def record(lock, n):
    with lock:  # parameter lock: worker.py passes EVENTS_LOCK
        EVENTS.append(n)


class Stream:
    def next_chunk(self):
        if probe():
            return [1, 2, 3]
        return []
