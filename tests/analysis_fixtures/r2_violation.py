# jaxlint R2 fixture: host-device syncs inside loops (linted as a hot
# module by the tests).  Read as text — never imported.
import jax
import jax.numpy as jnp
import numpy as np


def stream(chunks, kernel):
    hits = []
    for c in chunks:
        v = np.asarray(kernel(c))  # line 11: blocking copy per chunk
        if v[0]:
            hits.append(v)
    return hits


def polling_loop(kernel, x):
    while True:
        out = kernel(x)
        out.block_until_ready()  # line 20: serializes every dispatch
        if jax.device_get(out)[0]:  # line 21: second sync per iteration
            return out


def scalar_coercion(xs):
    total = 0.0
    for x in xs:
        total += float(jnp.sum(x))  # line 28: device reduction synced per item
    return total


def item_per_iter(kernel, xs):
    flags = []
    for x in xs:
        flags.append(kernel(x).item())  # line 35: scalar transfer per item
    return flags
