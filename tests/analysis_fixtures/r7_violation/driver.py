"""Dispatch-side registry escapes (dirty twin)."""
import jax

from .registry import KERNELS, fault_point


def kernel_call(name, args):
    return KERNELS[name].name, args


def run(xs):
    out = kernel_call("gate_sweep", xs)
    fn = jax.jit(lambda x: x + 1)
    return fn(out)


def tally(stats, n):
    stats.inc("sweeps", n)
    stats.inc("sweep_total", n)


def probe():
    fault_point("ckpt.write")
    fault_point("ckpt.rename")
