"""Journal-key contract escapes (dirty twin)."""
import argparse

JOURNAL_CONFIG_KEYS = (
    "seed",
    "ghost_flag",
)

JOURNAL_KEY_DEFAULTS = {"late_flag": 1}


def build_parser():
    p = argparse.ArgumentParser()
    p.add_argument("--seed", type=int)
    p.add_argument("--verbose", action="store_true")
    return p


class Options:
    def __init__(self, seed=None, verbosity=0):
        self.seed = seed
        self.verbosity = verbosity


def main(argv):
    args = build_parser().parse_args(argv)
    return Options(
        seed=args.seed,
        verbosity=args.verbose,
    )
