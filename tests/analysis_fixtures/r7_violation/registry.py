"""Miniature declared registries for the R7 drift pass (dirty twin)."""
from collections import namedtuple

KernelDef = namedtuple("KernelDef", ["name", "statics"])


class MetricsRegistry:  # stand-in for telemetry.metrics.MetricsRegistry
    def __init__(self, initial=None, declared=None):
        self.values = dict(initial or {})

    def inc(self, name, by=1):
        self.values[name] = self.values.get(name, 0) + by


def fault_point(site):
    return site


KERNELS = {
    d.name: d
    for d in (
        KernelDef("gate_sweep", ()),
        KernelDef("orphan_sweep", ()),
    )
}

FLEET_SHARED = {
    "gate_sweep": (0,),
    "ghost_sweep": (1,),
}

METRICS = {
    "sweeps": ("counter", "candidates"),
}

KNOWN_SITES = (
    "ckpt.write",
)
