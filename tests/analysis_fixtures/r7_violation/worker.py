"""Unpinned thread entry (dirty twin)."""
import threading


def work():
    return None


def spawn():
    threading.Thread(target=work).start()
