"""Dispatch seam stub the pack's drivers call (never executed)."""


def kernel_call(name, *operands):
    return name, operands


def bucket_size(n):
    return max(64, n)
