"""Bucket-disciplined operand shapes (clean twin): every axis derives
from a declared bucket ladder, the padding idiom ``bucket - n``
included; constants are static (one shape, no hazard)."""
import numpy as np

from .kernels import bucket_size, kernel_call


def sweep(items):
    b = bucket_size(len(items))
    ops = np.zeros((b, 8))
    return kernel_call("gate_sweep", ops)


def pad_tail(items, bucket):
    tail = np.zeros((bucket - len(items), 8))
    return kernel_call("gate_sweep", tail)


def fixed_probe():
    probe = np.zeros((64, 8))
    return kernel_call("gate_sweep", probe)


def rebucket(arr, items):
    b = bucket_size(len(items))
    ops = np.reshape(arr, (b, 8))
    return kernel_call("gate_sweep", ops)
