"""Rank-gated branches where every process still reaches agreement."""

import jax


def breach_verdict(flag):
    return bool(flag)


def symmetric(flag):
    # Matching peer path: the guard returns through the same agreement
    # site the fall-through does, so every process issues one call.
    if jax.process_index() == 0:
        return breach_verdict(True)
    return breach_verdict(flag)


def both_sides(flag):
    if jax.process_index() == 0:
        breach_verdict(True)
    else:
        breach_verdict(flag)


def replicated_guard(flag):
    # process_count is a replicated predicate, not a rank source: every
    # process takes the same side.
    if jax.process_count() <= 1:
        return bool(flag)
    return breach_verdict(flag)


def local_only(items):
    rank = jax.process_index()
    out = []
    for i, item in enumerate(items):
        if i % 3 == rank:
            out.append(item)
    return out
