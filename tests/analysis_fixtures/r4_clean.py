# jaxlint R4 clean twin: every module-state mutation holds the module lock.
import threading

RESULTS = []
COUNTS = {}
_TOTAL = 0
_lock = threading.Lock()


def worker(job):
    out = job()
    with _lock:
        RESULTS.append(out)
        COUNTS[job.__name__] = out


def tally(n):
    global _TOTAL
    with _lock:
        _TOTAL += n


def collect(job):
    local = [job()]  # closure-local list: no lock needed
    return local


def launch(jobs):
    threads = [threading.Thread(target=worker, args=(j,)) for j in jobs]
    threads.append(threading.Thread(target=tally, args=(1,)))
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return RESULTS
