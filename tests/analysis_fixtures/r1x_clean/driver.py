"""Clean twin: statics hoisted / hashable — no recompile churn."""

from .kernels import compute, fast_plain


def run(xs):
    out = []
    n = 4  # hoisted: one compile for the whole loop
    for _i in range(8):
        out.append(compute(xs, n=n))
    out.append(compute(xs, n=(1, 2)))  # tuple: hashable static
    for _j in range(4):
        out.append(fast_plain(xs, n=n))
    return out
