"""Clean twin: same kernels as r1x_violation."""

import functools

import jax


@functools.partial(jax.jit, static_argnames=("n",))
def compute(x, n):
    return x * n


def plain(x, n):
    return x + n


fast_plain = jax.jit(plain, static_argnames=("n",))
