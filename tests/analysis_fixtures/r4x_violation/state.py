"""Dirty twin: module state mutated on thread-reachable paths, no lock.

Mirrors the pre-fix ``ops/combinatorics._native_stream_available`` miss:
``probe()`` mutates ``_probe_ok`` and is reached from the prefetch
thread via ``Prefetcher._work -> Prefetcher._produce ->
Stream.next_chunk -> probe`` (see worker.py) — invisible to the
per-file R4, caught by R4x.
"""

_probe_ok = None
EVENTS = []


def probe():
    global _probe_ok
    if _probe_ok is None:
        _probe_ok = True  # R4x: unlocked, thread-reachable transitively
    return _probe_ok


class Stream:
    def next_chunk(self):
        if probe():
            return [1, 2, 3]
        return []
